"""Dependency-free HTTP front end for the ServingEngine.

stdlib ``http.server.ThreadingHTTPServer`` only — one handler thread per
connection, all of them funneling into the engine's bounded queue, so
the adaptive batcher (not the HTTP layer) is the concurrency boundary.

Endpoints:
  POST /predict   {"inputs": [nested-list, ...], "dtypes"?, "deadline_ms"?}
                  → {"outputs": [...], "dtypes": [...], "latency_ms": t}
                  429 on queue-full backpressure, 503 while draining,
                  504 on deadline expiry
  POST /generate  {"prompt": [ids], "max_new_tokens"?, "do_sample"?,
                  "temperature"?, "top_k"?, "seed"?, "resume_pos"?,
                  "eos_token_id"?, "deadline_ms"?, "stream"?} —
                  continuous-batching generation (requires a mounted
                  GenerationEngine).  `resume_pos` is the router's
                  mid-stream failover hook: the request's PRNG chain is
                  fast-forwarded past that many already-emitted tokens
                  so a re-admitted stream resumes deterministically.
                  stream=false → one JSON body {"tokens": [...]};
                  stream=true  → Server-Sent Events over chunked
                  transfer, one `data: {"token": t}` event per decoded
                  token as the decode loop produces it, then a final
                  `data: {"done": true, ...}` event.  Same 400/429/503/
                  504 admission split as /predict.
  GET  /healthz   200 {"status": "ok", ...} | 503 {"status": "draining",
                  ...} — plus framework/jax versions, device kind/count,
                  uptime_s and pid (fleet version-skew detection)
  GET  /metrics   Prometheus text from every mounted engine (batching
                  qps/p50/p99 + genserve decode tokens/s, TTFT,
                  inter-token quantiles, slot occupancy)

Graceful shutdown reuses the resilience latch pattern
(distributed/resilience.py PreemptionGuard): SIGTERM/SIGINT is LATCHED,
new work is rejected (healthz flips to draining), every queued and
in-flight request completes, then the listener closes and ``wait()``
returns 0 — the serving analog of "finish the in-flight step, then exit
clean".
"""
from __future__ import annotations

import concurrent.futures
import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..distributed.resilience import PreemptionGuard
from ..monitor import flightrec as _flightrec
from ..monitor import tracing as _tracing
from ..monitor.server import runtime_health
from .engine import (DeadlineExceededError, EngineStoppedError,
                     QueueFullError, ServingEngine)

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["ServingServer"]


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # stdlib default listen backlog is 5 — a thundering herd of clients
    # gets TCP resets before the engine's queue (the REAL admission
    # control) ever sees them.  Backpressure must come from HTTP 429,
    # not the kernel.
    request_queue_size = 128


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # ``self.server`` is the ThreadingHTTPServer; the ServingServer
    # attaches itself as ``.owner``.
    def _send(self, code: int, body: bytes, ctype="application/json"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if code in (429, 503):
            self.send_header("Retry-After", "1")
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj):
        self._send(code, json.dumps(obj).encode())

    def do_GET(self):  # noqa: N802 - http.server API
        owner = self.server.owner
        if self.path == "/healthz":
            info = {"uptime_s": owner.uptime_s, **runtime_health()}
            if owner.draining:
                self._send_json(503, {"status": "draining", **info})
            else:
                self._send_json(200, {"status": "ok", **info})
        elif self.path == "/metrics":
            from ..utils.metrics import default_registry

            parts = [e.metrics.prometheus_text() for e in
                     (owner.engine, owner.gen_engine) if e is not None]
            # process-wide shared registry (e.g. the Pallas fallback
            # counter paddle_pallas_fallbacks_total from ops/fused.py)
            parts.append(default_registry().prometheus_text())
            self._send(200, "".join(parts).encode(),
                       ctype="text/plain; version=0.0.4")
        else:
            self._send_json(404, {"error": f"no route {self.path}"})

    def do_POST(self):  # noqa: N802 - http.server API
        owner = self.server.owner
        # always drain the declared body FIRST: an early error response
        # on a keep-alive connection would otherwise leave the body
        # bytes to be misparsed as the next request line
        n = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(n)
        # adopt the caller's W3C trace context (or head-sample a fresh
        # trace); a NullSpan when unsampled/disabled, so every handler
        # below threads it through unconditionally
        tracer = _tracing.default_tracer()
        tp = self.headers.get("traceparent")
        if self.path == "/generate":
            span = tracer.start_span("server.generate", traceparent=tp)
            try:
                self._do_generate(owner, raw, span)
            finally:
                span.end()
            return
        if self.path != "/predict":
            self._send_json(404, {"error": f"no route {self.path}"})
            return
        span = tracer.start_span("server.predict", traceparent=tp)
        try:
            self._do_predict(owner, raw, span)
        finally:
            span.end()

    def _do_predict(self, owner, raw, span):
        if owner.engine is None:
            self._send_json(404, {"error": "no predict engine mounted"})
            return
        t0 = time.monotonic()
        try:
            payload = json.loads(raw or b"{}")
            inputs = payload["inputs"]
            if not isinstance(inputs, list) or not inputs:
                raise ValueError("'inputs' must be a non-empty list")
            arrays = owner._decode(inputs, payload.get("dtypes"))
        except (KeyError, ValueError, TypeError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        # admission errors (the client's fault / state) get 4xx-503 —
        # separately from execution errors, so a server-side ValueError
        # out of the model can never masquerade as "bad request"
        try:
            fut = owner.engine.submit(
                arrays, deadline_ms=payload.get("deadline_ms"), span=span)
        except ValueError as e:  # shape/spec mismatch caught at submit
            span.set_attr("status", "bad_request")
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        except QueueFullError as e:
            span.set_attr("status", "rejected_queue_full")
            self._send_json(429, {"error": str(e)})
            return
        except EngineStoppedError as e:
            span.set_attr("status", "rejected_draining")
            self._send_json(503, {"error": str(e)})
            return
        try:
            # bounded wait: a stalled model execution must release the
            # handler thread (queued-phase deadlines are the engine's
            # job; this is the dispatched-phase backstop)
            outs = fut.result(timeout=owner.request_timeout_s)
        except DeadlineExceededError as e:
            self._send_json(504, {"error": str(e)})
            return
        except concurrent.futures.TimeoutError:
            fut.cancel()
            self._send_json(504, {"error": "request timed out in "
                                  f"{owner.request_timeout_s:g}s"})
            return
        except Exception as e:  # noqa: BLE001 - model failure → 500
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        latency_ms = round((time.monotonic() - t0) * 1e3, 3)
        span.set_attr("latency_ms", latency_ms)
        self._send_json(200, {
            "outputs": [np.asarray(o).tolist() for o in outs],
            "dtypes": [str(np.asarray(o).dtype) for o in outs],
            "latency_ms": latency_ms,
        })

    def _do_generate(self, owner, raw, span):
        gen = owner.gen_engine
        if gen is None:
            self._send_json(404, {"error": "no generation engine mounted"})
            return
        t0 = time.monotonic()
        try:
            payload = json.loads(raw or b"{}")
            prompt = payload["prompt"]
            if not isinstance(prompt, list) or not prompt:
                raise ValueError(
                    "'prompt' must be a non-empty list of token ids")
            stream = bool(payload.get("stream", False))
            kw = dict(
                max_new_tokens=int(payload.get("max_new_tokens", 32)),
                do_sample=bool(payload.get("do_sample", False)),
                temperature=float(payload.get("temperature", 1.0)),
                top_k=int(payload.get("top_k", 0)),
                seed=int(payload.get("seed", 0)),
                resume_pos=int(payload.get("resume_pos", 0)),
                eos_token_id=payload.get("eos_token_id"),
                deadline_ms=payload.get("deadline_ms"),
            )
        except (KeyError, ValueError, TypeError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        try:
            handle = gen.submit(prompt, span=span, **kw)
        except ValueError as e:  # geometry/sampling bounds, at submit
            span.set_attr("status", "bad_request")
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        except QueueFullError as e:
            span.set_attr("status", "rejected_queue_full")
            self._send_json(429, {"error": str(e)})
            return
        except EngineStoppedError as e:
            span.set_attr("status", "rejected_draining")
            self._send_json(503, {"error": str(e)})
            return
        if stream:
            self._stream_tokens(owner, handle, t0, span)
            return
        try:
            toks = handle.result(timeout=owner.request_timeout_s)
        except DeadlineExceededError as e:
            self._send_json(504, {"error": str(e)})
            return
        except TimeoutError:
            handle.cancel()
            self._send_json(504, {"error": "generation timed out in "
                                  f"{owner.request_timeout_s:g}s"})
            return
        except EngineStoppedError as e:
            self._send_json(503, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 - engine failure → 500
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        if handle.ttft_ms is not None:
            span.set_attr("ttft_ms", round(handle.ttft_ms, 3))
        span.set_attr("tokens", len(toks))
        self._send_json(200, {
            "tokens": toks,
            "ttft_ms": round(handle.ttft_ms, 3)
            if handle.ttft_ms is not None else None,
            "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
        })

    def _stream_tokens(self, owner, handle, t0, span):
        """Server-Sent Events over explicit chunked framing.  The
        response is open-ended, so the connection is marked close — a
        keep-alive client would otherwise wait on a Content-Length that
        can never be known up front."""
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Connection", "close")
        self.end_headers()
        self.close_connection = True

        def event(obj):
            data = b"data: " + json.dumps(obj).encode() + b"\n\n"
            self.wfile.write(b"%X\r\n" % len(data) + data + b"\r\n")
            self.wfile.flush()

        n = 0
        try:
            try:
                while True:
                    tok = handle.next_token(timeout=owner.request_timeout_s)
                    if tok is None:
                        break
                    n += 1
                    event({"token": tok})
                span.set_attr("tokens", n)
                if handle.ttft_ms is not None:
                    span.set_attr("ttft_ms", round(handle.ttft_ms, 3))
                event({"done": True, "tokens": n,
                       "ttft_ms": round(handle.ttft_ms, 3)
                       if handle.ttft_ms is not None else None,
                       "latency_ms": round((time.monotonic() - t0) * 1e3,
                                           3)})
            except TimeoutError as e:  # covers DeadlineExceededError
                handle.cancel()
                event({"done": True, "tokens": n, "error": str(e)})
            except Exception as e:  # noqa: BLE001 - surface in-band
                event({"done": True, "tokens": n,
                       "error": f"{type(e).__name__}: {e}"})
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            handle.cancel()  # client went away mid-stream: free the slot

    def log_message(self, fmt, *args):  # route through logging, not stderr
        logger.debug("%s - %s", self.address_string(), fmt % args)


class ServingServer:
    """HTTP server + engine lifecycle + SIGTERM drain.

    ``start()`` warms the engine and begins serving; ``wait()`` blocks
    until a latched SIGTERM/SIGINT (or ``shutdown()``) finishes the
    graceful drain, and returns 0 on a clean exit.  Signal handlers are
    installed when running on the main thread (the PreemptionGuard
    pattern); off the main thread only programmatic shutdown works.
    """

    def __init__(self, engine: ServingEngine, host="127.0.0.1", port=8866,
                 install_signal_handlers=True, drain_timeout_s=60.0,
                 request_timeout_s=120.0, *, gen_engine=None):
        if engine is None and gen_engine is None:
            raise ValueError("ServingServer needs at least one engine "
                             "(predict and/or generation)")
        self.engine = engine
        self.gen_engine = gen_engine
        self._host = host
        self._requested_port = int(port)
        self._install_signals = install_signal_handlers
        self.drain_timeout_s = drain_timeout_s
        self.request_timeout_s = request_timeout_s
        self._httpd = None
        self._guard = None
        self._threads = []
        self._done = threading.Event()
        self._drain_clean = None
        self._shutdown_once = threading.Lock()
        self._started_at = None

    @property
    def uptime_s(self) -> float:
        return round(time.monotonic() - self._started_at, 1) \
            if self._started_at is not None else 0.0

    # -- input decode ------------------------------------------------------
    def _decode(self, inputs, dtypes=None):
        specs = self.engine._input_specs
        arrays = []
        for i, x in enumerate(inputs):
            if dtypes and i < len(dtypes):
                dt = np.dtype(dtypes[i])
            elif specs and i < len(specs):
                dt = np.dtype(specs[i][1])
            else:
                dt = None
            a = np.asarray(x) if dt is None else np.asarray(x, dtype=dt)
            if a.dtype == object:
                raise ValueError(f"inputs[{i}] is ragged/non-numeric")
            arrays.append(a)
        return arrays

    # -- lifecycle ---------------------------------------------------------
    @property
    def _engines(self):
        return [e for e in (self.engine, self.gen_engine) if e is not None]

    @property
    def draining(self) -> bool:
        return any(e.draining for e in self._engines) or self._done.is_set()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd \
            else self._requested_port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def start(self) -> "ServingServer":
        for e in self._engines:
            e.start()
        self._httpd = _HTTPServer((self._host, self._requested_port),
                                  _Handler)
        self._httpd.owner = self
        self._started_at = time.monotonic()
        if self._install_signals:
            # latch, don't die: the handler only sets .preempted — the
            # watcher thread performs the drain (same latch→finish→exit
            # contract as the training runtime)
            self._guard = PreemptionGuard()
            self._guard.__enter__()
        t_serve = threading.Thread(target=self._httpd.serve_forever,
                                   kwargs={"poll_interval": 0.05},
                                   daemon=True, name="paddle-serving-http")
        t_watch = threading.Thread(target=self._watch, daemon=True,
                                   name="paddle-serving-sigwatch")
        self._threads = [t_serve, t_watch]
        t_serve.start()
        t_watch.start()
        logger.info(
            "serving on %s (%s)", self.url,
            ", ".join(f"{b}" for b in [
                self.engine.buckets if self.engine is not None else None,
                f"genserve slots={self.gen_engine.max_slots}"
                if self.gen_engine is not None else None] if b))
        return self

    def _watch(self):
        while not self._done.wait(0.05):
            if self._guard is not None and self._guard.preempted:
                logger.warning("signal %s latched — draining serving "
                               "engine", self._guard.signum)
                self.shutdown()
                return

    def shutdown(self) -> bool:
        """Graceful drain: reject new work, finish queued + in-flight
        requests, close the listener.  Idempotent; returns True when the
        drain completed cleanly."""
        with self._shutdown_once:
            if self._drain_clean is not None:
                return self._drain_clean
            clean = True
            for e in self._engines:
                clean = e.drain(timeout=self.drain_timeout_s) and clean
                e.stop()
            if self._httpd is not None:
                self._httpd.shutdown()
                self._httpd.server_close()
            if self._guard is not None:
                self._guard.__exit__(None, None, None)
                self._guard = None
            self._drain_clean = clean
            self._done.set()
            logger.info("serving drain %s", "clean" if clean else "TIMED OUT")
            # serving postmortem: when a flight recorder is configured
            # (FLAGS_telemetry_dir), leave the last spans + engine state
            # for the goodput ledger / on-call (no-op otherwise)
            _flightrec.record("drain", clean=clean)
            _flightrec.dump("drain")
            return clean

    def wait(self, timeout=None) -> int:
        """Block until shutdown completes; 0 = clean drain."""
        if not self._done.wait(timeout):
            return -1
        for t in self._threads:
            t.join(5.0)
        return 0 if self._drain_clean else 1

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(
        description="paddle_tpu serving server (adaptive batching over an "
                    "AOT-exported artifact)")
    parser.add_argument("--model", required=True,
                        help="export path prefix (save_inference_model)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8866,
                        help="0 picks a free port (printed on stdout)")
    parser.add_argument("--max-batch", type=int, default=None)
    parser.add_argument("--timeout-ms", type=float, default=None)
    parser.add_argument("--buckets", default=None,
                        help='e.g. "1,2,4,8" or "1,2,4,8x16,32"')
    parser.add_argument("--queue-depth", type=int, default=None)
    parser.add_argument("--seq-axis", type=int, default=0)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    engine = ServingEngine(args.model, max_batch_size=args.max_batch,
                           batch_timeout_ms=args.timeout_ms,
                           buckets=args.buckets,
                           queue_depth=args.queue_depth,
                           seq_axis=args.seq_axis)
    server = ServingServer(engine, host=args.host, port=args.port).start()
    # parse-friendly readiness line (tools/serve_smoke.sh greps it)
    print(f"paddle_tpu.serving listening on {server.url}", flush=True)
    return server.wait()


if __name__ == "__main__":
    import sys

    sys.exit(main())
