"""paddle.slim — quantization-aware training + int8 export.

Reference parity: python/paddle/fluid/contrib/slim/quantization/
(quantization_pass.py QuantizationTransformPass — fake-quant op insertion
on conv/mul inputs+weights with moving-average abs-max scales;
imperative/qat.py ImperativeQuantAware — the dygraph API this module
mirrors).

TPU-native design: the reference rewrites the program graph, inserting
fake_quantize_dequantize ops; here quantization is a LAYER TRANSFORM —
quantizable layers (Linear/Conv2D) are wrapped so weights and activations
pass through a straight-through-estimator fake-quant before compute.  The
wrapped model stays a normal Layer: it jits, trains, saves.  Export packs
weights as int8 + per-tensor scale (the artifact the reference's
save_quantized_model produces) and serves through the standard Predictor
with an inline dequantize — XLA folds the int8→f32 convert into the
matmul epilogue on TPU.
"""
from __future__ import annotations

import json
import os
import pickle

import numpy as np

import jax
import jax.numpy as jnp

from ..nn.layer_base import Layer
from ..tensor import Tensor, apply, unwrap

__all__ = ["QAT", "ImperativeQuantAware", "fake_quant",
           "QuantizedLinear", "QuantizedConv2D", "save_quantized_model",
           "load_quantized_predictor", "PostTrainingQuantization"]


def fake_quant(x, scale, bits=8):
    """Symmetric per-tensor fake quantize-dequantize with a straight-
    through estimator gradient (quantization_pass.py
    fake_quantize_dequantize_moving_average_abs_max): values round onto
    the int grid in the forward pass, gradients flow as identity."""
    def f(v, s):
        qmax = float(2 ** (bits - 1) - 1)
        step = jnp.maximum(s.astype(v.dtype), 1e-8) / qmax
        q = jnp.clip(jnp.round(v / step), -qmax, qmax) * step
        return v + jax.lax.stop_gradient(q - v)

    return apply(f, x, scale)


class _QuantWrapper(Layer):
    """Shared machinery: activation observer (moving-average abs-max) +
    weight fake-quant around an inner layer's compute."""

    def __init__(self, inner, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self.inner = inner
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate
        self.register_buffer("act_scale",
                             Tensor(jnp.ones((), jnp.float32)),
                             persistable=True)
        self.register_buffer("weight_scale",
                             Tensor(jnp.ones((), jnp.float32)),
                             persistable=True)

    def _observe(self, x):
        """Update the activation scale (EMA of abs-max) during training;
        buffer-update semantics match BN running stats (jit-safe through
        the functional bridge)."""
        if not self.training:
            return
        cur = jnp.max(jnp.abs(unwrap(x))).astype(jnp.float32)
        r = self._rate
        self.act_scale.set_value(
            unwrap(self.act_scale) * r + cur * (1 - r))

    def _wscale(self):
        w = unwrap(self.inner.weight)
        cur = jnp.max(jnp.abs(w)).astype(jnp.float32)
        if self.training:
            self.weight_scale.set_value(cur)
        return cur

    def forward(self, x):
        self._observe(x)
        xq = fake_quant(x, self.act_scale, self._abits)
        wq = fake_quant(self.inner.weight, Tensor(self._wscale()),
                        self._wbits)
        return self._compute(xq, wq)


class QuantizedLinear(_QuantWrapper):
    def _compute(self, xq, wq):
        from ..nn import functional as F

        return F.linear(xq, wq, self.inner.bias)


class QuantizedConv2D(_QuantWrapper):
    def _compute(self, xq, wq):
        from ..nn import functional as F

        inner = self.inner
        return F.conv2d(xq, wq, inner.bias, stride=inner._stride,
                        padding=inner._padding, dilation=inner._dilation,
                        groups=inner._groups,
                        data_format=inner._data_format)


_QUANTIZABLE = {"Linear": QuantizedLinear, "Conv2D": QuantizedConv2D}


class ImperativeQuantAware:
    """Dygraph QAT driver (imperative/qat.py): wrap quantizable sublayers
    in place, train as usual, then export int8."""

    def __init__(self, quantizable_layer_type=("Linear", "Conv2D"),
                 weight_bits=8, activation_bits=8, moving_rate=0.9):
        self._types = tuple(quantizable_layer_type)
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate

    def quantize(self, model: Layer) -> Layer:
        for holder, name, sub in _walk(model):
            kind = type(sub).__name__
            if kind in self._types and kind in _QUANTIZABLE:
                wrapped = _QUANTIZABLE[kind](
                    sub, self._wbits, self._abits, self._rate)
                setattr(holder, name, wrapped)
        return model

    def save_quantized_model(self, model, path, input_spec=None,
                             example_inputs=None):
        return save_quantized_model(model, path, input_spec,
                                    example_inputs)


QAT = ImperativeQuantAware  # paddle.slim 2.x alias


def _walk(layer, prefix=""):
    """Yield (holder, attr_name, sublayer) for every direct child,
    recursively (post-order not needed: wrapping replaces leaves)."""
    for name, sub in list(layer._sub_layers.items()):
        yield layer, name, sub
        yield from _walk(sub, prefix + name + ".")


def save_quantized_model(model, path_prefix, input_spec=None,
                         example_inputs=None):
    """Export the trained QAT model with REAL int8 weights + scales
    (the reference's save_quantized_model artifact): .pdqparams holds
    int8 weight bytes and f32 scales; serving dequantizes inline."""
    model.eval()
    qlayers = {}
    for holder, name, sub in _walk(model):
        if isinstance(sub, _QuantWrapper):
            w = np.asarray(unwrap(sub.inner.weight))
            qmax = 2 ** (sub._wbits - 1) - 1
            if isinstance(sub, _PTQWrapper):
                # calibration froze the scale (maybe per-channel) — the
                # wrapper computes with exactly this buffer, so the int8
                # payload must pack with it too
                scale = np.asarray(unwrap(sub.weight_scale), np.float32)
            else:
                # QAT: abs-max of the CURRENT weight — the same value
                # _wscale() returns during the eval-mode export trace
                # below.  The weight_scale buffer only updates on training
                # forwards, so after the final optimizer step it is stale
                # and the packed int8 payload would not reproduce the
                # served numerics.
                scale = np.float32(np.max(np.abs(w)))
            step = np.maximum(scale, 1e-8) / qmax
            wq = np.clip(np.round(w / step), -qmax, qmax).astype(np.int8)
            key = _layer_path(model, sub)
            qlayers[key] = {
                "int8_weight": wq,
                "weight_scale": (scale.tolist() if scale.ndim
                                 else float(scale)),
                "act_scale": float(np.asarray(unwrap(sub.act_scale))),
                "bits": sub._wbits,
            }
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdqparams", "wb") as f:
        pickle.dump(qlayers, f)
    manifest = {k: {kk: vv for kk, vv in v.items() if kk != "int8_weight"}
                for k, v in qlayers.items()}
    with open(path_prefix + ".pdquant.json", "w") as f:
        json.dump(manifest, f, indent=2)

    # serving export through the standard predictor path: weights enter
    # the AOT artifact already fake-quantized (int grid), so serving
    # numerics == QAT eval numerics
    from ..inference import save_inference_model

    return save_inference_model(path_prefix, model,
                                input_spec=input_spec,
                                example_inputs=example_inputs)


def _layer_path(root, target):
    for name, sub in root.named_sublayers():
        if sub is target:
            return name
    return f"id{id(target)}"


# --------------------------------------------------------------------------
# Post-training quantization
# --------------------------------------------------------------------------


class _PTQWrapper(_QuantWrapper):
    """Frozen-scale variant used by PostTrainingQuantization: both scales
    come from calibration buffers (weight_scale may be per-channel) and
    are never re-observed — eval-only, no STE training path."""

    def forward(self, x):
        xq = fake_quant(x, self.act_scale, self._abits)
        wq = fake_quant(self.inner.weight, self.weight_scale, self._wbits)
        return self._compute(xq, wq)


class _PTQLinear(_PTQWrapper, QuantizedLinear):
    pass


class _PTQConv2D(_PTQWrapper, QuantizedConv2D):
    pass


_PTQ_TYPES = {"Linear": _PTQLinear, "Conv2D": _PTQConv2D}
# per-channel axis of the weight tensor: Linear weight is [in, out]
# (nn/functional linear convention), Conv2D weight is [out, in, kh, kw]
_CHANNEL_AXIS = {"Linear": 1, "Conv2D": 0}

_HIST_BINS = 2048


class _ActObserver:
    """Accumulates |activation| statistics across calibration batches:
    running abs-max, per-batch abs-max list, and a re-binnable histogram
    (the data the KL/hist/mse threshold searches run on).  Mirrors the
    collection phase of the reference's PostTrainingQuantization
    (post_training_quantization.py:120 _sample_abs_max/_sample_histogram)
    without its Program instrumentation — here it is a forward-pre-hook.
    """

    def __init__(self):
        self.abs_max = 0.0
        self.batch_maxes = []
        self.hist = np.zeros(_HIST_BINS, np.float64)
        self.hist_max = 0.0

    def collect(self, x):
        a = np.abs(np.asarray(unwrap(x), np.float32)).ravel()
        m = float(a.max()) if a.size else 0.0
        self.batch_maxes.append(m)
        self.abs_max = max(self.abs_max, m)
        if m == 0.0:
            return
        if m > self.hist_max:  # re-bin the old histogram into the new range
            if self.hist_max > 0.0:
                old_centers = (np.arange(_HIST_BINS) + 0.5) \
                    * (self.hist_max / _HIST_BINS)
                idx = np.minimum(
                    (old_centers / m * _HIST_BINS).astype(np.int64),
                    _HIST_BINS - 1)
                new = np.zeros(_HIST_BINS, np.float64)
                np.add.at(new, idx, self.hist)
                self.hist = new
            self.hist_max = m
        h, _ = np.histogram(a, bins=_HIST_BINS, range=(0.0, self.hist_max))
        self.hist += h

    # --- threshold selection ---------------------------------------------

    def threshold(self, algo, hist_percent=0.99999, bits=8):
        if self.abs_max == 0.0:
            return 1e-8
        if algo in ("abs_max", "min_max"):
            return self.abs_max
        if algo == "avg":
            return float(np.mean(self.batch_maxes))
        if algo == "hist":
            cdf = np.cumsum(self.hist) / max(self.hist.sum(), 1.0)
            bin_ = int(np.searchsorted(cdf, hist_percent))
            return (bin_ + 0.5) * self.hist_max / _HIST_BINS
        if algo == "KL":
            return self._kl_threshold(bits)
        if algo == "mse":
            return self._mse_threshold(bits)
        raise ValueError(f"unknown PTQ algo '{algo}'")

    def _kl_threshold(self, bits):
        """TensorRT-style search: pick the clip bin whose clipped+requantized
        distribution minimizes KL(P||Q) against the original."""
        levels = 2 ** (bits - 1)
        hist = self.hist / max(self.hist.sum(), 1.0)
        best_bin, best_kl = _HIST_BINS - 1, np.inf
        for end in range(levels, _HIST_BINS + 1, 16):
            p = hist[:end].copy()
            p[-1] += hist[end:].sum()  # clip mass onto the last kept bin
            psum = p.sum()
            if psum <= 0:
                continue
            p /= psum
            # Q is built from the UNCLIPPED slice: the clipped tail mass
            # belongs to P only, so saturating early (end == levels) is
            # penalized by exactly that tail mass rather than scoring a
            # degenerate KL of 0
            ref = hist[:end]
            q = np.zeros(end)
            chunk = end / levels
            for i in range(levels):  # downsample to the int8 grid
                lo = int(i * chunk)
                hi = max(int((i + 1) * chunk), lo + 1)
                mass = ref[lo:hi].sum()
                nz = (ref[lo:hi] > 0).sum()
                if nz:
                    q[lo:hi] = np.where(ref[lo:hi] > 0, mass / nz, 0.0)
            qsum = q.sum()
            if qsum <= 0:
                continue
            q /= qsum
            keep = p > 0
            kl = float(np.sum(p[keep] * np.log(
                p[keep] / np.maximum(q[keep], 1e-12))))
            if kl < best_kl:
                best_kl, best_bin = kl, end - 1
        return (best_bin + 0.5) * self.hist_max / _HIST_BINS

    def _mse_threshold(self, bits):
        """Pick the clip threshold minimizing expected squared quant error
        under the collected histogram."""
        qmax = 2 ** (bits - 1) - 1
        centers = (np.arange(_HIST_BINS) + 0.5) * (self.hist_max / _HIST_BINS)
        best_t, best_err = self.abs_max, np.inf
        for frac in np.linspace(0.3, 1.0, 50):
            t = self.hist_max * frac
            step = t / qmax
            q = np.clip(np.round(centers / step), -qmax, qmax) * step
            err = float(np.sum(self.hist * (centers - q) ** 2))
            if err < best_err:
                best_err, best_t = err, t
        return best_t


class PostTrainingQuantization:
    """Calibration-based int8 quantization of a trained model — no
    retraining (reference: fluid/contrib/slim/quantization/
    post_training_quantization.py:120, minus the Program/executor
    machinery: calibration here is eager forwards over a DataLoader).

    ``algo``: activation-threshold selection — 'abs_max' (global max),
    'avg' (mean of per-batch maxes), 'hist' (percentile),
    'KL' (min-divergence clip), 'mse' (min squared error clip).
    ``weight_quantize_type``: 'abs_max' (per-tensor) or
    'channel_wise_abs_max' (per-output-channel, the reference default
    for conv).

    Usage::

        ptq = PostTrainingQuantization(model, data_loader, batch_nums=8,
                                       algo='KL')
        qmodel = ptq.quantize()
        ptq.save_quantized_model('export/int8_model', example_inputs=[x])
    """

    def __init__(self, model: Layer, data_loader=None, batch_nums=10,
                 algo="hist", hist_percent=0.99999,
                 quantizable_layer_type=("Linear", "Conv2D"),
                 weight_bits=8, activation_bits=8,
                 weight_quantize_type="channel_wise_abs_max"):
        self._model = model
        self._loader = data_loader
        self._batch_nums = int(batch_nums)
        self._algo = algo
        self._hist_percent = hist_percent
        self._types = tuple(quantizable_layer_type)
        self._wbits = weight_bits
        self._abits = activation_bits
        self._wtype = weight_quantize_type
        self._quantized = None

    def quantize(self) -> Layer:
        model = self._model
        model.eval()
        # 1) attach observers to every quantizable leaf
        observers, removes = {}, []
        for holder, name, sub in _walk(model):
            kind = type(sub).__name__
            if kind in self._types and kind in _PTQ_TYPES:
                obs = _ActObserver()
                observers[id(sub)] = (holder, name, sub, kind, obs)
                removes.append(sub.register_forward_pre_hook(
                    lambda layer, args, _o=obs: _o.collect(args[0])))
        if not observers:
            raise ValueError("no quantizable sublayers found "
                             f"(types={self._types})")
        # 2) calibration forwards
        if self._loader is not None:
            for i, batch in enumerate(self._loader):
                if i >= self._batch_nums:
                    break
                xs = batch[0] if isinstance(batch, (list, tuple)) else batch
                model(xs if isinstance(xs, Tensor) else Tensor(np.asarray(xs)))
        for h in removes:
            h.remove()
        if not any(obs.batch_maxes for *_, obs in observers.values()):
            raise ValueError(
                "PostTrainingQuantization saw no calibration batches — "
                "pass a data_loader yielding representative inputs "
                "(activation scales cannot be inferred without them)")
        # 3) freeze scales into PTQ wrappers
        for holder, name, sub, kind, obs in observers.values():
            wrapper = _PTQ_TYPES[kind](sub, self._wbits, self._abits)
            act_scale = obs.threshold(self._algo, self._hist_percent,
                                      self._abits) if obs.batch_maxes \
                else float(np.max(np.abs(np.asarray(unwrap(sub.weight)))))
            wrapper.act_scale.set_value(jnp.asarray(act_scale, jnp.float32))
            w = np.asarray(unwrap(sub.weight), np.float32)
            if self._wtype == "channel_wise_abs_max":
                axis = _CHANNEL_AXIS[kind]
                red = tuple(i for i in range(w.ndim) if i != axis)
                ws = np.max(np.abs(w), axis=red, keepdims=True)
            else:
                ws = np.max(np.abs(w))
            wrapper.weight_scale.set_value(
                jnp.asarray(np.maximum(ws, 1e-8), jnp.float32))
            setattr(holder, name, wrapper)
        self._quantized = model
        return model

    def save_quantized_model(self, path_prefix, input_spec=None,
                             example_inputs=None):
        if self._quantized is None:
            self.quantize()
        return save_quantized_model(self._quantized, path_prefix,
                                    input_spec, example_inputs)


def load_quantized_predictor(path_prefix):
    """Serve an int8 export: standard Predictor + access to the int8
    payload (size check / custom kernels)."""
    from ..inference import Predictor, Config

    pred = Predictor(Config(path_prefix))
    with open(path_prefix + ".pdqparams", "rb") as f:
        pred.quant_params = pickle.load(f)
    return pred
