"""paddle.slim — quantization-aware training + int8 export.

Reference parity: python/paddle/fluid/contrib/slim/quantization/
(quantization_pass.py QuantizationTransformPass — fake-quant op insertion
on conv/mul inputs+weights with moving-average abs-max scales;
imperative/qat.py ImperativeQuantAware — the dygraph API this module
mirrors).

TPU-native design: the reference rewrites the program graph, inserting
fake_quantize_dequantize ops; here quantization is a LAYER TRANSFORM —
quantizable layers (Linear/Conv2D) are wrapped so weights and activations
pass through a straight-through-estimator fake-quant before compute.  The
wrapped model stays a normal Layer: it jits, trains, saves.  Export packs
weights as int8 + per-tensor scale (the artifact the reference's
save_quantized_model produces) and serves through the standard Predictor
with an inline dequantize — XLA folds the int8→f32 convert into the
matmul epilogue on TPU.
"""
from __future__ import annotations

import json
import os
import pickle

import numpy as np

import jax
import jax.numpy as jnp

from ..nn.layer_base import Layer
from ..tensor import Tensor, apply, unwrap

__all__ = ["QAT", "ImperativeQuantAware", "fake_quant",
           "QuantizedLinear", "QuantizedConv2D", "save_quantized_model",
           "load_quantized_predictor"]


def fake_quant(x, scale, bits=8):
    """Symmetric per-tensor fake quantize-dequantize with a straight-
    through estimator gradient (quantization_pass.py
    fake_quantize_dequantize_moving_average_abs_max): values round onto
    the int grid in the forward pass, gradients flow as identity."""
    def f(v, s):
        qmax = float(2 ** (bits - 1) - 1)
        step = jnp.maximum(s.astype(v.dtype), 1e-8) / qmax
        q = jnp.clip(jnp.round(v / step), -qmax, qmax) * step
        return v + jax.lax.stop_gradient(q - v)

    return apply(f, x, scale)


class _QuantWrapper(Layer):
    """Shared machinery: activation observer (moving-average abs-max) +
    weight fake-quant around an inner layer's compute."""

    def __init__(self, inner, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self.inner = inner
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate
        self.register_buffer("act_scale",
                             Tensor(jnp.ones((), jnp.float32)),
                             persistable=True)
        self.register_buffer("weight_scale",
                             Tensor(jnp.ones((), jnp.float32)),
                             persistable=True)

    def _observe(self, x):
        """Update the activation scale (EMA of abs-max) during training;
        buffer-update semantics match BN running stats (jit-safe through
        the functional bridge)."""
        if not self.training:
            return
        cur = jnp.max(jnp.abs(unwrap(x))).astype(jnp.float32)
        r = self._rate
        self.act_scale.set_value(
            unwrap(self.act_scale) * r + cur * (1 - r))

    def _wscale(self):
        w = unwrap(self.inner.weight)
        cur = jnp.max(jnp.abs(w)).astype(jnp.float32)
        if self.training:
            self.weight_scale.set_value(cur)
        return cur

    def forward(self, x):
        self._observe(x)
        xq = fake_quant(x, self.act_scale, self._abits)
        wq = fake_quant(self.inner.weight, Tensor(self._wscale()),
                        self._wbits)
        return self._compute(xq, wq)


class QuantizedLinear(_QuantWrapper):
    def _compute(self, xq, wq):
        from ..nn import functional as F

        return F.linear(xq, wq, self.inner.bias)


class QuantizedConv2D(_QuantWrapper):
    def _compute(self, xq, wq):
        from ..nn import functional as F

        inner = self.inner
        return F.conv2d(xq, wq, inner.bias, stride=inner._stride,
                        padding=inner._padding, dilation=inner._dilation,
                        groups=inner._groups,
                        data_format=inner._data_format)


_QUANTIZABLE = {"Linear": QuantizedLinear, "Conv2D": QuantizedConv2D}


class ImperativeQuantAware:
    """Dygraph QAT driver (imperative/qat.py): wrap quantizable sublayers
    in place, train as usual, then export int8."""

    def __init__(self, quantizable_layer_type=("Linear", "Conv2D"),
                 weight_bits=8, activation_bits=8, moving_rate=0.9):
        self._types = tuple(quantizable_layer_type)
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate

    def quantize(self, model: Layer) -> Layer:
        for holder, name, sub in _walk(model):
            kind = type(sub).__name__
            if kind in self._types and kind in _QUANTIZABLE:
                wrapped = _QUANTIZABLE[kind](
                    sub, self._wbits, self._abits, self._rate)
                setattr(holder, name, wrapped)
        return model

    def save_quantized_model(self, model, path, input_spec=None,
                             example_inputs=None):
        return save_quantized_model(model, path, input_spec,
                                    example_inputs)


QAT = ImperativeQuantAware  # paddle.slim 2.x alias


def _walk(layer, prefix=""):
    """Yield (holder, attr_name, sublayer) for every direct child,
    recursively (post-order not needed: wrapping replaces leaves)."""
    for name, sub in list(layer._sub_layers.items()):
        yield layer, name, sub
        yield from _walk(sub, prefix + name + ".")


def save_quantized_model(model, path_prefix, input_spec=None,
                         example_inputs=None):
    """Export the trained QAT model with REAL int8 weights + scales
    (the reference's save_quantized_model artifact): .pdqparams holds
    int8 weight bytes and f32 scales; serving dequantizes inline."""
    model.eval()
    qlayers = {}
    for holder, name, sub in _walk(model):
        if isinstance(sub, _QuantWrapper):
            w = np.asarray(unwrap(sub.inner.weight))
            # abs-max of the CURRENT weight — the same value _wscale()
            # returns during the eval-mode export trace below.  The
            # weight_scale buffer only updates on training forwards, so
            # after the final optimizer step it is stale and the packed
            # int8 payload would not reproduce the served numerics.
            scale = float(np.max(np.abs(w)))
            qmax = 2 ** (sub._wbits - 1) - 1
            step = max(scale, 1e-8) / qmax
            wq = np.clip(np.round(w / step), -qmax, qmax).astype(np.int8)
            key = _layer_path(model, sub)
            qlayers[key] = {
                "int8_weight": wq,
                "weight_scale": scale,
                "act_scale": float(np.asarray(unwrap(sub.act_scale))),
                "bits": sub._wbits,
            }
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdqparams", "wb") as f:
        pickle.dump(qlayers, f)
    manifest = {k: {kk: vv for kk, vv in v.items() if kk != "int8_weight"}
                for k, v in qlayers.items()}
    with open(path_prefix + ".pdquant.json", "w") as f:
        json.dump(manifest, f, indent=2)

    # serving export through the standard predictor path: weights enter
    # the AOT artifact already fake-quantized (int grid), so serving
    # numerics == QAT eval numerics
    from ..inference import save_inference_model

    return save_inference_model(path_prefix, model,
                                input_spec=input_spec,
                                example_inputs=example_inputs)


def _layer_path(root, target):
    for name, sub in root.named_sublayers():
        if sub is target:
            return name
    return f"id{id(target)}"


def load_quantized_predictor(path_prefix):
    """Serve an int8 export: standard Predictor + access to the int8
    payload (size check / custom kernels)."""
    from ..inference import Predictor, Config

    pred = Predictor(Config(path_prefix))
    with open(path_prefix + ".pdqparams", "rb") as f:
        pred.quant_params = pickle.load(f)
    return pred
