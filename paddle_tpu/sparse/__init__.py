"""paddle_tpu.sparse — the TPU-native recommender/sparse workload.

The reference Paddle served this workload with a parameter-server core
(`PSServer`/`PSClient`, `CommonSparseTable`); here the same three jobs
are mesh-native:

* `table` — `ShardedEmbeddingTable` / `embedding_lookup`: the table is
  row-sharded over the mesh via SpecLayout (`P(('fsdp','tp'), None)`),
  lookup is an in-graph gather and the gradient a deduped scatter-add
  inside the one donated jitted step (the PS pull/push round-trip,
  deleted).
* `vocab` — `VocabAdmission`: count-min frequency sketch + admission
  threshold + cold-row eviction on the host input thread; state rides
  the checkpoint manifest.
* `stream` — ragged click-log batches → padded/bucketed dense batches
  on the prefetch thread, pre-sharded via `shard_batch`.
* `serve` — `SparseLookupPredictor` / `lookup_engine`: sharded lookup
  behind the serving batcher, AOT-warmed per bucket.
"""
from .table import (ShardedEmbeddingTable, dedup_segments,  # noqa: F401
                    embedding_lookup, table_spec)
from .vocab import OOV_ROW, CountMinSketch, VocabAdmission  # noqa: F401
from .stream import (ClickLogDataset, bucket_for,  # noqa: F401
                     make_stream_loader, ragged_collate,
                     synthetic_click_log)
from .serve import SparseLookupPredictor, lookup_engine  # noqa: F401

__all__ = [
    "ShardedEmbeddingTable", "embedding_lookup", "dedup_segments",
    "table_spec", "OOV_ROW", "CountMinSketch", "VocabAdmission",
    "ClickLogDataset", "bucket_for", "make_stream_loader",
    "ragged_collate", "synthetic_click_log", "SparseLookupPredictor",
    "lookup_engine",
]
