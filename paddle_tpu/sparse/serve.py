"""Serving path for sharded embedding lookup.

`SparseLookupPredictor` wraps a (mesh-sharded) table behind the same
duck-typed predictor contract the `serving.ServingEngine` batcher
already speaks — ``.run(list) -> list`` plus ``compile_count`` and
``_input_specs`` — so the whole serving stack (adaptive batching,
bucket warmup, queue backpressure, /metrics) works on embedding lookups
unchanged.  Each (batch × id-list-length) bucket is AOT-compiled via
``jit(...).lower().compile()`` exactly once; steady-state lookups never
compile, and per-call device latency feeds the
``paddle_sparse_lookup_ms`` reservoir (p50/p99 in /metrics).
"""
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.transfer import host_fetch
from ..utils.metrics import default_registry
from .table import table_spec

__all__ = ["SparseLookupPredictor", "lookup_engine"]


def _pooled(table, ids):
    """Mean-pool embedding rows per request; padded slots (row 0 under
    an admission vocab) participate like any OOV click — serving has no
    per-request length channel, and the shared row is trained."""
    emb = jnp.take(table, ids, axis=0)
    return jnp.mean(emb, axis=1)


class SparseLookupPredictor:
    """AOT-bucketed sharded-table lookup with the Predictor duck type.

    Args:
      table: ``[vocab, dim]`` array (numpy or jax).
      mesh: optional Mesh; the table is placed ONCE, row-sharded on
        ``spec`` (axes absent from the mesh are dropped), and every
        lookup gathers from the sharded copy.
      spec: row-sharding PartitionSpec, default ``P(('fsdp','tp'), None)``.
      vocab: optional `VocabAdmission` — raw request ids are translated
        through its read-only ``lookup_rows`` (unknown ids → OOV row).
      pooled: return the mean-pooled ``[B, dim]`` vector per request
        (the wide-and-deep serving half) instead of ``[B, L, dim]``.
    """

    def __init__(self, table, mesh=None, spec=None, vocab=None,
                 pooled=True, registry=None):
        spec = spec if spec is not None else table_spec()
        arr = jnp.asarray(getattr(table, "value", table))
        if mesh is not None:
            axes = mesh.axis_names
            kept = tuple(
                tuple(a for a in e if a in axes) or None
                if isinstance(e, tuple) else (e if e in axes else None)
                for e in spec)
            arr = jax.device_put(arr, NamedSharding(mesh, P(*kept)))
        self._table = arr
        self._mesh = mesh
        self._vocab = vocab
        self._pooled = pooled
        self._cache = {}
        self.compile_count = 0
        # ServingEngine reads this for bucket warmup: one int32 input of
        # [batch, id-list-length], both dims dynamic (bucketed).
        self._input_specs = [{"shape": (-1, -1), "dtype": "int32"}]
        reg = registry or default_registry()
        self._lookup_ms = reg.reservoir("paddle_sparse_lookup_ms")

    def _compiled(self, shape):
        fn = self._cache.get(shape)
        if fn is None:
            fun = _pooled if self._pooled \
                else lambda t, i: jnp.take(t, i, axis=0)
            tspec = jax.ShapeDtypeStruct(self._table.shape,
                                         self._table.dtype,
                                         sharding=self._table.sharding)
            ispec = jax.ShapeDtypeStruct(shape, jnp.int32)
            if self._mesh is not None:
                ispec = jax.ShapeDtypeStruct(
                    shape, jnp.int32,
                    sharding=NamedSharding(self._mesh, P()))
            fn = jax.jit(fun).lower(tspec, ispec).compile()
            self._cache[shape] = fn
            self.compile_count += 1
        return fn

    def run(self, args):
        """[ids_batch] -> [embeddings]: the ServingEngine predictor
        contract (one padded int32 ``[B, L]`` array in, one array out)."""
        (ids,) = args
        ids = np.asarray(ids, np.int32)
        if self._vocab is not None:
            ids = self._vocab.lookup_rows(ids).astype(np.int32)
        fn = self._compiled(ids.shape)
        t0 = time.perf_counter()
        dev_ids = (jax.device_put(ids, NamedSharding(self._mesh, P()))
                   if self._mesh is not None else jnp.asarray(ids))
        out = fn(self._table, dev_ids)
        with host_fetch():
            # the latency a client sees includes materializing the
            # result; blocking here also makes the reservoir honest
            out.block_until_ready()
        self._lookup_ms.observe((time.perf_counter() - t0) * 1e3)
        return [out]


def lookup_engine(table, mesh=None, vocab=None, pooled=True,
                  max_batch_size=8, id_buckets=(4, 8, 16), **kw):
    """A started-ready `serving.ServingEngine` over a sharded table.

    Requests are single ``[L]`` int32 id lists; the batcher pads L to
    ``id_buckets`` and the batch dim to its power-of-two buckets, all
    AOT-warmed on ``start()`` so steady-state lookups never compile.
    """
    from ..serving.engine import BucketSpec, ServingEngine

    predictor = SparseLookupPredictor(table, mesh=mesh, vocab=vocab,
                                      pooled=pooled)
    batches = [b for b in (1, 2, 4, 8, 16, 32, 64, 128)
               if b <= max_batch_size] or [max_batch_size]
    buckets = BucketSpec(batch_sizes=tuple(batches),
                         seq_lens=tuple(sorted(id_buckets)))
    return ServingEngine(predictor, max_batch_size=max_batch_size,
                         buckets=buckets, seq_axis=0, **kw)
