"""Streaming recommender data plane: click logs → dense sharded batches.

A click-log sample is ragged — ``(user_id, [item_id, ...], label)`` with
a per-sample item-list length — and the jitted step wants fixed shapes.
This module turns the former into the latter ON THE PREFETCH THREAD,
riding the existing `io.DataLoader` seams end to end:

* `ragged_collate(...)` pads each batch's item lists to the smallest
  configured length bucket (a handful of XLA shapes, not one per batch)
  and runs vocab admission (`VocabAdmission.map_ids`) on the raw ids —
  both execute inside `DataLoader._produce`, i.e. on the prefetch
  thread, overlapped with device compute.
* `make_stream_loader(...)` wires the collate into a buffered
  `DataLoader` and installs `framework.transfer.shard_batch` as the
  placement hook, so every batch lands pre-sharded on the mesh's batch
  axes.  The loader's bounded prefetch queue IS the backpressure: a
  slow consumer blocks the producer after `prefetch_factor` batches.

`synthetic_click_log` generates a seeded Zipf-ish stream for tests,
benches, and the wide-and-deep example.
"""
from functools import partial

import numpy as np

from ..framework.transfer import shard_batch
from ..io import DataLoader, IterableDataset, pad_ragged

__all__ = ["synthetic_click_log", "ClickLogDataset", "bucket_for",
           "ragged_collate", "make_stream_loader"]


def synthetic_click_log(num_events, num_users=10000, num_items=50000,
                        max_items=12, seed=0):
    """Seeded synthetic click-log reader-creator.

    Returns a zero-arg callable yielding ``(user_id, item_ids, label)``
    — the same creator convention as `dataset.movielens`.  Item ids are
    Zipf-distributed so a head of hot ids exists for the admission
    policy to find; the label is a noisy function of user/item parity so
    a model can actually learn it.
    """
    def reader():
        rs = np.random.RandomState(seed)
        for _ in range(num_events):
            user = int(rs.randint(0, num_users))
            n = int(rs.randint(1, max_items + 1))
            items = np.minimum(rs.zipf(1.3, size=n), num_items - 1) \
                .astype(np.int64)
            signal = (user + int(items.sum())) % 2
            label = signal if rs.rand() > 0.1 else 1 - signal
            yield user, items.tolist(), float(label)
    return reader


class ClickLogDataset(IterableDataset):
    """IterableDataset over a reader creator (re-iterable per epoch)."""

    def __init__(self, reader_creator):
        self._creator = reader_creator

    def __iter__(self):
        return iter(self._creator())


def bucket_for(n, buckets):
    """Smallest bucket >= n (the last bucket caps — longer lists are
    truncated to it, keeping the most recent items)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def ragged_collate(batch, user_vocab=None, item_vocab=None,
                   buckets=(4, 8, 16), pad_value=0):
    """Collate ``(user_id, item_ids, label)`` samples into dense arrays.

    Returns ``(users [B], items [B, L], lengths [B], labels [B, 1])``
    with ``L`` the batch's length bucket.  Padded item slots carry
    ``pad_value`` (row 0 — the OOV row — under an admission vocab, so
    padding gathers the shared row and the mask, not the table layout,
    defines semantics).  Vocab admission runs here, on whichever thread
    drives the loader's producer generator — the prefetch thread.
    """
    users = np.asarray([s[0] for s in batch], np.int64)
    labels = np.asarray([s[2] for s in batch],
                        np.float32).reshape(-1, 1)
    items, lens = pad_ragged([s[1] for s in batch], buckets=buckets,
                             pad_value=pad_value)
    if user_vocab is not None:
        users = user_vocab.map_ids(users)
    if item_vocab is not None:
        items = item_vocab.map_ids(items)
    return (users.astype(np.int32), items.astype(np.int32),
            np.asarray(lens, np.int32), labels)


def make_stream_loader(reader_creator, batch_size, user_vocab=None,
                       item_vocab=None, buckets=(4, 8, 16), pad_value=0,
                       mesh=None, batch_axis="dp", drop_last=True,
                       prefetch_factor=2):
    """Buffered DataLoader over a click-log reader creator.

    With ``mesh=`` the placement hook pre-shards every batch over
    ``batch_axis`` (an axis name or tuple, e.g.
    ``SpecLayout.batch_axes(mesh)``) via `shard_batch` — on the prefetch
    thread, overlapping the device_put with compute.  The bounded
    prefetch queue (``prefetch_factor`` batches) is the backpressure
    between the log reader and the training step.
    """
    loader = DataLoader(
        ClickLogDataset(reader_creator), batch_size=batch_size,
        drop_last=drop_last,
        collate_fn=partial(ragged_collate, user_vocab=user_vocab,
                           item_vocab=item_vocab, buckets=buckets,
                           pad_value=pad_value),
        prefetch_factor=prefetch_factor)
    if mesh is not None:
        loader.placement = partial(shard_batch, mesh=mesh,
                                   axis=batch_axis)
    return loader
