"""Mesh-sharded embedding tables for the recommender workload.

The table parameter lives ROW-SHARDED over the mesh — `P(('fsdp','tp'),
None)` through the SpecLayout embeddings rule — so `vocab × dim` may
exceed any single device's HBM.  Lookup is an in-graph gather and the
gradient is a scatter-add that runs INSIDE the one donated jitted train
step: no host round-trip, no parameter-server RPC.  Repeated ids are
deduplicated before the scatter (sort + fixed-shape segment-sum), so a
hot id costs one scatter row per batch instead of one per occurrence.

Two entry points:

* `embedding_lookup(table, ids)` — the raw functional op (jax arrays in,
  jax array out), differentiable through the dedup scatter-add VJP.
* `ShardedEmbeddingTable` — an `nn.Embedding`-compatible layer whose
  parameter is named `embedding`, which the SpecLayout `_EMBED` pattern
  places on `P(('fsdp','tp'), None)`, so `Model.fit(layout=...)` shards
  it with no engine changes.
"""
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..nn import initializer as I
from ..nn.layer_base import Layer
from ..tensor import apply as _apply

__all__ = ["embedding_lookup", "dedup_segments", "ShardedEmbeddingTable"]


def dedup_segments(ids, values):
    """Combine `values` rows that share an id, at fixed shapes.

    `jnp.unique` is not jittable (data-dependent output shape), so the
    dedup is sort-based: sort by id, segment-sum runs of equal ids, and
    report one representative position per segment.  Returns
    ``(combined, rep_ids)`` both of length ``len(ids)``; segments past
    the (traced) unique count carry all-zero rows and rep_id 0, so a
    follow-up ``.at[rep_ids].add(combined)`` adds exact zeros there.
    """
    n = ids.shape[0]
    order = jnp.argsort(ids)
    sid = ids[order]
    svals = values[order]
    starts = jnp.concatenate(
        [jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    seg = jnp.cumsum(starts) - 1  # 0..n_unique-1, per sorted position
    combined = jax.ops.segment_sum(svals, seg, num_segments=n)
    rep = jnp.zeros((n,), sid.dtype).at[seg].max(sid)
    return combined, rep


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _lookup(num_rows, dim, dtype, table, ids):
    return jnp.take(table, ids, axis=0)


def _lookup_fwd(num_rows, dim, dtype, table, ids):
    return jnp.take(table, ids, axis=0), ids


def _lookup_bwd(num_rows, dim, dtype, ids, g):
    flat_ids = ids.reshape(-1)
    flat_g = g.reshape(-1, dim).astype(dtype)
    combined, rep = dedup_segments(flat_ids, flat_g)
    dtable = jnp.zeros((num_rows, dim), dtype).at[rep].add(combined)
    d_ids = np.zeros(ids.shape, dtype=jax.dtypes.float0)
    return dtable, d_ids


_lookup.defvjp(_lookup_fwd, _lookup_bwd)


def embedding_lookup(table, ids):
    """Gather rows of a (possibly mesh-sharded) `[vocab, dim]` table.

    Forward is `table[ids]`; the VJP scatter-adds the output cotangent
    back into a zero table AFTER merging duplicate ids (see
    `dedup_segments`), entirely in-graph.  `ids` may be any integer
    shape; output is `ids.shape + (dim,)`.
    """
    vocab, dim = table.shape
    return _lookup(int(vocab), int(dim), jnp.dtype(table.dtype).name,
                   table, ids.astype(jnp.int32))


def table_spec(fsdp_axis="fsdp", tp_axis="tp"):
    """The canonical row-sharding spec for a sparse table: vocab rows
    split over the combined fsdp×tp device group, dim replicated."""
    return P((fsdp_axis, tp_axis), None)


class ShardedEmbeddingTable(Layer):
    """`nn.Embedding`-compatible layer over a row-sharded table.

    The parameter attribute is named ``embedding`` so SpecLayout's
    `_EMBED` name pattern matches it (``P(('fsdp','tp'), None)`` with
    divisibility-aware pruning) under ``Model.fit(layout=...)``; a
    ``weight`` property keeps the `nn.Embedding` surface.  Pass
    ``shard_axes=('fsdp', 'tp')`` to annotate a `dist_spec` directly and
    shard without a layout (absent mesh axes degrade to replicated).

    ``vocab`` optionally attaches a `sparse.vocab.VocabAdmission`; its
    id→row state then rides the fault-tolerance checkpoint manifest
    beside this leaf (see `hapi.Model._ft_save_inner`) so resume keeps
    the mapping.
    """

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 weight_attr=None, vocab=None, shard_axes=None, name=None):
        super().__init__()
        self._num_embeddings = int(num_embeddings)
        self._embedding_dim = int(embedding_dim)
        self._padding_idx = padding_idx
        self._name = name
        self.embedding = self.create_parameter(
            [self._num_embeddings, self._embedding_dim], weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if shard_axes is not None:
            # same contract as distributed.meta_parallel.annotate: the
            # engine drops axes the mesh does not have
            self.embedding.dist_spec = P(tuple(shard_axes), None)
        self.vocab = vocab

    # nn.Embedding API surface
    @property
    def weight(self):
        return self.embedding

    @property
    def num_embeddings(self):
        return self._num_embeddings

    @property
    def embedding_dim(self):
        return self._embedding_dim

    def map_ids(self, ids):
        """Host-side admission: raw feature ids → table rows (or the
        shared OOV row).  Identity when no vocab policy is attached."""
        if self.vocab is None:
            return np.asarray(ids)
        return self.vocab.map_ids(ids)

    def forward(self, x):
        def f(ids, w):
            out = embedding_lookup(w, ids)
            if self._padding_idx is not None:
                mask = (ids == self._padding_idx)[..., None]
                out = jnp.where(mask, 0.0, out)
            return out
        return _apply(f, x, self.embedding)

    # -- checkpointable vocab state (picked up by Model._ft_save_inner) --
    def vocab_state_dict(self):
        if self.vocab is None:
            return None
        return self.vocab.state_dict()

    def load_vocab_state_dict(self, state):
        if self.vocab is not None and state:
            self.vocab.load_state_dict(state)

    def extra_repr(self):
        return (f"{self._num_embeddings}, {self._embedding_dim}"
                + (f", padding_idx={self._padding_idx}"
                   if self._padding_idx is not None else ""))
