"""Frequency-capped vocab with an admission policy.

The raw id space of a recommender (user ids, item ids, crossed
features) is unbounded; the device table is not.  `VocabAdmission` maps
raw ids to table rows on the HOST INPUT THREAD (the DataLoader prefetch
thread — never inside the jitted step):

* a count-min sketch estimates per-id frequency without storing ids,
* ids at/above the admission threshold get a dedicated row while free
  rows last,
* everything else shares the reserved OOV row 0,
* an eviction pass recycles rows whose id has not been seen for a
  configurable number of batches (cold rows), so the table tracks the
  current head of the distribution.

The whole policy is a deterministic function of the id stream (sketch
hashing is seeded, admission order is stream order), so two runs over
the same data produce the same id→row mapping — and the mapping is
JSON-serializable (`state_dict`) so it rides the checkpoint manifest
beside the table leaf and survives resume.

Admission telemetry lands in the shared metrics registry:
`paddle_sparse_admitted_total`, `paddle_sparse_oov_total`,
`paddle_sparse_evicted_total`.
"""
import base64

import numpy as np

from ..framework import flags as _flags
from ..utils.metrics import default_registry

__all__ = ["CountMinSketch", "VocabAdmission", "OOV_ROW"]

#: Row 0 of every admission-managed table is the shared out-of-vocab row.
OOV_ROW = 0

_PRIME_A = np.uint64(0x9E3779B97F4A7C15)   # splitmix64 odd constants
_PRIME_B = np.uint64(0xBF58476D1CE4E5B9)


class CountMinSketch:
    """Fixed-memory frequency estimates over an unbounded id stream.

    `depth` multiply-shift hash rows of `width` uint32 counters
    (`width` rounded up to a power of two); estimates never
    undercount, and overcount with probability that shrinks with
    depth×width.  All ops are vectorized numpy — this runs per batch on
    the input thread.
    """

    def __init__(self, width=8192, depth=4, seed=0):
        self.width = 1 << int(np.ceil(np.log2(max(2, width))))
        self.depth = int(depth)
        self._shift = np.uint64(64 - int(np.log2(self.width)))
        rs = np.random.RandomState(seed)
        # odd 64-bit multipliers: multiply-shift needs odd a
        self._a = (rs.randint(0, 2**63 - 1, size=self.depth)
                   .astype(np.uint64) * np.uint64(2) + np.uint64(1))
        self._b = rs.randint(0, 2**63 - 1, size=self.depth).astype(np.uint64)
        self.counts = np.zeros((self.depth, self.width), np.uint32)

    def _rows(self, ids):
        x = np.asarray(ids, np.uint64) * _PRIME_A
        x ^= x >> np.uint64(31)
        x *= _PRIME_B
        return [((x * self._a[r] + self._b[r]) >> self._shift)
                .astype(np.int64) for r in range(self.depth)]

    def add(self, ids):
        for r, idx in enumerate(self._rows(ids)):
            np.add.at(self.counts[r], idx, 1)

    def estimate(self, ids):
        """Per-id min-over-rows count estimate (uint32 array)."""
        rows = self._rows(ids)
        est = self.counts[0][rows[0]]
        for r in range(1, self.depth):
            est = np.minimum(est, self.counts[r][rows[r]])
        return est

    def state_dict(self):
        return {"width": int(self.width), "depth": int(self.depth),
                "counts": base64.b64encode(self.counts.tobytes()).decode()}

    def load_state_dict(self, state):
        if (int(state["width"]) != self.width
                or int(state["depth"]) != self.depth):
            raise ValueError(
                "sketch geometry mismatch: checkpoint "
                f"{state['depth']}x{state['width']} vs live "
                f"{self.depth}x{self.width}")
        self.counts = np.frombuffer(
            base64.b64decode(state["counts"]), np.uint32).reshape(
                self.depth, self.width).copy()


class VocabAdmission:
    """id→row mapping under a row budget, with frequency-gated admission.

    Args:
      capacity: total table rows, INCLUDING the reserved OOV row 0 —
        pass the table's ``num_embeddings``.
      threshold: minimum estimated frequency (inclusive) before an id
        earns a dedicated row; 1 admits on first sight.
      evict_after: batches an id may go unseen before `evict()` may
        recycle its row (None disables eviction).
      sketch_width / sketch_depth / seed: CountMinSketch geometry.
    """

    def __init__(self, capacity, threshold=None, evict_after=None,
                 sketch_width=8192, sketch_depth=4, seed=0,
                 registry=None):
        if capacity < 2:
            raise ValueError("capacity must be >= 2 (row 0 is OOV)")
        if threshold is None:
            threshold = int(_flags.flag(
                "FLAGS_sparse_admission_threshold", 2))
        if evict_after is None:
            evict_after = int(_flags.flag(
                "FLAGS_sparse_evict_after", 0)) or None
        self.capacity = int(capacity)
        self.threshold = int(threshold)
        self.evict_after = evict_after
        self.sketch = CountMinSketch(sketch_width, sketch_depth, seed)
        self._rows = {}            # raw id -> row
        self._row_id = {}          # row -> raw id (for eviction)
        self._free = list(range(self.capacity - 1, OOV_ROW, -1))
        self._last_seen = {}       # row -> batch counter at last sighting
        self.batches = 0
        reg = registry or default_registry()
        self._m_admit = reg.counter(
            "paddle_sparse_admitted_total",
            "ids granted a dedicated embedding row")
        self._m_oov = reg.counter(
            "paddle_sparse_oov_total",
            "id occurrences routed to the shared OOV row")
        self._m_evict = reg.counter(
            "paddle_sparse_evicted_total",
            "embedding rows recycled by the eviction pass")

    @property
    def free_rows(self):
        return len(self._free)

    @property
    def assigned(self):
        return len(self._rows)

    def lookup_rows(self, ids):
        """Read-only id→row mapping (serving path): no counting, no
        admission; unknown ids go to OOV."""
        flat = np.asarray(ids).reshape(-1)
        out = np.fromiter((self._rows.get(int(i), OOV_ROW) for i in flat),
                          np.int32, count=flat.size)
        return out.reshape(np.shape(ids))

    def map_ids(self, ids):
        """Training-path mapping: count every occurrence, admit ids that
        cross the threshold while rows last, route the rest to OOV.
        Deterministic in stream order.  Returns int32 rows, same shape
        as `ids`."""
        shape = np.shape(ids)
        flat = np.asarray(ids, np.int64).reshape(-1)
        self.batches += 1
        self.sketch.add(flat)
        # admission decisions on first occurrence per batch, stream order
        uniq, first_pos = np.unique(flat, return_index=True)
        order = np.argsort(first_pos)
        est = self.sketch.estimate(uniq)
        admitted = 0
        for k in order:
            rid = int(uniq[k])
            row = self._rows.get(rid)
            if row is None and int(est[k]) >= self.threshold and self._free:
                row = self._free.pop()
                self._rows[rid] = row
                self._row_id[row] = rid
                admitted += 1
            if row is not None:
                self._last_seen[row] = self.batches
        out = np.fromiter((self._rows.get(int(i), OOV_ROW) for i in flat),
                          np.int32, count=flat.size)
        n_oov = int((out == OOV_ROW).sum())
        if admitted:
            self._m_admit.inc(admitted)
        if n_oov:
            self._m_oov.inc(n_oov)
        return out.reshape(shape)

    def evict(self, now=None):
        """Recycle rows unseen for > `evict_after` batches.  Returns the
        recycled row indices (the caller may zero those table rows).
        Freed rows are re-admitted lowest-index-first, deterministic."""
        if self.evict_after is None:
            return []
        now = self.batches if now is None else now
        cold = [row for row, seen in self._last_seen.items()
                if now - seen > self.evict_after]
        for row in cold:
            rid = self._row_id.pop(row)
            del self._rows[rid]
            del self._last_seen[row]
            self._free.append(row)
        if cold:
            self._free.sort(reverse=True)
            self._m_evict.inc(len(cold))
        return sorted(cold)

    # -- persistence (JSON-safe: rides the checkpoint manifest) ----------
    def state_dict(self):
        return {
            "capacity": self.capacity,
            "threshold": self.threshold,
            "evict_after": self.evict_after,
            "batches": self.batches,
            "rows": {str(k): int(v) for k, v in self._rows.items()},
            "last_seen": {str(k): int(v)
                          for k, v in self._last_seen.items()},
            "sketch": self.sketch.state_dict(),
        }

    def load_state_dict(self, state):
        if int(state["capacity"]) != self.capacity:
            raise ValueError(
                f"vocab capacity mismatch: checkpoint {state['capacity']} "
                f"vs live {self.capacity}")
        self.threshold = int(state["threshold"])
        self.evict_after = state.get("evict_after")
        self.batches = int(state["batches"])
        self._rows = {int(k): int(v) for k, v in state["rows"].items()}
        self._row_id = {v: k for k, v in self._rows.items()}
        self._last_seen = {int(k): int(v)
                           for k, v in state.get("last_seen", {}).items()}
        used = set(self._rows.values())
        self._free = [r for r in range(self.capacity - 1, OOV_ROW, -1)
                      if r not in used]
        self.sketch.load_state_dict(state["sketch"])
