"""paddle.static — compatibility surface.

Reference parity: python/paddle/static (Program/Executor/program_guard/
InputSpec/data).  TPU-native stance (SURVEY.md §7): static mode IS
`jax.jit` of traced functions — there is no separate graph-building API.
This module keeps the entrypoints so reference scripts can be ported: a
"Program" records a python callable + input specs and Executor.run jit-runs
it.  New code should use paddle_tpu.jit.to_static directly.
"""
from __future__ import annotations

import contextlib
import threading

from ..jit import InputSpec  # re-export (paddle.static.InputSpec)
from ..tensor import Tensor
from . import nn  # noqa: F401  (paddle.static.nn.while_loop/cond/...)


class _Mode(threading.local):
    def __init__(self):
        self.static = False


_mode = _Mode()


def enable_static():
    _mode.static = True


def disable_static():
    _mode.static = False


def in_static_mode() -> bool:
    return _mode.static


class Program:
    """A captured computation (fluid framework.py Program:4094): ops on
    static.data() Variables record into an expression DAG (see
    program.py); `_train` holds the (loss, optimizer) a `minimize` under
    this program registered; Executor.run evaluates under jax.jit."""

    def __init__(self):
        self._builders = []  # legacy: callables executed by Executor.run
        self._train = None   # (loss Variable, Optimizer) from minimize
        self._jit_cache = {}
        self.random_seed = 0

    def clone(self, for_test=False):
        p = Program()
        p._builders = list(self._builders)
        if not for_test:
            p._train = self._train
        return p

    def global_block(self):
        return self

    def __repr__(self):
        return (f"Program(train={self._train is not None}, "
                f"num_builders={len(self._builders)})")


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _default_main, _default_startup
    prev = (_default_main, _default_startup)
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = prev


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed placeholder (fluid.data / framework.py Variable:938):
    returns a symbolic Variable; any op applied to it is captured into the
    current Program's expression DAG instead of executing (program.py)."""
    from .program import Variable

    return Variable(name=name, shape=shape, dtype=dtype)


class Executor:
    """Executor parity (fluid/executor.py Executor:475 / run:916): runs a
    captured Program (fetch evaluation and minimize-training under
    jax.jit), or a plain python callable over feeds."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None, fn=None, **kw):
        from .program import evaluate, train_step

        if fn is None and callable(program) and not isinstance(
                program, (Program, CompiledProgram)):
            fn = program
        if fn is not None:
            feed = feed or {}
            out = fn(**{k: (v if isinstance(v, Tensor) else Tensor(v))
                        for k, v in feed.items()})
            if fetch_list:
                return [out[k] if isinstance(out, dict) else out
                        for k in fetch_list]
            return out
        prog = program if program is not None else default_main_program()
        if isinstance(prog, CompiledProgram):
            prog = prog.program
        if not isinstance(prog, Program):
            raise TypeError(f"cannot run {type(prog).__name__}")
        feed = feed or {}
        if prog._train is not None:
            loss_var, opt = prog._train
            return train_step(loss_var, opt, feed, fetch_list,
                              prog._jit_cache)
        if not fetch_list:
            return []  # e.g. exe.run(startup_program): params are eager
        return evaluate(list(fetch_list), feed, jit_cache=prog._jit_cache)


@contextlib.contextmanager
def scope_guard(scope):
    yield


def global_scope():
    return None


class CompiledProgram:
    """Parity shim for fluid/compiler.py CompiledProgram — on TPU the
    multi-device build strategy is a sharding decision, see
    paddle_tpu.distributed."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy

    def with_data_parallel(self, loss_name=None, **kw):
        return self


class BuildStrategy:
    """Knob struct parity (framework/details/build_strategy.h) — consumed as
    hints; XLA performs the fusions these flags used to toggle."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.fuse_all_reduce_ops = True
        self.fuse_bn_act_ops = True
        self.fuse_elewise_add_act_ops = True
        self.enable_inplace = True
        self.memory_optimize = True
        self.sequential_execution = False
        self.sync_batch_norm = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=False, print_phase="both"):
    """Print a tensor's value and pass it through (operators/print_op.cc).
    Eager values print immediately; under a trace this lowers to
    jax.debug.print, so the compiled program prints at run time — the
    TPU-native equivalent of the reference's host-side PrintOp."""
    import jax
    import jax.numpy as jnp

    from ..tensor import Tensor, apply

    msg = message or ""
    name = getattr(input, "name", None) or "var"
    head = f"{msg} {name if print_tensor_name else ''}".strip()

    def f(v):
        if isinstance(v, jax.core.Tracer):
            jax.debug.print(head + " {}", v)
        else:
            parts = [head]
            if print_tensor_shape:
                parts.append(f"shape={tuple(v.shape)}")
            if print_tensor_type:
                parts.append(f"dtype={v.dtype}")
            flat = jnp.ravel(v)[:summarize]
            parts.append(f"data={flat}")
            print("  ".join(parts))
        return v

    return apply(f, input)
