"""paddle.static — compatibility surface.

Reference parity: python/paddle/static (Program/Executor/program_guard/
InputSpec/data).  TPU-native stance (SURVEY.md §7): static mode IS
`jax.jit` of traced functions — there is no separate graph-building API.
This module keeps the entrypoints so reference scripts can be ported: a
"Program" records a python callable + input specs and Executor.run jit-runs
it.  New code should use paddle_tpu.jit.to_static directly.
"""
from __future__ import annotations

import contextlib
import threading

from ..jit import InputSpec  # re-export (paddle.static.InputSpec)
from ..tensor import Tensor
from . import nn  # noqa: F401  (paddle.static.nn.while_loop/cond/...)


class _Mode(threading.local):
    def __init__(self):
        self.static = False


_mode = _Mode()


def enable_static():
    _mode.static = True


def disable_static():
    _mode.static = False


def in_static_mode() -> bool:
    return _mode.static


class Program:
    """A captured computation (fluid framework.py Program:4094): ops on
    static.data() Variables record into an expression DAG (see
    program.py); `_train` holds the (loss, optimizer) a `minimize` under
    this program registered; Executor.run evaluates under jax.jit."""

    def __init__(self):
        self._builders = []  # legacy: callables executed by Executor.run
        self._train = None   # (loss Variable, Optimizer) from minimize
        self._jit_cache = {}
        self.random_seed = 0

    def clone(self, for_test=False):
        p = Program()
        p._builders = list(self._builders)
        if not for_test:
            p._train = self._train
        return p

    def global_block(self):
        return self

    def __repr__(self):
        return (f"Program(train={self._train is not None}, "
                f"num_builders={len(self._builders)})")


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _default_main, _default_startup
    prev = (_default_main, _default_startup)
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = prev


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed placeholder (fluid.data / framework.py Variable:938):
    returns a symbolic Variable; any op applied to it is captured into the
    current Program's expression DAG instead of executing (program.py)."""
    from .program import Variable

    return Variable(name=name, shape=shape, dtype=dtype)


def builtins_any_is(v, seq):
    return any(v is s for s in seq)


class Executor:
    """Executor parity (fluid/executor.py Executor:475 / run:916): runs a
    captured Program (fetch evaluation and minimize-training under
    jax.jit), or a plain python callable over feeds."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None, fn=None, **kw):
        from .program import evaluate, train_step

        if fn is None and callable(program) and not isinstance(
                program, (Program, CompiledProgram)):
            fn = program
        if fn is not None:
            feed = feed or {}
            out = fn(**{k: (v if isinstance(v, Tensor) else Tensor(v))
                        for k, v in feed.items()})
            if fetch_list:
                return [out[k] if isinstance(out, dict) else out
                        for k in fetch_list]
            return out
        prog = program if program is not None else default_main_program()
        if isinstance(prog, CompiledProgram):
            prog = prog.program
        if not isinstance(prog, Program):
            raise TypeError(f"cannot run {type(prog).__name__}")
        feed = feed or {}
        if fetch_list:
            # remember fetch roots so static.save can find the captured
            # parameters of inference-only programs
            # Record the captured PARAMETERS (deduped by identity) —
            # what static.save actually needs — instead of accumulating
            # whole fetch DAGs, which kept every past expression (and
            # everything it closed over) alive for the Program's
            # lifetime (advisor r04).  The root list itself only keeps
            # the most recent fetches.
            from .program import collect_params

            cap = getattr(prog, "_captured_params", [])
            for p in collect_params(list(fetch_list)):
                if not builtins_any_is(p, cap):
                    cap.append(p)
            prog._captured_params = cap
            seen = [v for v in getattr(prog, "_captured_vars", [])
                    if not builtins_any_is(v, fetch_list)]
            prog._captured_vars = (seen + list(fetch_list))[-32:]
        if prog._train is not None:
            loss_var, opt = prog._train
            return train_step(loss_var, opt, feed, fetch_list,
                              prog._jit_cache)
        if not fetch_list:
            return []  # e.g. exe.run(startup_program): params are eager
        return evaluate(list(fetch_list), feed, jit_cache=prog._jit_cache)


@contextlib.contextmanager
def scope_guard(scope):
    yield


_global_scope = None


def global_scope():
    global _global_scope
    if _global_scope is None:
        _global_scope = Scope()
    return _global_scope


class CompiledProgram:
    """Parity shim for fluid/compiler.py CompiledProgram — on TPU the
    multi-device build strategy is a sharding decision, see
    paddle_tpu.distributed."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy

    def with_data_parallel(self, loss_name=None, **kw):
        return self


class BuildStrategy:
    """Knob struct parity (framework/details/build_strategy.h) — consumed as
    hints; XLA performs the fusions these flags used to toggle."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.fuse_all_reduce_ops = True
        self.fuse_bn_act_ops = True
        self.fuse_elewise_add_act_ops = True
        self.enable_inplace = True
        self.memory_optimize = True
        self.sequential_execution = False
        self.sync_batch_norm = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=False, print_phase="both"):
    """Print a tensor's value and pass it through (operators/print_op.cc).
    Eager values print immediately; under a trace this lowers to
    jax.debug.print, so the compiled program prints at run time — the
    TPU-native equivalent of the reference's host-side PrintOp."""
    import jax
    import jax.numpy as jnp

    from ..tensor import Tensor, apply

    msg = message or ""
    name = getattr(input, "name", None) or "var"
    head = f"{msg} {name if print_tensor_name else ''}".strip()

    def f(v):
        if isinstance(v, jax.core.Tracer):
            jax.debug.print(head + " {}", v)
        else:
            parts = [head]
            if print_tensor_shape:
                parts.append(f"shape={tuple(v.shape)}")
            if print_tensor_type:
                parts.append(f"dtype={v.dtype}")
            flat = jnp.ravel(v)[:summarize]
            parts.append(f"data={flat}")
            # static.Print emulates the reference Print OP: stdout
            # side effect is the operator's documented behavior
            print("  ".join(parts))  # noqa: PTA006
        return v

    return apply(f, input)


# --------------------------------------------------------------------------
# reference paddle.static surface completion (round-4)
# --------------------------------------------------------------------------
import os  # noqa: E402

from ..nn.layer_base import ParamAttr  # noqa: E402
from .program import Variable  # noqa: E402,F401


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from .. import create_parameter as _cp

    return _cp(shape, dtype, name, attr, is_bias, default_initializer)


def create_global_var(shape, value, dtype="float32", persistable=False,
                      force_cpu=False, name=None):
    from .. import create_global_var as _cg

    return _cg(shape, value, dtype, persistable, force_cpu, name)


def cpu_places(device_count=None):
    from ..framework.place import CPUPlace

    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """There is no CUDA here; accelerator places are TPUPlace
    (framework/place.py) — returned so device-list plumbing keeps
    working."""
    from ..framework.place import TPUPlace

    ids = device_ids if device_ids is not None else [0]
    return [TPUPlace(i) for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


class Scope:
    """Variable scope shim (fluid/executor.py global_scope): eager
    tensors own their storage, so a scope is a name->Tensor dict."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        from ..tensor import Tensor

        import jax.numpy as jnp

        if name not in self._vars:
            self._vars[name] = Tensor(jnp.zeros(()))
        return self._vars[name]

    def find_var(self, name):
        return self._vars.get(name)


class ParallelExecutor:
    """Shim (fluid/parallel_executor.py): multi-device execution is a
    sharding decision on the jitted step (paddle_tpu.distributed); runs
    delegate to Executor."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 build_strategy=None, exec_strategy=None, **kw):
        self._exe = Executor()
        self._program = main_program

    def run(self, fetch_list=None, feed=None, program=None, **kw):
        return self._exe.run(program or self._program, feed=feed,
                             fetch_list=fetch_list)


class WeightNormParamAttr(ParamAttr):
    """ParamAttr requesting weight normalization (fluid/param_attr.py
    WeightNormParamAttr).  The static-graph reparameterization hook does
    not exist here; `dim` is recorded and nn.utils-style weight norm
    should be applied at the layer level."""

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim


@contextlib.contextmanager
def name_scope(prefix=None):
    """Naming-only context (framework.py name_scope): names are cosmetic
    under tracing; kept for script compatibility."""
    yield


def py_func(func, x, out=None, backward_func=None,
            skip_vars_in_backward_input=None):
    """Run a python callable on tensors (py_func_op.cc).  Eager python IS
    the host language: the call happens directly; under program capture
    this is unsupported (use eager mode or to_static)."""
    import builtins

    from .program import Variable as _V

    xs = x if isinstance(x, (list, tuple)) else [x]
    if builtins.any(isinstance(a, _V) for a in xs):
        raise NotImplementedError(
            "py_func inside a captured Program is unsupported; run this "
            "part eagerly or wrap it with paddle.jit.to_static (README "
            "static-graph compatibility)")
    return func(*xs)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Batch AUC of concrete predictions (metrics/auc_op.cc): returns
    (auc_value, batch_auc, [stat_pos, stat_neg]) like the reference's
    three outputs.  Streaming accumulation lives in paddle.metric.Auc."""
    import numpy as np

    from ..metric import Auc as _Auc
    from ..tensor import Tensor, unwrap

    m = _Auc(num_thresholds=num_thresholds)
    m.update(np.asarray(unwrap(input)), np.asarray(unwrap(label)))
    v = float(m.accumulate())
    return (Tensor(np.float32(v)), Tensor(np.float32(v)),
            [Tensor(m._stat_pos.astype(np.float32)),
             Tensor(m._stat_neg.astype(np.float32))])


# -- program/parameter persistence ----------------------------------------
def _program_params(program):
    """Named captured parameters of a Program: the train objective's, or
    the tensors captured by fetch DAGs Executor.run has evaluated (kept
    on program._captured_vars)."""
    from .program import collect_params

    roots = []
    if program is not None:
        if program._train is not None:
            roots.append(program._train[0])
        roots.extend(getattr(program, "_captured_vars", ()))
    ps = list(collect_params(roots)) if roots else []
    # parameters recorded across ALL past Executor.run fetches (the
    # root list above is bounded to recent fetches; this is not)
    for p in getattr(program, "_captured_params", ()) if program else ():
        if not builtins_any_is(p, ps):
            ps.append(p)
    return {getattr(p, "name", None) or f"param_{i}": p
            for i, p in enumerate(ps)}


def save(program, model_path, protocol=4, **configs):
    """Persist a captured Program's parameters (static.save contract:
    .pdparams; no ProgramDesc exists to write — the compiled artifact
    path is inference.save_inference_model/StableHLO, see README)."""
    import pickle as _p
    import warnings as _w

    import numpy as _np

    params = {k: _np.asarray(v.numpy())
              for k, v in _program_params(program).items()}
    if not params:
        _w.warn(
            "static.save: this Program has no captured parameters (no "
            "minimize registered and no fetch evaluated yet) — writing "
            "an empty .pdparams", stacklevel=2)
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    with open(model_path + ".pdparams", "wb") as f:
        _p.dump(params, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """Restore parameters saved by static.save into the Program's
    captured tensors (by name, shape-checked)."""
    import pickle as _p

    import numpy as _np

    with open(model_path + ".pdparams", "rb") as f:
        state = _p.load(f)
    tgt = _program_params(program)
    for k, v in state.items():
        if k in tgt:
            have = tuple(tgt[k].shape)
            want = tuple(_np.shape(v))
            if have != want:
                raise ValueError(
                    f"static.load: parameter {k!r} has shape "
                    f"{list(have)} but the checkpoint holds "
                    f"{list(want)}")
            tgt[k].set_value(v)


def load_program_state(model_path, var_list=None):
    import pickle as _p

    with open(model_path + ".pdparams", "rb") as f:
        return _p.load(f)


def set_program_state(program, state_dict):
    import numpy as _np

    tgt = _program_params(program)
    for k, v in state_dict.items():
        if k in tgt:
            if tuple(tgt[k].shape) != tuple(_np.shape(v)):
                raise ValueError(
                    f"set_program_state: parameter {k!r} shape "
                    f"{list(tgt[k].shape)} != state {list(_np.shape(v))}")
            tgt[k].set_value(v)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    save(main_program, os.path.join(dirname, filename or "params"))


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    load(main_program, os.path.join(dirname, filename or "params"))


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Static-graph export (fluid/io.py save_inference_model:1198): the
    captured fetch DAG compiles straight to the StableHLO serving
    artifact (.pdexport + manifest) that inference.Predictor loads;
    captured parameters are baked into the exported graph as constants
    (a dedicated-weights export is jit.save / inference on a Layer)."""
    import json as _json
    import pickle as _pickle

    import numpy as _np

    import jax as _jax

    from ..framework.dtype import convert_dtype
    from ..tensor import unwrap
    from .program import _eval_fn, collect_params

    feeds = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetches = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    leaf_names = [fv.name for fv in feeds]
    params = collect_params(list(fetches))
    param_vals = [unwrap(p) for p in params]
    f = _eval_fn(list(fetches), leaf_names, params)

    def fn(*arrays):
        return tuple(f(list(arrays), param_vals))

    from ..inference import symbolic_input_specs, write_export_artifacts

    manifest_shapes = [[-1 if (d is None or d < 0) else int(d)
                        for d in fv.shape] for fv in feeds]
    dtypes = [convert_dtype(fv.dtype) or "float32" for fv in feeds]
    specs = symbolic_input_specs(manifest_shapes, dtypes)
    if specs is None:
        specs = [_jax.ShapeDtypeStruct(tuple(shp), _np.dtype(dt))
                 for shp, dt in zip(manifest_shapes, dtypes)]
    exported = _jax.export.export(_jax.jit(fn))(*specs)
    return write_export_artifacts(
        path_prefix, exported, [fv.name for fv in feeds],
        manifest_shapes, dtypes, aot_params={})  # params baked constant


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..inference import load_inference_model as _load

    return _load(path_prefix)


def serialize_program(feed_vars, fetch_vars, **kwargs):
    raise NotImplementedError(
        "there is no ProgramDesc to serialize on TPU (README static-graph "
        "compatibility): export compiled graphs with "
        "static.save_inference_model (StableHLO) and parameters with "
        "static.save")


def deserialize_program(data):
    raise NotImplementedError(
        "there is no ProgramDesc on TPU; load StableHLO exports with "
        "static.load_inference_model (README static-graph compatibility)")


def serialize_persistables(feed_vars, fetch_vars, **kwargs):
    raise NotImplementedError(
        "serialize parameters with static.save / load with static.load "
        "(no ProgramDesc persistable scan exists on TPU; README)")


def deserialize_persistables(program, data, executor=None):
    raise NotImplementedError(
        "restore parameters with static.load / set_program_state "
        "(README static-graph compatibility)")


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content if isinstance(content, bytes) else bytes(content))


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    raise NotImplementedError(
        "append_backward's op-insertion contract has no analog under "
        "tracing: use optimizer.minimize(loss) on a captured Program "
        "(gradients are taken by jax.value_and_grad at Executor.run; "
        "README static-graph compatibility)")


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    raise NotImplementedError(
        "symbolic static.gradients is not part of the capture layer: "
        "differentiate with paddle.grad (eager), jax.grad inside "
        "to_static, or optimizer.minimize on a Program (README "
        "static-graph compatibility)")
