"""Control-flow ops: while_loop / cond / case / switch_case + TensorArray.

Reference parity: python/paddle/fluid/layers/control_flow.py
(while_loop:1111, cond:2291, case:2470, switch_case:3587, array ops
:1455-2023) over paddle/fluid/operators/controlflow/{while_op.cc,
conditional_block_op.cc}.  Re-exported as paddle.static.nn.* like the
reference's python/paddle/static/nn/__init__.py:39-68.

TPU-native lowering: the reference executes sub-blocks op-by-op on the
host; here every construct lowers to XLA's structured control flow —
`lax.while_loop` / `lax.cond` / `lax.switch` — so it compiles into the
jitted step with no host round-trips and no unrolling.  Tensors are
pytree-registered, so loop_vars / branch outputs may be arbitrary nests of
paddle Tensors, jax arrays, and python scalars.

Gradients: `cond`/`case`/`switch_case` are reverse-differentiable
(lax.cond transposes).  Plain `while_loop` is forward-only under autodiff
(an XLA limit: reverse-mode needs a known trip count); pass
`max_iters=N` to lower to a masked bounded scan that IS
reverse-differentiable, or use `fori_collect` for fixed trip counts.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor

__all__ = ["while_loop", "cond", "case", "switch_case", "increment",
           "create_array", "array_write", "array_read", "array_length",
           "TensorArray", "StaticTensorArray", "tensor_array_to_tensor",
           "fori_collect"]


def _scalar_bool(x):
    v = x.value if isinstance(x, Tensor) else x
    if isinstance(v, bool):
        return jnp.bool_(v)
    v = jnp.asarray(v)
    if v.size != 1:
        raise TypeError(f"predicate must have exactly one element, "
                        f"got shape {v.shape}")
    return v.reshape(()).astype(jnp.bool_)


def _strip_tensors(tree):
    """Replace Tensor leaves with their raw arrays, recording (stop_gradient,
    name) metadata in flatten order.  Tensor carries aux data in its pytree
    treedef, so two branches (or a loop's init vs body output) that differ
    only in stop_gradient would otherwise be a structure mismatch inside
    lax.cond / lax.while_loop."""
    metas = []

    def f(x):
        if isinstance(x, Tensor):
            metas.append((x.stop_gradient, x.name))
            return x.value
        metas.append(None)
        return x

    stripped = jax.tree_util.tree_map(
        f, tree, is_leaf=lambda x: isinstance(x, Tensor))
    return stripped, metas


def _rewrap_tensors(tree, metas):
    """Inverse of _strip_tensors (same flatten order)."""
    it = iter(metas)

    def f(x):
        m = next(it)
        return Tensor(x, stop_gradient=m[0], name=m[1]) if m else x

    return jax.tree_util.tree_map(f, tree)


def _merge_metas(a, b):
    """Join branch metadata: a leaf is a Tensor if either branch made it
    one; gradient flows (stop_gradient False) if either branch tracked."""
    out = []
    for ma, mb in zip(a, b):
        if ma is None and mb is None:
            out.append(None)
        else:
            sg = ((ma[0] if ma else True) and (mb[0] if mb else True))
            out.append((sg, (ma or mb)[1]))
    return out


def while_loop(cond, body, loop_vars, is_test=False, name=None,
               max_iters=None):
    """Repeat `body` until `cond` is False (control_flow.py:1111).

    cond/body take as many arguments as loop_vars; body returns the same
    arity and structure.  Lowers to lax.while_loop (traced once, runs
    on-device).

    Reverse-mode: lax.while_loop cannot transpose (unknown trip count), so
    plain while_loop is forward-only under autodiff. Pass `max_iters=N` to
    lower to a masked lax.scan of N steps instead — iterations past the
    cond-False point are no-ops — which IS reverse-differentiable (the
    WhileGradOp analog, ref while_op.cc:209, with a static trip bound as
    the price of XLA's static-shape model)."""
    if not callable(cond) or not callable(body):
        raise TypeError("cond and body must be callable")
    if not isinstance(loop_vars, (list, tuple)):
        raise TypeError("loop_vars must be a list or tuple")
    if not loop_vars:
        raise ValueError("loop_vars is empty")
    vars_t = tuple(loop_vars)
    vars_s, metas = _strip_tensors(vars_t)
    body_metas = {}

    def cond_fn(vs):
        return _scalar_bool(cond(*_rewrap_tensors(vs, metas)))

    def body_fn(vs):
        out = body(*_rewrap_tensors(vs, metas))
        if not isinstance(out, (list, tuple)):
            out = (out,)
        if len(out) != len(vars_t):
            raise ValueError(
                f"body must return {len(vars_t)} values like loop_vars, "
                f"got {len(out)}")
        stripped, m = _strip_tensors(tuple(out))
        body_metas["m"] = m
        return stripped

    if max_iters is not None:
        def scan_body(vs, _):
            live = cond_fn(vs)
            new = body_fn(vs)
            vs = jax.tree.map(
                lambda a, b: jnp.where(live, b, a), vs, new)
            return vs, None

        out, _ = jax.lax.scan(scan_body, vars_s, None,
                              length=int(max_iters))
    else:
        out = jax.lax.while_loop(cond_fn, body_fn, vars_s)
    # a leaf tracks gradients (stop_gradient False) if EITHER the init or
    # the body output tracked it — rewrapping with init metas alone would
    # silently mark grad-carrying outputs stop_gradient=True
    out_metas = (_merge_metas(metas, body_metas["m"])
                 if "m" in body_metas else metas)
    out = _rewrap_tensors(out, out_metas)
    return list(out) if isinstance(loop_vars, list) else out


def cond(pred, true_fn=None, false_fn=None, name=None):
    """true_fn() if pred else false_fn() (control_flow.py:2291).

    Both branches must return the same nest structure; either may be None
    (treated as returning None).  Lowers to lax.cond — differentiable, and
    only the taken branch executes at runtime."""
    if true_fn is not None and not callable(true_fn):
        raise TypeError("true_fn must be callable")
    if false_fn is not None and not callable(false_fn):
        raise TypeError("false_fn must be callable")
    if true_fn is None and false_fn is None:
        return None
    t_fn = true_fn or (lambda: None)
    f_fn = false_fn or (lambda: None)
    if isinstance(pred, bool):  # python-static predicate: pick eagerly
        return t_fn() if pred else f_fn()

    info = {}

    def branch(fn, key):
        def g(_):
            stripped, metas = _strip_tensors(fn())
            info[key] = metas
            return stripped
        return g

    out = jax.lax.cond(_scalar_bool(pred), branch(t_fn, "t"),
                       branch(f_fn, "f"), 0)
    return _rewrap_tensors(out, _merge_metas(info["t"], info["f"]))


def case(pred_fn_pairs, default=None, name=None):
    """First (pred, fn) pair with a true pred wins (control_flow.py:2470).
    If none is true, `default` runs; if default is None the reference runs
    the LAST pair's fn — same here.  Lowers to a chain of lax.cond."""
    if not isinstance(pred_fn_pairs, (list, tuple)) or not pred_fn_pairs:
        raise TypeError("pred_fn_pairs must be a non-empty list/tuple")
    pairs = list(pred_fn_pairs)
    for i, pair in enumerate(pairs):
        if not (isinstance(pair, (list, tuple)) and len(pair) == 2
                and callable(pair[1])):
            raise TypeError(f"pred_fn_pairs[{i}] must be (pred, callable)")
    if default is None:
        default = pairs[-1][1]
        pairs = pairs[:-1]
    if not callable(default):
        raise TypeError("default must be callable")

    out = default()
    for pred, fn in reversed(pairs):
        if isinstance(pred, bool):
            out = fn() if pred else out
            continue
        out = cond(pred, fn, lambda o=out: o)
    return out


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Run the fn whose index matches branch_index (control_flow.py:3587).

    branch_fns: list of callables (indices 0..n-1), or list of (int, fn)
    pairs, or a dict {int: fn}.  Out-of-range / unmatched indices run
    `default` (or the fn with the MAX index when default is None — the
    reference's rule).  Lowers to lax.switch."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    elif isinstance(branch_fns, (list, tuple)) and branch_fns and \
            callable(branch_fns[0]):
        pairs = list(enumerate(branch_fns))
    else:
        pairs = sorted(branch_fns, key=lambda p: p[0])
    for idx, fn in pairs:
        if not isinstance(idx, int):
            raise TypeError(f"branch index {idx!r} must be int")
        if not callable(fn):
            raise TypeError(f"branch_fns[{idx}] must be callable")
    keys = [idx for idx, _ in pairs]
    if len(set(keys)) != len(keys):
        raise ValueError(f"duplicate branch indices: {keys}")
    if default is None:
        default = dict(pairs)[max(keys)]
    if not callable(default):
        raise TypeError("default must be callable")

    bi = branch_index.value if isinstance(branch_index, Tensor) \
        else branch_index
    bi = jnp.asarray(bi).reshape(()).astype(jnp.int32)
    # position in the dense fn table: count of keys < bi when matched,
    # else the trailing default slot
    keys_arr = jnp.asarray(keys, jnp.int32)
    matched = (keys_arr == bi)
    pos = jnp.where(matched.any(), jnp.argmax(matched), len(keys))
    metas_by_slot = {}

    def wrap(fn, slot):
        def g(_):
            stripped, metas = _strip_tensors(fn())
            metas_by_slot[slot] = metas
            return stripped
        return g

    fns = [wrap(fn, i) for i, (_, fn) in enumerate(pairs)]
    fns.append(wrap(default, len(pairs)))
    out = jax.lax.switch(pos, fns, 0)
    merged = metas_by_slot[0]
    for i in range(1, len(fns)):
        merged = _merge_metas(merged, metas_by_slot[i])
    return _rewrap_tensors(out, merged)


def increment(x, value=1.0, in_place=True):
    """x + value (control_flow.py:1419; in_place is meaningless under a
    functional runtime — returns the new Tensor)."""
    v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    out = v + jnp.asarray(value, v.dtype)
    return Tensor(out) if isinstance(x, Tensor) else out


# ---------------------------------------------------------------------------
# TensorArray (reference LoDTensorArray + array ops :1455-2023)
# ---------------------------------------------------------------------------

class TensorArray:
    """Eager, list-backed tensor array — the dygraph analog of the
    reference's LoDTensorArray.  For use INSIDE jitted control flow see
    StaticTensorArray (fixed capacity, XLA-safe)."""

    def __init__(self, dtype="float32"):
        self.dtype = dtype
        self._items = []

    def write(self, i, x):
        i = int(i.value if isinstance(i, Tensor) else i)
        if i < len(self._items):
            self._items[i] = x
        elif i == len(self._items):
            self._items.append(x)
        else:
            raise IndexError(
                f"array_write index {i} beyond length {len(self._items)} "
                f"(writes must be dense, like the reference op)")
        return self

    def read(self, i):
        i = int(i.value if isinstance(i, Tensor) else i)
        return self._items[i]

    def __len__(self):
        return len(self._items)

    def stack(self, axis=0):
        vals = [v.value if isinstance(v, Tensor) else jnp.asarray(v)
                for v in self._items]
        return Tensor(jnp.stack(vals, axis=axis))

    def concat(self, axis=0):
        vals = [v.value if isinstance(v, Tensor) else jnp.asarray(v)
                for v in self._items]
        return Tensor(jnp.concatenate(vals, axis=axis))


def create_array(dtype="float32"):
    return TensorArray(dtype)


def array_write(x, i, array=None):
    if array is None:
        array = TensorArray(getattr(x, "dtype", "float32"))
    array.write(i, x)
    return array


def array_read(array, i):
    return array.read(i)


def array_length(array):
    return Tensor(jnp.asarray(len(array), jnp.int64))


def tensor_array_to_tensor(input, axis=0, use_stack=False):
    """(tensor, per-item sizes) like the reference fused op."""
    if use_stack:
        out = input.stack(axis=axis)
        n = out.shape[axis]
        sizes = jnp.ones((n,), jnp.int32)
    else:
        out = input.concat(axis=axis)
        sizes = jnp.asarray(
            [(v.shape[axis] if getattr(v, "ndim", 0) else 1)
             for v in input._items], jnp.int32)
    return out, Tensor(sizes)


@jax.tree_util.register_pytree_node_class
class StaticTensorArray:
    """Fixed-capacity tensor array usable inside jit / lax control flow.

    A functional buffer [capacity, *shape] + write mask; every method
    returns a NEW array (XLA needs static shapes, so capacity is fixed up
    front — the TPU-idiomatic replacement for the dynamic LoDTensorArray)."""

    def __init__(self, capacity, shape, dtype=jnp.float32, _data=None,
                 _written=None):
        self.capacity = int(capacity)
        self.shape = tuple(shape)
        self.dtype = dtype
        self.data = _data if _data is not None else \
            jnp.zeros((self.capacity,) + self.shape, dtype)
        self.written = _written if _written is not None else \
            jnp.zeros((self.capacity,), jnp.bool_)

    def write(self, i, x):
        x = x.value if isinstance(x, Tensor) else jnp.asarray(x, self.dtype)
        i = jnp.asarray(i.value if isinstance(i, Tensor) else i, jnp.int32)
        data = jax.lax.dynamic_update_index_in_dim(
            self.data, x.astype(self.dtype), i, 0)
        written = self.written.at[i].set(True)
        return StaticTensorArray(self.capacity, self.shape, self.dtype,
                                 _data=data, _written=written)

    def read(self, i):
        i = jnp.asarray(i.value if isinstance(i, Tensor) else i, jnp.int32)
        return jax.lax.dynamic_index_in_dim(self.data, i, 0, keepdims=False)

    def length(self):
        return self.written.sum().astype(jnp.int32)

    def stack(self):
        return self.data

    def tree_flatten(self):
        return (self.data, self.written), (self.capacity, self.shape,
                                           self.dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        cap, shape, dtype = aux
        data, written = children
        return cls(cap, shape, dtype, _data=data, _written=written)


def fori_collect(lower, upper, body, init):
    """Differentiable bounded loop that collects per-iteration outputs.

    body(i, carry) -> (carry, y).  Returns (carry, ys[upper-lower, ...]).
    Backed by lax.scan, so jax.grad works through it — use this where the
    reference used While + array_write for a KNOWN trip count."""
    def scan_body(carry, i):
        carry, y = body(i, carry)
        return carry, y

    return jax.lax.scan(scan_body, init, jnp.arange(lower, upper))


# -- builder surface (reference python/paddle/static/nn/__init__.py
#    re-exports these from fluid.layers; imported lazily to avoid the
#    static <-> fluid import cycle at package-init time) --------------
def __getattr__(name):
    _builders = {
        "fc", "embedding", "conv2d", "conv2d_transpose", "conv3d",
        "conv3d_transpose", "batch_norm", "layer_norm", "group_norm",
        "instance_norm", "data_norm", "bilinear_tensor_product", "prelu",
        "row_conv", "spectral_norm", "crf_decoding", "deform_conv2d",
        "py_func", "nce", "sparse_embedding", "multi_box_head",
        "create_parameter",
    }
    if name in _builders:
        from ..fluid import layers as _fl

        return getattr(_fl, name)
    raise AttributeError(name)
