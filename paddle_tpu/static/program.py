"""Program capture — the fluid static-graph workflow on a tracing core.

Reference parity: python/paddle/fluid/framework.py (Program:4094,
Variable:938, `fluid.data`) + executor.py (Executor.run:916).

TPU-native design: the reference builds an op-desc graph that a C++
interpreter walks.  Here `static.data()` returns a symbolic
:class:`Variable`, and the ONE eager dispatch point (`tensor.apply`)
defers any op touching a Variable into an expression DAG instead of
executing it.  `Executor.run(program, feed, fetch_list)` evaluates the
DAG under `jax.jit` — so a classic
``program_guard -> data -> layers -> minimize -> run`` fluid script
compiles into exactly the same XLA program a `to_static` rewrite would
produce.  Real `nn.Layer` parameters stay eager Tensors: trainable ones
become differentiable jit inputs, everything else is baked constant.

Deliberate limit (documented divergence, README "static graph" section):
data-dependent python control flow inside a program_guard block is not
capturable — use `to_static` (or static.nn.cond/while_loop) for that.
Multi-output ops capture as one shared op node with per-output index
Variables (_build).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..tensor import Tensor, register_deferred_hook, unwrap

__all__ = ["Variable", "evaluate", "collect_params"]


class Variable:
    """A node in the captured expression DAG: a feed leaf (`fn is None`)
    or a deferred op application."""

    def __init__(self, name=None, shape=None, dtype="float32", fn=None,
                 args=None, kwargs=None):
        self.name = name
        self._shape = list(shape) if shape is not None else None
        self._dtype = dtype
        self._fn = fn
        self._args = args or ()
        self._kwargs = kwargs or {}
        self.stop_gradient = False

    # -- graph structure ---------------------------------------------------
    def leaves(self, acc=None, seen=None):
        acc = acc if acc is not None else []
        seen = seen if seen is not None else set()
        if id(self) in seen:
            return acc
        seen.add(id(self))
        if self._fn is None:
            acc.append(self)
        for a in self._args:
            if isinstance(a, Variable):
                a.leaves(acc, seen)
        return acc

    def tensors(self, acc=None, seen=None):
        """Eager Tensor inputs captured in the DAG (layer parameters)."""
        acc = acc if acc is not None else []
        seen = seen if seen is not None else set()
        if id(self) in seen:
            return acc
        seen.add(id(self))
        for a in self._args:
            if isinstance(a, Variable):
                a.tensors(acc, seen)
            elif isinstance(a, Tensor) and not any(a is t for t in acc):
                acc.append(a)
        return acc

    # -- Tensor-like surface ----------------------------------------------
    @property
    def shape(self):
        if self._shape is None:
            self._shape = list(self._abstract().shape)
        return self._shape

    @property
    def dtype(self):
        if self._fn is not None and self._dtype is None:
            self._dtype = str(self._abstract().dtype)
        return self._dtype

    def _abstract(self):
        """Shape/dtype inference by jax.eval_shape over the DAG (None
        feed dims evaluated as 1)."""
        def run(v, memo):
            if id(v) in memo:
                return memo[id(v)]
            if v._fn is None:
                out = jax.ShapeDtypeStruct(
                    tuple(1 if (d is None or d == -1) else int(d)
                          for d in (v._shape or ())), jnp.dtype(v._dtype))
            else:
                args = [run(a, memo) if isinstance(a, Variable)
                        else unwrap(a) if isinstance(a, Tensor) else a
                        for a in v._args]
                out = jax.eval_shape(
                    lambda *xs: v._fn(*xs, **v._kwargs), *args)
            memo[id(v)] = out
            return out

        return run(self, {})

    def __repr__(self):
        if self._fn is None:
            return f"Variable(name={self.name!r}, shape={self._shape})"
        return f"Variable(op={getattr(self._fn, '__name__', self._fn)})"

    # arithmetic routes back through tensor_ops -> apply -> deferred
    def _op(self, name, *others):
        from .. import tensor_ops as T

        return getattr(T, name)(self, *others)

    def __add__(self, o):
        return self._op("add", o)

    def __radd__(self, o):
        return self._op("add", o)

    def __sub__(self, o):
        return self._op("subtract", o)

    def __rsub__(self, o):
        from .. import tensor_ops as T

        return T.subtract(o, self)

    def __mul__(self, o):
        return self._op("multiply", o)

    def __rmul__(self, o):
        return self._op("multiply", o)

    def __truediv__(self, o):
        return self._op("divide", o)

    def __pow__(self, o):
        return self._op("pow", o)

    def __matmul__(self, o):
        return self._op("matmul", o)

    def __neg__(self):
        return self._op("scale", -1.0)

    def __getitem__(self, idx):
        from ..tensor import apply as _apply

        return _apply(lambda v: v[idx], self)

    # comparisons defer too (fluid.layers.accuracy: argmax(pred) == label);
    # identity hashing is preserved — the capture machinery keys on id()
    def __eq__(self, o):
        return self._op("equal", o)

    def __ne__(self, o):
        return self._op("not_equal", o)

    def __lt__(self, o):
        return self._op("less_than", o)

    def __le__(self, o):
        return self._op("less_equal", o)

    def __gt__(self, o):
        return self._op("greater_than", o)

    def __ge__(self, o):
        return self._op("greater_equal", o)

    __hash__ = object.__hash__

    def __getattr__(self, item):
        # tensor methods (v.mean(), v.reshape(...)) resolve to the
        # tensor_ops function of the same name, keeping ONE op surface
        from .. import tensor_ops as T

        f = getattr(T, item, None)
        if f is None or item.startswith("_"):
            raise AttributeError(item)

        def method(*a, **k):
            return f(self, *a, **k)

        return method


# -- apply() hook ----------------------------------------------------------

def _is_deferred(args, kwargs):
    return any(isinstance(a, Variable) for a in args)


def _build(fn, args, kwargs, multi):
    if not multi:
        return Variable(fn=fn, args=args, kwargs=kwargs)
    # Multi-output op (topk, ViterbiDecoder, ...): one shared op node
    # evaluates the function once; each returned Variable indexes into
    # its tuple result.  The output count comes from abstract shape
    # evaluation at capture time (jax.eval_shape over the DAG, the same
    # machinery Variable.shape uses).
    op = Variable(fn=fn, args=args, kwargs=kwargs)
    outs = op._abstract()
    if not isinstance(outs, (tuple, list)):
        return Variable(fn=fn, args=args, kwargs=kwargs)
    return tuple(
        Variable(fn=(lambda t, _i=i: t[_i]), args=(op,))
        for i in range(len(outs)))


register_deferred_hook(_is_deferred, _build)


# -- evaluation ------------------------------------------------------------

_JIT_CACHE_MAX = 64


def _cache_put(cache, key, entry):
    """FIFO-bounded insert: per-iteration fetch expressions would
    otherwise pin one compiled executable + fetch DAG per call forever
    (the dominant retainer behind advisor r04's leak finding — bounding
    _captured_vars alone left this cache unbounded)."""
    while len(cache) >= _JIT_CACHE_MAX:
        cache.pop(next(iter(cache)))
    cache[key] = entry


def collect_params(fetch_vars):
    """Trainable eager Tensors captured by the DAG (stop_gradient False).
    Eager Tensors can appear directly in a fetch list (host-computed
    outputs like prior_box) — they capture no parameters themselves."""
    params = []
    for v in fetch_vars:
        if not isinstance(v, Variable):
            continue
        for t in v.tensors():
            if not t.stop_gradient and not any(t is p for p in params):
                params.append(t)
    return params


def _eval_fn(fetch_vars, leaf_names, params):
    """A pure function (feed_values, param_values) -> fetch values, ready
    for jax.jit / jax.grad."""
    pid = {id(p): i for i, p in enumerate(params)}

    def f(feed_vals, param_vals):
        memo = {}

        def run(v):
            if id(v) in memo:
                return memo[id(v)]
            if v._fn is None:
                out = feed_vals[leaf_names.index(v.name)]
            else:
                args = [run(a) if isinstance(a, Variable)
                        else (param_vals[pid[id(a)]] if id(a) in pid
                              else unwrap(a))
                        for a in v._args]
                out = v._fn(*args, **v._kwargs)
            memo[id(v)] = out
            return out

        return [run(v) for v in fetch_vars]

    return f


def evaluate(fetch_vars, feed, params=None, jit_cache=None):
    """Evaluate DAG nodes under jax.jit.  feed: {name: array}.
    Eager Tensors in the fetch list (host-computed values like
    prior_box outputs) pass through without entering the jit."""
    all_fetches = list(fetch_vars)
    eager = {i: v for i, v in enumerate(all_fetches)
             if not isinstance(v, Variable)}
    fetch_vars = [v for v in all_fetches if isinstance(v, Variable)]
    leaves = []
    for v in fetch_vars:
        for leaf in v.leaves():
            if leaf.name not in [x.name for x in leaves]:
                leaves.append(leaf)
    leaf_names = [x.name for x in leaves]
    missing = [n for n in leaf_names if n not in (feed or {})]
    if missing:
        raise ValueError(f"feed is missing static.data inputs: {missing}")
    params = params if params is not None else collect_params(fetch_vars)
    feed_vals = [jnp.asarray(unwrap(feed[n])) for n in leaf_names]
    param_vals = [unwrap(p) for p in params]
    f = _eval_fn(fetch_vars, leaf_names, params)
    key = (tuple(id(v) for v in fetch_vars),
           tuple((v.shape, str(v.dtype)) for v in feed_vals))
    if jit_cache is not None:
        hit = jit_cache.get(key)
        # id() keys can be reused after GC of the original Variables; a
        # hit is only valid if the cached fetch list is the SAME objects
        # (advisor r04: a stale compiled graph could otherwise run on
        # new feeds).  The entry keeps the fetch_vars alive alongside
        # the jitted fn, so surviving entries can't have ids recycled.
        if hit is not None and all(a is b for a, b in
                                   zip(hit[1], fetch_vars)):
            jf = hit[0]
        else:
            jf = jax.jit(f)
            _cache_put(jit_cache, key, (jf, list(fetch_vars)))
    else:
        jf = jax.jit(f)
    outs = jf(feed_vals, param_vals)
    # re-interleave eager fetches at their original positions
    it = iter(np.asarray(o) for o in outs)
    return [np.asarray(unwrap(eager[i])) if i in eager else next(it)
            for i in range(len(all_fetches))]


def train_step(loss_var, optimizer, feed, fetch_list, jit_cache=None):
    """One captured-program training step: value_and_grad of the loss wrt
    the DAG's trainable parameters in the SAME jitted forward that
    evaluates fetch_list (so fetches are pre-update values, like the
    reference Executor), then the optimizer's eager update."""
    fetch_list = list(fetch_list or [loss_var])
    all_vars = [loss_var] + fetch_list
    params = collect_params(all_vars)
    leaves = []
    for v in all_vars:
        for leaf in v.leaves():
            if leaf.name not in [x.name for x in leaves]:
                leaves.append(leaf)
    leaf_names = [x.name for x in leaves]
    missing = [n for n in leaf_names if n not in (feed or {})]
    if missing:
        raise ValueError(f"feed is missing static.data inputs: {missing}")
    feed_vals = [jnp.asarray(unwrap(feed[n])) for n in leaf_names]
    f = _eval_fn(all_vars, leaf_names, params)

    def loss_of(param_vals, feed_vals):
        outs = f(feed_vals, param_vals)
        return outs[0].reshape(()), outs[1:]

    key = ("train", tuple(id(v) for v in all_vars),
           tuple((v.shape, str(v.dtype)) for v in feed_vals))
    if jit_cache is not None:
        hit = jit_cache.get(key)
        # identity-verify the hit (see evaluate: id() reuse after GC)
        if hit is not None and all(a is b for a, b in zip(hit[1], all_vars)):
            jf = hit[0]
        else:
            jf = jax.jit(jax.value_and_grad(loss_of, has_aux=True))
            _cache_put(jit_cache, key, (jf, list(all_vars)))
    else:
        jf = jax.jit(jax.value_and_grad(loss_of, has_aux=True))
    (loss, fetches), grads = jf([unwrap(p) for p in params], feed_vals)
    del loss
    for p, g in zip(params, grads):
        p.grad = Tensor(g)
    optimizer.step()
    optimizer.clear_grad()
    return [np.asarray(o) for o in fetches]
