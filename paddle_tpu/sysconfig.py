"""`paddle.sysconfig` — install-tree introspection.

Reference parity: python/paddle/sysconfig.py:17 (get_include returns the
C header dir, get_lib the shared-library dir).  Here the native core is
csrc/core.cc built to a cached .so by paddle_tpu.core; get_lib points at
that .so's directory and get_include at the csrc headers.
"""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory of the native-core C/C++ sources/headers."""
    return os.path.normpath(os.path.join(os.path.dirname(_PKG_DIR), "csrc"))


def get_lib():
    """Directory containing the compiled native core
    (libpaddle_tpu_core.so), building it on first call if needed."""
    from . import core
    core._load()  # compile-on-first-use; harmless no-op if unavailable
    return os.path.dirname(core._SO)
