"""Tensor: the eager array type.

Reference parity: paddle/fluid/imperative/layer.h VarBase (eager tensor wrapping
a framework::Variable + grad var) and the python Tensor surface
(python/paddle/fluid/framework.py:978 Variable / dygraph core.VarBase methods).

TPU-native design: a Tensor is a thin, pytree-registered wrapper over a
`jax.Array` plus autograd metadata (`stop_gradient`, `.grad`).  Every eager op
funnels through `apply(fn, *args)`, which either (a) just runs the pure jax
function, or (b) when taping, runs `jax.vjp` to get primal + backward closure
in one pass and records a GradNode (the TraceOp/CreateGradOpNode analog,
tracer.cc:131,185).  Under `jax.jit` tracing the wrapper is transparent: value
may be a tracer, taping is suspended, and the op is just the jax function —
so the SAME layer code serves both dygraph and compiled static mode.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from .framework import dtype as _dtype_mod
from .framework.dtype import convert_dtype, get_default_dtype, is_floating
from .framework.flags import flag as _flag


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


class Tensor:
    __array_priority__ = 100  # beat numpy in mixed dunder dispatch

    def __init__(self, value, dtype=None, stop_gradient: bool = True, name: str | None = None):
        if isinstance(value, Tensor):
            value = value.value
        if not isinstance(value, (jax.Array, jax.core.Tracer)):
            dt = convert_dtype(dtype)
            if dt is None and isinstance(value, (float,)):
                dt = get_default_dtype()
            if dt is None and isinstance(value, np.ndarray) and value.dtype == np.float64:
                dt = get_default_dtype()
            value = jnp.asarray(value, dtype=dt)
        elif dtype is not None and convert_dtype(dtype) != value.dtype:
            value = value.astype(convert_dtype(dtype))
        self._value = value
        self.stop_gradient = bool(stop_gradient)
        self._grad: Tensor | None = None
        self._produced_by_op = False
        self.name = name

    # -- basic properties --------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self) -> int:
        return self._value.ndim

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def size(self) -> int:
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        from .framework.place import CPUPlace, TPUPlace

        if _is_tracer(self._value):
            return TPUPlace(0)
        dev = next(iter(self._value.devices()), None)
        if dev is not None and dev.platform.lower() == "cpu":
            return CPUPlace(dev.id)
        return TPUPlace(getattr(dev, "id", 0))

    @property
    def is_leaf(self) -> bool:
        return not self._produced_by_op

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, g):
        if g is None:
            self._grad = None
        else:
            self._grad = g if isinstance(g, Tensor) else Tensor(g)

    @property
    def T(self):
        return apply(jnp.transpose, self)

    @property
    def mT(self):
        return apply(lambda x: jnp.swapaxes(x, -1, -2), self)

    @property
    def real(self):
        return apply(jnp.real, self)

    @property
    def imag(self):
        return apply(jnp.imag, self)

    # -- autograd ----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph)

    def _accumulate_grad(self, g):
        if self.stop_gradient:
            return
        if self._grad is None:
            self._grad = Tensor(g)
        else:
            self._grad = Tensor(self._grad.value + g)

    def clear_grad(self):
        self._grad = None

    def clear_gradient(self):
        self._grad = None

    def detach(self) -> "Tensor":
        return Tensor(self._value, stop_gradient=True, name=self.name)

    def clone(self) -> "Tensor":
        return apply(lambda x: x + 0, self)

    def register_hook(self, hook):
        """Register a grad hook: called with this tensor's gradient during
        backward()/grad(); returning a Tensor replaces the gradient,
        returning None leaves it unchanged. Returns a removable handle
        (reference: imperative/hooks.h GradAccumulator hooks)."""
        if self.stop_gradient:
            raise RuntimeError(
                "Cannot register_hook on a tensor with stop_gradient=True")
        if not hasattr(self, "_grad_hooks"):
            self._grad_hooks = {}
        hid = len(self._grad_hooks)
        while hid in self._grad_hooks:
            hid += 1
        self._grad_hooks[hid] = hook
        return _HookRemoveHelper(self, hid)

    # -- host bridge -------------------------------------------------------
    def numpy(self) -> np.ndarray:
        if _is_tracer(self._value):
            raise RuntimeError("Cannot call .numpy() inside a jit-traced function")
        return np.asarray(self._value)

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def item(self):
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def numel(self) -> int:
        return self.size

    def element_size(self) -> int:
        return np.dtype(self.dtype).itemsize

    # -- dtype / device ----------------------------------------------------
    def astype(self, dtype) -> "Tensor":
        dt = convert_dtype(dtype)
        return apply(lambda x: x.astype(dt), self)

    cast = astype

    def cpu(self) -> "Tensor":
        return Tensor(jax.device_put(self._value, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient)

    def to(self, *args, **kwargs):
        # minimal: dtype and/or device string
        out = self
        for a in args:
            if isinstance(a, str) and a.split(":")[0] in ("cpu", "tpu", "gpu", "cuda"):
                from .framework.place import set_device, get_place
                prev = get_place()
                try:
                    place = set_device(a)
                finally:
                    set_device(prev)
                out = Tensor(jax.device_put(out.value, place.jax_device()),
                             stop_gradient=out.stop_gradient)
            else:
                out = out.astype(a)
        if "dtype" in kwargs:
            out = out.astype(kwargs["dtype"])
        return out

    # -- python protocol ---------------------------------------------------
    def __len__(self):
        if not self._value.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __repr__(self):
        if _is_tracer(self._value):
            return f"Tensor(traced, shape={self.shape}, dtype={self.dtype})"
        return (
            f"Tensor(shape={self.shape}, dtype={_dtype_mod.dtype_name(self.dtype)}, "
            f"stop_gradient={self.stop_gradient},\n       {np.asarray(self._value)!r})"
        )

    def __bool__(self):
        return bool(self._value)

    def __int__(self):
        return int(self._value)

    def __float__(self):
        return float(self._value)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __getitem__(self, idx):
        idx = _unwrap_index(idx)
        return apply(lambda x: x[idx], self)

    def __setitem__(self, idx, val):
        idx = _unwrap_index(idx)
        v = val.value if isinstance(val, Tensor) else val
        self._value = self._value.at[idx].set(v)

    def __hash__(self):
        return id(self)

    def dim(self):
        return self.ndim

    def rank(self):
        return self.ndim


class _HookRemoveHelper:
    """Handle returned by Tensor.register_hook (reference
    TensorHookRemoveHelper): .remove() unregisters the hook."""

    def __init__(self, tensor, hook_id):
        self._tensor = tensor
        self._hook_id = hook_id

    def remove(self):
        hooks = getattr(self._tensor, "_grad_hooks", None)
        if hooks is not None:
            hooks.pop(self._hook_id, None)


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx.value
    if isinstance(idx, tuple):
        return tuple(i.value if isinstance(i, Tensor) else i for i in idx)
    return idx


# -- pytree registration ---------------------------------------------------
def _tensor_flatten(t: Tensor):
    return (t._value,), (t.stop_gradient, t.name)


def _tensor_unflatten(aux, children):
    stop_gradient, name = aux
    value = children[0]
    if not isinstance(value, (Tensor, jax.Array, jax.core.Tracer, np.ndarray,
                              int, float, complex, bool, list, tuple)):
        # jax pytree plumbing unflattens with NON-array placeholders:
        # prefix broadcasting (e.g. a None leaf in jit out_shardings
        # spanning a Tensor subtree) and treedef.unflatten over
        # sentinels.  Skip __init__'s value coercion for those — the
        # placeholder Tensor only exists to be re-flattened.
        t = object.__new__(Tensor)
        t._value = value
        t.stop_gradient = stop_gradient
        t._grad = None
        t._produced_by_op = False
        t.name = name
        return t
    return Tensor(value, stop_gradient=stop_gradient, name=name)


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)


# -- generic eager op dispatch ---------------------------------------------
def unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _needs_grad(x) -> bool:
    return isinstance(x, Tensor) and not x.stop_gradient and is_floating(x.dtype)


def _maybe_check_nan_inf(fn, out):
    """FLAGS_check_nan_inf: per-op output finiteness guard in eager mode
    (reference: operator.cc:1192 CheckOpHasNanOrInf via
    details/nan_inf_utils_detail.cc). Debug-only — forces a host sync."""
    if not _flag("FLAGS_check_nan_inf"):
        return
    import numpy as _np

    leaves = out if isinstance(out, (tuple, list)) else [out]
    for o in leaves:
        v = o._value if isinstance(o, Tensor) else o
        if _is_tracer(v):
            # inside an OUTER trace (e.g. make_jaxpr over functional_call)
            # an op whose inputs are all closure constants still produces
            # a tracer; the eager-only guard must not host-sync it
            return
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
            arr = _np.asarray(v)
            if not _np.isfinite(arr).all():
                name = getattr(fn, "__name__", str(fn))
                kind = "nan" if _np.isnan(arr).any() else "inf"
                raise FloatingPointError(
                    f"Operator {name} output contains {kind} "
                    f"(FLAGS_check_nan_inf is set); shape={arr.shape}")


# program-capture hook (paddle.static): set by static/__init__ to a
# (is_deferred(args, kwargs), build(fn, args, kwargs, multi)) pair so ops
# over static Variables record into the expression DAG instead of running
_deferred_hook = None


def register_deferred_hook(is_deferred, build):
    global _deferred_hook
    _deferred_hook = (is_deferred, build)


def apply(fn, *args, _multi_out: bool = False, **kwargs):
    """Run pure jax function `fn` over (possibly Tensor) args.

    This is the single Python/XLA boundary for eager mode — the TraceOp analog.
    When the tape is live and any input requires grad, use jax.vjp so the
    backward closure is captured (one forward pass total).
    """
    if _deferred_hook is not None and _deferred_hook[0](args, kwargs):
        return _deferred_hook[1](fn, args, kwargs, _multi_out)
    jvals = [unwrap(a) for a in args]
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    any_tracer = any(_is_tracer(v) for v in jvals)

    if any_tracer or not autograd.tape_enabled() or not any(_needs_grad(a) for a in args):
        out = fn(*jvals, **kwargs)
        if not any_tracer:
            _maybe_check_nan_inf(fn, out)
        # under no_grad / inside traces outputs do not require grad
        rg = (any_tracer or autograd.tape_enabled()) and \
            any(_needs_grad(a) for a in args)
        return _wrap_out(out, tensor_args, produced=True, multi=_multi_out,
                         requires_grad=rg)

    diff_pos = [i for i, a in enumerate(args) if _needs_grad(a)]
    diff_vals = [jvals[i] for i in diff_pos]

    def closed(*dvals):
        vals = list(jvals)
        for i, v in zip(diff_pos, dvals):
            vals[i] = v
        return fn(*vals, **kwargs)

    primal, vjp_fn = jax.vjp(closed, *diff_vals)
    _maybe_check_nan_inf(fn, primal)
    out = _wrap_out(primal, tensor_args, produced=True, multi=_multi_out, requires_grad=True)

    outs = out if isinstance(out, (list, tuple)) else (out,)
    out_tensors = [o for o in outs if isinstance(o, Tensor)]
    node = autograd.GradNode(
        vjp_fn,
        [args[i] for i in diff_pos],
        [id(o) for o in out_tensors],
        [(tuple(o.shape), o.dtype) for o in out_tensors],
        multi_out=len(out_tensors) > 1,
        fwd_fn=closed,
    )
    autograd.record(node)
    return out


def _wrap_out(out, tensor_args, produced: bool, multi: bool, requires_grad: bool | None = None):
    if requires_grad is None:
        requires_grad = any(_needs_grad(a) for a in tensor_args)

    def mk(v):
        if not isinstance(v, (jax.Array, jax.core.Tracer, np.ndarray)):
            return v
        t = Tensor(v, stop_gradient=not requires_grad)
        # leaf-ness is about GRAD HISTORY, not mere production: an output
        # of an unrecorded op (no grad required at the time) is a leaf, so
        # marking it trainable later accumulates into .grad (torch/paddle
        # semantics) instead of dropping the gradient in backward()
        t._produced_by_op = produced and requires_grad
        return t

    if isinstance(out, (tuple, list)):
        vals = [mk(v) for v in out]
        if hasattr(out, "_fields"):  # NamedTuple (jax EighResult/QRResult
            return type(out)(*vals)  # /SVDResult need positional args)
        return type(out)(vals)
    return mk(out)
