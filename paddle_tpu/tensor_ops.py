"""Tensor math / manipulation / linalg / search / logic ops.

Reference parity: python/paddle/tensor/{math,manipulation,linalg,search,logic,
stat}.py (~9k LoC re-exported as Tensor methods) over the dense C++ op zoo
(paddle/fluid/operators/*.cc — SURVEY.md §2.4).  TPU-native: every op is a
direct jnp/lax lowering dispatched through tensor.apply (one table, no
kernel-per-op registration); XLA fuses elementwise chains so there is no need
for the reference's fusion_group codegen here.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from .framework.dtype import convert_dtype, get_default_dtype
from .tensor import Tensor, apply, unwrap


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# ---------------------------------------------------------------------------
# elementwise binary (broadcasting) — elementwise/* ops in the reference
# ---------------------------------------------------------------------------
def add(x, y, name=None):
    return apply(jnp.add, x, y)


def subtract(x, y, name=None):
    return apply(jnp.subtract, x, y)


def multiply(x, y, name=None):
    return apply(jnp.multiply, x, y)


def divide(x, y, name=None):
    return apply(jnp.true_divide, x, y)


def floor_divide(x, y, name=None):
    return apply(jnp.floor_divide, x, y)


def mod(x, y, name=None):
    return apply(jnp.mod, x, y)


remainder = mod


def pow(x, y, name=None):
    return apply(jnp.power, x, y)


def maximum(x, y, name=None):
    return apply(jnp.maximum, x, y)


def minimum(x, y, name=None):
    return apply(jnp.minimum, x, y)


def fmax(x, y, name=None):
    return apply(jnp.fmax, x, y)


def fmin(x, y, name=None):
    return apply(jnp.fmin, x, y)


def atan2(x, y, name=None):
    return apply(jnp.arctan2, x, y)


def hypot(x, y, name=None):
    return apply(jnp.hypot, x, y)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = unwrap(scale), unwrap(bias)

    def f(v):
        return v * s + b if bias_after_scale else (v + b) * s

    out = apply(f, x)
    if act:
        from .nn import functional as F

        out = getattr(F, act)(out)
    return out


# ---------------------------------------------------------------------------
# elementwise unary — activations live in nn.functional; these are math
# ---------------------------------------------------------------------------
_UNARY = {
    "abs": jnp.abs,
    "acos": jnp.arccos,
    "acosh": jnp.arccosh,
    "asin": jnp.arcsin,
    "asinh": jnp.arcsinh,
    "atan": jnp.arctan,
    "atanh": jnp.arctanh,
    "ceil": jnp.ceil,
    "conj": jnp.conj,
    "cos": jnp.cos,
    "cosh": jnp.cosh,
    "digamma": jax.scipy.special.digamma,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "floor": jnp.floor,
    "i0": lambda x: jax.scipy.special.i0(x),
    "lgamma": jax.scipy.special.gammaln,
    "log": jnp.log,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "log2": jnp.log2,
    "neg": jnp.negative,
    "reciprocal": jnp.reciprocal,
    "rsqrt": jax.lax.rsqrt,
    "sign": jnp.sign,
    "sin": jnp.sin,
    "sinh": jnp.sinh,
    "sqrt": jnp.sqrt,
    "square": jnp.square,
    "tan": jnp.tan,
    "trunc": jnp.trunc,
}

_g = globals()
for _name, _fn in _UNARY.items():
    def _mk(fn):
        def op(x, name=None):
            return apply(fn, x)
        return op
    _g[_name] = _mk(_fn)
    _g[_name].__name__ = _name


def round(x, decimals=0, name=None):  # noqa: A001
    return apply(lambda v: jnp.round(v, decimals), x)


def frac(x, name=None):
    return apply(lambda v: v - jnp.trunc(v), x)


def angle(x, name=None):
    return apply(jnp.angle, x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf), x)


def clip(x, min=None, max=None, name=None):
    return apply(lambda v: jnp.clip(v, unwrap(min), unwrap(max)), x)


def lerp(x, y, weight, name=None):
    return apply(lambda a, b, w: a + w * (b - a), x, y, weight)


def isfinite(x, name=None):
    return apply(jnp.isfinite, x)


def isinf(x, name=None):
    return apply(jnp.isinf, x)


def isnan(x, name=None):
    return apply(jnp.isnan, x)


# ---------------------------------------------------------------------------
# reductions — reduce_ops/* in the reference
# ---------------------------------------------------------------------------
def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    dt = convert_dtype(dtype)
    return apply(lambda v: jnp.sum(v, axis=_axis(axis), dtype=dt, keepdims=keepdim), x)


def mean(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.mean(v, axis=_axis(axis), keepdims=keepdim), x)


def max(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply(lambda v: jnp.max(v, axis=_axis(axis), keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply(lambda v: jnp.min(v, axis=_axis(axis), keepdims=keepdim), x)


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    dt = convert_dtype(dtype)
    return apply(lambda v: jnp.prod(v, axis=_axis(axis), dtype=dt, keepdims=keepdim), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jax.scipy.special.logsumexp(v, axis=_axis(axis), keepdims=keepdim), x)


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply(lambda v: jnp.all(v, axis=_axis(axis), keepdims=keepdim), x)


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply(lambda v: jnp.any(v, axis=_axis(axis), keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda v: jnp.std(v, axis=_axis(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda v: jnp.var(v, axis=_axis(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.median(v, axis=_axis(axis), keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.quantile(v, jnp.asarray(unwrap(q)), axis=_axis(axis),
                                        keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.nanmean(v, axis=_axis(axis), keepdims=keepdim), x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return apply(lambda v: jnp.nansum(v, axis=_axis(axis), dtype=convert_dtype(dtype),
                                      keepdims=keepdim), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.count_nonzero(v, axis=_axis(axis), keepdims=keepdim)
                 .astype(jnp.int64), x)


def cumsum(x, axis=None, dtype=None, name=None):
    dt = convert_dtype(dtype)
    return apply(lambda v: jnp.cumsum(v if axis is not None else v.ravel(),
                                      axis=axis if axis is not None else 0, dtype=dt), x)


def cumprod(x, dim=None, dtype=None, name=None):
    dt = convert_dtype(dtype)
    return apply(lambda v: jnp.cumprod(v, axis=dim, dtype=dt), x)


def cummax(x, axis=None, dtype="int64", name=None):
    def f(v):
        a = 0 if axis is None else axis
        vv = v.ravel() if axis is None else v
        out = jax.lax.associative_scan(jnp.maximum, vv, axis=a)
        idx = jnp.argmax(jnp.cumsum(jnp.ones_like(vv, jnp.int32), a) *
                         (vv == out), axis=a)
        return out, idx
    o, i = apply(f, x, _multi_out=True)
    return o, i


def logcumsumexp(x, axis=None, name=None):
    def f(v):
        a = 0 if axis is None else axis
        vv = v.ravel() if axis is None else v
        return jax.lax.associative_scan(jnp.logaddexp, vv, axis=a)
    return apply(f, x)


# ---------------------------------------------------------------------------
# comparison / logic
# ---------------------------------------------------------------------------
def equal(x, y, name=None):
    return apply(jnp.equal, x, y)


def not_equal(x, y, name=None):
    return apply(jnp.not_equal, x, y)


def less_than(x, y, name=None):
    return apply(jnp.less, x, y)


def less_equal(x, y, name=None):
    return apply(jnp.less_equal, x, y)


def greater_than(x, y, name=None):
    return apply(jnp.greater, x, y)


def greater_equal(x, y, name=None):
    return apply(jnp.greater_equal, x, y)


def equal_all(x, y, name=None):
    return apply(lambda a, b: jnp.array_equal(a, b), x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan), x, y)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                          equal_nan=equal_nan), x, y)


def logical_and(x, y, out=None, name=None):
    return apply(jnp.logical_and, x, y)


def logical_or(x, y, out=None, name=None):
    return apply(jnp.logical_or, x, y)


def logical_xor(x, y, out=None, name=None):
    return apply(jnp.logical_xor, x, y)


def logical_not(x, out=None, name=None):
    return apply(jnp.logical_not, x)


def bitwise_and(x, y, out=None, name=None):
    return apply(jnp.bitwise_and, x, y)


def bitwise_or(x, y, out=None, name=None):
    return apply(jnp.bitwise_or, x, y)


def bitwise_xor(x, y, out=None, name=None):
    return apply(jnp.bitwise_xor, x, y)


def bitwise_not(x, out=None, name=None):
    return apply(jnp.bitwise_not, x)


# ---------------------------------------------------------------------------
# manipulation — reshape/transpose/concat/split/... ops
# ---------------------------------------------------------------------------
def _as_dim(s):
    """int for concrete sizes; jax.export symbolic dims pass through
    unchanged (int() on a _DimExpr raises — shape-polymorphic serving
    artifacts reshape with symbolic batch dims)."""
    return int(s) if isinstance(s, (int, np.integer, float)) else s


def reshape(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    # Tensors and deferred Variables both expose .shape; raw arrays via
    # np.shape.  NB: a Variable's batch dim reports the placeholder (1),
    # so 0-copy of a symbolic batch dim would bake it — prefer -1 there.
    xs = (x.shape if hasattr(x, "shape") and not isinstance(x, np.ndarray)
          else list(np.shape(unwrap(x))))
    # paddle semantics: 0 means "copy this dim from input"
    def _is_zero(s):
        return isinstance(s, (int, np.integer)) and s == 0

    # NB: builtins.any — this module shadows `any` with the paddle op
    has_zero = builtins.any(_is_zero(s) for s in shape)
    shape = [xs[i] if _is_zero(s) else _as_dim(s)
             for i, s in enumerate(shape)] if has_zero \
        else [_as_dim(s) for s in shape]
    return apply(lambda v: jnp.reshape(v, shape), x)


def transpose(x, perm, name=None):
    return apply(lambda v: jnp.transpose(v, perm), x)


def moveaxis(x, source, destination, name=None):
    return apply(lambda v: jnp.moveaxis(v, source, destination), x)


def swapaxes(x, axis1, axis2, name=None):
    return apply(lambda v: jnp.swapaxes(v, axis1, axis2), x)


def squeeze(x, axis=None, name=None):
    return apply(lambda v: jnp.squeeze(v, _axis(axis)), x)


def unsqueeze(x, axis, name=None):
    ax = _axis(axis)
    return apply(lambda v: jnp.expand_dims(v, ax), x)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(v):
        nd = v.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = list(v.shape[:s]) + [-1] + list(v.shape[e + 1:])
        return jnp.reshape(v, new_shape)
    return apply(f, x)


def concat(x, axis=0, name=None):
    xs = list(x)
    # spread through apply when ANY element is a Tensor or a deferred
    # Variable (a list arg hides Variables from the deferred-hook check;
    # raw jnp.concatenate cannot consume them)
    wrapped = [t for t in xs if isinstance(t, Tensor)
               or type(t).__name__ == "Variable"]
    ax = int(unwrap(axis)) if not isinstance(axis, int) else axis
    return apply(lambda *vs: jnp.concatenate(vs, axis=ax), *xs) if wrapped \
        else Tensor(jnp.concatenate([unwrap(v) for v in xs], axis=ax))


def stack(x, axis=0, name=None):
    xs = list(x)
    return apply(lambda *vs: jnp.stack(vs, axis=axis), *xs)


def unstack(x, axis=0, num=None, name=None):
    n = num if num is not None else x.shape[axis]
    outs = apply(lambda v: tuple(jnp.moveaxis(v, axis, 0)[i] for i in range(n)),
                 x, _multi_out=True)
    return list(outs)


def split(x, num_or_sections, axis=0, name=None):
    ax = int(unwrap(axis)) if not isinstance(axis, int) else axis

    def f(v):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(v, num_or_sections, axis=ax))
        secs = [int(unwrap(s)) for s in num_or_sections]
        total = v.shape[ax]
        known = builtins_sum(s for s in secs if s != -1)
        secs = [s if s != -1 else total - known for s in secs]
        idx = np.cumsum(secs)[:-1]
        return tuple(jnp.split(v, idx, axis=ax))

    outs = apply(f, x, _multi_out=True)
    return list(outs)


builtins_sum = __import__("builtins").sum


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tile(x, repeat_times, name=None):
    rt = [int(unwrap(r)) for r in repeat_times] if not isinstance(repeat_times, int) \
        else repeat_times
    return apply(lambda v: jnp.tile(v, rt), x)


def expand(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    xs = list(np.shape(unwrap(x)))
    tgt = list(shape)
    # -1 means keep input dim (aligned from the right)
    off = len(tgt) - len(xs)
    tgt = [xs[i - off] if (s == -1 and i >= off) else int(s) for i, s in enumerate(tgt)]
    return apply(lambda v: jnp.broadcast_to(v, tgt), x)


def expand_as(x, y, name=None):
    return apply(lambda v, w: jnp.broadcast_to(v, w.shape), x, y)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    vs = [unwrap(v) for v in inputs]
    shape = np.broadcast_shapes(*[v.shape for v in vs])
    return [apply(lambda v: jnp.broadcast_to(v, shape), t) for t in inputs]


def flip(x, axis, name=None):
    return apply(lambda v: jnp.flip(v, _axis(axis)), x)


def roll(x, shifts, axis=None, name=None):
    return apply(lambda v: jnp.roll(v, shifts, _axis(axis)), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda v: jnp.rot90(v, k, axes), x)


def repeat_interleave(x, repeats, axis=None, name=None):
    r = unwrap(repeats)
    return apply(lambda v: jnp.repeat(v, r, axis=axis), x)


def take_along_axis(arr, indices, axis, name=None):
    return apply(lambda v, i: jnp.take_along_axis(v, i, axis=axis), arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def f(v, i, val):
        val = jnp.broadcast_to(val, i.shape).astype(v.dtype)
        dims = [d for d in range(v.ndim)]
        # build full index grid
        idxs = jnp.meshgrid(*[jnp.arange(s) for s in i.shape], indexing="ij")
        idxs[axis] = i
        if reduce == "assign":
            return v.at[tuple(idxs)].set(val)
        if reduce == "add":
            return v.at[tuple(idxs)].add(val)
        if reduce == "multiply" or reduce == "mul":
            return v.at[tuple(idxs)].multiply(val)
        raise ValueError(reduce)
    return apply(f, arr, indices, values)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, Tensor):
        pad = pad.tolist()

    def f(v):
        p = list(pad)
        if len(p) == 2 * v.ndim:
            # paddle flat format: [d0_l, d0_r, d1_l, d1_r, ...] over ALL dims
            width = [(p[2 * i], p[2 * i + 1]) for i in range(v.ndim)]
        else:
            # partial spec applies to the spatial dims with pairs running
            # from the LAST dim backwards (paddle F.pad: 2D
            # [left, right, top, bottom] -> pair 0 pads W, pair 1 pads H)
            nsp = len(p) // 2
            pairs = [(p[2 * i], p[2 * i + 1]) for i in range(nsp)]
            if data_format.endswith("C"):  # NHWC/NLC/NDHWC
                sp_dims = list(range(1, 1 + nsp))
            else:                          # NCHW/NCL/NCDHW
                sp_dims = list(range(v.ndim - nsp, v.ndim))
            width = [(0, 0)] * v.ndim
            for i, d in enumerate(reversed(sp_dims)):
                width[d] = pairs[i]
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        kw = {"constant_values": value} if jmode == "constant" else {}
        return jnp.pad(v, width, mode=jmode, **kw)

    return apply(f, x)


def gather(x, index, axis=0, name=None):
    ax = int(unwrap(axis)) if not isinstance(axis, int) else axis
    return apply(lambda v, i: jnp.take(v, i.astype(jnp.int32), axis=ax), x, index)


def gather_nd(x, index, name=None):
    def f(v, i):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return v[idx]
    return apply(f, x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    def f(v, i, u):
        i = i.astype(jnp.int32)
        if overwrite:
            return v.at[i].set(u)
        # paddle overwrite=False: zero the rows then accumulate
        z = v.at[i].set(jnp.zeros_like(u))
        return z.at[i].add(u)
    return apply(f, x, index, updates)


def scatter_nd_add(x, index, updates, name=None):
    def f(v, i, u):
        idx = tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))
        return v.at[idx].add(u)
    return apply(f, x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    def f(i, u):
        base = jnp.zeros(tuple(shape), u.dtype)
        idx = tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))
        return base.at[idx].add(u)
    return apply(f, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply(lambda v, i: jnp.take(v, i.astype(jnp.int32), axis=axis), x, index)


def index_sample(x, index, name=None):
    return apply(lambda v, i: jnp.take_along_axis(v, i.astype(jnp.int32), axis=1),
                 x, index)


def masked_select(x, mask, name=None):
    # dynamic shape: eager only (documented; inside jit use where())
    return apply(lambda v, m: v[m], x, mask)


def masked_fill(x, mask, value, name=None):
    return apply(lambda v, m: jnp.where(m, jnp.asarray(unwrap(value), v.dtype), v), x, mask)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply(lambda c, a, b: jnp.where(c, a, b), condition, x, y)


def nonzero(x, as_tuple=False):
    v = unwrap(x)
    outs = jnp.nonzero(v)  # eager only (dynamic shape)
    if as_tuple:
        return tuple(Tensor(o[:, None]) for o in outs)
    return Tensor(jnp.stack(outs, axis=1))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """TP embedding helper (reference distributed/collective.py:526)."""
    def f(ids):
        shard_size = (index_num + nshards - 1) // nshards
        lo = shard_id * shard_size
        local = ids - lo
        ok = (ids >= lo) & (ids < lo + shard_size)
        return jnp.where(ok, local, ignore_value)
    return apply(f, input)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    v = unwrap(x)  # eager only
    res = jnp.unique(v, return_index=return_index, return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


def unbind(x, axis=0):
    return unstack(x, axis)


def as_complex(x, name=None):
    return apply(lambda v: jax.lax.complex(v[..., 0], v[..., 1]), x)


def as_real(x, name=None):
    return apply(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), x)


def crop(x, shape=None, offsets=None, name=None):
    def f(v):
        off = [int(unwrap(o)) for o in (offsets or [0] * v.ndim)]
        shp = [int(unwrap(s)) for s in (shape or v.shape)]
        shp = [v.shape[i] - off[i] if s == -1 else s for i, s in enumerate(shp)]
        return jax.lax.dynamic_slice(v, off, shp)
    return apply(f, x)


# ---------------------------------------------------------------------------
# search / sort — topk/argsort ops
# ---------------------------------------------------------------------------
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(v):
        out = jnp.argmax(v, axis=axis, keepdims=keepdim if axis is not None else False)
        return out.astype(convert_dtype(dtype))
    return apply(f, x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(v):
        out = jnp.argmin(v, axis=axis, keepdims=keepdim if axis is not None else False)
        return out.astype(convert_dtype(dtype))
    return apply(f, x)


def argsort(x, axis=-1, descending=False, name=None):
    def f(v):
        idx = jnp.argsort(-v if descending else v, axis=axis, stable=True)
        return idx.astype(jnp.int64)
    return apply(f, x)


def sort(x, axis=-1, descending=False, name=None):
    def f(v):
        s = jnp.sort(v, axis=axis, stable=True)
        return jnp.flip(s, axis=axis) if descending else s
    return apply(f, x)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):  # noqa: A002
    k = int(unwrap(k))

    def f(v):
        ax = axis % v.ndim
        vm = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vm, k)
        else:
            vals, idx = jax.lax.top_k(-vm, k)
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax)

    vals, idx = apply(f, x, _multi_out=True)
    return vals, idx


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(v):
        ax = axis % v.ndim
        s = jnp.sort(v, axis=ax)
        i = jnp.argsort(v, axis=ax).astype(jnp.int64)
        val = jnp.take(s, k - 1, axis=ax)
        ind = jnp.take(i, k - 1, axis=ax)
        if keepdim:
            val, ind = jnp.expand_dims(val, ax), jnp.expand_dims(ind, ax)
        return val, ind
    return apply(f, x, _multi_out=True)


def mode(x, axis=-1, keepdim=False, name=None):
    def f(v):
        ax = axis % v.ndim
        n = v.shape[ax]
        s = jnp.sort(v, axis=ax)
        shape = [1] * v.ndim
        shape[ax] = n
        pos = jnp.arange(n).reshape(shape)
        # run length ending at i == i - (start index of i's run) + 1.
        # Run starts marked where the sorted value changes; a cumulative
        # MAX over (start ? position : 0) is associative (the previous
        # formulation fed a non-associative op to associative_scan and
        # returned wrong modes — caught by the torch-oracle suite).
        head = jnp.ones_like(jnp.take(s, jnp.array([0]), ax), bool)
        starts = jnp.concatenate(
            [head, jnp.diff(s, axis=ax) != 0], axis=ax)
        start_idx = jax.lax.associative_scan(
            jnp.maximum, jnp.where(starts, pos, 0), axis=ax)
        run = pos - start_idx + 1
        # argmax takes the FIRST maximal run end; sorted ascending, that
        # is the smallest most-frequent value (torch's tie convention)
        k = jnp.argmax(run, axis=ax, keepdims=True)
        val = jnp.take_along_axis(s, k, axis=ax)
        # index into the ORIGINAL input: last occurrence (torch returns
        # the last index of the modal value)
        idx = jnp.argmax(jnp.where(v == val, pos, -1), axis=ax,
                         keepdims=True)
        if not keepdim:
            val, idx = jnp.squeeze(val, ax), jnp.squeeze(idx, ax)
        return val, idx.astype(jnp.int64)
    return apply(f, x, _multi_out=True)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def f(s, v):
        side = "right" if right else "left"
        if s.ndim == 1:
            out = jnp.searchsorted(s, v, side=side)
        else:
            out = jax.vmap(lambda a, b: jnp.searchsorted(a, b, side=side))(
                s.reshape(-1, s.shape[-1]), v.reshape(-1, v.shape[-1]))
            out = out.reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply(f, sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


# ---------------------------------------------------------------------------
# linalg — matmul/mul ops + math/blas.h dispatch (→ MXU via XLA dot)
# ---------------------------------------------------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        from .amp import white_cast

        a, b = white_cast(a, b)
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply(f, x, y)


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def inner(x, y, name=None):
    return apply(jnp.inner, x, y)


def outer(x, y, name=None):
    return apply(lambda a, b: jnp.outer(a, b), x, y)


def cross(x, y, axis=None, name=None):
    ax = 9 if axis is None else axis  # numpy default resolution

    def f(a, b):
        use_ax = axis
        if use_ax is None:
            # paddle: first axis with dim 3
            for i, s in enumerate(a.shape):
                if s == 3:
                    use_ax = i
                    break
        return jnp.cross(a, b, axis=use_ax)
    return apply(f, x, y)


def t(x, name=None):
    return apply(lambda v: v.T if v.ndim <= 2 else jnp.swapaxes(v, -1, -2), x)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def f(v):
        if p == "fro" and axis is None:
            return jnp.sqrt(jnp.sum(v * v))
        if axis is None:
            return jnp.linalg.norm(v.ravel(), ord=p, keepdims=keepdim)
        ax = _axis(axis)
        return jnp.linalg.norm(v, ord="fro" if p == "fro" else p, axis=ax,
                               keepdims=keepdim)
    return apply(f, x)


def dist(x, y, p=2, name=None):
    return apply(lambda a, b: jnp.linalg.norm((a - b).ravel(), ord=p), x, y)


def histogram(x, bins=100, min=0, max=0, name=None):  # noqa: A002
    v = unwrap(x)
    lo, hi = (float(jnp.min(v)), float(jnp.max(v))) if min == 0 and max == 0 else (min, max)
    h, _ = jnp.histogram(v, bins=bins, range=(lo, hi))
    return Tensor(h.astype(jnp.int64))


def bincount(x, weights=None, minlength=0, name=None):
    v = unwrap(x)
    return Tensor(jnp.bincount(v, unwrap(weights), minlength=minlength))


def einsum(equation, *operands):
    return apply(lambda *ops: jnp.einsum(equation, *ops), *operands)


def tensordot(x, y, axes=2, name=None):
    return apply(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def kron(x, y, name=None):
    return apply(jnp.kron, x, y)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2), x)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2), x)


def mv(x, vec, name=None):
    return apply(lambda a, b: a @ b, x, vec)


def multiplex(inputs, index, name=None):
    def f(idx, *vs):
        stacked = jnp.stack(vs, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))).astype(jnp.int32),
            axis=0)[0]
    return apply(f, index, *inputs)


class _Linalg:
    """paddle.linalg namespace."""

    @staticmethod
    def norm(x, p="fro", axis=None, keepdim=False, name=None):
        return norm(x, p, axis, keepdim)

    @staticmethod
    def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
        return matmul(x, y, transpose_x, transpose_y)

    @staticmethod
    def inv(x, name=None):
        return apply(jnp.linalg.inv, x)

    @staticmethod
    def pinv(x, rcond=1e-15, hermitian=False, name=None):
        return apply(lambda v: jnp.linalg.pinv(v, rcond=rcond, hermitian=hermitian), x)

    @staticmethod
    def det(x, name=None):
        return apply(jnp.linalg.det, x)

    @staticmethod
    def slogdet(x, name=None):
        def f(v):
            sign, logdet = jnp.linalg.slogdet(v)
            return jnp.stack([sign, logdet])
        return apply(f, x)

    @staticmethod
    def svd(x, full_matrices=False, name=None):
        return apply(lambda v: jnp.linalg.svd(v, full_matrices=full_matrices),
                     x, _multi_out=True)

    @staticmethod
    def qr(x, mode="reduced", name=None):
        return apply(lambda v: jnp.linalg.qr(v, mode=mode), x, _multi_out=True)

    @staticmethod
    def eig(x, name=None):
        return apply(jnp.linalg.eig, x, _multi_out=True)

    @staticmethod
    def eigh(x, UPLO="L", name=None):
        return apply(lambda v: jnp.linalg.eigh(v, UPLO=UPLO), x, _multi_out=True)

    @staticmethod
    def eigvals(x, name=None):
        return apply(jnp.linalg.eigvals, x)

    @staticmethod
    def eigvalsh(x, UPLO="L", name=None):
        return apply(lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), x)

    @staticmethod
    def cholesky(x, upper=False, name=None):
        def f(v):
            c = jnp.linalg.cholesky(v)
            return jnp.swapaxes(c, -1, -2) if upper else c
        return apply(f, x)

    @staticmethod
    def solve(x, y, name=None):
        return apply(jnp.linalg.solve, x, y)

    @staticmethod
    def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                         name=None):
        return apply(lambda a, b: jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular), x, y)

    @staticmethod
    def lstsq(x, y, rcond=None, driver=None, name=None):
        return apply(lambda a, b: jnp.linalg.lstsq(a, b, rcond=rcond),
                     x, y, _multi_out=True)

    @staticmethod
    def matrix_power(x, n, name=None):
        return apply(lambda v: jnp.linalg.matrix_power(v, n), x)

    @staticmethod
    def matrix_rank(x, tol=None, hermitian=False, name=None):
        return apply(lambda v: jnp.linalg.matrix_rank(v, tol=tol), x)

    @staticmethod
    def multi_dot(xs, name=None):
        return apply(lambda *vs: jnp.linalg.multi_dot(vs), *xs)

    @staticmethod
    def cond(x, p=None, name=None):
        return apply(lambda v: jnp.linalg.cond(v, p=p), x)


linalg = _Linalg()


# top-level aliases of the linalg namespace (paddle exposes both; the
# C++ registry names are `inverse`/`cholesky`: operators/inverse_op.cc,
# cholesky_op.cc)
def inverse(x, name=None):
    return linalg.inv(x)


def cholesky(x, upper=False, name=None):
    return linalg.cholesky(x, upper)


def add_n(inputs, name=None):
    """Sum a list of same-shape tensors (operators/sum_op.cc)."""
    if isinstance(inputs, (list, tuple)):
        def f(*vs):  # NB: `sum` here is paddle's reduce, not builtins.sum
            out = vs[0]
            for v in vs[1:]:
                out = out + v
            return out
        return apply(f, *inputs)
    return apply(lambda v: v, inputs)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) (operators/addmm_op.cc)."""
    return apply(lambda i, a, b: beta * i + alpha * (a @ b), input, x, y)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    """scale_b * tanh(scale_a * x) (activation_op.h STanhFunctor)."""
    return apply(lambda v: scale_b * jnp.tanh(scale_a * v), x)


def slice(x, axes, starts, ends, name=None):  # noqa: A001 - paddle API name
    """Static multi-axis slice (operators/slice_op.cc): negative indices
    wrap, out-of-range clamps."""
    def f(v):
        idx = [builtins.slice(None)] * v.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = builtins.slice(int(s), int(e))
        return v[tuple(idx)]
    return apply(f, x)


def strided_slice(x, axes, starts, ends, strides, name=None):
    """slice with per-axis stride (operators/strided_slice_op.cc);
    negative strides walk backwards like python slicing."""
    def f(v):
        idx = [builtins.slice(None)] * v.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            st = int(st)
            s, e = int(s), int(e)
            if st < 0 and e == -1:
                e = None  # walk through index 0 inclusively
            idx[ax] = builtins.slice(s, e, st)
        return v[tuple(idx)]
    return apply(f, x)


def _num_segments(ids, num_segments, op):
    if num_segments is not None:
        return int(num_segments)
    if isinstance(ids, jax.core.Tracer):
        raise ValueError(
            f"{op}: segment_ids is traced, so the output row count cannot "
            "be derived from max(ids); pass num_segments= explicitly "
            "inside jit (XLA needs a static output shape)")
    return int(jnp.max(ids)) + 1


def segment_sum(data, segment_ids, num_segments=None, name=None):
    """Sum rows sharing a segment id (operators/segment_pool_op.cc,
    pooltype SUM).  Output has max(ids)+1 rows eagerly; under jit pass
    num_segments= (a traced max would make the result shape dynamic)."""
    def f(v, ids):
        n = _num_segments(ids, num_segments, "segment_sum")
        return jax.ops.segment_sum(v, ids.astype(jnp.int32),
                                   num_segments=n)
    return apply(f, data, segment_ids)


def segment_mean(data, segment_ids, num_segments=None, name=None):
    def f(v, ids):
        n = _num_segments(ids, num_segments, "segment_mean")
        ids = ids.astype(jnp.int32)
        s = jax.ops.segment_sum(v, ids, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones_like(v), ids, num_segments=n)
        return s / jnp.maximum(c, 1)
    return apply(f, data, segment_ids)


# ---------------------------------------------------------------------------
# dtype casting helper (paddle.cast)
# ---------------------------------------------------------------------------
def cast(x, dtype):
    if isinstance(x, Tensor):
        return x.astype(dtype)
    # non-Tensor (deferred Variable / raw array): route through apply so
    # static-program capture defers the cast like every other op
    from .framework.dtype import convert_dtype

    np_dt = convert_dtype(dtype)
    return apply(lambda v: v.astype(np_dt), x)


def increment(x, value=1.0, name=None):
    out = apply(lambda v: v + jnp.asarray(value, v.dtype), x)
    x._value = out.value
    return x


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, jnp.int64))


def rank(x):
    return Tensor(jnp.asarray(unwrap(x).ndim, jnp.int32))


def shape(x):
    return Tensor(jnp.asarray(np.asarray(unwrap(x).shape), jnp.int32))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.complexfloating)


def is_integer(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.integer)


def is_floating_point(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.floating)


def real(x, name=None):
    return apply(jnp.real, x)


def imag(x, name=None):
    return apply(jnp.imag, x)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(unwrap(x).size == 0))


# ---------------------------------------------------------------------------
# method installation on Tensor
# ---------------------------------------------------------------------------
_METHOD_NAMES = [
    "abs", "acos", "acosh", "add", "all", "allclose", "amax", "amin", "angle",
    "any", "argmax", "argmin", "argsort", "asin", "asinh", "astype", "atan",
    "atan2", "atanh", "bincount", "bitwise_and", "bitwise_not", "bitwise_or",
    "bitwise_xor", "bmm", "broadcast_to", "bucketize", "cast", "ceil", "chunk",
    "clip", "concat", "conj", "cos", "cosh", "count_nonzero", "cross", "cumprod",
    "cumsum", "diagonal", "digamma", "dist", "divide", "dot", "einsum", "equal",
    "equal_all", "erf", "erfinv", "exp", "expand", "expand_as", "expm1",
    "flatten", "flip", "floor", "floor_divide", "fmax", "fmin", "frac",
    "gather", "gather_nd", "greater_equal", "greater_than", "histogram",
    "imag", "index_sample", "index_select", "inner", "isclose", "isfinite",
    "isinf", "isnan", "kron", "kthvalue", "lerp", "less_equal", "less_than",
    "lgamma", "log", "log10", "log1p", "log2", "logcumsumexp", "logical_and",
    "logical_not", "logical_or", "logical_xor", "logsumexp", "masked_fill",
    "masked_select", "matmul", "max", "maximum", "mean", "median", "min",
    "minimum", "mm", "mod", "mode", "moveaxis", "multiplex", "multiply", "mv",
    "nan_to_num", "nanmean", "nansum", "neg", "nonzero", "norm", "not_equal",
    "numel", "outer", "pad", "pow", "prod", "put_along_axis", "quantile",
    "real", "reciprocal", "remainder", "repeat_interleave", "reshape", "roll",
    "rot90", "round", "rsqrt", "scale", "scatter", "scatter_nd_add", "sign",
    "sin", "sinh", "sort", "split", "sqrt", "square", "squeeze", "stack",
    "std", "subtract", "sum", "swapaxes", "t", "take_along_axis", "tan",
    "tanh_", "tensordot", "tile", "topk", "trace", "transpose", "tril", "triu",
    "trunc", "unbind", "unique", "unsqueeze", "unstack", "var", "where",
]


def tanh(x, name=None):
    return apply(jnp.tanh, x)


def sigmoid(x, name=None):
    return apply(jax.nn.sigmoid, x)


def _install_methods():
    g = globals()
    for name in _METHOD_NAMES + ["tanh", "sigmoid", "tril", "triu", "diag"]:
        fn = g.get(name)
        if fn is None:
            from . import creation

            fn = getattr(creation, name, None)
        if fn is None:
            continue
        if not hasattr(Tensor, name):
            setattr(Tensor, name, fn)

    # dunders
    def _binop(fn, reflected=False):
        def op(self, other):
            if reflected:
                return fn(other if isinstance(other, Tensor) else Tensor(np.asarray(other)), self)
            return fn(self, other)
        return op

    Tensor.__add__ = _binop(add)
    Tensor.__radd__ = _binop(add, True)
    Tensor.__sub__ = _binop(subtract)
    Tensor.__rsub__ = _binop(subtract, True)
    Tensor.__mul__ = _binop(multiply)
    Tensor.__rmul__ = _binop(multiply, True)
    Tensor.__truediv__ = _binop(divide)
    Tensor.__rtruediv__ = _binop(divide, True)
    Tensor.__floordiv__ = _binop(floor_divide)
    Tensor.__rfloordiv__ = _binop(floor_divide, True)
    Tensor.__mod__ = _binop(mod)
    Tensor.__rmod__ = _binop(mod, True)
    Tensor.__pow__ = _binop(pow)
    Tensor.__rpow__ = _binop(pow, True)
    Tensor.__matmul__ = _binop(matmul)
    Tensor.__rmatmul__ = _binop(matmul, True)
    Tensor.__neg__ = lambda self: apply(jnp.negative, self)
    Tensor.__abs__ = lambda self: apply(jnp.abs, self)
    Tensor.__invert__ = lambda self: apply(jnp.logical_not, self)
    Tensor.__eq__ = _binop(equal)
    Tensor.__ne__ = _binop(not_equal)
    Tensor.__lt__ = _binop(less_than)
    Tensor.__le__ = _binop(less_equal)
    Tensor.__gt__ = _binop(greater_than)
    Tensor.__ge__ = _binop(greater_equal)
    Tensor.__and__ = _binop(logical_and)
    Tensor.__or__ = _binop(logical_or)
    Tensor.__xor__ = _binop(logical_xor)


_install_methods()


# ---------------------------------------------------------------------------
# fluid-era top-level aliases (python/paddle/__init__.py #DEFINE_ALIAS
# block): same lowerings under the legacy names
# ---------------------------------------------------------------------------
def _fluid_axis_align(x, y, axis):
    """fluid's elementwise axis semantics (elementwise_op_function.h):
    y's dims align to x's starting at `axis` (counted from the left), so
    trailing singleton axes are appended to y before broadcasting."""
    if axis == -1:
        return y
    xv, yv = unwrap(x), unwrap(y)
    pad = xv.ndim - int(axis) - yv.ndim
    if pad < 0:
        raise ValueError(
            f"elementwise axis={axis} incompatible with ranks "
            f"{xv.ndim} vs {yv.ndim}")
    if pad == 0:
        return y
    return apply(lambda v: v.reshape(v.shape + (1,) * pad), y)


def elementwise_add(x, y, axis=-1, name=None):
    return add(x, _fluid_axis_align(x, y, axis))


def elementwise_sub(x, y, axis=-1, name=None):
    return subtract(x, _fluid_axis_align(x, y, axis))


def elementwise_mul(x, y, axis=-1, name=None):
    return multiply(x, _fluid_axis_align(x, y, axis))


def elementwise_div(x, y, axis=-1, name=None):
    return divide(x, _fluid_axis_align(x, y, axis))


def elementwise_floordiv(x, y, axis=-1, name=None):
    return floor_divide(x, _fluid_axis_align(x, y, axis))


def elementwise_mod(x, y, axis=-1, name=None):
    return mod(x, _fluid_axis_align(x, y, axis))


def elementwise_pow(x, y, axis=-1, name=None):
    return pow(x, _fluid_axis_align(x, y, axis))


def floor_mod(x, y, name=None):
    return mod(x, y)


def reduce_sum(x, dim=None, keep_dim=False, name=None):
    return sum(x, axis=dim, keepdim=keep_dim)


def reduce_mean(x, dim=None, keep_dim=False, name=None):
    return mean(x, axis=dim, keepdim=keep_dim)


def reduce_max(x, dim=None, keep_dim=False, name=None):
    return max(x, axis=dim, keepdim=keep_dim)


def reduce_min(x, dim=None, keep_dim=False, name=None):
    return min(x, axis=dim, keepdim=keep_dim)


def reduce_prod(x, dim=None, keep_dim=False, name=None):
    return prod(x, axis=dim, keepdim=keep_dim)


def broadcast_shape(x_shape, y_shape):
    """Shape of broadcasting x_shape against y_shape
    (paddle.broadcast_shape)."""
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def has_inf(x, name=None):
    return apply(lambda v: jnp.isinf(v).any(), x)


def has_nan(x, name=None):
    return apply(lambda v: jnp.isnan(v).any(), x)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor repr formatting (paddle.set_printoptions): Tensor printing
    routes through numpy, so this forwards to numpy's printoptions."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def get_tensor_from_selected_rows(x, name=None):
    """SelectedRows do not exist on TPU (gradients are dense pytree
    arrays — COVERAGE.md); the contained tensor IS the input."""
    return x if isinstance(x, Tensor) else Tensor(unwrap(x))
