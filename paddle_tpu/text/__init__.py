"""paddle.text — text datasets.

Reference parity: python/paddle/text/datasets (Imdb, Imikolov, WMT14/16,
UCIHousing, Movielens).  Zero-egress environment: local files when present,
deterministic synthetic fallbacks otherwise (structured so language-model
convergence tests have signal to learn).
"""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset
from . import sequence  # noqa: F401 — paddle_tpu.text.sequence op family
from .conll05 import Conll05st  # noqa: F401 — text/datasets/conll05.py:43

_CACHE = os.path.expanduser("~/.cache/paddle_tpu/datasets")


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train", download=True):
        rng = np.random.RandomState(42)
        n = 404 if mode == "train" else 102
        w = rng.randn(13).astype(np.float32)
        self.x = rng.randn(n, 13).astype(np.float32)
        self.y = (self.x @ w + 0.1 * rng.randn(n)).astype(np.float32)[:, None]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]


class Imdb(Dataset):
    """Synthetic sentiment data: positive docs draw tokens from one zipf
    region, negative from another."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=True,
                 seq_len=128, vocab_size=5000):
        rng = np.random.RandomState(7 if mode == "train" else 8)
        n = 2000 if mode == "train" else 400
        self.vocab_size = vocab_size
        labels = rng.randint(0, 2, n)
        docs = []
        for y in labels:
            base = rng.zipf(1.3, seq_len).clip(1, vocab_size // 2 - 1)
            offset = 0 if y == 0 else vocab_size // 2
            docs.append((base + offset).astype(np.int64))
        self.docs = np.stack(docs)
        self.labels = labels.astype(np.int64)
        self.word_idx = {f"tok{i}": i for i in range(vocab_size)}

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]


class Imikolov(Dataset):
    """Synthetic n-gram LM data with Markov structure."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True,
                 vocab_size=2000):
        rng = np.random.RandomState(11 if mode == "train" else 12)
        n = 5000 if mode == "train" else 1000
        self.window = window_size
        # first-order Markov chain: next token = (3*prev + noise) % vocab
        seqs = np.zeros((n, window_size), np.int64)
        seqs[:, 0] = rng.randint(0, vocab_size, n)
        for t in range(1, window_size):
            seqs[:, t] = (3 * seqs[:, t - 1] + rng.randint(0, 7, n)) % vocab_size
        self.data = seqs
        self.word_idx = {f"w{i}": i for i in range(vocab_size)}

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]


class Movielens(Dataset):
    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        rng = np.random.RandomState(rand_seed)
        n = 4000 if mode == "train" else 400
        self.users = rng.randint(0, 500, n).astype(np.int64)
        self.movies = rng.randint(0, 1000, n).astype(np.int64)
        u_bias = rng.randn(500)
        m_bias = rng.randn(1000)
        score = 3 + u_bias[self.users] + m_bias[self.movies]
        self.ratings = np.clip(np.round(score), 1, 5).astype(np.float32)

    def __len__(self):
        return len(self.users)

    def __getitem__(self, idx):
        return (self.users[idx], self.movies[idx]), self.ratings[idx]


class WMT14(Dataset):
    """Synthetic translation pairs: target = deterministic permutation map of
    source tokens (learnable copy-map task)."""

    def __init__(self, data_file=None, mode="train", dict_size=3000,
                 download=True, seq_len=24):
        rng = np.random.RandomState(17 if mode == "train" else 18)
        n = 2000 if mode == "train" else 200
        self.dict_size = dict_size
        perm = np.random.RandomState(99).permutation(dict_size)
        self.src = rng.randint(4, dict_size, (n, seq_len)).astype(np.int64)
        self.tgt = perm[self.src]
        self.src_ids = self.src
        self.trg_ids = self.tgt

    def __len__(self):
        return len(self.src)

    def __getitem__(self, idx):
        return self.src[idx], self.tgt[idx], self.tgt[idx]


class WMT16(WMT14):
    pass


class ViterbiDecoder:
    """Viterbi decoding for linear-chain CRF outputs.

    scores, paths = decoder(potentials [B,L,C], lengths [B]) — max-sum
    forward pass then backtrack, both as lax.scan so it jits on TPU
    (reference: paddle/fluid/operators/viterbi_decode_op.cc /
    python/paddle/text/viterbi_decode.py). With include_bos_eos_tag, tag
    C-2 is BOS (start transition) and C-1 is EOS (stop transition)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        from ..tensor import Tensor, unwrap
        import jax.numpy as jnp

        self.transitions = jnp.asarray(unwrap(transitions), jnp.float32)
        self.include_bos_eos_tag = bool(include_bos_eos_tag)

    def __call__(self, potentials, lengths):
        from ..tensor import Tensor, apply

        trans = self.transitions
        use_tag = self.include_bos_eos_tag

        def f(pot, lens):
            import jax
            import jax.numpy as jnp
            from jax import lax

            pot = pot.astype(jnp.float32)
            B, L, C = pot.shape
            lens = lens.astype(jnp.int32)
            bos, eos = C - 2, C - 1

            alpha0 = pot[:, 0] + (trans[bos][None] if use_tag else 0.0)

            def fwd(alpha, pot_t_and_t):
                pot_t, t = pot_t_and_t
                # [B, prev, cur]
                m = alpha[:, :, None] + trans[None]
                best = jnp.max(m, axis=1) + pot_t
                idx = jnp.argmax(m, axis=1).astype(jnp.int32)
                live = (t < lens)[:, None]
                return jnp.where(live, best, alpha), idx

            ts = jnp.arange(1, L)
            alpha, idxs = lax.scan(fwd, alpha0,
                                   (jnp.moveaxis(pot[:, 1:], 1, 0), ts))
            if use_tag:
                alpha = alpha + trans[:, eos][None]
            scores = jnp.max(alpha, axis=-1)
            last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)

            def bwd(tag, idx_t_and_t):
                idx_t, t = idx_t_and_t
                prev = jnp.take_along_axis(idx_t, tag[:, None],
                                           axis=1)[:, 0]
                live = t < lens  # step t produced tags for position t
                tag_prev = jnp.where(live, prev, tag)
                return tag_prev, tag_prev

            # walk t = L-1 .. 1, emitting the tag at t-1
            _, rev = lax.scan(bwd, last_tag, (idxs, ts), reverse=True)
            paths = jnp.concatenate(
                [jnp.moveaxis(rev, 0, 1), last_tag[:, None]], axis=1)
            # positions beyond each sequence's length report tag 0
            pos = jnp.arange(L)[None]
            paths = jnp.where(pos < lens[:, None], paths, 0)
            return scores, paths.astype(jnp.int64)

        return apply(f, potentials, lengths, _multi_out=True)


# --------------------------------------------------------------------------
# decoding ops (operators/gather_tree_op.cc, beam_search_op.cc,
# beam_search_decode_op.cc, linear_chain_crf_op.cc) — dense [B,...]
# re-designs of the reference's LoD forms
# --------------------------------------------------------------------------

def gather_tree(ids, parents):
    """Backtrack beam parent pointers into full sequences
    (gather_tree_op.cc): ids/parents [T, B, W] -> [T, B, W] where output
    step t holds the token on the surviving path through beam parents."""
    import jax
    import jax.numpy as jnp

    from ..tensor import apply

    def f(idv, par):
        T, B, W = idv.shape
        b = jnp.arange(B)[:, None]

        def step(beam, t):
            tok = idv[t, b, beam]
            beam2 = par[t, b, beam]
            return beam2, tok

        last = jnp.broadcast_to(jnp.arange(W)[None, :],
                                (B, W)).astype(par.dtype)
        _, toks = jax.lax.scan(step, last, jnp.arange(T - 1, -1, -1))
        return toks[::-1]  # scanned back-to-front

    return apply(f, ids, parents)


def beam_search_step(log_probs, pre_scores, beam_size, end_token=None,
                     finished=None):
    """One beam expansion (beam_search_op.cc re-designed functionally):
    log_probs [B, W, V] for the current step, pre_scores [B, W] running
    scores -> (ids [B, beam], parents [B, beam], scores [B, beam]) by
    top-k over the W*V joint candidates.  Finished beams (optional mask
    [B, W]) keep their score and only propose end_token."""
    import jax
    import jax.numpy as jnp

    from ..tensor import apply

    def f(lp, ps, *rest):
        B, W, V = lp.shape
        if rest:
            fin = rest[0]
            keep = jnp.full((V,), -jnp.inf, lp.dtype).at[end_token].set(0.0)
            lp = jnp.where(fin[..., None], keep[None, None, :], lp)
        total = ps[..., None] + lp
        flat = total.reshape(B, W * V)
        scores, idx = jax.lax.top_k(flat, beam_size)
        return idx % V, idx // V, scores

    args = (log_probs, pre_scores) + ((finished,) if finished is not None
                                      else ())
    return apply(f, *args, _multi_out=True)


def beam_search_decode(step_ids, step_parents, final_scores):
    """Assemble beam outputs into ranked sequences
    (beam_search_decode_op.cc): step_ids/step_parents [T, B, W] plus
    final scores [B, W] -> (sequences [B, W, T], scores [B, W])."""
    import jax.numpy as jnp

    from ..tensor import Tensor, unwrap

    toks = gather_tree(step_ids, step_parents)
    seq = jnp.transpose(unwrap(toks), (1, 2, 0))
    return Tensor(seq), (final_scores if isinstance(final_scores, Tensor)
                         else Tensor(final_scores))


def linear_chain_crf(emission, transition, label, seq_len):
    """Per-sequence CRF log-likelihood (linear_chain_crf_op.h):
    emission [B, T, K]; transition [K+2, K] with row 0 = start weights,
    row 1 = stop weights, rows 2: = square transition matrix (the
    reference's layout); label [B, T]; seq_len [B] -> ll [B].

    Forward algorithm as a lax.scan over time with a validity mask —
    differentiable, so -ll.mean() trains the CRF end to end."""
    import jax
    import jax.numpy as jnp

    from ..tensor import apply

    def f(em, tr, lab, ln):
        B, T, K = em.shape
        start, stop, trans = tr[0], tr[1], tr[2:]

        # --- partition (log Z) via masked forward recursion
        alpha0 = start[None, :] + em[:, 0]            # [B, K]

        def fwd(alpha, t):
            nxt = jax.scipy.special.logsumexp(
                alpha[:, :, None] + trans[None], axis=1) + em[:, t]
            live = (t < ln)[:, None]
            return jnp.where(live, nxt, alpha), None

        alpha, _ = jax.lax.scan(fwd, alpha0, jnp.arange(1, T))
        logz = jax.scipy.special.logsumexp(alpha + stop[None, :], axis=1)

        # --- gold path score
        t_idx = jnp.arange(T)[None, :]
        valid = t_idx < ln[:, None]
        em_g = jnp.take_along_axis(em, lab[..., None], -1)[..., 0]
        em_score = jnp.where(valid, em_g, 0).sum(1)
        prev, cur = lab[:, :-1], lab[:, 1:]
        tr_g = trans[prev, cur]
        tr_score = jnp.where(valid[:, 1:], tr_g, 0).sum(1)
        first = lab[:, 0]
        last_idx = jnp.maximum(ln - 1, 0)
        last_lab = jnp.take_along_axis(lab, last_idx[:, None], 1)[:, 0]
        gold = start[first] + em_score + tr_score + stop[last_lab]
        return gold - logz

    return apply(f, emission, transition, label, seq_len)

from . import datasets  # noqa: E402,F401 — ref text/__init__.py submodule
