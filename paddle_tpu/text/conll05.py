"""CoNLL-2005 semantic-role-labeling dataset.

Reference parity: python/paddle/text/datasets/conll05.py:43 — each
sample is the 9-tuple (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
pred_id, mark, label_ids) the fluid SRL demo feeds; context windows are
broadcast over the sentence and `mark` flags the 5-token predicate
window.  Zero-egress house rule: the official conll05st-tests tar is
used when present locally, else a deterministic synthetic SRL corpus
marked `synthetic=True`.
"""
from __future__ import annotations

import gzip
import os
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["Conll05st"]

_CACHE = os.path.expanduser("~/.cache/paddle_tpu/datasets")
_TAR = os.path.join(_CACHE, "conll05st-tests.tar.gz")
_WORDS_NAME = "conll05st-release/test.wsj/words/test.wsj.words.gz"
_PROPS_NAME = "conll05st-release/test.wsj/props/test.wsj.props.gz"
UNK_IDX = 0


def _parse_label_column(lbl):
    """One props column -> BIO tag sequence (reference conll05.py:200
    bracket-walk: '(A0*' opens, '*)' closes, bare '*' continues)."""
    cur_tag, in_bracket, seq = "O", False, []
    for tok in lbl:
        if tok == "*" and not in_bracket:
            seq.append("O")
        elif tok == "*" and in_bracket:
            seq.append("I-" + cur_tag)
        elif tok == "*)":
            seq.append("I-" + cur_tag)
            in_bracket = False
        elif "(" in tok and ")" in tok:
            cur_tag = tok[1:tok.find("*")]
            seq.append("B-" + cur_tag)
            in_bracket = False
        elif "(" in tok:
            cur_tag = tok[1:tok.find("*")]
            seq.append("B-" + cur_tag)
            in_bracket = True
        else:
            raise RuntimeError(f"Unexpected label: {tok}")
    return seq


class Conll05st(Dataset):
    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 download=True):
        self.data_file = data_file or (_TAR if os.path.exists(_TAR) else None)
        self.emb_file = emb_file
        self.synthetic = self.data_file is None
        self.sentences, self.predicates, self.labels = [], [], []
        if self.synthetic:
            self._make_synthetic()
        else:
            self._load_tar()
        self.word_dict = self._read_dict(word_dict_file) or self._build_dict(
            (w for s in self.sentences for w in s), extra=("bos", "eos"))
        self.predicate_dict = (self._read_dict(verb_dict_file)
                               or self._build_dict(self.predicates))
        self.label_dict = (self._read_dict(target_dict_file)
                           or self._build_dict(
                               t for ls in self.labels for t in ls))

    @staticmethod
    def _read_dict(path):
        if path is None or not os.path.exists(path):
            return None
        with open(path) as f:
            return {ln.strip(): i for i, ln in enumerate(f) if ln.strip()}

    @staticmethod
    def _build_dict(tokens, extra=()):
        vocab = sorted(set(tokens) | set(extra))
        return {w: i for i, w in enumerate(vocab)}

    def _make_synthetic(self):
        rng = np.random.RandomState(0)
        nouns = [f"n{i}" for i in range(40)]
        verbs = [f"v{i}" for i in range(8)]
        for _ in range(80):
            n = int(rng.randint(4, 12))
            vi = int(rng.randint(1, n - 1))
            sent = [nouns[rng.randint(40)] for _ in range(n)]
            sent[vi] = verbs[rng.randint(8)]
            lbl = ["O"] * n
            lbl[vi] = "B-V"
            lbl[0], lbl[vi - 1] = "B-A0", "I-A0" if vi > 1 else lbl[vi - 1]
            if vi + 1 < n:
                lbl[vi + 1] = "B-A1"
            self.sentences.append(sent)
            self.predicates.append(sent[vi])
            self.labels.append(lbl)

    def _load_tar(self):
        with tarfile.open(self.data_file) as tf:
            words = gzip.decompress(
                tf.extractfile(_WORDS_NAME).read()).decode().splitlines()
            props = gzip.decompress(
                tf.extractfile(_PROPS_NAME).read()).decode().splitlines()
        sentence, columns = [], []
        for wline, pline in zip(words, props):
            w = wline.strip()
            p = pline.strip().split()
            if not w:  # sentence boundary
                if sentence and columns:
                    verbs = [c[0] for c in columns if c[0] != "-"]
                    cols = list(zip(*columns))[1:]
                    for i, col in enumerate(cols):
                        try:
                            seq = _parse_label_column(col)
                        except RuntimeError:
                            continue
                        if "B-V" in seq and i < len(verbs):
                            self.sentences.append(sentence)
                            self.predicates.append(verbs[i])
                            self.labels.append(seq)
                sentence, columns = [], []
                continue
            sentence = sentence + [w.split()[0]]
            columns.append(p)

    def __getitem__(self, idx):
        sentence, predicate = self.sentences[idx], self.predicates[idx]
        labels = self.labels[idx]
        n = len(sentence)
        vi = labels.index("B-V")
        mark = [0] * n
        ctx = {}
        for off, name, pad in ((-2, "n2", "bos"), (-1, "n1", "bos"),
                               (0, "0", None), (1, "p1", "eos"),
                               (2, "p2", "eos")):
            j = vi + off
            if 0 <= j < n:
                mark[j] = 1
                ctx[name] = sentence[j]
            else:
                ctx[name] = pad
        wd = self.word_dict
        word_idx = [wd.get(w, UNK_IDX) for w in sentence]
        ctx_cols = [[wd.get(ctx[k], UNK_IDX)] * n
                    for k in ("n2", "n1", "0", "p1", "p2")]
        pred_idx = [self.predicate_dict.get(predicate, 0)] * n
        label_idx = [self.label_dict.get(t, 0) for t in labels]
        return (np.array(word_idx), np.array(ctx_cols[0]),
                np.array(ctx_cols[1]), np.array(ctx_cols[2]),
                np.array(ctx_cols[3]), np.array(ctx_cols[4]),
                np.array(pred_idx), np.array(mark), np.array(label_idx))

    def __len__(self):
        return len(self.sentences)

    def get_dict(self):
        """(word_dict, verb_dict, label_dict) — reference conll05.py:295."""
        return self.word_dict, self.predicate_dict, self.label_dict

    def get_embedding(self):
        if self.emb_file and os.path.exists(self.emb_file):
            return np.load(self.emb_file)
        return None
