"""paddle.text.datasets — submodule alias (reference
python/paddle/text/__init__.py: `from . import datasets`); the dataset
classes live on the package for direct access either way."""
from . import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16)

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]
