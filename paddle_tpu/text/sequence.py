"""Sequence ops — the dense+mask re-design of the reference's LoD family.

Reference parity: paddle/fluid/operators/sequence_ops/*.cc
(sequence_pool, sequence_conv, sequence_pad/unpad, sequence_expand(_as),
sequence_reverse, sequence_softmax, sequence_erase, sequence_enumerate,
sequence_slice, sequence_reshape, sequence_scatter, sequence_concat).

TPU-native design: the reference represents variable-length batches as
LoD (level-of-detail) tensors — a flat value buffer plus host-side
offset tables — and every sequence op walks the offsets.  XLA has static
shapes, so here a batch is a PADDED dense array ``[B, T, ...]`` plus an
explicit ``seq_len [B]`` int vector.  Ops whose output shape is
data-independent are pure jnp (jit-safe, differentiable); ops whose
output is inherently ragged (pad/unpad/expand/reshape between flat and
padded forms) run eagerly on concrete arrays and raise a clear error
under tracing — inside jit you stay padded+masked.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..tensor import Tensor, apply, unwrap

__all__ = [
    "sequence_mask", "sequence_pool", "sequence_softmax",
    "sequence_reverse", "sequence_conv", "sequence_concat",
    "sequence_erase", "sequence_enumerate", "sequence_slice",
    "sequence_scatter", "sequence_pad", "sequence_unpad",
    "sequence_expand", "sequence_expand_as", "sequence_reshape",
]


def _eager(x, op):
    v = unwrap(x) if isinstance(x, Tensor) else x
    if isinstance(v, jax.core.Tracer):
        raise TypeError(
            f"{op} produces a data-dependent (ragged) shape and cannot run "
            "under jit; keep the padded [B,T,...] + seq_len form inside "
            "compiled code and call this op eagerly at the host boundary")
    return np.asarray(v)


def sequence_mask(seq_len, maxlen, dtype="bool"):
    """[B, maxlen] validity mask (sequence_mask_op.cc... the one LoD util
    the reference itself exposes as a dense op)."""
    def f(ln):
        m = jnp.arange(maxlen)[None, :] < ln[:, None]
        return m if dtype == "bool" else m.astype(dtype)
    return apply(f, seq_len)


def sequence_pool(x, seq_len, pool_type="SUM", pad_value=0.0):
    """Per-sequence pooling over the time axis
    (sequence_ops/sequence_pool_op.cc): SUM / AVERAGE / SQRT / MAX /
    MIN / LAST / FIRST.  x [B,T,...], seq_len [B] -> [B,...]."""
    pt = pool_type.upper()

    def f(v, ln):
        T = v.shape[1]
        mask = jnp.arange(T)[None, :] < ln[:, None]
        m = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
        ln_f = jnp.maximum(ln, 1).astype(v.dtype)
        ln_f = ln_f.reshape((-1,) + (1,) * (v.ndim - 2))
        if pt == "SUM":
            out = jnp.where(m, v, 0).sum(axis=1)
        elif pt == "AVERAGE":
            out = jnp.where(m, v, 0).sum(axis=1) / ln_f
        elif pt == "SQRT":
            out = jnp.where(m, v, 0).sum(axis=1) / jnp.sqrt(ln_f)
        elif pt == "MAX":
            out = jnp.where(m, v, -jnp.inf).max(axis=1)
        elif pt == "MIN":
            out = jnp.where(m, v, jnp.inf).min(axis=1)
        elif pt == "FIRST":
            out = v[:, 0]
        elif pt == "LAST":
            idx = jnp.maximum(ln - 1, 0)
            out = jnp.take_along_axis(
                v, idx.reshape((-1, 1) + (1,) * (v.ndim - 2)), axis=1
            ).squeeze(1)
        else:
            raise ValueError(f"unknown pool_type {pool_type}")
        # empty sequences pool to pad_value (reference behavior)
        empty = (ln == 0).reshape((-1,) + (1,) * (out.ndim - 1))
        return jnp.where(empty, jnp.asarray(pad_value, out.dtype), out)

    return apply(f, x, seq_len)


def sequence_softmax(x, seq_len):
    """Masked softmax over the valid prefix of each row
    (sequence_softmax_op.cc).  x [B,T] -> [B,T] with zeros at padding."""
    def f(v, ln):
        mask = jnp.arange(v.shape[1])[None, :] < ln[:, None]
        # zero-length rows: an all(-inf) row softmaxes to NaN (and NaN
        # survives jnp.where grads — advisor r04); compute from a
        # NaN-free masked input and zero those rows out afterwards
        z = jnp.where(mask, v, -1e30)
        p = jax.nn.softmax(z, axis=1)
        p = jnp.where(mask, p, 0)
        return jnp.where((ln > 0)[:, None], p, 0)
    return apply(f, x, seq_len)


def sequence_reverse(x, seq_len):
    """Reverse each valid prefix, padding stays in place
    (sequence_reverse_op.h).  x [B,T,...]."""
    def f(v, ln):
        T = v.shape[1]
        t = jnp.arange(T)[None, :]
        src = jnp.where(t < ln[:, None], ln[:, None] - 1 - t, t)
        return jnp.take_along_axis(
            v, src.reshape(src.shape + (1,) * (v.ndim - 2)), axis=1)
    return apply(f, x, seq_len)


def sequence_conv(x, seq_len, filter, context_length, context_start=None,
                  padding=True):
    """Context-window convolution over time (sequence_conv_op.cc):
    gather a [context_length] window around each step (zeros outside the
    valid range), flatten to [B,T,ctx*D], matmul with
    filter [ctx*D, num_filters]."""
    if context_start is None:
        context_start = -((context_length - 1) // 2)

    def f(v, ln, w):
        B, T, D = v.shape
        t = jnp.arange(T)[None, :, None]                 # [1,T,1]
        off = jnp.arange(context_length)[None, None, :]  # [1,1,C]
        src = t + off + context_start                    # [1,T,C]
        valid = (src >= 0) & (src < ln[:, None, None])
        src_c = jnp.clip(src, 0, T - 1)
        g = v[jnp.arange(B)[:, None, None], src_c]       # [B,T,C,D]
        g = jnp.where(valid[..., None], g, 0)
        out = g.reshape(B, T, context_length * D) @ w
        mask = (jnp.arange(T)[None, :] < ln[:, None])[..., None]
        return jnp.where(mask, out, 0)

    return apply(f, x, seq_len, filter)


def sequence_concat(xs, seq_lens):
    """Concatenate per-row valid prefixes (sequence_concat_op.cc):
    ([B,T1,...],[B,T2,...]) + lens -> [B, sum(Ti), ...] packed left,
    new lens = sum of lens.  jit-safe scatter build."""
    vs = [unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x)
          for x in xs]
    lns = [unwrap(l).astype(jnp.int32) if isinstance(l, Tensor)
           else jnp.asarray(l, jnp.int32) for l in seq_lens]
    B = vs[0].shape[0]
    T_out = sum(v.shape[1] for v in vs)
    feat = vs[0].shape[2:]
    out = jnp.zeros((B, T_out) + feat, vs[0].dtype)
    base = jnp.zeros((B,), jnp.int32)
    for v, ln in zip(vs, lns):
        T = v.shape[1]
        t = jnp.arange(T)[None, :]
        dst = base[:, None] + t                       # [B,T]
        valid = t < ln[:, None]
        dst_c = jnp.where(valid, dst, T_out)          # OOB drops
        bidx = jnp.broadcast_to(jnp.arange(B)[:, None], dst_c.shape)
        out = out.at[bidx.reshape(-1), dst_c.reshape(-1)].set(
            v.reshape((-1,) + feat), mode="drop")
        base = base + ln
    total = base
    return Tensor(out), Tensor(total)


def sequence_erase(ids, seq_len, tokens):
    """Remove the given token values, shift survivors left, update lens
    (sequence_erase_op.cc).  ids [B,T] int -> ([B,T], new_len [B]);
    vacated positions are zero-filled."""
    tokens = jnp.asarray(list(tokens))

    def f(v, ln):
        T = v.shape[1]
        t = jnp.arange(T)[None, :]
        valid = t < ln[:, None]
        keep = valid & ~jnp.isin(v, tokens)
        # stable order of kept elements: sort by (not keep, position)
        order = jnp.argsort(jnp.where(keep, t, T + t), axis=1)
        packed = jnp.take_along_axis(v, order, axis=1)
        new_len = keep.sum(axis=1)
        packed = jnp.where(t < new_len[:, None], packed, 0)
        return packed, new_len

    out = apply(f, ids, seq_len, _multi_out=True)
    return out


def sequence_enumerate(ids, seq_len, win_size, pad_value=0):
    """Sliding windows (sequence_enumerate_op.cc): out[b,t,k] =
    ids[b,t+k] while t+k is valid, else pad_value.  [B,T] -> [B,T,win]."""
    def f(v, ln):
        B, T = v.shape
        t = jnp.arange(T)[None, :, None]
        k = jnp.arange(win_size)[None, None, :]
        src = t + k
        valid = (src < ln[:, None, None])
        src_c = jnp.clip(src, 0, T - 1)
        g = v[jnp.arange(B)[:, None, None], src_c]
        g = jnp.where(valid, g, pad_value)
        row_valid = (jnp.arange(T)[None, :] < ln[:, None])[..., None]
        return jnp.where(row_valid, g, pad_value)
    return apply(f, ids, seq_len)


def sequence_slice(x, seq_len, offset, length):
    """Per-row subsequence (sequence_slice_op.h): take length[b] steps
    starting at offset[b]; output packed left in the same container,
    new lens = length."""
    def f(v, ln, off, lgt):
        B, T = v.shape[0], v.shape[1]
        t = jnp.arange(T)[None, :]
        src = jnp.clip(off[:, None] + t, 0, T - 1)
        g = jnp.take_along_axis(
            v, src.reshape(src.shape + (1,) * (v.ndim - 2)), axis=1)
        valid = t < lgt[:, None]
        m = valid.reshape(valid.shape + (1,) * (v.ndim - 2))
        return jnp.where(m, g, 0), lgt

    return apply(f, x, seq_len, offset, length, _multi_out=True)


def sequence_scatter(x, index, updates, seq_len):
    """Scatter-add each sequence's updates into its row
    (sequence_scatter_op.cc): x [B,D]; index/updates [B,T] padded with
    seq_len valid entries; out[b, index[b,k]] += updates[b,k]."""
    def f(v, idx, upd, ln):
        B, D = v.shape
        T = idx.shape[1]
        t = jnp.arange(T)[None, :]
        valid = t < ln[:, None]
        idx_c = jnp.where(valid, idx, D)  # OOB drops
        bidx = jnp.broadcast_to(jnp.arange(B)[:, None], idx_c.shape)
        return v.at[bidx.reshape(-1), idx_c.reshape(-1)].add(
            jnp.where(valid, upd, 0).reshape(-1), mode="drop")
    return apply(f, x, index, updates, seq_len)


# ---- ragged <-> padded converters (eager: data-dependent shapes) ---------

def sequence_pad(x, seq_len, maxlen=None, pad_value=0.0):
    """Flat [sum(len), ...] + lens -> padded [B, maxlen, ...]
    (sequence_pad_op.cc).  Eager-only: the flat layout itself is the
    dynamic-shape artifact."""
    v = _eager(x, "sequence_pad")
    ln = _eager(seq_len, "sequence_pad").astype(np.int64)
    B = len(ln)
    T = int(maxlen) if maxlen else int(ln.max() if B else 0)
    out = np.full((B, T) + v.shape[1:], pad_value, v.dtype)
    o = 0
    for b, n in enumerate(ln):
        n = int(n)
        out[b, :n] = v[o:o + n]
        o += n
    return Tensor(out), Tensor(ln)


def sequence_unpad(x, seq_len):
    """Padded [B,T,...] + lens -> flat [sum(len), ...]
    (sequence_unpad_op.cc).  Eager-only (ragged output)."""
    v = _eager(x, "sequence_unpad")
    ln = _eager(seq_len, "sequence_unpad").astype(np.int64)
    return Tensor(np.concatenate(
        [v[b, :int(n)] for b, n in enumerate(ln)], axis=0)
        if len(ln) else v[:0].reshape((0,) + v.shape[2:]))


def sequence_expand(x, x_len, ref_len):
    """Repeat each sequence by its reference count
    (sequence_expand_op.cc, ref_level=0): row-block b of x is tiled
    ref_len[b] times.  Eager-only (ragged output)."""
    v = _eager(x, "sequence_expand")
    xl = _eager(x_len, "sequence_expand").astype(np.int64)
    rl = _eager(ref_len, "sequence_expand").astype(np.int64)
    chunks, o = [], 0
    for n, r in zip(xl, rl):
        n = int(n)
        chunks.extend([v[o:o + n]] * int(r))
        o += n
    return Tensor(np.concatenate(chunks, axis=0) if chunks
                  else v[:0])


def sequence_expand_as(x, ref_len):
    """Row b of x repeated ref_len[b] times (sequence_expand_as_op.cc).
    Eager-only (ragged output)."""
    v = _eager(x, "sequence_expand_as")
    rl = _eager(ref_len, "sequence_expand_as").astype(np.int64)
    return Tensor(np.repeat(v, rl, axis=0))


def sequence_reshape(x, seq_len, new_dim):
    """Flat [sum, D] -> [sum*D/new_dim, new_dim]; lens scale by
    D/new_dim (sequence_reshape_op.cc).  Eager-only."""
    v = _eager(x, "sequence_reshape")
    ln = _eager(seq_len, "sequence_reshape").astype(np.int64)
    D = v.shape[-1]
    if (ln * D) .sum() % new_dim:
        raise ValueError("total elements not divisible by new_dim")
    new_len = ln * D // new_dim
    return Tensor(v.reshape(-1, new_dim)), Tensor(new_len)
