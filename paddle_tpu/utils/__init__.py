from . import chaos  # noqa: F401
from . import download  # noqa: F401
from . import image_util  # noqa: F401
from . import install_check  # noqa: F401
from . import op_version  # noqa: F401
from . import profiler  # noqa: F401
from ..framework import unique_name  # noqa: F401 — ref utils/__init__.py
from .deprecated import deprecated  # noqa: F401
from .download import get_weights_path_from_url  # noqa: F401
from .lazy_import import try_import  # noqa: F401
from .profiler import Profiler, ProfilerOptions, get_profiler  # noqa: F401

__all__ = ["deprecated", "download", "run_check", "unique_name",
           "load_op_library", "require_version", "try_import",
           "get_weights_path_from_url"]


def require_version(min_version, max_version=None):
    """Check the installed framework version against [min, max]
    (reference fluid/framework.py require_version)."""
    from ..version import full_version

    def parts(v):
        p = [int(x) for x in str(v).split("+")[0].split(".")[:3]]
        return p + [0] * (3 - len(p))  # zero-pad: '2.0' allows 2.0.x

    cur = parts(full_version)
    if parts(min_version) > cur:
        raise Exception(
            f"installed version {full_version} < required {min_version}")
    if max_version is not None and parts(max_version) < cur:
        raise Exception(
            f"installed version {full_version} > allowed {max_version}")
    return True


def load_op_library(lib_filename):
    """Custom C++ op loading is the reference's mechanism for user
    kernels; here custom kernels are Pallas/jax functions registered in
    python — nothing to dlopen."""
    import warnings
    warnings.warn(
        "load_op_library is a no-op on the TPU build: write custom ops as "
        "jax/Pallas functions (ops/pallas/) instead of C++ operator "
        "libraries", stacklevel=2)


from .install_check import run_check  # noqa: F401
from .op_version import OpLastCheckpointChecker  # noqa: F401
