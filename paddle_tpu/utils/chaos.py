"""Deterministic fault injection for the fault-tolerant runtime.

Every recovery path in `paddle_tpu.distributed.resilience` (preemption,
NaN anomaly policies, hung-step watchdog, checkpoint-IO retry) is
exercised by REAL tests through this layer rather than mocks: the
injectors fire at exact step numbers / call counts, so a chaos test is
bit-for-bit reproducible.

Two drive modes, composable:

  * env flags — set before the trainer process starts (the launcher /
    subprocess tests use these):
        PADDLE_CHAOS_CRASH_STEP=N     raise ChaosCrash at step N
        PADDLE_CHAOS_NAN_STEP=N[,M..] inject a NaN loss at steps N,M,…
        PADDLE_CHAOS_SLOW_STEP=N      stall step N
        PADDLE_CHAOS_SLOW_SECONDS=S   …for S seconds (default 30)
        PADDLE_CHAOS_PREEMPT_STEP=N   SIGTERM ourselves at step N
        PADDLE_CHAOS_FAIL_IO=K        next K chaos-guarded IO calls
                                      raise OSError
  * `inject(...)` context manager — in-process unit tests push a chaos
    config for the duration of a `with` block.

NaN/slow/crash/preempt step injections are ONE-SHOT: once fired at step
N they are consumed, so a `rollback` recovery that replays step N does
not re-trip the same fault (transient-corruption semantics — exactly
what the rollback policy exists to survive).

Runtime hook points (called by resilience.py / checkpoint.py):
    on_step(step)  -> bool   may raise/sleep/self-signal; True = poison
                             this step's loss with NaN
    on_io(label)             may raise OSError (decrements the budget)
"""
from __future__ import annotations

import contextlib
import logging
import os
import signal
import threading
import time

logger = logging.getLogger("paddle_tpu.chaos")

__all__ = ["ChaosCrash", "ChaosConfig", "inject", "on_step", "on_io",
           "active_config", "reset"]


class ChaosCrash(RuntimeError):
    """Raised by on_step() for crash-at-step-N injection.  Deliberately
    NOT caught by the resilient runner — it propagates and kills the
    trainer like any unhandled exception would."""


class ChaosConfig:
    """Mutable fault plan.  `fail_io` counts DOWN as faults fire."""

    def __init__(self, crash_at_step=None, nan_at_step=None, slow_step=None,
                 slow_seconds=30.0, preempt_at_step=None, fail_io=0,
                 io_error=None):
        self.crash_at_step = crash_at_step
        # accept a single step or an iterable of steps
        if nan_at_step is None:
            nan_at_step = ()
        elif isinstance(nan_at_step, int):
            nan_at_step = (nan_at_step,)
        self.nan_at_steps = set(nan_at_step)
        self.slow_step = slow_step
        self.slow_seconds = float(slow_seconds)
        self.preempt_at_step = preempt_at_step
        self.fail_io = int(fail_io)
        self.io_error = io_error or OSError(
            "chaos: injected transient IO failure")
        self.fired: list[str] = []  # audit trail for tests

    def is_noop(self):
        return (self.crash_at_step is None and not self.nan_at_steps
                and self.slow_step is None and self.preempt_at_step is None
                and self.fail_io <= 0)

    @classmethod
    def from_env(cls, environ=None):
        env = os.environ if environ is None else environ

        def _int(key):
            v = env.get(key)
            return int(v) if v not in (None, "") else None

        nan = env.get("PADDLE_CHAOS_NAN_STEP", "")
        nan_steps = tuple(int(s) for s in nan.split(",") if s.strip())
        return cls(
            crash_at_step=_int("PADDLE_CHAOS_CRASH_STEP"),
            nan_at_step=nan_steps,
            slow_step=_int("PADDLE_CHAOS_SLOW_STEP"),
            slow_seconds=float(env.get("PADDLE_CHAOS_SLOW_SECONDS", "30")),
            preempt_at_step=_int("PADDLE_CHAOS_PREEMPT_STEP"),
            fail_io=_int("PADDLE_CHAOS_FAIL_IO") or 0,
        )


# stack of active configs; index 0 is the env-derived base (parsed lazily
# so tests can mutate os.environ before first use)
_lock = threading.Lock()
_stack: list[ChaosConfig] = []


def _base() -> ChaosConfig:
    if not _stack:
        _stack.append(ChaosConfig.from_env())
    return _stack[0]


def active_config() -> ChaosConfig:
    """The innermost chaos config (env base if no inject() is active)."""
    with _lock:
        _base()
        return _stack[-1]


def reset():
    """Drop all state; the env base is re-parsed on next use."""
    with _lock:
        _stack.clear()


@contextlib.contextmanager
def inject(**kwargs):
    """Push a ChaosConfig for the dynamic extent of the block:

        with chaos.inject(nan_at_step=(3, 4), fail_io=1):
            run_resilient(...)
    """
    cfg = ChaosConfig(**kwargs)
    with _lock:
        _base()
        _stack.append(cfg)
    try:
        yield cfg
    finally:
        with _lock:
            if cfg in _stack:
                _stack.remove(cfg)


def on_step(step: int) -> bool:
    """Step-boundary hook.  May raise ChaosCrash, sleep, or SIGTERM the
    process; returns True when this step's loss should be poisoned with
    NaN.  All step triggers are one-shot (consumed on fire)."""
    cfg = active_config()
    if cfg.is_noop():
        return False
    if cfg.crash_at_step is not None and step == cfg.crash_at_step:
        cfg.crash_at_step = None
        cfg.fired.append(f"crash@{step}")
        logger.warning("chaos: crashing at step %d", step)
        raise ChaosCrash(f"chaos: injected crash at step {step}")
    if cfg.preempt_at_step is not None and step == cfg.preempt_at_step:
        cfg.preempt_at_step = None
        cfg.fired.append(f"preempt@{step}")
        logger.warning("chaos: SIGTERM self at step %d", step)
        os.kill(os.getpid(), signal.SIGTERM)
    if cfg.slow_step is not None and step == cfg.slow_step:
        cfg.slow_step = None
        cfg.fired.append(f"slow@{step}")
        logger.warning("chaos: stalling step %d for %.1fs", step,
                       cfg.slow_seconds)
        time.sleep(cfg.slow_seconds)
    if step in cfg.nan_at_steps:
        cfg.nan_at_steps.discard(step)
        cfg.fired.append(f"nan@{step}")
        logger.warning("chaos: poisoning step %d loss with NaN", step)
        return True
    return False


def on_io(label: str = "io"):
    """IO-call hook (checkpoint save/restore etc).  While the fail-IO
    budget is positive, each call decrements it and raises OSError."""
    cfg = active_config()
    if cfg.fail_io > 0:
        cfg.fail_io -= 1
        cfg.fired.append(f"io@{label}")
        logger.warning("chaos: failing IO call %r (%d more to fail)",
                       label, cfg.fail_io)
        raise type(cfg.io_error)(*cfg.io_error.args)
