"""Deterministic fault injection for the fault-tolerant runtime.

Every recovery path in `paddle_tpu.distributed.resilience` (preemption,
NaN anomaly policies, hung-step watchdog, checkpoint-IO retry) is
exercised by REAL tests through this layer rather than mocks: the
injectors fire at exact step numbers / call counts, so a chaos test is
bit-for-bit reproducible.

Two drive modes, composable:

  * env flags — set before the trainer process starts (the launcher /
    subprocess tests use these):
        PADDLE_CHAOS_CRASH_STEP=N     raise ChaosCrash at step N
        PADDLE_CHAOS_NAN_STEP=N[,M..] inject a NaN loss at steps N,M,…
        PADDLE_CHAOS_SLOW_STEP=N      stall step N
        PADDLE_CHAOS_SLOW_SECONDS=S   …for S seconds (default 30)
        PADDLE_CHAOS_PREEMPT_STEP=N   SIGTERM ourselves at step N
        PADDLE_CHAOS_FAIL_IO=K        next K chaos-guarded IO calls
                                      raise OSError
        PADDLE_CHAOS_CKPT_TORN=K      next K checkpoint commits die AFTER
                                      the generation dir is renamed into
                                      place but BEFORE the COMMIT marker
                                      (a SIGKILL mid-save, in-process)
        PADDLE_CHAOS_CKPT_BITFLIP=K   flip one bit in a payload file of
                                      the next K COMMITTED generations
                                      (silent at-rest corruption)
        PADDLE_CHAOS_CKPT_ENOSPC=K    next K checkpoint saves raise
                                      OSError(ENOSPC) — the persistent,
                                      non-retryable errno class
        PADDLE_CHAOS_CKPT_SLOW_IO=S   every checkpoint IO call stalls S
                                      seconds while active (async-save
                                      stall / overlap measurements)
        PADDLE_CHAOS_RANK_KILL=k@N    pod drill: rank k SIGKILLs itself
                                      at step N (no cleanup, no dump —
                                      the flightrec JSONL fallback is
                                      that rank's only ledger evidence)
        PADDLE_CHAOS_RANK_SLOW=k@N[:S]  rank k stalls step N for S
                                      seconds (default SLOW_SECONDS);
                                      unlike a partition it KEEPS
                                      heartbeating — the detector must
                                      not declare it dead
        PADDLE_CHAOS_RANK_PARTITION=k@N  rank k stops heartbeating from
                                      step N while continuing to run —
                                      the failure detector declares it
                                      dead and the supervisor fences it
        PADDLE_CHAOS_INIT_FLAKY=K     next K distributed-init dials raise
                                      ConnectionError (drives
                                      retry_with_backoff bring-up)
        PADDLE_CHAOS_REPLICA_KILL=k@N serving drill: replica rank k
                                      SIGKILLs itself at decode
                                      iteration N (mid-stream death —
                                      the router must fail inflight
                                      requests over to a survivor)
        PADDLE_CHAOS_REPLICA_SLOW=k@N[:S]  replica rank k stalls EVERY
                                      decode iteration from N onward for
                                      S seconds (default 0.25) — a sick-
                                      but-alive replica (hedging bait);
                                      persistent, unlike the one-shot
                                      step stalls
        PADDLE_CHAOS_REPLICA_PARTITION=k@N  replica rank k stops
                                      heartbeating to the fleet
                                      coordinator at iteration N while
                                      continuing to serve — the router's
                                      epoch subscription must evict it
                                      faster than the probe timeout
  * `inject(...)` context manager — in-process unit tests push a chaos
    config for the duration of a `with` block.

NaN/slow/crash/preempt step injections are ONE-SHOT: once fired at step
N they are consumed, so a `rollback` recovery that replays step N does
not re-trip the same fault (transient-corruption semantics — exactly
what the rollback policy exists to survive).

Runtime hook points (called by resilience.py / checkpoint.py):
    on_step(step)  -> bool   may raise/sleep/self-signal; True = poison
                             this step's loss with NaN
    on_io(label, path=None)  may raise OSError/ChaosTorn, stall, or (for
                             the bitflip injector, given a committed
                             generation `path`) corrupt a payload file
                             in place and return normally
"""
from __future__ import annotations

import contextlib
import logging
import os
import signal
import threading
import time

logger = logging.getLogger("paddle_tpu.chaos")

__all__ = ["ChaosCrash", "ChaosTorn", "ChaosConfig", "inject", "on_step",
           "on_io", "on_init", "active_config", "reset",
           "register_partition_hook", "pod_rank"]


class ChaosCrash(RuntimeError):
    """Raised by on_step() for crash-at-step-N injection.  Deliberately
    NOT caught by the resilient runner — it propagates and kills the
    trainer like any unhandled exception would."""


class ChaosTorn(RuntimeError):
    """Raised by on_io('checkpoint.commit') for torn-write injection:
    the save dies AFTER the generation directory landed on disk but
    BEFORE its COMMIT marker was written — the in-process equivalent of
    a SIGKILL between rename and marker.  Deliberately NOT an OSError:
    the save path's transient-IO retry must not catch it and re-commit
    the generation cleanly (that would erase the torn state the test —
    and reality — just produced)."""


class ChaosConfig:
    """Mutable fault plan.  `fail_io`/`ckpt_*` budgets count DOWN as
    faults fire (except `ckpt_slow_io`, a stall applied while active)."""

    def __init__(self, crash_at_step=None, nan_at_step=None, slow_step=None,
                 slow_seconds=30.0, preempt_at_step=None, fail_io=0,
                 io_error=None, ckpt_torn=0, ckpt_bitflip=0, ckpt_enospc=0,
                 ckpt_slow_io=0.0, rank_kill=None, rank_slow=None,
                 rank_partition=None, init_flaky=0, replica_kill=None,
                 replica_slow=None, replica_partition=None):
        self.crash_at_step = crash_at_step
        # accept a single step or an iterable of steps
        if nan_at_step is None:
            nan_at_step = ()
        elif isinstance(nan_at_step, int):
            nan_at_step = (nan_at_step,)
        self.nan_at_steps = set(nan_at_step)
        self.slow_step = slow_step
        self.slow_seconds = float(slow_seconds)
        self.preempt_at_step = preempt_at_step
        self.fail_io = int(fail_io)
        self.io_error = io_error or OSError(
            "chaos: injected transient IO failure")
        self.ckpt_torn = int(ckpt_torn)
        self.ckpt_bitflip = int(ckpt_bitflip)
        self.ckpt_enospc = int(ckpt_enospc)
        self.ckpt_slow_io = float(ckpt_slow_io)
        # pod drills: (rank, step[, seconds]) triggers, one-shot like the
        # other step injectors.  The rank is matched against THIS
        # process's pod rank at fire time (PADDLE_POD_RANK /
        # PADDLE_TRAINER_ID), so one env spec can be handed to every rank.
        self.rank_kill = rank_kill          # (rank, step)
        self.rank_slow = rank_slow          # (rank, step, seconds)
        self.rank_partition = rank_partition  # (rank, step)
        self.init_flaky = int(init_flaky)
        # serving-fleet drills: same (rank, step[, seconds]) triggers,
        # matched against PADDLE_POD_RANK at fire time.  kill/partition
        # are one-shot; replica_slow is PERSISTENT (a sick-but-alive
        # replica stays sick until the drill is reset)
        self.replica_kill = replica_kill          # (rank, step)
        self.replica_slow = replica_slow          # (rank, step, seconds)
        self.replica_partition = replica_partition  # (rank, step)
        self.fired: list[str] = []  # audit trail for tests

    def is_noop(self):
        return (self.crash_at_step is None and not self.nan_at_steps
                and self.slow_step is None and self.preempt_at_step is None
                and self.fail_io <= 0 and self.ckpt_torn <= 0
                and self.ckpt_bitflip <= 0 and self.ckpt_enospc <= 0
                and self.ckpt_slow_io <= 0 and self.rank_kill is None
                and self.rank_slow is None and self.rank_partition is None
                and self.init_flaky <= 0 and self.replica_kill is None
                and self.replica_slow is None
                and self.replica_partition is None)

    @classmethod
    def from_env(cls, environ=None):
        env = os.environ if environ is None else environ

        def _int(key):
            v = env.get(key)
            return int(v) if v not in (None, "") else None

        def _rank_at(key, with_seconds=False):
            """Parse 'rank@step' (optionally ':seconds') pod-drill specs."""
            v = env.get(key)
            if not v:
                return None
            secs = None
            if with_seconds and ":" in v:
                v, secs = v.rsplit(":", 1)
            rank, step = v.split("@", 1)
            out = (int(rank), int(step))
            if with_seconds:
                out += (float(secs) if secs is not None else None,)
            return out

        nan = env.get("PADDLE_CHAOS_NAN_STEP", "")
        nan_steps = tuple(int(s) for s in nan.split(",") if s.strip())
        return cls(
            crash_at_step=_int("PADDLE_CHAOS_CRASH_STEP"),
            nan_at_step=nan_steps,
            slow_step=_int("PADDLE_CHAOS_SLOW_STEP"),
            slow_seconds=float(env.get("PADDLE_CHAOS_SLOW_SECONDS", "30")),
            preempt_at_step=_int("PADDLE_CHAOS_PREEMPT_STEP"),
            fail_io=_int("PADDLE_CHAOS_FAIL_IO") or 0,
            ckpt_torn=_int("PADDLE_CHAOS_CKPT_TORN") or 0,
            ckpt_bitflip=_int("PADDLE_CHAOS_CKPT_BITFLIP") or 0,
            ckpt_enospc=_int("PADDLE_CHAOS_CKPT_ENOSPC") or 0,
            ckpt_slow_io=float(env.get("PADDLE_CHAOS_CKPT_SLOW_IO", "0")),
            rank_kill=_rank_at("PADDLE_CHAOS_RANK_KILL"),
            rank_slow=_rank_at("PADDLE_CHAOS_RANK_SLOW", with_seconds=True),
            rank_partition=_rank_at("PADDLE_CHAOS_RANK_PARTITION"),
            init_flaky=_int("PADDLE_CHAOS_INIT_FLAKY") or 0,
            replica_kill=_rank_at("PADDLE_CHAOS_REPLICA_KILL"),
            replica_slow=_rank_at("PADDLE_CHAOS_REPLICA_SLOW",
                                  with_seconds=True),
            replica_partition=_rank_at("PADDLE_CHAOS_REPLICA_PARTITION"),
        )


# stack of active configs; index 0 is the env-derived base (parsed lazily
# so tests can mutate os.environ before first use)
_lock = threading.Lock()
_stack: list[ChaosConfig] = []


def _base() -> ChaosConfig:
    if not _stack:
        _stack.append(ChaosConfig.from_env())
    return _stack[0]


def active_config() -> ChaosConfig:
    """The innermost chaos config (env base if no inject() is active)."""
    with _lock:
        _base()
        return _stack[-1]


def reset():
    """Drop all state; the env base is re-parsed on next use."""
    with _lock:
        _stack.clear()
        _partition_hooks.clear()


@contextlib.contextmanager
def inject(**kwargs):
    """Push a ChaosConfig for the dynamic extent of the block:

        with chaos.inject(nan_at_step=(3, 4), fail_io=1):
            run_resilient(...)
    """
    cfg = ChaosConfig(**kwargs)
    with _lock:
        _base()
        _stack.append(cfg)
    try:
        yield cfg
    finally:
        with _lock:
            if cfg in _stack:
                _stack.remove(cfg)


def pod_rank() -> int:
    """This process's pod rank for rank-targeted drills (elastic pod env
    first, classic trainer env second, 0 in single-process runs)."""
    return int(os.environ.get("PADDLE_POD_RANK",
                              os.environ.get("PADDLE_TRAINER_ID", "0")))


# callbacks a pod runtime registers so a RANK_PARTITION drill can silence
# its heartbeats without chaos importing the pod stack (layering: utils
# must not depend on distributed)
_partition_hooks: list = []


def register_partition_hook(fn):
    """Register fn() to run when a RANK_PARTITION drill fires on this
    rank (the elastic runtime uses it to stop heartbeating).  Hooks are
    cleared by reset()."""
    with _lock:
        _partition_hooks.append(fn)


def _fire_partition():
    with _lock:
        hooks = list(_partition_hooks)
    for fn in hooks:
        try:
            fn()
        except Exception:  # noqa: BLE001 - a drill must not crash the rank
            logger.exception("chaos: partition hook failed")


def on_step(step: int) -> bool:
    """Step-boundary hook.  May raise ChaosCrash, sleep, or SIGTERM the
    process; returns True when this step's loss should be poisoned with
    NaN.  All step triggers are one-shot (consumed on fire)."""
    cfg = active_config()
    if cfg.is_noop():
        return False
    if cfg.crash_at_step is not None and step == cfg.crash_at_step:
        cfg.crash_at_step = None
        cfg.fired.append(f"crash@{step}")
        logger.warning("chaos: crashing at step %d", step)
        raise ChaosCrash(f"chaos: injected crash at step {step}")
    if cfg.preempt_at_step is not None and step == cfg.preempt_at_step:
        cfg.preempt_at_step = None
        cfg.fired.append(f"preempt@{step}")
        logger.warning("chaos: SIGTERM self at step %d", step)
        os.kill(os.getpid(), signal.SIGTERM)
    if cfg.rank_kill is not None and step == cfg.rank_kill[1] \
            and pod_rank() == cfg.rank_kill[0]:
        cfg.rank_kill = None
        cfg.fired.append(f"rank_kill@{step}")
        logger.warning("chaos: SIGKILL self (pod rank %d) at step %d",
                       pod_rank(), step)
        os.kill(os.getpid(), signal.SIGKILL)
    if cfg.rank_partition is not None and step >= cfg.rank_partition[1] \
            and pod_rank() == cfg.rank_partition[0]:
        cfg.rank_partition = None
        cfg.fired.append(f"rank_partition@{step}")
        logger.warning("chaos: partitioning pod rank %d from step %d "
                       "(heartbeats stop; the rank keeps running)",
                       pod_rank(), step)
        _fire_partition()
    if cfg.rank_slow is not None and step == cfg.rank_slow[1] \
            and pod_rank() == cfg.rank_slow[0]:
        _, _, secs = cfg.rank_slow
        cfg.rank_slow = None
        cfg.fired.append(f"rank_slow@{step}")
        secs = cfg.slow_seconds if secs is None else secs
        logger.warning("chaos: stalling pod rank %d at step %d for %.1fs",
                       pod_rank(), step, secs)
        time.sleep(secs)
    if cfg.replica_kill is not None and step >= cfg.replica_kill[1] \
            and pod_rank() == cfg.replica_kill[0]:
        cfg.replica_kill = None
        cfg.fired.append(f"replica_kill@{step}")
        logger.warning("chaos: SIGKILL self (replica rank %d) at decode "
                       "iteration %d", pod_rank(), step)
        os.kill(os.getpid(), signal.SIGKILL)
    if cfg.replica_partition is not None \
            and step >= cfg.replica_partition[1] \
            and pod_rank() == cfg.replica_partition[0]:
        cfg.replica_partition = None
        cfg.fired.append(f"replica_partition@{step}")
        logger.warning("chaos: partitioning replica rank %d from decode "
                       "iteration %d (coordinator heartbeats stop; the "
                       "replica keeps serving)", pod_rank(), step)
        _fire_partition()
    if cfg.replica_slow is not None and step >= cfg.replica_slow[1] \
            and pod_rank() == cfg.replica_slow[0]:
        _, at, secs = cfg.replica_slow
        secs = 0.25 if secs is None else secs
        if not any(f.startswith("replica_slow@") for f in cfg.fired):
            cfg.fired.append(f"replica_slow@{step}")
            logger.warning("chaos: replica rank %d slow from iteration %d "
                           "(%.2fs per decode iteration, persistent)",
                           pod_rank(), at, secs)
        time.sleep(secs)  # NOT consumed: a sick replica stays sick
    if cfg.slow_step is not None and step == cfg.slow_step:
        cfg.slow_step = None
        cfg.fired.append(f"slow@{step}")
        logger.warning("chaos: stalling step %d for %.1fs", step,
                       cfg.slow_seconds)
        time.sleep(cfg.slow_seconds)
    if step in cfg.nan_at_steps:
        cfg.nan_at_steps.discard(step)
        cfg.fired.append(f"nan@{step}")
        logger.warning("chaos: poisoning step %d loss with NaN", step)
        return True
    return False


def on_init(label: str = "distributed.init"):
    """Bring-up hook: while the init-flaky budget is positive each dial
    attempt decrements it and raises ConnectionError — the transient
    class retry_with_backoff retries — BEFORE the real initialize runs,
    modelling a coordinator that comes up later than its pod."""
    cfg = active_config()
    if cfg.init_flaky > 0:
        cfg.init_flaky -= 1
        cfg.fired.append(f"init_flaky@{label}")
        logger.warning("chaos: failing init dial %r (%d more to fail)",
                       label, cfg.init_flaky)
        raise ConnectionError(
            f"chaos: injected flaky init dial ({label})")


def _flip_one_bit(gen_dir: str):
    """Deterministic at-rest corruption: XOR one bit in the middle of
    the first payload file (sorted order) of a committed generation."""
    leaves_dir = os.path.join(gen_dir, "leaves")
    root = leaves_dir if os.path.isdir(leaves_dir) else gen_dir
    files = sorted(
        f for f in os.listdir(root)
        if os.path.isfile(os.path.join(root, f)) and f != "COMMIT")
    if not files:
        return None
    target = os.path.join(root, files[0])
    size = os.path.getsize(target)
    if size == 0:
        return None
    offset = size // 2
    with open(target, "r+b") as f:
        f.seek(offset)
        byte = f.read(1)
        f.seek(offset)
        f.write(bytes([byte[0] ^ 0x01]))
    return target


def on_io(label: str = "io", path: str = None):
    """IO-call hook (checkpoint save/restore etc).

    While the fail-IO budget is positive, each call decrements it and
    raises OSError.  Checkpoint-specific injectors key on the label the
    durable save protocol passes:

      * ``checkpoint.save``      — ENOSPC budget raises the persistent
        errno (never retried by the errno-split save path); slow-IO
        stalls here too.
      * ``checkpoint.commit``    — torn budget raises ChaosTorn after
        the generation dir is in place but before its COMMIT marker.
      * ``checkpoint.committed`` — bitflip budget corrupts one bit of a
        payload file under `path` and returns normally (the save looks
        like it succeeded — only the manifest crc can tell).
    """
    cfg = active_config()
    is_ckpt = label.startswith("checkpoint")
    if is_ckpt and cfg.ckpt_slow_io > 0:
        logger.warning("chaos: stalling IO call %r for %.2fs", label,
                       cfg.ckpt_slow_io)
        time.sleep(cfg.ckpt_slow_io)
    if label == "checkpoint.commit" and cfg.ckpt_torn > 0:
        cfg.ckpt_torn -= 1
        cfg.fired.append(f"torn@{label}")
        logger.warning("chaos: tearing checkpoint commit (%d more)",
                       cfg.ckpt_torn)
        raise ChaosTorn("chaos: injected torn write — generation left "
                        "on disk without its COMMIT marker")
    if label == "checkpoint.committed" and cfg.ckpt_bitflip > 0 and path:
        cfg.ckpt_bitflip -= 1
        flipped = _flip_one_bit(path)
        cfg.fired.append(f"bitflip@{flipped or path}")
        logger.warning("chaos: flipped one bit in %s (%d more)", flipped,
                       cfg.ckpt_bitflip)
        return
    if label == "checkpoint.save" and cfg.ckpt_enospc > 0:
        import errno as _errno

        cfg.ckpt_enospc -= 1
        cfg.fired.append(f"enospc@{label}")
        logger.warning("chaos: injecting ENOSPC on %r (%d more)", label,
                       cfg.ckpt_enospc)
        raise OSError(_errno.ENOSPC,
                      "chaos: injected ENOSPC (persistent IO failure)")
    if cfg.fail_io > 0:
        cfg.fail_io -= 1
        cfg.fired.append(f"io@{label}")
        logger.warning("chaos: failing IO call %r (%d more to fail)",
                       label, cfg.fail_io)
        raise type(cfg.io_error)(*cfg.io_error.args)
