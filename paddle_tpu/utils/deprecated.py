"""paddle.utils.deprecated — deprecation-warning decorator.

Reference parity: python/paddle/utils/deprecated.py (appends a
Deprecated note to the docstring and warns once per call site).
"""
from __future__ import annotations

import functools
import warnings

__all__ = ["deprecated"]


def deprecated(update_to="", since="", reason=""):
    def decorator(func):
        msg = f"API {func.__module__}.{func.__name__} is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use {update_to} instead"
        if reason:
            msg += f"; reason: {reason}"
        note = f"\n\n    .. warning:: {msg}\n"
        func.__doc__ = (func.__doc__ or "") + note

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        return wrapper

    return decorator
