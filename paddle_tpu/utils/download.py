"""`paddle.utils.download` — cached artifact fetching.

Reference parity: python/paddle/utils/download.py
(get_weights_path_from_url:112, get_path_from_url:158).  Local-cache
aware: a file already present under WEIGHTS_HOME (or DATA_HOME) —
including one pre-seeded by the operator in an egress-less environment —
is used without any network touch; only a cache miss attempts a
download, and a clear error names the cache path to seed on failure.
"""
from __future__ import annotations

import hashlib
import os
import os.path as osp
import shutil
import tarfile
import zipfile

__all__ = ["get_weights_path_from_url"]

WEIGHTS_HOME = osp.expanduser("~/.cache/paddle/hapi/weights")
DATA_HOME = osp.expanduser("~/.cache/paddle/dataset")


def is_url(path):
    return isinstance(path, str) and path.startswith(("http://", "https://"))


def _md5check(fullname, md5sum=None):
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def _download(url, path, md5sum=None):
    os.makedirs(path, exist_ok=True)
    fname = osp.split(url)[-1]
    fullname = osp.join(path, fname)
    if osp.exists(fullname) and _md5check(fullname, md5sum):
        return fullname
    import urllib.request
    try:
        tmp = fullname + ".tmp"
        with urllib.request.urlopen(url, timeout=60) as r, \
                open(tmp, "wb") as f:
            shutil.copyfileobj(r, f)
        if not _md5check(tmp, md5sum):
            os.remove(tmp)
            raise IOError(f"md5 mismatch downloading {url}")
        os.replace(tmp, fullname)
        return fullname
    except Exception as e:
        raise RuntimeError(
            f"Could not download {url} ({e}). In an offline environment, "
            f"place the file at {fullname} to use the local cache.") from e


def _decompress(fname):
    d = osp.dirname(fname)
    if tarfile.is_tarfile(fname):
        with tarfile.open(fname) as tf:
            tf.extractall(d, filter="data")
            names = tf.getnames()
        return osp.join(d, names[0].split("/")[0]) if names else d
    if zipfile.is_zipfile(fname):
        with zipfile.ZipFile(fname) as zf:
            zf.extractall(d)
            names = zf.namelist()
        return osp.join(d, names[0].split("/")[0]) if names else d
    return fname


def get_path_from_url(url, root_dir, md5sum=None, check_exist=True):
    """Cached fetch: return the local path for `url` under `root_dir`,
    downloading (and un-tar/zipping) only on cache miss."""
    fname = osp.split(url)[-1]
    fullname = osp.join(root_dir, fname)
    if check_exist and osp.exists(fullname) and _md5check(fullname, md5sum):
        fullpath = fullname
    else:
        fullpath = _download(url, root_dir, md5sum)
    if tarfile.is_tarfile(fullpath) or zipfile.is_zipfile(fullpath):
        return _decompress(fullpath)
    return fullpath


def get_weights_path_from_url(url, md5sum=None):
    """Local weights-cache path for `url` (downloads on miss)."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
