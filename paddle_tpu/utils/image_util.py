"""paddle.utils.image_util — classic image preprocessing helpers.

Reference: python/paddle/utils/image_util.py (resize_image, flip,
crop_img, preprocess_img, oversample, ImageTransformer — the pre-
paddle.vision transform toolkit used by the old image-classification
demos).  Re-implemented over numpy (PIL only for load/decode, optional):
same function surface, channel conventions preserved (flattened CHW
float vectors in, like the original), no direct code reuse.
"""
from __future__ import annotations

import io

import numpy as np

__all__ = [
    "resize_image", "flip", "crop_img", "decode_jpeg", "preprocess_img",
    "load_meta", "load_image", "oversample", "ImageTransformer",
]


def _to_hwc(im, color):
    """The classic helpers carry images as flattened CHW float vectors
    of a SQUARE image (the reference's feeding format); accept that form
    or an H,W[,C] array."""
    im = np.asarray(im)
    if im.ndim == 1:
        c = 3 if color else 1
        side = int(round((im.size / c) ** 0.5))
        if c * side * side != im.size:
            raise ValueError(
                f"flattened image of size {im.size} is not a square "
                f"{'RGB' if color else 'gray'} CHW vector")
        im = im.reshape(c, side, side).transpose(1, 2, 0)
        if c == 1:
            im = im[:, :, 0]
    return im


def resize_image(img, target_size):
    """Resize so the SHORT side equals target_size (reference
    image_util.py:20 keeps aspect ratio) — nearest-neighbor, numpy-only."""
    im = _to_hwc(img, True)
    h, w = im.shape[:2]
    if h < w:
        nh, nw = target_size, max(int(round(w * target_size / h)), 1)
    else:
        nh, nw = max(int(round(h * target_size / w)), 1), target_size
    ys = np.minimum((np.arange(nh) * h / nh).astype(np.int64), h - 1)
    xs = np.minimum((np.arange(nw) * w / nw).astype(np.int64), w - 1)
    return im[ys][:, xs]


def flip(im):
    """Horizontal mirror (reference :33 flips the width axis)."""
    im = _to_hwc(im, True)
    return im[:, ::-1].copy()


def crop_img(im, inner_size, color=True, test=True):
    """Center crop when test else random crop + random mirror
    (reference :45)."""
    im = _to_hwc(im, color)
    h, w = im.shape[:2]
    ih = iw = inner_size
    if h < ih or w < iw:
        raise ValueError(f"image {h}x{w} smaller than crop {inner_size}")
    if test:
        top, left = (h - ih) // 2, (w - iw) // 2
        out = im[top:top + ih, left:left + iw]
    else:
        rng = _rng()
        top = int(rng.randint(0, max(h - ih, 0) + 1))
        left = int(rng.randint(0, max(w - iw, 0) + 1))
        out = im[top:top + ih, left:left + iw]
        if rng.rand() < 0.5:
            out = out[:, ::-1]
    return out.copy()


def _rng():
    from ..framework.random import np_random_state

    return np_random_state()


def decode_jpeg(jpeg_string):
    """Decode an encoded image buffer to an H,W,C uint8 array
    (reference :89; PIL-backed, gated)."""
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover - PIL is in the image
        raise RuntimeError("decode_jpeg needs Pillow") from e
    return np.asarray(Image.open(io.BytesIO(jpeg_string)).convert("RGB"))


def preprocess_img(im, img_mean, crop_size, is_train, color=True):
    """Crop (+train-time mirror), subtract mean, return flattened float32
    CHW vector — the reference's feeding format (:96)."""
    out = crop_img(im, crop_size, color=color, test=not is_train)
    out = out.astype(np.float32)
    if out.ndim == 2:
        out = out[:, :, None]
    chw = np.transpose(out, (2, 0, 1)).reshape(-1)
    mean = np.asarray(img_mean, np.float32).reshape(-1)
    if mean.size == chw.size:
        chw = chw - mean
    elif mean.size == out.shape[2]:  # per-channel mean
        chw = chw - np.repeat(mean, out.shape[0] * out.shape[1])
    else:
        raise ValueError(
            f"img_mean size {mean.size} matches neither the flattened "
            f"crop ({chw.size}) nor the channel count ({out.shape[2]}) "
            f"— was the mean built for a different crop_size?")
    return chw


def load_meta(meta_path, mean_img_size, crop_size, color=True):
    """Load a pickled mean image and center-crop it to crop_size
    (reference :111)."""
    import pickle

    with open(meta_path, "rb") as f:
        mean = pickle.load(f, encoding="latin1")
    c = 3 if color else 1
    mean = np.asarray(mean).reshape(c, mean_img_size, mean_img_size)
    off = (mean_img_size - crop_size) // 2
    mean = mean[:, off:off + crop_size, off:off + crop_size]
    return mean.astype(np.float32).reshape(-1)


def load_image(img_path, is_color=True):
    """Read an image file to H,W,C uint8 (reference :133; PIL-backed)."""
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("load_image needs Pillow") from e
    img = Image.open(img_path)
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def oversample(img, crop_dims):
    """10-crop oversampling (reference :144): 4 corners + center, plus
    mirrors, for a batch of H,W,C images."""
    imgs = np.asarray(img)
    if imgs.ndim == 3:
        imgs = imgs[None]
    n, h, w = imgs.shape[:3]
    ch, cw = int(crop_dims[0]), int(crop_dims[1])
    if h < ch or w < cw:
        raise ValueError(f"image {h}x{w} smaller than crop {crop_dims}")
    tops = [0, 0, h - ch, h - ch, (h - ch) // 2]
    lefts = [0, w - cw, 0, w - cw, (w - cw) // 2]
    crops = []
    for im in imgs:
        views = [im[t:t + ch, le:le + cw] for t, le in zip(tops, lefts)]
        crops.extend(views)
        crops.extend(v[:, ::-1] for v in views)
    return np.stack(crops)


class ImageTransformer:
    """Channel-order/mean/scale pipeline (reference :183): configure
    once, call transform(im) to get the feeding array."""

    def __init__(self, transpose=None, channel_swap=None, mean=None,
                 is_color=True):
        self.transpose_order = transpose
        self.channel_swap = channel_swap
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        self.is_color = is_color

    def set_transpose(self, order):
        self.transpose_order = order

    def set_channel_swap(self, order):
        self.channel_swap = order

    def set_mean(self, mean):
        self.mean = None if mean is None else np.asarray(mean, np.float32)

    def transformer(self, im):  # reference method name
        return self.transform(im)

    def transform(self, im):
        out = np.asarray(im, np.float32)
        if out.ndim == 2:
            out = out[:, :, None]
        if self.channel_swap is not None:
            out = out[:, :, list(self.channel_swap)]
        if self.transpose_order is not None:
            out = np.transpose(out, self.transpose_order)
        if self.mean is not None:
            m = self.mean
            if m.ndim == 1 and out.ndim == 3 and m.size == out.shape[0]:
                m = m.reshape(-1, 1, 1)
            out = out - m
        return out
