"""paddle.utils.run_check — install sanity check.

Reference: python/paddle/utils/install_check.py:134 run_check() builds a
tiny linear model and runs it single-device, then data-parallel across
all visible devices, printing an "installed successfully" verdict.  The
TPU-native equivalent checks the same three tiers: eager forward+
backward, one jitted train step, and (when more than one device is
visible) the same step dp-sharded over a mesh.
"""
from __future__ import annotations

__all__ = ["run_check"]


def _simple_step():
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    net = paddle.nn.Linear(16, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(8, 16).astype(np.float32))
    y = paddle.to_tensor(np.arange(8, dtype=np.int64) % 4)
    loss = F.cross_entropy(net(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss), net


def _parallel_step(net):
    import jax
    import numpy as np

    from ..distributed.mesh import build_mesh, mesh_guard
    from ..nn.layer_base import functional_call, state_pytrees

    devices = jax.devices()
    if len(devices) < 2:
        return None
    params, buffers = state_pytrees(net)

    def loss_fn(p, xs, ys):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F

        out, _ = functional_call(net, p, (paddle.to_tensor(xs),),
                                 buffers=buffers, mutable=False)
        return F.cross_entropy(out, paddle.to_tensor(ys)).value

    mesh = build_mesh({"dp": len(devices)})
    with mesh_guard(mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        n = len(devices) * 4
        xs = np.random.RandomState(1).randn(n, 16).astype(np.float32)
        ys = (np.arange(n) % 4).astype(np.int64)
        sharded = jax.jit(
            jax.value_and_grad(loss_fn),
            in_shardings=(None, NamedSharding(mesh, P("dp")),
                          NamedSharding(mesh, P("dp"))))
        loss, _ = sharded(params, xs, ys)
    return float(loss)


def run_check():
    """Verify the install end-to-end; raises on failure, prints the
    reference's success message shape otherwise."""
    import jax

    devs = jax.devices()
    # run_check() mirrors the reference's stdout success messages
    print(f"Running verify PaddlePaddle(paddle_tpu) "  # noqa: PTA006
          f"program ... device: {devs[0].platform} x{len(devs)}")
    loss, net = _simple_step()
    ploss = _parallel_step(net)
    if ploss is not None:
        print(f"PaddlePaddle(paddle_tpu) works well "  # noqa: PTA006
              f"on {len(devs)} devices (dp loss {ploss:.4f}).")
    print("PaddlePaddle(paddle_tpu) is installed "  # noqa: PTA006
          "successfully! Let's start deep learning with "
          "paddle_tpu now.")
    return True
