"""Shared runtime metrics registry: counters, gauges, histograms,
reservoir quantiles, Prometheus text exposition.

Reference parity: paddle/fluid/platform/monitor.* (the StatRegistry that
backed Fluid's runtime counters) generalized for every subsystem here —
serving (paddle_tpu.serving.metrics builds its exposition on these
types), training telemetry (paddle_tpu.monitor), checkpoint durability
(distributed/checkpoint.py), and the launcher's restart accounting.

Dependency-free by design (no prometheus_client): the exposition format
is a few lines of text
(https://prometheus.io/docs/instrumenting/exposition_formats/) and the
framework needs exactly counters, gauges, histograms, and order-statistic
quantiles.  Every metric registered in a `MetricsRegistry` shares ONE
lock — recording threads (training loop, checkpoint writer, batcher, HTTP
handlers) and the /metrics scraper all touch the same state, and a single
RLock keeps the exposition a consistent snapshot without per-metric lock
ordering.

None of the record/render paths touch jax: incrementing a counter from
the checkpoint writer thread (which must stay jax-free — see
distributed/checkpoint.py) is pure-python dict work under the lock.

Quantiles come from a bounded reservoir of recent observations rather
than histogram interpolation, so a scraped `*_p99_ms` reads an exact
order statistic over the last window instead of a bucket-boundary
estimate.
"""
from __future__ import annotations

import bisect
import collections
import threading
import time

__all__ = ["Counter", "Gauge", "Histogram", "Reservoir", "MetricsRegistry",
           "default_registry"]


def _fmt(v) -> str:
    """Value formatting for exposition lines: ints verbatim (counters,
    counts), floats through %g (gauges, sums) — matching what the
    pre-registry serving exposition emitted byte-for-byte."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    return format(float(v), "g")


class Counter:
    """Monotonic counter; optionally labeled.

    `label=` is ONE label key (a str) or a TUPLE of label keys — with a
    tuple, `inc()` takes a matching tuple of label values and each
    series renders as `name{k1="v1",k2="v2"}` (the
    `paddle_pallas_fallbacks_total{kernel,reason}` shape).  Values are
    tracked per label value (a `collections.Counter`); `preset=`
    pre-creates entries so zero-valued series still render, in
    declaration order.  `fixed=True` restricts the exposition to exactly
    the preset series (extra recorded names stay readable
    programmatically but are not rendered) — the serving exposition
    contract.
    """

    kind = "counter"

    def __init__(self, name: str, help_: str, lock, label=None,
                 preset=(), fixed: bool = False):
        self.name = name
        self.help = help_
        self._lock = lock
        self.label = label
        self.fixed = fixed
        self.values = collections.Counter()
        self._order = []
        for key in preset:
            self.values[key] = 0
            self._order.append(key)
        self._preset_len = len(self._order)
        self.value = 0  # unlabeled total

    def inc(self, arg=1, n: int = None):
        """Unlabeled: `inc()` / `inc(3)`.  Labeled: `inc("reason")` /
        `inc("reason", 3)`.  A float labeled increment stays a float
        (seconds-style counters, e.g. the goodput ledger's badput
        accounting); integral increments keep rendering as ints."""
        with self._lock:
            if self.label is None:
                self.value += int(arg)
                return
            if isinstance(self.label, tuple):
                key = tuple(str(a) for a in arg)
            else:
                key = str(arg)
            if key not in self.values:
                self._order.append(key)
            self.values[key] += 1 if n is None else \
                (float(n) if isinstance(n, float) else int(n))

    def get(self, key=None) -> int:
        with self._lock:
            return self.value if key is None else self.values[key]

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} counter"]
        if self.label is None:
            lines.append(f"{self.name} {_fmt(self.value)}")
            return lines
        keys = self._order[:self._preset_len] if self.fixed else self._order
        for key in keys:
            if isinstance(self.label, tuple):
                lbl = ",".join(f'{k}="{v}"'
                               for k, v in zip(self.label, key))
            else:
                lbl = f'{self.label}="{key}"'
            lines.append(f'{self.name}{{{lbl}}} {_fmt(self.values[key])}')
        return lines


class Gauge:
    """Instantaneous value; either `set()` explicitly or computed at
    scrape time via `fn` (called with the registry lock held — keep it
    lock-free or reentrant)."""

    kind = "gauge"

    def __init__(self, name: str, help_: str, lock, fn=None):
        self.name = name
        self.help = help_
        self._lock = lock
        self.fn = fn
        self.value = 0

    def set(self, v):
        with self._lock:
            self.value = v

    def add(self, v):
        with self._lock:
            self.value += v

    def get(self):
        with self._lock:
            return self.fn() if self.fn is not None else self.value

    def render(self) -> list[str]:
        v = self.fn() if self.fn is not None else self.value
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} gauge",
                f"{self.name} {_fmt(v)}"]


class Histogram:
    """Cumulative-bucket histogram (Prometheus `histogram` type)."""

    kind = "histogram"

    def __init__(self, name: str, help_: str, buckets, lock=None):
        self.name = name
        self.help = help_
        self._lock = lock or threading.RLock()
        self.uppers = sorted(float(b) for b in buckets)
        self.counts = [0] * len(self.uppers)  # per-bucket (non-cumulative)
        self.total = 0
        self.sum = 0.0

    def observe(self, value: float):
        with self._lock:
            self._observe_locked(value)

    def _observe_locked(self, value: float):
        self.total += 1
        self.sum += value
        i = bisect.bisect_left(self.uppers, value)
        if i < len(self.counts):
            self.counts[i] += 1

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        cum = 0
        for upper, c in zip(self.uppers, self.counts):
            cum += c
            le = f"{upper:g}"
            lines.append(f'{self.name}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {self.total}')
        lines.append(f"{self.name}_sum {self.sum:g}")
        lines.append(f"{self.name}_count {self.total}")
        return lines


class Reservoir:
    """Bounded window of recent observations for exact order-statistic
    quantiles.  Not itself rendered — pair it with computed `Gauge`s
    (`fn=lambda: res.quantile(0.99)`).

    Bounded by COUNT (the last `size` observations, the default) and
    optionally by TIME: with `window_s` set, observations older than the
    window are evicted before every quantile, so a scraped p99 after a
    traffic lull describes recent behavior instead of stale history.
    `window_s=None` keeps the lifetime-cumulative default."""

    def __init__(self, size: int = 4096, lock=None,
                 window_s: float = None):
        self._lock = lock or threading.RLock()
        self.values = collections.deque(maxlen=size)
        self.window_s = float(window_s) if window_s else None
        self._stamps = collections.deque(maxlen=size) \
            if self.window_s else None

    def observe(self, v: float):
        with self._lock:
            self.values.append(float(v))
            if self._stamps is not None:
                self._stamps.append(time.monotonic())

    def _evict_locked(self):
        # values/_stamps share maxlen and are appended in lockstep, so
        # ring overflow drops the same (oldest) entries from both
        cutoff = time.monotonic() - self.window_s
        while self._stamps and self._stamps[0] < cutoff:
            self._stamps.popleft()
            self.values.popleft()

    def __len__(self):
        return len(self.values)

    def quantile(self, q: float) -> float:
        with self._lock:
            return self.quantile_locked(q)

    def quantile_locked(self, q: float) -> float:
        if self._stamps is not None:
            self._evict_locked()
        if not self.values:
            return 0.0
        xs = sorted(self.values)
        idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
        return xs[idx]


class MetricsRegistry:
    """Ordered collection of metrics sharing one RLock, rendered as one
    Prometheus text document in registration order.

    `counter`/`gauge`/`histogram`/`reservoir` are get-or-create: a second
    registration of the same name returns the existing metric (so a
    second `Model.fit` in the same process reuses the gauges instead of
    colliding)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: dict[str, object] = {}
        self._reservoirs: dict[str, Reservoir] = {}

    # -- registration (get-or-create) --------------------------------------
    def _existing(self, name: str, kind: str):
        """Get-or-create guard: a second registration of `name` must ask
        for the SAME kind — `counter("x")` after `gauge("x")` would hand
        back a Gauge and fail later at `.inc()`, far from the typo.
        (PTA007 catches the static cases; this is the runtime
        complement for dynamically-built names.)"""
        m = self._metrics.get(name)
        if m is not None and m.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}, "
                f"re-requested as {kind}")
        return m

    def counter(self, name: str, help_: str = "", label=None,
                preset=(), fixed: bool = False) -> Counter:
        with self._lock:
            m = self._existing(name, "counter")
            if m is None:
                m = Counter(name, help_, self._lock, label=label,
                            preset=preset, fixed=fixed)
                self._metrics[name] = m
            return m

    def gauge(self, name: str, help_: str = "", fn=None) -> Gauge:
        with self._lock:
            m = self._existing(name, "gauge")
            if m is None:
                m = Gauge(name, help_, self._lock, fn=fn)
                self._metrics[name] = m
            elif fn is not None:
                m.fn = fn
            return m

    def histogram(self, name: str, help_: str = "", buckets=(1, 10, 100)) \
            -> Histogram:
        with self._lock:
            m = self._existing(name, "histogram")
            if m is None:
                m = Histogram(name, help_, buckets, lock=self._lock)
                self._metrics[name] = m
            return m

    def reservoir(self, name: str, size: int = 4096,
                  window_s: float = None) -> Reservoir:
        """Unrendered observation window (see Reservoir); keyed separately
        from rendered metrics.  `window_s=None` defers to
        `FLAGS_metrics_window_s` (0 = lifetime-cumulative, the
        default)."""
        with self._lock:
            r = self._reservoirs.get(name)
            if r is None:
                if window_s is None:
                    try:  # lazy: utils.metrics stays importable standalone
                        from ..framework import flags as _flags
                        window_s = float(
                            _flags.flag("FLAGS_metrics_window_s", 0.0)
                            or 0.0)
                    except Exception:  # noqa: BLE001
                        window_s = 0.0
                r = Reservoir(size, lock=self._lock,
                              window_s=window_s or None)
                self._reservoirs[name] = r
            return r

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    # -- exposition --------------------------------------------------------
    def prometheus_text(self) -> str:
        with self._lock:
            lines = []
            for m in self._metrics.values():
                lines.extend(m.render())
            return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Programmatic view: {name: value | {label: value} | {hist
        summary}} for bench fields and tests."""
        with self._lock:
            out = {}
            for name, m in self._metrics.items():
                if m.kind == "counter":
                    if m.label is None:
                        out[name] = m.value
                    else:
                        # tuple-labeled series join their label values so
                        # the snapshot stays JSON-serializable
                        out[name] = {
                            (",".join(k) if isinstance(k, tuple) else k): v
                            for k, v in m.values.items()}
                elif m.kind == "gauge":
                    out[name] = m.fn() if m.fn is not None else m.value
                else:
                    out[name] = {"count": m.total, "sum": m.sum,
                                 "mean": (m.sum / m.total) if m.total
                                 else 0.0}
            return out


_default_registry = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-wide registry that training telemetry
    (paddle_tpu.monitor), checkpoint durability counters
    (distributed/checkpoint.py), the NaN-policy counters
    (distributed/resilience.py), and the launcher all share — one
    /metrics endpoint describes the whole job.  Serving keeps its own
    per-engine registry (ServingMetrics) so multiple engines in one
    process don't collide."""
    global _default_registry
    if _default_registry is None:
        with _default_lock:
            if _default_registry is None:
                _default_registry = MetricsRegistry()
    return _default_registry
