"""paddle.utils.op_version — op version checkpoint queries.

Reference: python/paddle/utils/op_version.py OpLastCheckpointChecker
reads the C++ OpVersionRegistry (op upgrade checkpoints registered by
REGISTER_OP_VERSION).  This build has no versioned C++ op registry — op
semantics are pinned by COVERAGE.md and the test suite — so the checker
serves the same query API over a static table of the ops whose observable
behavior DIFFERS from some historical reference version (the cases a
version-gated converter would care about).
"""
from __future__ import annotations

__all__ = ["OpLastCheckpointChecker"]

# op -> (version id, note).  Version 0 == never upgraded / original
# semantics.  Entries mirror upgrade checkpoints the reference registers
# that are visible in this build's op surface.
_CHECKPOINTS = {
    # reference REGISTER_OP_VERSION entries with behavior-visible bumps
    "roi_align": (1, "aligned=True pixel-offset convention supported"),
    "generate_proposals": (1, "pixel_offset attribute"),
    "grid_sampler": (1, "align_corners/padding_mode attributes"),
    "momentum": (1, "multi_precision / rescale_grad attributes"),
    "adam": (1, "multi_precision master weights (amp O2)"),
    "leaky_relu": (1, "alpha default 0.01 (was 0.02 pre-2.0)"),
    "gaussian_random": (1, "shape tensor input form"),
    "unique": (1, "return_index/inverse/counts form"),
}


class _Singleton:
    _inst = None

    def __new__(cls, *a, **k):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst


class OpLastCheckpointChecker(_Singleton):
    """Query the last upgrade checkpoint of an op (reference
    op_version.py:50).  ``get_version(op)`` -> int; unknown ops return
    version 0 (original semantics), matching the reference's default."""

    def get_version(self, op_name: str) -> int:
        return _CHECKPOINTS.get(op_name, (0, ""))[0]

    def get_note(self, op_name: str) -> str:
        return _CHECKPOINTS.get(op_name, (0, ""))[1]

    def check_upgrade(self, op_name: str, since_version: int) -> bool:
        """True if the op has an upgrade checkpoint >= since_version."""
        return self.get_version(op_name) >= since_version

    # reference-API compat (op_version.py:50 exposes category queries
    # over the C++ registry's change records; this build keeps version
    # ids + notes only, so category listings are empty)
    def check_modified(self, *a, **k):
        return []

    def check_bugfix(self, *a, **k):
        return []
