"""Profiler.

Reference parity: paddle/fluid/platform/profiler.* (RecordEvent RAII scopes,
EnableProfiler/DisableProfiler, chrome-trace via tools/timeline.py) and
python fluid/profiler.py.

TPU-native: jax.profiler does the heavy lifting — traces carry XLA/TPU
device activity and land in TensorBoard/perfetto format (the
CUPTI DeviceTracer + timeline.py analog).  RecordEvent maps to
jax.profiler.TraceAnnotation so named scopes appear inside device traces.
"""
from __future__ import annotations

import contextlib
import time

import jax


class RecordEvent:
    """Named scope visible in profiler traces (platform/profiler.cc:53)."""

    def __init__(self, name: str):
        self.name = name
        self._ann = jax.profiler.TraceAnnotation(name)
        self.begin = None

    def __enter__(self):
        self.begin = time.perf_counter()
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(*exc)
        self.elapsed = time.perf_counter() - self.begin
        return False


_trace_dir = None


def start_profiler(log_dir="/tmp/paddle_tpu_profile", state=None,
                   tracer_option=None):
    global _trace_dir
    _trace_dir = log_dir
    jax.profiler.start_trace(log_dir)


def stop_profiler(sorted_key=None, profile_path=None):
    jax.profiler.stop_trace()
    print(f"profiler trace written to {_trace_dir} "
          "(open with TensorBoard or perfetto)")


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/paddle_tpu_profile",
             tracer_option=None):
    """fluid.profiler.profiler context-manager parity (profiler.py:255)."""
    start_profiler(profile_path, state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
