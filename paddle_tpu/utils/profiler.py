"""Profiler.

Reference parity: paddle/fluid/platform/profiler.* (RecordEvent RAII scopes,
EnableProfiler/DisableProfiler, chrome-trace via tools/timeline.py) and
python fluid/profiler.py.

TPU-native: jax.profiler does the heavy lifting — traces carry XLA/TPU
device activity and land in TensorBoard/perfetto format (the
CUPTI DeviceTracer + timeline.py analog).  RecordEvent maps to
jax.profiler.TraceAnnotation so named scopes appear inside device traces.
"""
from __future__ import annotations

import contextlib
import logging
import time

import jax

logger = logging.getLogger("paddle_tpu.profiler")


class RecordEvent:
    """Named scope visible in profiler traces (platform/profiler.cc:53).
    Annotates both the XLA device trace (jax.profiler) and the native host
    event buffer (csrc/core.cc) when host profiling is enabled."""

    def __init__(self, name: str):
        self.name = name
        self._ann = jax.profiler.TraceAnnotation(name)
        self.begin = None

    def __enter__(self):
        from .. import core as _native
        self._native = _native if _native.profiler_enabled() else None
        if self._native:
            self._native.event_push(self.name)
        self.begin = time.perf_counter()
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(*exc)
        self.elapsed = time.perf_counter() - self.begin
        if self._native:
            self._native.event_pop()
        return False


class StepTimers:
    """Per-step phase timing for the fit hot loop.

    Each `scope(name)` is a RecordEvent — so `data` / `dispatch` / `sync`
    phases appear as named spans inside jax.profiler / host chrome traces
    — plus a host-side accumulator cheap enough to run every step, so
    `summary()` answers "where does step time go" without a trace viewer.
    Note that under the async engine `dispatch` measures enqueue cost
    only; device execution overlaps and is paid for inside `sync`."""

    def __init__(self):
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    def reset(self):
        """Zero the accumulators: per-epoch phase summaries should
        describe that epoch, not the whole process lifetime."""
        self.totals.clear()
        self.counts.clear()

    @contextlib.contextmanager
    def scope(self, name: str):
        ev = RecordEvent(f"paddle.fit/{name}")
        with ev:
            yield
        self.totals[name] = self.totals.get(name, 0.0) + ev.elapsed
        self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> dict:
        """{phase: {total_s, count, mean_ms}} for every recorded phase."""
        return {
            name: {"total_s": round(t, 6),
                   "count": self.counts[name],
                   "mean_ms": round(t / self.counts[name] * 1e3, 4)}
            for name, t in self.totals.items()
        }


class _BoundedCapture:
    """Self-driven bounded capture for loops without a TrainTelemetry:
    the caller IS the dispatching thread, so it brackets its own step
    loop — ``with`` starts the trace, ``step()`` after each dispatched
    step counts it down, and the trace stops at zero (or scope exit,
    whichever first)."""

    def __init__(self, steps: int, out_dir: str):
        self.steps_left = max(1, int(steps))
        self.trace_dir = out_dir
        self._active = False

    def __enter__(self):
        import os

        os.makedirs(self.trace_dir, exist_ok=True)
        jax.profiler.start_trace(self.trace_dir)
        self._active = True
        return self

    def step(self):
        if self._active:
            self.steps_left -= 1
            if self.steps_left <= 0:
                self._stop()

    def _stop(self):
        if self._active:
            self._active = False
            jax.profiler.stop_trace()

    def __exit__(self, *exc):
        self._stop()
        return False


def capture_device_trace(steps: int, out_dir: str, telemetry=None):
    """Bounded ``jax.profiler`` capture of the next ``steps`` steps.

    With a live monitored fit (a TrainTelemetry — passed explicitly or
    the process one), the capture is ARMED on it and returns the trace
    dir: start/stop happen at step boundaries ON the training thread
    (monitor/telemetry.py arm/poll — jax.profiler must be driven from
    the dispatching thread), so any thread may call this against a
    running job.  Without one, returns a ``_BoundedCapture`` context
    manager for the caller's own step loop.  Either way the artifacts
    under ``out_dir`` feed ``monitor.perf.load_trace_op_times`` /
    ``op_report(trace_dir=...)``."""
    if telemetry is None:
        from ..monitor import get_telemetry

        telemetry = get_telemetry()
    if telemetry is not None:
        return telemetry.arm_trace(steps, trace_dir=out_dir)
    return _BoundedCapture(steps, out_dir)


_trace_dir = None


def start_profiler(log_dir="/tmp/paddle_tpu_profile", state=None,
                   tracer_option=None):
    global _trace_dir
    _trace_dir = log_dir
    from .. import core as _native
    _native.trace_clear()
    _native.profiler_enable(True)
    jax.profiler.start_trace(log_dir)


def stop_profiler(sorted_key=None, profile_path=None):
    import os

    jax.profiler.stop_trace()
    from .. import core as _native
    _native.profiler_enable(False)
    if _native.available():
        # profile_path may be the jax trace DIRECTORY (the fluid API passes
        # one path for both); host events go to a file inside it
        target = profile_path
        if not target or os.path.isdir(target):
            target = os.path.join(target or _trace_dir or ".",
                                  "host_trace.json")
        n = export_chrome_trace(target)
        if n < 0:
            logger.warning("host trace export to %s failed", target)
    logger.info("profiler trace written to %s (open with TensorBoard or "
                "perfetto)", _trace_dir)


def export_chrome_trace(path: str) -> int:
    """Dump host RecordEvent scopes as chrome://tracing JSON — the
    tools/timeline.py analog. Returns number of events."""
    from .. import core as _native
    return _native.trace_export(path)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/paddle_tpu_profile",
             tracer_option=None):
    """fluid.profiler.profiler context-manager parity (profiler.py:255)."""
    start_profiler(profile_path, state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class ProfilerOptions:
    """Option bag (reference utils/profiler.py ProfilerOptions): a dict
    facade over the knobs the TPU profiler honors (output_dir; the
    CUDA-specific ones are accepted and inert)."""

    DEFAULTS = {
        "state": "All", "sorted_key": "default", "tracer_level": "Default",
        "batch_range": [0, 100], "output_thread_detail": False,
        "profile_path": "/tmp/paddle_tpu_profile",
        "timeline_path": "/tmp/paddle_tpu_profile/host_trace.json",
        "op_summary_path": "", "exit_on_finished": False,
    }

    def __init__(self, options=None):
        self._options = dict(self.DEFAULTS)
        if options:
            self._options.update(options)

    def with_state(self, state):
        self._options["state"] = state
        return self

    def __getitem__(self, name):
        if name not in self._options:
            raise ValueError(f"ProfilerOptions does not have an option "
                             f"named {name}.")
        return self._options[name]


class Profiler:
    """Start/stop facade over the jax.profiler + host-event tracing
    (reference utils/profiler.py Profiler; use as a context manager or
    via start()/stop())."""

    def __init__(self, enabled=True, options=None):
        self.enabled = enabled
        self.profiler_options = ProfilerOptions(options)
        self._running = False

    def start(self):
        if self.enabled and not self._running:
            start_profiler(self.profiler_options["profile_path"],
                           self.profiler_options["state"])
            self._running = True
        return self

    def stop(self):
        if self._running:
            stop_profiler(self.profiler_options["sorted_key"],
                          self.profiler_options["profile_path"])
            self._running = False

    def reset(self):
        from .. import core as _native
        _native.trace_clear()

    def export_chrome_tracing(self, path: str,
                              include_spans: bool = True) -> int:
        """Chrome-trace export with the monitor tracer's request/fit
        spans merged in: native host RecordEvent scopes AND
        monitor/tracing.py spans land in ONE perfetto-loadable file
        (the /debug/spans?format=chrome document, offline).  Returns
        the total event count."""
        import json
        import os

        from .. import core as _native

        doc = {"traceEvents": [], "displayTimeUnit": "ms"}
        if _native.available() and _native.trace_export(path) > 0:
            try:
                with open(path) as fh:
                    loaded = json.load(fh)
                doc = ({"traceEvents": loaded, "displayTimeUnit": "ms"}
                       if isinstance(loaded, list) else loaded)
            except (OSError, ValueError):
                pass
        if include_spans:
            from ..monitor.tracing import default_tracer

            span_doc = default_tracer().chrome_trace()
            doc.setdefault("traceEvents", []).extend(
                span_doc.get("traceEvents", ()))
            if span_doc.get("metadata"):
                doc.setdefault("metadata", {}).update(span_doc["metadata"])
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, path)
        return len(doc.get("traceEvents", ()))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


_profiler_singleton = None


def get_profiler(options=None):
    global _profiler_singleton
    if _profiler_singleton is None:
        _profiler_singleton = Profiler(options=options)
    return _profiler_singleton
