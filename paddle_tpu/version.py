"""`paddle.version` — build version metadata.

Reference parity: the generated python/paddle/version.py (setup.py
write_version_py): full_version/major/minor/patch/rc, commit, istaged,
with_mkl, and the mkl()/show() helpers.
"""
from __future__ import annotations

full_version = "2.0.0+tpu"
major = "2"
minor = "0"
patch = "0"
rc = "0"
istaged = True
commit = "tpu-native"
with_mkl = "OFF"

__all__ = ["full_version", "major", "minor", "patch", "rc", "istaged",
           "commit", "with_mkl", "mkl", "show"]


def mkl():
    return with_mkl


def show():
    # paddle.version.show() prints by API contract (reference parity)
    print("full_version:", full_version)  # noqa: PTA006
    print("major:", major)  # noqa: PTA006
    print("minor:", minor)  # noqa: PTA006
    print("patch:", patch)  # noqa: PTA006
    print("rc:", rc)  # noqa: PTA006
    print("commit:", commit)  # noqa: PTA006
