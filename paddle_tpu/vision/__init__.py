from . import datasets  # noqa: F401
from . import image  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import ops  # noqa: F401
from .image import (  # noqa: F401 — ref vision/__init__.py DEFINE_ALIAS
    get_image_backend, image_load, set_image_backend)
