"""Vision datasets.

Reference parity: python/paddle/vision/datasets (MNIST, Cifar10/100,
FashionMNIST, Flowers, VOC2012...).  This environment has zero network
egress, so datasets load from local files when present
(~/.cache/paddle_tpu/datasets or an explicit path) and otherwise fall back
to a deterministic synthetic sample generator clearly marked as such —
enough to exercise the full input pipeline, convergence tests use the
synthetic data's learnable structure.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

_CACHE = os.path.expanduser("~/.cache/paddle_tpu/datasets")


class _SyntheticImageClasses(Dataset):
    """Deterministic learnable synthetic data: each class has a fixed random
    template; samples are template + noise.  Lets convergence tests assert
    loss decrease without network access."""

    def __init__(self, num_samples, image_shape, num_classes,
                 template_seed=0, sample_seed=1, transform=None):
        rng = np.random.RandomState(template_seed)
        self.templates = rng.rand(num_classes, *image_shape).astype(np.float32)
        self.num_samples = num_samples
        self.num_classes = num_classes
        self.image_shape = image_shape
        self.transform = transform
        self._rng = np.random.RandomState(sample_seed)
        self.labels = self._rng.randint(0, num_classes, num_samples)
        self.noise_seeds = self._rng.randint(0, 2 ** 31 - 1, num_samples)

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        label = int(self.labels[idx])
        rng = np.random.RandomState(self.noise_seeds[idx])
        img = self.templates[label] + 0.25 * rng.randn(*self.image_shape) \
            .astype(np.float32)
        img = np.clip(img, 0.0, 1.0)
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), np.asarray(label, np.int64)


class MNIST(Dataset):
    """MNIST from local idx files if available, else synthetic fallback.
    Reference: python/paddle/vision/datasets/mnist.py."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        img_name = ("train-images-idx3-ubyte.gz" if mode == "train"
                    else "t10k-images-idx3-ubyte.gz")
        lbl_name = ("train-labels-idx1-ubyte.gz" if mode == "train"
                    else "t10k-labels-idx1-ubyte.gz")
        image_path = image_path or os.path.join(_CACHE, "mnist", img_name)
        label_path = label_path or os.path.join(_CACHE, "mnist", lbl_name)
        if os.path.exists(image_path) and os.path.exists(label_path):
            self.images, self.labels = self._load_idx(image_path, label_path)
            self.synthetic = False
        else:
            n = 2048 if mode == "train" else 512
            # templates shared across splits (same "digit" classes);
            # noise/sampling differs per split
            self._synth = _SyntheticImageClasses(
                n, (28, 28), 10, template_seed=0,
                sample_seed=1 if mode == "train" else 2)
            self.synthetic = True

    @staticmethod
    def _load_idx(image_path, label_path):
        opener = gzip.open if image_path.endswith(".gz") else open
        with opener(image_path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with opener(label_path, "rb") as f:
            _, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        return images, labels

    def __len__(self):
        return len(self._synth) if self.synthetic else len(self.images)

    def __getitem__(self, idx):
        if self.synthetic:
            img, label = self._synth[idx]
        else:
            img = self.images[idx].astype(np.float32) / 255.0
            label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        if img.ndim == 2:
            img = img[None]
        return img.astype(np.float32), np.asarray(label, np.int64)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """CIFAR-10 from local pickled batches if available, else synthetic."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        path = data_file or os.path.join(_CACHE, "cifar10")
        self.num_classes = 10
        if os.path.isdir(path):
            import pickle

            batches = ([f"data_batch_{i}" for i in range(1, 6)]
                       if mode == "train" else ["test_batch"])
            imgs, labels = [], []
            for b in batches:
                with open(os.path.join(path, b), "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                imgs.append(d[b"data"].reshape(-1, 3, 32, 32))
                labels.extend(d[b"labels"])
            self.images = np.concatenate(imgs).astype(np.float32) / 255.0
            self.labels = np.asarray(labels, np.int64)
            self.synthetic = False
        else:
            n = 2048 if mode == "train" else 512
            self._synth = _SyntheticImageClasses(
                n, (3, 32, 32), 10, template_seed=5,
                sample_seed=1 if mode == "train" else 2)
            self.synthetic = True

    def __len__(self):
        return len(self._synth) if self.synthetic else len(self.images)

    def __getitem__(self, idx):
        if self.synthetic:
            img, label = self._synth[idx]
        else:
            img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), np.asarray(label, np.int64)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        n = 2048 if mode == "train" else 512
        self._synth = _SyntheticImageClasses(
            n, (3, 32, 32), 100, template_seed=6,
            sample_seed=1 if mode == "train" else 2)
        self.synthetic = True
        self.num_classes = 100


class Flowers(Dataset):
    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        n = 1024 if mode == "train" else 256
        self._synth = _SyntheticImageClasses(
            n, (3, 64, 64), 102, template_seed=7,
            sample_seed=1 if mode == "train" else 2)

    def __len__(self):
        return len(self._synth)

    def __getitem__(self, idx):
        img, label = self._synth[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), np.asarray(label, np.int64)


from .folder import (  # noqa: E402,F401 — vision/datasets/folder.py:62
    DatasetFolder, ImageFolder)
from .voc2012 import VOC2012  # noqa: E402,F401 — vision/datasets/voc2012.py:41
