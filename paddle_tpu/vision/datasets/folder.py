"""DatasetFolder / ImageFolder — bring-your-own-images datasets.

Reference parity: python/paddle/vision/datasets/folder.py
(DatasetFolder:62, ImageFolder:216, make_dataset:39).  Purely local
directory walkers — no download path — so they work unchanged in a
zero-egress environment.
"""
from __future__ import annotations

import os

import numpy as np

from ...io import Dataset

__all__ = ["DatasetFolder", "ImageFolder", "has_valid_extension",
           "make_dataset", "pil_loader", "default_loader", "IMG_EXTENSIONS"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp", ".npy")


def has_valid_extension(filename, extensions):
    """Case-insensitive extension filter (reference folder.py:26)."""
    return filename.lower().endswith(tuple(extensions))


def make_dataset(directory, class_to_idx, extensions, is_valid_file=None):
    """Collect (path, class_index) pairs under root/class_x/** — sorted
    walk so sample order is deterministic across filesystems."""
    if extensions is not None and is_valid_file is not None:
        raise ValueError(
            "extensions and is_valid_file cannot both be passed")
    if is_valid_file is None:
        def is_valid_file(p):  # noqa: PLR1704 - mirrors reference shape
            return has_valid_extension(p, extensions)
    samples = []
    directory = os.path.expanduser(directory)
    for cls in sorted(class_to_idx):
        d = os.path.join(directory, cls)
        if not os.path.isdir(d):
            continue
        for root, _, fnames in sorted(os.walk(d, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(root, fname)
                if is_valid_file(path):
                    samples.append((path, class_to_idx[cls]))
    return samples


def pil_loader(path):
    from PIL import Image
    with open(path, "rb") as f:
        return Image.open(f).convert("RGB")


def _npy_loader(path):
    return np.load(path)


def default_loader(path):
    """PIL for image formats, numpy for .npy dumps (the TPU input
    pipeline consumes numpy either way)."""
    if path.lower().endswith(".npy"):
        return _npy_loader(path)
    return pil_loader(path)


class DatasetFolder(Dataset):
    """Generic loader for root/class_a/xxx.ext layouts.

    Attributes match the reference: classes, class_to_idx, samples,
    targets.  __getitem__ -> (sample, class_index).
    """

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        classes, class_to_idx = self._find_classes(root)
        samples = make_dataset(root, class_to_idx, extensions, is_valid_file)
        if not samples:
            raise RuntimeError(
                f"Found 0 files in subfolders of: {root}\n"
                f"Supported extensions are: {','.join(extensions or [])}")
        self.loader = loader or default_loader
        self.extensions = extensions
        self.classes = classes
        self.class_to_idx = class_to_idx
        self.samples = samples
        self.targets = [s[1] for s in samples]

    @staticmethod
    def _find_classes(directory):
        classes = sorted(e.name for e in os.scandir(directory) if e.is_dir())
        return classes, {c: i for i, c in enumerate(classes)}

    def __getitem__(self, index):
        path, target = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat (label-less) image folder: __getitem__ -> [sample]
    (reference folder.py:216 returns a single-element list)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        if is_valid_file is None:
            def is_valid_file(p):
                return has_valid_extension(p, extensions)
        samples = []
        for root_, _, fnames in sorted(os.walk(os.path.expanduser(root),
                                               followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(root_, fname)
                if is_valid_file(path):
                    samples.append(path)
        if not samples:
            raise RuntimeError(
                f"Found 0 files in subfolders of: {root}\n"
                f"Supported extensions are: {','.join(extensions or [])}")
        self.loader = loader or default_loader
        self.extensions = extensions
        self.samples = samples

    def __getitem__(self, index):
        sample = self.loader(self.samples[index])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
