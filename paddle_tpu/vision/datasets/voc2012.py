"""VOC2012 segmentation dataset.

Reference parity: python/paddle/vision/datasets/voc2012.py:41 — reads
(image, segmentation-label) pairs straight out of the VOCtrainval tar
without unpacking.  Zero-egress house rule (datasets/__init__.py): a
local tar (explicit `data_file` or the cache path) is used when present;
otherwise a deterministic synthetic segmentation set marked
`synthetic=True` keeps the pipeline exercisable.
"""
from __future__ import annotations

import io as _io
import os
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["VOC2012"]

_CACHE = os.path.expanduser("~/.cache/paddle_tpu/datasets")
_VOC_TAR = os.path.join(_CACHE, "VOCtrainval_11-May-2012.tar")
_SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
_DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
_LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
_MODE_FLAG = {"train": "train", "valid": "val", "test": "val"}


class VOC2012(Dataset):
    """__getitem__ -> (image, label) numpy arrays (HWC uint8 image,
    HW uint8 class-index mask), matching the reference's cv2 backend
    output — the TPU input pipeline consumes numpy."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        mode = mode.lower()
        if mode not in _MODE_FLAG:
            raise AssertionError(
                f"mode should be 'train', 'valid' or 'test', got {mode}")
        self.flag = _MODE_FLAG[mode]
        self.transform = transform
        self.data_file = data_file or (
            _VOC_TAR if os.path.exists(_VOC_TAR) else None)
        self.synthetic = self.data_file is None
        if self.synthetic:
            rng = np.random.RandomState(0 if self.flag == "train" else 1)
            n = 64 if self.flag == "train" else 16
            self._images = (rng.rand(n, 64, 64, 3) * 255).astype(np.uint8)
            self._labels = rng.randint(0, 21, (n, 64, 64)).astype(np.uint8)
        else:
            self._load_anno()

    def _load_anno(self):
        self._tar = tarfile.open(self.data_file)
        self._members = {m.name: m for m in self._tar.getmembers()}
        names = self._tar.extractfile(
            self._members[_SET_FILE.format(self.flag)]).read().split()
        self._keys = [n.decode() for n in names]

    def __getitem__(self, idx):
        if self.synthetic:
            image, label = self._images[idx], self._labels[idx]
        else:
            from PIL import Image
            raw = self._tar.extractfile(
                self._members[_DATA_FILE.format(self._keys[idx])]).read()
            lab = self._tar.extractfile(
                self._members[_LABEL_FILE.format(self._keys[idx])]).read()
            image = np.asarray(Image.open(_io.BytesIO(raw)).convert("RGB"))
            label = np.asarray(Image.open(_io.BytesIO(lab)))
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self._images) if self.synthetic else len(self._keys)

    def __del__(self):
        tar = getattr(self, "_tar", None)
        if tar is not None:
            tar.close()
