"""paddle.vision.image — image backend selection + loading.

Reference parity: python/paddle/vision/image.py:23
(set_image_backend/get_image_backend/image_load).  Backends: 'pil'
(default) and 'cv2' is accepted but served through PIL->numpy (cv2 is
not in this environment; arrays come back HWC like cv2 would return).
"""
from __future__ import annotations

import numpy as np

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_BACKEND = "pil"


def set_image_backend(backend):
    global _BACKEND
    if backend not in ("pil", "cv2"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2'], but got {backend}")
    _BACKEND = backend


def get_image_backend():
    return _BACKEND


def image_load(path, backend=None):
    """Load an image: PIL.Image for the pil backend, HWC ndarray for
    cv2."""
    backend = backend or _BACKEND
    if backend not in ("pil", "cv2"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2'], but got {backend}")
    from PIL import Image
    img = Image.open(path)
    if backend == "cv2":
        return np.asarray(img)
    return img
