"""paddle.vision.ops — detection operators.

Reference parity: paddle/fluid/operators/detection/ (~40 CUDA/C++ ops,
SURVEY.md §2.4) — the subset modern detectors actually use: nms,
multiclass_nms, roi_align, roi_pool, yolo_box, box_coder, prior_box, plus
box_iou/box_area helpers (operators/detection/{multiclass_nms_op.cc,
roi_align_op.cc, yolo_box_op.cc, box_coder_op.cc, prior_box_op.cc,
iou_similarity_op.cc}).

TPU disposition: everything is expressed with static shapes so it jits —
NMS is a fixed-trip-count `lax.fori_loop` producing a keep mask (no
dynamic-size outputs; callers slice by `keep_num`), RoI align is a
vectorized bilinear gather, decoders are pure elementwise. No dynamic
boxes-count recompilation as long as inputs are padded to a fixed N.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor, apply, unwrap

__all__ = ["box_area", "box_iou", "nms", "multiclass_nms", "roi_align",
           "roi_pool", "yolo_box", "box_coder", "prior_box",
           # round-3 detection breadth (operators/detection/*.cc)
           "iou_similarity", "box_clip", "anchor_generator",
           "density_prior_box", "polygon_box_transform",
           "sigmoid_focal_loss", "matrix_nms", "bipartite_match",
           "target_assign", "mine_hard_examples", "generate_proposals",
           "generate_proposals_v2", "distribute_fpn_proposals",
           "collect_fpn_proposals", "box_decoder_and_assign"]


def _v(x):
    return unwrap(x)


def box_area(boxes):
    """[N,4] xyxy -> [N] (detection/iou_similarity_op.h area)."""
    def f(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return apply(f, boxes)


def _pairwise_iou(a, b):
    """jnp-level [N,4]x[M,4] -> [N,M] IoU (single implementation shared by
    box_iou and the NMS mask)."""
    area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / (area1[:, None] + area2[None, :] - inter + 1e-10)


def box_iou(boxes1, boxes2):
    """[N,4] x [M,4] xyxy -> [N,M] IoU (iou_similarity_op.cc)."""
    return apply(_pairwise_iou, boxes1, boxes2)


def _nms_mask(boxes, scores, iou_threshold):
    """Greedy NMS as a keep mask over a FIXED N (the jit-safe variant for
    compiled detector steps)."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    pair_iou = _pairwise_iou(b, b)

    def body(i, keep):
        # suppress j>i overlapping a kept i
        row = (pair_iou[i] > iou_threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~row

    keep_sorted = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # scatter back to original indexing
    keep = jnp.zeros((n,), bool).at[order].set(keep_sorted)
    return keep


def _greedy_nms_numpy(b, s, iou_threshold):
    """Host-side greedy NMS — no XLA compile per distinct box count."""
    order = np.argsort(-s)
    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    area = (x2 - x1) * (y2 - y1)
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        rest = order[1:]
        xx1 = np.maximum(x1[i], x1[rest])
        yy1 = np.maximum(y1[i], y1[rest])
        xx2 = np.minimum(x2[i], x2[rest])
        yy2 = np.minimum(y2[i], y2[rest])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        iou = inter / (area[i] + area[rest] - inter + 1e-10)
        order = rest[iou <= iou_threshold]
    return np.asarray(keep, np.int64)


def nms(boxes, scores=None, iou_threshold=0.3, top_k=None):
    """Greedy hard NMS (eager/host path). Returns kept indices sorted by
    descending score (reference nms op); jit callers use the static-shape
    mask variant paddle.vision.ops._nms_mask."""
    b = np.asarray(_v(boxes))
    s = (np.asarray(_v(scores)) if scores is not None
         else np.arange(len(b), 0, -1, dtype=np.float32))
    idx = _greedy_nms_numpy(b, s, iou_threshold)
    if top_k is not None:
        idx = idx[:top_k]
    return Tensor(idx)


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, background_label=0):
    """Per-class NMS + global top-k (multiclass_nms_op.cc semantics,
    single image). bboxes [N,4], scores [C,N]. Returns [M,6]
    (label, score, x1, y1, x2, y2).  background_label defaults to 0 like
    the reference op (class row 0 = background is skipped); pass -1 to
    keep every class."""
    b = np.asarray(_v(bboxes))
    s = np.asarray(_v(scores))
    out = []
    for c in range(s.shape[0]):
        if c == background_label:
            continue
        mask = s[c] > score_threshold
        if not mask.any():
            continue
        cb, cs = b[mask], s[c][mask]
        ord_ = np.argsort(-cs)[:nms_top_k]
        cb, cs = cb[ord_], cs[ord_]
        kept = np.asarray(nms(cb, cs, nms_threshold).numpy())
        for i in kept:
            out.append([c, cs[i], *cb[i]])
    if not out:
        return Tensor(np.zeros((0, 6), np.float32))
    out = np.asarray(out, np.float32)
    out = out[np.argsort(-out[:, 1])][:keep_top_k]
    return Tensor(out)


def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign (roi_align_op.cc): x [N,C,H,W], boxes [R,4] xyxy in input
    coords, boxes assumed on image 0 unless boxes_num splits them.
    Bilinear-gather implementation — pure XLA, grads for free."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(xv, bv):
        xv = jnp.asarray(xv)
        bv = jnp.asarray(bv)
        N, C, H, W = xv.shape
        R = bv.shape[0]
        # batch index per roi — traced-safe: jnp.repeat with a static
        # total length, so boxes_num may be a tracer under jit
        if boxes_num is not None:
            bn = jnp.asarray(_v(boxes_num))
            bidx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                              total_repeat_length=R).astype(jnp.int32)
        else:
            bidx = jnp.zeros((R,), jnp.int32)
        offset = 0.5 if aligned else 0.0
        x1 = bv[:, 0] * spatial_scale - offset
        y1 = bv[:, 1] * spatial_scale - offset
        x2 = bv[:, 2] * spatial_scale - offset
        y2 = bv[:, 3] * spatial_scale - offset
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: [R, oh*sr, ow*sr]
        ys = (y1[:, None] + (jnp.arange(oh * sr) + 0.5)[None, :]
              * rh[:, None] / (oh * sr))
        xs = (x1[:, None] + (jnp.arange(ow * sr) + 0.5)[None, :]
              * rw[:, None] / (ow * sr))

        def bilinear(img, yy, xx):
            # img [C,H,W]; yy [P], xx [Q] -> [C,P,Q]
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)
            wy = jnp.clip(yy, 0, H - 1) - y0
            wx = jnp.clip(xx, 0, W - 1) - x0
            v00 = img[:, y0i][:, :, x0i]
            v01 = img[:, y0i][:, :, x1i]
            v10 = img[:, y1i][:, :, x0i]
            v11 = img[:, y1i][:, :, x1i]
            return (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                    + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
                    + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
                    + v11 * wy[None, :, None] * wx[None, None, :])

        def per_roi(r):
            img = xv[bidx[r]]
            samples = bilinear(img, ys[r], xs[r])  # [C, oh*sr, ow*sr]
            return samples.reshape(C, oh, sr, ow, sr).mean((2, 4))

        return jax.vmap(per_roi)(jnp.arange(R))

    return apply(f, x, boxes)


def roi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0):
    """RoIPool (roi_pool_op.cc) via dense-sampled max."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(xv, bv):
        xv = jnp.asarray(xv)
        bv = jnp.asarray(bv)
        N, C, H, W = xv.shape
        R = bv.shape[0]
        if boxes_num is not None:
            bn = jnp.asarray(_v(boxes_num))
            bidx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                              total_repeat_length=R).astype(jnp.int32)
        else:
            bidx = jnp.zeros((R,), jnp.int32)
        sr = 4  # dense samples per output cell edge

        def per_roi(r):
            x1 = bv[r, 0] * spatial_scale
            y1 = bv[r, 1] * spatial_scale
            x2 = jnp.maximum(bv[r, 2] * spatial_scale, x1 + 1)
            y2 = jnp.maximum(bv[r, 3] * spatial_scale, y1 + 1)
            ys = jnp.clip(y1 + (jnp.arange(oh * sr) + 0.5) * (y2 - y1)
                          / (oh * sr), 0, H - 1).astype(jnp.int32)
            xs = jnp.clip(x1 + (jnp.arange(ow * sr) + 0.5) * (x2 - x1)
                          / (ow * sr), 0, W - 1).astype(jnp.int32)
            img = xv[bidx[r]]
            samples = img[:, ys][:, :, xs]
            return samples.reshape(C, oh, sr, ow, sr).max((2, 4))

        return jax.vmap(per_roi)(jnp.arange(R))

    return apply(f, x, boxes)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """Decode YOLO head output (yolo_box_op.cc): x [N, A*(5+C), H, W],
    img_size [N,2] (h,w). Returns (boxes [N, A*H*W, 4] xyxy,
    scores [N, A*H*W, C])."""
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    A = anchors.shape[0]

    def f(xv, imgv):
        N, _, H, W = xv.shape
        xv = xv.reshape(N, A, 5 + class_num, H, W)
        gx = (jnp.arange(W))[None, None, None, :]
        gy = (jnp.arange(H))[None, None, :, None]
        alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
        cx = (jax.nn.sigmoid(xv[:, :, 0]) * alpha + beta + gx) / W
        cy = (jax.nn.sigmoid(xv[:, :, 1]) * alpha + beta + gy) / H
        anc = jnp.asarray(anchors)
        pw = anc[None, :, 0, None, None] * jnp.exp(xv[:, :, 2]) \
            / (downsample_ratio * W)
        ph = anc[None, :, 1, None, None] * jnp.exp(xv[:, :, 3]) \
            / (downsample_ratio * H)
        conf = jax.nn.sigmoid(xv[:, :, 4])
        cls = jax.nn.sigmoid(xv[:, :, 5:]) * conf[:, :, None]
        cls = jnp.where(conf[:, :, None] >= conf_thresh, cls, 0.0)
        imh = imgv[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = imgv[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - pw / 2) * imw
        y1 = (cy - ph / 2) * imh
        x2 = (cx + pw / 2) * imw
        y2 = (cy + ph / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(N, -1, 4)
        scores = jnp.moveaxis(cls, 2, -1).reshape(N, -1, class_num)
        return boxes, scores

    return apply(f, x, img_size, _multi_out=True)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0):
    """Encode/decode boxes against priors (box_coder_op.cc).

    Encode: priors [N,4], targets [N,4] -> [N,4] deltas.
    Decode: priors [N,4] broadcast into targets [N,M,4] along `axis`
    (axis=0: priors vary along dim 0; axis=1: along dim 1 — the reference's
    per-class decode shape); 2-D targets decode elementwise.
    """
    def f(pb, pbv, tb):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[..., 2] - pb[..., 0] + norm
        ph = pb[..., 3] - pb[..., 1] + norm
        pcx = pb[..., 0] + pw / 2
        pcy = pb[..., 1] + ph / 2
        if code_type == "encode_center_size":
            tw = tb[..., 2] - tb[..., 0] + norm
            th = tb[..., 3] - tb[..., 1] + norm
            tcx = tb[..., 0] + tw / 2
            tcy = tb[..., 1] + th / 2
            dx = (tcx - pcx) / pw / pbv[..., 0]
            dy = (tcy - pcy) / ph / pbv[..., 1]
            dw = jnp.log(tw / pw) / pbv[..., 2]
            dh = jnp.log(th / ph) / pbv[..., 3]
            return jnp.stack([dx, dy, dw, dh], -1)
        # decode — broadcast [N,4] priors against [N,M,4] targets per axis
        if tb.ndim == 3:
            exp = 1 if axis == 0 else 0
            pw, ph, pcx, pcy = (jnp.expand_dims(v, exp)
                                for v in (pw, ph, pcx, pcy))
            pbv_b = jnp.expand_dims(pbv, exp)
        else:
            pbv_b = pbv
        dcx = pbv_b[..., 0] * tb[..., 0] * pw + pcx
        dcy = pbv_b[..., 1] * tb[..., 1] * ph + pcy
        dw = jnp.exp(pbv_b[..., 2] * tb[..., 2]) * pw
        dh = jnp.exp(pbv_b[..., 3] * tb[..., 3]) * ph
        return jnp.stack([dcx - dw / 2, dcy - dh / 2,
                          dcx + dw / 2 - norm, dcy + dh / 2 - norm], -1)

    return apply(f, prior_box, prior_box_var, target_box)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5):
    """SSD prior (anchor) boxes (prior_box_op.cc). input [N,C,H,W] feature
    map, image [N,C,IH,IW]. Returns (boxes [H,W,A,4], variances same)."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]
    # reference anchor ordering (prior_box_op.h): per min_size emit
    # [min, aspect-ratio anchors, max] — heads trained against paddle
    # depend on this exact order
    boxes = []
    for k, s in enumerate(min_sizes):
        boxes.append((s, s))
        for a in ars:
            if a == 1.0:
                continue
            boxes.append((s * np.sqrt(a), s / np.sqrt(a)))
        if max_sizes:
            smax = max_sizes[k]
            boxes.append((np.sqrt(s * smax),) * 2)
    A = len(boxes)
    wh = np.asarray(boxes, np.float32)  # [A,2]
    cx = (np.arange(fw) + offset) * step_w
    cy = (np.arange(fh) + offset) * step_h
    out = np.zeros((fh, fw, A, 4), np.float32)
    out[..., 0] = (cx[None, :, None] - wh[None, None, :, 0] / 2) / iw
    out[..., 1] = (cy[:, None, None] - wh[None, None, :, 1] / 2) / ih
    out[..., 2] = (cx[None, :, None] + wh[None, None, :, 0] / 2) / iw
    out[..., 3] = (cy[:, None, None] + wh[None, None, :, 1] / 2) / ih
    if clip:
        out = np.clip(out, 0, 1)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return Tensor(out), Tensor(var)


# ---------------------------------------------------------------------------
# Round-3 breadth: the rest of the reference detection family
# (operators/detection/*.cc) in TPU form — static shapes, mask/pad outputs.
# ---------------------------------------------------------------------------

def iou_similarity(x, y, box_normalized=True):
    """Pairwise IoU matrix [N,M] (iou_similarity_op.cc)."""
    return box_iou(x, y)


def box_clip(input, im_info):
    """Clip boxes to image bounds (box_clip_op.cc). input [...,4] xyxy;
    im_info [3] or [N,3] = (H, W, scale) — boxes clip to the RESCALED
    image (H/scale - 1, W/scale - 1), matching the reference kernel."""
    def f(b, info):
        info = info.reshape(-1)[:3]
        h = info[0] / info[2] - 1.0
        w = info[1] / info[2] - 1.0
        x1 = jnp.clip(b[..., 0], 0.0, w)
        y1 = jnp.clip(b[..., 1], 0.0, h)
        x2 = jnp.clip(b[..., 2], 0.0, w)
        y2 = jnp.clip(b[..., 3], 0.0, h)
        return jnp.stack([x1, y1, x2, y2], -1)

    return apply(f, input, im_info)


def anchor_generator(input, anchor_sizes, aspect_ratios, variances,
                     stride, offset=0.5):
    """RPN anchors for one feature map (anchor_generator_op.cc).
    input [N,C,H,W]; returns (anchors [H,W,A,4] xyxy in image coords,
    variances [H,W,A,4])."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    sw, sh = float(stride[0]), float(stride[1])
    wh = []
    for ar in aspect_ratios:
        for s in anchor_sizes:
            # reference kernel (anchor_generator_op.h:66-73): base dims are
            # ROUNDED from the stride cell's area, then scaled by size/stride
            base_w = np.round(np.sqrt(sw * sh / ar))
            base_h = np.round(base_w * ar)
            wh.append(((s / sw) * base_w, (s / sh) * base_h))
    A = len(wh)
    wh = np.asarray(wh, np.float32)
    # centers use the (stride-1) pixel convention (anchor_generator_op.h:55)
    cx = np.arange(fw, dtype=np.float32) * sw + offset * (sw - 1.0)
    cy = np.arange(fh, dtype=np.float32) * sh + offset * (sh - 1.0)
    out = np.zeros((fh, fw, A, 4), np.float32)
    # corners use the +/-0.5*(dim-1) convention (anchor_generator_op.h:74-81)
    out[..., 0] = cx[None, :, None] - (wh[None, None, :, 0] - 1.0) / 2
    out[..., 1] = cy[:, None, None] - (wh[None, None, :, 1] - 1.0) / 2
    out[..., 2] = cx[None, :, None] + (wh[None, None, :, 0] - 1.0) / 2
    out[..., 3] = cy[:, None, None] + (wh[None, None, :, 1] - 1.0) / 2
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          out.shape).copy()
    return Tensor(out), Tensor(var)


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False):
    """Densified SSD priors (density_prior_box_op.cc): each fixed_size is
    tiled density×density times across its cell."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    # per-location anchor list: for each (density, fixed_size) and ratio,
    # a density×density grid of shifted centers
    anchors = []  # (dx, dy, w, h) offsets relative to cell center
    for dens, fs in zip(densities, fixed_sizes):
        for ar in fixed_ratios:
            w = fs * np.sqrt(ar)
            h = fs / np.sqrt(ar)
            shift_w = step_w / dens
            shift_h = step_h / dens
            for di in range(dens):
                for dj in range(dens):
                    dx = (dj + 0.5) * shift_w - step_w / 2
                    dy = (di + 0.5) * shift_h - step_h / 2
                    anchors.append((dx, dy, w, h))
    A = len(anchors)
    anc = np.asarray(anchors, np.float32)
    cx = (np.arange(fw, dtype=np.float32) + offset) * step_w
    cy = (np.arange(fh, dtype=np.float32) + offset) * step_h
    out = np.zeros((fh, fw, A, 4), np.float32)
    ax = cx[None, :, None] + anc[None, None, :, 0]
    ay = cy[:, None, None] + anc[None, None, :, 1]
    out[..., 0] = (ax - anc[None, None, :, 2] / 2) / iw
    out[..., 1] = (ay - anc[None, None, :, 3] / 2) / ih
    out[..., 2] = (ax + anc[None, None, :, 2] / 2) / iw
    out[..., 3] = (ay + anc[None, None, :, 3] / 2) / ih
    if clip:
        out = np.clip(out, 0, 1)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    if flatten_to_2d:
        out = out.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return Tensor(out), Tensor(var)


def polygon_box_transform(input):
    """EAST geometry map -> quad coordinates (polygon_box_transform_op.cc):
    even channels are x-offsets (out = 4*col - in), odd channels are
    y-offsets (out = 4*row - in)."""
    def f(v):
        n, c, h, w = v.shape
        cols = jnp.arange(w, dtype=v.dtype)[None, None, None, :] * 4.0
        rows = jnp.arange(h, dtype=v.dtype)[None, None, :, None] * 4.0
        even = (jnp.arange(c) % 2 == 0)[None, :, None, None]
        return jnp.where(even, cols - v, rows - v)

    return apply(f, input)


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    """Focal loss on logits (sigmoid_focal_loss_op.cc): x [N,C] logits,
    label [N,1] int in [0,C] (0 = background), fg_num [1] normalizer.
    Per-element loss [N,C], positives at column label-1."""
    def f(v, lab, fg):
        n, c = v.shape
        fg = jnp.maximum(fg.reshape(()).astype(v.dtype), 1.0)
        lab = lab.reshape(-1)
        cls = jnp.arange(1, c + 1)[None, :]
        pos = (lab[:, None] == cls).astype(v.dtype)
        p = jax.nn.sigmoid(v)
        # standard numerically-stable BCE-with-logits split by sign
        ce_pos = jax.nn.softplus(-v)       # -log(sigmoid(x))
        ce_neg = jax.nn.softplus(v)        # -log(1 - sigmoid(x))
        loss = pos * (alpha * (1 - p) ** gamma * ce_pos) + \
            (1 - pos) * ((1 - alpha) * p ** gamma * ce_neg)
        return loss / fg

    return apply(f, x, label, fg_num)


def matrix_nms(bboxes, scores, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=400, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True):
    """Matrix NMS (matrix_nms_op.cc — SOLOv2): fully parallel decay-based
    suppression, a natural fit for TPU (no sequential greedy loop).
    bboxes [N,M,4], scores [N,C,M]. Returns Out [R,6] (label, score, box),
    optional Index [R,1], RoisNum [N]."""
    b_all = np.asarray(_v(bboxes), np.float32)
    s_all = np.asarray(_v(scores), np.float32)
    outs, idxs, nums = [], [], []
    N, C, M = s_all.shape
    for n in range(N):
        per_img = []
        for c in range(C):
            if c == background_label:
                continue
            s = s_all[n, c]
            sel = np.nonzero(s > score_threshold)[0]
            if sel.size == 0:
                continue
            order = sel[np.argsort(-s[sel])][:nms_top_k]
            bb, ss = b_all[n][order], s[order]
            iou = np.asarray(_v(box_iou(Tensor(bb), Tensor(bb))))
            iou = np.triu(iou, 1)               # IoU with higher-scored
            max_iou = iou.max(axis=0)           # per box: worst overlap
            if use_gaussian:
                decay = np.exp((max_iou[:, None] ** 2 - iou ** 2)
                               / gaussian_sigma)
            else:
                decay = (1.0 - iou) / (1.0 - max_iou[:, None] + 1e-10)
            decay = np.where(np.triu(np.ones_like(iou), 1) > 0,
                             decay, np.inf).min(axis=0)
            decay = np.where(np.isinf(decay), 1.0, decay)
            ds = ss * decay
            keep = ds > post_threshold if post_threshold > 0 else \
                np.ones_like(ds, bool)
            for j in np.nonzero(keep)[0]:
                per_img.append((c, ds[j], *bb[j], n * M + order[j]))
        per_img.sort(key=lambda r: -r[1])
        per_img = per_img[:keep_top_k]
        nums.append(len(per_img))
        for r in per_img:
            outs.append(r[:6])
            idxs.append(r[6])
    out = (np.asarray(outs, np.float32) if outs
           else np.zeros((0, 6), np.float32))
    res = [Tensor(out)]
    if return_index:
        res.append(Tensor(np.asarray(idxs, np.int64).reshape(-1, 1)))
    if return_rois_num:
        res.append(Tensor(np.asarray(nums, np.int32)))
    return tuple(res) if len(res) > 1 else res[0]


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None):
    """Greedy bipartite matching (bipartite_match_op.cc): dist [R,C] —
    repeatedly take the globally largest entry, binding its row to its
    column. Returns (match_indices [1,C] int32 row-or--1,
    match_dist [1,C]). match_type='per_prediction' additionally matches
    leftover columns to their argmax row when dist > dist_threshold."""
    def f(dist):
        R, C = dist.shape
        NEG = jnp.finfo(dist.dtype).min

        def body(_, carry):
            d, m_idx, m_dist = carry
            flat = jnp.argmax(d)
            r, c = flat // C, flat % C
            ok = d[r, c] > NEG / 2
            m_idx = jnp.where(ok, m_idx.at[c].set(r.astype(jnp.int32)),
                              m_idx)
            m_dist = jnp.where(ok, m_dist.at[c].set(dist[r, c]), m_dist)
            d = jnp.where(ok, d.at[r, :].set(NEG).at[:, c].set(NEG), d)
            return d, m_idx, m_dist

        init = (dist, jnp.full((C,), -1, jnp.int32),
                jnp.zeros((C,), dist.dtype))
        _, m_idx, m_dist = jax.lax.fori_loop(0, min(R, C), body, init)
        if match_type == "per_prediction":
            thr = 0.5 if dist_threshold is None else float(dist_threshold)
            best_r = jnp.argmax(dist, axis=0).astype(jnp.int32)
            best_d = jnp.max(dist, axis=0)
            fill = (m_idx < 0) & (best_d > thr)
            m_idx = jnp.where(fill, best_r, m_idx)
            m_dist = jnp.where(fill, best_d, m_dist)
        return m_idx[None, :], m_dist[None, :]

    return apply(f, dist_matrix, _multi_out=True)


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0):
    """Gather per-column targets by match indices (target_assign_op.cc):
    input [R,K], matched_indices [1,C] (-1 = mismatch) ->
    (out [1,C,K], out_weight [1,C,1])."""
    def f(x, midx):
        idx = midx[0]
        safe = jnp.clip(idx, 0, x.shape[0] - 1)
        out = x[safe]
        matched = (idx >= 0)[:, None]
        out = jnp.where(matched, out, jnp.asarray(mismatch_value, x.dtype))
        w = matched.astype(x.dtype)
        return out[None], w[None]

    return apply(f, input, matched_indices, _multi_out=True)


def mine_hard_examples(cls_loss, loc_loss=None, match_indices=None,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       mining_type="max_negative", sample_size=None):
    """SSD hard-negative mining (mine_hard_examples_op.cc): rank negatives
    (match_indices == -1) by loss, keep neg_pos_ratio × #positives.
    cls_loss/loc_loss [N,Np]; returns NegIndices as a mask [N,Np] int32
    (1 = selected hard negative) — static-shape stand-in for the
    reference's LoD index list."""
    def f(cl, midx, *ll):
        loss = cl + (ll[0] if ll else 0.0)
        neg = midx < 0
        n_pos = jnp.sum(midx >= 0, axis=1, keepdims=True)
        quota = (n_pos * neg_pos_ratio).astype(jnp.int32)
        if sample_size is not None:
            quota = jnp.minimum(quota, jnp.int32(sample_size))
        masked = jnp.where(neg, loss, -jnp.inf)
        order = jnp.argsort(-masked, axis=1)
        rank = jnp.argsort(order, axis=1)  # rank of each elem among negs
        sel = (rank < quota) & neg
        return sel.astype(jnp.int32)

    args = [cls_loss, match_indices] + ([loc_loss] if loc_loss is not None
                                        else [])
    return apply(f, *args)


def generate_proposals(scores, bbox_deltas, im_shape, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=True, return_rois_num=False):
    """Faster-RCNN RPN proposals (generate_proposals_op.cc / _v2):
    scores [N,A,H,W], bbox_deltas [N,4A,H,W], anchors/variances [H,W,A,4],
    im_shape [N,2] (H,W).  Per image: top-pre_nms scores → decode deltas
    against anchors → clip → filter tiny → greedy NMS → top post_nms.
    Returns (rois [R,4], roi_probs [R,1][, rois_num [N]]) — eager/host op
    like the reference (the RPN head itself stays jitted)."""
    sc = np.asarray(_v(scores), np.float32)
    bd = np.asarray(_v(bbox_deltas), np.float32)
    ims = np.asarray(_v(im_shape), np.float32)
    anc = np.asarray(_v(anchors), np.float32).reshape(-1, 4)
    var = np.asarray(_v(variances), np.float32).reshape(-1, 4)
    N, A, H, W = sc.shape
    offset = 1.0 if pixel_offset else 0.0
    all_rois, all_probs, nums = [], [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)          # HWA order
        d = bd[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], anc[order], var[order]
        # decode (variance-weighted center-size, box_coder decode path)
        aw = a[:, 2] - a[:, 0] + offset
        ah = a[:, 3] - a[:, 1] + offset
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        w = np.exp(np.minimum(v[:, 2] * d[:, 2], np.log(1000. / 16))) * aw
        h = np.exp(np.minimum(v[:, 3] * d[:, 3], np.log(1000. / 16))) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - offset, cy + h / 2 - offset], -1)
        ih, iw = ims[n, 0], ims[n, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - offset)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - offset)
        ws = boxes[:, 2] - boxes[:, 0] + offset
        hs = boxes[:, 3] - boxes[:, 1] + offset
        keep = (ws >= min_size) & (hs >= min_size)
        boxes, s = boxes[keep], s[keep]
        if boxes.shape[0]:
            kept = _greedy_nms_numpy(boxes, s, nms_thresh)[:post_nms_top_n]
            boxes, s = boxes[kept], s[kept]
        all_rois.append(boxes)
        all_probs.append(s[:, None])
        nums.append(boxes.shape[0])
    rois = Tensor(np.concatenate(all_rois, 0) if all_rois
                  else np.zeros((0, 4), np.float32))
    probs = Tensor(np.concatenate(all_probs, 0) if all_probs
                   else np.zeros((0, 1), np.float32))
    if return_rois_num:
        return rois, probs, Tensor(np.asarray(nums, np.int32))
    return rois, probs


generate_proposals_v2 = generate_proposals


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=True,
                             rois_num=None):
    """Assign RoIs to FPN levels by scale (distribute_fpn_proposals_op.cc):
    level = floor(log2(sqrt(area) / refer_scale + eps)) + refer_level.
    Returns (multi_rois list per level, restore_index [R,1]
    [, multi_level_rois_num])."""
    r = np.asarray(_v(fpn_rois), np.float32)
    offset = 1.0 if pixel_offset else 0.0
    w = r[:, 2] - r[:, 0] + offset
    h = r[:, 3] - r[:, 1] + offset
    scale = np.sqrt(np.clip(w * h, 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi, order, nums = [], [], []
    for L in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == L)[0]
        multi.append(Tensor(r[idx]))
        nums.append(len(idx))
        order.append(idx)
    order = np.concatenate(order) if order else np.zeros((0,), np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(order.size)
    out = (multi, Tensor(restore[:, None].astype(np.int32)))
    if rois_num is not None:
        return out[0], out[1], [Tensor(np.asarray([n], np.int32))
                                for n in nums]
    return out


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None):
    """Merge per-level RPN outputs and keep global top-k by score
    (collect_fpn_proposals_op.cc)."""
    rois = np.concatenate([np.asarray(_v(r)) for r in multi_rois], 0)
    scores = np.concatenate(
        [np.asarray(_v(s)).reshape(-1) for s in multi_scores], 0)
    order = np.argsort(-scores)[:post_nms_top_n]
    out = Tensor(rois[order].astype(np.float32))
    if rois_num_per_level is not None:
        return out, Tensor(np.asarray([len(order)], np.int32))
    return out


def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, box_clip_value=4.135):
    """Decode per-class deltas and pick the best-scored class's box
    (box_decoder_and_assign_op.cc): priors [N,4], targets [N,4C],
    scores [N,C+1] (col 0 = background). Returns (decoded [N,4C],
    assigned [N,4])."""
    def f(pb, pbv, tb, sc):
        n = pb.shape[0]
        c4 = tb.shape[1]
        pw = pb[:, 2] - pb[:, 0] + 1.0
        ph = pb[:, 3] - pb[:, 1] + 1.0
        pcx = pb[:, 0] + pw / 2
        pcy = pb[:, 1] + ph / 2
        t = tb.reshape(n, -1, 4)
        v = pbv.reshape(n, 1, 4)
        dx = jnp.clip(t[..., 0] * v[..., 0], -box_clip_value,
                      box_clip_value)
        dy = jnp.clip(t[..., 1] * v[..., 1], -box_clip_value,
                      box_clip_value)
        dw = jnp.clip(t[..., 2] * v[..., 2], -box_clip_value,
                      box_clip_value)
        dh = jnp.clip(t[..., 3] * v[..., 3], -box_clip_value,
                      box_clip_value)
        cx = dx * pw[:, None] + pcx[:, None]
        cy = dy * ph[:, None] + pcy[:, None]
        w = jnp.exp(dw) * pw[:, None]
        h = jnp.exp(dh) * ph[:, None]
        dec = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - 1, cy + h / 2 - 1], -1)
        dec = dec.reshape(n, c4)
        best = jnp.argmax(sc[:, 1:], axis=1)  # skip background col
        assigned = jnp.take_along_axis(
            dec.reshape(n, -1, 4), best[:, None, None], axis=1)[:, 0]
        return dec, assigned

    return apply(f, prior_box, prior_box_var, target_box, box_score,
                 _multi_out=True)


# --------------------------------------------------------------------------
# op-registry tail (COVERAGE.md round-4)
# --------------------------------------------------------------------------

def _pairwise_iou_np(a, b):
    """[N,4] x [M,4] xyxy -> [N,M] IoU, vectorized numpy (host-side
    assignment ops share this instead of re-deriving the formula)."""
    a = np.asarray(a, np.float64).reshape(-1, 4)
    b = np.asarray(b, np.float64).reshape(-1, 4)
    ix = np.maximum(0.0, np.minimum(a[:, None, 2], b[None, :, 2])
                    - np.maximum(a[:, None, 0], b[None, :, 0]))
    iy = np.maximum(0.0, np.minimum(a[:, None, 3], b[None, :, 3])
                    - np.maximum(a[:, None, 1], b[None, :, 1]))
    inter = ix * iy
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    ua = area_a[:, None] + area_b[None, :] - inter
    return np.where(ua > 0, inter / np.maximum(ua, 1e-12), 0.0)

def affine_channel(x, scale, bias, data_layout="NCHW"):
    """Per-channel x*scale+bias (operators/affine_channel_op.cc)."""
    def f(v, s, b):
        if data_layout == "NCHW":
            shape = (1, -1) + (1,) * (v.ndim - 2)
        else:
            shape = (1,) * (v.ndim - 1) + (-1,)
        return v * s.reshape(shape) + b.reshape(shape)
    return apply(f, x, scale, bias)


def channel_shuffle(x, groups, data_format="NCHW"):
    """Interleave channel groups (operators/shuffle_channel_op.h)."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"unsupported data_format {data_format!r}")

    def f(v):
        if data_format == "NCHW":
            B, C, H, W = v.shape
            return v.reshape(B, groups, C // groups, H, W) \
                .swapaxes(1, 2).reshape(B, C, H, W)
        B, H, W, C = v.shape
        return v.reshape(B, H, W, groups, C // groups) \
            .swapaxes(3, 4).reshape(B, H, W, C)
    return apply(f, x)


def space_to_depth(x, blocksize):
    """Rearrange spatial blocks into channels
    (operators/space_to_depth_op.cc)."""
    def f(v):
        B, C, H, W = v.shape
        b = blocksize
        v = v.reshape(B, C, H // b, b, W // b, b)
        return v.transpose(0, 3, 5, 1, 2, 4).reshape(
            B, C * b * b, H // b, W // b)
    return apply(f, x)


def correlation(x1, x2, pad_size, kernel_size, max_displacement,
                stride1=1, stride2=1, corr_type_multiply=1):
    """FlowNet cost volume (operators/correlation_op.cc): mean over
    channels of x1[h,w] * x2[h+dy, w+dx] for each displacement in the
    (2*max_displacement/stride2+1)^2 window.  kernel_size=1 form."""
    def f(a, b):
        B, C, H, W = a.shape
        d = max_displacement // stride2
        pads = ((0, 0), (0, 0), (max_displacement, max_displacement),
                (max_displacement, max_displacement))
        bp = jnp.pad(b, pads)
        outs = []
        for dy in range(-d, d + 1):
            for dx in range(-d, d + 1):
                oy = max_displacement + dy * stride2
                ox = max_displacement + dx * stride2
                shifted = jax.lax.dynamic_slice(
                    bp, (0, 0, oy, ox), (B, C, H, W))
                outs.append((a * shifted).mean(1))
        return jnp.stack(outs, 1)  # [B, (2d+1)^2, H, W]
    return apply(f, x1, x2)


def deform_conv2d(x, offset, weight, mask=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, bias=None):
    """Deformable convolution v1/v2 (operators/deformable_conv_op.cc,
    deformable_conv_v1_op.cc): each kernel tap samples the input at a
    learned fractional offset (bilinear); v2 additionally modulates each
    tap with a mask.  offset [B, 2*dg*kh*kw, Ho, Wo] (y,x interleaved per
    tap, the reference layout), mask [B, dg*kh*kw, Ho, Wo]."""
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def f(v, off, w, *rest):
        B, C, H, W = v.shape
        O, Cg, kh, kw = w.shape
        Ho = (H + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        Wo = (W + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        K = kh * kw
        off = off.reshape(B, deformable_groups, K, 2, Ho, Wo)

        oy = jnp.arange(Ho) * st[0] - pd[0]
        ox = jnp.arange(Wo) * st[1] - pd[1]
        ky = jnp.arange(kh) * dl[0]
        kx = jnp.arange(kw) * dl[1]
        # base sample positions [K, Ho, Wo]
        base_y = (oy[None, :, None] + ky.repeat(kw)[:, None, None])
        base_x = (ox[None, None, :] + jnp.tile(kx, kh)[:, None, None])
        py = base_y[None, None] + off[:, :, :, 0]      # [B,dg,K,Ho,Wo]
        px = base_x[None, None] + off[:, :, :, 1]

        y0 = jnp.floor(py); x0 = jnp.floor(px)
        wy = py - y0; wx = px - x0

        def gather(vv, yy, xx):
            # vv [B,C,H,W]; yy/xx [B,dg,K,Ho,Wo] -> [B,dg,K,Ho,Wo,cg]
            valid = ((yy >= 0) & (yy < H) & (xx >= 0) & (xx < W))
            yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            cg = C // deformable_groups
            vg = jnp.moveaxis(            # [B,dg,H,W,cg]
                vv.reshape(B, deformable_groups, cg, H, W), 2, -1)
            bidx = jnp.arange(B)[:, None, None, None, None]
            gidx = jnp.arange(deformable_groups)[None, :, None, None, None]
            g = vg[bidx, gidx, yc, xc]
            return jnp.where(valid[..., None], g, 0.0)

        g00 = gather(v, y0, x0)
        g01 = gather(v, y0, x0 + 1)
        g10 = gather(v, y0 + 1, x0)
        g11 = gather(v, y0 + 1, x0 + 1)
        wy_ = wy[..., None]; wx_ = wx[..., None]
        samp = (g00 * (1 - wy_) * (1 - wx_) + g01 * (1 - wy_) * wx_
                + g10 * wy_ * (1 - wx_) + g11 * wy_ * wx_)
        if rest:  # v2 modulation mask
            m = rest[0].reshape(B, deformable_groups, K, Ho, Wo)
            samp = samp * m[..., None]
        # samp [B,dg,K,Ho,Wo,cg] -> im2col [B, C, K, Ho, Wo]
        samp = jnp.moveaxis(samp, -1, 3)   # [B,dg,K,cg,Ho,Wo]
        colk = jnp.moveaxis(samp, 2, 3).reshape(B, C, K, Ho, Wo)
        wk = w.reshape(O, Cg, K)
        if groups == 1:
            out = jnp.einsum("bckhw,ock->bohw", colk, wk)
        else:
            cg2 = C // groups
            og = O // groups
            colg = colk.reshape(B, groups, cg2, K, Ho, Wo)
            wg = wk.reshape(groups, og, Cg, K)
            out = jnp.einsum("bgckhw,gock->bgohw", colg, wg).reshape(
                B, O, Ho, Wo)
        return out

    args = (x, offset, weight) + ((mask,) if mask is not None else ())
    out = apply(f, *args)
    if bias is not None:
        out = apply(lambda o, b: o + b.reshape(1, -1, 1, 1), out, bias)
    return out


def psroi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
               output_channels=None):
    """Position-sensitive RoI average pooling (operators/detection/
    psroi_pool_op.cc): output channel c at bin (ph, pw) averages input
    channel (c*ph_total + ph)*pw_total + pw — the reference's
    CHANNEL-MAJOR block layout (psroi_pool_op.h:125).  boxes_num assigns
    rois to batch images like roi_align above."""
    ps = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)

    def f(v, rois):
        B, C, H, W = v.shape
        oc = output_channels or C // (ps[0] * ps[1])
        R = rois.shape[0]
        if boxes_num is not None:
            bn = jnp.asarray(_v(boxes_num))
            bidx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                              total_repeat_length=R).astype(jnp.int32)
        else:
            bidx = jnp.zeros((R,), jnp.int32)
        # input channel for (c, ph, pw): (c*ps0 + ph)*ps1 + pw
        cidx = ((jnp.arange(oc)[:, None, None] * ps[0]
                 + jnp.arange(ps[0])[None, :, None]) * ps[1]
                + jnp.arange(ps[1])[None, None, :])     # [oc,ph,pw]

        def one(r):
            roi = rois[r]
            img = v[bidx[r]]
            x1, y1, x2, y2 = [roi[i] * spatial_scale for i in range(4)]
            rh = jnp.maximum(y2 - y1, 0.1) / ps[0]
            rw = jnp.maximum(x2 - x1, 0.1) / ps[1]
            ys = jnp.arange(H, dtype=v.dtype)
            xs = jnp.arange(W, dtype=v.dtype)
            ph = jnp.arange(ps[0], dtype=v.dtype)
            pw = jnp.arange(ps[1], dtype=v.dtype)
            ys_in = (ys[None, :] >= jnp.floor(y1 + ph[:, None] * rh)) & \
                    (ys[None, :] < jnp.ceil(y1 + (ph[:, None] + 1) * rh))
            xs_in = (xs[None, :] >= jnp.floor(x1 + pw[:, None] * rw)) & \
                    (xs[None, :] < jnp.ceil(x1 + (pw[:, None] + 1) * rw))
            m = ys_in[:, None, :, None] & xs_in[None, :, None, :]
            cnt = jnp.maximum(m.sum((2, 3)), 1)            # [ph,pw]
            blocks = img[cidx]                             # [oc,ph,pw,H,W]
            val = (blocks * m[None]).sum((3, 4)) / cnt[None]
            return val                                     # [oc,ph,pw]

        return jax.vmap(one)(jnp.arange(R))

    return apply(f, x, boxes)


def prroi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
               samples=4):
    """Precise RoI pooling (operators/prroi_pool_op.cc): continuous
    average of the bilinearly-interpolated feature over each bin,
    computed here by dense sub-sampling (`samples`^2 points per bin — the
    integral-free approximation; exact closed-form integration is the
    reference's CUDA path).  boxes_num assigns rois to batch images."""
    ps = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)

    def f(v, rois):
        B, C, H, W = v.shape
        R = rois.shape[0]
        if boxes_num is not None:
            bn = jnp.asarray(_v(boxes_num))
            bidx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                              total_repeat_length=R).astype(jnp.int32)
        else:
            bidx = jnp.zeros((R,), jnp.int32)

        def bilinear(img, y, x_):
            y0 = jnp.floor(y); x0 = jnp.floor(x_)
            wy = y - y0; wx = x_ - x0

            def at(yy, xx):
                ok = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
                g = img[:, jnp.clip(yy, 0, H - 1).astype(jnp.int32),
                        jnp.clip(xx, 0, W - 1).astype(jnp.int32)]
                return jnp.where(ok, g, 0.0)

            return (at(y0, x0) * (1 - wy) * (1 - wx)
                    + at(y0, x0 + 1) * (1 - wy) * wx
                    + at(y0 + 1, x0) * wy * (1 - wx)
                    + at(y0 + 1, x0 + 1) * wy * wx)

        def one(r):
            roi = rois[r]
            img = v[bidx[r]]
            x1, y1, x2, y2 = [roi[i] * spatial_scale for i in range(4)]
            bh = (y2 - y1) / ps[0]
            bw = (x2 - x1) / ps[1]
            ph = jnp.arange(ps[0], dtype=v.dtype)
            pw = jnp.arange(ps[1], dtype=v.dtype)
            s = (jnp.arange(samples, dtype=v.dtype) + 0.5) / samples
            yy = y1 + (ph[:, None] + s[None, :]) * bh   # [ph, s]
            xx = x1 + (pw[:, None] + s[None, :]) * bw   # [pw, s]
            g = jax.vmap(lambda y: jax.vmap(
                lambda x_: bilinear(img, y, x_))(xx.reshape(-1)))(
                    yy.reshape(-1))
            # g [ph*s, pw*s, C] -> bins
            g = g.reshape(ps[0], samples, ps[1], samples, C)
            return g.mean((1, 3)).transpose(2, 0, 1)

        return jax.vmap(one)(jnp.arange(R))

    return apply(f, x, boxes)


def _np_rng():
    """numpy RandomState chained off the framework RNG so paddle.seed()
    reproduces host-side detection sampling (advisor r04: these kernels
    drew from the GLOBAL np.random state, which paddle.seed never
    touches — the reference seeds its sampling engine from the op seed
    attribute)."""
    from ..framework.random import np_random_state

    return np_random_state()


def rpn_target_assign(anchors, gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=False):
    """Anchor-GT assignment for RPN training (operators/detection/
    rpn_target_assign_op.cc, host-side like the reference CPU kernel):
    label 1 = fg (IoU >= positive_overlap or argmax per gt), 0 = bg
    (IoU < negative_overlap), -1 = ignore; subsample to batch size.
    Returns (loc_index, score_index, tgt_label, tgt_bbox)."""
    an = np.asarray(unwrap(anchors), np.float64).reshape(-1, 4)
    gt = np.asarray(unwrap(gt_boxes), np.float64).reshape(-1, 4)
    n = len(an)
    iou = _pairwise_iou_np(an, gt) if len(gt) else np.zeros((n, 1))
    best = iou.max(1) if len(gt) else np.zeros(n)
    argbest = iou.argmax(1) if len(gt) else np.zeros(n, int)
    label = -np.ones(n, np.int64)
    label[best < rpn_negative_overlap] = 0
    if len(gt):
        label[iou.argmax(0)] = 1          # best anchor per gt
        label[best >= rpn_positive_overlap] = 1
    fg = np.where(label == 1)[0]
    num_fg = int(rpn_fg_fraction * rpn_batch_size_per_im)
    rng = _np_rng() if use_random else None
    if len(fg) > num_fg:
        drop = fg[num_fg:] if not use_random else rng.choice(
            fg, len(fg) - num_fg, replace=False)
        label[drop] = -1
        fg = np.where(label == 1)[0]
    bg = np.where(label == 0)[0]
    num_bg = rpn_batch_size_per_im - len(fg)
    if len(bg) > num_bg:
        drop = bg[num_bg:] if not use_random else rng.choice(
            bg, len(bg) - num_bg, replace=False)
        label[drop] = -1
        bg = np.where(label == 0)[0]
    # bbox regression targets for fg anchors (box_coder encode_center_size)
    tgt = np.zeros((len(fg), 4), np.float32)
    for k, i in enumerate(fg):
        g = gt[argbest[i]]
        aw = an[i, 2] - an[i, 0] + 1.0
        ah = an[i, 3] - an[i, 1] + 1.0
        ax = an[i, 0] + aw / 2
        ay = an[i, 1] + ah / 2
        gw = g[2] - g[0] + 1.0
        gh = g[3] - g[1] + 1.0
        gx = g[0] + gw / 2
        gy = g[1] + gh / 2
        tgt[k] = [(gx - ax) / aw, (gy - ay) / ah,
                  np.log(gw / aw), np.log(gh / ah)]
    score_index = np.concatenate([fg, bg]).astype(np.int64)
    tgt_label = np.concatenate(
        [np.ones(len(fg), np.int64), np.zeros(len(bg), np.int64)])
    return (Tensor(fg.astype(np.int64)), Tensor(score_index),
            Tensor(tgt_label), Tensor(tgt))


def generate_proposal_labels(rpn_rois, gt_classes, gt_boxes,
                             batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.5, bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0, num_classes=81,
                             use_random=False):
    """Sample RoIs for the RCNN head (operators/detection/
    generate_proposal_labels_op.cc, host-side): fg if IoU>=fg_thresh
    (labeled with its gt class), bg if bg_thresh_lo<=IoU<bg_thresh_hi
    (label 0).  Returns (rois, labels, bbox_targets)."""
    rois = np.asarray(unwrap(rpn_rois), np.float64).reshape(-1, 4)
    gtc = np.asarray(unwrap(gt_classes)).ravel().astype(int)
    gtb = np.asarray(unwrap(gt_boxes), np.float64).reshape(-1, 4)
    rois = np.concatenate([rois, gtb], 0)  # gt boxes join the pool
    n = len(rois)
    iou = _pairwise_iou_np(rois, gtb) if len(gtb) else np.zeros((n, 1))
    best = iou.max(1) if len(gtb) else np.zeros(n)
    arg = iou.argmax(1) if len(gtb) else np.zeros(n, int)
    fg = np.where(best >= fg_thresh)[0]
    bg = np.where((best < bg_thresh_hi) & (best >= bg_thresh_lo))[0]
    num_fg = min(int(fg_fraction * batch_size_per_im), len(fg))
    num_bg = min(batch_size_per_im - num_fg, len(bg))
    if use_random:
        rng = _np_rng()
        fg = rng.permutation(fg)
        bg = rng.permutation(bg)
    fg, bg = fg[:num_fg], bg[:num_bg]
    keep = np.concatenate([fg, bg])
    labels = np.concatenate([gtc[arg[fg]], np.zeros(len(bg), int)])
    tgt = np.zeros((len(keep), 4), np.float32)
    for k, i in enumerate(fg):
        g = gtb[arg[i]]
        r = rois[i]
        rw = r[2] - r[0] + 1.0
        rh = r[3] - r[1] + 1.0
        rx, ry = r[0] + rw / 2, r[1] + rh / 2
        gw = g[2] - g[0] + 1.0
        gh = g[3] - g[1] + 1.0
        gx, gy = g[0] + gw / 2, g[1] + gh / 2
        tgt[k] = [(gx - rx) / rw, (gy - ry) / rh,
                  np.log(gw / rw), np.log(gh / rh)]
    return (Tensor(rois[keep].astype(np.float32)),
            Tensor(labels.astype(np.int64)), Tensor(tgt))


def retinanet_detection_output(bboxes, scores, anchors, im_info=None,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.5,
                               nms_eta=1.0):
    """RetinaNet post-processing (operators/detection/
    retinanet_detection_output_op.cc): decode per-level deltas against
    anchors, threshold scores, NMS per class, keep top-k overall.
    bboxes/scores/anchors: lists per FPN level ([A,4] deltas [A,C]
    scores [A,4] anchors).  Host-side like the reference CPU kernel."""
    all_boxes, all_scores = [], []
    for dl, sc, an in zip(bboxes, scores, anchors):
        d = np.asarray(unwrap(dl), np.float64).reshape(-1, 4)
        s = np.asarray(unwrap(sc), np.float64)
        a = np.asarray(unwrap(an), np.float64).reshape(-1, 4)
        aw = a[:, 2] - a[:, 0] + 1.0
        ah = a[:, 3] - a[:, 1] + 1.0
        ax = a[:, 0] + aw / 2
        ay = a[:, 1] + ah / 2
        cx = d[:, 0] * aw + ax
        cy = d[:, 1] * ah + ay
        w = np.exp(np.clip(d[:, 2], -10, 10)) * aw
        h = np.exp(np.clip(d[:, 3], -10, 10)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2, cy + h / 2], 1)
        all_boxes.append(boxes)
        all_scores.append(s)
    boxes = np.concatenate(all_boxes, 0)
    scores_c = np.concatenate(all_scores, 0)
    C = scores_c.shape[1]
    out = []
    for c in range(C):
        s = scores_c[:, c]
        keep = s > score_threshold
        if not keep.any():
            continue
        b, s = boxes[keep], s[keep]
        order = np.argsort(-s)[:nms_top_k]
        b, s = b[order], s[order]
        picked = _greedy_nms_numpy(b, s, nms_threshold)
        for i in picked:
            out.append([c, s[i], *b[i]])
    out = sorted(out, key=lambda r: -r[1])[:keep_top_k]
    return Tensor(np.asarray(out, np.float32).reshape(-1, 6))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh=0.7, downsample_ratio=32, gt_score=None,
              use_label_smooth=False):
    """YOLOv3 training loss (operators/detection/yolov3_loss_op.h):
    x [B, A*(5+C), H, W] raw head output; gt_box [B,G,4] (cx,cy,w,h in
    [0,1] image units), gt_label [B,G].  Objectness uses the best-anchor
    assignment rule; predictions overlapping any gt above ignore_thresh
    are excluded from the no-object loss."""
    am = list(anchor_mask)
    A = len(am)

    def f(xv, gb, gl, gs):
        B, _, H, W = xv.shape
        C = class_num
        p = xv.reshape(B, A, 5 + C, H, W)
        px_l, py_l = p[:, :, 0], p[:, :, 1]  # raw logits (loss is SCE)
        px, py = jax.nn.sigmoid(px_l), jax.nn.sigmoid(py_l)  # decoded
        pw, ph = p[:, :, 2], p[:, :, 3]
        pobj = p[:, :, 4]
        pcls = p[:, :, 5:]
        G = gb.shape[1]
        anc = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
        anc_m = anc[jnp.asarray(am)]
        in_w, in_h = W * downsample_ratio, H * downsample_ratio

        # gt in grid units
        gx = gb[:, :, 0] * W
        gy = gb[:, :, 1] * H
        gw = gb[:, :, 2] * in_w
        gh = gb[:, :, 3] * in_h
        gi = jnp.clip(gx.astype(jnp.int32), 0, W - 1)
        gj = jnp.clip(gy.astype(jnp.int32), 0, H - 1)
        valid = (gb[:, :, 2] > 0) & (gb[:, :, 3] > 0)

        # best anchor (over the FULL anchor set, reference rule) per gt
        def wh_iou(w1, h1, w2, h2):
            inter = jnp.minimum(w1, w2) * jnp.minimum(h1, h2)
            return inter / (w1 * h1 + w2 * h2 - inter + 1e-9)

        ious_a = wh_iou(gw[..., None], gh[..., None],
                        anc[:, 0][None, None],
                        anc[:, 1][None, None])             # [B,G,Atot]
        best_a = ious_a.argmax(-1)                         # [B,G]
        # responsible only if best anchor is in this level's mask
        mask_arr = jnp.asarray(am)
        resp_slot = (best_a[..., None] == mask_arr[None, None])  # [B,G,A]
        resp = resp_slot.any(-1) & valid

        obj_tgt = jnp.zeros((B, A, H, W))
        loss_xy = loss_wh = loss_cls = 0.0
        bidx = jnp.arange(B)[:, None]
        slot = resp_slot.argmax(-1)                        # [B,G]
        # scatter per-gt losses (stop-gradient-free, masked sums)
        tx = gx - jnp.floor(gx)
        ty = gy - jnp.floor(gy)
        tw = jnp.log(jnp.maximum(gw, 1e-9) /
                     jnp.maximum(anc_m[slot][..., 0], 1e-9))
        th = jnp.log(jnp.maximum(gh, 1e-9) /
                     jnp.maximum(anc_m[slot][..., 1], 1e-9))
        scale = 2.0 - gb[:, :, 2] * gb[:, :, 3]  # small-box upweight
        pxl_g = px_l[bidx, slot, gj, gi]
        pyl_g = py_l[bidx, slot, gj, gi]
        pw_g = pw[bidx, slot, gj, gi]
        ph_g = ph[bidx, slot, gj, gi]
        # every per-gt term is scaled by gt_score (mixup weighting,
        # yolov3_loss_op.h CalcBoxLocationLoss/CalcLabelLoss)
        m = resp.astype(jnp.float32) * scale * gs
        bce = lambda z, t: jnp.maximum(z, 0) - z * t + jnp.log1p(  # noqa
            jnp.exp(-jnp.abs(z)))
        # x/y: sigmoid cross-entropy on RAW logits vs tx/ty; w/h: L1 —
        # the reference kernel's exact loss shapes (yolov3_loss_op.h
        # CalcBoxLocationLoss), not squared error (advisor r04, medium)
        loss_xy = (m * (bce(pxl_g, tx) + bce(pyl_g, ty))).sum()
        loss_wh = (m * (jnp.abs(pw_g - tw) + jnp.abs(ph_g - th))).sum()
        cls_logit = pcls[bidx, slot, :, gj, gi]            # [B,G,C]
        smooth = 1.0 / C if use_label_smooth else 0.0
        tgt_cls = jax.nn.one_hot(gl, C) * (1 - 2 * smooth) + smooth
        loss_cls = ((resp.astype(jnp.float32) * gs)[..., None]
                    * bce(cls_logit, tgt_cls)).sum()
        obj_tgt = obj_tgt.at[bidx, slot, gj, gi].max(
            resp.astype(jnp.float32) * gs)

        # ignore mask: predicted boxes with IoU>thresh vs any gt
        cell_x = (jnp.arange(W)[None, None, None] + px) / W
        cell_y = (jnp.arange(H)[None, None, :, None] + py) / H
        bw = jnp.exp(jnp.clip(pw, -10, 10)) * anc_m[:, 0][None, :, None,
                                                          None] / in_w
        bh = jnp.exp(jnp.clip(ph, -10, 10)) * anc_m[:, 1][None, :, None,
                                                          None] / in_h

        def box_iou_xywh(x1, y1, w1, h1, x2, y2, w2, h2):
            l = jnp.maximum(x1 - w1 / 2, x2 - w2 / 2)   # noqa: E741
            r = jnp.minimum(x1 + w1 / 2, x2 + w2 / 2)
            t = jnp.maximum(y1 - h1 / 2, y2 - h2 / 2)
            b = jnp.minimum(y1 + h1 / 2, y2 + h2 / 2)
            inter = jnp.maximum(r - l, 0) * jnp.maximum(b - t, 0)
            return inter / (w1 * h1 + w2 * h2 - inter + 1e-9)

        iou_pg = box_iou_xywh(
            cell_x[..., None], cell_y[..., None], bw[..., None],
            bh[..., None],
            gb[:, None, None, None, :, 0], gb[:, None, None, None, :, 1],
            gb[:, None, None, None, :, 2], gb[:, None, None, None, :, 3])
        iou_best = jnp.where(valid[:, None, None, None],
                             iou_pg, 0.0).max(-1)
        noobj_ok = (iou_best < ignore_thresh).astype(jnp.float32)
        # positives: SCE against the (score-valued) target — reference
        # CalcObjnessLoss uses the mixup score as the objectness target
        pos = (obj_tgt > 0).astype(jnp.float32)
        loss_obj = (pos * bce(pobj, obj_tgt)
                    + (1 - pos) * noobj_ok
                    * bce(pobj, jnp.zeros_like(pobj))).sum()
        return (loss_xy + loss_wh + loss_cls + loss_obj) / B

    if gt_score is None:
        ones = jnp.ones(np.shape(unwrap(gt_label)), jnp.float32)
        return apply(f, x, gt_box, gt_label, ones)
    return apply(f, x, gt_box, gt_label, gt_score)


def random_crop(x, shape, seed=None):
    """Random spatial crop (random_crop_op.cc): crop the trailing dims of
    x to `shape` at a uniformly random offset.  Offsets come from the
    framework RNG chain (paddle.seed reproduces them) unless `seed` pins
    a local key."""
    import jax

    from ..framework import random as _random

    def f(v):
        tgt = list(shape)
        nlead = v.ndim - len(tgt)
        if seed is not None:
            keys = list(jax.random.split(jax.random.PRNGKey(int(seed)),
                                         len(tgt)))
        else:
            k = _random.split_key(len(tgt))
            keys = list(k) if isinstance(k, (list, tuple)) else [k]
        out = v
        for d, t in enumerate(tgt):
            limit = out.shape[nlead + d] - t
            off = jax.random.randint(keys[d], (), 0,
                                     limit + 1) if limit > 0 else 0
            out = jax.lax.dynamic_slice_in_dim(out, off, t, nlead + d)
        return out

    return apply(f, x)
