"""paddle.vision.ops — detection operators.

Reference parity: paddle/fluid/operators/detection/ (~40 CUDA/C++ ops,
SURVEY.md §2.4) — the subset modern detectors actually use: nms,
multiclass_nms, roi_align, roi_pool, yolo_box, box_coder, prior_box, plus
box_iou/box_area helpers (operators/detection/{multiclass_nms_op.cc,
roi_align_op.cc, yolo_box_op.cc, box_coder_op.cc, prior_box_op.cc,
iou_similarity_op.cc}).

TPU disposition: everything is expressed with static shapes so it jits —
NMS is a fixed-trip-count `lax.fori_loop` producing a keep mask (no
dynamic-size outputs; callers slice by `keep_num`), RoI align is a
vectorized bilinear gather, decoders are pure elementwise. No dynamic
boxes-count recompilation as long as inputs are padded to a fixed N.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor, apply, unwrap

__all__ = ["box_area", "box_iou", "nms", "multiclass_nms", "roi_align",
           "roi_pool", "yolo_box", "box_coder", "prior_box"]


def _v(x):
    return unwrap(x)


def box_area(boxes):
    """[N,4] xyxy -> [N] (detection/iou_similarity_op.h area)."""
    def f(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return apply(f, boxes)


def _pairwise_iou(a, b):
    """jnp-level [N,4]x[M,4] -> [N,M] IoU (single implementation shared by
    box_iou and the NMS mask)."""
    area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / (area1[:, None] + area2[None, :] - inter + 1e-10)


def box_iou(boxes1, boxes2):
    """[N,4] x [M,4] xyxy -> [N,M] IoU (iou_similarity_op.cc)."""
    return apply(_pairwise_iou, boxes1, boxes2)


def _nms_mask(boxes, scores, iou_threshold):
    """Greedy NMS as a keep mask over a FIXED N (the jit-safe variant for
    compiled detector steps)."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    pair_iou = _pairwise_iou(b, b)

    def body(i, keep):
        # suppress j>i overlapping a kept i
        row = (pair_iou[i] > iou_threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~row

    keep_sorted = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # scatter back to original indexing
    keep = jnp.zeros((n,), bool).at[order].set(keep_sorted)
    return keep


def _greedy_nms_numpy(b, s, iou_threshold):
    """Host-side greedy NMS — no XLA compile per distinct box count."""
    order = np.argsort(-s)
    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    area = (x2 - x1) * (y2 - y1)
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        rest = order[1:]
        xx1 = np.maximum(x1[i], x1[rest])
        yy1 = np.maximum(y1[i], y1[rest])
        xx2 = np.minimum(x2[i], x2[rest])
        yy2 = np.minimum(y2[i], y2[rest])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        iou = inter / (area[i] + area[rest] - inter + 1e-10)
        order = rest[iou <= iou_threshold]
    return np.asarray(keep, np.int64)


def nms(boxes, scores=None, iou_threshold=0.3, top_k=None):
    """Greedy hard NMS (eager/host path). Returns kept indices sorted by
    descending score (reference nms op); jit callers use the static-shape
    mask variant paddle.vision.ops._nms_mask."""
    b = np.asarray(_v(boxes))
    s = (np.asarray(_v(scores)) if scores is not None
         else np.arange(len(b), 0, -1, dtype=np.float32))
    idx = _greedy_nms_numpy(b, s, iou_threshold)
    if top_k is not None:
        idx = idx[:top_k]
    return Tensor(idx)


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, background_label=0):
    """Per-class NMS + global top-k (multiclass_nms_op.cc semantics,
    single image). bboxes [N,4], scores [C,N]. Returns [M,6]
    (label, score, x1, y1, x2, y2).  background_label defaults to 0 like
    the reference op (class row 0 = background is skipped); pass -1 to
    keep every class."""
    b = np.asarray(_v(bboxes))
    s = np.asarray(_v(scores))
    out = []
    for c in range(s.shape[0]):
        if c == background_label:
            continue
        mask = s[c] > score_threshold
        if not mask.any():
            continue
        cb, cs = b[mask], s[c][mask]
        ord_ = np.argsort(-cs)[:nms_top_k]
        cb, cs = cb[ord_], cs[ord_]
        kept = np.asarray(nms(cb, cs, nms_threshold).numpy())
        for i in kept:
            out.append([c, cs[i], *cb[i]])
    if not out:
        return Tensor(np.zeros((0, 6), np.float32))
    out = np.asarray(out, np.float32)
    out = out[np.argsort(-out[:, 1])][:keep_top_k]
    return Tensor(out)


def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign (roi_align_op.cc): x [N,C,H,W], boxes [R,4] xyxy in input
    coords, boxes assumed on image 0 unless boxes_num splits them.
    Bilinear-gather implementation — pure XLA, grads for free."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(xv, bv):
        xv = jnp.asarray(xv)
        bv = jnp.asarray(bv)
        N, C, H, W = xv.shape
        R = bv.shape[0]
        # batch index per roi — traced-safe: jnp.repeat with a static
        # total length, so boxes_num may be a tracer under jit
        if boxes_num is not None:
            bn = jnp.asarray(_v(boxes_num))
            bidx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                              total_repeat_length=R).astype(jnp.int32)
        else:
            bidx = jnp.zeros((R,), jnp.int32)
        offset = 0.5 if aligned else 0.0
        x1 = bv[:, 0] * spatial_scale - offset
        y1 = bv[:, 1] * spatial_scale - offset
        x2 = bv[:, 2] * spatial_scale - offset
        y2 = bv[:, 3] * spatial_scale - offset
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: [R, oh*sr, ow*sr]
        ys = (y1[:, None] + (jnp.arange(oh * sr) + 0.5)[None, :]
              * rh[:, None] / (oh * sr))
        xs = (x1[:, None] + (jnp.arange(ow * sr) + 0.5)[None, :]
              * rw[:, None] / (ow * sr))

        def bilinear(img, yy, xx):
            # img [C,H,W]; yy [P], xx [Q] -> [C,P,Q]
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)
            wy = jnp.clip(yy, 0, H - 1) - y0
            wx = jnp.clip(xx, 0, W - 1) - x0
            v00 = img[:, y0i][:, :, x0i]
            v01 = img[:, y0i][:, :, x1i]
            v10 = img[:, y1i][:, :, x0i]
            v11 = img[:, y1i][:, :, x1i]
            return (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                    + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
                    + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
                    + v11 * wy[None, :, None] * wx[None, None, :])

        def per_roi(r):
            img = xv[bidx[r]]
            samples = bilinear(img, ys[r], xs[r])  # [C, oh*sr, ow*sr]
            return samples.reshape(C, oh, sr, ow, sr).mean((2, 4))

        return jax.vmap(per_roi)(jnp.arange(R))

    return apply(f, x, boxes)


def roi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0):
    """RoIPool (roi_pool_op.cc) via dense-sampled max."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(xv, bv):
        xv = jnp.asarray(xv)
        bv = jnp.asarray(bv)
        N, C, H, W = xv.shape
        R = bv.shape[0]
        if boxes_num is not None:
            bn = jnp.asarray(_v(boxes_num))
            bidx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                              total_repeat_length=R).astype(jnp.int32)
        else:
            bidx = jnp.zeros((R,), jnp.int32)
        sr = 4  # dense samples per output cell edge

        def per_roi(r):
            x1 = bv[r, 0] * spatial_scale
            y1 = bv[r, 1] * spatial_scale
            x2 = jnp.maximum(bv[r, 2] * spatial_scale, x1 + 1)
            y2 = jnp.maximum(bv[r, 3] * spatial_scale, y1 + 1)
            ys = jnp.clip(y1 + (jnp.arange(oh * sr) + 0.5) * (y2 - y1)
                          / (oh * sr), 0, H - 1).astype(jnp.int32)
            xs = jnp.clip(x1 + (jnp.arange(ow * sr) + 0.5) * (x2 - x1)
                          / (ow * sr), 0, W - 1).astype(jnp.int32)
            img = xv[bidx[r]]
            samples = img[:, ys][:, :, xs]
            return samples.reshape(C, oh, sr, ow, sr).max((2, 4))

        return jax.vmap(per_roi)(jnp.arange(R))

    return apply(f, x, boxes)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """Decode YOLO head output (yolo_box_op.cc): x [N, A*(5+C), H, W],
    img_size [N,2] (h,w). Returns (boxes [N, A*H*W, 4] xyxy,
    scores [N, A*H*W, C])."""
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    A = anchors.shape[0]

    def f(xv, imgv):
        N, _, H, W = xv.shape
        xv = xv.reshape(N, A, 5 + class_num, H, W)
        gx = (jnp.arange(W))[None, None, None, :]
        gy = (jnp.arange(H))[None, None, :, None]
        alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
        cx = (jax.nn.sigmoid(xv[:, :, 0]) * alpha + beta + gx) / W
        cy = (jax.nn.sigmoid(xv[:, :, 1]) * alpha + beta + gy) / H
        anc = jnp.asarray(anchors)
        pw = anc[None, :, 0, None, None] * jnp.exp(xv[:, :, 2]) \
            / (downsample_ratio * W)
        ph = anc[None, :, 1, None, None] * jnp.exp(xv[:, :, 3]) \
            / (downsample_ratio * H)
        conf = jax.nn.sigmoid(xv[:, :, 4])
        cls = jax.nn.sigmoid(xv[:, :, 5:]) * conf[:, :, None]
        cls = jnp.where(conf[:, :, None] >= conf_thresh, cls, 0.0)
        imh = imgv[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = imgv[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - pw / 2) * imw
        y1 = (cy - ph / 2) * imh
        x2 = (cx + pw / 2) * imw
        y2 = (cy + ph / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(N, -1, 4)
        scores = jnp.moveaxis(cls, 2, -1).reshape(N, -1, class_num)
        return boxes, scores

    return apply(f, x, img_size, _multi_out=True)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0):
    """Encode/decode boxes against priors (box_coder_op.cc).

    Encode: priors [N,4], targets [N,4] -> [N,4] deltas.
    Decode: priors [N,4] broadcast into targets [N,M,4] along `axis`
    (axis=0: priors vary along dim 0; axis=1: along dim 1 — the reference's
    per-class decode shape); 2-D targets decode elementwise.
    """
    def f(pb, pbv, tb):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[..., 2] - pb[..., 0] + norm
        ph = pb[..., 3] - pb[..., 1] + norm
        pcx = pb[..., 0] + pw / 2
        pcy = pb[..., 1] + ph / 2
        if code_type == "encode_center_size":
            tw = tb[..., 2] - tb[..., 0] + norm
            th = tb[..., 3] - tb[..., 1] + norm
            tcx = tb[..., 0] + tw / 2
            tcy = tb[..., 1] + th / 2
            dx = (tcx - pcx) / pw / pbv[..., 0]
            dy = (tcy - pcy) / ph / pbv[..., 1]
            dw = jnp.log(tw / pw) / pbv[..., 2]
            dh = jnp.log(th / ph) / pbv[..., 3]
            return jnp.stack([dx, dy, dw, dh], -1)
        # decode — broadcast [N,4] priors against [N,M,4] targets per axis
        if tb.ndim == 3:
            exp = 1 if axis == 0 else 0
            pw, ph, pcx, pcy = (jnp.expand_dims(v, exp)
                                for v in (pw, ph, pcx, pcy))
            pbv_b = jnp.expand_dims(pbv, exp)
        else:
            pbv_b = pbv
        dcx = pbv_b[..., 0] * tb[..., 0] * pw + pcx
        dcy = pbv_b[..., 1] * tb[..., 1] * ph + pcy
        dw = jnp.exp(pbv_b[..., 2] * tb[..., 2]) * pw
        dh = jnp.exp(pbv_b[..., 3] * tb[..., 3]) * ph
        return jnp.stack([dcx - dw / 2, dcy - dh / 2,
                          dcx + dw / 2 - norm, dcy + dh / 2 - norm], -1)

    return apply(f, prior_box, prior_box_var, target_box)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5):
    """SSD prior (anchor) boxes (prior_box_op.cc). input [N,C,H,W] feature
    map, image [N,C,IH,IW]. Returns (boxes [H,W,A,4], variances same)."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]
    # reference anchor ordering (prior_box_op.h): per min_size emit
    # [min, aspect-ratio anchors, max] — heads trained against paddle
    # depend on this exact order
    boxes = []
    for k, s in enumerate(min_sizes):
        boxes.append((s, s))
        for a in ars:
            if a == 1.0:
                continue
            boxes.append((s * np.sqrt(a), s / np.sqrt(a)))
        if max_sizes:
            smax = max_sizes[k]
            boxes.append((np.sqrt(s * smax),) * 2)
    A = len(boxes)
    wh = np.asarray(boxes, np.float32)  # [A,2]
    cx = (np.arange(fw) + offset) * step_w
    cy = (np.arange(fh) + offset) * step_h
    out = np.zeros((fh, fw, A, 4), np.float32)
    out[..., 0] = (cx[None, :, None] - wh[None, None, :, 0] / 2) / iw
    out[..., 1] = (cy[:, None, None] - wh[None, None, :, 1] / 2) / ih
    out[..., 2] = (cx[None, :, None] + wh[None, None, :, 0] / 2) / iw
    out[..., 3] = (cy[:, None, None] + wh[None, None, :, 1] / 2) / ih
    if clip:
        out = np.clip(out, 0, 1)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return Tensor(out), Tensor(var)
