"""Vision transforms package (reference python/paddle/vision/transforms/):
class transforms in .transforms, host-side functional ops in
.functional; both surfaces re-exported here."""
from . import functional  # noqa: F401
from . import transforms  # noqa: F401
from .functional import (  # noqa: F401
    adjust_brightness, adjust_contrast, adjust_hue, center_crop, crop,
    hflip, normalize, pad, resize, rotate, to_grayscale, to_tensor, vflip)
from .transforms import (  # noqa: F401
    BaseTransform, CenterCrop, Compose, Normalize, RandomCrop,
    RandomHorizontalFlip, Resize, ToTensor, Transpose)
