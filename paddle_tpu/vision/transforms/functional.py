"""paddle.vision.transforms.functional — host-side image ops.

Reference parity: python/paddle/vision/transforms/functional.py:39
(to_tensor, hflip, vflip, resize, pad, rotate, to_grayscale, crop,
center_crop, adjust_brightness/contrast/hue, normalize).  Operates on
PIL images or numpy HWC arrays — preprocessing stays on the host (it
feeds the device prefetch pipeline, not XLA).
"""
from __future__ import annotations

import numbers

import numpy as np

__all__ = ["to_tensor", "hflip", "vflip", "resize", "pad", "rotate",
           "to_grayscale", "crop", "center_crop", "adjust_brightness",
           "adjust_contrast", "adjust_hue", "normalize"]


def _is_pil(img):
    try:
        from PIL import Image
        return isinstance(img, Image.Image)
    except ImportError:
        return False


def _to_pil(img):
    from PIL import Image
    if _is_pil(img):
        return img
    arr = np.asarray(img)
    if arr.dtype != np.uint8:
        arr = np.clip(arr, 0, 255).astype(np.uint8)
    return Image.fromarray(arr)


def to_tensor(pic, data_format="CHW"):
    """PIL/HWC-ndarray -> float32, CHW (or HWC) layout.  Rescales by
    1/255 iff the input is 8-bit (PIL or uint8 ndarray) — dtype-based
    like the reference, so a near-black uint8 image normalizes the same
    as a bright one."""
    was_uint8 = _is_pil(pic) or np.asarray(pic).dtype == np.uint8
    arr = np.asarray(pic, np.float32)
    if was_uint8:
        arr = arr / 255.0
    if arr.ndim == 2:
        arr = arr[..., None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return arr


def hflip(img):
    if _is_pil(img):
        from PIL import Image
        return img.transpose(Image.FLIP_LEFT_RIGHT)
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    if _is_pil(img):
        from PIL import Image
        return img.transpose(Image.FLIP_TOP_BOTTOM)
    return np.asarray(img)[::-1].copy()


_PIL_INTERP = {"nearest": 0, "bilinear": 2, "bicubic": 3, "lanczos": 1}


def resize(img, size, interpolation="bilinear"):
    """size: int (short side) or (h, w)."""
    pil = _to_pil(img)
    w, h = pil.size
    if isinstance(size, int):
        if (w <= h and w == size) or (h <= w and h == size):
            out = pil
        elif w < h:
            out = pil.resize((size, int(size * h / w)),
                             _PIL_INTERP[interpolation])
        else:
            out = pil.resize((int(size * w / h), size),
                             _PIL_INTERP[interpolation])
    else:
        oh, ow = size
        out = pil.resize((ow, oh), _PIL_INTERP[interpolation])
    return out if _is_pil(img) else np.asarray(out)


def pad(img, padding, fill=0, padding_mode="constant"):
    """padding: int | (pad_lr, pad_tb) | (l, t, r, b)."""
    arr = np.asarray(img)
    was_pil = _is_pil(img)
    if isinstance(padding, numbers.Number):
        l = t = r = b = int(padding)  # noqa: E741
    elif len(padding) == 2:
        l = r = int(padding[0])  # noqa: E741
        t = b = int(padding[1])
    else:
        l, t, r, b = (int(p) for p in padding)  # noqa: E741
    spec = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    if padding_mode == "constant":
        out = np.pad(arr, spec, constant_values=fill)
    else:
        mode = {"edge": "edge", "reflect": "reflect",
                "symmetric": "symmetric"}[padding_mode]
        out = np.pad(arr, spec, mode=mode)
    return _to_pil(out) if was_pil else out


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    pil = _to_pil(img)
    out = pil.rotate(angle, resample=_PIL_INTERP.get(interpolation, 0),
                     expand=expand, center=center, fillcolor=fill)
    return out if _is_pil(img) else np.asarray(out)


def to_grayscale(img, num_output_channels=1):
    pil = _to_pil(img).convert("L")
    if num_output_channels == 3:
        arr = np.asarray(pil)
        out = np.stack([arr] * 3, -1)
        return _to_pil(out) if _is_pil(img) else out
    return pil if _is_pil(img) else np.asarray(pil)


def crop(img, top, left, height, width):
    arr = np.asarray(img)
    out = arr[top:top + height, left:left + width]
    return _to_pil(out) if _is_pil(img) else out


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = np.asarray(img)
    h, w = arr.shape[0], arr.shape[1]
    th, tw = output_size
    return crop(img, max((h - th) // 2, 0), max((w - tw) // 2, 0), th, tw)


def _enhance(img, factor, enhancer_name):
    from PIL import ImageEnhance
    pil = _to_pil(img)
    out = getattr(ImageEnhance, enhancer_name)(pil).enhance(factor)
    return out if _is_pil(img) else np.asarray(out)


def adjust_brightness(img, brightness_factor):
    return _enhance(img, brightness_factor, "Brightness")


def adjust_contrast(img, contrast_factor):
    return _enhance(img, contrast_factor, "Contrast")


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor in [-0.5, 0.5] via HSV rotation."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError(f"hue_factor {hue_factor} not in [-0.5, 0.5]")
    pil = _to_pil(img)
    hsv = np.asarray(pil.convert("HSV")).copy()
    hsv[..., 0] = (hsv[..., 0].astype(np.int16)
                   + int(hue_factor * 255)) % 256
    from PIL import Image
    out = Image.fromarray(hsv, "HSV").convert("RGB")
    return out if _is_pil(img) else np.asarray(out)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (arr - mean[:, None, None]) / std[:, None, None]
    return (arr - mean) / std
