"""Vision transforms. Reference: python/paddle/vision/transforms (functional
numpy/PIL pipeline) — host-side preprocessing stays numpy (it feeds the
device prefetch pipeline, not XLA)."""
from __future__ import annotations

import numbers

import numpy as np

from ...io import _host_rng


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        from .functional import to_tensor
        arr = np.asarray(img)
        if arr.ndim == 3 and arr.shape[-1] not in (1, 3, 4):
            # already CHW-ish input: only dtype-normalize
            out = arr.astype(np.float32)
            return out / 255.0 if arr.dtype == np.uint8 else out
        return to_tensor(img, data_format=self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        c = arr.shape[0] if self.data_format == "CHW" else arr.shape[-1]
        mean = self.mean[:c]
        std = self.std[:c]
        if self.data_format == "CHW":
            return (arr - mean[:, None, None]) / std[:, None, None]
        return (arr - mean) / std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        # keep the input dtype: uint8 in -> uint8 out, so a downstream
        # ToTensor still sees 8-bit data and rescales by 1/255
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        oh, ow = self.size
        ih, iw = arr.shape[h_ax], arr.shape[w_ax]
        yi = (np.arange(oh) * ih / oh).astype(np.int64).clip(0, ih - 1)
        xi = (np.arange(ow) * iw / ow).astype(np.int64).clip(0, iw - 1)
        arr = np.take(arr, yi, axis=h_ax)
        arr = np.take(arr, xi, axis=w_ax)
        return arr


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        # framework RNG chain: paddle.seed reproduces augmentation
        if _host_rng().rand() < self.prob:
            arr = np.asarray(img)
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
            return arr[..., ::-1].copy() if not chw else arr[:, :, ::-1].copy()
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0, keys=None, **kw):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        if self.padding:
            pad = [(0, 0)] * arr.ndim
            pad[h_ax] = (self.padding, self.padding)
            pad[w_ax] = (self.padding, self.padding)
            arr = np.pad(arr, pad)
        th, tw = self.size
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        rng = _host_rng()
        y = rng.randint(0, max(h - th, 0) + 1)
        x = rng.randint(0, max(w - tw, 0) + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(y, y + th)
        sl[w_ax] = slice(x, x + tw)
        return arr[tuple(sl)]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        th, tw = self.size
        y = max((arr.shape[h_ax] - th) // 2, 0)
        x = max((arr.shape[w_ax] - tw) // 2, 0)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(y, y + th)
        sl[w_ax] = slice(x, x + tw)
        return arr[tuple(sl)]


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)
