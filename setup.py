"""Build hook: compile the native runtime core into the wheel.

Reference: python/setup.py.in (the reference's setup links libpaddle with
its C++ core; here the analogous artifact is csrc/core.cc compiled to
paddle_tpu/core/libpaddle_tpu_core.so and shipped as package data —
ctypes loads it at import, no python C-extension ABI involved).
Metadata (name, deps, console scripts incl. fleetrun) lives in
pyproject.toml.
"""
import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNativeCore(build_py):
    def run(self):
        root = os.path.dirname(os.path.abspath(__file__))
        src = os.path.join(root, "csrc", "core.cc")
        out = os.path.join(root, "paddle_tpu", "core",
                           "libpaddle_tpu_core.so")
        if os.path.exists(src):
            try:
                subprocess.run(
                    ["g++", "-O2", "-std=c++17", "-fPIC", "-Wall",
                     "-pthread", "-shared", "-o", out, src], check=True)
            except (OSError, subprocess.CalledProcessError) as e:
                # package still works: paddle_tpu.core falls back to its
                # pure-python paths when the .so is absent
                print(f"WARNING: native core build skipped: {e}")
        super().run()


setup(cmdclass={"build_py": BuildWithNativeCore})
