"""PTA001 fixture: every zero-copy materialization face, flagged."""
import numpy as np


def materialize_leaf(x):
    return np.asarray(x)  # FINDING: zero-copy view


def read_bytes(raw, dt):
    return np.frombuffer(raw, dtype=dt)  # FINDING: view escapes


def alias_explicitly(x):
    return np.array(x, copy=False)  # FINDING: explicit alias
