"""PTA001 near-misses: owning copies and immediately-copied views."""
import numpy as np


def materialize_leaf(x):
    return np.array(x, copy=True)


def read_bytes(raw, dt):
    return np.frombuffer(raw, dtype=dt).copy()


def plain_array(x):
    return np.array(x)
