import jax


def snapshot(state):
    return jax.device_get(state)


def write_disk(payload):
    with open("/dev/null", "wb") as fh:
        fh.write(repr(payload).encode())
