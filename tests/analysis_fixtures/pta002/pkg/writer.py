"""PTA002 fixture: a jax-free writer root whose call chain reaches jax."""
from . import helpers


# pta: jax-free
def writer_loop(state):
    payload = helpers.snapshot(state)  # FINDING: chain reaches jax
    helpers.write_disk(payload)
