"""PTA002 near-miss: a jax-free root that only touches host helpers."""
from . import helpers


# pta: jax-free
def writer_loop(payload):
    helpers.write_disk(payload)
