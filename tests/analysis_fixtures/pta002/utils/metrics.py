"""PTA002 module fixture: utils/metrics.py must stay jax-free."""
import jax  # FINDING: jax import in a jax-free module


def record(value):
    return jax.numpy.asarray(value)
