"""PTA003 fixture: a registered handler that logs, locks, and calls a
same-module helper that prints."""
import logging
import signal
import threading

logger = logging.getLogger(__name__)
_lock = threading.Lock()


def _flush():
    print("flushing")  # FINDING (reached via handler -> _flush)


def handler(signum, frame):
    logger.warning("got signal %s", signum)  # FINDING: logs
    with _lock:  # FINDING: acquires a lock
        _flush()


signal.signal(signal.SIGTERM, handler)
