"""PTA003 near-miss: the one-int-mailbox pattern, plus an unregistered
function that logs (logging is fine OUTSIDE handler reachability)."""
import logging
import signal

logger = logging.getLogger(__name__)
_pending = 0


def handler(signum, frame):
    global _pending
    _pending = signum  # latch only — no locks, no logging


def poll():
    global _pending
    if _pending:
        logger.warning("acting on deferred signal %s", _pending)
        _pending = 0


signal.signal(signal.SIGTERM, handler)
