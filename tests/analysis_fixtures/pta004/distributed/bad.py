"""PTA004 fixture: per-process early exits ahead of a collective."""
import os


def save(path, state, allgather):
    if os.path.exists(os.path.join(path, "COMMIT")):
        return None  # FINDING: fs probe diverges across hosts
    merged = allgather(state)
    return merged
