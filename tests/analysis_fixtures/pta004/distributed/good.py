"""PTA004 near-misses: single-process-gated exit and a uniform gate."""
import os


def save(self, path, state, allgather):
    if self._single_process and os.path.exists(
            os.path.join(path, "COMMIT")):
        return None  # gated: only ever taken when there are no peers
    merged = allgather(state)
    return merged


def save_every(step, interval, state, allgather):
    if step % interval:
        return None  # uniform arithmetic on arguments — same on all hosts
    return allgather(state)
