"""PTA005 fixture: implicit device→host syncs inside engine hot paths."""
import numpy as np


class TrainEngine:
    def step(self, state, loss):
        lossf = float(loss)  # FINDING: per-step sync
        arr = np.asarray(state)  # FINDING: blocking conversion
        return lossf, arr


# pta: hot-path
def dispatch_batch(out):
    return out.item()  # FINDING: sync in a marked hot path
