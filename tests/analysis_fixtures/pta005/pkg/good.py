"""PTA005 near-misses: sanctioned host_fetch scopes, cold-path floats."""
import numpy as np

from paddle_tpu.framework.transfer import host_fetch, in_host_fetch


class TrainEngine:
    def step(self, state, loss):
        with host_fetch():
            lossf = float(loss)  # sanctioned scope
        if in_host_fetch():
            arr = np.asarray(state)  # sanctioned branch
        return lossf, float(3.5)  # constant: no device sync


class Reporter:
    def render(self, loss):
        return float(loss)  # not a hot path — no finding
