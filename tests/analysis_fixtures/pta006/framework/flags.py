"""PTA006 fixture registry."""


def define_flag(name, default, help_=""):
    return name


define_flag("FLAGS_known_flag", "", "declared flag")
