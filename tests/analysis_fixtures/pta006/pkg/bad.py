"""PTA006 fixture: undeclared flag read + library print."""
import os


def configure(env=os.environ):
    return env.get("FLAGS_mystery_flag", "")  # FINDING: undeclared


def report(msg):
    print(msg)  # FINDING: library print
