"""PTA006 near-misses: declared flag read, main()-guard prints."""
import os


def configure(env=os.environ):
    return env.get("FLAGS_known_flag", "")


def main():
    print("CLI entry points print by contract")


if __name__ == "__main__":
    print("module entry")
    main()
