"""PTA007 fixture: bad namespace, missing unit suffix, kind conflict."""


def build(reg):
    reg.counter("paddle_Serving_Errors")           # FINDING: uppercase
    reg.histogram("paddle_serving_batch")          # FINDING: no unit
    reg.reservoir("paddle_decode_gap")             # FINDING: no unit
    reg.gauge("paddle_train_loss")
    reg.counter("paddle_train_loss")               # FINDING: kind conflict


def build_fstring(reg, phase):
    reg.histogram(f"paddle_fit_{phase}")           # FINDING: no unit
