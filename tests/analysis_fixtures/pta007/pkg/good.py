"""PTA007 near-misses: clean names, f-string placeholders, the legal
histogram+reservoir name share, and non-metric histogram() calls."""
import numpy as np


def build(reg):
    reg.counter("paddle_serving_errors_total")
    reg.histogram("paddle_serving_batch_latency_ms")
    # same name as histogram AND reservoir is LEGAL: reservoirs are
    # keyed separately from rendered metrics
    reg.histogram("paddle_train_step_ms")
    reg.reservoir("paddle_train_step_ms")
    # second registration with the SAME kind is get-or-create, not a
    # conflict
    reg.histogram("paddle_train_step_ms")


def build_fstring(reg, phase):
    # placeholder substitutes as a well-formed segment; suffix literal
    reg.histogram(f"paddle_fit_{phase}_ms")


def not_a_metric(values):
    # numpy histogram: first arg is not a string literal
    h, edges = np.histogram(values, bins=10)
    return h, edges
