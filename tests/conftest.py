"""Test config: force a deterministic 8-device CPU mesh (SURVEY.md §4 —
multi-process NCCL tests are replaced by virtual-device mesh tests)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's sitecustomize may have imported jax already (TPU tunnel
# plugin) with jax_platforms baked to the accelerator; tests are CPU-only, so
# force the platform through jax.config — env vars alone are read too early.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running test, excluded from "
        "tier-1 (`-m 'not slow'`)")
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection test of the "
        "resilience runtime (run via tools/chaos.sh)")
    config.addinivalue_line(
        "markers", "perf: performance regression test (persistent compile "
        "cache, step-time) — run via tools/perf_smoke.sh")
    config.addinivalue_line(
        "markers", "serving: adaptive-batching serving engine test "
        "(paddle_tpu.serving) — run via tools/serve_smoke.sh")
    config.addinivalue_line(
        "markers", "genserve: continuous-batching generation serving test "
        "(paddle_tpu.serving.generation) — run via tools/serve_smoke.sh")
    config.addinivalue_line(
        "markers", "dp: SPMD-sharded TrainEngine test (Model.fit on a "
        "dp mesh of the 8 virtual devices) — run via tools/dp_smoke.sh")
    config.addinivalue_line(
        "markers", "monitor: runtime telemetry test (paddle_tpu.monitor "
        "+ utils.metrics) — run via tools/obs_smoke.sh")
    config.addinivalue_line(
        "markers", "lint: static-analysis suite test (paddle_tpu.analysis "
        "rules PTA001-006) — run via tools/lint.sh")
    config.addinivalue_line(
        "markers", "mesh3d: 3D-parallel layout/remat/accumulation test "
        "(SpecLayout over dp×fsdp×tp on the 8 virtual devices) — run via "
        "tools/mesh3d_smoke.sh")
    config.addinivalue_line(
        "markers", "trace: request-scoped tracing / flight recorder / "
        "goodput ledger test (monitor.tracing, monitor.flightrec, "
        "distributed.goodput) — run via tools/obs_smoke.sh")
    config.addinivalue_line(
        "markers", "kernels: Pallas fused-kernel parity/dispatch test "
        "(masked flash, paged decode, softmax-xent, bias-gelu; CPU "
        "interpret mode) — run via tools/kernels_smoke.sh")
    config.addinivalue_line(
        "markers", "pod: multi-process pod test (N real OS processes via "
        "distributed.podtest — coordinated jax.distributed bring-up or "
        "the elastic shrink supervisor) — run via tools/pod_smoke.sh")
    config.addinivalue_line(
        "markers", "specdec: speculative decode / chunked prefill / fleet "
        "router test (serving.generation draft path, serving.router) — "
        "run via tools/serve_smoke.sh")
    config.addinivalue_line(
        "markers", "sparse: sharded embedding table / vocab admission / "
        "streaming recommender data plane test (paddle_tpu.sparse) — run "
        "via tools/sparse_smoke.sh")
    config.addinivalue_line(
        "markers", "fleetchaos: fault-tolerant serving fleet test "
        "(elastic membership, mid-stream failover, retry budgets, "
        "serving chaos drills) — run via tools/serve_smoke.sh")


@pytest.fixture(autouse=True)
def _chaos_reset():
    """Chaos state is process-global; never let one test's fault plan
    leak into the next."""
    from paddle_tpu.utils import chaos

    chaos.reset()
    yield
    chaos.reset()


def cpu_subprocess_env(repo_on_path=True):
    """Env for spawning a python subprocess that must NEVER dial the TPU
    tunnel: strips the axon pool IP (the sitecustomize register() dials
    at interpreter startup when it is set — single-client tunnel, see
    bench.py _tunnel_lock) and forces the CPU backend.  Use this instead
    of hand-rolling the scrub in each test file."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "BENCH_POOL_IPS_STASH")}
    env["JAX_PLATFORMS"] = "cpu"
    if repo_on_path:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle

    paddle.seed(42)
    yield
