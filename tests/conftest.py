"""Test config: force a deterministic 8-device CPU mesh (SURVEY.md §4 —
multi-process NCCL tests are replaced by virtual-device mesh tests)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's sitecustomize may have imported jax already (TPU tunnel
# plugin) with jax_platforms baked to the accelerator; tests are CPU-only, so
# force the platform through jax.config — env vars alone are read too early.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle

    paddle.seed(42)
    yield
