"""OpTest — the numpy-reference + numeric-gradient op test harness.

Reference parity: python/paddle/fluid/tests/unittests/op_test.py —
`check_output_with_place` (op_test.py:1027) compares a one-op program
against a numpy reference on every place; `check_grad` (op_test.py:1329)
compares analytic gradients against `get_numeric_gradient` central finite
differences (op_test.py:101).  This is the contract every TPU op lowering
must satisfy (SURVEY.md §4).

TPU-native: the "one-op program" is the paddle_tpu eager op itself (which
is also what jit traces), the "places" matrix collapses to the active jax
backend, and analytic grads come from the autograd tape (jax.vjp under the
hood).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.tensor import Tensor


def numeric_gradient(fn, inputs: list[np.ndarray], wrt: int,
                     eps: float = 5e-3) -> np.ndarray:
    """Central finite differences of sum(fn(*inputs)) w.r.t. inputs[wrt]
    (op_test.py:101 get_numeric_gradient, delta-based)."""
    inputs = [np.asarray(a, np.float32) for a in inputs]
    x = inputs[wrt]
    grad = np.zeros_like(x, np.float64)
    flat = x.reshape(-1)
    gflat = grad.reshape(-1)

    def loss_at(v):
        probe = list(inputs)
        probe[wrt] = v
        out = fn(*[paddle.to_tensor(p) for p in probe])
        if isinstance(out, (tuple, list)):
            out = out[0]
        return float(np.asarray(out.numpy(), np.float64).sum())

    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        up = loss_at(x)
        flat[i] = orig - eps
        down = loss_at(x)
        flat[i] = orig
        gflat[i] = (up - down) / (2 * eps)
    return grad.reshape(x.shape)


def analytic_gradient(fn, inputs: list[np.ndarray], wrt: int) -> np.ndarray:
    """Tape gradient of sum(fn(*inputs)) (the BasicEngine walk)."""
    ts = [paddle.to_tensor(np.asarray(a, np.float32)) for a in inputs]
    for t in ts:
        t.stop_gradient = False
    out = fn(*ts)
    if isinstance(out, (tuple, list)):
        out = out[0]
    loss = paddle.sum(out)
    loss.backward()
    g = ts[wrt].grad
    assert g is not None, f"no grad flowed to input {wrt}"
    return np.asarray(g.numpy(), np.float64)


class OpTest:
    """Subclass per op; set `atol/rtol` for low-precision kernels."""

    atol = 1e-5
    rtol = 1e-5
    grad_eps = 5e-3
    max_relative_error = 5e-3  # reference check_grad default tolerance

    def check_output(self, fn, ref_fn, inputs, atol=None, rtol=None):
        """fn: paddle op over Tensors; ref_fn: numpy reference."""
        outs = fn(*[paddle.to_tensor(np.asarray(a)) for a in inputs])
        refs = ref_fn(*[np.asarray(a) for a in inputs])
        if not isinstance(outs, (tuple, list)):
            outs, refs = [outs], [refs]
        assert len(outs) == len(refs)
        for o, r in zip(outs, refs):
            np.testing.assert_allclose(
                np.asarray(o.numpy(), np.float64), np.asarray(r, np.float64),
                atol=atol if atol is not None else self.atol,
                rtol=rtol if rtol is not None else self.rtol)

    def check_grad(self, fn, inputs, wrt=None, eps=None,
                   max_relative_error=None):
        """Analytic-vs-numeric gradient check for each input in `wrt`
        (default: all float inputs)."""
        if wrt is None:
            wrt = [i for i, a in enumerate(inputs)
                   if np.issubdtype(np.asarray(a).dtype, np.floating)]
        tol = max_relative_error or self.max_relative_error
        for i in wrt:
            num = numeric_gradient(fn, inputs, i,
                                   eps=eps or self.grad_eps)
            ana = analytic_gradient(fn, inputs, i)
            denom = max(1.0, float(np.abs(num).max()))
            err = float(np.abs(num - ana).max()) / denom
            assert err < tol, (
                f"gradient mismatch on input {i}: max rel err {err:.2e} "
                f">= {tol:.0e}\n numeric:\n{num}\n analytic:\n{ana}")
