"""paddle_tpu.analysis: the framework-aware static checker suite.

Every rule is pinned twice — a seeded fixture it MUST flag (true
positive) and a near-miss it MUST NOT (the compliant twin of the same
code shape) — plus the machinery: suppression comments, the committed
baseline round-trip, the JSON report contract, and the live-tree gate
(zero unbaselined findings, inside the tier-1 time budget).
"""
import json
import os
import subprocess
import sys

import pytest

import paddle_tpu.analysis  # noqa: F401  (registers the checkers)
from paddle_tpu.analysis.core import (baseline_key, load_baseline,
                                      run_analysis, write_baseline)
from paddle_tpu.analysis.reporters import (REPORT_SCHEMA, json_report,
                                           text_report)

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "analysis_fixtures")
BASELINE = os.path.join(REPO, "tools", "analysis_baseline.json")


def fixture_run(rule, select=None):
    root = os.path.join(FIXTURES, rule.lower())
    return run_analysis([root], root=root, select=select or [rule])


def findings_in(result, path_part):
    return [f for f in result.new if path_part in f.path]


# -- one true positive + one near-miss per rule -----------------------------

class TestRuleFixtures:
    def test_pta001_flags_every_zero_copy_face(self):
        res = fixture_run("PTA001")
        bad = findings_in(res, "bad.py")
        assert {f.line for f in bad} == {6, 10, 14}, [f.text() for f in
                                                      res.new]
        assert not findings_in(res, "good.py")

    def test_pta002_reports_the_edge_into_jax(self):
        res = fixture_run("PTA002")
        chain = findings_in(res, "writer.py")
        assert len(chain) == 1
        assert "helpers.py" in chain[0].message  # names the jax module
        assert "writer_loop" in chain[0].message
        assert not findings_in(res, "writer_good.py")

    def test_pta002_jax_free_module(self):
        res = fixture_run("PTA002")
        mod = findings_in(res, "utils/metrics.py")
        assert len(mod) == 1 and mod[0].line == 2

    def test_pta003_handler_and_transitive_callees(self):
        res = fixture_run("PTA003")
        bad = findings_in(res, "bad.py")
        kinds = {f.line for f in bad}
        assert kinds == {12, 16, 17}, [f.text() for f in res.new]
        # the print is attributed through the call chain
        via = [f for f in bad if f.line == 12]
        assert "_flush" in via[0].message
        assert not findings_in(res, "good.py")

    def test_pta004_divergent_gate_before_collective(self):
        res = fixture_run("PTA004")
        bad = findings_in(res, "bad.py")
        assert len(bad) == 1 and bad[0].line == 7
        assert "allgather" in bad[0].message
        assert not findings_in(res, "good.py")

    def test_pta005_hot_path_syncs(self):
        res = fixture_run("PTA005")
        bad = findings_in(res, "bad.py")
        assert {f.line for f in bad} == {7, 8, 14}, [f.text() for f in
                                                     res.new]
        assert not findings_in(res, "good.py")

    def test_pta006_undeclared_flag_and_print(self):
        res = fixture_run("PTA006")
        bad = findings_in(res, "bad.py")
        assert {f.line for f in bad} == {6, 10}, [f.text() for f in res.new]
        assert not findings_in(res, "good.py")

    def test_pta007_names_units_and_kind_conflicts(self):
        res = fixture_run("PTA007")
        bad = findings_in(res, "bad.py")
        assert {f.line for f in bad} == {5, 6, 7, 9, 13}, \
            [f.text() for f in res.new]
        conflict = [f for f in bad if f.line == 9]
        assert "gauge" in conflict[0].message  # names the first kind
        assert not findings_in(res, "good.py")


# -- suppression + baseline machinery ---------------------------------------

class TestSuppression:
    def _run_src(self, tmp_path, source, select):
        d = tmp_path / "distributed"
        d.mkdir()
        (d / "mod.py").write_text(source)
        return run_analysis([str(tmp_path)], root=str(tmp_path),
                            select=select)

    def test_line_noqa(self, tmp_path):
        res = self._run_src(
            tmp_path,
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)  # noqa: PTA001\n",
            ["PTA001"])
        assert not res.new and res.suppressed == 1

    def test_noqa_for_other_rule_does_not_suppress(self, tmp_path):
        res = self._run_src(
            tmp_path,
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)  # noqa: PTA006\n",
            ["PTA001"])
        assert len(res.new) == 1

    def test_file_directives(self, tmp_path):
        res = self._run_src(
            tmp_path,
            "# pta: skip-file\n"
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)\n",
            ["PTA001"])
        assert not res.new and res.suppressed == 1

    def test_parse_error_is_a_finding(self, tmp_path):
        res = self._run_src(tmp_path, "def broken(:\n", ["PTA001"])
        assert res.parse_errors and res.parse_errors[0].rule == "PTA000"
        assert not res.ok


class TestBaseline:
    def test_round_trip_and_staleness(self, tmp_path):
        d = tmp_path / "distributed"
        d.mkdir()
        src = d / "mod.py"
        src.write_text("import numpy as np\n"
                       "def f(x):\n"
                       "    return np.asarray(x)\n")
        bl = tmp_path / "baseline.json"

        res = run_analysis([str(tmp_path)], root=str(tmp_path),
                           select=["PTA001"])
        assert len(res.new) == 1
        write_baseline(str(bl), res.all_findings,
                       justifications={baseline_key(res.new[0]):
                                       "grandfathered for the test"})

        # same tree + baseline -> clean
        res2 = run_analysis([str(tmp_path)], root=str(tmp_path),
                            baseline=str(bl), select=["PTA001"])
        assert not res2.new and len(res2.baselined) == 1
        assert res2.ok and not res2.stale_baseline

        # baseline identity survives edits ABOVE the finding
        src.write_text("import numpy as np\n\n\n"
                       "def f(x):\n"
                       "    return np.asarray(x)\n")
        res3 = run_analysis([str(tmp_path)], root=str(tmp_path),
                            baseline=str(bl), select=["PTA001"])
        assert not res3.new and len(res3.baselined) == 1

        # fixing the code makes the entry stale (baseline must shrink)
        src.write_text("import numpy as np\n"
                       "def f(x):\n"
                       "    return np.array(x, copy=True)\n")
        res4 = run_analysis([str(tmp_path)], root=str(tmp_path),
                            baseline=str(bl), select=["PTA001"])
        assert not res4.new
        assert len(res4.stale_baseline) == 1
        assert res4.stale_baseline[0]["justification"] == \
            "grandfathered for the test"

        # --write-baseline carries justifications over by key
        src.write_text("import numpy as np\n"
                       "def g(y):\n"
                       "    return np.asarray(y)\n")
        res5 = run_analysis([str(tmp_path)], root=str(tmp_path),
                            select=["PTA001"])
        write_baseline(str(bl), res5.all_findings)
        data = load_baseline(str(bl))
        assert len(data) == 1  # old entry dropped, new one present

    def test_duplicate_lines_counted_by_occurrence(self, tmp_path):
        d = tmp_path / "distributed"
        d.mkdir()
        src = d / "mod.py"
        src.write_text("import numpy as np\n"
                       "def f(x, y):\n"
                       "    a = np.asarray(x)\n"
                       "    b = np.asarray(x)\n"
                       "    return a, b\n")
        bl = tmp_path / "baseline.json"
        res = run_analysis([str(tmp_path)], root=str(tmp_path),
                           select=["PTA001"])
        assert len(res.new) == 2  # identical lines, two occurrences
        write_baseline(str(bl), res.all_findings)
        res2 = run_analysis([str(tmp_path)], root=str(tmp_path),
                            baseline=str(bl), select=["PTA001"])
        assert not res2.new and len(res2.baselined) == 2


# -- reporters --------------------------------------------------------------

class TestReporters:
    def test_json_schema(self):
        res = fixture_run("PTA001")
        doc = json.loads(json_report(res))
        assert doc["schema"] == REPORT_SCHEMA
        assert doc["ok"] is False
        assert doc["counts"]["new"] == len(res.new) == len(doc["findings"])
        for f in doc["findings"]:
            assert set(f) == {"rule", "path", "line", "col", "message",
                              "snippet", "snippet_hash"}
            assert f["rule"] == "PTA001"
            assert len(f["snippet_hash"]) == 12

    def test_text_summary_line(self):
        res = fixture_run("PTA001")
        out = text_report(res)
        assert "finding(s)" in out.splitlines()[-1]
        assert any(line.startswith("distributed/bad.py:")
                   for line in out.splitlines())


# -- the live-tree gate -----------------------------------------------------

class TestLiveTree:
    def test_live_tree_clean_within_budget(self):
        """The committed baseline covers the tree exactly: no new
        findings, no stale entries, under the tier-1 time budget."""
        res = run_analysis([os.path.join(REPO, "paddle_tpu")],
                           root=REPO, baseline=BASELINE)
        assert not res.new, "\n".join(f.text() for f in res.new)
        assert not res.parse_errors
        assert not res.stale_baseline, (
            "baseline entries with no matching code — refresh with "
            "--write-baseline: %r" % res.stale_baseline)
        assert res.elapsed_s < 10.0
        # every grandfathered finding carries a written justification
        for entries in load_baseline(BASELINE).values():
            for e in entries:
                assert e["justification"].strip(), e

    def test_no_print_regression_in_library_code(self):
        """The print() sweep stays swept: any NEW print in library code
        (outside main() guards) must be a logger call or carry a
        justified noqa."""
        res = run_analysis([os.path.join(REPO, "paddle_tpu")],
                           root=REPO, baseline=BASELINE, select=["PTA006"])
        assert not res.new, "\n".join(f.text() for f in res.new)
        # and the baseline grandfathers no PTA006 at all — prints were
        # fixed or individually justified, never waved through wholesale
        assert not any(k[0] == "PTA006" for k in load_baseline(BASELINE))

    def test_cli_exit_codes(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        clean = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis", "paddle_tpu",
             "--root", ".", "--baseline", "tools/analysis_baseline.json",
             "--format", "json"],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert clean.returncode == 0, clean.stdout + clean.stderr
        doc = json.loads(clean.stdout)
        assert doc["ok"] is True and doc["counts"]["new"] == 0

        dirty = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis",
             os.path.join("tests", "analysis_fixtures", "pta001"),
             "--root", os.path.join("tests", "analysis_fixtures",
                                    "pta001"),
             "--select", "PTA001"],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert dirty.returncode == 1

        usage = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis", "paddle_tpu",
             "--select", "PTA999"],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert usage.returncode == 2

    def test_list_rules_catalog(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis", "--list-rules"],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert out.returncode == 0
        for rule in ("PTA001", "PTA002", "PTA003", "PTA004", "PTA005",
                     "PTA006"):
            assert rule in out.stdout
