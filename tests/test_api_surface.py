"""Top-level API surface parity: every name the reference's
python/paddle/__init__.py exports (its #DEFINE_ALIAS block + __all__)
must exist on paddle_tpu."""
import os
import re

import pytest

import paddle_tpu as paddle

REF_INIT = "/root/reference/python/paddle/__init__.py"


REF_ROOT = "/root/reference/python/paddle"


def _ref_names(path):
    src = open(path).read()
    names = set(re.findall(
        r"from\s+[\w.]+\s+import\s+(\w+)\s+#DEFINE_ALIAS", src))
    names |= set(re.findall(r"^\s+'([\w.]+)',", src, re.M))
    # Plain submodule imports (`import paddle.batch`) and assignment
    # aliases (`batch = batch.batch`) are exports too — the regexes above
    # missed them, which is exactly how paddle.batch/compat/sysconfig
    # slipped through 4 rounds (VERDICT r04 weak #7).
    names |= set(re.findall(r"^import paddle\.(\w+)$", src, re.M))
    names |= set(re.findall(r"^(\w+) = \w+[\w.]*", src, re.M))
    # plain from-imports are exports too (`from .deprecated import
    # deprecated` — how paddle.utils exports most of its surface); skip
    # __future__ py2 artifacts
    for m in re.finditer(r"^from\s+([.\w]+)\s+import\s+([^#\n(]+)", src,
                         re.M):
        if m.group(1) == "__future__":
            continue
        for part in m.group(2).split(","):
            part = part.strip()
            if " as " in part:
                part = part.split(" as ")[-1].strip()
            if part.isidentifier():
                names.add(part)
    # module-level plumbing, not API: monkey patches and the fluid
    # type-checking/dispatch helpers leaf modules import internally
    # (scoped: 'Variable' stays pinned — it is a real export in the
    # reference static/__init__.py __all__)
    names -= {"monkey_patch_variable", "monkey_patch_math_varbase",
              "check_dtype", "check_type", "check_variable_and_dtype",
              "control_flow", "ops", "out_dtype", "core",
              "convert_dtype", "LayerHelper"}
    return {n for n in names if not n.startswith("_")}


@pytest.mark.skipif(not os.path.exists(REF_INIT),
                    reason="reference tree not present")
@pytest.mark.parametrize("mod,rel", [
    ("", "__init__.py"),
    ("nn", "nn/__init__.py"),
    ("nn.functional", "nn/functional/__init__.py"),
    ("tensor", "tensor/__init__.py"),
    ("distributed", "distributed/__init__.py"),
    ("distributed.fleet", "distributed/fleet/__init__.py"),
    ("optimizer", "optimizer/__init__.py"),
    ("io", "io/__init__.py"),
    ("static", "static/__init__.py"),
    ("static.nn", "static/nn/__init__.py"),
    ("dataset", "dataset/__init__.py"),
    ("distribution", "distribution.py"),
    ("jit", "jit/__init__.py"),
    ("amp", "amp/__init__.py"),
    ("vision", "vision/__init__.py"),
    ("vision.transforms", "vision/transforms/__init__.py"),
    ("text", "text/__init__.py"),
    ("utils", "utils/__init__.py"),
    ("metric", "metric/__init__.py"),
    ("inference", "inference/__init__.py"),
    ("regularizer", "regularizer.py"),
    ("hapi", "hapi/__init__.py"),
])
def test_reference_api_surface_all_present(mod, rel):
    names = _ref_names(os.path.join(REF_ROOT, rel))
    obj = paddle
    for part in (mod.split(".") if mod else []):
        obj = getattr(obj, part)
    missing = sorted(
        n for n in names
        if not hasattr(obj, n.split(".")[-1])
        and not hasattr(paddle, n.split(".")[-1]))
    assert not missing, f"paddle.{mod} missing: {missing}"


def test_legacy_aliases_behave():
    import numpy as np

    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert float(np.asarray(paddle.reduce_sum(x).numpy())) == 15.0
    assert np.asarray(paddle.elementwise_add(x, x).numpy())[1, 2] == 10.0
    assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
    assert bool(np.asarray(paddle.has_nan(
        paddle.to_tensor(np.array([np.nan], np.float32))).numpy()))
    t = paddle.create_global_var([2], 7.0)
    assert t.stop_gradient and np.asarray(t.numpy()).tolist() == [7.0, 7.0]
    assert isinstance(paddle.LoDTensor(np.zeros(2, np.float32)).lod(), list)


def test_fluid_axis_broadcast_and_param_attr():
    import numpy as np

    x = paddle.to_tensor(np.ones((2, 3, 4), np.float32))
    b = paddle.to_tensor(np.arange(3, dtype=np.float32))
    out = np.asarray(paddle.elementwise_add(x, b, axis=1).numpy())
    # fluid axis=1: b broadcasts along dim 1, constant over dims 0 and 2
    assert np.allclose(out[0, :, 0], [1, 2, 3])
    assert np.allclose(out[0, 1], 2.0)
    out2 = np.asarray(paddle.elementwise_mul(x, b, axis=1).numpy())
    assert np.allclose(out2[0, :, 0], [0, 1, 2])

    from paddle_tpu.nn.initializer import Constant

    p = paddle.create_parameter(
        [2, 2], attr=paddle.ParamAttr(initializer=Constant(1.5),
                                      trainable=False))
    assert p.stop_gradient is True
    assert np.allclose(np.asarray(p.numpy()), 1.5)

    # fill_constant out= fills in place (the fluid idiom)
    counter = paddle.zeros([1])
    paddle.fill_constant([1], "float32", 9.0, out=counter)
    assert float(np.asarray(counter.numpy())[0]) == 9.0

    # LoDTensor() + .set() construction pattern
    t = paddle.LoDTensor()
    t.set(np.ones((2, 2), np.float32))
    assert np.asarray(t.numpy()).shape == (2, 2)


def test_pad_conventions_and_pool_facades():
    import numpy as np

    F = paddle.nn.functional
    x = paddle.to_tensor(np.zeros((1, 1, 2, 3), np.float32))
    # paddle F.pad 2D partial spec: [left, right, top, bottom] -> W then H
    out = np.asarray(F.pad(x, [1, 1, 0, 0]).numpy())
    assert out.shape == (1, 1, 2, 5), out.shape
    # fluid pad2d: [top, bottom, left, right]
    out2 = np.asarray(F.pad2d(x, [1, 1, 0, 0]).numpy())
    assert out2.shape == (1, 1, 4, 3), out2.shape
    # pool2d facade honors NHWC global pooling
    xh = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(1, 2, 3, 4))
    g = np.asarray(F.pool2d(xh, global_pooling=True, pool_type="max",
                            data_format="NHWC").numpy())
    assert g.shape == (1, 1, 1, 4)
    np.testing.assert_allclose(g[0, 0, 0], xh.numpy()[0].max((0, 1)))


def test_dynamic_decode_beam_search():
    import numpy as np

    import paddle_tpu.nn as nn

    # a "cell" that deterministically prefers token (state+1) mod V
    V = 5

    class ToyCell:
        def __call__(self, ids, state):
            import jax.numpy as jnp

            from paddle_tpu.tensor import Tensor, unwrap

            s = unwrap(state)
            logits = jnp.eye(V)[(s + 1) % V] * 10.0
            return Tensor(logits), Tensor((s + 1) % V)

    dec = nn.BeamSearchDecoder(ToyCell(), start_token=0, end_token=V - 1,
                               beam_size=2)
    seqs, scores = nn.dynamic_decode(
        dec, inits=paddle.to_tensor(np.zeros(2, np.int64)),
        max_step_num=8)
    s = np.asarray(seqs.numpy())
    # best beam follows 1,2,3,4(end)
    assert s.shape[0] == 2 and list(s[0, 0, :4]) == [1, 2, 3, 4]


def test_static_persistence_and_export(tmp_path):
    import numpy as np

    from paddle_tpu import static

    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3], "float32")
        w = static.create_parameter([3, 1], name="w")
        pred = paddle.matmul(x, w)
        cost = (pred ** 2).mean()
        paddle.optimizer.SGD(learning_rate=0.0).minimize(cost)
    exe = static.Executor()
    exe.run(main, feed={"x": np.ones((2, 3), np.float32)},
            fetch_list=[cost])
    # param save/load roundtrip by name
    p0 = np.asarray(w.numpy()).copy()
    static.save(main, str(tmp_path / "m"))
    w.set_value(np.zeros((3, 1), np.float32))
    static.load(main, str(tmp_path / "m"))
    np.testing.assert_allclose(np.asarray(w.numpy()), p0)
    # static export -> predictor serve
    eval_prog = main.clone(for_test=True)
    with static.program_guard(eval_prog):
        pass
    static.save_inference_model(str(tmp_path / "exp"), [x], [pred])
    pred_exe = static.load_inference_model(str(tmp_path / "exp"))
    out, = pred_exe.run([np.ones((2, 3), np.float32)])
    np.testing.assert_allclose(out, np.ones((2, 3), np.float32) @ p0,
                               rtol=1e-5)
    # ProgramTranslator off -> plain tracing path still runs
    paddle.jit.ProgramTranslator.get_instance().enable(False)
    try:
        @paddle.jit.to_static
        def g(t):
            return t * 2.0
        r = g(paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(r.numpy()), [2.0, 2.0])
    finally:
        paddle.jit.ProgramTranslator.get_instance().enable(True)
