"""Top-level API surface parity: every name the reference's
python/paddle/__init__.py exports (its #DEFINE_ALIAS block + __all__)
must exist on paddle_tpu."""
import os
import re

import pytest

import paddle_tpu as paddle

REF_INIT = "/root/reference/python/paddle/__init__.py"


@pytest.mark.skipif(not os.path.exists(REF_INIT),
                    reason="reference tree not present")
def test_reference_top_level_names_all_present():
    src = open(REF_INIT).read()
    names = set(re.findall(
        r"from\s+[\w.]+\s+import\s+(\w+)\s+#DEFINE_ALIAS", src))
    names |= set(re.findall(r"^\s+'(\w+)',", src, re.M))
    missing = sorted(n for n in names if not hasattr(paddle, n))
    assert not missing, f"missing top-level names: {missing}"


def test_legacy_aliases_behave():
    import numpy as np

    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert float(np.asarray(paddle.reduce_sum(x).numpy())) == 15.0
    assert np.asarray(paddle.elementwise_add(x, x).numpy())[1, 2] == 10.0
    assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
    assert bool(np.asarray(paddle.has_nan(
        paddle.to_tensor(np.array([np.nan], np.float32))).numpy()))
    t = paddle.create_global_var([2], 7.0)
    assert t.stop_gradient and np.asarray(t.numpy()).tolist() == [7.0, 7.0]
    assert isinstance(paddle.LoDTensor(np.zeros(2, np.float32)).lod(), list)


def test_fluid_axis_broadcast_and_param_attr():
    import numpy as np

    x = paddle.to_tensor(np.ones((2, 3, 4), np.float32))
    b = paddle.to_tensor(np.arange(3, dtype=np.float32))
    out = np.asarray(paddle.elementwise_add(x, b, axis=1).numpy())
    # fluid axis=1: b broadcasts along dim 1, constant over dims 0 and 2
    assert np.allclose(out[0, :, 0], [1, 2, 3])
    assert np.allclose(out[0, 1], 2.0)
    out2 = np.asarray(paddle.elementwise_mul(x, b, axis=1).numpy())
    assert np.allclose(out2[0, :, 0], [0, 1, 2])

    from paddle_tpu.nn.initializer import Constant

    p = paddle.create_parameter(
        [2, 2], attr=paddle.ParamAttr(initializer=Constant(1.5),
                                      trainable=False))
    assert p.stop_gradient is True
    assert np.allclose(np.asarray(p.numpy()), 1.5)

    # fill_constant out= fills in place (the fluid idiom)
    counter = paddle.zeros([1])
    paddle.fill_constant([1], "float32", 9.0, out=counter)
    assert float(np.asarray(counter.numpy())[0]) == 9.0

    # LoDTensor() + .set() construction pattern
    t = paddle.LoDTensor()
    t.set(np.ones((2, 2), np.float32))
    assert np.asarray(t.numpy()).shape == (2, 2)
