"""Attention-stack parity vs torch: MultiHeadAttention (self and cross,
with and without mask) and a full TransformerEncoderLayer, weights
copied across layouts (paddle Linear weight is [in, out]; torch packs
qkv into in_proj_weight [3E, E] in [out, in] convention).  Pins the
flagship BERT/GPT attention math against an external oracle."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402

rs = np.random.RandomState(13)
E, H, B, S = 16, 4, 2, 7


def _cmp(pd_out, t_out, atol=1e-5):
    np.testing.assert_allclose(np.asarray(pd_out.numpy()),
                               t_out.detach().numpy(), atol=atol,
                               rtol=1e-4)


def _copy_mha(p_mha, t_mha):
    def w(lin):  # paddle [in, out] -> torch [out, in]
        return torch.tensor(np.asarray(lin.weight.numpy()).T.copy())

    def b(lin):
        return torch.tensor(np.asarray(lin.bias.numpy()))

    with torch.no_grad():
        t_mha.in_proj_weight.copy_(torch.cat(
            [w(p_mha.q_proj), w(p_mha.k_proj), w(p_mha.v_proj)]))
        t_mha.in_proj_bias.copy_(torch.cat(
            [b(p_mha.q_proj), b(p_mha.k_proj), b(p_mha.v_proj)]))
        t_mha.out_proj.weight.copy_(w(p_mha.out_proj))
        t_mha.out_proj.bias.copy_(b(p_mha.out_proj))


@pytest.fixture
def pair():
    paddle.seed(4)
    p_mha = nn.MultiHeadAttention(E, H, dropout=0.0)
    t_mha = torch.nn.MultiheadAttention(E, H, dropout=0.0,
                                        batch_first=True)
    _copy_mha(p_mha, t_mha)
    p_mha.eval()
    t_mha.eval()
    return p_mha, t_mha


def test_self_attention_parity(pair):
    p_mha, t_mha = pair
    x = rs.randn(B, S, E).astype(np.float32)
    got = p_mha(paddle.to_tensor(x), paddle.to_tensor(x),
                paddle.to_tensor(x))
    want, _ = t_mha(torch.tensor(x), torch.tensor(x), torch.tensor(x),
                    need_weights=False)
    _cmp(got, want, atol=1e-5)


def test_cross_attention_parity(pair):
    p_mha, t_mha = pair
    q = rs.randn(B, 5, E).astype(np.float32)
    kv = rs.randn(B, S, E).astype(np.float32)
    got = p_mha(paddle.to_tensor(q), paddle.to_tensor(kv),
                paddle.to_tensor(kv))
    want, _ = t_mha(torch.tensor(q), torch.tensor(kv), torch.tensor(kv),
                    need_weights=False)
    _cmp(got, want, atol=1e-5)


def test_causal_mask_parity(pair):
    p_mha, t_mha = pair
    x = rs.randn(B, S, E).astype(np.float32)
    causal = np.triu(np.full((S, S), -np.inf, np.float32), k=1)
    got = p_mha(paddle.to_tensor(x), paddle.to_tensor(x),
                paddle.to_tensor(x),
                attn_mask=paddle.to_tensor(causal))
    want, _ = t_mha(torch.tensor(x), torch.tensor(x), torch.tensor(x),
                    attn_mask=torch.tensor(causal), need_weights=False)
    _cmp(got, want, atol=1e-5)


def test_transformer_encoder_layer_parity():
    paddle.seed(6)
    p_tel = nn.TransformerEncoderLayer(d_model=E, nhead=H,
                                       dim_feedforward=32, dropout=0.0,
                                       activation="relu")
    t_tel = torch.nn.TransformerEncoderLayer(
        d_model=E, nhead=H, dim_feedforward=32, dropout=0.0,
        activation="relu", batch_first=True)
    p_tel.eval()
    t_tel.eval()
    _copy_mha(p_tel.self_attn, t_tel.self_attn)

    def w(lin):
        return torch.tensor(np.asarray(lin.weight.numpy()).T.copy())

    def b(lin):
        return torch.tensor(np.asarray(lin.bias.numpy()))

    with torch.no_grad():
        t_tel.linear1.weight.copy_(w(p_tel.linear1))
        t_tel.linear1.bias.copy_(b(p_tel.linear1))
        t_tel.linear2.weight.copy_(w(p_tel.linear2))
        t_tel.linear2.bias.copy_(b(p_tel.linear2))
        t_tel.norm1.weight.copy_(torch.tensor(
            np.asarray(p_tel.norm1.weight.numpy())))
        t_tel.norm1.bias.copy_(torch.tensor(
            np.asarray(p_tel.norm1.bias.numpy())))
        t_tel.norm2.weight.copy_(torch.tensor(
            np.asarray(p_tel.norm2.weight.numpy())))
        t_tel.norm2.bias.copy_(torch.tensor(
            np.asarray(p_tel.norm2.bias.numpy())))

    x = rs.randn(B, S, E).astype(np.float32)
    got = p_tel(paddle.to_tensor(x))
    want = t_tel(torch.tensor(x))
    _cmp(got, want, atol=1e-4)
