"""Autograd tape tests — analytic grads vs numeric finite differences
(the OpTest check_grad contract, reference op_test.py:1329/101)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def numeric_grad(fn, x, eps=1e-3):
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        hi = fn(x.copy().reshape(x.shape))
        flat[i] = orig - eps
        lo = fn(x.copy().reshape(x.shape))
        flat[i] = orig
        gf[i] = (hi - lo) / (2 * eps)
    return g


def test_backward_simple():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0, 6.0])


def test_backward_chain():
    x = paddle.to_tensor([0.5, 1.5], stop_gradient=False)
    y = paddle.exp(paddle.sin(x)).sum()
    y.backward()
    ref = np.exp(np.sin([0.5, 1.5])) * np.cos([0.5, 1.5])
    np.testing.assert_allclose(x.grad.numpy(), ref, rtol=1e-5)


def test_grad_accumulation():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_shared_input_two_paths():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x + x * 3  # dy/dx = 2x + 3 = 7
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0])


def test_stop_gradient():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0])  # stop_gradient True
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * 2
    z = y.detach() * x
    z.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])  # only the direct path


def test_no_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 5
    assert y.stop_gradient


def test_non_scalar_backward_requires_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        y.backward()
    y.backward(paddle.ones_like(y))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_matmul_grad_vs_numeric():
    a = np.random.rand(3, 4).astype(np.float32)
    b = np.random.rand(4, 2).astype(np.float32)
    ta = paddle.to_tensor(a.copy(), stop_gradient=False)
    tb = paddle.to_tensor(b.copy(), stop_gradient=False)
    loss = paddle.sum(ta @ tb)
    loss.backward()
    ga = numeric_grad(lambda x: float((x @ b).sum()), a.astype(np.float64))
    gb = numeric_grad(lambda x: float((a @ x).sum()), b.astype(np.float64))
    np.testing.assert_allclose(ta.grad.numpy(), ga, rtol=1e-2, atol=1e-3)
    np.testing.assert_allclose(tb.grad.numpy(), gb, rtol=1e-2, atol=1e-3)


def test_softmax_xent_grad_vs_numeric():
    import paddle_tpu.nn.functional as F

    logits = np.random.randn(4, 5).astype(np.float64)
    label = np.array([0, 2, 4, 1])

    def ref(z):
        zz = z - z.max(-1, keepdims=True)
        logp = zz - np.log(np.exp(zz).sum(-1, keepdims=True))
        return -logp[np.arange(4), label].mean()

    t = paddle.to_tensor(logits.astype(np.float32), stop_gradient=False)
    loss = F.cross_entropy(t, paddle.to_tensor(label))
    loss.backward()
    g = numeric_grad(ref, logits.copy())
    np.testing.assert_allclose(t.grad.numpy(), g, rtol=1e-2, atol=1e-4)


def test_paddle_grad_api():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x * x
    (gx,) = [paddle.grad(y, [x])] if False else [paddle.grad(y.sum(), [x])]
    np.testing.assert_allclose(gx.numpy(), [12.0])
    assert x.grad is None  # grad() must not touch .grad


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
    a, b = paddle.split(x, 2, axis=0)
    (a.sum() * 2 + b.sum() * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[2, 2, 2], [3, 3, 3]])


def test_getitem_grad():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    x[1].backward()
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 1.0, 0.0])


class TestLeafSemantics:
    def test_computed_tensor_marked_trainable_gets_grad(self):
        """A tensor produced by an UNRECORDED op (no grad history) is a
        leaf — marking it trainable afterwards must accumulate into .grad
        (torch/paddle leaf semantics), not silently drop the gradient."""
        b = paddle.randn([3]) * 0.01
        assert b.is_leaf  # no grad history
        b.stop_gradient = False
        loss = paddle.sum(b * 2.0)
        loss.backward()
        assert b.grad is not None
        np.testing.assert_allclose(np.asarray(b.grad.numpy()),
                                   np.full(3, 2.0), rtol=1e-6)

    def test_recorded_intermediate_is_not_leaf(self):
        a = paddle.randn([3])
        a.stop_gradient = False
        mid = a * 2.0
        assert not mid.is_leaf
        loss = paddle.sum(mid)
        loss.backward()
        assert a.grad is not None and mid.grad is None


class TestDoubleGrad:
    """create_graph=True re-derives each vjp as a taped op (the reference's
    double-grad path, partial_grad_engine.cc)."""

    def test_second_derivative_of_cube(self):
        x = paddle.to_tensor(np.array([3.0], np.float32),
                             stop_gradient=False)
        y = (x ** 3).sum()
        g = paddle.grad(y, [x], create_graph=True)
        g = g if isinstance(g, list) else [g]
        np.testing.assert_allclose(g[0].numpy(), [27.0], rtol=1e-5)
        gg = paddle.grad(g[0].sum(), [x])
        gg = gg if isinstance(gg, list) else [gg]
        np.testing.assert_allclose(gg[0].numpy(), [18.0], rtol=1e-5)

    def test_grad_penalty_through_matmul(self):
        """WGAN-GP shape: d/dw of ||dL/dx||^2."""
        w = paddle.to_tensor(np.array([[2.0]], np.float32),
                             stop_gradient=False)
        x = paddle.to_tensor(np.array([[3.0]], np.float32),
                             stop_gradient=False)
        y = paddle.matmul(x, w).sum()          # y = x w
        gx = paddle.grad(y, [x], create_graph=True)
        gx = gx if isinstance(gx, list) else [gx]
        # gx = w; penalty = w^2; d penalty/dw = 2w = 4
        penalty = (gx[0] ** 2).sum()
        gw = paddle.grad(penalty, [w])
        gw = gw if isinstance(gw, list) else [gw]
        np.testing.assert_allclose(gw[0].numpy(), [[4.0]], rtol=1e-5)

    def test_without_create_graph_still_raises_nothing(self):
        x = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        y = (x ** 2).sum()
        g = paddle.grad(y, [x])
        g = g if isinstance(g, list) else [g]
        np.testing.assert_allclose(g[0].numpy(), [4.0], rtol=1e-6)


class TestTensorHooks:
    def test_hook_observes_and_replaces_grad(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        seen = []
        h = x.register_hook(
            lambda g: seen.append(np.asarray(g.numpy())) or g * 2)
        (x * 5.0).sum().backward()
        np.testing.assert_allclose(seen[0], [5.0, 5.0, 5.0])
        np.testing.assert_allclose(x.grad.numpy(), [10.0, 10.0, 10.0])

    def test_hook_remove(self):
        x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        h = x.register_hook(lambda g: g * 100)
        h.remove()
        (x * 3.0).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])

    def test_hook_none_return_keeps_grad(self):
        x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        calls = []
        x.register_hook(lambda g: calls.append(1) and None)
        (x * 7.0).sum().backward()
        assert calls
        np.testing.assert_allclose(x.grad.numpy(), [7.0, 7.0])

    def test_hook_on_stop_gradient_raises(self):
        x = paddle.to_tensor(np.ones(2, np.float32))
        with pytest.raises(RuntimeError):
            x.register_hook(lambda g: g)

    def test_hook_on_intermediate(self):
        x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        mid = x * 2.0
        seen = []
        mid.register_hook(lambda g: seen.append(np.asarray(g.numpy())))
        (mid * 3.0).sum().backward()
        np.testing.assert_allclose(seen[0], [3.0, 3.0])
        np.testing.assert_allclose(x.grad.numpy(), [6.0, 6.0])
