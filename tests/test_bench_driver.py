"""bench.py driver plumbing (no jax in the driver by design, VERDICT r01
weak #1): result-line extraction must skip phase markers, probe failures
must classify to machine-readable causes, and per-config timeouts must
resolve."""
import json

import bench


def test_extract_skips_partial_phase_markers():
    out = "\n".join([
        json.dumps({"partial": True, "phase": "compile_start"}),
        json.dumps({"partial": True, "phase": "compile_done",
                    "seconds": 41.2}),
        json.dumps({"metric": "bert_base_samples_per_sec_per_chip",
                    "value": 1000.0, "unit": "samples/s",
                    "vs_baseline": 1.3}),
    ])
    got = bench._extract(out)
    assert got["metric"] == "bert_base_samples_per_sec_per_chip"
    # a timed-out body that only emitted markers yields None, never a
    # marker masquerading as a result
    partial_only = json.dumps({"partial": True, "phase": "compile_start",
                               "metric": "x"})
    assert bench._extract(partial_only) is None


def test_extract_partials_collects_phases():
    out = "\n".join([
        "[bench] noise",
        json.dumps({"partial": True, "phase": "compile_start"}),
        json.dumps({"partial": True, "phase": "compile_done",
                    "seconds": 12.5}),
        "not json {",
    ])
    got = bench._extract_partials(out)
    assert [p["phase"] for p in got] == ["compile_start", "compile_done"]
    assert got[1]["seconds"] == 12.5


def test_probe_failure_classification():
    cls = bench._classify_probe_failure
    assert cls(1, "... make_c_api_client blocked ...") == \
        "pjrt_client_init_hang"
    assert cls(-1, "some stack\ntimeout after 240s") == "timeout_hang"
    assert cls(1, "RPC UNAVAILABLE: channel") == "grpc_unavailable"
    assert cls(1, "axon not in the list of known backends") == \
        "axon_backend_unregistered"
    assert cls(1, "something else") == "error"


def test_per_config_timeouts():
    # big graphs get longer budgets; everything else the default
    assert bench.CONFIG_TIMEOUT_TPU["gpt13b"] > bench.CONFIG_TIMEOUT_TPU_S
    assert bench.CONFIG_TIMEOUT_TPU["bert"] > bench.CONFIG_TIMEOUT_TPU_S
    assert bench.CONFIG_TIMEOUT_TPU.get("mnist",
                                        bench.CONFIG_TIMEOUT_TPU_S) == \
        bench.CONFIG_TIMEOUT_TPU_S


def test_configs_cover_all_baseline_targets():
    # every BASELINE config + kernels/longseq/serving evidence, bert last
    assert bench.CONFIGS[-1] == "bert"
    for cfg in ("mnist", "resnet50", "ernie", "gpt13b", "kernels",
                "longseq", "predictor", "dp8"):
        assert cfg in bench.CONFIGS, cfg


def test_dp8_config_never_dials_tpu(monkeypatch):
    """The dp-scaling config always runs on an 8-virtual-device CPU
    mesh: _run_config must build a CPU env with the forced device count
    and reuse the existing line on the late-TPU pass instead of
    re-running."""
    calls = []

    def fake_run(args, env, timeout):
        calls.append((args, env))
        import json
        return 0, json.dumps({"metric": "dp8_samples_per_sec",
                              "value": 1.0, "unit": "samples/s",
                              "vs_baseline": 1.0}), ""

    monkeypatch.setattr(bench, "_run", fake_run)
    line = bench._run_config("dp8", on_tpu=True)
    assert line["metric"] == "dp8_samples_per_sec"
    (_, env), = calls
    assert env.get("JAX_PLATFORMS") == "cpu"
    assert "xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
    # late-TPU pass: the backend-independent line is reused verbatim
    again = bench._run_config("dp8", on_tpu=True, cpu_fallback=line)
    assert again is line
    assert len(calls) == 1
