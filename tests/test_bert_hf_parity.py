"""Flagship parity: our BertModel vs HuggingFace transformers BertModel
(torch CPU) with weights copied across — the exact post-LN BERT
semantics (embedding sum + LN, per-layer q/k/v/out + post-LN, gelu FFN,
tanh pooler) validated against the ecosystem-standard implementation."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.models import BertConfig, BertModel  # noqa: E402

V, H, L, A, I, S = 120, 32, 2, 4, 64, 16
rs = np.random.RandomState(17)


def _t(x):
    return torch.tensor(np.asarray(x.numpy()))


def _tT(lin):  # paddle Linear [in, out] -> torch [out, in]
    return torch.tensor(np.asarray(lin.weight.numpy()).T.copy())


def _copy_into_hf(pm, hf):
    e = hf.embeddings
    with torch.no_grad():
        e.word_embeddings.weight.copy_(_t(pm.embeddings.word.weight))
        e.position_embeddings.weight.copy_(
            _t(pm.embeddings.position.weight))
        e.token_type_embeddings.weight.copy_(
            _t(pm.embeddings.token_type.weight))
        e.LayerNorm.weight.copy_(_t(pm.embeddings.layer_norm.weight))
        e.LayerNorm.bias.copy_(_t(pm.embeddings.layer_norm.bias))
        for i, lay in enumerate(hf.encoder.layer):
            pl = pm.encoder.layers[i]
            lay.attention.self.query.weight.copy_(_tT(pl.self_attn.q_proj))
            lay.attention.self.query.bias.copy_(_t(pl.self_attn.q_proj.bias))
            lay.attention.self.key.weight.copy_(_tT(pl.self_attn.k_proj))
            lay.attention.self.key.bias.copy_(_t(pl.self_attn.k_proj.bias))
            lay.attention.self.value.weight.copy_(_tT(pl.self_attn.v_proj))
            lay.attention.self.value.bias.copy_(_t(pl.self_attn.v_proj.bias))
            lay.attention.output.dense.weight.copy_(
                _tT(pl.self_attn.out_proj))
            lay.attention.output.dense.bias.copy_(
                _t(pl.self_attn.out_proj.bias))
            lay.attention.output.LayerNorm.weight.copy_(_t(pl.norm1.weight))
            lay.attention.output.LayerNorm.bias.copy_(_t(pl.norm1.bias))
            lay.intermediate.dense.weight.copy_(_tT(pl.linear1))
            lay.intermediate.dense.bias.copy_(_t(pl.linear1.bias))
            lay.output.dense.weight.copy_(_tT(pl.linear2))
            lay.output.dense.bias.copy_(_t(pl.linear2.bias))
            lay.output.LayerNorm.weight.copy_(_t(pl.norm2.weight))
            lay.output.LayerNorm.bias.copy_(_t(pl.norm2.bias))
        hf.pooler.dense.weight.copy_(_tT(pm.pooler))
        hf.pooler.dense.bias.copy_(_t(pm.pooler.bias))


@pytest.fixture(scope="module")
def models():
    paddle.seed(21)
    pm = BertModel(BertConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=A,
        intermediate_size=I, max_position_embeddings=S, dropout=0.0))
    pm.eval()
    hf = transformers.BertModel(transformers.BertConfig(
        vocab_size=V, hidden_size=H, num_hidden_layers=L,
        num_attention_heads=A, intermediate_size=I,
        max_position_embeddings=S, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        hidden_act="gelu"))
    hf.eval()
    _copy_into_hf(pm, hf)
    return pm, hf


def test_bert_hidden_and_pooler_parity(models):
    pm, hf = models
    ids = rs.randint(0, V, (2, S)).astype(np.int64)
    seq, pooled = pm(paddle.to_tensor(ids))
    with torch.no_grad():
        out = hf(input_ids=torch.tensor(ids))
    np.testing.assert_allclose(np.asarray(seq.numpy()),
                               out.last_hidden_state.numpy(),
                               atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(pooled.numpy()),
                               out.pooler_output.numpy(),
                               atol=2e-5, rtol=1e-4)


def test_bert_token_type_parity(models):
    pm, hf = models
    ids = rs.randint(0, V, (2, S)).astype(np.int64)
    tt = (np.arange(S) >= S // 2).astype(np.int64)[None].repeat(2, 0)
    seq, _ = pm(paddle.to_tensor(ids), token_type_ids=paddle.to_tensor(tt))
    with torch.no_grad():
        out = hf(input_ids=torch.tensor(ids),
                 token_type_ids=torch.tensor(tt))
    np.testing.assert_allclose(np.asarray(seq.numpy()),
                               out.last_hidden_state.numpy(),
                               atol=2e-5, rtol=1e-4)
