"""Chaos injector unit tests (paddle_tpu/utils/chaos.py).

The injectors themselves must be deterministic and one-shot — they are
the instrument every resilience test relies on, so they get their own
direct coverage here.
"""
import os
import signal
import time

import pytest

from paddle_tpu.distributed.resilience import PreemptionGuard
from paddle_tpu.utils import chaos


pytestmark = pytest.mark.chaos


class TestCrashInjector:
    def test_crashes_exactly_at_step(self):
        with chaos.inject(crash_at_step=3) as cfg:
            assert chaos.on_step(1) is False
            assert chaos.on_step(2) is False
            with pytest.raises(chaos.ChaosCrash, match="step 3"):
                chaos.on_step(3)
            # one-shot: consumed after firing (rollback replay survives)
            assert chaos.on_step(3) is False
            assert cfg.fired == ["crash@3"]

    def test_other_steps_unaffected(self):
        with chaos.inject(crash_at_step=100):
            for s in range(1, 10):
                assert chaos.on_step(s) is False


class TestNanInjector:
    def test_poisons_listed_steps_once(self):
        with chaos.inject(nan_at_step=(2, 4)) as cfg:
            assert chaos.on_step(1) is False
            assert chaos.on_step(2) is True
            assert chaos.on_step(2) is False  # consumed
            assert chaos.on_step(3) is False
            assert chaos.on_step(4) is True
            assert cfg.fired == ["nan@2", "nan@4"]

    def test_single_int_accepted(self):
        with chaos.inject(nan_at_step=5):
            assert chaos.on_step(5) is True


class TestSlowInjector:
    def test_stalls_only_the_target_step(self):
        with chaos.inject(slow_step=2, slow_seconds=0.3):
            t0 = time.monotonic()
            chaos.on_step(1)
            assert time.monotonic() - t0 < 0.2
            t0 = time.monotonic()
            chaos.on_step(2)
            assert time.monotonic() - t0 >= 0.3
            t0 = time.monotonic()
            chaos.on_step(2)  # one-shot
            assert time.monotonic() - t0 < 0.2


class TestPreemptInjector:
    def test_self_sigterm_latched_by_guard(self):
        with PreemptionGuard() as g:
            with chaos.inject(preempt_at_step=2):
                chaos.on_step(1)
                assert not g.preempted
                chaos.on_step(2)
                assert g.preempted and g.signum == signal.SIGTERM


class TestFailIOInjector:
    def test_budget_counts_down(self):
        with chaos.inject(fail_io=2) as cfg:
            with pytest.raises(OSError, match="chaos"):
                chaos.on_io("save")
            with pytest.raises(OSError, match="chaos"):
                chaos.on_io("save")
            chaos.on_io("save")  # budget exhausted — passes
            assert cfg.fired == ["io@save", "io@save"]

    def test_custom_error_type(self):
        with chaos.inject(fail_io=1, io_error=TimeoutError("slow disk")):
            with pytest.raises(TimeoutError, match="slow disk"):
                chaos.on_io("x")


class TestConfigPlumbing:
    def test_env_parsing(self):
        env = {
            "PADDLE_CHAOS_CRASH_STEP": "7",
            "PADDLE_CHAOS_NAN_STEP": "3,5",
            "PADDLE_CHAOS_SLOW_STEP": "4",
            "PADDLE_CHAOS_SLOW_SECONDS": "1.5",
            "PADDLE_CHAOS_PREEMPT_STEP": "9",
            "PADDLE_CHAOS_FAIL_IO": "2",
            "PADDLE_CHAOS_CKPT_TORN": "1",
            "PADDLE_CHAOS_CKPT_BITFLIP": "2",
            "PADDLE_CHAOS_CKPT_ENOSPC": "3",
            "PADDLE_CHAOS_CKPT_SLOW_IO": "0.25",
        }
        cfg = chaos.ChaosConfig.from_env(env)
        assert cfg.crash_at_step == 7
        assert cfg.nan_at_steps == {3, 5}
        assert cfg.slow_step == 4 and cfg.slow_seconds == 1.5
        assert cfg.preempt_at_step == 9
        assert cfg.fail_io == 2
        assert cfg.ckpt_torn == 1 and cfg.ckpt_bitflip == 2
        assert cfg.ckpt_enospc == 3 and cfg.ckpt_slow_io == 0.25
        assert not cfg.is_noop()

    def test_ckpt_injectors_are_checkpoint_scoped(self):
        """The checkpoint injectors key on the durable-save protocol's
        labels — generic IO calls pass through untouched."""
        with chaos.inject(ckpt_enospc=1, ckpt_torn=1) as cfg:
            chaos.on_io("some.other.io")       # no label match: passes
            with pytest.raises(chaos.ChaosTorn):
                chaos.on_io("checkpoint.commit")
            with pytest.raises(OSError):
                chaos.on_io("checkpoint.save")
            chaos.on_io("checkpoint.save")     # budget exhausted
            assert cfg.fired == ["torn@checkpoint.commit",
                                 "enospc@checkpoint.save"]

    def test_empty_env_is_noop(self):
        cfg = chaos.ChaosConfig.from_env({})
        assert cfg.is_noop()

    def test_env_base_is_lazy(self, monkeypatch):
        chaos.reset()
        monkeypatch.setenv("PADDLE_CHAOS_NAN_STEP", "11")
        try:
            assert chaos.on_step(11) is True
        finally:
            chaos.reset()

    def test_inject_nests_and_restores(self):
        base = chaos.active_config()
        with chaos.inject(fail_io=1) as outer:
            assert chaos.active_config() is outer
            with chaos.inject(nan_at_step=1) as inner:
                assert chaos.active_config() is inner
            assert chaos.active_config() is outer
        assert chaos.active_config() is base
