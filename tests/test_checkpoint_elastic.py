"""Sharded checkpointing + restart policy + nan/inf guard tests
(SURVEY.md §5: checkpoint/resume replaces the reference's nonexistent
elasticity; FLAGS_check_nan_inf is the runtime correctness guard) — plus
the end-to-end preemption contract: SIGTERM mid-training → emergency
checkpoint → relaunch → bitwise-identical final parameters."""
import os
import re
import signal
import subprocess
import sys
import textwrap
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import (
    CheckpointManager,
    restore_sharded,
    save_sharded,
)
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.resilience import PREEMPTED_EXIT_CODE
from paddle_tpu.utils import chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestShardedCheckpoint:
    def test_roundtrip_replicated(self, tmp_path):
        state = {"w": jnp.arange(12.0).reshape(3, 4),
                 "step": jnp.int32(7),
                 "nested": {"m": jnp.ones((5,))}}
        path = str(tmp_path / "ckpt1")
        save_sharded(state, path)
        back = restore_sharded(path)
        np.testing.assert_array_equal(np.asarray(back["w"]),
                                      np.asarray(state["w"]))
        assert int(back["step"]) == 7
        np.testing.assert_array_equal(np.asarray(back["nested"]["m"]),
                                      np.ones(5))

    def test_sharded_save_restore_new_sharding(self, tmp_path):
        """Save sharded over dp=8, restore onto a DIFFERENT layout
        (dp=4 x mp=2) — the mesh-reshape resume the reference lacks."""
        mesh8 = build_mesh({"dp": 8})
        w = jnp.arange(64.0).reshape(8, 8)
        w8 = jax.device_put(w, NamedSharding(mesh8, P("dp", None)))
        path = str(tmp_path / "ckpt2")
        save_sharded({"w": w8}, path)

        mesh42 = build_mesh({"dp": 4, "mp": 2})
        target_sh = {"w": NamedSharding(mesh42, P("dp", "mp"))}
        back = restore_sharded(path, template={"w": w8},
                               shardings=target_sh)
        assert back["w"].sharding == target_sh["w"]
        np.testing.assert_array_equal(np.asarray(back["w"]), np.asarray(w))

    def test_manager_rolls_and_resumes(self, tmp_path):
        # context-manager form: an assertion failure mid-block no longer
        # leaks the underlying orbax manager
        with CheckpointManager(str(tmp_path / "run"), max_to_keep=2) as mgr:
            assert mgr.restore_latest()[0] is None
            for step in (1, 2, 3):
                state = {"w": jnp.full((4,), float(step)),
                         "step": jnp.int32(step)}
                assert mgr.save(step, state, force=True)
            mgr.wait()
            assert mgr.latest_step() == 3
            assert mgr.all_steps() == [2, 3]  # rolled: keeps newest 2
            step, back = mgr.restore_latest(template=state)
            assert step == 3
            np.testing.assert_array_equal(np.asarray(back["w"]),
                                          np.full(4, 3.0))

    def test_train_resume_equivalence(self, tmp_path):
        """Train 4 steps, checkpoint the full functional training state
        (params + opt slots + step) at step 2, resume → bitwise-identical
        params to the uninterrupted run (the TPU-native resume contract)."""
        rs = np.random.RandomState(0)
        w0 = {"w": jnp.asarray(rs.randn(4, 4) * 0.3, jnp.float32)}
        data = [jnp.asarray(rs.randn(8, 4), jnp.float32) for _ in range(4)]
        opt = paddle.optimizer.Adam(learning_rate=0.01)

        def loss_fn(p, x):
            return jnp.mean((x @ p["w"] - 1.0) ** 2)

        @jax.jit
        def step(p, s, t, x):
            _, g = jax.value_and_grad(loss_fn)(p, x)
            return opt.apply_pytree(p, g, s, step=t)

        # uninterrupted
        p, s = w0, opt.init_pytree(w0)
        for t, x in enumerate(data, 1):
            p, s = step(p, s, t, x)
        ref = np.asarray(p["w"])

        # interrupted at step 2 → checkpoint → fresh process state → resume
        p, s = w0, opt.init_pytree(w0)
        for t, x in enumerate(data[:2], 1):
            p, s = step(p, s, t, x)
        with CheckpointManager(str(tmp_path / "resume")) as mgr:
            mgr.save(2, {"params": p, "opt": s}, force=True)
            mgr.wait()

            t0, back = mgr.restore_latest(
                template={"params": p, "opt": s})
            p2, s2 = back["params"], back["opt"]
            for t, x in enumerate(data[2:], t0 + 1):
                p2, s2 = step(p2, s2, t, x)
            np.testing.assert_array_equal(np.asarray(p2["w"]), ref)

    @pytest.mark.chaos
    def test_save_retries_once_on_transient_io_error(self, tmp_path):
        """A single injected IO fault is absorbed by save()'s built-in
        retry; two consecutive faults escalate to the caller."""
        state = {"w": jnp.ones((3,))}
        with CheckpointManager(str(tmp_path / "retry")) as mgr:
            with chaos.inject(fail_io=1):
                assert mgr.save(1, state, force=True)
            mgr.wait()
            assert mgr.latest_step() == 1
            with chaos.inject(fail_io=2):
                with pytest.raises(OSError, match="chaos"):
                    mgr.save(2, state, force=True)


class TestNanInfGuard:
    def test_flag_catches_nan(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor(np.array([1.0, 0.0], "f"))
            with pytest.raises(FloatingPointError, match="nan|inf"):
                paddle.log(x - 1.0)  # log(0)=-inf / log(-1)=nan
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_flag_off_no_overhead_path(self):
        x = paddle.to_tensor(np.array([-1.0], "f"))
        out = paddle.log(x)  # nan, but unchecked
        assert np.isnan(np.asarray(out.numpy())).all()


# One deterministic trainer used by every end-to-end test below: 8 Adam
# steps on a fixed-seed problem, checkpointing through the resilient
# runner.  Writes per-step progress (so tests can SIGTERM mid-run) and
# the final params (so runs can be compared bitwise).
TRAINER_SRC = """
    import os, sys, time
    sys.path.insert(0, %r)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import jax, jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    from paddle_tpu.distributed.resilience import run_resilient

    out, ckpt = sys.argv[1], sys.argv[2]
    step_sleep = float(os.environ.get("TRAIN_STEP_SLEEP", "0"))
    rs = np.random.RandomState(0)
    w0 = {"w": jnp.asarray(rs.randn(4, 4) * 0.3, jnp.float32)}
    data = [jnp.asarray(rs.randn(8, 4), jnp.float32) for _ in range(8)]
    opt = paddle.optimizer.Adam(learning_rate=0.01)

    def loss_fn(p, x):
        return jnp.mean((x @ p["w"] - 1.0) ** 2)

    @jax.jit
    def train(p, s, t, x):
        l, g = jax.value_and_grad(loss_fn)(p, x)
        p2, s2 = opt.apply_pytree(p, g, s, step=t)
        return p2, s2, l

    def step_fn(step, st):
        p, s, l = train(st["params"], st["opt"], step, data[step - 1])
        with open(os.path.join(out, "progress"), "w") as f:
            f.write(str(step))
        if step_sleep:
            time.sleep(step_sleep)
        return {"params": p, "opt": s}, float(l)

    with CheckpointManager(ckpt) as mgr:
        state, info = run_resilient(
            step_fn, {"params": w0, "opt": opt.init_pytree(w0)}, mgr,
            num_steps=8, save_interval=2)
    np.save(os.path.join(out, "final.npy"),
            np.asarray(state["params"]["w"]))
""" % REPO


def _run_trainer(script, out_dir, ckpt_dir, env_extra=None, timeout=180):
    from conftest import cpu_subprocess_env
    os.makedirs(out_dir, exist_ok=True)
    env = cpu_subprocess_env()
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, str(script), str(out_dir), str(ckpt_dir)],
        env=env, capture_output=True, text=True, timeout=timeout)


def _wait_for_progress(out_dir, step, timeout=120):
    path = os.path.join(str(out_dir), "progress")
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if int(open(path).read()) >= step:
                return
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    raise AssertionError(f"trainer never reached step {step}")


@pytest.fixture(scope="session")
def preempt_script(tmp_path_factory):
    d = tmp_path_factory.mktemp("preempt")
    script = d / "trainer.py"
    script.write_text(textwrap.dedent(TRAINER_SRC))
    return script


@pytest.fixture(scope="session")
def uninterrupted_params(preempt_script, tmp_path_factory):
    """Final params of one clean 8-step run — the bitwise oracle."""
    d = tmp_path_factory.mktemp("clean")
    r = _run_trainer(preempt_script, d / "out", d / "ckpt")
    assert r.returncode == 0, r.stderr
    return np.load(str(d / "out" / "final.npy"))


@pytest.mark.slow
@pytest.mark.chaos
class TestEndToEndPreemption:
    def test_sigterm_resume_bitwise_identical(self, preempt_script,
                                              uninterrupted_params,
                                              tmp_path):
        """The acceptance path: SIGTERM a live trainer mid-run → it
        finishes the in-flight step, writes an emergency checkpoint and
        exits PREEMPTED_EXIT_CODE → a relaunch auto-resumes and reaches
        final params bitwise-identical to the uninterrupted run."""
        from conftest import cpu_subprocess_env
        out, ckpt = tmp_path / "out", tmp_path / "ckpt"
        os.makedirs(out)
        env = cpu_subprocess_env()
        env["TRAIN_STEP_SLEEP"] = "0.3"  # keep the run alive to kill it
        proc = subprocess.Popen(
            [sys.executable, str(preempt_script), str(out), str(ckpt)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        try:
            _wait_for_progress(out, 3)
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert rc == PREEMPTED_EXIT_CODE, proc.stderr.read()
        assert not (out / "final.npy").exists()

        # relaunch (as the launcher would, with PADDLE_RESTART_COUNT=1)
        r = _run_trainer(preempt_script, out, ckpt,
                         env_extra={"PADDLE_RESTART_COUNT": "1"})
        assert r.returncode == 0, r.stderr
        assert "auto-resume" in r.stderr
        resumed = np.load(str(out / "final.npy"))
        np.testing.assert_array_equal(resumed, uninterrupted_params)

    def test_launcher_chaos_preemption_roundtrip(self, preempt_script,
                                                 uninterrupted_params,
                                                 tmp_path):
        """Full-stack chaos drill: the trainer SIGTERMs itself at step 3
        (chaos injector), the hardened launcher sees the distinct
        preempted exit, backs off, restarts, and the resumed run ends
        bitwise-identical to the clean one — all under
        --restart_on=preempted."""
        from conftest import cpu_subprocess_env
        out, ckpt = tmp_path / "out", tmp_path / "ckpt"
        os.makedirs(out)
        env = cpu_subprocess_env()
        env["PADDLE_CHAOS_PREEMPT_STEP"] = "3"
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node=1", "--max_restarts=2",
             "--restart_on=preempted", "--restart_backoff=0.1",
             str(preempt_script), str(out), str(ckpt)],
            env=env, capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr
        assert "preempted — restart 1/2" in r.stderr
        final = np.load(str(out / "final.npy"))
        np.testing.assert_array_equal(final, uninterrupted_params)


class TestHardenedLauncher:
    """Restart policy, backoff, and orphan handling — plain scripts, no
    jax in the trainer, so these stay in tier-1."""

    def _launch(self, script, tmp_path, *extra, timeout=120, env=None):
        full_env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        full_env.update(env or {})
        return subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node=1", *extra, str(script)],
            env=full_env, capture_output=True, text=True, timeout=timeout)

    @pytest.mark.chaos
    def test_restart_on_preempted_restarts_preempted_trainer(self,
                                                             tmp_path):
        script = tmp_path / "pre.py"
        script.write_text(textwrap.dedent("""
            import os, sys
            if os.environ["PADDLE_RESTART_COUNT"] == "0":
                sys.exit(75)   # the resilience preempted exit code
            print("resumed fine")
        """))
        r = self._launch(script, tmp_path, "--max_restarts=2",
                         "--restart_on=preempted",
                         "--restart_backoff=0.05")
        assert r.returncode == 0, r.stderr
        assert "preempted — restart 1/2" in r.stderr

    @pytest.mark.chaos
    def test_restart_on_preempted_does_not_mask_crashes(self, tmp_path):
        script = tmp_path / "crash.py"
        script.write_text("import sys; sys.exit(1)\n")
        r = self._launch(script, tmp_path, "--max_restarts=3",
                         "--restart_on=preempted",
                         "--restart_backoff=0.05")
        assert r.returncode != 0
        assert "not restarting" in r.stderr
        # a crash with restart_on=preempted must fail FAST, not burn
        # three restart attempts
        assert "restart 1/3" not in r.stderr

    @pytest.mark.chaos
    def test_restart_backoff_logged_and_bounded(self, tmp_path):
        script = tmp_path / "flaky.py"
        script.write_text(textwrap.dedent("""
            import os, sys
            if os.environ["PADDLE_RESTART_COUNT"] == "0":
                sys.exit(1)
        """))
        r = self._launch(script, tmp_path, "--max_restarts=1",
                         "--restart_backoff=0.2")
        assert r.returncode == 0, r.stderr
        m = re.search(r"restart 1/1 in (\d+\.\d+)s", r.stderr)
        assert m, r.stderr
        # base * [1, 1 + jitter); upper bound padded for %.2f rounding
        assert 0.2 <= float(m.group(1)) <= 0.3

    def test_launcher_sigterm_reaps_trainers(self, tmp_path):
        """Orphan fix: SIGTERM to the launcher must tear down the
        trainer subprocesses (previously only KeyboardInterrupt did)."""
        script = tmp_path / "sleeper.py"
        script.write_text(textwrap.dedent("""
            import os, sys, time
            open(os.path.join(%r, "pid"), "w").write(str(os.getpid()))
            time.sleep(300)
        """ % str(tmp_path)))
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node=1", "--grace_period=5", str(script)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        try:
            pid_file = tmp_path / "pid"
            deadline = time.time() + 120
            while not pid_file.exists() and time.time() < deadline:
                time.sleep(0.05)
            assert pid_file.exists(), "trainer never started"
            trainer_pid = int(pid_file.read_text())
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert rc == 128 + signal.SIGTERM
        # the trainer must be gone (SIGTERM'd within the grace window)
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                os.kill(trainer_pid, 0)
            except ProcessLookupError:
                break
            time.sleep(0.1)
        else:
            os.kill(trainer_pid, signal.SIGKILL)
            raise AssertionError(
                f"trainer {trainer_pid} orphaned after launcher SIGTERM")


class TestLauncherRestart:
    def test_max_restarts_retries_then_succeeds(self, tmp_path):
        """Trainer fails on first attempt, succeeds on restart (reading
        PADDLE_RESTART_COUNT) — the checkpoint-resume relaunch policy."""
        script = tmp_path / "flaky.py"
        script.write_text(textwrap.dedent("""
            import os, sys
            attempt = int(os.environ["PADDLE_RESTART_COUNT"])
            if attempt == 0:
                sys.exit(1)
            print("recovered on attempt", attempt)
        """))
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node=1", "--max_restarts=2",
             "--log_dir", str(tmp_path / "lg"), str(script)],
            env=env, capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        log = (tmp_path / "lg" / "workerlog.0").read_text()
        assert "recovered on attempt 1" in log

    def test_restarts_exhausted_fails(self, tmp_path):
        script = tmp_path / "dead.py"
        script.write_text("import sys; sys.exit(1)\n")
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node=1", "--max_restarts=1", str(script)],
            env=env, capture_output=True, text=True, timeout=120)
        assert r.returncode != 0
