"""Sharded checkpointing + restart policy + nan/inf guard tests
(SURVEY.md §5: checkpoint/resume replaces the reference's nonexistent
elasticity; FLAGS_check_nan_inf is the runtime correctness guard)."""
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import (
    CheckpointManager,
    restore_sharded,
    save_sharded,
)
from paddle_tpu.distributed.mesh import build_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestShardedCheckpoint:
    def test_roundtrip_replicated(self, tmp_path):
        state = {"w": jnp.arange(12.0).reshape(3, 4),
                 "step": jnp.int32(7),
                 "nested": {"m": jnp.ones((5,))}}
        path = str(tmp_path / "ckpt1")
        save_sharded(state, path)
        back = restore_sharded(path)
        np.testing.assert_array_equal(np.asarray(back["w"]),
                                      np.asarray(state["w"]))
        assert int(back["step"]) == 7
        np.testing.assert_array_equal(np.asarray(back["nested"]["m"]),
                                      np.ones(5))

    def test_sharded_save_restore_new_sharding(self, tmp_path):
        """Save sharded over dp=8, restore onto a DIFFERENT layout
        (dp=4 x mp=2) — the mesh-reshape resume the reference lacks."""
        mesh8 = build_mesh({"dp": 8})
        w = jnp.arange(64.0).reshape(8, 8)
        w8 = jax.device_put(w, NamedSharding(mesh8, P("dp", None)))
        path = str(tmp_path / "ckpt2")
        save_sharded({"w": w8}, path)

        mesh42 = build_mesh({"dp": 4, "mp": 2})
        target_sh = {"w": NamedSharding(mesh42, P("dp", "mp"))}
        back = restore_sharded(path, template={"w": w8},
                               shardings=target_sh)
        assert back["w"].sharding == target_sh["w"]
        np.testing.assert_array_equal(np.asarray(back["w"]), np.asarray(w))

    def test_manager_rolls_and_resumes(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "run"), max_to_keep=2)
        assert mgr.restore_latest()[0] is None
        for step in (1, 2, 3):
            state = {"w": jnp.full((4,), float(step)),
                     "step": jnp.int32(step)}
            assert mgr.save(step, state, force=True)
        mgr.wait()
        assert mgr.latest_step() == 3
        assert mgr.all_steps() == [2, 3]  # rolled: keeps newest 2
        step, back = mgr.restore_latest(template=state)
        assert step == 3
        np.testing.assert_array_equal(np.asarray(back["w"]), np.full(4, 3.0))
        mgr.close()

    def test_train_resume_equivalence(self, tmp_path):
        """Train 4 steps, checkpoint the full functional training state
        (params + opt slots + step) at step 2, resume → bitwise-identical
        params to the uninterrupted run (the TPU-native resume contract)."""
        rs = np.random.RandomState(0)
        w0 = {"w": jnp.asarray(rs.randn(4, 4) * 0.3, jnp.float32)}
        data = [jnp.asarray(rs.randn(8, 4), jnp.float32) for _ in range(4)]
        opt = paddle.optimizer.Adam(learning_rate=0.01)

        def loss_fn(p, x):
            return jnp.mean((x @ p["w"] - 1.0) ** 2)

        @jax.jit
        def step(p, s, t, x):
            _, g = jax.value_and_grad(loss_fn)(p, x)
            return opt.apply_pytree(p, g, s, step=t)

        # uninterrupted
        p, s = w0, opt.init_pytree(w0)
        for t, x in enumerate(data, 1):
            p, s = step(p, s, t, x)
        ref = np.asarray(p["w"])

        # interrupted at step 2 → checkpoint → fresh process state → resume
        p, s = w0, opt.init_pytree(w0)
        for t, x in enumerate(data[:2], 1):
            p, s = step(p, s, t, x)
        mgr = CheckpointManager(str(tmp_path / "resume"))
        mgr.save(2, {"params": p, "opt": s}, force=True)
        mgr.wait()

        t0, back = mgr.restore_latest(
            template={"params": p, "opt": s})
        p2, s2 = back["params"], back["opt"]
        for t, x in enumerate(data[2:], t0 + 1):
            p2, s2 = step(p2, s2, t, x)
        np.testing.assert_array_equal(np.asarray(p2["w"]), ref)
        mgr.close()


class TestNanInfGuard:
    def test_flag_catches_nan(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor(np.array([1.0, 0.0], "f"))
            with pytest.raises(FloatingPointError, match="nan|inf"):
                paddle.log(x - 1.0)  # log(0)=-inf / log(-1)=nan
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_flag_off_no_overhead_path(self):
        x = paddle.to_tensor(np.array([-1.0], "f"))
        out = paddle.log(x)  # nan, but unchecked
        assert np.isnan(np.asarray(out.numpy())).all()


class TestLauncherRestart:
    def test_max_restarts_retries_then_succeeds(self, tmp_path):
        """Trainer fails on first attempt, succeeds on restart (reading
        PADDLE_RESTART_COUNT) — the checkpoint-resume relaunch policy."""
        script = tmp_path / "flaky.py"
        script.write_text(textwrap.dedent("""
            import os, sys
            attempt = int(os.environ["PADDLE_RESTART_COUNT"])
            if attempt == 0:
                sys.exit(1)
            print("recovered on attempt", attempt)
        """))
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node=1", "--max_restarts=2",
             "--log_dir", str(tmp_path / "lg"), str(script)],
            env=env, capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        log = (tmp_path / "lg" / "workerlog.0").read_text()
        assert "recovered on attempt 1" in log

    def test_restarts_exhausted_fails(self, tmp_path):
        script = tmp_path / "dead.py"
        script.write_text("import sys; sys.exit(1)\n")
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node=1", "--max_restarts=1", str(script)],
            env=env, capture_output=True, text=True, timeout=120)
        assert r.returncode != 0
