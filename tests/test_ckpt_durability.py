"""Durable-checkpoint corruption matrix (distributed/checkpoint.py).

Every way a checkpoint can rot on disk — torn write (SIGKILL between
rename and COMMIT marker), bit-flip at rest, missing manifest, missing
leaf, truncated leaf, ENOSPC at save time — crossed with every restore
path: fresh `restore_latest`, mid-cascade (newest TWO generations bad),
and all-generations-bad → clean `(None, None)` fresh start.  Plus the
non-blocking AsyncCheckpointer (depth-1 newest-wins queue, degrade-then-
escalate failure policy) and elastic resume (dp8-saved checkpoint onto a
dp1 mesh) at both the checkpoint and the Model.fit level.

All corruption is injected deterministically through the chaos layer
(PADDLE_CHAOS_CKPT_TORN / _BITFLIP / _ENOSPC / _SLOW_IO) or direct file
surgery — no mocks; the bytes on disk are really wrong.
"""
import errno
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import (
    COMMIT_NAME,
    MANIFEST_NAME,
    AsyncCheckpointer,
    CheckpointCorruption,
    CheckpointManager,
    restore_sharded,
    save_sharded,
)
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.resilience import (
    DURABILITY_EXIT_CODE,
    is_transient_io_error,
    retry_with_backoff,
)
from paddle_tpu.utils import chaos

pytestmark = pytest.mark.chaos


def _state(val: float):
    return {"w": jnp.full((4, 4), float(val), jnp.float32),
            "opt": {"m": jnp.full((4, 4), float(val) * 0.5, jnp.float32)},
            "step": jnp.int32(int(val))}


def _save_gens(mgr, vals):
    for v in vals:
        assert mgr.save(int(v), _state(v), force=True)


def _gen_dir(mgr, step):
    return os.path.join(mgr.directory, str(step))


def _assert_restores(mgr, expect_step):
    step, back = mgr.restore_latest(template=_state(0))
    assert step == expect_step
    np.testing.assert_array_equal(np.asarray(back["w"]),
                                  np.full((4, 4), float(expect_step), "f"))
    return back


class TestAtomicCommitProtocol:
    def test_generation_layout_and_manifest(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            mgr.save(3, _state(3), force=True,
                     meta={"mesh": {"dp": 8, "devices": 8}})
            gen = _gen_dir(mgr, 3)
            assert os.path.exists(os.path.join(gen, COMMIT_NAME))
            man = json.load(open(os.path.join(gen, MANIFEST_NAME)))
            assert man["format"] == "paddle_tpu.ckpt.v1"
            assert man["framework_version"] == paddle.__version__
            assert man["meta"]["mesh"]["dp"] == 8
            by_key = {e["key"]: e for e in man["leaves"]}
            assert set(by_key) == {"/w", "/opt/m", "/step"}
            e = by_key["/w"]
            assert e["dtype"] == "float32" and e["shape"] == [4, 4]
            raw = open(os.path.join(gen, e["file"]), "rb").read()
            import zlib
            assert (zlib.crc32(raw) & 0xFFFFFFFF) == e["crc32"]

    def test_no_tmp_dirs_left_behind(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1, 2])
            assert not [n for n in os.listdir(mgr.directory)
                        if n.startswith(".tmp-")]

    def test_manifest_api(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            mgr.save(5, _state(5), force=True, meta={"note": "x"})
            assert mgr.manifest(5)["meta"]["note"] == "x"
            assert mgr.manifest(99) is None


class TestCorruptionMatrix:
    """Injector × restore-path grid.  Every bad generation must be
    quarantined (with the true reason) and the cascade must land on the
    newest VALID generation bitwise."""

    def test_torn_write_chaos_cascades(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1])
            with chaos.inject(ckpt_torn=1) as cfg:
                with pytest.raises(chaos.ChaosTorn):
                    mgr.save(2, _state(2), force=True)
            assert cfg.fired == ["torn@checkpoint.commit"]
            # the torn generation IS on disk — visible, but unmarked
            assert os.path.isdir(_gen_dir(mgr, 2))
            assert not os.path.exists(
                os.path.join(_gen_dir(mgr, 2), COMMIT_NAME))
            assert mgr.latest_step() == 1  # torn gen not "committed"
            _assert_restores(mgr, 1)
            names = [n for n, _ in mgr.quarantined()]
            assert any(n.startswith("2.torn-write") for n in names), names

    def test_bitflip_chaos_cascades(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1])
            with chaos.inject(ckpt_bitflip=1) as cfg:
                mgr.save(2, _state(2), force=True)  # "succeeds"
            assert cfg.fired and cfg.fired[0].startswith("bitflip@")
            assert mgr.latest_step() == 2  # committed — only crc knows
            _assert_restores(mgr, 1)
            assert any("crc-mismatch" in n for n, _ in mgr.quarantined())

    def test_bitflip_direct_file_surgery(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1, 2])
            leaf = os.path.join(_gen_dir(mgr, 2), "leaves", "0.bin")
            blob = bytearray(open(leaf, "rb").read())
            blob[len(blob) // 2] ^= 0x10
            open(leaf, "wb").write(bytes(blob))
            _assert_restores(mgr, 1)

    def test_missing_manifest_cascades(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1, 2])
            os.remove(os.path.join(_gen_dir(mgr, 2), MANIFEST_NAME))
            _assert_restores(mgr, 1)
            assert any("missing-manifest" in n
                       for n, _ in mgr.quarantined())

    def test_missing_leaf_cascades(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1, 2])
            os.remove(os.path.join(_gen_dir(mgr, 2), "leaves", "1.bin"))
            _assert_restores(mgr, 1)
            assert any("missing-leaf" in n for n, _ in mgr.quarantined())

    def test_truncated_leaf_cascades(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1, 2])
            leaf = os.path.join(_gen_dir(mgr, 2), "leaves", "0.bin")
            blob = open(leaf, "rb").read()
            open(leaf, "wb").write(blob[:len(blob) // 2])
            _assert_restores(mgr, 1)

    def test_missing_commit_marker_cascades(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1, 2])
            os.remove(os.path.join(_gen_dir(mgr, 2), COMMIT_NAME))
            _assert_restores(mgr, 1)

    def test_mid_cascade_two_bad_generations(self, tmp_path):
        """Newest gen torn AND second-newest bit-flipped: the cascade
        walks through BOTH and lands on the oldest, still bounded by
        max_to_keep."""
        with CheckpointManager(str(tmp_path), max_to_keep=3) as mgr:
            _save_gens(mgr, [1, 2, 3])
            os.remove(os.path.join(_gen_dir(mgr, 3), COMMIT_NAME))
            leaf = os.path.join(_gen_dir(mgr, 2), "leaves", "0.bin")
            blob = bytearray(open(leaf, "rb").read())
            blob[0] ^= 0xFF
            open(leaf, "wb").write(bytes(blob))
            _assert_restores(mgr, 1)
            assert len(mgr.quarantined()) == 2

    def test_all_generations_bad_fresh_start(self, tmp_path):
        with CheckpointManager(str(tmp_path), max_to_keep=2) as mgr:
            _save_gens(mgr, [1, 2])
            for s in (1, 2):
                os.remove(os.path.join(_gen_dir(mgr, s), COMMIT_NAME))
            step, state = mgr.restore_latest(template=_state(0))
            assert (step, state) == (None, None)
            assert len(mgr.quarantined()) == 2
            # the manager still works after total loss: a new save and
            # restore round-trips (recovery, not a crash loop)
            mgr.save(7, _state(7), force=True)
            _assert_restores(mgr, 7)

    def test_explicit_restore_raises_instead_of_cascading(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1, 2])
            os.remove(os.path.join(_gen_dir(mgr, 2), COMMIT_NAME))
            with pytest.raises(CheckpointCorruption, match="torn-write"):
                mgr.restore(2, template=_state(0))
            # the explicit path must NOT quarantine behind the caller
            assert os.path.isdir(_gen_dir(mgr, 2))

    def test_quarantine_preserves_evidence(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1, 2])
            os.remove(os.path.join(_gen_dir(mgr, 2), MANIFEST_NAME))
            mgr.restore_latest(template=_state(0))
            (_, qpath), = [q for q in mgr.quarantined()]
            # payload bytes still there for the post-mortem
            assert os.path.exists(os.path.join(qpath, "leaves", "0.bin"))


class TestErrnoSplit:
    def test_classification(self):
        assert is_transient_io_error(OSError(errno.EIO, "io"))
        assert is_transient_io_error(OSError("gcs blip, no errno"))
        assert is_transient_io_error(TimeoutError("slow"))  # OSError kin
        assert not is_transient_io_error(OSError(errno.ENOSPC, "full"))
        assert not is_transient_io_error(OSError(errno.EROFS, "ro"))
        assert not is_transient_io_error(OSError(errno.EACCES, "perm"))
        assert not is_transient_io_error(ValueError("not io at all"))

    def test_save_does_not_retry_enospc(self, tmp_path):
        """The satellite fix: ENOSPC escalates on the FIRST attempt —
        were it retried like EIO, the second attempt would find the
        chaos budget exhausted and 'succeed', masking the condition."""
        with CheckpointManager(str(tmp_path)) as mgr:
            with chaos.inject(ckpt_enospc=1) as cfg:
                with pytest.raises(OSError) as ei:
                    mgr.save(1, _state(1), force=True)
            assert ei.value.errno == errno.ENOSPC
            assert cfg.fired == ["enospc@checkpoint.save"]
            assert mgr.latest_step() is None

    def test_save_still_retries_transient_once(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            with chaos.inject(fail_io=1):
                assert mgr.save(1, _state(1), force=True)
            assert mgr.latest_step() == 1

    def test_save_transient_retry_can_be_disabled(self, tmp_path):
        """transient_retry=False hands the FIRST transient failure to
        the caller: ResilientRunner owns its own backoff loop, and two
        stacked retry layers would multiply the worst-case stall."""
        with CheckpointManager(str(tmp_path)) as mgr:
            with chaos.inject(fail_io=1):
                with pytest.raises(OSError):
                    mgr.save(1, _state(1), force=True,
                             transient_retry=False)
            assert mgr.latest_step() is None

    def test_retry_with_backoff_predicate_stops_immediately(self):
        sleeps, calls = [], []

        def always_enospc():
            calls.append(1)
            raise OSError(errno.ENOSPC, "full")

        with pytest.raises(OSError):
            retry_with_backoff(always_enospc, retries=5,
                               should_retry=is_transient_io_error,
                               sleep=sleeps.append)
        assert len(calls) == 1 and sleeps == []


class TestAsyncCheckpointer:
    def test_submit_lands_durably(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            with AsyncCheckpointer(mgr) as saver:
                saver.submit(1, jax.tree_util.tree_map(np.asarray,
                                                       _state(1)))
                assert saver.flush(timeout=30)
            assert mgr.latest_step() == 1
            assert saver.saved_generations == 1

    def test_submit_never_blocks_on_slow_io(self, tmp_path):
        """The non-blocking contract: with every checkpoint IO stalled
        0.4s, submit() still returns in microseconds — the stall lands
        on the writer thread, not the training thread."""
        host = jax.tree_util.tree_map(np.asarray, _state(1))
        with CheckpointManager(str(tmp_path)) as mgr:
            with chaos.inject(ckpt_slow_io=0.4):
                with AsyncCheckpointer(mgr) as saver:
                    t0 = time.monotonic()
                    saver.submit(1, host)
                    elapsed = time.monotonic() - t0
                    assert elapsed < 0.2, elapsed
                    assert saver.flush(timeout=30)
            assert mgr.latest_step() == 1

    def test_newest_wins_depth_one(self, tmp_path):
        """Three rapid submits against a stalled disk: the queue holds
        ONE pending generation, intermediate ones are dropped, the
        newest survives."""
        with CheckpointManager(str(tmp_path)) as mgr:
            with chaos.inject(ckpt_slow_io=0.3):
                with AsyncCheckpointer(mgr) as saver:
                    for v in (1, 2, 3):
                        saver.submit(v, jax.tree_util.tree_map(
                            np.asarray, _state(v)))
                    assert saver.flush(timeout=30)
            assert saver.dropped >= 1
            assert mgr.latest_step() == 3

    def test_degrade_then_escalate(self, tmp_path):
        """K consecutive failed generations flip .fatal and fire
        on_fatal; a success in between resets the streak."""
        fatal_errs = []
        with CheckpointManager(str(tmp_path)) as mgr:
            saver = AsyncCheckpointer(mgr, max_failures=2,
                                      on_fatal=fatal_errs.append)
            host = jax.tree_util.tree_map(np.asarray, _state(1))
            with chaos.inject(ckpt_enospc=1):
                saver.submit(1, host)
                saver.flush(timeout=30)
            assert saver.consecutive_failures == 1 and not saver.fatal
            # success resets the streak (degrade, not escalate)
            saver.submit(2, host)
            saver.flush(timeout=30)
            assert saver.consecutive_failures == 0
            with chaos.inject(ckpt_enospc=4):
                saver.submit(3, host)
                saver.flush(timeout=30)
                saver.submit(4, host)
                saver.flush(timeout=30)
            assert saver.fatal
            assert fatal_errs and fatal_errs[0].errno == errno.ENOSPC
            # post-fatal submits are refused, not buffered
            assert saver.submit(5, host) is False
            saver.close()


def _model_and_data(n=32):
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                               paddle.nn.ReLU(),
                               paddle.nn.Linear(8, 2))
    rs = np.random.RandomState(0)
    x = rs.randn(n, 4).astype("float32")
    y = (x.sum(1) > 0).astype("int64")
    ds = paddle.io.TensorDataset([paddle.to_tensor(x),
                                  paddle.to_tensor(y)])
    from paddle_tpu.hapi import Model

    model = Model(net)
    model.prepare(
        paddle.optimizer.Adam(learning_rate=0.01,
                              parameters=net.parameters()),
        paddle.nn.CrossEntropyLoss())
    return model, ds


def _weights(model):
    return {k: np.asarray(p._value)
            for k, p in model.network.named_parameters()}


class TestFitDurability:
    def test_fit_escalates_after_k_failed_generations(self, tmp_path):
        """Training itself stays healthy while saves fail (degrade);
        after FLAGS_ckpt_max_failures consecutive failed generations fit
        aborts with the distinct durability exit code so the launcher
        can alert."""
        model, ds = _model_and_data()
        with chaos.inject(ckpt_enospc=99):
            with pytest.raises(SystemExit) as ei:
                model.fit(ds, batch_size=8, epochs=8, shuffle=False,
                          verbose=0, resume=str(tmp_path),
                          checkpoint_interval=1)
        assert ei.value.code == DURABILITY_EXIT_CODE

    def test_fit_escalates_sync_path(self, tmp_path):
        """Degrade-then-escalate holds with SYNCHRONOUS saves too
        (FLAGS_ckpt_async=False): failed generations warn and training
        continues, the K-th consecutive failure exits with the
        durability code — never a raw OSError out of fit (the launcher
        would treat that as a crash and burn restarts on a full
        disk)."""
        paddle.set_flags({"FLAGS_ckpt_async": False})
        try:
            model, ds = _model_and_data()
            with chaos.inject(ckpt_enospc=99):
                with pytest.raises(SystemExit) as ei:
                    model.fit(ds, batch_size=8, epochs=8, shuffle=False,
                              verbose=0, resume=str(tmp_path),
                              checkpoint_interval=1)
            assert ei.value.code == DURABILITY_EXIT_CODE
        finally:
            paddle.set_flags({"FLAGS_ckpt_async": True})

    def test_max_failures_zero_does_not_spuriously_escalate(self,
                                                            tmp_path):
        """FLAGS_ckpt_max_failures=0 (zero tolerance) must still mean
        'escalate on the first FAILURE' — not 'exit 91 with zero
        failures on the first healthy batch' (0 >= 0)."""
        paddle.set_flags({"FLAGS_ckpt_max_failures": 0})
        try:
            model, ds = _model_and_data()
            model.fit(ds, batch_size=8, epochs=1, shuffle=False,
                      verbose=0, resume=str(tmp_path),
                      checkpoint_interval=1)
        finally:
            paddle.set_flags({"FLAGS_ckpt_max_failures": 3})
        with CheckpointManager(os.path.join(str(tmp_path),
                                            "resilient")) as mgr:
            assert mgr.latest_step() == 4

    def test_fit_inside_exception_handler_completes(self, tmp_path):
        """sys.exc_info() is THREAD-wide, not frame-local: a caller
        retry loop (`except: model.fit(...)`) must not silently disable
        fit's success-path finally branches (final write-back,
        durability escalation)."""
        model, ds = _model_and_data()
        try:
            raise RuntimeError("ambient exception in the caller")
        except RuntimeError:
            h = model.fit(ds, batch_size=8, epochs=1, shuffle=False,
                          verbose=0, resume=str(tmp_path),
                          checkpoint_interval=2)
        assert len(h["loss"]) == 1
        with CheckpointManager(os.path.join(str(tmp_path),
                                            "resilient")) as mgr:
            assert mgr.latest_step() == 4

    def test_preempted_exit_survives_failed_emergency_save(self,
                                                           tmp_path):
        """A failed emergency checkpoint (disk died after the last
        durable generation) must not mask the preempted exit code: the
        launcher still sees exit 75 and restarts, resuming from the
        newest durable generation."""
        from paddle_tpu.distributed.resilience import PREEMPTED_EXIT_CODE

        model, ds = _model_and_data()
        with chaos.inject(preempt_at_step=2, ckpt_enospc=99):
            with pytest.raises(SystemExit) as ei:
                model.fit(ds, batch_size=8, epochs=4, shuffle=False,
                          verbose=0, fault_tolerant=True,
                          resume=str(tmp_path))
        assert ei.value.code == PREEMPTED_EXIT_CODE

    def test_emergency_save_skips_already_durable_generation(
            self, tmp_path, monkeypatch):
        """Preemption landing on the same iteration as an interval save
        must NOT force-rewrite the just-committed generation: the
        rewrite would spend SIGTERM-grace-window time on a duplicate
        write while transiently TEARING the very generation that is the
        recovery point (force = rmtree-then-rewrite)."""
        import paddle_tpu.distributed.checkpoint as ckpt
        from paddle_tpu.distributed.resilience import PREEMPTED_EXIT_CODE

        writes = []
        real = ckpt._write_generation

        def counting(final_dir, state, meta=None, step=None):
            writes.append(os.path.basename(final_dir))
            return real(final_dir, state, meta=meta, step=step)

        monkeypatch.setattr(ckpt, "_write_generation", counting)
        model, ds = _model_and_data()
        with chaos.inject(preempt_at_step=2):
            with pytest.raises(SystemExit) as ei:
                model.fit(ds, batch_size=8, epochs=2, shuffle=False,
                          verbose=0, fault_tolerant=True,
                          resume=str(tmp_path), checkpoint_interval=2)
        assert ei.value.code == PREEMPTED_EXIT_CODE
        assert writes.count("2") == 1  # interval save only, no rewrite
        with CheckpointManager(os.path.join(str(tmp_path),
                                            "resilient")) as mgr:
            assert mgr.latest_step() == 2

    def test_fit_resumes_through_corrupted_latest(self, tmp_path):
        """End-to-end cascade: phase 1 checkpoints at iterations 4 and
        8; the newest generation is torn (COMMIT removed); a fresh
        process-equivalent resume quarantines it, restores iteration 4,
        replays, and ends bitwise-identical to the uninterrupted run."""
        ma, ds = _model_and_data()
        ma.fit(ds, batch_size=8, epochs=4, shuffle=False, verbose=0)
        ref = _weights(ma)

        mb, ds = _model_and_data()
        mb.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
               resume=str(tmp_path), checkpoint_interval=4)
        ckdir = os.path.join(str(tmp_path), "resilient")
        with CheckpointManager(ckdir) as mgr:
            assert mgr.latest_step() == 8
        os.remove(os.path.join(ckdir, "8", COMMIT_NAME))

        mc, ds = _model_and_data()
        mc.fit(ds, batch_size=8, epochs=4, shuffle=False, verbose=0,
               resume=str(tmp_path), checkpoint_interval=4)
        got = _weights(mc)
        for k in ref:
            np.testing.assert_array_equal(got[k], ref[k], err_msg=k)
        with CheckpointManager(ckdir) as mgr:
            assert any(n.startswith("8.torn-write")
                       for n, _ in mgr.quarantined())

    def test_fit_async_saves_match_sync_bitwise(self, tmp_path):
        """FLAGS_ckpt_async must be invisible to training numerics: the
        same run with background and synchronous saves produces
        bitwise-identical checkpoints."""
        import paddle_tpu.framework.flags as fl

        ma, ds = _model_and_data()
        ma.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
               resume=str(tmp_path / "async"))
        paddle.set_flags({"FLAGS_ckpt_async": False})
        try:
            mb, ds = _model_and_data()
            mb.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
                   resume=str(tmp_path / "sync"))
        finally:
            paddle.set_flags({"FLAGS_ckpt_async": True})
        wa, wb = _weights(ma), _weights(mb)
        for k in wa:
            np.testing.assert_array_equal(wa[k], wb[k], err_msg=k)
        for sub in ("async", "sync"):
            with CheckpointManager(os.path.join(str(tmp_path), sub,
                                                "resilient")) as mgr:
                assert mgr.latest_step() == 8


@pytest.mark.dp
class TestElasticResume:
    """dp-degree elasticity: a checkpoint saved on a dp=8 mesh restores
    and continues on dp=1 (and vice versa)."""

    def test_manager_level_reshard(self, tmp_path):
        mesh8 = build_mesh({"dp": 8})
        w = jnp.arange(64.0, dtype=jnp.float32).reshape(8, 8)
        state = {"w": jax.device_put(w, NamedSharding(mesh8, P("dp")))}
        with CheckpointManager(str(tmp_path)) as mgr:
            mgr.save(1, state, force=True,
                     meta={"mesh": {"dp": 8, "devices": 8}})
            mesh4 = build_mesh({"dp": 4}, devices=jax.devices()[:4])
            sh = {"w": NamedSharding(mesh4, P("dp"))}
            step, back = mgr.restore_latest(template={"w": w},
                                            shardings=sh)
        assert step == 1
        assert back["w"].sharding == sh["w"]
        np.testing.assert_array_equal(np.asarray(back["w"]),
                                      np.asarray(w))
        assert mgr.last_restore_manifest["meta"]["mesh"]["dp"] == 8

    def test_dp8_save_dp1_restore_bitwise_at_restore_point(self, tmp_path,
                                                           caplog):
        """The restore itself is lossless across meshes: weights right
        after a dp8→dp1 elastic resume equal the dp8-saved weights
        bit for bit."""
        ma, ds = _model_and_data()
        ma.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
               mesh={"dp": 8}, resume=str(tmp_path))
        w8 = _weights(ma)

        mb, ds = _model_and_data()
        with caplog.at_level("INFO", logger="paddle_tpu.hapi"):
            mb.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
                   mesh={"dp": 1}, resume=str(tmp_path))
        out = caplog.text
        assert "ELASTIC resume" in out and "dp=8" in out
        got = _weights(mb)
        for k in w8:
            np.testing.assert_array_equal(got[k], w8[k], err_msg=k)

    def test_dp8_save_dp1_continue_training_ulp(self, tmp_path):
        """Continued training after the elastic restore agrees with a
        dp1-throughout run to f32 ULP (PR 4's documented reassociation
        bound — XLA re-associates batch reductions across dp degrees,
        so bitwise equality across dp is unattainable by construction)."""
        ma, ds = _model_and_data()
        ma.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
               mesh={"dp": 1})
        ref = _weights(ma)

        mb, ds = _model_and_data()
        mb.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
               mesh={"dp": 8}, resume=str(tmp_path))
        mc, ds = _model_and_data()
        mc.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
               mesh={"dp": 1}, resume=str(tmp_path))
        got = _weights(mc)
        for k in ref:
            np.testing.assert_allclose(got[k], ref[k], rtol=1e-4,
                                       atol=1e-6, err_msg=k)

    def test_dp1_save_dp8_restore(self, tmp_path, caplog):
        """Elasticity is symmetric: scale UP from dp1 to dp8 too."""
        ma, ds = _model_and_data()
        ma.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
               resume=str(tmp_path))
        w1 = _weights(ma)

        mb, ds = _model_and_data()
        with caplog.at_level("INFO", logger="paddle_tpu.hapi"):
            mb.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
                   mesh={"dp": 8}, resume=str(tmp_path))
        out = caplog.text
        assert "ELASTIC resume" in out
        got = _weights(mb)
        for k in w1:
            np.testing.assert_array_equal(got[k], w1[k], err_msg=k)


class TestReviewHardening:
    """Regressions pinned after review: template drift must not
    quarantine valid bytes, legacy orbax generations must still resume,
    the lr schedule must be LIVE after resume, the launcher must not
    burn restarts on durability loss, and a failed fit-setup must not
    leak the mesh placement hook onto the user's DataLoader."""

    def test_template_mismatch_propagates_without_quarantine(self,
                                                             tmp_path):
        from paddle_tpu.distributed.checkpoint import (
            CheckpointTemplateMismatch)

        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1])
            bad_template = dict(_state(0), extra=jnp.zeros((2,)))
            with pytest.raises(CheckpointTemplateMismatch,
                               match="absent from checkpoint"):
                mgr.restore_latest(template=bad_template)
            # the intact generation is STILL there, not quarantined
            assert mgr.latest_step() == 1
            assert mgr.quarantined() == []
            _assert_restores(mgr, 1)

    def test_restore_sharded_without_template_applies_shardings(
            self, tmp_path):
        """The template-less restore path must honor `shardings` — the
        docstring sells it as the elastic-resume routing with no
        template requirement, so silently landing everything on the
        default device would be a lie with an OOM attached."""
        mesh = build_mesh({"dp": jax.device_count()})
        path = str(tmp_path / "gen")
        state = {"w": jnp.arange(16, dtype=jnp.float32).reshape(8, 2),
                 "b": jnp.float32(7)}
        save_sharded(state, path)
        sh = {"w": NamedSharding(mesh, P("dp")),
              "b": NamedSharding(mesh, P())}
        back = restore_sharded(path, shardings=sh)
        assert back["w"].sharding == sh["w"]
        assert back["b"].sharding == sh["b"]
        np.testing.assert_array_equal(
            np.asarray(back["w"]),
            np.arange(16, dtype="f").reshape(8, 2))

    def test_restore_sharded_missing_manifest_is_corruption(
            self, tmp_path):
        """A generation with native artifacts (COMMIT, leaves/) but no
        manifest is corrupted-NATIVE, not legacy orbax: the functional
        API must raise the designed CheckpointCorruption, not hand the
        dir to orbax for an opaque format error."""
        path = str(tmp_path / "gen")
        save_sharded(_state(3), path)
        os.remove(os.path.join(path, MANIFEST_NAME))
        with pytest.raises(CheckpointCorruption, match="missing-manifest"):
            restore_sharded(path, template=_state(0))

    def test_save_rejects_colliding_keypaths(self, tmp_path):
        """A dict key containing '/' can flatten to the same keypath as
        genuine nesting; restoring such a manifest would silently hand
        both slots the same bytes — the save must fail loudly."""
        state = {"a": {"b": jnp.ones((2,), jnp.float32)},
                 "a/b": jnp.zeros((2,), jnp.float32)}
        with CheckpointManager(str(tmp_path)) as mgr:
            with pytest.raises(ValueError, match="colliding"):
                mgr.save(1, state, force=True)
            assert mgr.latest_step() is None

    def test_save_rejects_object_dtype_leaves(self, tmp_path):
        """np.asarray(None).tobytes() would 'save' 8 pointer bytes the
        manifest faithfully crcs — verification passes forever, restore
        ALWAYS fails (frombuffer cannot build object arrays).  Reject at
        save time, where the caller can still see why."""
        state = {"w": jnp.ones((2,), jnp.float32), "rng": None}
        with CheckpointManager(str(tmp_path)) as mgr:
            with pytest.raises(ValueError, match="object dtype"):
                mgr.save(1, state, force=True)
            assert mgr.latest_step() is None

    def test_read_error_cascades_without_quarantine(self, tmp_path,
                                                    monkeypatch):
        """An OSError READING a verified generation's payload (EIO
        blip, a leaf vanishing between verify's stat and the open) must
        cascade past the generation — never crash auto-resume into the
        launcher's restart budget — and must NOT quarantine bytes
        nothing proved bad."""
        import paddle_tpu.distributed.checkpoint as ckpt

        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1, 2])
            real = ckpt._read_leaf

            def flaky(gen_dir, entry):
                if gen_dir.endswith(os.sep + "2"):
                    raise OSError(errno.EIO, "injected read blip")
                return real(gen_dir, entry)

            monkeypatch.setattr(ckpt, "_read_leaf", flaky)
            _assert_restores(mgr, 1)
            assert mgr.quarantined() == []
            assert os.path.exists(os.path.join(_gen_dir(mgr, 2),
                                               COMMIT_NAME))

    def test_async_close_timeout_logs_loudly(self, tmp_path, caplog):
        """AsyncCheckpointer.close() abandoning an undrained write must
        say so — silently dropping the newest generation while fit's
        comment promises durability would be the worst kind of lie."""
        import logging

        with CheckpointManager(str(tmp_path)) as mgr:
            saver = AsyncCheckpointer(mgr)
            with chaos.inject(ckpt_slow_io=2.0):
                saver.submit(1, _state(1), force=True)
                time.sleep(0.1)  # let the writer pick the job up
                with caplog.at_level(logging.ERROR,
                                     logger="paddle_tpu.checkpoint"):
                    saver.close(timeout=0.2)
        assert "not drained" in caplog.text

    def test_legacy_orbax_generation_restores(self, tmp_path):
        import orbax.checkpoint as ocp

        state = {"w": jnp.full((3,), 9.0, jnp.float32)}
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(str(tmp_path), "4"), state)
        ckptr.wait_until_finished()
        with CheckpointManager(str(tmp_path)) as mgr:
            step, back = mgr.restore_latest(template=state)
            assert step == 4
            np.testing.assert_array_equal(np.asarray(back["w"]),
                                          np.full(3, 9.0, "f"))
            assert mgr.quarantined() == []

    def test_legacy_orbax_with_structure_only_template(self, tmp_path):
        """The fit resume path passes a None-leaf template; jax.tree.map
        treats None as an EMPTY pytree, so a naive orbax fallback would
        silently echo the Nones back as the 'restored' state — the
        fallback must restore the REAL arrays instead."""
        import orbax.checkpoint as ocp

        state = {"params": {"w": jnp.full((3,), 9.0, jnp.float32)},
                 "meta": {"it": jnp.int32(4)}}
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(str(tmp_path), "4"), state)
        ckptr.wait_until_finished()
        with CheckpointManager(str(tmp_path)) as mgr:
            step, back = mgr.restore_latest(
                template={"params": {"w": None}, "meta": {"it": None}})
            assert step == 4
            assert back["params"]["w"] is not None
            np.testing.assert_array_equal(np.asarray(back["params"]["w"]),
                                          np.full(3, 9.0, "f"))
            assert int(np.asarray(back["meta"]["it"])) == 4

    def test_reduce_on_plateau_state_survives_resume(self, tmp_path):
        """ReduceOnPlateau's decision state (best / num_bad_epochs /
        the already-reduced last_lr) rides in the manifest meta —
        step(epoch=) alone is a silent no-op for it."""
        from paddle_tpu.optimizer.lr import ReduceOnPlateau

        def build(sched):
            paddle.seed(0)
            net = paddle.nn.Sequential(paddle.nn.Linear(4, 2))
            from paddle_tpu.hapi import Model
            m = Model(net)
            m.prepare(paddle.optimizer.Adam(
                learning_rate=sched, parameters=net.parameters()),
                paddle.nn.CrossEntropyLoss())
            return m

        rs = np.random.RandomState(0)
        ds = paddle.io.TensorDataset(
            [paddle.to_tensor(rs.randn(16, 4).astype("float32")),
             paddle.to_tensor(rs.randint(0, 2, (16,)).astype("int64"))])

        sa = ReduceOnPlateau(learning_rate=0.1, patience=0)
        # drive the plateau logic: two non-improving metrics cut the lr
        sa.step(metrics=1.0)
        sa.step(metrics=2.0)
        sa.step(metrics=3.0)
        assert sa.last_lr < 0.1
        ma = build(sa)
        ma.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
               resume=str(tmp_path))

        sb = ReduceOnPlateau(learning_rate=0.1, patience=0)
        mb = build(sb)
        from paddle_tpu.hapi.engine import TrainEngine
        mb._engine = TrainEngine(mb).begin()
        with CheckpointManager(os.path.join(str(tmp_path),
                                            "resilient")) as mgr:
            mb._ft_restore(mgr)
        assert sb.last_lr == pytest.approx(sa.last_lr)
        assert sb.best == pytest.approx(sa.best)

    def test_lr_schedule_live_after_resume(self, tmp_path):
        """sched.step(epoch=) on restore recomputes last_lr: the
        resumed optimizer serves the epoch-N lr immediately, not the
        fresh-init lr."""
        def build(lr_sched):
            paddle.seed(0)
            net = paddle.nn.Sequential(paddle.nn.Linear(4, 4))
            from paddle_tpu.hapi import Model
            m = Model(net)
            m.prepare(paddle.optimizer.Adam(
                learning_rate=lr_sched, parameters=net.parameters()),
                paddle.nn.CrossEntropyLoss())
            return m

        rs = np.random.RandomState(0)
        ds = paddle.io.TensorDataset(
            [paddle.to_tensor(rs.randn(16, 4).astype("float32")),
             paddle.to_tensor(rs.randint(0, 2, (16,)).astype("int64"))])

        from paddle_tpu.optimizer.lr import StepDecay
        from paddle_tpu.hapi.callbacks import LRScheduler as LRCb

        ma = build(StepDecay(learning_rate=0.1, step_size=1, gamma=0.5))
        ma.fit(ds, batch_size=8, epochs=3, shuffle=False, verbose=0,
               resume=str(tmp_path), callbacks=[LRCb()])
        lr_after = ma._optimizer.get_lr()

        mb = build(StepDecay(learning_rate=0.1, step_size=1, gamma=0.5))
        mb._engine = None
        from paddle_tpu.hapi.engine import TrainEngine
        mb._engine = TrainEngine(mb).begin()
        with CheckpointManager(os.path.join(str(tmp_path),
                                            "resilient")) as mgr:
            mb._ft_restore(mgr)
        assert mb._optimizer.get_lr() == pytest.approx(lr_after)

    def test_launcher_does_not_restart_on_durability_exit(self, tmp_path):
        import subprocess
        import sys
        import textwrap

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = tmp_path / "lost.py"
        script.write_text(textwrap.dedent(f"""
            import sys
            sys.exit({DURABILITY_EXIT_CODE})
        """))
        env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node=1", "--max_restarts=3",
             "--restart_backoff=0.05", str(script)],
            env=env, capture_output=True, text=True, timeout=120)
        assert r.returncode != 0
        assert "lost checkpoint durability" in r.stderr
        assert "restart 1/3" not in r.stderr  # budget untouched

    def test_failed_ft_setup_does_not_leak_placement(self, tmp_path):
        from paddle_tpu.io import DataLoader

        model, ds = _model_and_data()
        loader = DataLoader(ds, batch_size=8, shuffle=False)
        with pytest.raises(ValueError, match="directory"):
            model.fit(loader, epochs=1, verbose=0, mesh={"dp": 8},
                      fault_tolerant=True)  # no dir -> raises in setup
        assert loader.placement is None


class TestSecondReviewHardening:
    """Regressions pinned after the second review pass: shared-path
    mutations are writer-only, the close() drain budget is honored for
    stalled writers, mixed-type dict keys reach the requires-template
    fallback instead of a TypeError, and legacy orbax generations are
    reclaimed once native coverage fills the retention window."""

    def test_non_writer_process_never_quarantines(self, tmp_path):
        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1, 2])
            # tear generation 2 the way a racing writer mid-save looks
            os.remove(os.path.join(_gen_dir(mgr, 2), "COMMIT"))
            mgr._is_writer_process = False
            _assert_restores(mgr, 1)  # cascades past the torn gen
            # ...but the shared dir was NOT mutated out from under the
            # writer process that owns it
            assert os.path.isdir(_gen_dir(mgr, 2))
            assert mgr.quarantined() == []

    def test_close_skips_join_when_drain_budget_blown(self, tmp_path):
        import threading
        import time

        from paddle_tpu.distributed.checkpoint import AsyncCheckpointer

        release = threading.Event()

        class StallingMgr(CheckpointManager):
            def save(self, *a, **kw):
                release.wait(timeout=30.0)
                return super().save(*a, **kw)

        with StallingMgr(str(tmp_path)) as mgr:
            saver = AsyncCheckpointer(mgr)
            saver.submit(1, {"w": np.ones((2,), np.float32)})
            t0 = time.monotonic()
            saver.close(timeout=0.0)  # the preemption path's budget
            elapsed = time.monotonic() - t0
            release.set()
            assert elapsed < 2.0, (
                f"close() with a zero drain budget blocked {elapsed:.1f}s "
                "joining a stalled writer")

    def test_mixed_type_dict_keys_roundtrip_with_template(self, tmp_path):
        state = {"w": np.ones((3,), np.float32),
                 0: np.zeros((2,), np.float32)}
        with CheckpointManager(str(tmp_path)) as mgr:
            # assume_host: the async-writer path, which skips jax's own
            # (also mixed-key-intolerant) pytree sort in _host_view
            assert mgr.save(1, state, force=True, assume_host=True)
            step, back = mgr.restore_latest(template={"w": None, 0: None})
        assert step == 1
        np.testing.assert_array_equal(back["w"], state["w"])
        np.testing.assert_array_equal(back[0], state[0])

    def test_legacy_generation_pruned_after_native_window_fills(
            self, tmp_path):
        legacy = str(tmp_path / "0")
        os.makedirs(legacy)
        with open(os.path.join(legacy, "checkpoint"), "w") as f:
            f.write("orbax-era payload")
        with CheckpointManager(str(tmp_path), max_to_keep=2) as mgr:
            _save_gens(mgr, [1])
            # window not yet full: the legacy dir is still a potential
            # recovery point and must survive
            assert os.path.isdir(legacy)
            _save_gens(mgr, [2, 3])
            # native coverage now fills max_to_keep: reclaimed
            assert not os.path.exists(legacy)
            assert mgr.all_steps() == [2, 3]


class TestThirdReviewHardening:
    """Regressions pinned after the third review pass: NamedTuple nodes
    round-trip as their own type, the functional restore API gets the
    same structure-only-template guard as the manager path, a forced
    overwrite of a committed generation can no longer destroy it, and
    the DataLoader permutation is drawn at iter() time, not first
    next()."""

    def test_namedtuple_roundtrips_with_template(self, tmp_path):
        import collections

        from paddle_tpu.distributed.checkpoint import (restore_sharded,
                                                       save_sharded)

        AdamState = collections.namedtuple("AdamState", "count mu nu")
        state = {"opt": AdamState(np.int32(3),
                                  np.ones((2,), np.float32),
                                  np.full((2,), 2.0, np.float32))}
        path = str(tmp_path / "gen")
        save_sharded(state, path)
        back = restore_sharded(path, template=state)
        assert isinstance(back["opt"], AdamState)
        assert int(back["opt"].count) == 3
        np.testing.assert_array_equal(np.asarray(back["opt"].mu),
                                      np.asarray(state["opt"].mu))

    def test_restore_sharded_none_leaf_template_on_legacy_dir(
            self, tmp_path):
        import orbax.checkpoint as ocp

        from paddle_tpu.distributed.checkpoint import restore_sharded

        state = {"w": np.arange(4, dtype=np.float32)}
        path = str(tmp_path / "legacy")
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, state)
        ckptr.wait_until_finished()
        back = restore_sharded(path, template={"w": None})
        assert back["w"] is not None, (
            "structure-only template echoed back as 'restored' state")
        np.testing.assert_array_equal(np.asarray(back["w"]), state["w"])

    def test_forced_overwrite_preserves_committed_generation(
            self, tmp_path):
        qdir = tmp_path / "quarantine"
        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1])
            # a SIGKILL lands between the rename-aside and the new
            # generation's COMMIT marker (the torn injector fires in
            # exactly that window)
            with chaos.inject(ckpt_torn=1):
                with pytest.raises(chaos.ChaosTorn):
                    mgr.save(1, _state(7), force=True)
            # the superseded committed bytes survived the crash
            aside = [n for n in os.listdir(qdir)
                     if n.startswith("1.superseded-")]
            assert aside, "old committed generation destroyed by " \
                          "forced overwrite crash"
            assert os.path.exists(
                os.path.join(qdir, aside[0], "COMMIT"))
            # a SUCCESSFUL forced overwrite leaves no aside residue
            assert mgr.save(2, _state(2), force=True)
            assert mgr.save(2, _state(9), force=True)
            assert not [n for n in os.listdir(qdir)
                        if n.startswith("2.superseded-")]
            step, back = mgr.restore_latest(template=_state(0))
            assert step == 2
            np.testing.assert_array_equal(
                np.asarray(back["w"]), np.full((4, 4), 9.0, "f"))

    def test_dataloader_permutation_drawn_at_iter_time(self):
        import threading

        from paddle_tpu.io import DataLoader

        class DS:
            def __len__(self):
                return 16

            def __getitem__(self, i):
                return np.float32(i)

        def batches(loader, consume_on_thread):
            it = iter(loader)  # called on the seeded main thread
            out = []
            if consume_on_thread:
                def drain():
                    out.extend(np.asarray(b).tolist() for b in it)
                t = threading.Thread(target=drain)
                t.start()
                t.join(timeout=30)
            else:
                out.extend(np.asarray(b).tolist() for b in it)
            return out

        paddle.seed(1234)
        main = batches(DataLoader(DS(), batch_size=4, shuffle=True,
                                  use_buffer_reader=False), False)
        paddle.seed(1234)
        threaded = batches(DataLoader(DS(), batch_size=4, shuffle=True,
                                      use_buffer_reader=False), True)
        assert main == threaded, (
            "shuffle permutation drawn on the consuming (unseeded) "
            "thread instead of at iter() time")

    def test_failed_overwrite_rolls_superseded_generation_back(
            self, tmp_path):
        from paddle_tpu.distributed.checkpoint import _write_generation

        with CheckpointManager(str(tmp_path)) as mgr:
            _save_gens(mgr, [1])
            # a transient disk error lands between the rename-aside and
            # the new COMMIT marker (fail_io raises plain OSError at the
            # checkpoint.commit hook, unlike ChaosTorn which simulates
            # SIGKILL and must NOT trigger the rollback)
            with chaos.inject(fail_io=1):
                with pytest.raises(OSError):
                    _write_generation(_gen_dir(mgr, 1),
                                      {"w": np.zeros((2,), np.float32)})
            # the superseded generation is back in its slot, committed,
            # and nothing leaked into quarantine/
            _assert_restores(mgr, 1)
            qdir = os.path.join(str(tmp_path), "quarantine")
            assert not os.path.isdir(qdir) or not os.listdir(qdir)
