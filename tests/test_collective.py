"""paddle.distributed collective API — eager (dygraph parity) and traced.

Reference contract: python/paddle/distributed/collective.py broadcast:101 /
all_reduce:157 / reduce:231 / all_gather:313 / scatter:386 / barrier:457;
eager semantics match the dygraph `core.ops.c_*` path (round-1 VERDICT #8:
these previously raised NotImplementedError outside pjit)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.mesh import build_mesh, mesh_guard


@pytest.fixture
def mesh8():
    mesh = build_mesh({"dp": 8})
    with mesh_guard(mesh):
        yield mesh


class TestEagerCollectives:
    def test_all_reduce_identity_on_replicated(self, mesh8):
        # replicated eager tensor: each of the 8 shards holds the value,
        # sum = 8x (the dygraph all_reduce over an 8-rank ring)
        t = paddle.ones([4])
        out = dist.all_reduce(t)
        np.testing.assert_allclose(np.asarray(out.value), 8.0 * np.ones(4))

    def test_all_reduce_max(self, mesh8):
        t = paddle.full([2], 3.0)
        out = dist.all_reduce(t, op=dist.ReduceOp.MAX)
        np.testing.assert_allclose(np.asarray(out.value), [3.0, 3.0])

    def test_all_gather(self, mesh8):
        t = paddle.ones([2])
        got = []
        dist.all_gather(got, t)
        assert len(got) == 8
        np.testing.assert_allclose(np.asarray(got[3].value), [1.0, 1.0])

    def test_broadcast(self, mesh8):
        t = paddle.full([3], 7.0)
        out = dist.broadcast(t, src=0)
        np.testing.assert_allclose(np.asarray(out.value), [7.0] * 3)

    def test_reduce_scatter(self, mesh8):
        t = paddle.ones([8])
        out = dist.reduce_scatter(t)
        # rank-local shard: sum over the 8 ranks of this rank's slice
        assert np.asarray(out.value).shape == (1,)
        np.testing.assert_allclose(np.asarray(out.value), [8.0])

    def test_scatter_assigns_rank_slice(self, mesh8):
        target = paddle.zeros([2])
        parts = [paddle.full([2], float(i)) for i in range(8)]
        dist.scatter(target, parts, src=0)
        # rank 0 without a launcher
        np.testing.assert_allclose(np.asarray(target.value), [0.0, 0.0])

    def test_scatter_without_list_raises(self, mesh8):
        with pytest.raises(ValueError, match="tensor_list"):
            dist.scatter(paddle.zeros([2]), src=0)

    def test_alltoall_eager(self, mesh8):
        ins = [paddle.full([2], float(i)) for i in range(8)]
        outs = []
        dist.alltoall(ins, outs)
        assert len(outs) == 8
        # replicated in_list degenerate: rank 0 receives in_list[0] from
        # every peer
        for o in outs:
            np.testing.assert_allclose(np.asarray(o.value), [0.0, 0.0])

    def test_send_recv_mailbox(self):
        src = paddle.full([3], 5.0)
        dst = paddle.zeros([3])
        # canonical exchange: rank 0 sends to rank 1; the receiver names
        # the SENDER (src=0) — works regardless of the declared dst
        dist.send(src, dst=1)
        dist.recv(dst, src=0)
        np.testing.assert_allclose(np.asarray(dst.value), [5.0] * 3)

    def test_recv_without_send_raises(self):
        with pytest.raises(RuntimeError, match="no matching send"):
            dist.recv(paddle.zeros([1]), src=3)

    def test_recv_shape_mismatch_keeps_message(self):
        dist.send(paddle.ones([4]), dst=0)
        with pytest.raises(ValueError, match="shape mismatch"):
            dist.recv(paddle.zeros([2]), src=0)
        # the message survives the failed recv; a corrected retry succeeds
        ok = paddle.zeros([4])
        dist.recv(ok, src=0)
        np.testing.assert_allclose(np.asarray(ok.value), [1.0] * 4)

    def test_barrier_and_wait(self, mesh8):
        dist.barrier()
        t = paddle.ones([2])
        assert dist.wait(t) is t


class TestTracedCollectives:
    def test_all_reduce_inside_shard_map(self, mesh8):
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        def f(x):
            t = paddle.Tensor(x)
            return dist.all_reduce(t, op=dist.ReduceOp.SUM).value

        x = jnp.arange(8.0)
        out = shard_map(f, mesh=mesh8, in_specs=(P("dp"),),
                        out_specs=P("dp"))(x)
        # every shard holds the global sum after the psum
        np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))

    def test_psum_matches_manual(self, mesh8):
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        def f(x):
            return dist.all_reduce(paddle.Tensor(x)).value

        x = jnp.arange(16.0).reshape(8, 2)
        out = shard_map(f, mesh=mesh8, in_specs=(P("dp"),),
                        out_specs=P("dp"))(x)
        # each shard's 1x2 row replaced by the column sums
        expect = np.tile(np.asarray(x).sum(0, keepdims=True), (8, 1))
        np.testing.assert_allclose(np.asarray(out), expect)
