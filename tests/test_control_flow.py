"""Control-flow ops (paddle.static.nn) — OpTest-style coverage.

Reference contract: python/paddle/fluid/layers/control_flow.py
(while_loop:1111, cond:2291, case:2470, switch_case:3587) and
operators/controlflow/*.cc, including gradients through cond (the
conditional_block grad op)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn


class TestWhileLoop:
    def test_counts_to_ten(self):
        def cond(i, ten):
            return i < ten

        def body(i, ten):
            return [i + 1, ten]

        i = paddle.zeros([1], dtype="int64")
        ten = paddle.full([1], 10, dtype="int64")
        out_i, out_ten = snn.while_loop(cond, body, [i, ten])
        assert int(out_i.value[0]) == 10

    def test_pytree_loop_vars_and_jit(self):
        def run(x):
            def cond(state):
                return state["n"] < 5

            def body(state):
                return ({"n": state["n"] + 1, "acc": state["acc"] * 2.0},)

            (out,) = snn.while_loop(cond, body,
                                    [{"n": jnp.int32(0), "acc": x}])
            return out["acc"]

        out = jax.jit(run)(jnp.float32(3.0))
        assert float(out) == 3.0 * 32

    def test_type_errors(self):
        with pytest.raises(TypeError):
            snn.while_loop("notfn", lambda: None, [1])
        with pytest.raises(TypeError):
            snn.while_loop(lambda x: x, "notfn", [1])
        with pytest.raises(TypeError):
            snn.while_loop(lambda x: x, lambda x: x, "notalist")
        with pytest.raises(ValueError):
            snn.while_loop(lambda: True, lambda: (), [])

    def test_bad_pred_shape(self):
        with pytest.raises(TypeError, match="one element"):
            snn.while_loop(lambda x: x, lambda x: (x,),
                           [jnp.zeros((2,), jnp.bool_)])

    def test_mixed_stop_gradient_branchs_and_carry(self):
        # Tensor carries stop_gradient in its pytree aux: a loop whose
        # body flips it (zeros init + param-derived update) must not be a
        # lax structure mismatch, and the output must keep tracking
        w = paddle.to_tensor(np.float32(2.0))
        w.stop_gradient = False
        acc0 = paddle.zeros([])          # stop_gradient True

        def cond(i, a):
            return i < 3

        def body(i, a):
            return (i + 1, a + w)

        _, out = snn.while_loop(cond, body, (paddle.zeros([], "int32"),
                                             acc0))
        assert out.stop_gradient is False  # grad flows if body tracked
        # cond with branch-dependent stop_gradient must unify too
        r = snn.cond(paddle.to_tensor(np.bool_(True)),
                     lambda: acc0 + w, lambda: acc0)
        assert float(np.asarray(r.numpy())) == 2.0
        assert r.stop_gradient is False


class TestCond:
    def test_scalar_pred_branches(self):
        x = paddle.full([1], 3.0)
        y = paddle.full([1], 5.0)
        lt = snn.cond(x < y, lambda: x + y, lambda: x - y)
        gt = snn.cond(x > y, lambda: x + y, lambda: x - y)
        assert float(lt.value[0]) == 8.0
        assert float(gt.value[0]) == -2.0

    def test_python_bool_pred(self):
        assert snn.cond(True, lambda: 1, lambda: 2) == 1
        assert snn.cond(False, lambda: 1, lambda: 2) == 2

    def test_none_fns(self):
        assert snn.cond(True, None, None) is None
        assert snn.cond(jnp.bool_(True), lambda: None, None) is None

    def test_gradient_through_cond(self):
        """d/dx of cond(x>0, x^2, 3x) — the conditional_block grad-op
        semantics: only the taken branch contributes."""
        def f(x):
            return snn.cond(x > 0, lambda: x * x, lambda: 3.0 * x)

        g_pos = jax.grad(f)(jnp.float32(2.0))
        g_neg = jax.grad(f)(jnp.float32(-2.0))
        assert float(g_pos) == 4.0
        assert float(g_neg) == 3.0

    def test_inside_jit_runs_taken_branch_only(self):
        def f(x):
            return snn.cond(x.sum() > 0,
                            lambda: jnp.log(jnp.abs(x).sum()),
                            lambda: x.sum())

        out = jax.jit(f)(jnp.asarray([-1.0, -2.0]))
        assert float(out) == -3.0


class TestCaseSwitch:
    def test_case_first_true_wins(self):
        out = snn.case([(jnp.bool_(False), lambda: jnp.float32(1.0)),
                        (jnp.bool_(True), lambda: jnp.float32(2.0)),
                        (jnp.bool_(True), lambda: jnp.float32(3.0))],
                       default=lambda: jnp.float32(9.0))
        assert float(out) == 2.0

    def test_case_default_is_last_fn_when_none(self):
        out = snn.case([(jnp.bool_(False), lambda: jnp.float32(1.0)),
                        (jnp.bool_(False), lambda: jnp.float32(2.0)),
                        (jnp.bool_(True), lambda: jnp.float32(7.0))])
        # reference rule: default=None -> last pair's fn is the default;
        # preds before it are all false -> 7.0 runs as default
        assert float(out) == 7.0

    def test_case_type_errors(self):
        with pytest.raises(TypeError):
            snn.case([])
        with pytest.raises(TypeError):
            snn.case([(True, "notfn")])

    def test_switch_list_of_fns(self):
        fns = [lambda: jnp.float32(10.0), lambda: jnp.float32(20.0),
               lambda: jnp.float32(30.0)]
        assert float(snn.switch_case(jnp.int32(1), fns)) == 20.0
        # out-of-range -> max-index fn when default is None
        assert float(snn.switch_case(jnp.int32(7), fns)) == 30.0

    def test_switch_pairs_and_default(self):
        out = snn.switch_case(
            jnp.int32(5),
            [(1, lambda: jnp.float32(1.0)), (3, lambda: jnp.float32(3.0))],
            default=lambda: jnp.float32(-1.0))
        assert float(out) == -1.0
        out = snn.switch_case(
            jnp.int32(3),
            {1: lambda: jnp.float32(1.0), 3: lambda: jnp.float32(3.0)})
        assert float(out) == 3.0

    def test_switch_duplicate_indices(self):
        with pytest.raises(ValueError, match="duplicate"):
            snn.switch_case(jnp.int32(0), [(1, lambda: 1), (1, lambda: 2)])

    def test_switch_under_jit_and_grad(self):
        def f(x, idx):
            return snn.switch_case(
                idx, [lambda: x * 2.0, lambda: x * x, lambda: x + 1.0])

        g = jax.jit(jax.grad(f))(jnp.float32(3.0), jnp.int32(1))
        assert float(g) == 6.0


class TestTensorArray:
    def test_eager_write_read_stack(self):
        arr = snn.create_array("float32")
        for i in range(4):
            snn.array_write(paddle.full([2], float(i)), i, arr)
        assert int(snn.array_length(arr).value) == 4
        got = snn.array_read(arr, 2)
        assert float(got.value[0]) == 2.0
        stacked = arr.stack()
        assert stacked.shape == [4, 2]
        cat, sizes = snn.tensor_array_to_tensor(arr, axis=0)
        assert cat.shape == [8]
        assert list(np.asarray(sizes.value)) == [2, 2, 2, 2]

    def test_sparse_write_raises(self):
        arr = snn.create_array()
        with pytest.raises(IndexError, match="dense"):
            arr.write(3, paddle.ones([1]))

    def test_static_array_in_while_loop(self):
        """The reference seq2seq pattern: While + array_write, jit-safe."""
        def collect(n):
            arr = snn.StaticTensorArray(8, (2,), jnp.float32)

            def cond(state):
                return state[0] < n

            def body(state):
                i, arr = state
                arr = arr.write(i, jnp.full((2,), i, jnp.float32))
                return ((i + 1, arr),)

            (out,) = snn.while_loop(cond, body,
                                    [(jnp.int32(0), arr)])
            i, arr = out
            return arr.stack(), arr.length()

        data, n = jax.jit(collect)(jnp.int32(5))
        assert int(n) == 5
        np.testing.assert_array_equal(np.asarray(data[:5, 0]),
                                      np.arange(5, dtype=np.float32))

    def test_fori_collect_differentiable(self):
        def f(x):
            def body(i, carry):
                carry = carry * x
                return carry, carry

            last, ys = snn.fori_collect(0, 3, body, jnp.float32(1.0))
            return ys.sum()  # x + x^2 + x^3

        g = jax.grad(f)(jnp.float32(2.0))
        assert float(g) == 1 + 2 * 2 + 3 * 4  # d/dx(x+x^2+x^3) at 2


class TestIncrement:
    def test_increment(self):
        x = paddle.full([1], 1.0)
        y = snn.increment(x, 2.0)
        assert float(y.value[0]) == 3.0


class TestWhileLoopReverseMode:
    """max_iters lowers while_loop to a masked bounded scan, which
    reverse-differentiates (ref while_op.cc:209 WhileGradOp)."""

    def test_grad_through_dynamic_while(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.static.nn import while_loop

        def f(x, n):
            # x doubles until i == n (data-dependent trip count)
            def cond(i, v):
                return i < n

            def body(i, v):
                return i + 1, v * 2.0

            _, out = while_loop(cond, body, (jnp.int32(0), x), max_iters=8)
            return out.sum()

        x = jnp.ones((3,), jnp.float32)
        for n in (0, 3, 5, 8):
            val, g = jax.value_and_grad(f)(x, jnp.int32(n))
            assert val == 3 * 2.0 ** n
            np.testing.assert_allclose(np.asarray(g), 2.0 ** n)

    def test_masked_scan_matches_while(self):
        import jax.numpy as jnp
        from paddle_tpu.static.nn import while_loop

        def cond(i, acc):
            return i < 5

        def body(i, acc):
            return i + 1, acc + jnp.float32(i)

        i1, a1 = while_loop(cond, body, (jnp.int32(0), jnp.float32(0)))
        i2, a2 = while_loop(cond, body, (jnp.int32(0), jnp.float32(0)),
                            max_iters=9)
        assert int(i1) == int(i2) == 5
        assert float(a1) == float(a2) == 10.0

    def test_tensor_loop_vars(self):
        from paddle_tpu.static.nn import while_loop

        x = paddle.to_tensor(np.float32(1.0))
        i = paddle.to_tensor(np.int32(0))
        io, xo = while_loop(lambda i, x: i < 4,
                            lambda i, x: (i + 1, x * 3.0), (i, x),
                            max_iters=6)
        assert float(xo.numpy()) == 81.0
