"""COVERAGE.md must stay truthful: every implemented-at path importable,
zero unclassified rows (round-3 next-step #4)."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference/paddle/fluid/operators"


@pytest.mark.skipif(not os.path.isdir(REF),
                    reason="reference tree not present")
def test_gen_coverage_check_passes():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env.pop("PALLAS_AXON_POOL_IPS", None)
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "gen_coverage.py"),
         "--check"],
        env=env, capture_output=True, text=True, timeout=600)
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-1000:]
    assert os.path.exists(os.path.join(REPO, "COVERAGE.md"))
