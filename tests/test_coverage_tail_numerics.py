"""Numeric tests for every op COVERAGE.md marked implemented-but-
import-verified-only (VERDICT r04 weak #6 / next-step #6).  References:
numpy closed forms for elementwise/manipulation ops, torch (CPU) for
conv/norm/interpolate/ctc oracles — the same oracle style as the
reference's OpTest numpy hooks (fluid/tests/unittests/op_test.py:232).
"""
import numpy as np
import pytest

import paddle_tpu as paddle

F = paddle.nn.functional
T = paddle.to_tensor
rs = np.random.RandomState(0)


def A(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


X1 = rs.rand(3, 4).astype(np.float32) * 0.8 + 0.1   # (0.1, 0.9)
XS = (rs.randn(3, 4) * 2).astype(np.float32)        # signed


# ---- elementwise unary vs numpy -------------------------------------
UNARY = [
    (paddle.acos, X1, np.arccos),
    (paddle.asin, X1, np.arcsin),
    (paddle.atan, XS, np.arctan),
    (paddle.cosh, XS, np.cosh),
    (paddle.sinh, XS, np.sinh),
    (paddle.tan, X1, np.tan),
    (paddle.log2, X1, np.log2),
    (paddle.log10, X1, np.log10),
    (paddle.reciprocal, X1, lambda x: 1.0 / x),
]


@pytest.mark.parametrize("fn,x,ref", UNARY,
                         ids=[f[0].__name__ for f in UNARY])
def test_unary_vs_numpy(fn, x, ref):
    np.testing.assert_allclose(A(fn(T(x))), ref(x), rtol=1e-5, atol=1e-6)


def test_complex_conj_imag():
    z = (XS[:2] + 1j * XS[1:3]).astype(np.complex64)
    np.testing.assert_allclose(A(paddle.conj(T(z))), np.conj(z))
    np.testing.assert_allclose(A(paddle.imag(T(z))), np.imag(z))


def test_floor_divide_and_argmin():
    a = np.array([7.0, -7.0, 9.0], np.float32)
    b = np.array([2.0, 2.0, -4.0], np.float32)
    np.testing.assert_allclose(A(paddle.floor_divide(T(a), T(b))),
                               np.floor_divide(a, b))
    np.testing.assert_allclose(A(paddle.argmin(T(XS), axis=1)),
                               XS.argmin(1))


# ---- activations vs closed forms ------------------------------------
def _selu(x, a=1.6732632423543772, s=1.0507009873554805):
    return s * np.where(x > 0, x, a * (np.exp(x) - 1))


ACTS = [
    ("relu6", lambda x: F.relu6(T(x)), lambda x: np.clip(x, 0, 6)),
    ("elu", lambda x: F.elu(T(x), alpha=0.5),
     lambda x: np.where(x > 0, x, 0.5 * (np.exp(x) - 1))),
    ("selu", lambda x: F.selu(T(x)), _selu),
    ("mish", lambda x: F.mish(T(x)),
     lambda x: x * np.tanh(np.log1p(np.exp(x)))),
    ("swish", lambda x: F.swish(T(x)), lambda x: x / (1 + np.exp(-x))),
    ("softsign", lambda x: F.softsign(T(x)), lambda x: x / (1 + np.abs(x))),
    ("softshrink", lambda x: F.softshrink(T(x), threshold=0.5),
     lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0))),
    ("hardshrink", lambda x: F.hardshrink(T(x), threshold=0.5),
     lambda x: np.where(np.abs(x) > 0.5, x, 0)),
    ("hardsigmoid", lambda x: F.hardsigmoid(T(x)),
     lambda x: np.clip(x / 6 + 0.5, 0, 1)),
    ("hardswish", lambda x: F.hardswish(T(x)),
     lambda x: x * np.clip(x + 3, 0, 6) / 6),
    ("hardtanh", lambda x: F.hardtanh(T(x), min=-1, max=1),
     lambda x: np.clip(x, -1, 1)),
    ("leaky_relu", lambda x: F.leaky_relu(T(x), negative_slope=0.1),
     lambda x: np.where(x > 0, x, 0.1 * x)),
    ("log_sigmoid", lambda x: F.log_sigmoid(T(x)),
     lambda x: -np.log1p(np.exp(-x))),
    ("tanhshrink", lambda x: F.tanhshrink(T(x)), lambda x: x - np.tanh(x)),
    ("thresholded_relu", lambda x: F.thresholded_relu(T(x), threshold=1.0),
     lambda x: np.where(x > 1.0, x, 0)),
]


@pytest.mark.parametrize("name,fn,ref", ACTS, ids=[a[0] for a in ACTS])
def test_activation_closed_form(name, fn, ref):
    np.testing.assert_allclose(A(fn(XS)), ref(XS), rtol=1e-5, atol=1e-6)


def test_prelu_and_maxout():
    w = np.array([0.25], np.float32)
    np.testing.assert_allclose(A(F.prelu(T(XS), T(w))),
                               np.where(XS > 0, XS, 0.25 * XS), rtol=1e-6)
    x = rs.randn(2, 6, 4, 4).astype(np.float32)
    got = A(F.maxout(T(x), groups=3))
    # maxout_op: C_out = C/groups, each output maxes over `groups`
    # consecutive channels
    ref = x.reshape(2, 2, 3, 4, 4).max(2)
    np.testing.assert_allclose(got, ref)


# ---- losses vs closed forms -----------------------------------------
def test_bce_and_bce_with_logits():
    p = X1
    t = (rs.rand(3, 4) > 0.5).astype(np.float32)
    ref = -(t * np.log(p) + (1 - t) * np.log(1 - p)).mean()
    np.testing.assert_allclose(A(F.binary_cross_entropy(T(p), T(t))),
                               ref, rtol=1e-5)
    z = XS
    ref2 = np.mean(np.maximum(z, 0) - z * t + np.log1p(np.exp(-np.abs(z))))
    np.testing.assert_allclose(
        A(F.binary_cross_entropy_with_logits(T(z), T(t))), ref2, rtol=1e-5)


def test_smooth_l1_and_kl_and_margin_rank():
    a, b = XS, XS + rs.randn(3, 4).astype(np.float32)
    d = np.abs(a - b)
    ref = np.where(d < 1, 0.5 * d * d, d - 0.5).mean()
    np.testing.assert_allclose(A(F.smooth_l1_loss(T(a), T(b))), ref,
                               rtol=1e-5)
    p = X1 / X1.sum(-1, keepdims=True)
    q = np.roll(p, 1, -1)
    logq = np.log(q)
    np.testing.assert_allclose(
        A(F.kl_div(T(logq), T(p), reduction="sum")),
        (p * (np.log(p) - logq)).sum(), rtol=1e-4)
    x1, x2 = XS[0], XS[1]
    lab = np.sign(rs.randn(4)).astype(np.float32)
    ref3 = np.maximum(0, -lab * (x1 - x2) + 0.1).mean()
    np.testing.assert_allclose(
        A(F.margin_ranking_loss(T(x1), T(x2), T(lab), margin=0.1)),
        ref3, rtol=1e-5)


def test_nll_softmax_ce_cosine():
    logp = np.log(X1 / X1.sum(-1, keepdims=True))
    lab = rs.randint(0, 4, (3,))
    np.testing.assert_allclose(
        A(F.nll_loss(T(logp), T(lab))),
        -logp[np.arange(3), lab].mean(), rtol=1e-5)
    z = XS
    lse = np.log(np.exp(z).sum(-1, keepdims=True))
    ref = (lse.squeeze(-1) - z[np.arange(3), lab])
    got = A(F.softmax_with_cross_entropy(T(z), T(lab[:, None])))
    np.testing.assert_allclose(got.squeeze(), ref, rtol=1e-5)
    a, b = XS[0], XS[1]
    cs = (a * b).sum() / (np.linalg.norm(a) * np.linalg.norm(b))
    np.testing.assert_allclose(A(F.cosine_similarity(T(XS[:1]), T(XS[1:2]))),
                               [cs], rtol=1e-5)


def test_ctc_loss_vs_torch():
    torch = pytest.importorskip("torch")
    B, S, C, L = 2, 8, 5, 3
    logits = rs.randn(B, S, C).astype(np.float32)  # [B, T, C]
    labels = rs.randint(1, C, (B, L)).astype(np.int32)
    in_len = np.array([S, S], np.int32)
    lab_len = np.array([L, L], np.int32)
    got = A(F.ctc_loss(T(logits.transpose(1, 0, 2)), T(labels),
                       T(in_len), T(lab_len), blank=0,
                       reduction="none"))
    tl = torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.tensor(logits.transpose(1, 0, 2)), -1),
        torch.tensor(labels.astype(np.int64)),
        torch.tensor(in_len.astype(np.int64)),
        torch.tensor(lab_len.astype(np.int64)),
        blank=0, reduction="none")
    np.testing.assert_allclose(got, tl.numpy(), rtol=1e-4, atol=1e-4)


def test_fsp_label_smooth():
    a = rs.randn(2, 3, 4, 4).astype(np.float32)
    b = rs.randn(2, 5, 4, 4).astype(np.float32)
    got = A(F.fsp_matrix(T(a), T(b)))
    ref = np.einsum("bchw,bdhw->bcd", a, b) / 16.0
    np.testing.assert_allclose(got, ref, rtol=1e-4)
    oh = np.eye(4, dtype=np.float32)[[0, 2]]
    np.testing.assert_allclose(A(F.label_smooth(T(oh), epsilon=0.1)),
                               oh * 0.9 + 0.1 / 4, rtol=1e-6)


# ---- manipulation vs numpy ------------------------------------------
def test_manipulation_family():
    np.testing.assert_allclose(A(paddle.dot(T(XS[0]), T(XS[1]))),
                               XS[0] @ XS[1], rtol=1e-5)
    M = XS[:3, :3]
    v = XS[0, :3]
    np.testing.assert_allclose(A(paddle.mv(T(M), T(v))), M @ v, rtol=1e-5)
    np.testing.assert_allclose(A(paddle.kron(T(XS[:2, :2]), T(XS[1:3, :2]))),
                               np.kron(XS[:2, :2], XS[1:3, :2]), rtol=1e-5)
    np.testing.assert_allclose(A(paddle.roll(T(XS), 2, axis=1)),
                               np.roll(XS, 2, 1))
    parts = paddle.unbind(T(XS), axis=0)
    assert len(parts) == 3
    np.testing.assert_allclose(A(parts[1]), XS[1])
    parts2 = paddle.unstack(T(XS), axis=1)
    assert len(parts2) == 4 and A(parts2[2]).tolist() == XS[:, 2].tolist()
    np.testing.assert_allclose(A(paddle.expand(T(XS[:1]), [3, 4])),
                               np.broadcast_to(XS[:1], (3, 4)))
    np.testing.assert_allclose(A(paddle.expand_as(T(XS[:1]), T(XS))),
                               np.broadcast_to(XS[:1], (3, 4)))
    np.testing.assert_allclose(A(paddle.full_like(T(XS), 7.0)),
                               np.full_like(XS, 7.0))
    e = paddle.empty([2, 3], "float32")
    assert list(A(e).shape) == [2, 3]
    assert bool(A(paddle.is_empty(paddle.zeros([0, 3]))))
    assert not bool(A(paddle.is_empty(T(XS))))
    g = paddle.meshgrid(T(np.arange(3, dtype=np.float32)),
                        T(np.arange(2, dtype=np.float32)))
    ref = np.meshgrid(np.arange(3), np.arange(2), indexing="ij")
    np.testing.assert_allclose(A(g[0]), ref[0])
    np.testing.assert_allclose(A(g[1]), ref[1])


def test_gather_scatter_mask_family():
    idx = np.array([[0, 1], [2, 3]], np.int64)
    x3 = rs.randn(3, 4, 5).astype(np.float32)
    np.testing.assert_allclose(A(paddle.gather_nd(T(x3), T(idx))),
                               x3[idx[:, 0], idx[:, 1]])
    base = np.zeros((4,), np.float32)
    upd = np.array([1.0, 2.0, 3.0], np.float32)
    sidx = np.array([[1], [1], [3]], np.int64)
    got = A(paddle.scatter_nd_add(T(base), T(sidx), T(upd)))
    np.testing.assert_allclose(got, [0, 3, 0, 3])
    m = XS > 0
    np.testing.assert_allclose(A(paddle.masked_select(T(XS), T(m))), XS[m])
    np.testing.assert_allclose(A(paddle.nonzero(T((XS > 0).astype(
        np.float32)))), np.argwhere(XS > 0))
    inputs = [T(np.full((2, 2), i, np.float32)) for i in range(3)]
    sel = np.array([[2], [0]], np.int32)
    got = A(paddle.multiplex(inputs, T(sel)))
    np.testing.assert_allclose(got, [[2, 2], [0, 0]])
    x = rs.randn(2, 5).astype(np.float32)
    ii = np.array([[0, 2], [4, 1]], np.int64)
    np.testing.assert_allclose(A(paddle.index_sample(T(x), T(ii))),
                               np.take_along_axis(x, ii, 1))


def test_unique_histogram_shard_onehot_crop():
    v = np.array([2, 1, 2, 3, 1], np.int64)
    u = A(paddle.unique(T(v)))
    np.testing.assert_allclose(np.sort(u), [1, 2, 3])
    u2, cnt = paddle.unique(T(v), return_counts=True)
    order = np.argsort(A(u2))
    np.testing.assert_allclose(A(cnt)[order], [2, 2, 1])
    h = A(paddle.histogram(T(np.array([0.1, 0.5, 0.9], np.float32)),
                           bins=2, min=0.0, max=1.0))
    np.testing.assert_allclose(h, [1, 2])  # 0.5 falls in the right bin
    sh = A(paddle.shard_index(T(np.array([[1], [5], [9]], np.int64)),
                              index_num=12, nshards=3, shard_id=1,
                              ignore_value=-1))
    # shard 1 owns [4, 8): 5 -> 5-4=1, others ignored
    np.testing.assert_allclose(sh, [[-1], [1], [-1]])
    oh = A(F.one_hot(T(np.array([0, 2], np.int64)), num_classes=3))
    np.testing.assert_allclose(oh, np.eye(3)[[0, 2]])
    c = A(paddle.crop(T(XS), shape=[2, 2], offsets=[1, 1]))
    np.testing.assert_allclose(c, XS[1:3, 1:3])
    sm = A(F.sequence_mask(T(np.array([1, 3], np.int64)), maxlen=4))
    np.testing.assert_allclose(sm, [[1, 0, 0, 0], [1, 1, 1, 0]])


def test_sequence_erase():
    from paddle_tpu.text.sequence import sequence_erase
    x = np.array([[3, 5, 3, 7, 0]], np.int64)
    ln = np.array([5])
    out, new_len = sequence_erase(T(x), T(ln), tokens=[3])
    assert int(A(new_len)[0]) == 3
    np.testing.assert_allclose(A(out)[0, :3], [5, 7, 0])


# ---- conv / norm / resize vs torch ----------------------------------
def _torch():
    return pytest.importorskip("torch")


def test_conv_transpose2d_vs_torch():
    torch = _torch()
    x = rs.randn(1, 3, 6, 6).astype(np.float32)
    w = rs.randn(3, 4, 3, 3).astype(np.float32)  # [Cin, Cout, kh, kw]
    got = A(F.conv2d_transpose(T(x), T(w), stride=2, padding=1))
    ref = torch.nn.functional.conv_transpose2d(
        torch.tensor(x), torch.tensor(w), stride=2, padding=1).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_depthwise_conv_transpose_vs_torch():
    torch = _torch()
    x = rs.randn(1, 4, 6, 6).astype(np.float32)
    w = rs.randn(4, 1, 3, 3).astype(np.float32)
    got = A(F.conv2d_transpose(T(x), T(w), stride=2, groups=4))
    ref = torch.nn.functional.conv_transpose2d(
        torch.tensor(x), torch.tensor(w), stride=2, groups=4).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_conv3d_and_transpose_vs_torch():
    torch = _torch()
    x = rs.randn(1, 2, 5, 5, 5).astype(np.float32)
    w = rs.randn(3, 2, 3, 3, 3).astype(np.float32)
    got = A(F.conv3d(T(x), T(w), padding=1))
    ref = torch.nn.functional.conv3d(torch.tensor(x), torch.tensor(w),
                                     padding=1).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    wt = rs.randn(2, 3, 3, 3, 3).astype(np.float32)
    got2 = A(F.conv3d_transpose(T(x), T(wt), stride=2))
    ref2 = torch.nn.functional.conv_transpose3d(
        torch.tensor(x), torch.tensor(wt), stride=2).numpy()
    np.testing.assert_allclose(got2, ref2, rtol=1e-4, atol=1e-4)


def test_norms_vs_torch():
    torch = _torch()
    x = rs.randn(2, 6, 4, 4).astype(np.float32)
    w = rs.rand(6).astype(np.float32) + 0.5
    b = rs.randn(6).astype(np.float32)
    got = A(F.group_norm(T(x), num_groups=3, weight=T(w), bias=T(b),
                         epsilon=1e-5))
    ref = torch.nn.functional.group_norm(
        torch.tensor(x), 3, torch.tensor(w), torch.tensor(b),
        eps=1e-5).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    got2 = A(F.instance_norm(T(x), weight=T(w), bias=T(b), eps=1e-5))
    ref2 = torch.nn.functional.instance_norm(
        torch.tensor(x), weight=torch.tensor(w), bias=torch.tensor(b),
        eps=1e-5).numpy()
    np.testing.assert_allclose(got2, ref2, rtol=1e-4, atol=1e-4)
    # paddle's lrn_op uses alpha * sum (torch divides alpha by size);
    # hand torch the pre-multiplied alpha so both compute the same thing
    got3 = A(F.local_response_norm(T(x), size=3, alpha=1e-4))
    ref3 = torch.nn.functional.local_response_norm(
        torch.tensor(x), 3, alpha=3e-4).numpy()
    np.testing.assert_allclose(got3, ref3, rtol=1e-4, atol=1e-4)


def test_data_norm():
    x = rs.randn(4, 3).astype(np.float32)
    size = np.full((3,), 4.0, np.float32)
    ssum = x.sum(0)
    sqsum = (x * x).sum(0)
    got = A(F.data_norm(T(x), batch_size=T(size), batch_sum=T(ssum),
                        batch_square_sum=T(sqsum)))
    mean = ssum / 4
    scale = 1.0 / np.sqrt(sqsum / 4 - mean ** 2 + 1e-4)
    np.testing.assert_allclose(got, (x - mean) * scale, rtol=1e-3,
                               atol=1e-3)


def test_interpolate_modes_vs_torch():
    torch = _torch()
    x = rs.randn(1, 2, 6, 6).astype(np.float32)
    tx = torch.tensor(x)
    for mode, tmode, kw in [("nearest", "nearest", {}),
                            ("bilinear", "bilinear",
                             {"align_corners": False}),
                            ("bicubic", "bicubic",
                             {"align_corners": False})]:
        got = A(F.interpolate(T(x), size=[12, 12], mode=mode, **kw))
        ref = torch.nn.functional.interpolate(tx, size=(12, 12),
                                              mode=tmode, **kw).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=2e-3,
                                   err_msg=mode)
    x1 = rs.randn(1, 2, 8).astype(np.float32)
    got = A(F.interpolate(T(x1), size=[16], mode="linear",
                          align_corners=False))
    ref = torch.nn.functional.interpolate(
        torch.tensor(x1), size=16, mode="linear",
        align_corners=False).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    x3 = rs.randn(1, 2, 4, 4, 4).astype(np.float32)
    got = A(F.interpolate(T(x3), size=[8, 8, 8], mode="trilinear",
                          align_corners=False))
    ref = torch.nn.functional.interpolate(
        torch.tensor(x3), size=(8, 8, 8), mode="trilinear",
        align_corners=False).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_pool3d_and_pixel_shuffle():
    torch = _torch()
    x = rs.randn(1, 2, 4, 4, 4).astype(np.float32)
    got = A(F.max_pool3d(T(x), kernel_size=2, stride=2))
    ref = torch.nn.functional.max_pool3d(torch.tensor(x), 2, 2).numpy()
    np.testing.assert_allclose(got, ref)
    y = rs.randn(1, 8, 3, 3).astype(np.float32)
    got2 = A(F.pixel_shuffle(T(y), 2))
    ref2 = torch.nn.functional.pixel_shuffle(torch.tensor(y), 2).numpy()
    np.testing.assert_allclose(got2, ref2)


# ---- rnn cells / rnn layer ------------------------------------------
def test_rnn_cells_vs_torch():
    torch = _torch()
    paddle.seed(0)
    x = rs.randn(2, 4).astype(np.float32)
    h = rs.randn(2, 6).astype(np.float32)
    c = rs.randn(2, 6).astype(np.float32)

    cell = paddle.nn.LSTMCell(4, 6)
    tcell = torch.nn.LSTMCell(4, 6)
    with torch.no_grad():
        tcell.weight_ih.copy_(torch.tensor(A(cell.weight_ih)))
        tcell.weight_hh.copy_(torch.tensor(A(cell.weight_hh)))
        tcell.bias_ih.copy_(torch.tensor(A(cell.bias_ih)))
        tcell.bias_hh.copy_(torch.tensor(A(cell.bias_hh)))
    out, (h2, c2) = cell(T(x), (T(h), T(c)))
    th, tc = tcell(torch.tensor(x), (torch.tensor(h), torch.tensor(c)))
    np.testing.assert_allclose(A(h2), th.detach().numpy(), rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(A(c2), tc.detach().numpy(), rtol=1e-4,
                               atol=1e-4)

    gcell = paddle.nn.GRUCell(4, 6)
    tg = torch.nn.GRUCell(4, 6)
    with torch.no_grad():
        tg.weight_ih.copy_(torch.tensor(A(gcell.weight_ih)))
        tg.weight_hh.copy_(torch.tensor(A(gcell.weight_hh)))
        tg.bias_ih.copy_(torch.tensor(A(gcell.bias_ih)))
        tg.bias_hh.copy_(torch.tensor(A(gcell.bias_hh)))
    out, h3 = gcell(T(x), T(h))
    th3 = tg(torch.tensor(x), torch.tensor(h))
    np.testing.assert_allclose(A(h3), th3.detach().numpy(), rtol=1e-4,
                               atol=1e-4)


def test_simple_rnn_runs_and_grads():
    paddle.seed(0)
    net = paddle.nn.SimpleRNN(4, 6, num_layers=1)
    x = T(rs.randn(2, 5, 4).astype(np.float32))
    x.stop_gradient = False
    out, h = net(x)
    assert list(A(out).shape) == [2, 5, 6]
    loss = (out ** 2).mean()
    loss.backward()
    assert np.isfinite(A(x.grad)).all()


# ---- random family (statistical / shape) ----------------------------
def test_random_family():
    paddle.seed(7)
    b = A(paddle.bernoulli(T(np.full((2000,), 0.3, np.float32))))
    assert set(np.unique(b)) <= {0.0, 1.0}
    assert 0.2 < b.mean() < 0.4
    m = A(paddle.multinomial(T(np.array([0.0, 0.7, 0.3], np.float32)),
                             num_samples=500, replacement=True))
    assert 0 not in np.unique(m)
    u = A(paddle.uniform([2000], min=-2.0, max=2.0))
    assert u.min() >= -2 and u.max() <= 2 and abs(u.mean()) < 0.2
    tn = paddle.nn.initializer.TruncatedNormal(mean=0.0, std=1.0)
    p = paddle.create_parameter([1000], attr=paddle.ParamAttr(
        initializer=tn))
    vals = A(p)
    assert np.abs(vals).max() <= 2.0 + 1e-5  # truncation at 2 std
    from paddle_tpu.vision.ops import random_crop
    img = rs.randn(3, 8, 8).astype(np.float32)
    crop = A(random_crop(T(img), [4, 4]))
    assert crop.shape == (3, 4, 4)
    paddle.seed(11)
    c1 = A(random_crop(T(img), [4, 4]))
    paddle.seed(11)
    c2 = A(random_crop(T(img), [4, 4]))
    np.testing.assert_allclose(c1, c2)  # paddle.seed reproduces crops


def test_detection_sampling_reproducible_under_seed():
    """advisor r04: use_random sampling must follow paddle.seed."""
    from paddle_tpu.vision.ops import generate_proposal_labels
    rois = np.array([[0, 0, 10, 10], [0, 0, 9, 9], [20, 20, 30, 30],
                     [40, 40, 50, 50], [1, 1, 11, 11]], np.float32)
    gtc = np.array([3])
    gtb = np.array([[0, 0, 10, 10]], np.float32)
    paddle.seed(5)
    r1, l1, t1 = generate_proposal_labels(
        T(rois), T(gtc), T(gtb), batch_size_per_im=4, use_random=True)
    paddle.seed(5)
    r2, l2, t2 = generate_proposal_labels(
        T(rois), T(gtc), T(gtb), batch_size_per_im=4, use_random=True)
    np.testing.assert_allclose(A(r1), A(r2))
    np.testing.assert_allclose(A(l1), A(l2))


# ---- misc remaining --------------------------------------------------
def test_elementwise_remainder():
    np.testing.assert_allclose(A(paddle.ceil(T(XS))), np.ceil(XS))
    np.testing.assert_allclose(A(paddle.floor(T(XS))), np.floor(XS))
    np.testing.assert_allclose(A(paddle.square(T(XS))), XS * XS, rtol=1e-6)
    import math
    np.testing.assert_allclose(
        A(paddle.erf(T(np.array([0.0, 1.0], np.float32)))),
        [0.0, math.erf(1.0)], rtol=1e-5)
    z = (XS[:2] + 1j * XS[1:3]).astype(np.complex64)
    np.testing.assert_allclose(A(paddle.real(T(z))), np.real(z))
    a3 = np.array([1.0, 0.0, 0.0], np.float32)
    b3 = np.array([0.0, 1.0, 0.0], np.float32)
    np.testing.assert_allclose(A(paddle.cross(T(a3), T(b3))),
                               np.cross(a3, b3))
    y = paddle.assign(T(XS))
    np.testing.assert_allclose(A(y), XS)


def test_update_loss_scaling_transitions():
    import jax.numpy as jnp

    from paddle_tpu.amp import update_loss_scaling
    # overflow: scale halves (after decr_every_n=2 bad steps), good resets
    s, g, b = update_loss_scaling(jnp.float32(1024.0), jnp.int32(5),
                                  jnp.int32(1), jnp.bool_(True),
                                  decr_every_n=2)
    assert float(s) == 512.0 and int(g) == 0
    # clean streak reaching incr_every_n: scale doubles
    s2, g2, b2 = update_loss_scaling(jnp.float32(1024.0), jnp.int32(999),
                                     jnp.int32(0), jnp.bool_(False),
                                     incr_every_n=1000)
    assert float(s2) == 2048.0 and int(b2) == 0


def test_collective_reduce_ops():
    import jax

    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.collective import ReduceOp, reduce
    from paddle_tpu.distributed.mesh import build_mesh, mesh_guard

    mesh = build_mesh({"dp": jax.device_count()})
    with mesh_guard(mesh):
        for op, ref in [(ReduceOp.SUM, lambda v, n: v * n),
                        (ReduceOp.MAX, lambda v, n: v),
                        (ReduceOp.MIN, lambda v, n: v),
                        (ReduceOp.PROD, lambda v, n: v ** n)]:
            # fresh tensor per op: all_reduce writes back in place
            x = T(np.array([2.0, 3.0], np.float32))
            out = reduce(x, dst=0, op=op)
            np.testing.assert_allclose(
                A(out), ref(np.array([2.0, 3.0]), jax.device_count()),
                rtol=1e-5)


def test_affine_channel_and_clip_by_norm():
    from paddle_tpu.vision.ops import affine_channel
    x = rs.randn(1, 3, 2, 2).astype(np.float32)
    s = np.array([1.0, 2.0, 0.5], np.float32)
    b = np.array([0.0, 1.0, -1.0], np.float32)
    got = A(affine_channel(T(x), T(s), T(b)))
    ref = x * s[None, :, None, None] + b[None, :, None, None]
    np.testing.assert_allclose(got, ref, rtol=1e-6)

    clip = paddle.nn.ClipGradByNorm(clip_norm=1.0)
    g = np.array([3.0, 4.0], np.float32)  # norm 5 -> scaled to 1
    p = paddle.create_parameter([2], attr=paddle.ParamAttr())
    p.grad = T(g)
    out = clip([(p, p.grad)])
    gg = A(out[0][1])
    np.testing.assert_allclose(np.linalg.norm(gg), 1.0, rtol=1e-5)


def test_check_finite_and_unscale():
    from paddle_tpu.amp import check_finite_and_unscale
    import jax.numpy as jnp
    grads = {"a": jnp.array([2.0, 4.0]), "b": jnp.array([6.0])}
    out, found = check_finite_and_unscale(grads, jnp.float32(2.0))
    assert not bool(found)
    np.testing.assert_allclose(np.asarray(out["a"]), [1.0, 2.0])
    grads = {"a": jnp.array([np.inf])}
    _, found = check_finite_and_unscale(grads, jnp.float32(2.0))
    assert bool(found)


def test_beam_search_decode_and_retinanet_output():
    from paddle_tpu.text import beam_search_decode, gather_tree
    # [T, B, W]: 3 steps, 1 batch, 2 beams; step-2 beam 0 came from
    # parent beam 1, so its backtracked path is 2 -> 4 -> 5
    ids = T(np.array([[[1, 2]], [[3, 4]], [[5, 6]]], np.int64))
    parents = T(np.array([[[0, 0]], [[0, 1]], [[1, 0]]], np.int64))
    tree = A(gather_tree(ids, parents))
    assert tree.shape == (3, 1, 2)
    np.testing.assert_allclose(tree[:, 0, 0], [2, 4, 5])
    scores = T(np.array([[0.9, 0.1]], np.float32))
    seqs, sc = beam_search_decode(ids, parents, scores)
    assert A(seqs).shape == (1, 2, 3)
    np.testing.assert_allclose(A(seqs)[0, 0], [2, 4, 5])

    from paddle_tpu.vision.ops import retinanet_detection_output
    # smoke numeric: one level, one anchor ([A,4] deltas / [A,C] scores)
    bboxes = T(np.zeros((1, 4), np.float32))  # zero deltas: box == anchor
    scores = T(np.array([[0.9, 0.1]], np.float32))
    anchors = T(np.array([[0.0, 0.0, 10.0, 10.0]], np.float32))
    im_info = T(np.array([[20.0, 20.0, 1.0]], np.float32))
    dets = A(retinanet_detection_output([bboxes], [scores], [anchors],
                                        im_info, score_threshold=0.05))
    assert dets.shape[-1] == 6 and dets.shape[0] >= 1
    assert dets[0, 1] == pytest.approx(0.9)  # top score survives
