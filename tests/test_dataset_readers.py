"""paddle.dataset reader creators (reference python/paddle/dataset/):
the fluid book scripts' data entry point — each train()/test() returns a
zero-arg reader yielding the reference's sample tuples."""
import numpy as np

import paddle_tpu as paddle


def _first(reader, n=3):
    out = []
    for i, s in enumerate(reader()):
        out.append(s)
        if i + 1 >= n:
            break
    return out


def test_mnist_reader():
    samples = _first(paddle.dataset.mnist.train())
    img, lab = samples[0]
    assert img.shape == (784,) and img.dtype == np.float32
    assert -1.0 <= img.min() and img.max() <= 1.0
    assert isinstance(lab, int) and 0 <= lab <= 9


def test_uci_and_cifar_readers():
    x, y = _first(paddle.dataset.uci_housing.train())[0]
    assert x.shape == (13,) and y.shape == (1,)
    img, lab = _first(paddle.dataset.cifar.train10())[0]
    assert img.shape == (3072,) and 0 <= lab <= 9
    img, lab = _first(paddle.dataset.cifar.train100())[0]
    assert img.shape == (3072,) and 0 <= lab <= 99


def test_text_readers():
    doc, lab = _first(paddle.dataset.imdb.train(None))[0]
    assert isinstance(doc, list) and lab in (0, 1)
    assert len(paddle.dataset.imdb.word_dict()) > 100
    gram = _first(paddle.dataset.imikolov.train(None, 5))[0]
    assert len(gram) == 5 and all(isinstance(t, int) for t in gram)
    u, m, r = _first(paddle.dataset.movielens.train())[0]
    assert len(u) == 1 and len(m) == 1 and 1.0 <= r[0] <= 5.0
    src, trg, nxt = _first(paddle.dataset.wmt14.train(3000))[0]
    assert len(src) > 0 and len(trg) == len(nxt)
    src, trg, nxt = _first(paddle.dataset.wmt16.train())[0]
    assert len(src) > 0
    nine = _first(paddle.dataset.conll05.test())[0]
    assert len(nine) == 9
    wd, vd, ld = paddle.dataset.conll05.get_dict()
    assert "B-V" in ld


def test_vision_readers_and_image_helpers():
    img, lab = _first(paddle.dataset.flowers.train())[0]
    assert img.ndim == 3
    im, mask = _first(paddle.dataset.voc2012.train())[0]
    assert im.shape[-1] == 3 and mask.ndim == 2

    rgb = (np.random.RandomState(0).rand(20, 30, 3) * 255).astype(np.uint8)
    rs = paddle.dataset.image.resize_short(rgb, 16)
    assert min(rs.shape[:2]) == 16
    cc = paddle.dataset.image.center_crop(rs, 12)
    assert cc.shape[:2] == (12, 12)
    chw = paddle.dataset.image.to_chw(cc)
    assert chw.shape[0] == 3
    out = paddle.dataset.image.simple_transform(rgb, 18, 14, is_train=True)
    assert out.shape == (3, 14, 14) and out.dtype == np.float32
    # train pipeline reproducible under paddle.seed
    paddle.seed(4)
    a = paddle.dataset.image.simple_transform(rgb, 18, 14, is_train=True)
    paddle.seed(4)
    b = paddle.dataset.image.simple_transform(rgb, 18, 14, is_train=True)
    np.testing.assert_array_equal(a, b)


def test_review_fixes():
    """Per-channel mean subtraction, imdb.build_dict signature,
    imikolov SEQ samples, wmt16 serves its own class."""
    rgb = (np.random.RandomState(1).rand(20, 20, 3) * 255).astype(np.uint8)
    out = paddle.dataset.image.simple_transform(
        rgb, 18, 14, is_train=False, mean=[120.0, 121.0, 122.0])
    assert out.shape == (3, 14, 14)
    raw = paddle.dataset.image.simple_transform(rgb, 18, 14,
                                                is_train=False)
    np.testing.assert_allclose(out[1], raw[1] - 121.0, rtol=1e-6)
    # full-array mean subtracts raw
    out2 = paddle.dataset.image.simple_transform(
        rgb, 18, 14, is_train=False, mean=raw)
    np.testing.assert_allclose(out2, 0.0, atol=1e-6)

    import re
    d = paddle.dataset.imdb.build_dict(re.compile(".*"), 150)
    assert len(d) > 100

    seq = next(iter(paddle.dataset.imikolov.train(
        None, 5, paddle.dataset.imikolov.DataType.SEQ)()))
    assert isinstance(seq, list) and len(seq) == 5

    import paddle_tpu.dataset.wmt14 as w14
    src16, _, _ = next(iter(paddle.dataset.wmt16.train()()))
    assert isinstance(src16, list)  # WMT16-backed reader yields normally


def test_reader_composes_with_paddle_batch():
    batched = paddle.batch(paddle.dataset.mnist.train(), batch_size=32)
    first = next(iter(batched()))
    assert len(first) == 32 and first[0][0].shape == (784,)


def test_buffered_loader_shuffle_is_seeded_and_thread_agnostic():
    """The buffered-reader prefetch thread must NOT draw the shuffle
    permutation from its own (never-seeded, thread-local) RNG chain:
    the epoch's batch indices are materialized on the consumer thread,
    so `paddle.seed` controls shuffle order identically with and
    without the prefetch thread (this once made an e2e loss-decrease
    test order-sensitive across the suite)."""
    from paddle_tpu.io import DataLoader, TensorDataset

    ds = TensorDataset([paddle.to_tensor(
        np.arange(64, dtype="float32").reshape(64, 1))])

    def epoch_order(use_buffer_reader):
        paddle.seed(777)
        loader = DataLoader(ds, batch_size=8, shuffle=True,
                            use_buffer_reader=use_buffer_reader)
        return [tuple(np.asarray(b[0].numpy()).ravel().astype(int))
                for b in loader]

    buffered = epoch_order(True)
    unbuffered = epoch_order(False)
    assert buffered == unbuffered          # thread placement irrelevant
    assert epoch_order(True) == buffered   # reseeding reproduces
    paddle.seed(123)
    loader = DataLoader(ds, batch_size=8, shuffle=True)
    other = [tuple(np.asarray(b[0].numpy()).ravel().astype(int))
             for b in loader]
    assert other != buffered               # seed actually controls it


def test_user_batch_sampler_stays_lazy():
    """Only the framework's own BatchSampler is materialized eagerly for
    the RNG fix above — a user-supplied batch_sampler may be generator-
    backed (even infinite), so iter(loader) must not consume it up
    front."""
    from paddle_tpu.io import DataLoader, TensorDataset

    ds = TensorDataset([paddle.to_tensor(
        np.arange(64, dtype="float32").reshape(64, 1))])

    class InfiniteSampler:
        batch_size = 4

        def __iter__(self):
            i = 0
            while True:  # never exhausts — eager materialization hangs
                yield [(i + j) % 64 for j in range(4)]
                i += 4

    for buffered in (False, True):
        loader = DataLoader(ds, batch_sampler=InfiniteSampler(),
                            use_buffer_reader=buffered)
        it = iter(loader)
        got = [np.asarray(next(it)[0].numpy()).ravel() for _ in range(3)]
        assert [tuple(g.astype(int)) for g in got] == [
            (0, 1, 2, 3), (4, 5, 6, 7), (8, 9, 10, 11)]
