"""Detection-op tests (round-3 breadth) — numpy references per the OpTest
contract (reference operators/detection/*.cc; python wrappers in
fluid/layers/detection.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def T(x):
    return paddle.to_tensor(np.asarray(x))


class TestSimpleOps:
    def test_iou_similarity_matches_box_iou(self):
        a = np.array([[0, 0, 10, 10], [5, 5, 15, 15]], np.float32)
        b = np.array([[0, 0, 10, 10]], np.float32)
        out = np.asarray(V.iou_similarity(T(a), T(b)).numpy())
        np.testing.assert_allclose(out[0, 0], 1.0, atol=1e-6)
        # IoU(a2, b1): inter 5x5=25, union 100+100-25=175
        np.testing.assert_allclose(out[1, 0], 25 / 175, atol=1e-6)

    def test_box_clip(self):
        boxes = np.array([[-5.0, -5.0, 30.0, 40.0]], np.float32)
        im_info = np.array([20.0, 25.0, 1.0], np.float32)  # H, W, scale
        out = np.asarray(V.box_clip(T(boxes), T(im_info)).numpy())
        np.testing.assert_allclose(out[0], [0, 0, 24, 19])

    def test_polygon_box_transform(self):
        x = np.zeros((1, 2, 2, 3), np.float32)
        out = np.asarray(V.polygon_box_transform(T(x)).numpy())
        # even channel: 4*col; odd channel: 4*row
        np.testing.assert_allclose(out[0, 0], [[0, 4, 8], [0, 4, 8]])
        np.testing.assert_allclose(out[0, 1], [[0, 0, 0], [4, 4, 4]])

    def test_target_assign(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        midx = np.array([[2, -1, 0]], np.int32)
        out, w = V.target_assign(T(x), T(midx), mismatch_value=7)
        out = np.asarray(out.numpy())
        np.testing.assert_allclose(out[0, 0], x[2])
        np.testing.assert_allclose(out[0, 1], [7, 7, 7, 7])
        np.testing.assert_allclose(out[0, 2], x[0])
        np.testing.assert_allclose(np.asarray(w.numpy())[0, :, 0],
                                   [1, 0, 1])


class TestAnchors:
    def test_anchor_generator_shapes_and_centers(self):
        fm = np.zeros((1, 8, 2, 3), np.float32)
        anc, var = V.anchor_generator(
            fm, anchor_sizes=[32, 64], aspect_ratios=[1.0],
            variances=[0.1, 0.1, 0.2, 0.2], stride=[16.0, 16.0])
        anc = np.asarray(anc.numpy())
        assert anc.shape == (2, 3, 2, 4)
        # reference pixel convention: center 0.5*(16-1)=7.5, size-32 anchor
        # spans +/-0.5*(32-1) => [-8, 23]
        np.testing.assert_allclose(anc[0, 0, 0], [-8, -8, 23, 23])
        np.testing.assert_allclose(np.asarray(var.numpy())[0, 0, 0],
                                   [0.1, 0.1, 0.2, 0.2])

    def test_anchor_generator_matches_reference_kernel(self):
        # direct numpy replica of anchor_generator_op.h:53-86 (round-3
        # advisor finding: rounded base dims, (dim-1) corner convention,
        # offset*(stride-1) centers)
        sizes, ratios = [32.0, 64.0], [0.5, 1.0, 2.0]
        sw, sh, offset = 16.0, 12.0, 0.5
        fh, fw = 3, 4
        fm = np.zeros((1, 8, fh, fw), np.float32)
        anc, _ = V.anchor_generator(
            fm, anchor_sizes=sizes, aspect_ratios=ratios,
            variances=[0.1, 0.1, 0.2, 0.2], stride=[sw, sh], offset=offset)
        anc = np.asarray(anc.numpy())
        exp = np.zeros((fh, fw, len(ratios) * len(sizes), 4), np.float32)
        for hi in range(fh):
            for wi in range(fw):
                x_ctr = wi * sw + offset * (sw - 1)
                y_ctr = hi * sh + offset * (sh - 1)
                idx = 0
                for ar in ratios:
                    for s in sizes:
                        base_w = np.round(np.sqrt(sw * sh / ar))
                        base_h = np.round(base_w * ar)
                        w = (s / sw) * base_w
                        h = (s / sh) * base_h
                        exp[hi, wi, idx] = [x_ctr - 0.5 * (w - 1),
                                            y_ctr - 0.5 * (h - 1),
                                            x_ctr + 0.5 * (w - 1),
                                            y_ctr + 0.5 * (h - 1)]
                        idx += 1
        np.testing.assert_allclose(anc, exp, rtol=1e-6)

    def test_density_prior_box_counts(self):
        fm = np.zeros((1, 8, 4, 4), np.float32)
        img = np.zeros((1, 3, 32, 32), np.float32)
        boxes, var = V.density_prior_box(
            fm, img, densities=[2, 1], fixed_sizes=[8.0, 16.0],
            fixed_ratios=[1.0], clip=True)
        b = np.asarray(boxes.numpy())
        # densities 2 and 1 with one ratio: 4 + 1 anchors per cell
        assert b.shape == (4, 4, 5, 4)
        assert (b >= 0).all() and (b <= 1).all()


class TestFocalLoss:
    def test_matches_numpy_reference(self):
        rng = np.random.RandomState(0)
        x = rng.randn(6, 3).astype(np.float32)
        label = np.array([[1], [0], [3], [2], [0], [1]], np.int32)
        fg = np.array([4], np.int32)
        gamma, alpha = 2.0, 0.25
        out = np.asarray(V.sigmoid_focal_loss(
            T(x), T(label), T(fg), gamma, alpha).numpy())
        p = 1 / (1 + np.exp(-x))
        expect = np.zeros_like(x)
        for i in range(6):
            for c in range(3):
                pos = label[i, 0] == c + 1
                if pos:
                    expect[i, c] = -alpha * (1 - p[i, c]) ** gamma * \
                        np.log(p[i, c])
                else:
                    expect[i, c] = -(1 - alpha) * p[i, c] ** gamma * \
                        np.log(1 - p[i, c])
        expect /= 4.0
        np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-6)


class TestMatrixNMS:
    def test_suppresses_duplicates_keeps_distinct(self):
        boxes = np.array([[[0, 0, 10, 10], [0, 0, 10, 10],
                           [50, 50, 60, 60]]], np.float32)
        scores = np.zeros((1, 2, 3), np.float32)
        scores[0, 1] = [0.9, 0.8, 0.7]
        out, idx, num = V.matrix_nms(T(boxes), T(scores),
                                     score_threshold=0.1,
                                     post_threshold=0.3,
                                     return_index=True)
        out = np.asarray(out.numpy())
        # duplicate of the 0.9 box decays to ~0 and drops; distinct stays
        assert int(np.asarray(num.numpy())[0]) == 2
        np.testing.assert_allclose(sorted(out[:, 1], reverse=True),
                                   out[:, 1])
        assert 0.9 in out[:, 1] and 0.7 in out[:, 1]

    def test_gaussian_decay(self):
        boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11]]], np.float32)
        scores = np.zeros((1, 2, 2), np.float32)
        scores[0, 1] = [0.9, 0.8]
        out = V.matrix_nms(T(boxes), T(scores), score_threshold=0.1,
                           use_gaussian=True, gaussian_sigma=2.0,
                           return_rois_num=False)
        out = np.asarray(out.numpy())
        assert out.shape[0] == 2
        # second box decayed: exp(-iou^2/sigma) < 1
        assert out[1, 1] < 0.8


class TestBipartiteMatch:
    def test_greedy_global_order(self):
        dist = np.array([[0.9, 0.1, 0.3],
                         [0.8, 0.7, 0.2]], np.float32)
        idx, d = V.bipartite_match(T(dist))
        idx = np.asarray(idx.numpy())[0]
        d = np.asarray(d.numpy())[0]
        # global max 0.9 -> row0/col0; next best among remaining: row1/col1
        assert idx[0] == 0 and idx[1] == 1 and idx[2] == -1
        np.testing.assert_allclose(d[:2], [0.9, 0.7])

    def test_per_prediction_fills_leftovers(self):
        dist = np.array([[0.9, 0.1, 0.6]], np.float32)
        idx, d = V.bipartite_match(T(dist), match_type="per_prediction",
                                   dist_threshold=0.5)
        idx = np.asarray(idx.numpy())[0]
        assert idx[0] == 0      # greedy match
        assert idx[2] == 0      # filled: 0.6 > 0.5
        assert idx[1] == -1     # 0.1 < threshold

    def test_jit_safe(self):
        import jax

        dist = np.random.RandomState(0).rand(4, 6).astype(np.float32)

        @jax.jit
        def f(d):
            i, dd = V.bipartite_match(paddle.Tensor(d))
            return i.value, dd.value

        i1, _ = f(dist)
        i2 = np.asarray(V.bipartite_match(T(dist))[0].numpy())
        np.testing.assert_array_equal(np.asarray(i1), i2)


class TestMineHardExamples:
    def test_quota_and_ranking(self):
        cls_loss = np.array([[5.0, 1.0, 4.0, 3.0, 2.0]], np.float32)
        midx = np.array([[1, -1, -1, -1, -1]], np.int32)  # 1 positive
        sel = np.asarray(V.mine_hard_examples(
            T(cls_loss), match_indices=T(midx),
            neg_pos_ratio=2.0).numpy())
        # 1 positive * ratio 2 = 2 negatives: the two highest-loss negs
        assert sel[0].tolist() == [0, 0, 1, 1, 0]


class TestGenerateProposals:
    def _inputs(self, N=1, A=2, H=3, W=3):
        rng = np.random.RandomState(7)
        scores = rng.rand(N, A, H, W).astype(np.float32)
        deltas = (rng.randn(N, 4 * A, H, W) * 0.1).astype(np.float32)
        fm = np.zeros((N, 8, H, W), np.float32)
        anchors, var = V.anchor_generator(
            fm, anchor_sizes=[16.0], aspect_ratios=[1.0, 2.0],
            variances=[1.0, 1.0, 1.0, 1.0], stride=[8.0, 8.0])
        im_shape = np.array([[24.0, 24.0]] * N, np.float32)
        return scores, deltas, im_shape, anchors, var

    def test_basic_pipeline(self):
        scores, deltas, im_shape, anchors, var = self._inputs()
        rois, probs, num = V.generate_proposals(
            T(scores), T(deltas), T(im_shape), anchors, var,
            pre_nms_top_n=12, post_nms_top_n=5, nms_thresh=0.7,
            min_size=1.0, return_rois_num=True)
        r = np.asarray(rois.numpy())
        p = np.asarray(probs.numpy())
        n = int(np.asarray(num.numpy())[0])
        assert r.shape[0] == p.shape[0] == n <= 5
        assert (r[:, 0] >= 0).all() and (r[:, 2] <= 23).all()
        assert (p[:-1, 0] >= p[1:, 0]).all()  # score-sorted

    def test_min_size_filters(self):
        scores, deltas, im_shape, anchors, var = self._inputs()
        rois, _ = V.generate_proposals(
            T(scores), T(deltas), T(im_shape), anchors, var,
            min_size=1e6)
        assert np.asarray(rois.numpy()).shape[0] == 0


class TestFPN:
    def test_distribute_and_restore(self):
        rois = np.array([[0, 0, 10, 10],       # small -> low level
                         [0, 0, 200, 200],     # large -> high level
                         [0, 0, 14, 14]], np.float32)
        multi, restore = V.distribute_fpn_proposals(
            T(rois), min_level=2, max_level=5, refer_level=4,
            refer_scale=224)
        sizes = [np.asarray(m.numpy()).shape[0] for m in multi]
        assert sum(sizes) == 3
        assert sizes[0] == 2          # both small boxes at min level
        ridx = np.asarray(restore.numpy())[:, 0]
        cat = np.concatenate([np.asarray(m.numpy()) for m in multi], 0)
        np.testing.assert_allclose(cat[ridx], rois)

    def test_collect_top_k(self):
        r1 = np.array([[0, 0, 1, 1], [0, 0, 2, 2]], np.float32)
        r2 = np.array([[0, 0, 3, 3]], np.float32)
        s1 = np.array([0.2, 0.9], np.float32)
        s2 = np.array([0.5], np.float32)
        out = V.collect_fpn_proposals([T(r1), T(r2)], [T(s1), T(s2)],
                                      2, 3, post_nms_top_n=2)
        out = np.asarray(out.numpy())
        np.testing.assert_allclose(out[0], [0, 0, 2, 2])  # 0.9 first
        np.testing.assert_allclose(out[1], [0, 0, 3, 3])  # then 0.5


class TestBoxDecoderAndAssign:
    def test_decode_and_pick_best_class(self):
        priors = np.array([[0, 0, 10, 10]], np.float32)
        pvar = np.array([[1, 1, 1, 1]], np.float32)
        targets = np.zeros((1, 8), np.float32)  # 2 classes, zero deltas
        targets[0, 4:] = [0.1, 0.1, 0.0, 0.0]   # class-2 shifted
        scores = np.array([[0.1, 0.2, 0.7]], np.float32)  # bg, c1, c2
        dec, assigned = V.box_decoder_and_assign(
            T(priors), T(pvar), T(targets), T(scores))
        dec = np.asarray(dec.numpy())
        a = np.asarray(assigned.numpy())
        # zero deltas decode back to the prior
        np.testing.assert_allclose(dec[0, :4], [0, 0, 10, 10], atol=1e-5)
        # best class (c2) is the shifted box
        np.testing.assert_allclose(a[0], dec[0, 4:], atol=1e-5)
