"""DCN-shaped hybrid parallelism test (VERDICT r04 next-step #7): TWO
processes (the "hosts", dp over DCN) × FOUR virtual CPU devices each
(the "chips", mp over ICI) — the v4-style topology where tensor
parallelism stays inside a host and data parallelism crosses hosts.

jax 0.4.37's CPU backend rejects multiprocess XLA computations, so the
DCN axis cannot be a global in-graph mesh dimension here.  That split is
exactly the reference runtime's (SURVEY §2.5): tensor parallelism rides
the interconnect IN-GRAPH (a local mp=4 mesh per process), while the
cross-host dp grad sync rides the control plane — podcoll's host-level
all_reduce_mean over the jax coordination KV, the same transport the
elastic pod runtime uses.  Parity oracle: per-step loss and parameters
against the same model trained single-process on a global dp=2 x mp=4
mesh of 8 virtual devices, where XLA inserts the dp all-reduce itself.
"""
import os
import re
import socket
import subprocess
import sys
import textwrap

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Shared by both modes.  Multi-process (PADDLE_TRAINER_ID set): jax
# .distributed.initialize, a LOCAL {"mp": 4} mesh per process, the dp
# half-batch strided by rank, and host-level grad averaging through
# podcoll.  Single-process reference: a global {"dp": 2, "mp": 4} mesh
# over 8 virtual devices, full batch, in-graph dp all-reduce.
TRAINER = textwrap.dedent("""
    import json
    import os

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    multi = os.environ.get("PADDLE_TRAINER_ID") is not None
    if multi:
        import paddle_tpu.distributed as dist_env
        env = dist_env.init_parallel_env()   # jax.distributed.initialize
        rank = env.rank
        assert jax.process_count() == 2
        assert len(jax.local_devices()) == 4
    else:
        rank = 0

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import podcoll
    from paddle_tpu.distributed.mesh import build_mesh, mesh_guard
    from paddle_tpu.nn.layer_base import functional_call, state_pytrees

    if multi:
        # mp (ICI) is in-graph over the LOCAL devices; dp (DCN) is a
        # host-level collective — no global mesh on the CPU backend
        mesh = build_mesh({"mp": 4}, devices=jax.local_devices())
        group = podcoll.default_group()
        assert group is not None and group.world == 2
    else:
        assert jax.device_count() == 8
        mesh = build_mesh({"dp": 2, "mp": 4})
    with mesh_guard(mesh):
        paddle.seed(0)
        net = paddle.nn.Sequential(
            dist.ColumnParallelLinear(8, 32, gather_output=False),
            dist.RowParallelLinear(32, 1, input_is_parallel=True))
        params, buffers = state_pytrees(net)
        shardings = dist.param_sharding(net, mesh)
        params = {k: jax.device_put(v, shardings[k])
                  for k, v in params.items()}

        rs = np.random.RandomState(7)
        X = rs.randn(16, 8).astype(np.float32)
        Y = (X @ rs.randn(8, 1).astype(np.float32))
        if multi:
            # this host's dp shard, replicated over the local mp mesh
            Xg, Yg = X[rank::2], Y[rank::2]
        else:
            xsh = NamedSharding(mesh, P("dp"))
            Xg = jax.make_array_from_callback(X.shape, xsh,
                                              lambda i: X[i])
            Yg = jax.make_array_from_callback(Y.shape, xsh,
                                              lambda i: Y[i])

        def fwd(p, x, y):
            def loss_fn(p):
                out, _ = functional_call(net, p, (paddle.Tensor(x),),
                                         buffers=buffers)
                return ((out.value - y) ** 2).mean()
            return jax.value_and_grad(loss_fn)(p)

        jfwd = jax.jit(fwd)
        losses = []
        for _ in range(5):
            loss, g = jfwd(params, Xg, Yg)
            if multi:
                # DCN hop: average grads (and the reported loss) across
                # hosts on the control plane; equal dp shards make the
                # mean of local means the full-batch value
                g = {k: jax.device_put(
                        np.asarray(group.all_reduce_mean(np.asarray(v))),
                        shardings[k]) for k, v in g.items()}
                loss = group.all_reduce_mean(np.asarray(loss))
            params = {k: params[k] - 0.05 * g[k] for k in params}
            losses.append(float(np.asarray(loss)))
    print("DCN_LOSSES_RANK%d " % rank + json.dumps(losses), flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _base_env():
    from conftest import cpu_subprocess_env

    env = cpu_subprocess_env()
    env["PYTHONPATH"] = REPO
    return env


def _run_multi(script):
    port = _free_port()
    eps = [f"127.0.0.1:{port}", f"127.0.0.1:{port + 1}"]
    procs = []
    for rank in range(2):
        env = _base_env()
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TRAINER_ENDPOINTS": ",".join(eps),
            "PADDLE_CURRENT_ENDPOINT": eps[rank],
            "PADDLE_MASTER": eps[0],
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            return None, "trainer hung"
        if p.returncode != 0:
            for q in procs:
                q.kill()
            return None, err[-2000:]
        outs.append(out)
    return outs, ""


def _losses(out):
    m = re.search(r"DCN_LOSSES_RANK\d (\[.*\])", out)
    assert m, out
    import json
    return json.loads(m.group(1))


def test_dcn_hybrid_two_process_parity(tmp_path):
    script = tmp_path / "dcn_trainer.py"
    script.write_text(TRAINER)

    # single-process 8-device reference
    env = _base_env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    for k in ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM",
              "PADDLE_TRAINER_ENDPOINTS", "PADDLE_CURRENT_ENDPOINT",
              "PADDLE_MASTER"):
        env.pop(k, None)
    ref = subprocess.run([sys.executable, str(script)], env=env,
                         capture_output=True, text=True, timeout=300)
    assert ref.returncode == 0, ref.stderr[-2000:]
    ref_losses = _losses(ref.stdout)
    assert len(ref_losses) == 5
    assert ref_losses[-1] < ref_losses[0]  # it actually trains

    outs, err = _run_multi(script)
    if outs is None and ("port" in err.lower() or "bind" in err.lower()
                         or "hung" in err):
        outs, err = _run_multi(script)  # one retry on port races
    assert outs is not None, err
    l0, l1 = _losses(outs[0]), _losses(outs[1])
    np.testing.assert_allclose(l0, l1, rtol=1e-6)  # ranks agree
    np.testing.assert_allclose(l0, ref_losses, rtol=1e-4, atol=1e-6)
