"""TestDistBase analog — REAL multi-process training parity.

Reference contract: fluid/tests/unittests/test_dist_base.py:652,765-831 —
spawn separate trainer processes, train the same model data-parallel, and
assert per-step losses match a single-process run within delta.  This is
the only test that exercises init_parallel_env →
jax.distributed.initialize → cross-process eager collectives end to end
(distributed/parallel.py:39-44); the 8-virtual-device mesh tests cannot,
because they live in one process.
"""
import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRAINER = textwrap.dedent("""
    import json
    import os
    import sys

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    env = dist.init_parallel_env()      # -> jax.distributed.initialize
    rank, world = env.rank, env.world_size
    assert jax.process_count() == world, (jax.process_count(), world)
    assert jax.device_count() == world  # one cpu device per process

    paddle.seed(0)                      # identical init on every rank
    rs = np.random.RandomState(42)
    X = rs.randn(32, 8).astype(np.float32)
    W = rs.randn(8, 1).astype(np.float32)
    Y = X @ W + 0.1 * rs.randn(32, 1).astype(np.float32)

    model = paddle.nn.Linear(8, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    losses = []
    for step in range(5):
        xb, yb = X[rank::world], Y[rank::world]
        out = model(paddle.to_tensor(xb))
        loss = ((out - paddle.to_tensor(yb)) ** 2).mean()
        loss.backward()
        for p in model.parameters():    # DP grad sync (Reducer analog)
            if p.grad is not None:
                dist.all_reduce(p.grad)
                p.grad.set_value(np.asarray(p.grad.numpy()) / world)
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss.numpy())))
    print("LOSSES_RANK%d " % rank + json.dumps(losses), flush=True)
""")


def _single_process_reference():
    """The same 5 steps on the full batch in-process."""
    import jax

    import paddle_tpu as paddle

    paddle.seed(0)
    rs = np.random.RandomState(42)
    X = rs.randn(32, 8).astype(np.float32)
    W = rs.randn(8, 1).astype(np.float32)
    Y = X @ W + 0.1 * rs.randn(32, 1).astype(np.float32)
    model = paddle.nn.Linear(8, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    losses = []
    for step in range(5):
        out = model(paddle.to_tensor(X))
        loss = ((out - paddle.to_tensor(Y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss.numpy())))
    return losses


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _launch_trainers(script):
    port = _free_port()
    eps = [f"127.0.0.1:{port}", f"127.0.0.1:{port + 1}"]
    procs = []
    for rank in range(2):
        from conftest import cpu_subprocess_env

        env = cpu_subprocess_env()
        env.pop("XLA_FLAGS", None)             # exactly 1 device/process
        env.update({
            "PYTHONPATH": REPO,
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TRAINER_ENDPOINTS": ",".join(eps),
            "PADDLE_CURRENT_ENDPOINT": eps[rank],
            "PADDLE_MASTER": eps[0],
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs, ok, err_tail = [], True, ""
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            return None, "trainer process hung (coordination service?)"
        if p.returncode != 0:
            ok, err_tail = False, err[-2000:]
        outs.append(out)
    return (outs, "") if ok else (None, err_tail)


def test_two_process_dp_loss_parity(tmp_path):
    script = tmp_path / "trainer.py"
    script.write_text(TRAINER)
    # one retry with fresh ports, gated on the port-race signature only:
    # under a loaded machine the freed probe port can be re-taken before
    # the coordination service binds it (deterministic trainer bugs must
    # fail immediately)
    outs, err = _launch_trainers(script)
    port_race = any(sig in err for sig in (
        "hung", "Failed to bind", "address already in use",
        "UNAVAILABLE", "DEADLINE_EXCEEDED"))
    if outs is None and port_race:
        outs, err = _launch_trainers(script)
    assert outs is not None, f"trainers failed:\n{err}"

    per_rank = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("LOSSES_RANK"):
                rank = int(line[len("LOSSES_RANK")])
                per_rank[rank] = json.loads(line.split(" ", 1)[1])
    assert set(per_rank) == {0, 1}, f"missing rank output: {outs}"

    ref = _single_process_reference()
    # full-batch MSE == mean of the two stride-shard MSEs (equal shards),
    # and averaged grads make the updates identical -> per-step parity
    for step in range(5):
        dist_loss = 0.5 * (per_rank[0][step] + per_rank[1][step])
        assert abs(dist_loss - ref[step]) < 1e-4, (
            step, dist_loss, ref[step], per_rank)
    # training actually progressed
    assert ref[-1] < ref[0]
