"""paddle.distribution parity tests (reference:
fluid/layers/distributions.py Normal:260 / Uniform:115 / Categorical:425
/ MultivariateNormalDiag:531)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distribution import (Categorical, MultivariateNormalDiag,
                                     Normal, Uniform, kl_divergence)


class TestNormal:
    def test_log_prob_and_entropy(self):
        d = Normal(0.0, 2.0)
        lp = float(np.asarray(d.log_prob(
            paddle.to_tensor(np.float32(0.0))).numpy()))
        assert abs(lp - (-np.log(2.0) - 0.5 * np.log(2 * np.pi))) < 1e-5
        ent = float(np.asarray(d.entropy().numpy()))
        assert abs(ent - (0.5 + 0.5 * np.log(2 * np.pi) + np.log(2.0))) < 1e-5

    def test_kl_zero_for_same(self):
        d = Normal(1.0, 3.0)
        assert abs(float(np.asarray(
            kl_divergence(d, Normal(1.0, 3.0)).numpy()))) < 1e-7

    def test_sampling_moments(self):
        paddle.seed(0)
        d = Normal(2.0, 0.5)
        s = np.asarray(d.sample((4000,)).numpy())
        assert abs(s.mean() - 2.0) < 0.05
        assert abs(s.std() - 0.5) < 0.05


class TestUniform:
    def test_lp_inside_outside(self):
        d = Uniform(0.0, 4.0)
        inside = float(np.asarray(d.log_prob(
            paddle.to_tensor(np.float32(1.0))).numpy()))
        assert abs(inside + np.log(4.0)) < 1e-6


class TestCategorical:
    def test_kl_and_entropy(self):
        p = Categorical(paddle.to_tensor(np.log(
            np.array([0.5, 0.5], np.float32))))
        q = Categorical(paddle.to_tensor(np.log(
            np.array([0.9, 0.1], np.float32))))
        kl = float(np.asarray(kl_divergence(p, q).numpy()))
        expect = 0.5 * np.log(0.5 / 0.9) + 0.5 * np.log(0.5 / 0.1)
        assert abs(kl - expect) < 1e-5


class TestMultivariateNormalDiag:
    def test_closed_forms(self):
        p = MultivariateNormalDiag(np.zeros(3, np.float32),
                                   np.ones(3, np.float32))
        q = MultivariateNormalDiag(np.ones(3, np.float32),
                                   2 * np.ones(3, np.float32))
        lp = float(np.asarray(p.log_prob(np.zeros(3, np.float32)).numpy()))
        assert abs(lp + 1.5 * np.log(2 * np.pi)) < 1e-5
        ent = float(np.asarray(p.entropy().numpy()))
        assert abs(ent - 1.5 * (1 + np.log(2 * np.pi))) < 1e-5
        kl = float(np.asarray(kl_divergence(p, q).numpy()))
        expect = 3 * 0.5 * (0.25 + 0.25 - 1 - np.log(0.25))
        assert abs(kl - expect) < 1e-5

    def test_diag_matrix_input_accepted(self):
        # the reference stores a diagonal MATRIX; both forms must agree
        s = np.diag([1.0, 2.0, 3.0]).astype(np.float32)
        a = MultivariateNormalDiag(np.zeros(3, np.float32), s)
        b = MultivariateNormalDiag(np.zeros(3, np.float32),
                                   np.array([1, 2, 3], np.float32))
        np.testing.assert_allclose(np.asarray(a.entropy().numpy()),
                                   np.asarray(b.entropy().numpy()))

    def test_sampling_moments(self):
        paddle.seed(1)
        d = MultivariateNormalDiag(np.array([1.0, -1.0], np.float32),
                                   np.array([0.5, 2.0], np.float32))
        s = np.asarray(d.sample((4000,)).numpy())
        assert np.abs(s.mean(0) - [1.0, -1.0]).max() < 0.1
        assert np.abs(s.std(0) - [0.5, 2.0]).max() < 0.15

    def test_broadcast_loc_and_scalar_rejection(self):
        # broadcast loc [1] against scale [3]: K must be 3, so log_prob
        # at the mean is -1.5*log(2*pi), not the K=1 value
        d = MultivariateNormalDiag(np.zeros(1, np.float32),
                                   np.ones(3, np.float32))
        lp = float(np.asarray(d.log_prob(np.zeros(3, np.float32)).numpy()))
        assert abs(lp + 1.5 * np.log(2 * np.pi)) < 1e-5
        with pytest.raises(ValueError, match="event axis"):
            MultivariateNormalDiag(0.0, 1.0)

    def test_non_diagonal_matrix_rejected(self):
        m = np.array([[1.0, 0.5], [0.0, 2.0]], np.float32)
        with pytest.raises(ValueError, match="DIAGONAL"):
            MultivariateNormalDiag(np.zeros(2, np.float32), m)

    def test_batched_vector_scale_not_misread_as_matrix(self):
        # loc [B,K] + scale [B,K] with B==K must stay a batch of vectors
        loc = np.zeros((3, 3), np.float32)
        sc = np.array([[1, 1, 1], [2, 2, 2], [3, 3, 3]], np.float32)
        d = MultivariateNormalDiag(loc, sc)
        ent = np.asarray(d.entropy().numpy())
        assert ent.shape == (3,)
        assert ent[1] > ent[0] and ent[2] > ent[1]
