"""Distribution math parity vs torch.distributions: log_prob, entropy,
and KL divergence closed forms on identical parameters."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.distributions as td  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.distribution import (Categorical, Normal, Uniform,
                                     kl_divergence)  # noqa: E402

rs = np.random.RandomState(29)


def _cmp(pd_out, t_out, atol=1e-5):
    np.testing.assert_allclose(np.asarray(pd_out.numpy()),
                               t_out.numpy(), atol=atol, rtol=1e-5)


def test_normal_log_prob_entropy_kl():
    mu = rs.randn(5).astype(np.float32)
    sd = (rs.rand(5).astype(np.float32) + 0.3)
    x = rs.randn(5).astype(np.float32)
    pn = Normal(paddle.to_tensor(mu), paddle.to_tensor(sd))
    tn = td.Normal(torch.tensor(mu), torch.tensor(sd))
    _cmp(pn.log_prob(paddle.to_tensor(x)),
         tn.log_prob(torch.tensor(x)))
    _cmp(pn.entropy(), tn.entropy())
    mu2 = rs.randn(5).astype(np.float32)
    sd2 = (rs.rand(5).astype(np.float32) + 0.3)
    pn2 = Normal(paddle.to_tensor(mu2), paddle.to_tensor(sd2))
    tn2 = td.Normal(torch.tensor(mu2), torch.tensor(sd2))
    _cmp(kl_divergence(pn, pn2), td.kl_divergence(tn, tn2))


def test_uniform_log_prob_entropy():
    lo = np.float32(-1.5)
    hi = np.float32(2.5)
    pu = Uniform(paddle.to_tensor(lo), paddle.to_tensor(hi))
    tu = td.Uniform(torch.tensor(lo), torch.tensor(hi))
    x = np.array([-1.0, 0.0, 2.0], np.float32)
    _cmp(pu.log_prob(paddle.to_tensor(x)), tu.log_prob(torch.tensor(x)))
    _cmp(pu.entropy(), tu.entropy())


def test_categorical_log_prob_entropy_kl():
    # reference contract: Categorical takes unnormalized LOGITS
    # (distribution.py:640), like td.Categorical(logits=...)
    logits = rs.randn(6).astype(np.float32)
    pc = Categorical(paddle.to_tensor(logits))
    tc = td.Categorical(logits=torch.tensor(logits))
    ids = np.array([0, 3, 5], np.int64)
    _cmp(pc.log_prob(paddle.to_tensor(ids)),
         tc.log_prob(torch.tensor(ids)))
    _cmp(pc.entropy(), tc.entropy())
    logits2 = rs.randn(6).astype(np.float32)
    pc2 = Categorical(paddle.to_tensor(logits2))
    tc2 = td.Categorical(logits=torch.tensor(logits2))
    _cmp(kl_divergence(pc, pc2), td.kl_divergence(tc, tc2))
