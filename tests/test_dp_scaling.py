"""Data-parallel scaling shape (BASELINE config 2's "linear scaling"
target, VERDICT r04 weak #4): with per-device batch held constant, the
per-device compiled work must stay constant as dp grows 1 -> 8 — that is
the throughput model behind linear scaling (total samples/s = dp x
per-device samples/s).  Asserted deterministically from XLA cost
analysis (8 virtual CPU devices share real cores, so wall-clock here
cannot show the linearity a real pod would).
Reference: fluid/dygraph/parallel.py:314 (DataParallel scale_loss /
apply_collective_grads)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.mesh import build_mesh, mesh_guard
from paddle_tpu.nn.layer_base import functional_call, state_pytrees
from paddle_tpu.vision.models import resnet18


PER_DEVICE_B = 2


def _compiled_step(dp):
    mesh = build_mesh({"dp": dp}, devices=jax.devices()[:dp])
    with mesh_guard(mesh):
        paddle.seed(0)
        model = resnet18(num_classes=10)
        model.train()
        params, buffers = state_pytrees(model)
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        opt_state = opt.init_pytree(params)

        def step(carry, images, labels):
            p, s = carry

            def loss_fn(p):
                out, _ = functional_call(model, p,
                                         (paddle.Tensor(images),),
                                         buffers=buffers)
                logits = out.value.astype(jnp.float32)
                logp = jax.nn.log_softmax(logits, -1)
                return -jnp.take_along_axis(
                    logp, labels[:, None], -1).mean()

            loss, grads = jax.value_and_grad(loss_fn)(p)
            p, s = opt.apply_pytree(p, grads, s, lr=0.1, step=1)
            return (p, s), loss

        B = PER_DEVICE_B * dp
        rs = np.random.RandomState(0)
        images = jax.device_put(
            jnp.asarray(rs.randn(B, 3, 32, 32), jnp.float32),
            NamedSharding(mesh, P("dp")))
        labels = jax.device_put(
            jnp.asarray(rs.randint(0, 10, (B,)), jnp.int32),
            NamedSharding(mesh, P("dp")))
        rep = NamedSharding(mesh, P())
        carry = jax.device_put((params, opt_state), rep)
        compiled = jax.jit(step).lower(carry, images, labels).compile()
        return compiled, (carry, images, labels)


def _flops(compiled):
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    return float(ca.get("flops", 0.0))


def test_dp_scaling_constant_per_device_work():
    if jax.device_count() < 8:
        pytest.skip("needs the 8-virtual-device conftest mesh")
    c1, args1 = _compiled_step(1)
    c8, args8 = _compiled_step(8)
    f1, f8 = _flops(c1), _flops(c8)
    assert f1 > 0 and f8 > 0
    # XLA reports per-device flops for SPMD partitioned modules: with
    # per-device batch fixed, dp=8 work per device must stay within 15%
    # of dp=1 (the grad all-reduce adds no flops, only comms)
    assert f8 / f1 < 1.15, (f1, f8)
    # the dp grad sync must exist (all-reduce over the dp axis); dp=1
    # compiles to a single-device module with no collective
    hlo8 = c8.as_text()
    assert "all-reduce" in hlo8
    assert "all-reduce" not in c1.as_text()
    # both actually execute
    (_, loss1) = c1(*args1)
    (_, loss8) = c8(*args8)
    assert np.isfinite(float(np.asarray(loss1)))
    assert np.isfinite(float(np.asarray(loss8)))
