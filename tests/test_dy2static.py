"""dygraph→static AST conversion tests (reference:
dygraph_to_static/program_translator.py:233 + convert_operators.py —
python control flow over tensors must survive to_static with BOTH branches
live in the compiled program)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit import dy2static


class BranchNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 4)
        self.fc2 = nn.Linear(4, 4)

    def forward(self, x):
        if x.sum() > 0:
            y = self.fc1(x)
        else:
            y = self.fc2(x)
        return y


class TestConvertIf:
    def test_both_branches_live_after_to_static(self):
        net = BranchNet()
        paddle.jit.to_static(net)
        xp = paddle.to_tensor(np.ones((2, 4), np.float32))
        xn = paddle.to_tensor(-np.ones((2, 4), np.float32))
        np.testing.assert_allclose(np.asarray(net(xp).numpy()),
                                   np.asarray(net.fc1(xp).numpy()),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(net(xn).numpy()),
                                   np.asarray(net.fc2(xn).numpy()),
                                   atol=1e-6)

    def test_early_return_pattern(self):
        @paddle.jit.to_static
        def f(x):
            if x.sum() > 0:
                return x * 2.0
            return x - 1.0

        pos = f(paddle.to_tensor(np.ones(2, np.float32)))
        neg = f(paddle.to_tensor(-np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(pos.numpy()), [2.0, 2.0])
        np.testing.assert_allclose(np.asarray(neg.numpy()), [-2.0, -2.0])

    def test_python_pred_stays_python(self):
        @paddle.jit.to_static
        def f(x, flag):
            if flag:          # python bool argument
                y = x * 2.0
            else:
                y = x * 3.0
            return y

        # flag traces as an array; the converted dispatch still works
        out = f(paddle.to_tensor(np.ones(2, np.float32)), True)
        np.testing.assert_allclose(np.asarray(out.numpy()), [2.0, 2.0])

    def test_var_assigned_in_one_branch_only_raises_clearly(self):
        @paddle.jit.to_static
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                z = x * 3.0  # noqa: F841 — deliberate one-sided assign
            return y  # noqa: F821

        with pytest.raises(Exception):
            f(paddle.to_tensor(np.ones(2, np.float32)))


class TestConvertWhile:
    def test_data_dependent_trip_count(self):
        @paddle.jit.to_static
        def collatz(x):
            n = 0
            while x > 1.0:
                x = paddle.where((x % 2.0) == 0.0, x / 2.0, 3.0 * x + 1.0)
                n = n + 1
            return n

        r = collatz(paddle.to_tensor(np.float32(6.0)))
        assert int(np.asarray(r.numpy() if hasattr(r, "numpy") else r)) == 8
        r = collatz(paddle.to_tensor(np.float32(1.0)))
        assert int(np.asarray(r.numpy() if hasattr(r, "numpy") else r)) == 0

    def test_for_over_traced_range(self):
        @paddle.jit.to_static
        def f(x, n):
            acc = paddle.zeros([2])
            for i in range(n):
                acc = acc + x * (i + 1.0)
            return acc

        out = f(paddle.to_tensor(np.ones(2, np.float32)), 3)
        np.testing.assert_allclose(np.asarray(out.numpy()), [6.0, 6.0])

    def test_python_range_still_python(self):
        @paddle.jit.to_static
        def f(x):
            acc = x
            for _ in range(3):
                acc = acc * 2.0
            return acc

        out = f(paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(out.numpy()), [8.0, 8.0])


class TestSaveLoadRoundtrip:
    def test_saved_model_keeps_both_branches(self, tmp_path):
        from paddle_tpu.inference import (load_inference_model,
                                          save_inference_model)

        net = BranchNet()
        paddle.jit.to_static(net)
        prefix = str(tmp_path / "branchy")
        save_inference_model(
            prefix, net,
            example_inputs=[np.ones((2, 4), np.float32)])
        pred = load_inference_model(prefix)
        xp = np.ones((2, 4), np.float32)
        xn = -np.ones((2, 4), np.float32)
        op, = pred.run([xp])
        on, = pred.run([xn])
        np.testing.assert_allclose(
            op, np.asarray(net.fc1(paddle.to_tensor(xp)).numpy()),
            atol=1e-5)
        np.testing.assert_allclose(
            on, np.asarray(net.fc2(paddle.to_tensor(xn)).numpy()),
            atol=1e-5)


class TestConversionFallbacks:
    def test_unsupported_constructs_fall_back(self):
        # break inside a loop: conversion declines, plain tracing still
        # works because the loop is over a python range
        @paddle.jit.to_static
        def f(x):
            acc = x
            for i in range(5):
                if i >= 2:
                    break
                acc = acc * 2.0
            return acc

        out = f(paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(out.numpy()), [4.0, 4.0])

    def test_no_control_flow_is_not_converted(self):
        def f(x):
            return x * 2.0

        with pytest.raises(dy2static.ConversionError):
            dy2static.convert_function(f)

    def test_not_to_static_opts_out(self):
        @paddle.jit.not_to_static
        def f(x):
            if isinstance(x, str):
                return None
            return x * 2.0

        g = paddle.jit.to_static(f)
        out = g(paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(out.numpy()), [2.0, 2.0])
