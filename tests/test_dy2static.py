"""dygraph→static AST conversion tests (reference:
dygraph_to_static/program_translator.py:233 + convert_operators.py —
python control flow over tensors must survive to_static with BOTH branches
live in the compiled program)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit import dy2static


class BranchNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 4)
        self.fc2 = nn.Linear(4, 4)

    def forward(self, x):
        if x.sum() > 0:
            y = self.fc1(x)
        else:
            y = self.fc2(x)
        return y


class TestConvertIf:
    def test_both_branches_live_after_to_static(self):
        net = BranchNet()
        paddle.jit.to_static(net)
        xp = paddle.to_tensor(np.ones((2, 4), np.float32))
        xn = paddle.to_tensor(-np.ones((2, 4), np.float32))
        np.testing.assert_allclose(np.asarray(net(xp).numpy()),
                                   np.asarray(net.fc1(xp).numpy()),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(net(xn).numpy()),
                                   np.asarray(net.fc2(xn).numpy()),
                                   atol=1e-6)

    def test_early_return_pattern(self):
        @paddle.jit.to_static
        def f(x):
            if x.sum() > 0:
                return x * 2.0
            return x - 1.0

        pos = f(paddle.to_tensor(np.ones(2, np.float32)))
        neg = f(paddle.to_tensor(-np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(pos.numpy()), [2.0, 2.0])
        np.testing.assert_allclose(np.asarray(neg.numpy()), [-2.0, -2.0])

    def test_python_pred_stays_python(self):
        @paddle.jit.to_static
        def f(x, flag):
            if flag:          # python bool argument
                y = x * 2.0
            else:
                y = x * 3.0
            return y

        # flag traces as an array; the converted dispatch still works
        out = f(paddle.to_tensor(np.ones(2, np.float32)), True)
        np.testing.assert_allclose(np.asarray(out.numpy()), [2.0, 2.0])

    def test_var_assigned_in_one_branch_only_raises_clearly(self):
        @paddle.jit.to_static
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                z = x * 3.0  # noqa: F841 — deliberate one-sided assign
            return y  # noqa: F821

        with pytest.raises(Exception):
            f(paddle.to_tensor(np.ones(2, np.float32)))


class TestConvertWhile:
    def test_data_dependent_trip_count(self):
        @paddle.jit.to_static
        def collatz(x):
            n = 0
            while x > 1.0:
                x = paddle.where((x % 2.0) == 0.0, x / 2.0, 3.0 * x + 1.0)
                n = n + 1
            return n

        r = collatz(paddle.to_tensor(np.float32(6.0)))
        assert int(np.asarray(r.numpy() if hasattr(r, "numpy") else r)) == 8
        r = collatz(paddle.to_tensor(np.float32(1.0)))
        assert int(np.asarray(r.numpy() if hasattr(r, "numpy") else r)) == 0

    def test_for_over_traced_range(self):
        @paddle.jit.to_static
        def f(x, n):
            acc = paddle.zeros([2])
            for i in range(n):
                acc = acc + x * (i + 1.0)
            return acc

        out = f(paddle.to_tensor(np.ones(2, np.float32)), 3)
        np.testing.assert_allclose(np.asarray(out.numpy()), [6.0, 6.0])

    def test_python_range_still_python(self):
        @paddle.jit.to_static
        def f(x):
            acc = x
            for _ in range(3):
                acc = acc * 2.0
            return acc

        out = f(paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(out.numpy()), [8.0, 8.0])


class TestSaveLoadRoundtrip:
    def test_saved_model_keeps_both_branches(self, tmp_path):
        from paddle_tpu.inference import (load_inference_model,
                                          save_inference_model)

        net = BranchNet()
        paddle.jit.to_static(net)
        prefix = str(tmp_path / "branchy")
        save_inference_model(
            prefix, net,
            example_inputs=[np.ones((2, 4), np.float32)])
        pred = load_inference_model(prefix)
        xp = np.ones((2, 4), np.float32)
        xn = -np.ones((2, 4), np.float32)
        op, = pred.run([xp])
        on, = pred.run([xn])
        np.testing.assert_allclose(
            op, np.asarray(net.fc1(paddle.to_tensor(xp)).numpy()),
            atol=1e-5)
        np.testing.assert_allclose(
            on, np.asarray(net.fc2(paddle.to_tensor(xn)).numpy()),
            atol=1e-5)


class TestBreakContinue:
    """break/continue flag-elimination (reference
    break_continue_transformer.py analog)."""

    def test_break_python_range(self):
        @paddle.jit.to_static
        def f(x):
            acc = x
            for i in range(5):
                if i >= 2:
                    break
                acc = acc * 2.0
            return acc

        out = f(paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(out.numpy()), [4.0, 4.0])

    def test_break_tensor_condition_compiles_both_ways(self):
        @paddle.jit.to_static
        def f(x, n):
            acc = paddle.zeros([2])
            for i in range(n):          # traced trip count
                if (acc.sum() > 5.0):   # tensor break condition
                    break
                acc = acc + x
            return acc

        # n traced: 2 ones per step; after 3 steps sum=6>5 -> stops at 3
        out = f(paddle.to_tensor(np.ones(2, np.float32)), 10)
        np.testing.assert_allclose(np.asarray(out.numpy()), [3.0, 3.0])

    def test_continue_tensor_condition(self):
        @paddle.jit.to_static
        def f(x, n):
            acc = paddle.zeros([])
            for i in range(n):
                if (i % 2) == 1:   # traced parity -> tensor condition
                    continue
                acc = acc + 1.0
            return acc

        out = f(paddle.to_tensor(np.ones(2, np.float32)), 6)
        assert float(np.asarray(out.numpy())) == 3.0

    def test_break_in_while_tensor(self):
        @paddle.jit.to_static
        def f(x):
            i = paddle.zeros([])
            while i < 100.0:
                if x.sum() * i > 4.0:
                    break
                i = i + 1.0
            return i

        out = f(paddle.to_tensor(np.ones(2, np.float32) * 0.5))
        # x.sum()=1.0; break when i>4 -> loop leaves i==5
        assert float(np.asarray(out.numpy())) == 5.0

    def test_python_break_condition_not_reevaluated(self):
        # the loop condition must not re-run after break fires on the
        # python path (it may index past the break point)
        q = [1.0, 2.0, 3.0]

        @paddle.jit.to_static
        def f(x):
            i = 0
            while q[i] > 0:
                i = i + 1
                if i == len(q):
                    break
            return x * float(i)

        out = f(paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(out.numpy()), [3.0, 3.0])

    def test_break_only_inside_try_falls_back_cleanly(self):
        import warnings

        def f(x):
            acc = x
            for i in range(4):
                if i >= 1:
                    try:
                        break
                    finally:
                        pass
                acc = acc * 2.0
            return acc

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            g = paddle.jit.to_static(f)
            out = g(paddle.to_tensor(np.ones(2, np.float32)))
        assert any("falling back" in str(x.message) for x in w)
        np.testing.assert_allclose(np.asarray(out.numpy()), [2.0, 2.0])

    def test_statements_after_breaking_if_are_guarded(self):
        @paddle.jit.to_static
        def f(x, n):
            acc = paddle.zeros([])
            for i in range(n):
                if acc > 2.5:
                    break
                acc = acc + x.sum()
                acc = acc + 0.0
            return acc

        out = f(paddle.to_tensor(np.ones(1, np.float32)), 10)
        assert float(np.asarray(out.numpy())) == 3.0


class TestConversionFallbacks:

    def test_no_control_flow_is_not_converted(self):
        def f(x):
            return x * 2.0

        with pytest.raises(dy2static.ConversionError):
            dy2static.convert_function(f)

    def test_not_to_static_opts_out(self):
        @paddle.jit.not_to_static
        def f(x):
            if isinstance(x, str):
                return None
            return x * 2.0

        g = paddle.jit.to_static(f)
        out = g(paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(out.numpy()), [2.0, 2.0])

    def test_fallback_warns_loudly(self):
        import warnings

        # with/try around break does not convert -> ConversionError -> the
        # fallback must WARN (round-3 verdict: silent fallback could bake
        # a data-dependent branch with no signal)
        def f(x):
            acc = x
            for i in range(3):
                try:
                    if i >= 1:
                        break
                finally:
                    pass
                acc = acc * 2.0
            return acc

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            g = paddle.jit.to_static(f)
            out = g(paddle.to_tensor(np.ones(2, np.float32)))
        assert any("falling back to plain tracing" in str(x.message)
                   for x in w)
        np.testing.assert_allclose(np.asarray(out.numpy()), [2.0, 2.0])

    def test_foreign_decorator_refused_with_warning(self):
        import functools
        import warnings

        def deco(fn):
            @functools.wraps(fn)
            def inner(*a, **k):
                return fn(*a, **k) + 1.0
            return inner

        @deco
        def f(x):
            if x.shape[0] > 0:  # static-shape branch: plain trace works
                y = x * 2.0
            else:
                y = x * 3.0
            return y

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            g = paddle.jit.to_static(f)
            out = g(paddle.to_tensor(np.ones(2, np.float32)))
        assert any("decorator" in str(x.message) for x in w)
        # fallback keeps the decorator's behavior (2*x + 1)
        np.testing.assert_allclose(np.asarray(out.numpy()), [3.0, 3.0])


class TestClosureSemantics:
    def test_late_binding_closure_preserved(self):
        scale = [2.0]

        def f(x):
            if x.sum() > 0:
                y = x * scale[0]
            else:
                y = x * 0.0
            return y

        g = dy2static.convert_function(f)
        x = paddle.to_tensor(np.ones(2, np.float32))
        np.testing.assert_allclose(np.asarray(g(x).numpy()), [2.0, 2.0])
        scale[0] = 5.0  # late rebinding must be visible post-conversion
        np.testing.assert_allclose(np.asarray(g(x).numpy()), [5.0, 5.0])

    def test_zero_arg_super_survives_conversion(self):
        class Base(nn.Layer):
            def forward(self, x):
                return x + 1.0

        class Child(Base):
            def forward(self, x):
                if x.sum() > 0:
                    y = super().forward(x) * 2.0
                else:
                    y = x * 0.0
                return y

        net = Child()
        # conversion itself must succeed (no ConversionError fallback) and
        # the converted function must run zero-arg super() correctly
        conv = dy2static.convert_function(Child.forward)
        assert getattr(conv, "__dy2static__", False)
        out = conv(net, paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(out.numpy()), [4.0, 4.0])
        # and end-to-end through to_static
        paddle.jit.to_static(net)
        out = net(paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(out.numpy()), [4.0, 4.0])


class TestProgramTranslatorToggle:
    def test_enable_false_after_decoration_takes_effect(self):
        import warnings

        pt = paddle.jit.ProgramTranslator.get_instance()

        @paddle.jit.to_static
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x * 3.0
            return y

        xp = paddle.to_tensor(np.ones(2, np.float32))
        np.testing.assert_allclose(np.asarray(f(xp).numpy()), [2.0, 2.0])
        # disabling AFTER decoration must route to the unconverted path:
        # the tensor-dependent `if` then fails under plain tracing, which
        # proves conversion is genuinely bypassed per call
        pt.enable(False)
        try:
            with pytest.raises(Exception):
                f(xp)
        finally:
            pt.enable(True)
        np.testing.assert_allclose(np.asarray(f(xp).numpy()), [2.0, 2.0])
