"""Reference "book" e2e contracts beyond MNIST
(fluid/tests/book/: test_word2vec, test_understand_sentiment,
test_label_semantic_roles): small models must TRAIN — loss drops and the
task is learned — through the public API on synthetic data.  Kept small:
each case trains in seconds on the CPU mesh."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class TestWord2Vec:
    """N-gram LM (book/test_word2vec.py): embeddings + 2-layer MLP over
    concatenated context embeddings, next-word softmax."""

    def test_ngram_lm_learns_deterministic_sequence(self):
        paddle.seed(0)
        V, E, CTX = 20, 16, 4
        # deterministic cyclic corpus: next token fully predictable
        corpus = np.arange(200) % V
        X = np.stack([corpus[i:i + CTX] for i in range(len(corpus) - CTX)])
        Y = corpus[CTX:].copy()

        class NGram(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(V, E)
                self.fc1 = nn.Linear(CTX * E, 64)
                self.fc2 = nn.Linear(64, V)

            def forward(self, ids):
                e = self.emb(ids)
                e = paddle.reshape(e, [ids.shape[0], -1])
                return self.fc2(paddle.nn.functional.relu(self.fc1(e)))

        net = NGram()
        opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                    parameters=net.parameters())
        first = last = None
        for epoch in range(12):
            logits = net(paddle.to_tensor(X.astype(np.int64)))
            loss = paddle.nn.functional.cross_entropy(
                logits, paddle.to_tensor(Y.astype(np.int64))).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            v = float(np.asarray(loss.numpy()))
            first = v if first is None else first
            last = v
        assert last < first * 0.2, (first, last)
        pred = np.asarray(net(paddle.to_tensor(
            X[:50].astype(np.int64))).numpy()).argmax(-1)
        assert (pred == Y[:50]).mean() > 0.9


class TestUnderstandSentiment:
    """LSTM classifier (book/test_understand_sentiment.py) on the
    synthetic Imdb dataset (token distributions differ per class)."""

    def test_lstm_classifier_learns(self):
        from paddle_tpu.text import Imdb

        paddle.seed(0)
        ds = Imdb(mode="train", seq_len=32, vocab_size=200)
        X = np.stack([ds[i][0] for i in range(256)]).astype(np.int64)
        Y = np.array([ds[i][1] for i in range(256)]).astype(np.int64)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(200, 32)
                self.lstm = nn.LSTM(32, 32)
                self.fc = nn.Linear(32, 2)

            def forward(self, ids):
                e = self.emb(ids)
                out, (h, c) = self.lstm(e)
                return self.fc(h[-1] if h.ndim == 3 else h)

        net = Net()
        opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                    parameters=net.parameters())
        for step in range(15):
            logits = net(paddle.to_tensor(X))
            loss = paddle.nn.functional.cross_entropy(
                logits, paddle.to_tensor(Y)).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        acc = (np.asarray(net(paddle.to_tensor(X)).numpy()).argmax(-1)
               == Y).mean()
        assert acc > 0.85, acc


class TestLabelSemanticRoles:
    """CRF sequence tagging (book/test_label_semantic_roles.py):
    emissions from a Linear + linear_chain_crf loss + ViterbiDecoder."""

    def test_crf_tagger_learns(self):
        from paddle_tpu.text import ViterbiDecoder, linear_chain_crf

        paddle.seed(0)
        rs = np.random.RandomState(0)
        K, D, T, N = 3, 8, 6, 160
        Wt = rs.randn(D, K).astype(np.float32)
        feats = rs.randn(N, T, D).astype(np.float32)
        tags = (feats @ Wt).argmax(-1)
        lens = np.full((N,), T, np.int64)

        lin = nn.Linear(D, K)
        trans = paddle.to_tensor(np.zeros((K + 2, K), np.float32))
        trans.stop_gradient = False
        opt = paddle.optimizer.Adam(
            learning_rate=0.1, parameters=list(lin.parameters()) + [trans])
        for step in range(60):
            em = lin(paddle.to_tensor(feats))
            ll = linear_chain_crf(em, trans, paddle.to_tensor(tags),
                                  paddle.to_tensor(lens))
            loss = -(ll.mean())
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(np.asarray(loss.numpy())) < 2.0
        vit = ViterbiDecoder(
            paddle.to_tensor(np.asarray(trans.numpy())[2:]),
            include_bos_eos_tag=False)
        _, paths = vit(lin(paddle.to_tensor(feats[:16])),
                       paddle.to_tensor(lens[:16]))
        acc = (np.asarray(paths.numpy()) == tags[:16]).mean()
        assert acc > 0.9, acc
