"""End-to-end MNIST LeNet slice (SURVEY.md §7 step 3 milestone; the
tests/book/test_recognize_digits.py analog): dataloader -> jitted train step
-> loss decreases -> checkpoint round-trips."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def test_sampler_follows_paddle_seed():
    """Shuffle order must come from the framework RNG chain: paddle.seed
    reproduces it, successive epochs differ, and the GLOBAL np.random
    state is irrelevant (a polluted global state once made this module's
    loss-decrease test order-dependent across the suite)."""
    from paddle_tpu.io import RandomSampler

    class DS:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return i

    paddle.seed(7)
    e1, e2 = list(RandomSampler(DS())), list(RandomSampler(DS()))
    paddle.seed(7)
    r1 = list(RandomSampler(DS()))
    assert e1 != e2          # epochs reshuffle
    assert e1 == r1          # reseeding reproduces
    np.random.seed(123)
    paddle.seed(7)
    assert list(RandomSampler(DS())) == e1  # global state is irrelevant


def test_lenet_loss_decreases_dygraph():
    """Pure dygraph loop: tape autograd + eager optimizer."""
    paddle.seed(1)
    net = LeNet()
    opt = paddle.optimizer.Adam(0.002, parameters=net.parameters())
    loss_fn = nn.CrossEntropyLoss()
    ds = MNIST(mode="train")
    loader = DataLoader(ds, batch_size=64, shuffle=True)
    losses = []
    for i, (img, label) in enumerate(loader):
        out = net(img)
        loss = loss_fn(out, label)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
        if i >= 14:
            break
    assert np.mean(losses[-3:]) < np.mean(losses[:3]) - 0.2, losses


def test_lenet_model_fit_and_eval():
    """hapi Model path: jitted train step."""
    paddle.seed(2)
    net = LeNet()
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(0.002, parameters=net.parameters()),
                  nn.CrossEntropyLoss(), paddle.metric.Accuracy())
    train = MNIST(mode="train")
    test = MNIST(mode="test")
    h = model.fit(train, batch_size=64, epochs=2, verbose=0)
    assert h["loss"][-1] < h["loss"][0]
    res = model.evaluate(test, batch_size=64, verbose=0)
    # synthetic data is separable: accuracy must beat chance by a lot
    assert res["acc"] > 0.5, res


def test_checkpoint_roundtrip(tmp_path):
    net = LeNet()
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(0.001, parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    path = str(tmp_path / "ckpt")
    model.save(path)
    w = net.features[0].weight.numpy().copy()
    # perturb then load back
    net.features[0].weight.set_value(np.zeros_like(w))
    model.load(path)
    np.testing.assert_allclose(net.features[0].weight.numpy(), w)


def test_paddle_save_load(tmp_path):
    net = LeNet()
    p = str(tmp_path / "m.pdparams")
    paddle.save(net.state_dict(), p)
    sd = paddle.load(p)
    assert "features.0.weight" in sd
    net.set_state_dict(sd)


def test_jit_to_static_forward():
    net = LeNet()
    net.eval()
    x = paddle.randn([2, 1, 28, 28])
    ref = net(x).numpy()
    sf = paddle.jit.to_static(net.forward)
    out = sf(x)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)
    # params not baked: update a weight, jit output must follow
    net.fc[2].bias.set_value(net.fc[2].bias.numpy() + 1.0)
    out2 = sf(x)
    np.testing.assert_allclose(out2.numpy(), ref + 1.0, rtol=1e-4, atol=1e-4)
