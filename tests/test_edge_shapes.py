"""Degenerate-shape sweep: zero-size tensors, scalars, and broadcast
combinations through the elementwise/reduction/matmul surface must match
numpy (the reference's OpTest grids include 0-d and empty cases;
operator.cc InferShape handles zero dims).  XLA handles these fine —
this pins that none of OUR lowerings (dispatch, dtype promotion, jit
paths) choke on them."""
import numpy as np
import pytest

import paddle_tpu as paddle

_ELEMWISE = [
    ("add", np.add), ("subtract", np.subtract), ("multiply", np.multiply),
    ("maximum", np.maximum), ("minimum", np.minimum),
]
_UNARY = [
    ("abs", np.abs), ("exp", np.exp), ("tanh", np.tanh),
    ("sqrt", lambda a: np.sqrt(np.abs(a) + 1e-9)), ("floor", np.floor),
]
_SHAPES = [(0,), (3,), (1, 1), (2, 0, 4), (2, 3)]
rs = np.random.RandomState(0)


@pytest.mark.parametrize("name,ref", _ELEMWISE)
@pytest.mark.parametrize("shape", _SHAPES)
def test_elemwise_degenerate(name, ref, shape):
    a = rs.randn(*shape).astype(np.float32)
    b = rs.randn(*shape).astype(np.float32)
    got = np.asarray(getattr(paddle, name)(
        paddle.to_tensor(a), paddle.to_tensor(b)).numpy())
    want = (ref(a, b) if name != "sqrt" else ref(a))
    np.testing.assert_allclose(got, want, rtol=1e-6)


@pytest.mark.parametrize("name,ref", _UNARY)
@pytest.mark.parametrize("shape", [(0,), (2, 0, 4), (3, 2)])
def test_unary_degenerate(name, ref, shape):
    a = rs.randn(*shape).astype(np.float32)
    fn = getattr(paddle, name)
    arg = np.abs(a) + 1e-9 if name == "sqrt" else a
    got = np.asarray(fn(paddle.to_tensor(arg)).numpy())
    np.testing.assert_allclose(got, ref(a) if name != "sqrt"
                               else np.sqrt(arg), rtol=1e-6, atol=1e-7)


def test_broadcast_matrix():
    cases = [((3, 1), (1, 4)), ((2, 1, 4), (3, 1)), ((1,), (5, 1)),
             ((2, 3), ())]
    for sa, sb in cases:
        a = np.asarray(rs.randn(*sa), np.float32)  # () gives a 0-d array
        b = np.asarray(rs.randn(*sb), np.float32)
        got = np.asarray(paddle.add(paddle.to_tensor(a),
                                    paddle.to_tensor(b)).numpy())
        np.testing.assert_allclose(got, a + b, rtol=1e-6)


def test_reductions_empty_and_scalar():
    empty = paddle.to_tensor(np.zeros((0, 4), np.float32))
    assert float(paddle.sum(empty)) == 0.0
    s = paddle.sum(empty, axis=0)
    assert tuple(s.shape) == (4,)
    scalar = paddle.to_tensor(np.float32(3.5))
    assert float(paddle.sum(scalar)) == 3.5
    assert float(paddle.max(paddle.to_tensor(
        np.array([2.0, -1.0], np.float32)))) == 2.0
    # mean of empty: NaN like numpy, not a crash
    m = float(paddle.mean(empty))
    assert np.isnan(m)


def test_matmul_zero_dims():
    a = rs.randn(0, 4).astype(np.float32)
    b = rs.randn(4, 5).astype(np.float32)
    got = np.asarray(paddle.matmul(paddle.to_tensor(a),
                                   paddle.to_tensor(b)).numpy())
    assert got.shape == (0, 5)
    c = rs.randn(3, 0).astype(np.float32)
    d = rs.randn(0, 2).astype(np.float32)
    got2 = np.asarray(paddle.matmul(paddle.to_tensor(c),
                                    paddle.to_tensor(d)).numpy())
    np.testing.assert_allclose(got2, np.zeros((3, 2), np.float32))


def test_concat_split_empty():
    a = rs.randn(0, 3).astype(np.float32)
    b = rs.randn(2, 3).astype(np.float32)
    got = np.asarray(paddle.concat(
        [paddle.to_tensor(a), paddle.to_tensor(b)]).numpy())
    np.testing.assert_allclose(got, np.concatenate([a, b]))
    parts = paddle.split(paddle.to_tensor(b), 2, axis=0)
    assert len(parts) == 2 and tuple(parts[0].shape) == (1, 3)


def test_grad_through_zero_size():
    """Backward through a zero-size branch must produce zero-size grads,
    not crash (autograd tape over jax.vjp)."""
    x = paddle.to_tensor(rs.randn(0, 4).astype(np.float32),
                         stop_gradient=False)
    y = paddle.to_tensor(rs.randn(3, 4).astype(np.float32),
                         stop_gradient=False)
    loss = paddle.sum(x * 2.0) + paddle.sum(y * y)
    loss.backward()
    assert tuple(x.grad.shape) == (0, 4)
    np.testing.assert_allclose(np.asarray(y.grad.numpy()),
                               2 * np.asarray(y.numpy()), rtol=1e-6)
