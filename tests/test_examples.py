"""Every script in examples/ must run end-to-end and print its OK
marker — the examples are living documentation (MIGRATION.md's script
generations) and double as user-style integration drives."""
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = sorted(f[:-3] for f in os.listdir(os.path.join(_REPO, "examples"))
                   if f.endswith(".py"))


@pytest.mark.parametrize("name", _EXAMPLES)
def test_example_runs(name):
    from conftest import cpu_subprocess_env

    env = cpu_subprocess_env()
    p = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", name + ".py")],
        env=env, capture_output=True, text=True, timeout=420)
    assert p.returncode == 0, p.stderr[-2000:]
    assert f"OK {name}" in p.stdout, p.stdout[-500:]
