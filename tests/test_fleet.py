"""Fleet facade tests — strategy knobs, role maker, meta-opt composition.

Mirrors the reference's fleet meta-optimizer tests (SURVEY.md §4: build a
program with a strategy and assert the expected transforms were applied —
here we assert on the compiled HLO / jaxpr instead of inserted ops).
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed.fleet as fleet_mod
from paddle_tpu.distributed.fleet import DistributedStrategy, fleet
from paddle_tpu.distributed.fleet.base.role_maker import (
    PaddleCloudRoleMaker, Role, UserDefinedRoleMaker)
from paddle_tpu.distributed.mesh import build_mesh, mesh_guard


def _toy_loss_params(d=16):
    rs = np.random.RandomState(0)
    params = {"w": jnp.asarray(rs.randn(d, d) * 0.1, jnp.float32),
              "b": jnp.zeros((d,), jnp.float32)}

    def loss_fn(p, batch):
        x, y = batch
        pred = jnp.tanh(x @ p["w"] + p["b"])
        return jnp.mean((pred - y) ** 2)

    x = jnp.asarray(rs.randn(32, d), jnp.float32)
    y = jnp.asarray(rs.randn(32, d), jnp.float32)
    return loss_fn, params, (x, y)


class TestDistributedStrategy:
    def test_defaults_and_flags(self):
        s = DistributedStrategy()
        assert s.amp is False and s.sharding is False
        assert s.sync_nccl_allreduce is True
        s.amp = True
        s.gradient_merge = True
        assert s.amp and s.gradient_merge

    def test_flag_type_checked(self):
        s = DistributedStrategy()
        with pytest.raises(TypeError):
            s.amp = "yes"

    def test_configs_update_and_unknown_key(self):
        s = DistributedStrategy()
        s.amp_configs = {"init_loss_scaling": 1024.0, "use_bf16": False}
        assert s.amp_configs["init_loss_scaling"] == 1024.0
        with pytest.raises(ValueError):
            s.gradient_merge_configs = {"bogus": 1}

    def test_proto_knob_names_present(self):
        """Every top-level flag from distributed_strategy.proto:120-163."""
        s = DistributedStrategy()
        for knob in ["amp", "recompute", "localsgd", "dgc", "gradient_merge",
                     "lars", "lamb", "pipeline", "elastic", "auto", "a_sync",
                     "sync_nccl_allreduce", "nccl_comm_num",
                     "use_hierarchical_allreduce",
                     "hierarchical_allreduce_inter_nranks", "sync_batch_norm",
                     "fuse_all_reduce_ops", "fuse_grad_size_in_MB",
                     "fp16_allreduce", "sharding", "adaptive_localsgd"]:
            getattr(s, knob)
        for cfg in ["amp_configs", "recompute_configs", "sharding_configs",
                    "pipeline_configs", "gradient_merge_configs",
                    "localsgd_configs", "dgc_configs", "lars_configs",
                    "lamb_configs", "a_sync_configs", "build_strategy",
                    "execution_strategy", "hybrid_configs"]:
            assert isinstance(getattr(s, cfg), dict)

    def test_save_load_roundtrip(self, tmp_path):
        s = DistributedStrategy()
        s.sharding = True
        s.sharding_configs = {"stage": 2}
        path = str(tmp_path / "strategy.prototxt")
        s.save_to_prototxt(path)
        s2 = DistributedStrategy()
        s2.load_from_prototxt(path)
        assert s2.sharding is True
        assert s2.sharding_configs["stage"] == 2


class TestRoleMaker:
    def test_paddlecloud_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
        monkeypatch.setenv("PADDLE_TRAINER_ENDPOINTS",
                           "127.0.0.1:6170,127.0.0.1:6171,"
                           "127.0.0.1:6172,127.0.0.1:6173")
        rm = PaddleCloudRoleMaker(is_collective=True)
        assert rm._worker_index() == 2
        assert rm._worker_num() == 4
        assert not rm._is_first_worker()
        assert rm._is_worker()
        assert len(rm._get_trainer_endpoints()) == 4

    def test_user_defined(self):
        rm = UserDefinedRoleMaker(
            current_id=0, role=Role.WORKER,
            worker_endpoints=["127.0.0.1:1", "127.0.0.1:2"], worker_num=2)
        assert rm._is_first_worker()
        assert rm._worker_num() == 2


class TestFleetFacade:
    def test_init_and_queries(self):
        fleet.init(is_collective=True)
        assert fleet.is_worker()
        assert fleet.worker_num() >= 1
        assert fleet.worker_index() >= 0
        fleet.barrier_worker()

    def test_distributed_model_wraps(self):
        fleet.init(is_collective=True)
        net = paddle.nn.Linear(4, 4)
        dm = fleet_mod.distributed_model(net)
        out = dm(paddle.randn([2, 4]))
        assert tuple(out.shape) == (2, 4)

    def test_run_server_raises(self):
        with pytest.raises(NotImplementedError):
            fleet.run_server()


class TestStrategyComposition:
    def _build(self, strategy, mesh=None, batch_spec=None):
        loss_fn, params, batch = _toy_loss_params()
        fleet.init(is_collective=True)
        opt = fleet.distributed_optimizer(
            paddle.optimizer.AdamW(learning_rate=1e-3), strategy)
        step, init_state, shardings = opt.build_train_step(
            loss_fn, params, mesh=mesh, batch_spec=batch_spec, donate=False)
        return opt, step, init_state, params, batch

    def test_plain_dp_allreduces_grads(self):
        """Batch sharded over dp + replicated params → XLA must insert an
        all-reduce for the grads (the multi_devices_graph_pass contract)."""
        mesh = build_mesh({"dp": 8})
        strategy = DistributedStrategy()
        opt, step, init_state, params, batch = self._build(
            strategy, mesh=mesh, batch_spec=P("dp"))
        hlo = step.lower(params, init_state(params), batch) \
                  .compile().as_text()
        assert "all-reduce" in hlo
        p2, s2, loss = step(params, init_state(params), batch)
        assert np.isfinite(float(loss))

    def test_sharding_stage2_reduce_scatters(self):
        mesh = build_mesh({"dp": 8})
        strategy = DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs = {"stage": 2}
        opt, step, init_state, params, batch = self._build(
            strategy, mesh=mesh, batch_spec=P("dp"))
        assert "sharding" in opt.applied_meta_list
        hlo = step.lower(params, init_state(params), batch) \
                  .compile().as_text()
        assert ("reduce-scatter" in hlo) or ("all-reduce" in hlo)
        p2, s2, loss = step(params, init_state(params), batch)
        assert np.isfinite(float(loss))

    def test_gradient_merge_scans_microbatches(self):
        strategy = DistributedStrategy()
        strategy.gradient_merge = True
        strategy.gradient_merge_configs = {"k_steps": 4, "avg": True}
        opt, step, init_state, params, batch = self._build(strategy)
        assert "gradient_merge" in opt.applied_meta_list
        state = init_state(params)
        p2, s2, loss = step(params, state, batch)
        assert int(s2["step"]) == 1
        # merged update == update on the same data with k=1 mean-equivalent
        assert np.isfinite(float(loss))

    def test_gradient_merge_matches_full_batch_grads(self):
        """mean-of-microbatch-grads == full-batch grad for a mean loss."""
        loss_fn, params, batch = _toy_loss_params()
        fleet.init(is_collective=True)
        strategy = DistributedStrategy()
        strategy.gradient_merge = True
        strategy.gradient_merge_configs = {"k_steps": 4}
        opt = fleet.distributed_optimizer(
            paddle.optimizer.SGD(learning_rate=0.1), strategy)
        step, init_state, _ = opt.build_train_step(loss_fn, params,
                                                   donate=False)
        p_merged, _, _ = step(params, init_state(params), batch)

        sgd = paddle.optimizer.SGD(learning_rate=0.1)
        ref_loss, ref_g = jax.value_and_grad(loss_fn)(params, batch)
        p_ref, _ = sgd.apply_pytree(params, ref_g, sgd.init_pytree(params),
                                    step=1)
        np.testing.assert_allclose(np.asarray(p_merged["w"]),
                                   np.asarray(p_ref["w"]), rtol=2e-5,
                                   atol=2e-6)

    def test_amp_bf16_casts_compute(self):
        """Autocast affects the paddle op layer (matmul is white-listed) —
        the traced program must contain bf16 compute."""
        rs = np.random.RandomState(0)
        params = {"w": jnp.asarray(rs.randn(16, 16) * 0.1, jnp.float32)}

        def loss_fn(p, batch):
            x, y = batch
            pred = paddle.matmul(paddle.Tensor(x), paddle.Tensor(p["w"]))
            return jnp.mean((pred.value.astype(jnp.float32) - y) ** 2)

        x = jnp.asarray(rs.randn(32, 16), jnp.float32)
        batch = (x, x)
        fleet.init(is_collective=True)
        strategy = DistributedStrategy()
        strategy.amp = True
        opt = fleet.distributed_optimizer(
            paddle.optimizer.AdamW(learning_rate=1e-3), strategy)
        step, init_state, _ = opt.build_train_step(loss_fn, params,
                                                   donate=False)
        assert "amp" in opt.applied_meta_list
        jaxpr = str(jax.make_jaxpr(
            lambda p, b: opt._last_ctx.loss_fn(p, b))(params, batch))
        assert "bf16" in jaxpr
        _, _, loss = step(params, init_state(params), batch)
        assert np.isfinite(float(loss))

    def test_amp_fp16_dynamic_loss_scaling(self):
        strategy = DistributedStrategy()
        strategy.amp = True
        strategy.amp_configs = {"use_bf16": False,
                                "init_loss_scaling": 1024.0}
        opt, step, init_state, params, batch = self._build(strategy)
        state = init_state(params)
        assert float(state["loss_scale"]) == 1024.0
        p2, s2, loss = step(params, state, batch)
        assert np.isfinite(float(loss))
        assert int(s2["step"]) == 1  # finite grads → update applied
        assert int(s2["good_steps"]) == 1

    def test_fp16_loss_scaling_skips_on_inf(self):
        """Poisoned batch → found_inf → params kept, scale decreased after
        decr_every_n_nan_or_inf bad steps (update_loss_scaling semantics)."""
        loss_fn, params, (x, y) = _toy_loss_params()
        fleet.init(is_collective=True)
        strategy = DistributedStrategy()
        strategy.amp = True
        strategy.amp_configs = {"use_bf16": False,
                                "init_loss_scaling": 1024.0,
                                "decr_every_n_nan_or_inf": 1}
        opt = fleet.distributed_optimizer(
            paddle.optimizer.SGD(learning_rate=0.1), strategy)
        step, init_state, _ = opt.build_train_step(loss_fn, params,
                                                   donate=False)
        bad = (x.at[0, 0].set(jnp.inf), y)
        state = init_state(params)
        p2, s2, loss = step(params, state, bad)
        np.testing.assert_array_equal(np.asarray(p2["w"]),
                                      np.asarray(params["w"]))
        assert int(s2["step"]) == 0
        assert float(s2["loss_scale"]) < 1024.0

    def test_recompute_applies(self):
        strategy = DistributedStrategy()
        strategy.recompute = True
        opt, step, init_state, params, batch = self._build(strategy)
        assert "recompute" in opt.applied_meta_list
        _, _, loss = step(params, init_state(params), batch)
        assert np.isfinite(float(loss))

    def test_lamb_swaps_optimizer(self):
        from paddle_tpu.optimizer import Lamb
        strategy = DistributedStrategy()
        strategy.lamb = True
        opt, step, init_state, params, batch = self._build(strategy)
        assert isinstance(opt._last_ctx.optimizer, Lamb)
        _, _, loss = step(params, init_state(params), batch)
        assert np.isfinite(float(loss))

    def test_pipeline_flag_sets_accumulation(self):
        strategy = DistributedStrategy()
        strategy.pipeline = True
        strategy.pipeline_configs = {"accumulate_steps": 4}
        opt, step, init_state, params, batch = self._build(strategy)
        assert opt._last_ctx.k_steps == 4

    def test_composed_amp_recompute_merge_sharding(self):
        """The reference's canonical chain AMP→Recompute→GradientMerge→
        Sharding (strategy_compiler.py:168 ordering)."""
        mesh = build_mesh({"dp": 8})
        strategy = DistributedStrategy()
        strategy.amp = True
        strategy.recompute = True
        strategy.gradient_merge = True
        strategy.gradient_merge_configs = {"k_steps": 2}
        strategy.sharding = True
        opt, step, init_state, params, batch = self._build(
            strategy, mesh=mesh, batch_spec=P("dp"))
        assert opt.applied_meta_list == ["amp", "recompute",
                                        "gradient_merge", "sharding"]
        p2, s2, loss = step(params, init_state(params), batch)
        assert np.isfinite(float(loss))

    def test_localsgd_warns_noop(self):
        strategy = DistributedStrategy()
        strategy.localsgd = True
        with pytest.warns(UserWarning, match="localsgd"):
            self._build(strategy)


class TestFleetUtils:
    def test_localfs(self, tmp_path):
        from paddle_tpu.distributed.fleet.utils import LocalFS
        fs = LocalFS()
        d = str(tmp_path / "x")
        fs.mkdirs(d)
        assert fs.is_exist(d) and fs.is_dir(d)
        f = os.path.join(d, "a.txt")
        fs.touch(f)
        assert fs.is_file(f)
        dirs, files = fs.ls_dir(d)
        assert files == ["a.txt"]
        fs.delete(d)
        assert not fs.is_exist(d)

    def test_metrics_single_process(self):
        from paddle_tpu.distributed.fleet import metrics
        assert float(np.sum(metrics.sum(np.array([1.0, 2.0])))) == 3.0
        assert metrics.acc(np.array(8.0), np.array(10.0)) == pytest.approx(0.8)
        # perfect separation → auc 1.0
        pos = np.zeros(10); pos[9] = 5
        neg = np.zeros(10); neg[0] = 5
        assert metrics.auc(pos, neg) == pytest.approx(1.0)
        assert metrics.rmse(np.array(4.0), np.array(1.0)) == pytest.approx(2.0)
