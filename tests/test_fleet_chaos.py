"""Fault-tolerant serving fleet (serving/router.py failover paths,
serving/fleet.py supervisor, utils/chaos.py replica dials).

The tentpole contract: the generation fleet loses a replica under load
with ZERO failed requests.  Pieces under test here:

  * mid-stream failover — a replica's SSE stream severed after K tokens
    is resumed on a survivor with the emitted prefix appended to the
    prompt and ``resume_pos`` fast-forwarding the per-request PRNG
    chain; the client's reassembled stream is BITWISE the uninterrupted
    run (greedy) / deterministically identical (seeded sampling).
  * elastic membership — the router subscribed to the pod coordinator
    evicts a dead rank on the EPOCH DELTA (no probe-timeout wait) and
    re-admits a revived rank without restart.
  * probe flap damping — a dead replica needs `healthy_after`
    CONSECUTIVE probe successes before taking traffic again.
  * retry budget — against a fully-failing fleet, total upstream
    dispatches are pinned at requests + budget; exhaustion degrades to
    fast 503, never a retry storm.
  * hedged dispatch — a slow replica's non-streaming request is
    duplicated after the hedge delay and the fast replica's answer
    wins, exactly once.
  * client retries — idempotent non-streaming requests retry on 5xx /
    connection failure with Retry-After honored on 429, and report
    attempts.

The multi-process drill (real SIGKILL of a replica subprocess, real
supervisor respawn) is marked `slow`; tools/serve_smoke.sh runs the
same scenario end-to-end from the shell.

Run via tools/serve_smoke.sh (`pytest -m fleetchaos`); fast cases also
ride tier-1.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.serving.client import ServingClient, ServingHTTPError
from paddle_tpu.serving.generation import GenerationEngine
from paddle_tpu.serving.router import FleetRouter, RetryBudget

pytestmark = pytest.mark.fleetchaos

PROMPT = list(range(3, 11))          # 8 tokens
MAX_NEW = 12
SAMPLE_KW = dict(do_sample=True, temperature=0.8, top_k=5)


def _gpt(seed=0):
    paddle.seed(seed)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=211, hidden_size=48, num_layers=2, num_heads=4,
        max_position_embeddings=64, dropout=0.0, attn_dropout=0.0))
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _gpt(0)


@pytest.fixture(scope="module")
def eng(model):
    """Oracle engine: buckets must cover RESUMED prompts (prompt +
    emitted prefix), not just originals."""
    e = GenerationEngine(model, max_slots=2, max_seq_len=64,
                         prompt_buckets=(8, 16, 32), page_size=4).start()
    yield e
    e.stop()


@pytest.fixture(scope="module")
def real_server(model):
    from paddle_tpu.serving.server import ServingServer

    e = GenerationEngine(model, max_slots=2, max_seq_len=64,
                         prompt_buckets=(8, 16, 32), page_size=4)
    srv = ServingServer(None, gen_engine=e, port=0,
                        install_signal_handlers=False).start()
    yield srv
    srv.shutdown()


# ---------------------------------------------------------------------------
# stub replicas
# ---------------------------------------------------------------------------
class _FlakyGen(BaseHTTPRequestHandler):
    """A replica that computes the TRUE stream (via the oracle engine,
    honoring resume_pos) but severs the connection after
    `server.cut_after` token events on its first request — the
    in-process stand-in for a SIGKILL mid-stream."""

    protocol_version = "HTTP/1.1"

    def do_GET(self):  # noqa: N802
        body = b'{"status": "ok"}'
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _chunk(self, obj):
        data = b"data: " + json.dumps(obj).encode() + b"\n\n"
        self.wfile.write(b"%X\r\n" % len(data) + data + b"\r\n")
        self.wfile.flush()

    def do_POST(self):  # noqa: N802
        raw = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        p = json.loads(raw)
        h = self.server.eng.submit(
            p["prompt"], p.get("max_new_tokens", 32),
            do_sample=p.get("do_sample", False),
            temperature=p.get("temperature", 1.0),
            top_k=p.get("top_k", 0), seed=p.get("seed", 0),
            resume_pos=p.get("resume_pos", 0))
        tokens = h.result(60)
        cut = None
        if not self.server.cut_done:
            self.server.cut_done = True
            cut = self.server.cut_after
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Connection", "close")
        self.end_headers()
        self.close_connection = True
        for i, t in enumerate(tokens):
            if cut is not None and i >= cut:
                return  # no done event, no terminal chunk: severed
            self._chunk({"token": int(t)})
        self._chunk({"done": True, "tokens": len(tokens)})
        self.wfile.write(b"0\r\n\r\n")

    def log_message(self, *a):  # noqa: D102
        pass


def _start_stub(handler_cls, **attrs):
    stub = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    for k, v in attrs.items():
        setattr(stub, k, v)
    threading.Thread(target=stub.serve_forever, daemon=True).start()
    return stub, f"http://127.0.0.1:{stub.server_address[1]}"


class _FailingGen(BaseHTTPRequestHandler):
    """Healthy /healthz, every POST 500 — a fleet that accepts probes
    but fails every request (the retry-budget exhaustion scenario)."""

    def do_GET(self):  # noqa: N802
        body = b'{"status": "ok"}'
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        with self.server.lock:
            self.server.posts += 1
        body = b'{"error": "internal"}'
        self.send_response(500)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # noqa: D102
        pass


class _SpeedGen(BaseHTTPRequestHandler):
    """Answers /predict after `server.delay_s`, tagging who answered."""

    def do_GET(self):  # noqa: N802
        body = b'{"status": "ok"}'
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        time.sleep(self.server.delay_s)
        body = json.dumps({"who": self.server.tag}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # noqa: D102
        pass


class _FlakyOnce(BaseHTTPRequestHandler):
    """POST fails once (with `server.first_status`), then succeeds —
    the client-retry scenario."""

    def do_POST(self):  # noqa: N802
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        with self.server.lock:
            self.server.posts += 1
            first = self.server.posts == 1
        if first:
            body = b'{"error": "transient"}'
            self.send_response(self.server.first_status)
            if self.server.first_status == 429:
                self.send_header("Retry-After", "0")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        body = json.dumps({"outputs": [[1.0]],
                           "dtypes": ["float32"]}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # noqa: D102
        pass


# ---------------------------------------------------------------------------
# engine-level resume determinism
# ---------------------------------------------------------------------------
class TestResumeDeterminism:
    def test_greedy_resume_bitwise(self, eng):
        """Splitting a greedy run at any point and resuming with the
        emitted prefix appended reproduces the suffix bitwise."""
        full = eng.submit(PROMPT, MAX_NEW, seed=3).result(60)
        assert len(full) == MAX_NEW
        for cut in (1, 5, MAX_NEW - 1):
            head = full[:cut]
            tail = eng.submit(PROMPT + head, MAX_NEW - cut, seed=3,
                              resume_pos=cut).result(60)
            assert head + tail == full, f"cut={cut}"

    def test_sampled_resume_same_chain(self, eng):
        """The per-request PRNG chain is positional: resume_pos=K
        fast-forwards K splits, so the resumed sampled stream continues
        the SAME chain the uninterrupted run walked."""
        full = eng.submit(PROMPT, MAX_NEW, seed=7,
                          **SAMPLE_KW).result(60)
        for cut in (2, 6):
            head = full[:cut]
            tail = eng.submit(PROMPT + head, MAX_NEW - cut, seed=7,
                              resume_pos=cut, **SAMPLE_KW).result(60)
            assert head + tail == full, f"cut={cut}"

    def test_resume_pos_zero_is_identity(self, eng):
        """resume_pos=0 is exactly the historical behavior."""
        a = eng.submit(PROMPT, 6, seed=11, **SAMPLE_KW).result(60)
        b = eng.submit(PROMPT, 6, seed=11, resume_pos=0,
                       **SAMPLE_KW).result(60)
        assert a == b

    def test_resume_pos_validation(self, eng):
        with pytest.raises(ValueError):
            eng.submit(PROMPT, 4, resume_pos=-1)


# ---------------------------------------------------------------------------
# router mid-stream failover
# ---------------------------------------------------------------------------
class TestMidStreamFailover:
    def _run(self, eng, real_server, gen_kw, cut=5):
        stub, stub_url = _start_stub(_FlakyGen, eng=eng, cut_after=cut,
                                     cut_done=False)
        router = FleetRouter([stub_url, real_server.url], port=0,
                             page_size=4, probe_interval_s=0.2,
                             dead_after=2,
                             install_signal_handlers=False).start()
        try:
            c = ServingClient(router.url, timeout=60.0)
            toks, err = [], None
            for evt in c.generate_stream(PROMPT, MAX_NEW, **gen_kw):
                if "token" in evt:
                    toks.append(evt["token"])
                if evt.get("done"):
                    err = evt.get("error")
            snap = router.metrics.snapshot()
            return toks, err, snap
        finally:
            router.shutdown()
            stub.shutdown()

    def test_greedy_stream_resumes_bitwise(self, eng, real_server):
        """r0 dies after 5 relayed tokens; the client stream must be
        the full uninterrupted greedy output, zero failed requests."""
        oracle = eng.submit(PROMPT, MAX_NEW, seed=3).result(60)
        toks, err, snap = self._run(eng, real_server, dict(seed=3))
        assert err is None
        assert toks == oracle
        assert snap["failovers"].get("mid_stream") == 1
        assert snap["requests_failed"] == 0
        assert snap["availability_ratio"] == 1.0

    def test_sampled_stream_resumes_deterministically(self, eng,
                                                      real_server):
        """Same contract under seeded sampling: the survivor continues
        the request's PRNG chain, not a fresh one."""
        oracle = eng.submit(PROMPT, MAX_NEW, seed=7,
                            **SAMPLE_KW).result(60)
        toks, err, snap = self._run(eng, real_server,
                                    dict(seed=7, **SAMPLE_KW))
        assert err is None
        assert toks == oracle
        assert snap["failovers"].get("mid_stream") == 1

    def test_done_event_carries_total_count(self, eng, real_server):
        """The rewritten done event reports tokens across BOTH legs."""
        stub, stub_url = _start_stub(_FlakyGen, eng=eng, cut_after=4,
                                     cut_done=False)
        router = FleetRouter([stub_url, real_server.url], port=0,
                             page_size=4, probe_interval_s=0.2,
                             dead_after=2,
                             install_signal_handlers=False).start()
        try:
            c = ServingClient(router.url, timeout=60.0)
            done = None
            n = 0
            for evt in c.generate_stream(PROMPT, MAX_NEW, seed=3):
                if "token" in evt:
                    n += 1
                if evt.get("done"):
                    done = evt
            assert done is not None and done["tokens"] == n == MAX_NEW
        finally:
            router.shutdown()
            stub.shutdown()


# ---------------------------------------------------------------------------
# elastic membership
# ---------------------------------------------------------------------------
class TestMembership:
    def test_epoch_eviction_and_readmission(self, real_server):
        """Coordinator-declared death evicts on the epoch delta (ahead
        of any probe evidence — probes still see the server healthy);
        mark_live re-admits without a router restart."""
        from paddle_tpu.distributed.podcoord import (PodClient,
                                                     PodCoordinator)

        coord = PodCoordinator(2, heartbeat_timeout_s=60.0).start()
        router = None
        try:
            kv = PodClient(coord.address, rank=-1)
            kv.kv_set("serving/replica/0/url",
                      real_server.url.encode())
            kv.kv_set("serving/replica/1/url",
                      real_server.url.encode())
            router = FleetRouter([], coord=coord.address, port=0,
                                 page_size=4, probe_interval_s=30.0,
                                 dead_after=2, membership_poll_s=0.05,
                                 install_signal_handlers=False).start()
            assert sorted(r.name for r in router.replicas) == ["r0",
                                                               "r1"]
            assert all(r.alive for r in router.replicas)
            coord.mark_dead(0, "exit")
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline \
                    and router.replicas[0].alive:
                time.sleep(0.02)
            assert not router.replicas[0].alive, \
                "epoch-delta eviction did not land"
            assert router.metrics.snapshot()["membership_epoch"] >= 1
            # requests keep flowing on the survivor
            c = ServingClient(router.url)
            assert len(c.generate(PROMPT, 3)["tokens"]) == 3
            # supervisor-style revive: same rank re-admitted live
            coord.mark_live(0)
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline \
                    and not router.replicas[0].alive:
                time.sleep(0.02)
            assert router.replicas[0].alive, \
                "membership re-admission did not land"
        finally:
            if router is not None:
                router.shutdown()
            coord.close()


# ---------------------------------------------------------------------------
# probe flap damping
# ---------------------------------------------------------------------------
class _ToggleHealth(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802
        code = 200 if self.server.healthy else 500
        body = b"{}"
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # noqa: D102
        pass


class TestFlapDamping:
    def test_dead_needs_consecutive_successes(self):
        """2 failed probes mark a replica dead; re-admission takes
        `healthy_after`=3 CONSECUTIVE successes — an interleaved
        failure resets the count."""
        stub, url = _start_stub(_ToggleHealth, healthy=False)
        router = FleetRouter([url], dead_after=2, healthy_after=3,
                             install_signal_handlers=False)
        rep = router.replicas[0]
        try:
            for _ in range(2):
                router._probe_one(rep)
            assert not rep.alive
            stub.healthy = True
            router._probe_one(rep)
            assert not rep.alive and rep.succs == 1
            router._probe_one(rep)
            assert not rep.alive and rep.succs == 2
            # one flap resets the streak
            stub.healthy = False
            router._probe_one(rep)
            assert not rep.alive and rep.succs == 0
            stub.healthy = True
            for _ in range(3):
                assert not rep.alive
                router._probe_one(rep)
            assert rep.alive, "3 consecutive successes must re-admit"
        finally:
            stub.shutdown()

    def test_probe_loop_staggers(self):
        """The probe loop spaces per-replica probes at interval/N —
        one replica at a time, never the whole fleet as a herd."""
        router = FleetRouter(["http://127.0.0.1:1", "http://127.0.0.1:2"],
                             probe_interval_s=0.2,
                             install_signal_handlers=False)
        times = []
        router._probe_one = \
            lambda rep: times.append((time.monotonic(), rep.name))
        t = threading.Thread(target=router._probe_loop, daemon=True)
        t.start()
        time.sleep(0.55)
        router._stop_probe.set()
        t.join(2.0)
        assert len(times) >= 4
        assert [n for _, n in times[:4]] == ["r0", "r1", "r0", "r1"]
        gaps = [b[0] - a[0] for a, b in zip(times, times[1:])]
        assert all(g >= 0.05 for g in gaps), \
            f"probes fired back-to-back: {gaps}"


# ---------------------------------------------------------------------------
# retry budget + circuit breaking
# ---------------------------------------------------------------------------
class TestRetryBudget:
    def test_bucket_math(self):
        b = RetryBudget(ratio=0.5, min_budget=2.0)
        assert b.withdraw() and b.withdraw()
        assert not b.withdraw(), "floor budget is 2 retries"
        for _ in range(4):
            b.deposit()
        assert b.withdraw() and b.withdraw()
        assert not b.withdraw()

    def test_exhaustion_pins_dispatches(self):
        """Fully-failing fleet, M requests: total upstream dispatches
        are pinned at M + budget_min — the budget converts a retry
        storm into fast 503s."""
        lock = threading.Lock()
        stubs = []
        urls = []
        for _ in range(2):
            s, u = _start_stub(_FailingGen, lock=lock, posts=0)
            stubs.append(s)
            urls.append(u)
        router = FleetRouter(urls, port=0, page_size=4,
                             probe_interval_s=30.0, dead_after=10,
                             retry_budget_min=2.0,
                             retry_budget_ratio=0.0,
                             breaker_threshold=100,
                             install_signal_handlers=False).start()
        try:
            c = ServingClient(router.url)
            n_req = 6
            statuses = []
            for _ in range(n_req):
                with pytest.raises(ServingHTTPError) as ei:
                    c.generate(PROMPT, 3)
                statuses.append(ei.value.status)
            total = sum(s.posts for s in stubs)
            assert total <= n_req + 2, \
                f"dispatches {total} exceed requests+budget"
            assert total >= n_req
            snap = router.metrics.snapshot()
            assert snap["retry_budget_exhausted"] >= 1
            assert snap["requests_failed"] == n_req
            assert snap["availability_ratio"] == 0.0
            assert all(s in (502, 503) for s in statuses), statuses
        finally:
            router.shutdown()
            for s in stubs:
                s.shutdown()

    def test_breaker_stops_dispatch(self):
        """After `breaker_threshold` consecutive request failures the
        replica stops receiving dispatches entirely (fast 503, zero
        upstream traffic) until the cooldown expires."""
        lock = threading.Lock()
        stub, url = _start_stub(_FailingGen, lock=lock, posts=0)
        router = FleetRouter([url], port=0, page_size=4,
                             probe_interval_s=30.0, dead_after=10,
                             retry_budget_min=100.0,
                             breaker_threshold=2,
                             breaker_cooldown_s=60.0,
                             install_signal_handlers=False).start()
        try:
            c = ServingClient(router.url)
            for _ in range(4):
                with pytest.raises(ServingHTTPError):
                    c.generate(PROMPT, 3)
            # threshold=2: dispatches stop once the breaker opens
            assert stub.posts == 2, stub.posts
        finally:
            router.shutdown()
            stub.shutdown()


# ---------------------------------------------------------------------------
# hedged dispatch
# ---------------------------------------------------------------------------
class TestHedging:
    def test_slow_replica_hedge_wins_exactly_once(self):
        """r0 sits on the request past the hedge delay; the duplicate
        lands on r1 and its answer wins — once, with both the hedge
        counter and the won/lost split recording it."""
        slow, slow_url = _start_stub(_SpeedGen, delay_s=1.2, tag="slow")
        fast, fast_url = _start_stub(_SpeedGen, delay_s=0.0, tag="fast")
        router = FleetRouter([slow_url, fast_url], port=0, page_size=4,
                             probe_interval_s=30.0,
                             hedge_floor_ms=100.0,
                             install_signal_handlers=False).start()
        try:
            req = urllib.request.Request(
                router.url + "/predict", data=b"{}",
                headers={"Content-Type": "application/json"},
                method="POST")
            t0 = time.monotonic()
            with urllib.request.urlopen(req, timeout=10.0) as r:
                out = json.loads(r.read())
            assert out["who"] == "fast"
            assert time.monotonic() - t0 < 1.0, \
                "hedge should beat the slow replica"
            time.sleep(1.3)  # let the abandoned primary finish
            snap = router.metrics.snapshot()
            assert snap["hedges"].get("won") == 1
            assert snap["hedges"].get("lost", 0) == 0
            assert snap["failovers"].get("hedge") == 1
        finally:
            router.shutdown()
            slow.shutdown()
            fast.shutdown()


# ---------------------------------------------------------------------------
# deadline admission
# ---------------------------------------------------------------------------
class TestDeadlineAdmission:
    def test_hopeless_deadline_rejected_504(self):
        """A request whose deadline is already smaller than the
        estimated queue wait is rejected at the router — the replica
        never sees the doomed dispatch."""
        stub, url = _start_stub(_SpeedGen, delay_s=0.0, tag="x")
        router = FleetRouter([url], port=0, probe_interval_s=30.0,
                             replica_slots=1,
                             install_signal_handlers=False).start()
        try:
            router._observe_latency(0.5)      # ~500ms per request
            router.replicas[0].inflight = 4   # 4 waves queued ahead
            c = ServingClient(router.url)
            with pytest.raises(ServingHTTPError) as ei:
                c.generate(PROMPT, 3, deadline_ms=10)
            assert ei.value.status == 504
            assert router.metrics.snapshot()["deadline_rejected"] == 1
        finally:
            router.replicas[0].inflight = 0   # let the drain finish
            router.shutdown()
            stub.shutdown()

    def test_no_estimate_admits_everything(self):
        """With no latency history the estimate is 0 — the router never
        rejects on a model it does not have yet."""
        router = FleetRouter(["http://127.0.0.1:1"],
                             install_signal_handlers=False)
        assert router._est_wait_ms(router.replicas[0]) == 0.0


# ---------------------------------------------------------------------------
# client retries
# ---------------------------------------------------------------------------
class TestClientRetries:
    def _predict(self, url, retries=2):
        c = ServingClient(url, retries=retries, retry_backoff_s=0.01)
        out = c.predict([np.zeros(1, np.float32)])
        return c, out

    def test_retries_5xx_and_reports_attempts(self):
        stub, url = _start_stub(_FlakyOnce, lock=threading.Lock(),
                                posts=0, first_status=500)
        try:
            c, out = self._predict(url)
            assert out[0].tolist() == [1.0]
            assert c.last_attempts == 2
        finally:
            stub.shutdown()

    def test_honors_retry_after_on_429(self):
        stub, url = _start_stub(_FlakyOnce, lock=threading.Lock(),
                                posts=0, first_status=429)
        try:
            c, out = self._predict(url)
            assert out[0].tolist() == [1.0]
            assert c.last_attempts == 2
        finally:
            stub.shutdown()

    def test_default_is_no_retry(self):
        stub, url = _start_stub(_FlakyOnce, lock=threading.Lock(),
                                posts=0, first_status=500)
        try:
            with pytest.raises(ServingHTTPError) as ei:
                self._predict(url, retries=0)
            assert ei.value.status == 500
            assert stub.posts == 1
        finally:
            stub.shutdown()

    def test_connection_refused_retries_then_raises(self):
        # unroutable port: every attempt fails; retries=2 -> 3 attempts
        c = ServingClient("http://127.0.0.1:1", retries=2,
                          retry_backoff_s=0.01, timeout=0.5)
        with pytest.raises(OSError):
            c._request("/predict", {"inputs": []})
        assert c.last_attempts == 3


# ---------------------------------------------------------------------------
# chaos dials
# ---------------------------------------------------------------------------
class TestChaosDials:
    def test_replica_dials_parse_from_env(self, monkeypatch):
        from paddle_tpu.utils import chaos

        monkeypatch.setenv("PADDLE_CHAOS_REPLICA_KILL", "1@3")
        monkeypatch.setenv("PADDLE_CHAOS_REPLICA_SLOW", "0@2:0.5")
        monkeypatch.setenv("PADDLE_CHAOS_REPLICA_PARTITION", "2@4")
        cfg = chaos.ChaosConfig.from_env()
        assert cfg.replica_kill == (1, 3)
        assert cfg.replica_slow == (0, 2, 0.5)
        assert cfg.replica_partition == (2, 4)
        assert not cfg.is_noop()

    def test_partition_dial_fires_hook_once(self, monkeypatch):
        from paddle_tpu.utils import chaos

        monkeypatch.setenv("PADDLE_POD_RANK", "0")
        fired = []
        chaos.register_partition_hook(lambda: fired.append(1))
        with chaos.inject(replica_partition=(0, 2)):
            chaos.on_step(0)
            chaos.on_step(1)
            assert not fired
            chaos.on_step(2)
            chaos.on_step(3)
        assert fired == [1], "partition is one-shot"

    def test_replica_slow_is_persistent(self, monkeypatch):
        from paddle_tpu.utils import chaos

        monkeypatch.setenv("PADDLE_POD_RANK", "0")
        with chaos.inject(replica_slow=(0, 1, 0.01)):
            t0 = time.monotonic()
            chaos.on_step(0)
            fast = time.monotonic() - t0
            t0 = time.monotonic()
            chaos.on_step(1)
            chaos.on_step(2)
            slow = time.monotonic() - t0
            assert chaos.active_config().replica_slow is not None, \
                "slow dial must persist (not one-shot)"
        assert slow >= 0.02 > fast


# ---------------------------------------------------------------------------
# the real drill: SIGKILL a replica subprocess mid-stream
# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestSigkillDrill:
    def test_mid_stream_sigkill_resumes_and_respawns(self, tmp_path):
        """End-to-end: supervisor fleet of 2 real replica processes,
        router on the coordinator, a streaming request whose replica is
        SIGKILLed mid-stream.  The stream must complete bitwise equal
        to the undisturbed run, the router must count zero failed
        requests, and the supervisor must respawn the victim."""
        from conftest import cpu_subprocess_env

        from paddle_tpu.serving.fleet import ReplicaSupervisor

        cmd = [sys.executable, "-m", "paddle_tpu.serving.generation",
               "--port", "0", "--slots", "2", "--page-size", "4",
               "--prompt-buckets", "8,16,32", "--max-seq-len", "64",
               "--seed", "0"]
        sup = ReplicaSupervisor(
            cmd, 2, env=cpu_subprocess_env(),
            heartbeat_timeout_s=5.0, respawn_backoff_s=0.2,
            telemetry_dir=str(tmp_path / "telemetry"),
            log_dir=str(tmp_path / "logs")).start()
        router = None
        try:
            assert sup.wait_ready(240), "fleet bring-up timed out"
            router = FleetRouter([], coord=sup.coord.address, port=0,
                                 page_size=4, probe_interval_s=0.3,
                                 dead_after=3, membership_poll_s=0.05,
                                 install_signal_handlers=False).start()
            c = ServingClient(router.url, timeout=120.0)
            oracle = c.generate(PROMPT, MAX_NEW)["tokens"]
            assert len(oracle) == MAX_NEW

            toks, err = [], None
            for evt in c.generate_stream(PROMPT, MAX_NEW):
                if "token" in evt:
                    toks.append(evt["token"])
                    if len(toks) == 3:
                        victim = max(router.replicas,
                                     key=lambda r: r.inflight)
                        rank = int(victim.name[1:])
                        os.kill(sup.procs[rank].pid, signal.SIGKILL)
                if evt.get("done"):
                    err = evt.get("error")
            assert err is None, f"stream failed: {err}"
            assert toks == oracle, "resumed stream is not bitwise equal"
            snap = router.metrics.snapshot()
            assert snap["failovers"].get("mid_stream", 0) >= 1
            assert snap["requests_failed"] == 0
            # the supervisor respawns the victim and the router
            # re-admits it on the membership channel
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline \
                    and not (sup.respawn_count >= 1 and sup.wait_ready(1)):
                time.sleep(0.5)
            assert sup.respawn_count >= 1
            assert sup.wait_ready(60)
            assert sup.downs and sup.downs[0] > 0
            # availability accounting left a replica_lost dump
            dumps = [p for p in
                     os.listdir(tmp_path / "telemetry")
                     if p.startswith("flightrec-")]
            assert dumps, "supervisor left no replica_lost dump"
            doc = json.loads(
                (tmp_path / "telemetry" / dumps[0]).read_text())
            assert doc["reason"] == "replica_lost"
            assert doc["accounting"]["down_s"] > 0
        finally:
            if router is not None:
                router.shutdown()
            sup.shutdown()
