"""Fleet-built distribution strategies, verified the hard way.

Round-1 VERDICT items 2/3/5: the knobs must change the compiled program,
not just set fields.  Mirrors the reference meta-optimizer tests that
assert on inserted ops (SURVEY.md §4) — here we assert on compiled HLO
(collective-permute / reduce-scatter / all-gather / bf16 all-reduce), on
physical shard shapes, and on numerics vs unsharded baselines.
"""
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet import DistributedStrategy, fleet
from paddle_tpu.distributed.mesh import build_mesh, mesh_guard
from paddle_tpu.models import GPTConfig, gpt_hybrid


def _toy(d=16, n=32):
    rs = np.random.RandomState(0)
    params = {"w": jnp.asarray(rs.randn(d, d) * 0.1, jnp.float32),
              "b": jnp.zeros((d,), jnp.float32)}

    def loss_fn(p, batch):
        x, y = batch
        pred = jnp.tanh(x @ p["w"] + p["b"])
        return jnp.mean((pred - y) ** 2)

    x = jnp.asarray(rs.randn(n, d), jnp.float32)
    y = jnp.asarray(rs.randn(n, d), jnp.float32)
    return loss_fn, params, (x, y)


def _build(loss_fn, params, strategy, mesh, opt=None, **kw):
    fleet.init(is_collective=True)
    dopt = fleet.distributed_optimizer(
        opt or paddle.optimizer.AdamW(learning_rate=1e-3), strategy)
    step, init_state, shardings = dopt.build_train_step(
        loss_fn, params, mesh=mesh, donate=False, **kw)
    return dopt, step, init_state, shardings


class TestPipelineThroughFleet:
    """strategy.pipeline + pp_degree routes a PipelineProgram through
    spmd_pipeline — the Fleet entry the reference provides via
    fluid.PipelineOptimizer (optimizer.py:3702)."""

    def _cfg_mesh(self):
        mesh = build_mesh({"dp": 2, "pp": 2, "mp": 2})
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                        num_heads=4, max_position_embeddings=16, dropout=0.0)
        return cfg, mesh

    def test_matches_direct_hybrid_train_step(self):
        cfg, mesh = self._cfg_mesh()
        M, steps = 2, 3
        rs = np.random.RandomState(0)
        ids = jnp.asarray(rs.randint(0, 64, (M * 2 * 2, 16)), jnp.int32)

        # direct path (models/gpt_hybrid.make_train_step)
        params = gpt_hybrid.init_params(cfg, pp=2, seed=0)
        opt_d = paddle.optimizer.AdamW(learning_rate=1e-3, weight_decay=0.01)
        step_d, init_d, (p_sh, s_sh, d_sh) = gpt_hybrid.make_train_step(
            cfg, mesh, opt_d, n_microbatches=M, lr=1e-3)
        pd = jax.device_put(params, p_sh)
        sd = jax.device_put(init_d(pd), s_sh)
        losses_direct = []
        for _ in range(steps):
            pd, sd, loss = step_d(pd, sd, jax.device_put(ids, d_sh))
            losses_direct.append(float(loss))

        # fleet path (strategy.pipeline + PipelineProgram)
        strategy = DistributedStrategy()
        strategy.pipeline = True
        strategy.pipeline_configs = {"accumulate_steps": M, "pp_degree": 2}
        program = gpt_hybrid.pipeline_program(cfg, mesh)
        params_f = gpt_hybrid.init_params(cfg, pp=2, seed=0)
        dopt, step_f, init_f, (pf_sh, sf_sh, bf_sh) = _build(
            program, params_f, strategy, mesh,
            opt=paddle.optimizer.AdamW(learning_rate=1e-3,
                                       weight_decay=0.01))
        assert "pipeline" in dopt.applied_meta_list
        pf = jax.device_put(params_f, pf_sh)
        sf = init_f(pf)
        losses_fleet = []
        for _ in range(steps):
            pf, sf, loss = step_f(pf, sf, ids)
            losses_fleet.append(float(loss))

        np.testing.assert_allclose(losses_fleet, losses_direct,
                                   rtol=1e-5, atol=1e-6)

    def test_hlo_contains_collective_permute(self):
        cfg, mesh = self._cfg_mesh()
        strategy = DistributedStrategy()
        strategy.pipeline = True
        strategy.pipeline_configs = {"accumulate_steps": 2, "pp_degree": 2}
        program = gpt_hybrid.pipeline_program(cfg, mesh)
        params = gpt_hybrid.init_params(cfg, pp=2, seed=0)
        dopt, step, init_state, (p_sh, _, _) = _build(
            program, params, strategy, mesh)
        params = jax.device_put(params, p_sh)
        ids = jnp.zeros((2 * 2 * 2, 16), jnp.int32)
        hlo = step.lower(params, init_state(params), ids).compile().as_text()
        assert "collective-permute" in hlo  # ppermute stage hops
        # per-stage weights are physically sharded over pp
        wqkv_sh = p_sh["blocks"]["wqkv"]
        assert "pp" in str(wqkv_sh.spec)

    def test_pp_degree_without_program_raises(self):
        loss_fn, params, batch = _toy()
        mesh = build_mesh({"dp": 4, "pp": 2})
        strategy = DistributedStrategy()
        strategy.pipeline = True
        strategy.pipeline_configs = {"accumulate_steps": 2, "pp_degree": 2}
        with pytest.raises(ValueError, match="PipelineProgram"):
            _build(loss_fn, params, strategy, mesh)

    def test_1f1b_schedule_mode_matches_gpipe_loss(self):
        """schedule_mode='1F1B' routes through the interleaved pipeline
        (round-3 next-step #9); with the gpt_hybrid per-device stage
        stack it degenerates to v=1 — same numerics as F-then-B (the
        chunked-speedup case is covered by TestInterleavedPipeline in
        test_parallel_transforms.py)."""
        cfg, mesh = self._cfg_mesh()
        M = 2
        ids = jnp.zeros((2 * M * 2, 16), jnp.int32)
        losses = {}
        for mode in ("F-then-B", "1F1B"):
            strategy = DistributedStrategy()
            strategy.pipeline = True
            strategy.pipeline_configs = {"accumulate_steps": M,
                                         "pp_degree": 2,
                                         "schedule_mode": mode}
            program = gpt_hybrid.pipeline_program(cfg, mesh)
            params = gpt_hybrid.init_params(cfg, pp=2, seed=0)
            dopt, step, init_state, (p_sh, _, _) = _build(
                program, params, strategy, mesh)
            params = jax.device_put(params, p_sh)
            _, _, loss = step(params, init_state(params), ids)
            losses[mode] = float(loss)
        assert np.isfinite(losses["1F1B"])
        np.testing.assert_allclose(losses["1F1B"], losses["F-then-B"],
                                   rtol=1e-5)

    def test_1f1b_virtual_chunks_match_gpipe_loss(self):
        """virtual_pipeline_degree=2 on num_layers=4/pp=2 (Lp=2, v=2,
        one block per chunk): the interleaved virtual-stage schedule
        computes the same loss as F-then-B with a strictly smaller
        bubble (pipeline_schedule_ticks), and its HLO still rides
        collective-permute."""
        cfg, mesh = self._cfg_mesh()
        M = 2
        ids = jnp.zeros((2 * M * 2, 16), jnp.int32)
        losses = {}
        for mode, vdeg in (("F-then-B", None), ("1F1B", 2)):
            strategy = DistributedStrategy()
            strategy.pipeline = True
            pcfg = {"accumulate_steps": M, "pp_degree": 2,
                    "schedule_mode": mode}
            if vdeg:
                pcfg["virtual_pipeline_degree"] = vdeg
            strategy.pipeline_configs = pcfg
            program = gpt_hybrid.pipeline_program(cfg, mesh)
            params = gpt_hybrid.init_params(cfg, pp=2, seed=0)
            dopt, step, init_state, (p_sh, _, _) = _build(
                program, params, strategy, mesh)
            params = jax.device_put(params, p_sh)
            state = init_state(params)
            if vdeg:
                compiled = step.lower(params, state, ids).compile()
                assert "collective-permute" in compiled.as_text()
                _, _, loss = compiled(params, state, ids)  # one compile
            else:
                _, _, loss = step(params, state, ids)
            losses[mode] = float(loss)
        np.testing.assert_allclose(losses["1F1B"], losses["F-then-B"],
                                   rtol=1e-4)


class TestTensorParallelThroughFleet:
    """Parameter.dist_spec annotations must reach the built step (round-1
    VERDICT #3: they previously never did)."""

    def _tp_model_loss(self, mesh, d=16):
        from paddle_tpu.distributed.meta_parallel import (
            ColumnParallelLinear, RowParallelLinear, dist_specs)
        from paddle_tpu.nn.layer_base import functional_call, state_pytrees
        import paddle_tpu.nn as nn

        with mesh_guard(mesh):
            paddle.seed(0)

            class Net(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.col = ColumnParallelLinear(d, 4 * d,
                                                    gather_output=False)
                    self.row = RowParallelLinear(4 * d, d,
                                                 input_is_parallel=True)

                def forward(self, x):
                    return self.row(paddle.nn.functional.relu(self.col(x)))

            net = Net()
            params, buffers = state_pytrees(net)

        def loss_fn(p, batch):
            x, y = batch
            out, _ = functional_call(net, p, (paddle.Tensor(x),),
                                     buffers=buffers)
            return jnp.mean((out.value - y) ** 2)

        return net, loss_fn, params, dist_specs(net)

    def test_specs_shard_weights_and_hlo_allreduces(self):
        mesh = build_mesh({"dp": 2, "mp": 4})
        net, loss_fn, params, specs = self._tp_model_loss(mesh)
        assert any(s is not None and "mp" in str(s)
                   for s in specs.values())
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(8, 16), jnp.float32)
        batch = (x, x)
        strategy = DistributedStrategy()
        strategy.tensor_parallel = True
        strategy.tensor_parallel_configs = {"tensor_parallel_degree": 4}
        with mesh_guard(mesh):
            dopt, step, init_state, (p_sh, s_sh, _) = _build(
                loss_fn, params, strategy, mesh, param_specs=specs)
            assert "tensor_parallel" in dopt.applied_meta_list
            col_key = next(k for k in params if "col" in k and "weight" in k)
            assert "mp" in str(p_sh[col_key].spec)
            # opt moments inherit the TP placement
            assert "mp" in str(s_sh["opt"][col_key]["moment1"].spec)
            sharded = jax.device_put(params, p_sh)
            hlo = step.lower(sharded, init_state(sharded), batch) \
                      .compile().as_text()
            assert "all-reduce" in hlo
            # physical shard of the column weight is 1/4 on the out dim
            p2, s2, loss = step(sharded, init_state(sharded), batch)
            w = p2[col_key]
            assert w.addressable_shards[0].data.shape == (16, 16)
            assert np.isfinite(float(loss))

    def test_tp_numerics_match_single_device(self):
        mesh = build_mesh({"dp": 2, "mp": 4})
        net, loss_fn, params, specs = self._tp_model_loss(mesh)
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.randn(8, 16), jnp.float32)
        batch = (x, x)
        strategy = DistributedStrategy()
        strategy.tensor_parallel = True
        with mesh_guard(mesh):
            _, step, init_state, (p_sh, _, _) = _build(
                loss_fn, params, strategy, mesh, param_specs=specs,
                opt=paddle.optimizer.SGD(learning_rate=0.1))
            sharded = jax.device_put(params, p_sh)
            p2, _, loss_tp = step(sharded, init_state(sharded), batch)

        # unsharded reference (no mesh: constraints no-op)
        ref_loss, ref_g = jax.value_and_grad(loss_fn)(params, batch)
        np.testing.assert_allclose(float(loss_tp), float(ref_loss),
                                   rtol=1e-5)
        col_key = next(k for k in params if "col" in k and "weight" in k)
        ref_w = params[col_key] - 0.1 * ref_g[col_key]
        np.testing.assert_allclose(np.asarray(p2[col_key]),
                                   np.asarray(ref_w), rtol=1e-4, atol=1e-5)


class TestZeroStages:
    def test_stage2_reduce_scatter_in_hlo(self):
        loss_fn, params, batch = _toy()
        mesh = build_mesh({"dp": 8})
        strategy = DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs = {"stage": 2}
        dopt, step, init_state, (p_sh, s_sh, _) = _build(
            loss_fn, params, strategy, mesh)
        hlo = step.lower(params, init_state(params), batch) \
                  .compile().as_text()
        # stage 2 = grads reduced to their owner shard + new params
        # all-gathered from sharded updates.  TPU/GPU emit a literal
        # reduce-scatter; the CPU simulator lowers the same sharding as
        # all-reduce + local slice, so accept either — but the all-gather
        # (sharded update math) must be there, which plain DP/stage-1
        # compilation does NOT produce.
        assert ("reduce-scatter" in hlo) or ("all-reduce" in hlo)
        assert "all-gather" in hlo
        # params replicated, opt slots sharded
        assert p_sh["w"].spec == P()
        assert "dp" in str(s_sh["opt"]["w"]["moment1"].spec)
        p2, s2, loss = step(params, init_state(params), batch)
        assert np.isfinite(float(loss))
        # physical proof: moment buffers live 1/8-sharded per device
        m = s2["opt"]["w"]["moment1"]
        assert np.prod(m.addressable_shards[0].data.shape) == \
            np.prod(params["w"].shape) // 8

    def test_stage3_all_gather_and_memory_shrink(self):
        loss_fn, params, batch = _toy(d=32)
        mesh = build_mesh({"dp": 8})
        strategy = DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs = {"stage": 3}
        dopt, step, init_state, (p_sh, s_sh, _) = _build(
            loss_fn, params, strategy, mesh)
        assert "dp" in str(p_sh["w"].spec)
        hlo = step.lower(params, init_state(params), batch) \
                  .compile().as_text()
        assert "all-gather" in hlo  # params gathered at use (FSDP)
        sharded = jax.device_put(params, p_sh)
        p2, s2, loss = step(sharded, init_state(sharded), batch)
        assert np.isfinite(float(loss))
        # per-device param buffer is 1/8 of the full tensor
        full = np.prod(params["w"].shape)
        local = np.prod(p2["w"].addressable_shards[0].data.shape)
        assert local == full // 8
        m_local = np.prod(
            s2["opt"]["w"]["moment1"].addressable_shards[0].data.shape)
        assert m_local == full // 8

    def test_stage3_numerics_match_unsharded(self):
        loss_fn, params, batch = _toy(d=32)
        mesh = build_mesh({"dp": 8})
        strategy = DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs = {"stage": 3}
        _, step, init_state, (p_sh, _, _) = _build(
            loss_fn, params, strategy, mesh,
            opt=paddle.optimizer.SGD(learning_rate=0.1))
        sharded = jax.device_put(params, p_sh)
        p2, _, loss = step(sharded, init_state(sharded), batch)
        ref_loss, ref_g = jax.value_and_grad(loss_fn)(params, batch)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(p2["w"]), np.asarray(params["w"] - 0.1 * ref_g["w"]),
            rtol=1e-5, atol=1e-6)


class TestFP16AllReduce:
    def test_wire_dtype_is_bf16(self):
        """The gradient all-reduce operand must actually be bf16 in HLO —
        round-1 Weak #2 showed a cast round-trip XLA folds away."""
        loss_fn, params, batch = _toy()
        mesh = build_mesh({"dp": 8})
        strategy = DistributedStrategy()
        strategy.fp16_allreduce = True
        dopt, step, init_state, _ = _build(loss_fn, params, strategy, mesh)
        assert "fp16_allreduce" in dopt.applied_meta_list
        # assert on the emitted StableHLO (what the program requests): the
        # CPU backend's excess-precision pass promotes bf16 reductions back
        # to f32, but TPU keeps bf16 on the ICI.  Round-1's cast round-trip
        # produced ZERO bf16 all_reduces here — that's the regression
        # this test pins.
        shlo = step.lower(params, init_state(params), batch).as_text()
        blocks = re.findall(
            r'"stablehlo\.all_reduce".*?\n(?:.*?\n)*?.*?->\s*tensor<[^>]*>',
            shlo)
        bf16_ars = [b for b in blocks if b.splitlines()[-1].count("bf16")]
        assert len(bf16_ars) >= 2, \
            f"expected bf16 grad all_reduces, got {len(bf16_ars)}"

    def test_numerics_close_to_fp32_comm(self):
        loss_fn, params, batch = _toy()
        mesh = build_mesh({"dp": 8})
        s_on = DistributedStrategy()
        s_on.fp16_allreduce = True
        _, step_on, init_on, _ = _build(
            loss_fn, params, s_on, mesh,
            opt=paddle.optimizer.SGD(learning_rate=0.1))
        p_on, _, loss_on = step_on(params, init_on(params), batch)

        s_off = DistributedStrategy()
        _, step_off, init_off, _ = _build(
            loss_fn, params, s_off, mesh,
            opt=paddle.optimizer.SGD(learning_rate=0.1))
        p_off, _, loss_off = step_off(params, init_off(params), batch)
        np.testing.assert_allclose(float(loss_on), float(loss_off),
                                   rtol=1e-5)
        # bf16 grad quantization: loose but bounded
        np.testing.assert_allclose(np.asarray(p_on["w"]),
                                   np.asarray(p_off["w"]),
                                   rtol=2e-2, atol=2e-4)

    def test_single_psum_with_gradient_merge(self):
        """fp16_allreduce + gradient_merge must psum ONCE on the merged
        grad, not once per microbatch (one bf16 all_reduce pair in the
        StableHLO, not k)."""
        loss_fn, params, batch = _toy()
        mesh = build_mesh({"dp": 8})
        strategy = DistributedStrategy()
        strategy.fp16_allreduce = True
        strategy.gradient_merge = True
        strategy.gradient_merge_configs = {"k_steps": 4}
        dopt, step, init_state, _ = _build(loss_fn, params, strategy, mesh)
        shlo = step.lower(params, init_state(params), batch).as_text()
        blocks = re.findall(
            r'"stablehlo\.all_reduce".*?\n(?:.*?\n)*?.*?->\s*tensor<[^>]*>',
            shlo)
        bf16_ars = [b for b in blocks if b.splitlines()[-1].count("bf16")]
        # 2 grad tensors (w, b) -> exactly 2 bf16 all_reduces, and none
        # inside the scan body (which would multiply them by k)
        assert len(bf16_ars) == 2, f"got {len(bf16_ars)} bf16 all_reduces"
        _, _, loss = step(params, init_state(params), batch)
        assert np.isfinite(float(loss))

    def test_warns_when_not_applicable(self):
        # widened to dp x mp (round-3 next-step #10): the remaining
        # exclusions are ZeRO stage >= 2 (grads are reduce-scattered to
        # owners, not all-reduced) and pipeline programs
        loss_fn, params, batch = _toy()
        mesh = build_mesh({"dp": 8})
        strategy = DistributedStrategy()
        strategy.fp16_allreduce = True
        strategy.sharding = True
        strategy.sharding_configs = {"stage": 2}
        with pytest.warns(UserWarning, match="fp16_allreduce"):
            _build(loss_fn, params, strategy, mesh)

    def test_dp_mp_mesh_bf16_comms_with_tp_model(self):
        """fp16_allreduce on a dp x mp mesh: bf16 all-reduce rides dp
        while the TP model's mp collectives stay intact, and the loss
        matches the fp32-comms build within bf16 tolerance."""
        from paddle_tpu.distributed.meta_parallel import (
            ColumnParallelLinear, RowParallelLinear, dist_specs)
        from paddle_tpu.nn.layer_base import functional_call, state_pytrees
        import paddle_tpu.nn as nn

        mesh = build_mesh({"dp": 4, "mp": 2})
        with mesh_guard(mesh):
            paddle.seed(0)
            d = 16

            class Net(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.col = ColumnParallelLinear(d, 4 * d,
                                                    gather_output=False)
                    self.row = RowParallelLinear(4 * d, d,
                                                 input_is_parallel=True)

                def forward(self, x):
                    return self.row(self.col(x))

            net = Net()
            params, buffers = state_pytrees(net)
            specs = dist_specs(net)

            def loss_fn(p, batch):
                out, _ = functional_call(
                    net, p, (paddle.Tensor(batch),), buffers=buffers)
                return (out.value ** 2).mean()

            rs = np.random.RandomState(0)
            batch = jnp.asarray(rs.randn(8, d), jnp.float32)
            losses = {}
            for fp16 in (False, True):
                strategy = DistributedStrategy()
                strategy.fp16_allreduce = fp16
                dopt, step, init_state, _ = _build(
                    loss_fn, params, strategy, mesh, param_specs=specs)
                if fp16:
                    assert "fp16_allreduce" in dopt.applied_meta_list
                    hlo = step.lower(params, init_state(params),
                                     batch).compile().as_text()
                    # dp grad combine + mp TP collectives both present
                    assert "all-reduce" in hlo
                    if jax.default_backend() != "cpu":
                        # the bf16 wire is TPU/GPU-only: XLA CPU's
                        # AllReducePromotion CHECK-fails under the
                        # partial-manual lowering (strategy_compiler.py)
                        assert "bf16" in hlo
                _, _, loss = step(params, init_state(params), batch)
                losses[fp16] = float(loss)
            np.testing.assert_allclose(losses[True], losses[False],
                                       rtol=2e-2)
