"""paddle.fluid compatibility namespace: the classic fluid-era script
shapes must run unchanged (reference fluid/tests/book style).  Programs
are deferred expression DAGs under the hood (static/program.py) — no
ProgramDesc — but the workflow below is byte-for-byte the fluid idiom."""
import numpy as np

import paddle_tpu.fluid as fluid
import paddle_tpu as paddle


def test_fluid_recognize_digits_workflow():
    """fluid/tests/book/test_recognize_digits.py shape: data -> fc ->
    softmax -> cross_entropy -> SGD.minimize -> Executor loop."""
    paddle.seed(0)
    main = fluid.Program()
    with fluid.program_guard(main):
        img = fluid.data("img", [None, 64], "float32")
        label = fluid.data("label", [None, 1], "int64")
        h = fluid.layers.fc(img, 32, act="relu")
        pred = fluid.layers.fc(h, 10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        acc = fluid.layers.accuracy(pred, label)
        fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rs = np.random.RandomState(0)
    # linearly separable toy digits: class = argmax of 10 fixed probes
    W = rs.randn(64, 10).astype(np.float32)
    X = rs.randn(256, 64).astype(np.float32)
    Y = (X @ W).argmax(1).astype(np.int64)[:, None]
    first = last = None
    for ep in range(30):
        lv, av = exe.run(main, feed={"img": X, "label": Y},
                         fetch_list=[loss, acc])
        first = float(lv) if first is None else first
        last, acc_v = float(lv), float(av)
    assert last < first * 0.5, (first, last)
    assert acc_v > 0.8, acc_v


def test_fluid_layers_builders():
    paddle.seed(1)
    main = fluid.Program()
    with fluid.program_guard(main):
        x = fluid.data("x", [None, 3, 8, 8], "float32")
        c = fluid.layers.conv2d(x, num_filters=4, filter_size=3,
                                padding=1, act="relu")
        p = fluid.layers.pool2d(c, pool_size=2, pool_stride=2) \
            if hasattr(fluid.layers, "pool2d") else c
        e = fluid.layers.embedding(
            fluid.data("ids", [None, 5], "int64"), size=[20, 6])
        bn = fluid.layers.batch_norm(c)
        ln = fluid.layers.layer_norm(fluid.data("h", [None, 16],
                                                "float32"))
    exe = fluid.Executor()
    feed = {"x": np.random.RandomState(0).randn(2, 3, 8, 8)
            .astype(np.float32),
            "ids": np.random.RandomState(1).randint(0, 20, (2, 5)),
            "h": np.random.RandomState(2).randn(2, 16).astype(np.float32)}
    cv, ev, bnv, lnv = exe.run(main, feed=feed,
                               fetch_list=[c, e, bn, ln])
    assert cv.shape == (2, 4, 8, 8) and (cv >= 0).all()  # relu applied
    assert ev.shape == (2, 5, 6)
    assert bnv.shape == (2, 4, 8, 8)
    np.testing.assert_allclose(lnv.mean(-1), 0.0, atol=1e-5)


def test_fluid_dygraph_and_io(tmp_path):
    with fluid.dygraph.guard():
        net = fluid.dygraph.Linear(4, 2, act="relu")
        x = fluid.dygraph.to_variable(
            np.ones((3, 4), np.float32))
        out = net(x)
        assert list(np.asarray(out.numpy()).shape) == [3, 2]
        assert (np.asarray(out.numpy()) >= 0).all()
        fluid.dygraph.save_dygraph(net.state_dict(), str(tmp_path / "m"))
        sd, opt_sd = fluid.dygraph.load_dygraph(str(tmp_path / "m"))
        assert opt_sd is None and set(sd) == set(net.state_dict())

    # io: reader combinators are the same objects as paddle.reader
    def r():
        yield from range(4)
    assert list(fluid.io.batch(r, 2)()) == [[0, 1], [2, 3]]


def test_fluid_layers_review_fixes():
    """Review findings: ignore_index masking, top-k accuracy, NHWC conv
    bias, is_test batch_norm, compose with ndarray samples."""
    # ignore_index: ignored positions contribute exactly zero
    p = paddle.to_tensor(np.full((3, 4), 0.25, np.float32))
    lab = paddle.to_tensor(np.array([[1], [0], [2]]))
    l_all = np.asarray(fluid.layers.cross_entropy(p, lab).numpy())
    l_ign = np.asarray(fluid.layers.cross_entropy(
        p, lab, ignore_index=0).numpy())
    assert l_ign[1, 0] == 0.0 and l_all[1, 0] > 1.0
    np.testing.assert_allclose(l_ign[[0, 2]], l_all[[0, 2]])

    # top-k accuracy (eager): label in top-2 but not top-1
    logits = paddle.to_tensor(np.array([[0.1, 0.9, 0.5]], np.float32))
    lab2 = paddle.to_tensor(np.array([[2]]))
    assert float(np.asarray(fluid.layers.accuracy(
        logits, lab2, k=1).numpy())) == 0.0
    assert float(np.asarray(fluid.layers.accuracy(
        logits, lab2, k=2).numpy())) == 1.0

    # NHWC conv bias broadcasts over channels, not height
    main = fluid.Program()
    with fluid.program_guard(main):
        x = fluid.data("x", [None, 8, 8, 3], "float32")
        c = fluid.layers.conv2d(x, num_filters=5, filter_size=3,
                                padding=1, data_format="NHWC")
    out, = fluid.Executor().run(
        main, feed={"x": np.zeros((2, 8, 8, 3), np.float32)},
        fetch_list=[c])
    assert out.shape == (2, 8, 8, 5)

    # is_test batch_norm: fixed moving stats, batch-size-1 safe
    main2 = fluid.Program()
    with fluid.program_guard(main2):
        xi = fluid.data("xi", [None, 3, 4, 4], "float32")
        bn = fluid.layers.batch_norm(xi, is_test=True)
    one = np.random.RandomState(0).randn(1, 3, 4, 4).astype(np.float32)
    o1, = fluid.Executor().run(main2, feed={"xi": one}, fetch_list=[bn])
    # moving stats init (mean 0, var 1): output ~= input, NOT collapsed
    np.testing.assert_allclose(o1, one, rtol=1e-2, atol=1e-2)

    # compose with ndarray samples must not crash on membership check
    def ra():
        yield np.ones(3)
        yield np.zeros(3)

    got = list(paddle.reader.compose(ra, ra)())
    assert len(got) == 2 and len(got[0]) == 2  # (arr_a, arr_b) per sample


def test_to_tensor_dtype_based_scaling():
    from paddle_tpu.vision.transforms import functional as TF
    dark = np.ones((4, 4, 3), np.uint8)          # max()==1 but uint8
    out = TF.to_tensor(dark)
    np.testing.assert_allclose(out, 1.0 / 255.0, rtol=1e-6)
    flt = np.ones((4, 4, 3), np.float32)         # float stays unscaled
    np.testing.assert_allclose(TF.to_tensor(flt), 1.0)


def test_require_version_bounds():
    paddle.utils.require_version("1.0")
    paddle.utils.require_version("1.0", "2.0")   # 2.0 allows 2.0.x
    paddle.utils.require_version("2.0.0", "2.0.0")
    import pytest as _pytest
    with _pytest.raises(Exception):
        paddle.utils.require_version("3.0")


def test_fluid_nets_and_unique_name():
    paddle.seed(3)
    main = fluid.Program()
    with fluid.program_guard(main):
        img = fluid.data("img", [None, 1, 28, 28], "float32")
        # the recognize_digits conv net, verbatim from the book script
        c1 = fluid.nets.simple_img_conv_pool(
            img, num_filters=6, filter_size=5, pool_size=2,
            pool_stride=2, act="relu")
        c2 = fluid.nets.simple_img_conv_pool(
            c1, num_filters=16, filter_size=5, pool_size=2,
            pool_stride=2, act="relu")
    exe = fluid.Executor()
    out1, out2 = exe.run(
        main, feed={"img": np.random.RandomState(0)
                    .randn(2, 1, 28, 28).astype(np.float32)},
        fetch_list=[c1, c2])
    assert out1.shape == (2, 6, 12, 12)
    assert out2.shape == (2, 16, 4, 4)

    # glu halves the feature dim
    x = paddle.to_tensor(np.random.RandomState(1)
                         .randn(3, 8).astype(np.float32))
    g = fluid.nets.glu(x)
    assert list(np.asarray(g.numpy()).shape) == [3, 4]

    a = fluid.unique_name.generate("fc")
    b = fluid.unique_name.generate("fc")
    assert a != b and a.startswith("fc")


def test_fluid_softmax_ce_and_version():
    logits = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 6).astype(np.float32))
    lab = paddle.to_tensor(np.array([[1], [2], [3], [0]]))
    out, sm = fluid.layers.softmax_with_cross_entropy(
        logits, lab, return_softmax=True)
    assert np.asarray(out.numpy()).shape[0] == 4
    np.testing.assert_allclose(np.asarray(sm.numpy()).sum(-1), 1.0,
                               rtol=1e-5)
    import paddle_tpu.version as v
    assert v.full_version and v.major == "2"
    v.show()
