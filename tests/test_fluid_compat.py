"""paddle.fluid compatibility namespace: the classic fluid-era script
shapes must run unchanged (reference fluid/tests/book style).  Programs
are deferred expression DAGs under the hood (static/program.py) — no
ProgramDesc — but the workflow below is byte-for-byte the fluid idiom."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu as paddle


def test_fluid_recognize_digits_workflow():
    """fluid/tests/book/test_recognize_digits.py shape: data -> fc ->
    softmax -> cross_entropy -> SGD.minimize -> Executor loop."""
    paddle.seed(0)
    main = fluid.Program()
    with fluid.program_guard(main):
        img = fluid.data("img", [None, 64], "float32")
        label = fluid.data("label", [None, 1], "int64")
        h = fluid.layers.fc(img, 32, act="relu")
        pred = fluid.layers.fc(h, 10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        acc = fluid.layers.accuracy(pred, label)
        fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rs = np.random.RandomState(0)
    # linearly separable toy digits: class = argmax of 10 fixed probes
    W = rs.randn(64, 10).astype(np.float32)
    X = rs.randn(256, 64).astype(np.float32)
    Y = (X @ W).argmax(1).astype(np.int64)[:, None]
    first = last = None
    for ep in range(30):
        lv, av = exe.run(main, feed={"img": X, "label": Y},
                         fetch_list=[loss, acc])
        first = float(lv) if first is None else first
        last, acc_v = float(lv), float(av)
    assert last < first * 0.5, (first, last)
    assert acc_v > 0.8, acc_v


def test_fluid_layers_builders():
    paddle.seed(1)
    main = fluid.Program()
    with fluid.program_guard(main):
        x = fluid.data("x", [None, 3, 8, 8], "float32")
        c = fluid.layers.conv2d(x, num_filters=4, filter_size=3,
                                padding=1, act="relu")
        p = fluid.layers.pool2d(c, pool_size=2, pool_stride=2) \
            if hasattr(fluid.layers, "pool2d") else c
        e = fluid.layers.embedding(
            fluid.data("ids", [None, 5], "int64"), size=[20, 6])
        bn = fluid.layers.batch_norm(c)
        ln = fluid.layers.layer_norm(fluid.data("h", [None, 16],
                                                "float32"))
    exe = fluid.Executor()
    feed = {"x": np.random.RandomState(0).randn(2, 3, 8, 8)
            .astype(np.float32),
            "ids": np.random.RandomState(1).randint(0, 20, (2, 5)),
            "h": np.random.RandomState(2).randn(2, 16).astype(np.float32)}
    cv, ev, bnv, lnv = exe.run(main, feed=feed,
                               fetch_list=[c, e, bn, ln])
    assert cv.shape == (2, 4, 8, 8) and (cv >= 0).all()  # relu applied
    assert ev.shape == (2, 5, 6)
    assert bnv.shape == (2, 4, 8, 8)
    np.testing.assert_allclose(lnv.mean(-1), 0.0, atol=1e-5)


def test_fluid_dygraph_and_io(tmp_path):
    with fluid.dygraph.guard():
        net = fluid.dygraph.Linear(4, 2, act="relu")
        x = fluid.dygraph.to_variable(
            np.ones((3, 4), np.float32))
        out = net(x)
        assert list(np.asarray(out.numpy()).shape) == [3, 2]
        assert (np.asarray(out.numpy()) >= 0).all()
        fluid.dygraph.save_dygraph(net.state_dict(), str(tmp_path / "m"))
        sd, opt_sd = fluid.dygraph.load_dygraph(str(tmp_path / "m"))
        assert opt_sd is None and set(sd) == set(net.state_dict())

    # io: reader combinators are the same objects as paddle.reader
    def r():
        yield from range(4)
    assert list(fluid.io.batch(r, 2)()) == [[0, 1], [2, 3]]


def test_fluid_layers_review_fixes():
    """Review findings: ignore_index masking, top-k accuracy, NHWC conv
    bias, is_test batch_norm, compose with ndarray samples."""
    # ignore_index: ignored positions contribute exactly zero
    p = paddle.to_tensor(np.full((3, 4), 0.25, np.float32))
    lab = paddle.to_tensor(np.array([[1], [0], [2]]))
    l_all = np.asarray(fluid.layers.cross_entropy(p, lab).numpy())
    l_ign = np.asarray(fluid.layers.cross_entropy(
        p, lab, ignore_index=0).numpy())
    assert l_ign[1, 0] == 0.0 and l_all[1, 0] > 1.0
    np.testing.assert_allclose(l_ign[[0, 2]], l_all[[0, 2]])

    # top-k accuracy (eager): label in top-2 but not top-1
    logits = paddle.to_tensor(np.array([[0.1, 0.9, 0.5]], np.float32))
    lab2 = paddle.to_tensor(np.array([[2]]))
    assert float(np.asarray(fluid.layers.accuracy(
        logits, lab2, k=1).numpy())) == 0.0
    assert float(np.asarray(fluid.layers.accuracy(
        logits, lab2, k=2).numpy())) == 1.0

    # NHWC conv bias broadcasts over channels, not height
    main = fluid.Program()
    with fluid.program_guard(main):
        x = fluid.data("x", [None, 8, 8, 3], "float32")
        c = fluid.layers.conv2d(x, num_filters=5, filter_size=3,
                                padding=1, data_format="NHWC")
    out, = fluid.Executor().run(
        main, feed={"x": np.zeros((2, 8, 8, 3), np.float32)},
        fetch_list=[c])
    assert out.shape == (2, 8, 8, 5)

    # is_test batch_norm: fixed moving stats, batch-size-1 safe
    main2 = fluid.Program()
    with fluid.program_guard(main2):
        xi = fluid.data("xi", [None, 3, 4, 4], "float32")
        bn = fluid.layers.batch_norm(xi, is_test=True)
    one = np.random.RandomState(0).randn(1, 3, 4, 4).astype(np.float32)
    o1, = fluid.Executor().run(main2, feed={"xi": one}, fetch_list=[bn])
    # moving stats init (mean 0, var 1): output ~= input, NOT collapsed
    np.testing.assert_allclose(o1, one, rtol=1e-2, atol=1e-2)

    # compose with ndarray samples must not crash on membership check
    def ra():
        yield np.ones(3)
        yield np.zeros(3)

    got = list(paddle.reader.compose(ra, ra)())
    assert len(got) == 2 and len(got[0]) == 2  # (arr_a, arr_b) per sample


def test_to_tensor_dtype_based_scaling():
    from paddle_tpu.vision.transforms import functional as TF
    dark = np.ones((4, 4, 3), np.uint8)          # max()==1 but uint8
    out = TF.to_tensor(dark)
    np.testing.assert_allclose(out, 1.0 / 255.0, rtol=1e-6)
    flt = np.ones((4, 4, 3), np.float32)         # float stays unscaled
    np.testing.assert_allclose(TF.to_tensor(flt), 1.0)


def test_require_version_bounds():
    paddle.utils.require_version("1.0")
    paddle.utils.require_version("1.0", "2.0")   # 2.0 allows 2.0.x
    paddle.utils.require_version("2.0.0", "2.0.0")
    import pytest as _pytest
    with _pytest.raises(Exception):
        paddle.utils.require_version("3.0")


def test_fluid_nets_and_unique_name():
    paddle.seed(3)
    main = fluid.Program()
    with fluid.program_guard(main):
        img = fluid.data("img", [None, 1, 28, 28], "float32")
        # the recognize_digits conv net, verbatim from the book script
        c1 = fluid.nets.simple_img_conv_pool(
            img, num_filters=6, filter_size=5, pool_size=2,
            pool_stride=2, act="relu")
        c2 = fluid.nets.simple_img_conv_pool(
            c1, num_filters=16, filter_size=5, pool_size=2,
            pool_stride=2, act="relu")
    exe = fluid.Executor()
    out1, out2 = exe.run(
        main, feed={"img": np.random.RandomState(0)
                    .randn(2, 1, 28, 28).astype(np.float32)},
        fetch_list=[c1, c2])
    assert out1.shape == (2, 6, 12, 12)
    assert out2.shape == (2, 16, 4, 4)

    # glu halves the feature dim
    x = paddle.to_tensor(np.random.RandomState(1)
                         .randn(3, 8).astype(np.float32))
    g = fluid.nets.glu(x)
    assert list(np.asarray(g.numpy()).shape) == [3, 4]

    a = fluid.unique_name.generate("fc")
    b = fluid.unique_name.generate("fc")
    assert a != b and a.startswith("fc")


def test_static_nn_builders():
    """static.nn re-exports the fluid builder surface (reference
    static/nn/__init__.py); builders create params and compute right."""
    sn = paddle.static.nn
    paddle.seed(6)
    rs = np.random.RandomState(0)

    main = fluid.Program()
    with fluid.program_guard(main):
        x = fluid.data("x", [None, 3, 8, 8], "float32")
        ct = sn.conv2d_transpose(x, num_filters=5, filter_size=3, stride=2)
        gn = sn.group_norm(x, groups=3)
        inorm = sn.instance_norm(x)
        pr = sn.prelu(x, mode="channel")
        a = fluid.data("a", [None, 4], "float32")
        b = fluid.data("b", [None, 6], "float32")
        bt = sn.bilinear_tensor_product(a, b, size=7)
    feed = {"x": rs.randn(2, 3, 8, 8).astype(np.float32),
            "a": rs.randn(2, 4).astype(np.float32),
            "b": rs.randn(2, 6).astype(np.float32)}
    ctv, gnv, inv, prv, btv = fluid.Executor().run(
        main, feed=feed, fetch_list=[ct, gn, inorm, pr, bt])
    assert ctv.shape == (2, 5, 17, 17)
    np.testing.assert_allclose(gnv.mean(), 0.0, atol=1e-4)
    np.testing.assert_allclose(inv.mean(axis=(2, 3)), 0.0, atol=1e-4)
    assert prv.shape == (2, 3, 8, 8)
    assert btv.shape == (2, 7)

    # spectral_norm: result has max singular value ~1 along dim 0
    w = paddle.to_tensor(rs.randn(6, 4).astype(np.float32) * 3)
    wsn = paddle.static.nn.spectral_norm(w, dim=0, power_iters=20)
    s = np.linalg.svd(np.asarray(wsn.numpy()), compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, rtol=1e-3)

    # loud non-goal stubs
    import pytest as _pytest
    with _pytest.raises(NotImplementedError, match="non-goal"):
        sn.nce(None, None, 10)
    with _pytest.raises(NotImplementedError, match="parameter-server"):
        sn.sparse_embedding(None, [10, 4])


def test_crf_decoding_and_multi_box_head():
    """Review fixes: crf_decoding's [c+2, c] layout adapts to the
    square ViterbiDecoder space; multi_box_head captures with symbolic
    batch dims."""
    from paddle_tpu.nn.layer_base import ParamAttr
    from paddle_tpu.nn.initializer import Constant

    paddle.seed(8)
    main = fluid.Program()
    with fluid.program_guard(main):
        em = fluid.data("em", [None, 6, 3], "float32")
        path = paddle.static.nn.crf_decoding(
            em, param_attr=ParamAttr(initializer=Constant(0.0)))
    rs = np.random.RandomState(0)
    E = rs.randn(2, 6, 3).astype(np.float32)
    pv, = fluid.Executor().run(main, feed={"em": E}, fetch_list=[path])
    # zero transitions: best path == per-step argmax of emissions
    np.testing.assert_array_equal(pv, E.argmax(-1))

    main2 = fluid.Program()
    with fluid.program_guard(main2):
        f1 = fluid.data("f1", [None, 8, 4, 4], "float32")
        f2 = fluid.data("f2", [None, 8, 2, 2], "float32")
        img = fluid.data("img", [None, 3, 32, 32], "float32")
        locs, confs, boxes, vars_ = paddle.static.nn.multi_box_head(
            [f1, f2], img, base_size=32, num_classes=5,
            aspect_ratios=[[2.0], [2.0]], min_ratio=20, max_ratio=90)
    feed = {"f1": rs.randn(3, 8, 4, 4).astype(np.float32),
            "f2": rs.randn(3, 8, 2, 2).astype(np.float32),
            "img": rs.randn(3, 3, 32, 32).astype(np.float32)}
    lv, cv, bv, vv = fluid.Executor().run(
        main2, feed=feed, fetch_list=[locs, confs, boxes, vars_])
    assert lv.shape[0] == 3 and lv.shape[2] == 4      # batch 3 survives
    assert cv.shape[:2] == lv.shape[:2] and cv.shape[2] == 5
    assert bv.shape == (lv.shape[1], 4) == vv.shape


def test_conv_transpose_output_size_and_data_norm_stats():
    torch = pytest.importorskip("torch")
    rs = np.random.RandomState(0)
    F2 = paddle.nn.functional
    x = rs.randn(1, 3, 7, 7).astype(np.float32)
    w = rs.randn(3, 4, 3, 3).astype(np.float32)
    # stride 2 base output is 15; request 16 -> output_padding 1
    got = np.asarray(F2.conv2d_transpose(
        paddle.to_tensor(x), paddle.to_tensor(w), stride=2,
        output_size=[16, 16]).numpy())
    ref = torch.nn.functional.conv_transpose2d(
        torch.tensor(x), torch.tensor(w), stride=2,
        output_padding=1).numpy()
    assert got.shape == (1, 4, 16, 16)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    # data_norm uses the GIVEN accumulators, not the batch's moments
    xb = rs.randn(4, 3).astype(np.float32)
    n = np.full((3,), 100.0, np.float32)
    s = np.full((3,), 50.0, np.float32)      # mean 0.5
    sq = np.full((3,), 125.0, np.float32)    # var 1.25 - 0.25 = 1.0
    got = np.asarray(F2.data_norm(
        paddle.to_tensor(xb), batch_size=paddle.to_tensor(n),
        batch_sum=paddle.to_tensor(s),
        batch_square_sum=paddle.to_tensor(sq)).numpy())
    np.testing.assert_allclose(got, (xb - 0.5) / np.sqrt(1.0 + 1e-4),
                               rtol=1e-4)


def test_py_func_host_callback():
    """py_func runs arbitrary host python inside the compiled program
    (jax.pure_callback under jit — the py_func_op.cc analog)."""
    main = fluid.Program()
    with fluid.program_guard(main):
        x = fluid.data("x", [None, 3], "float32")
        spec = fluid.data("o", [None, 3], "float32")  # out spec holder

        def host_fn(arr):
            return np.sort(arr, axis=-1)[:, ::-1].copy()  # numpy-only op

        y = paddle.static.nn.py_func(host_fn, x, spec)
        z = y * 2.0
    # batch size 2 != the spec's placeholder 1: dynamic dims must
    # resolve from the traced input shape
    X = np.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]], np.float32)
    yv, zv = fluid.Executor().run(main, feed={"x": X},
                                  fetch_list=[y, z])
    np.testing.assert_allclose(yv, [[3.0, 2.0, 1.0], [5.0, 4.0, 0.0]])
    np.testing.assert_allclose(zv, 2 * yv)


def test_fluid_softmax_ce_and_version():
    logits = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 6).astype(np.float32))
    lab = paddle.to_tensor(np.array([[1], [2], [3], [0]]))
    out, sm = fluid.layers.softmax_with_cross_entropy(
        logits, lab, return_softmax=True)
    assert np.asarray(out.numpy()).shape[0] == 4
    np.testing.assert_allclose(np.asarray(sm.numpy()).sum(-1), 1.0,
                               rtol=1e-5)
    import paddle_tpu.version as v
    assert v.full_version and v.major == "2"
    v.show()
