"""fluid.contrib tools (reference fluid/contrib/): op frequency over the
captured program DAG, memory estimation, decoupled-weight-decay
optimizer extension."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.contrib import (extend_with_decoupled_weight_decay,
                                      memory_usage, op_freq_statistic)


def _captured_program():
    prog = fluid.Program()
    start = fluid.Program()
    with paddle.static.program_guard(prog, start):
        x = fluid.data("x", [None, 8], "float32")
        y = fluid.data("y", [None, 1], "int64")
        h = fluid.layers.fc(x, 16, act="relu")
        p = fluid.layers.fc(h, 4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(p, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return prog, start, loss


def test_op_freq_statistic_counts_dag_ops():
    prog, start, _ = _captured_program()
    freq = op_freq_statistic(prog)
    assert sum(freq.values()) >= 5
    # two fc layers -> at least two matmul-family ops; softmax/relu appear
    names = " ".join(freq)
    assert any(k in names for k in ("matmul", "fc", "linear")), freq
    assert any(k in names for k in ("relu",)), freq
    # sorted most-frequent first
    counts = list(freq.values())
    assert counts == sorted(counts, reverse=True)


def test_memory_usage_scales_with_batch(capsys):
    prog, start, _ = _captured_program()
    s1, u1 = memory_usage(prog, batch_size=1)
    s64, u64 = memory_usage(prog, batch_size=64)
    def to_bytes(s, u):
        return s * {"B": 1, "KB": 2**10, "MB": 2**20, "GB": 2**30}[u]
    assert to_bytes(s64, u64) > to_bytes(s1, u1)
    assert "memory" in capsys.readouterr().out
    with pytest.raises(ValueError):
        memory_usage(prog, batch_size=0)


def test_extend_with_decoupled_weight_decay():
    paddle.seed(0)
    net = paddle.nn.Linear(4, 4)
    DecayedSGD = extend_with_decoupled_weight_decay(paddle.optimizer.SGD)
    assert "WithDecoupledWeightDecay" in DecayedSGD.__name__
    opt = DecayedSGD(learning_rate=0.1, coeff=0.5,
                     parameters=net.parameters())
    w_before = np.asarray(net.weight.numpy()).copy()
    # zero-grad step isolates the decay term: w <- w * (1 - lr*coeff)
    loss = (net(paddle.to_tensor(np.zeros((2, 4), np.float32)))
            * 0.0).sum()
    loss.backward()
    opt.step()
    np.testing.assert_allclose(np.asarray(net.weight.numpy()),
                               w_before * (1 - 0.1 * 0.5), rtol=1e-5)
    with pytest.raises(TypeError):
        extend_with_decoupled_weight_decay(object)
