"""Wrong-shape/dtype inputs must raise named framework errors before
dispatch, not raw XLA dot/conv messages (the known UX gap the verify
notes called out).  Reference analog: infer_shape PADDLE_ENFORCE
messages (operator.cc InferShape)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_dim_mismatch_named():
    net = nn.Linear(8, 4)
    with pytest.raises(ValueError, match="linear.*in_features"):
        net(paddle.to_tensor(np.zeros((2, 7), np.float32)))


def test_linear_ok_with_unknown_dims_and_valid_input():
    net = nn.Linear(8, 4)
    out = net(paddle.to_tensor(np.zeros((2, 8), np.float32)))
    assert out.shape[-1] == 4


def test_checks_skip_symbolic_dims():
    """A symbolic (non-int) dim must be SKIPPED by the check, never
    raise — shape-polymorphic tracing (jax.export) flows through here."""
    import jax
    from jax import export as jexport

    b, = jexport.symbolic_shape("b")
    net = nn.Linear(8, 4)

    def fwd(x):
        return net(paddle.to_tensor(x)).value

    # trace with a symbolic leading dim; the check reads dim -1 (static
    # 8, passes) and must tolerate the symbolic batch in the same shape
    closed = jax.make_jaxpr(fwd)(
        jax.ShapeDtypeStruct((b, 8), np.float32))
    assert closed.jaxpr.invars


def test_conv_channel_mismatch_named_all_ranks():
    net2 = nn.Conv2D(3, 8, 3)
    with pytest.raises(ValueError, match="conv2d.*channels"):
        net2(paddle.to_tensor(np.zeros((1, 4, 8, 8), np.float32)))
    net1 = nn.Conv1D(3, 8, 3)
    with pytest.raises(ValueError, match="conv1d.*channels"):
        net1(paddle.to_tensor(np.zeros((1, 4, 16), np.float32)))
    net3 = nn.Conv2DTranspose(3, 8, 3)
    with pytest.raises(ValueError, match="conv2d_transpose.*channels"):
        net3(paddle.to_tensor(np.zeros((1, 4, 8, 8), np.float32)))


def test_conv2d_groups_accounted():
    net = nn.Conv2D(8, 8, 3, groups=4, padding=1)  # weight [8, 2, 3, 3]
    out = net(paddle.to_tensor(np.zeros((1, 8, 6, 6), np.float32)))
    assert out.shape[1] == 8
    with pytest.raises(ValueError, match="conv2d"):
        net(paddle.to_tensor(np.zeros((1, 4, 6, 6), np.float32)))


def test_embedding_float_ids_named():
    emb = nn.Embedding(10, 4)
    with pytest.raises(TypeError, match="integer"):
        emb(paddle.to_tensor(np.zeros((2, 3), np.float32)))


def test_layer_norm_shape_mismatch_named():
    ln = nn.LayerNorm(16)
    with pytest.raises(ValueError, match="layer_norm.*normalized_shape"):
        ln(paddle.to_tensor(np.zeros((2, 8), np.float32)))


def test_cross_entropy_float_hard_labels_named():
    logits = paddle.to_tensor(np.zeros((4, 3), np.float32))
    with pytest.raises(TypeError, match="soft_label"):
        F.cross_entropy(logits, paddle.to_tensor(
            np.zeros((4,), np.float32)))
    # soft labels stay allowed
    probs = paddle.to_tensor(np.full((4, 3), 1 / 3, np.float32))
    loss = F.cross_entropy(logits, probs, soft_label=True)
    assert np.isfinite(float(loss))


def test_nan_check_flag_is_trace_safe():
    """FLAGS_check_nan_inf is an eager-only guard: with it enabled, ops
    whose inputs are all closure CONSTANTS inside an outer trace (e.g.
    weight[0] during an export trace) still produce tracers — the guard
    must skip them, not host-sync and crash (regression: leaked flag +
    BERT token-type row made every ONNX bert export fail)."""
    import jax

    net = nn.Linear(4, 2)
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        def fwd(x):
            # constant-only indexing inside the trace, like bert.py:64
            row = net.weight[0]
            return (net(paddle.to_tensor(x)) + row[0]).value

        closed = jax.make_jaxpr(fwd)(np.zeros((2, 4), np.float32))
        assert closed.jaxpr.outvars
        # eager path still guards: a NaN input raises
        with pytest.raises(FloatingPointError):
            paddle.log(paddle.to_tensor(
                np.array([-1.0], np.float32))) * 2.0
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_checks_are_jit_safe():
    """Static-shape checks must not break tracing (to_static path)."""
    net = nn.Sequential(nn.Linear(8, 16), nn.LayerNorm(16))
    fn = paddle.jit.to_static(lambda x: net(x))
    out = fn(paddle.to_tensor(np.zeros((2, 8), np.float32)))
    assert tuple(out.shape) == (2, 16)
