"""GPT autoregressive generation: KV-cache decode (prefill + lax.scan)
must reproduce full-forward greedy decoding exactly, and the sampling
path must be seed-deterministic.  Reference analog: the beam_search /
sampling decode ops (operators/beam_search_op.cc, sampling_id_op.cc) —
here a single static-shape XLA program."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM

rs = np.random.RandomState(0)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=211, hidden_size=48, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    dropout=0.0, attn_dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _full_forward_greedy(m, prompt, n):
    ids = prompt.copy()
    for _ in range(n):
        logits = np.asarray(m(paddle.to_tensor(ids)).numpy())
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    return ids


def test_greedy_matches_full_forward(model):
    prompt = rs.randint(0, 211, (2, 7)).astype(np.int32)
    out = np.asarray(model.generate(paddle.to_tensor(prompt),
                                    max_new_tokens=6).numpy())
    assert out.shape == (2, 13)
    assert (out[:, :7] == prompt).all()
    np.testing.assert_array_equal(out, _full_forward_greedy(model, prompt, 6))


def test_single_token_edge(model):
    prompt = rs.randint(0, 211, (1, 3)).astype(np.int32)
    out = np.asarray(model.generate(paddle.to_tensor(prompt),
                                    max_new_tokens=1).numpy())
    assert out.shape == (1, 4)
    np.testing.assert_array_equal(out, _full_forward_greedy(model, prompt, 1))


def test_sampling_deterministic_per_seed(model):
    prompt = rs.randint(0, 211, (2, 5)).astype(np.int32)
    kw = dict(max_new_tokens=5, do_sample=True, top_k=5, temperature=0.8)
    a = np.asarray(model.generate(paddle.to_tensor(prompt), seed=3,
                                  **kw).numpy())
    b = np.asarray(model.generate(paddle.to_tensor(prompt), seed=3,
                                  **kw).numpy())
    c = np.asarray(model.generate(paddle.to_tensor(prompt), seed=4,
                                  **kw).numpy())
    np.testing.assert_array_equal(a, b)
    assert (a[:, :5] == prompt).all() and a.shape == (2, 10)
    assert not (a == c).all()  # different seed explores a different path
    assert (a < 211).all() and (a >= 0).all()


def test_top_k_restricts_support(model):
    """With top_k=1, sampling degenerates to greedy regardless of seed."""
    prompt = rs.randint(0, 211, (2, 4)).astype(np.int32)
    greedy = np.asarray(model.generate(paddle.to_tensor(prompt),
                                       max_new_tokens=4).numpy())
    k1 = np.asarray(model.generate(paddle.to_tensor(prompt),
                                   max_new_tokens=4, do_sample=True,
                                   top_k=1, seed=9).numpy())
    np.testing.assert_array_equal(greedy, k1)


def test_context_overflow_raises(model):
    prompt = rs.randint(0, 211, (1, 60)).astype(np.int32)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        model.generate(paddle.to_tensor(prompt), max_new_tokens=10)


def test_beam_width_one_is_exactly_greedy(model):
    """A width-1 beam IS greedy decoding: the top-1 joint candidate each
    step is the argmax token of the single live beam — a sound invariant
    (unlike greedy-vs-wide-beam score dominance, which pruning can
    break).  Exercises _beam_traced directly since generate() routes
    num_beams=1 to the cheaper greedy decoder."""
    from paddle_tpu.nn.layer_base import functional_call, state_pytrees

    prompt = rs.randint(0, 211, (2, 5)).astype(np.int32)
    greedy = np.asarray(model.generate(paddle.to_tensor(prompt),
                                       max_new_tokens=5).numpy())
    params, buffers = state_pytrees(model)
    beam1, _ = functional_call(
        model, params, (paddle.to_tensor(prompt), 5, 1, None),
        buffers=buffers, mutable=False, method="_beam_traced")
    np.testing.assert_array_equal(np.asarray(beam1), greedy)


def test_beam_search_well_formed(model):
    prompt = rs.randint(0, 211, (2, 5)).astype(np.int32)
    beam = np.asarray(model.generate(paddle.to_tensor(prompt),
                                     max_new_tokens=5, num_beams=4).numpy())
    assert beam.shape == (2, 10)
    assert (beam[:, :5] == prompt).all()
    assert (beam >= 0).all() and (beam < 211).all()


def test_eos_pads_greedy_path(model):
    """Set eos to the token greedy emits at the first new position: every
    subsequent token must be eos (finished sequences emit only eos)."""
    prompt = rs.randint(0, 211, (2, 6)).astype(np.int32)
    base = np.asarray(model.generate(paddle.to_tensor(prompt),
                                     max_new_tokens=4).numpy())
    eos = int(base[0, 6])  # row 0's first generated token
    out = np.asarray(model.generate(paddle.to_tensor(prompt),
                                    max_new_tokens=4,
                                    eos_token_id=eos).numpy())
    assert (out[0, 6:] == eos).all(), out[0]
    # row 1 (if it never hit eos) must be unaffected by row 0 finishing
    if eos not in base[1, 6:]:
        np.testing.assert_array_equal(out[1], base[1])


def test_beam_and_sampling_exclusive(model):
    prompt = rs.randint(0, 211, (1, 3)).astype(np.int32)
    with pytest.raises(ValueError, match="exclusive"):
        model.generate(paddle.to_tensor(prompt), num_beams=2,
                       do_sample=True)


def test_training_mode_prefill_raises(model):
    model.train()
    try:
        with pytest.raises(RuntimeError, match="eval-only"):
            model.gpt.prefill(
                paddle.to_tensor(rs.randint(0, 211, (1, 4)).astype(np.int32)),
                cache_len=8)
    finally:
        model.eval()


def test_tensor_parallel_generate_on_mesh():
    """The TP decode path (shard_constraint on q/kv caches) must compile
    and run under a dp x mp mesh and agree with the single-device model
    (replicated weights, deterministic greedy)."""
    from paddle_tpu.distributed.mesh import build_mesh, mesh_guard

    paddle.seed(4)
    cfg = dict(vocab_size=101, hidden_size=32, num_layers=2, num_heads=4,
               max_position_embeddings=32, dropout=0.0, attn_dropout=0.0)
    ref = GPTForCausalLM(GPTConfig(**cfg))
    ref.eval()
    paddle.seed(4)  # identical init
    tp = GPTForCausalLM(GPTConfig(**cfg, tensor_parallel=True))
    tp.eval()
    prompt = rs.randint(0, 101, (2, 4)).astype(np.int32)
    want = np.asarray(ref.generate(paddle.to_tensor(prompt),
                                   max_new_tokens=4).numpy())
    mesh = build_mesh({"dp": 2, "mp": 4})
    with mesh_guard(mesh):
        got = np.asarray(tp.generate(paddle.to_tensor(prompt),
                                     max_new_tokens=4).numpy())
    np.testing.assert_array_equal(got, want)


def test_compiled_programs_cached_per_shape(model):
    """Two shapes coexist in the jit cache — alternating calls must not
    evict each other (one compile per shape, then reuse)."""
    getattr(model, "_gen_cache", {}).clear()
    p1 = rs.randint(0, 211, (1, 4)).astype(np.int32)
    p2 = rs.randint(0, 211, (2, 6)).astype(np.int32)
    model.generate(paddle.to_tensor(p1), max_new_tokens=2)
    model.generate(paddle.to_tensor(p2), max_new_tokens=2)
    n = len(model._gen_cache)
    model.generate(paddle.to_tensor(p1), max_new_tokens=2)
    model.generate(paddle.to_tensor(p2), max_new_tokens=2)
    assert len(model._gen_cache) == n == 2
