"""Continuous-batching generation serving (paddle_tpu.serving.generation).

The contract under test: continuous batching must be INVISIBLE to each
request — a prompt admitted into a busy decode batch produces tokens
bitwise-identical to running `model.generate` alone (greedy AND
temperature/top-k sampling, per-request seed); slots are reused without
leaking a prior occupant's KV; preemption (cancel / deadline) frees the
slot mid-decode; drain finishes every in-flight decode; and after
start()'s AOT warmup the steady state NEVER compiles.

Run via tools/serve_smoke.sh (`pytest -m genserve`); also in tier-1.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import (DeadlineExceededError, EngineStoppedError,
                                GenerationEngine)
from paddle_tpu.serving.kv_cache import CacheGeometry
from paddle_tpu.serving.scheduler import SlotScheduler

pytestmark = pytest.mark.genserve

PROMPT_A = list(range(3, 10))          # L=7  -> bucket 8
PROMPT_B = [5, 9, 2]                   # L=3  -> bucket 8
PROMPT_C = list(range(50, 62))         # L=12 -> bucket 16
SAMPLE_KW = dict(do_sample=True, temperature=0.8, top_k=5)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=211, hidden_size=48, num_layers=2, num_heads=4,
        max_position_embeddings=64, dropout=0.0, attn_dropout=0.0))
    m.eval()
    return m


@pytest.fixture(scope="module")
def engine(model):
    eng = GenerationEngine(model, max_slots=3, max_seq_len=40,
                           prompt_buckets="8,16").start()
    yield eng
    eng.stop()


def solo(model, prompt, max_new, **kw):
    """The reference: the model's own single-sequence generate loop."""
    ids = paddle.to_tensor(np.array([prompt], np.int32))
    out = model.generate(ids, max_new_tokens=max_new, **kw)
    return np.array(out.numpy())[0, len(prompt):].tolist()


class TestBitwiseParity:
    def test_greedy_matches_solo(self, model, engine):
        got = engine.generate(PROMPT_A, 12, timeout=60)
        assert got == solo(model, PROMPT_A, 12)

    def test_sampled_matches_solo(self, model, engine):
        got = engine.generate(PROMPT_B, 12, timeout=60, seed=7,
                              **SAMPLE_KW)
        assert got == solo(model, PROMPT_B, 12, seed=7, **SAMPLE_KW)

    def test_seed_determinism_across_slots(self, model, engine):
        """Same prompt+seed in different slots of the same batch → the
        same tokens; the per-slot PRNG chain is the request's alone."""
        hs = [engine.submit(PROMPT_B, 12, seed=7, **SAMPLE_KW)
              for _ in range(3)]
        outs = [h.result(60) for h in hs]
        ref = solo(model, PROMPT_B, 12, seed=7, **SAMPLE_KW)
        assert all(o == ref for o in outs)
        # and a different seed decodes a different (still solo-exact)
        # stream from a neighboring slot
        other = engine.generate(PROMPT_B, 12, timeout=60, seed=8,
                                **SAMPLE_KW)
        assert other == solo(model, PROMPT_B, 12, seed=8, **SAMPLE_KW)

    def test_mid_decode_admission_bitwise(self, model, engine):
        """A request submitted while another is mid-decode is admitted
        at an iteration boundary and decodes the SAME tokens it would
        alone — the acceptance criterion of the subsystem."""
        long_h = engine.submit(PROMPT_C, 25)
        first = long_h.next_token(timeout=60)   # decode provably underway
        mid = engine.submit(PROMPT_B, 12, seed=7, **SAMPLE_KW)
        assert mid.result(60) == solo(model, PROMPT_B, 12, seed=7,
                                      **SAMPLE_KW)
        rest = [first] + list(long_h)
        assert rest == solo(model, PROMPT_C, 25)

    def test_slot_reuse_isolation(self, model, engine):
        """More requests than slots: every retirement hands its slot to
        a new occupant; stale KV from the previous occupant must never
        leak into the next (the attention validity mask exposes only
        positions <= pos, and freed pages are re-written before reuse)."""
        refs = {
            "a": solo(model, PROMPT_A, 12),
            "b": solo(model, PROMPT_B, 12, seed=7, **SAMPLE_KW),
            "c": solo(model, PROMPT_C, 9),
        }
        jobs = [("a", engine.submit(PROMPT_A, 12)),
                ("b", engine.submit(PROMPT_B, 12, seed=7, **SAMPLE_KW)),
                ("c", engine.submit(PROMPT_C, 9))] * 3
        for name, h in jobs:
            assert h.result(60) == refs[name]

    def test_eos_and_single_token(self, model, engine):
        ref = solo(model, PROMPT_A, 12)
        eos = ref[4]
        got = engine.generate(PROMPT_A, 12, timeout=60, eos_token_id=eos)
        assert got == ref[:ref.index(eos) + 1]
        assert engine.generate(PROMPT_A, 1, timeout=60) == ref[:1]


class TestPreemption:
    def test_cancel_mid_decode_frees_slot(self, model, engine):
        h = engine.submit(PROMPT_C, 25)
        assert h.next_token(timeout=60) is not None
        h.cancel()
        t0 = time.monotonic()
        while not h.done and time.monotonic() - t0 < 30:
            time.sleep(0.01)
        assert h.done and h.error is None
        assert 0 < len(h.tokens) < 25
        # the slot is genuinely free: a full batch still fits
        hs = [engine.submit(PROMPT_A, 8) for _ in range(3)]
        ref = solo(model, PROMPT_A, 8)
        assert all(h2.result(60) == ref for h2 in hs)

    def test_deadline_mid_decode_frees_slot(self, model):
        """Deterministic mid-decode expiry: slow each decode iteration
        so the deadline provably lands while the lane is in flight."""
        paddle.seed(0)
        eng = GenerationEngine(model, max_slots=2, max_seq_len=40,
                               prompt_buckets="8").start()
        try:
            fast = eng._decode_exec

            def slow(params, state):
                time.sleep(0.02)
                return fast(params, state)

            eng._decode_exec = slow
            h = eng.submit(PROMPT_A, 30, deadline_ms=120)
            with pytest.raises(DeadlineExceededError):
                h.result(60)
            assert 0 < len(h.tokens) < 30      # it WAS decoding
            eng._decode_exec = fast
            # the preempted lane is free again: full batch still fits
            hs = [eng.submit(PROMPT_A, 6) for _ in range(2)]
            ref = solo(model, PROMPT_A, 6)
            assert all(h2.result(60) == ref for h2 in hs)
        finally:
            eng.stop()

    def test_validation_rejected_at_submit(self, engine):
        with pytest.raises(ValueError):
            engine.submit([], 4)
        with pytest.raises(ValueError):
            engine.submit(list(range(20)), 4)     # > largest bucket
        with pytest.raises(ValueError):
            engine.submit(PROMPT_A, 40)           # L+new > max_seq_len
        with pytest.raises(ValueError):
            engine.submit(PROMPT_A, 0)
        with pytest.raises(ValueError):
            engine.submit(PROMPT_A, 4, do_sample=True, top_k=10_000)


class TestLifecycle:
    def test_drain_finishes_inflight(self, model):
        """The SIGTERM-drain contract (ServingServer.shutdown calls
        exactly this): no new work, every queued + in-flight decode
        completes in full, loop exits."""
        paddle.seed(0)
        eng = GenerationEngine(model, max_slots=2, max_seq_len=40,
                               prompt_buckets="8").start()
        hs = [eng.submit(PROMPT_A, 10) for _ in range(4)]  # 2 queued
        assert eng.drain(timeout=120)
        ref = solo(model, PROMPT_A, 10)
        for h in hs:
            assert h.result(1) == ref        # finished BEFORE drain ret
        with pytest.raises(EngineStoppedError):
            eng.submit(PROMPT_A, 2)
        eng.stop()

    def test_stop_fails_inflight(self, model):
        paddle.seed(0)
        eng = GenerationEngine(model, max_slots=2, max_seq_len=40,
                               prompt_buckets="8").start()
        eng.submit(PROMPT_A, 30)
        eng.stop()
        # every handle resolves (no stranded client threads)


class _CompileTripwire:
    def __enter__(self):
        import jax._src.compiler as C

        self._mod = C
        self._orig = C.compile_or_get_cached

        def hook(*a, **k):
            raise AssertionError("XLA compilation after generation warmup "
                                 "— steady state must never compile")

        C.compile_or_get_cached = hook
        return self

    def __exit__(self, *exc):
        self._mod.compile_or_get_cached = self._orig
        return False


class TestZeroRecompile:
    def test_steady_state_never_compiles(self, model, engine):
        """With jax's compile entry point booby-trapped, admission +
        decode + retirement across both prompt buckets and both sampling
        modes must run purely from the warmed executables."""
        before = engine.compile_count
        with _CompileTripwire():
            hs = [engine.submit(PROMPT_A, 10),
                  engine.submit(PROMPT_B, 10, seed=3, **SAMPLE_KW),
                  engine.submit(PROMPT_C, 10)]
            for h in hs:
                assert len(h.result(120)) == 10
        assert engine.compile_count == before
        assert engine.metrics.snapshot()["compile_count"] == before


class TestMetrics:
    def test_snapshot_and_prometheus(self, engine, model):
        engine.generate(PROMPT_A, 8, timeout=60)
        snap = engine.metrics.snapshot()
        assert snap["decode_tokens_per_sec"] > 0
        assert snap["ttft_p50_ms"] > 0
        assert snap["inter_token_p99_ms"] >= snap["inter_token_p50_ms"] > 0
        assert snap["retired"] >= 1
        text = engine.metrics.prometheus_text()
        for name in ("paddle_genserve_decode_tokens_per_sec",
                     "paddle_genserve_inter_token_p99_ms",
                     "paddle_genserve_slot_occupancy",
                     "paddle_genserve_requests_total",
                     "paddle_genserve_compile_count"):
            assert name in text

    def test_monitor_co_exposure(self, engine):
        """One MonitorServer port serves training AND genserve metrics
        via extra_registries."""
        from paddle_tpu.monitor.server import MonitorServer

        mon = MonitorServer(port=0, extra_registries=(engine.metrics,))
        text = mon.metrics_text()
        assert "paddle_genserve_decode_tokens_per_sec" in text


class TestUnits:
    def test_scheduler(self):
        s = SlotScheduler(2)
        assert s.has_free() and s.free_slots == 2

        class R:
            cancelled = False
            deadline = None

        r1, r2 = R(), R()
        a, b = s.admit(r1), s.admit(r2)
        assert {a, b} == {0, 1} and not s.has_free()
        r2.cancelled = True
        swept = s.sweep()
        assert swept == [(b, r2, "cancelled")]
        assert s.retire(b) is r2
        r3 = R()
        r3.deadline = time.monotonic() - 1
        c = s.admit(r3)
        assert s.sweep() == [(c, r3, "deadline_expired")]

    def test_geometry(self):
        g = CacheGeometry(num_layers=2, max_slots=4, max_seq_len=8,
                          num_heads=2, head_dim=4, vocab_size=100,
                          page_size=4)
        assert g.pages_per_slot == 2
        assert g.num_pages == 8                    # dense-equivalent
        assert g.pool_shape == (2, 8, 4, 2, 4)
        # HBM formula: num_pages * page_bytes, page_bytes = 2(k+v) *
        # layers * page_size * heads * head_dim * itemsize
        assert g.page_bytes() == 2 * 2 * 4 * 2 * 4 * 4
        assert g.kv_bytes() == g.num_pages * g.page_bytes()
        assert g.pages_for(1) == 1 and g.pages_for(4) == 1 \
            and g.pages_for(5) == 2
        small = CacheGeometry(num_layers=2, max_slots=4, max_seq_len=8,
                              num_heads=2, head_dim=4, vocab_size=100,
                              page_size=4, num_pages=3)
        assert small.num_pages == 3                # oversubscribed pool

    def test_scheduler_page_accounting(self):
        """A free slot with an exhausted pool must NOT admit — the
        admit-and-crash (in-graph free-list underflow) failure mode."""

        class R:
            cancelled = False
            deadline = None

        s = SlotScheduler(3, num_pages=10)
        assert s.pages_available == 10
        assert s.can_admit(10) and not s.can_admit(11)
        a = s.admit(R(), n_pages=4)
        b = s.admit(R(), n_pages=4)
        assert s.pages_available == 2
        assert s.has_free() and not s.can_admit(4)   # slot free, pages not
        assert s.can_admit(2)
        s.set_shared_resident(1)                     # prefix-cache pages
        assert s.pages_available == 1 and not s.can_admit(2)
        s.retire(a)
        assert s.pages_available == 5 and s.can_admit(4)
        s.retire(b)
        s.set_shared_resident(0)
        assert s.pages_available == 10


class TestPagedPool:
    """The paged tentpole: a pool smaller than slots * pages_per_slot
    oversubscribes lanes against actual footprint; admission must queue
    (never crash) on pool exhaustion, and retirement must genuinely
    recycle pages."""

    def test_pool_exhaustion_queues_not_crashes(self, model):
        """Deterministic pool exhaustion with lanes free: a 5-page pool
        and 4-page requests serialize — the second request waits for the
        first retirement, then decodes its exact solo stream."""
        paddle.seed(0)
        eng = GenerationEngine(model, max_slots=3, max_seq_len=40,
                               prompt_buckets="8,16", page_size=4,
                               num_pages=5, prefix_cache=False).start()
        try:
            # pages_for(7 + 6) = 4 <= 5: admits alone, not alongside
            hs = [eng.submit(PROMPT_A, 6, seed=i) for i in range(3)]
            ref = solo(model, PROMPT_A, 6)
            assert hs[0].result(60) == ref
            assert hs[1].result(60) == ref and hs[2].result(60) == ref
            snap = eng.metrics.snapshot()
            assert snap["retired"] == 3 and snap.get("errors", 0) == 0
        finally:
            eng.stop()

    def test_request_larger_than_pool_rejected(self, model):
        paddle.seed(0)
        eng = GenerationEngine(model, max_slots=3, max_seq_len=40,
                               prompt_buckets="8,16", page_size=4,
                               num_pages=5, prefix_cache=False).start()
        try:
            with pytest.raises(ValueError, match="KV pages"):
                eng.submit(PROMPT_C, 12)    # pages_for(24) = 6 > 5
            assert eng.metrics.snapshot()["rejected_pages_exhausted"] == 1
        finally:
            eng.stop()

    def test_page_reuse_after_retirement(self, model):
        """Many waves through a minimal pool: every wave's pages are
        recycled from the previous wave's retirement and decode exactly
        the solo stream (stale-KV leak across page reuse would break
        parity)."""
        paddle.seed(0)
        eng = GenerationEngine(model, max_slots=3, max_seq_len=40,
                               prompt_buckets="8,16", page_size=4,
                               num_pages=8, prefix_cache=False).start()
        try:
            refs = {"a": solo(model, PROMPT_A, 6),
                    "b": solo(model, PROMPT_B, 6, seed=7, **SAMPLE_KW)}
            for _ in range(3):
                ha = eng.submit(PROMPT_A, 6)
                hb = eng.submit(PROMPT_B, 6, seed=7, **SAMPLE_KW)
                assert ha.result(60) == refs["a"]
                assert hb.result(60) == refs["b"]
        finally:
            eng.stop()


class TestPrefixCache:
    @pytest.fixture(scope="class")
    def peng(self, model):
        paddle.seed(0)
        eng = GenerationEngine(model, max_slots=3, max_seq_len=40,
                               prompt_buckets="8,16", page_size=4,
                               prefix_cache=True).start()
        yield eng
        eng.stop()

    def test_hit_tokens_identical_to_miss(self, model, peng):
        """The acceptance bar: a prefix-cache hit (suffix-only prefill
        over shared pages) decodes the SAME tokens as the cold miss."""
        ref = solo(model, PROMPT_C, 8, seed=7, **SAMPLE_KW)
        miss = peng.submit(PROMPT_C, 8, seed=7, **SAMPLE_KW).result(60)
        snap0 = peng.metrics.snapshot()
        hit = peng.submit(PROMPT_C, 8, seed=7, **SAMPLE_KW).result(60)
        snap1 = peng.metrics.snapshot()
        assert miss == ref and hit == ref
        assert snap1["prefix_cache_hits"] == snap0["prefix_cache_hits"] + 1
        assert snap1["prefix_cache_hit_ratio"] > 0

    def test_partial_prefix_hit(self, model, peng):
        """A prompt sharing only SOME leading full pages of a cached
        prompt still hits (longest page-aligned prefix) and still
        matches its own solo stream."""
        p = PROMPT_C[:8] + [7, 3, 11, 13]   # shares 2 of C's 2 pages?
        before = peng.metrics.snapshot()["prefix_cache_hits"]
        got = peng.submit(p, 8, seed=2).result(60)
        assert got == solo(model, p, 8, seed=2)
        assert peng.metrics.snapshot()["prefix_cache_hits"] == before + 1

    def test_no_hit_for_short_prompt(self, model, peng):
        """Prompts shorter than one full page + 1 token can never
        share; they run the plain prefill path."""
        before = peng.metrics.snapshot()["prefix_cache_misses"]
        got = peng.submit(PROMPT_B, 6, seed=7, **SAMPLE_KW).result(60)
        assert got == solo(model, PROMPT_B, 6, seed=7, **SAMPLE_KW)
        assert peng.metrics.snapshot()["prefix_cache_misses"] == before + 1

    def test_hit_path_never_compiles(self, peng):
        """The insert_prefix executables are warmed at start(): a hit
        admission mid-steady-state must not trigger XLA."""
        peng.generate(PROMPT_C, 4, timeout=60)      # ensure registered
        before = peng.compile_count
        with _CompileTripwire():
            assert len(peng.generate(PROMPT_C, 6, timeout=120)) == 6
        assert peng.compile_count == before

    def test_prefix_cache_units(self):
        from paddle_tpu.serving.prefix_cache import PrefixCache

        pc = PrefixCache(page_size=4)
        assert pc.shareable_pages(4) == 0       # needs >= 1 suffix token
        assert pc.shareable_pages(5) == 1
        assert pc.shareable_pages(12) == 2
        prompt = np.arange(12, dtype=np.int32)
        assert pc.lookup(prompt) == (0, ())
        row = np.array([10, 11, 12], np.int32)
        pc.pin([10, 11])
        assert pc.register(prompt, row, 0, 2) == []
        j, pages = pc.lookup(prompt)
        assert j == 2 and pages == (10, 11)
        # a prompt sharing one page hits the shorter entry
        other = np.array([0, 1, 2, 3, 9, 9], np.int32)
        assert pc.lookup(other) == (1, (10,))
        assert pc.resident_pages == 2
        # unpin: entries still reference both pages -> nothing reclaimed
        assert pc.unpin([10, 11]) == []
        assert pc.resident_pages == 2

    def test_prefix_cache_eviction_reclaims(self):
        from paddle_tpu.serving.prefix_cache import PrefixCache

        pc = PrefixCache(page_size=2, capacity=2)
        a = np.array([1, 2, 3], np.int32)       # 1 shareable page
        b = np.array([4, 5, 6], np.int32)
        c = np.array([7, 8, 9], np.int32)
        assert pc.register(a, np.array([0], np.int32), 0, 1) == []
        assert pc.register(b, np.array([1], np.int32), 0, 1) == []
        # third entry LRU-evicts a's entry; page 0 is unreferenced
        assert pc.register(c, np.array([2], np.int32), 0, 1) == [0]
        assert pc.lookup(a) == (0, ()) and pc.lookup(c) == (1, (2,))


class TestTensorParallel:
    def test_tp2_token_parity_and_zero_compiles(self, model):
        """One engine, tp=2 mesh: the page pool's head axis shards over
        tp, every executable compiles under NamedSharding at start(),
        steady state never compiles, and tokens match the unsharded
        engine exactly."""
        import jax

        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 devices")
        paddle.seed(0)
        eng = GenerationEngine(model, max_slots=3, max_seq_len=40,
                               prompt_buckets="8,16", page_size=4,
                               mesh={"tp": 2}).start()
        try:
            assert eng._mesh.devices.size == 2
            ref_a = solo(model, PROMPT_A, 8)
            ref_b = solo(model, PROMPT_B, 8, seed=7, **SAMPLE_KW)
            ref_c = solo(model, PROMPT_C, 6, seed=1)
            before = eng.compile_count
            with _CompileTripwire():
                ha = eng.submit(PROMPT_A, 8)
                hb = eng.submit(PROMPT_B, 8, seed=7, **SAMPLE_KW)
                assert ha.result(120) == ref_a
                assert hb.result(120) == ref_b
                # prefix hit under the mesh too
                hc = eng.submit(PROMPT_C, 6, seed=1)
                hc2 = eng.submit(PROMPT_C, 6, seed=1)
                assert hc.result(120) == hc2.result(120) == ref_c
            assert eng.compile_count == before
            assert eng.metrics.snapshot()["prefix_cache_hits"] >= 1
        finally:
            eng.stop()


@pytest.fixture(scope="module")
def server(model):
    from paddle_tpu.serving.server import ServingServer

    eng = GenerationEngine(model, max_slots=3, max_seq_len=40,
                           prompt_buckets="8,16")
    srv = ServingServer(None, gen_engine=eng, port=0,
                        install_signal_handlers=False).start()
    yield srv
    srv.shutdown()


class TestHTTP:
    def test_blocking_generate(self, model, server):
        from paddle_tpu.serving.client import ServingClient

        cli = ServingClient(server.url)
        out = cli.generate(PROMPT_A, 10)
        assert out["tokens"] == solo(model, PROMPT_A, 10)
        assert out["ttft_ms"] > 0 and out["latency_ms"] > 0

    def test_streaming_sse(self, model, server):
        from paddle_tpu.serving.client import ServingClient

        cli = ServingClient(server.url)
        toks, done = [], None
        for evt in cli.generate_stream(PROMPT_B, 10, seed=7, **SAMPLE_KW):
            if "token" in evt:
                toks.append(evt["token"])
            if evt.get("done"):
                done = evt
        assert toks == solo(model, PROMPT_B, 10, seed=7, **SAMPLE_KW)
        assert done["tokens"] == 10 and "error" not in done

    def test_concurrent_streams(self, model, server):
        from paddle_tpu.serving.client import ServingClient

        cli = ServingClient(server.url)
        ref, outs = solo(model, PROMPT_A, 10), {}

        def go(i):
            outs[i] = [e["token"] for e in cli.generate_stream(PROMPT_A, 10)
                       if "token" in e]

        ts = [threading.Thread(target=go, args=(i,)) for i in range(5)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(outs[i] == ref for i in range(5))

    def test_admission_errors(self, server):
        from paddle_tpu.serving.client import (ServingClient,
                                               ServingHTTPError)

        cli = ServingClient(server.url)
        with pytest.raises(ServingHTTPError) as e:
            cli.generate([], 4)
        assert e.value.status == 400
        with pytest.raises(ServingHTTPError) as e:
            cli.generate(PROMPT_A, 500)
        assert e.value.status == 400
        with pytest.raises(ServingHTTPError) as e:
            cli.predict([[1.0, 2.0]])       # no predict engine mounted
        assert e.value.status == 404

    def test_metrics_endpoint(self, server):
        from paddle_tpu.serving.client import ServingClient

        text = ServingClient(server.url).metrics()
        assert "paddle_genserve_decode_tokens_per_sec" in text
        assert "paddle_genserve_compile_count" in text
