"""Flagship decoder parity: our GPT vs HuggingFace GPT-2 (torch CPU)
with weights copied across — forward logits AND greedy generate() (the
KV-cache prefill+scan loop) validated against the ecosystem-standard
implementation.  HF GPT2's Conv1D keeps weights [in, out] (the paddle
Linear convention) with qkv packed in c_attn."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.models import GPTConfig, GPTForCausalLM  # noqa: E402

V, H, L, A, S = 150, 32, 2, 4, 24
rs = np.random.RandomState(23)


def _t(x):
    return torch.tensor(np.asarray(x.numpy()))


def _copy_into_hf(pm, hf):
    tr = hf.transformer
    with torch.no_grad():
        tr.wte.weight.copy_(_t(pm.gpt.wte.weight))
        tr.wpe.weight.copy_(_t(pm.gpt.wpe.weight))
        tr.ln_f.weight.copy_(_t(pm.gpt.ln_f.weight))
        tr.ln_f.bias.copy_(_t(pm.gpt.ln_f.bias))
        for i, blk in enumerate(tr.h):
            pb = pm.gpt.h[i]
            blk.ln_1.weight.copy_(_t(pb.ln_1.weight))
            blk.ln_1.bias.copy_(_t(pb.ln_1.bias))
            blk.ln_2.weight.copy_(_t(pb.ln_2.weight))
            blk.ln_2.bias.copy_(_t(pb.ln_2.bias))
            # our qkv Linear [H, 3H] == HF c_attn Conv1D [H, 3H]
            blk.attn.c_attn.weight.copy_(_t(pb.attn.qkv.weight))
            blk.attn.c_attn.bias.copy_(_t(pb.attn.qkv.bias))
            blk.attn.c_proj.weight.copy_(_t(pb.attn.out.weight))
            blk.attn.c_proj.bias.copy_(_t(pb.attn.out.bias))
            blk.mlp.c_fc.weight.copy_(_t(pb.mlp.fc1.weight))
            blk.mlp.c_fc.bias.copy_(_t(pb.mlp.fc1.bias))
            blk.mlp.c_proj.weight.copy_(_t(pb.mlp.fc2.weight))
            blk.mlp.c_proj.bias.copy_(_t(pb.mlp.fc2.bias))


@pytest.fixture(scope="module")
def models():
    paddle.seed(31)
    pm = GPTForCausalLM(GPTConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=A,
        max_position_embeddings=S, dropout=0.0, attn_dropout=0.0,
        tie_word_embeddings=True))
    pm.eval()
    hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=V, n_embd=H, n_layer=L, n_head=A, n_positions=S,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        activation_function="gelu"))  # erf form, matching F.gelu
    hf.eval()
    _copy_into_hf(pm, hf)
    return pm, hf


def test_gpt2_logits_parity(models):
    pm, hf = models
    ids = rs.randint(0, V, (2, 10)).astype(np.int64)
    got = np.asarray(pm(paddle.to_tensor(ids)).numpy())
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(got, want, atol=5e-5, rtol=1e-4)


def test_gpt2_greedy_generate_parity(models):
    """Our KV-cache prefill+scan greedy decode must produce the same
    token sequence HF's cached greedy decoding produces."""
    pm, hf = models
    prompt = rs.randint(0, V, (2, 6)).astype(np.int64)
    got = np.asarray(pm.generate(
        paddle.to_tensor(prompt.astype(np.int32)),
        max_new_tokens=8).numpy())
    with torch.no_grad():
        want = hf.generate(torch.tensor(prompt), max_new_tokens=8,
                           do_sample=False, pad_token_id=0).numpy()
    np.testing.assert_array_equal(got, want)
