"""hapi callbacks + distribution + regularizer tests."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.hapi import (
    Callback,
    EarlyStopping,
    Model,
    ModelCheckpoint,
    ProgBarLogger,
    VisualDL,
)


def _toy_model_and_data(n=64):
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.ReLU(),
                               paddle.nn.Linear(8, 2))
    rs = np.random.RandomState(0)
    x = rs.randn(n, 4).astype("float32")
    y = (x.sum(1) > 0).astype("int64")
    ds = paddle.io.TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
    model = Model(net)
    model.prepare(paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss())
    return model, ds


class TestCallbacks:
    def test_hooks_fire_in_order(self):
        model, ds = _toy_model_and_data()
        events = []

        class Recorder(Callback):
            def on_train_begin(self, logs=None):
                events.append("train_begin")

            def on_epoch_begin(self, epoch, logs=None):
                events.append(f"epoch_begin:{epoch}")

            def on_train_batch_end(self, step, logs=None):
                assert "loss" in logs
                events.append("batch_end")

            def on_epoch_end(self, epoch, logs=None):
                assert "loss" in logs
                events.append(f"epoch_end:{epoch}")

            def on_train_end(self, logs=None):
                events.append("train_end")

        model.fit(ds, batch_size=16, epochs=2, verbose=0,
                  callbacks=[Recorder()])
        assert events[0] == "train_begin"
        assert events[-1] == "train_end"
        assert events.count("batch_end") == 8
        assert "epoch_begin:0" in events and "epoch_end:1" in events

    def test_early_stopping_stops(self):
        model, ds = _toy_model_and_data()
        es = EarlyStopping(monitor="loss", patience=0, mode="min", verbose=0,
                           baseline=-1.0, save_best_model=False)
        model.fit(ds, batch_size=16, epochs=10, verbose=0, callbacks=[es])
        # baseline -1 can never improve → stops after first epoch
        assert model.stop_training
        assert es.stopped_epoch == 0

    def test_early_stopping_watches_eval_metric(self):
        """Reference semantics: with eval_data, monitor is the EVAL metric
        (on_eval_end), not the train metric."""
        model, ds = _toy_model_and_data()
        seen = []

        class Spy(EarlyStopping):
            def _check(self, epoch, logs):
                seen.append(dict(logs or {}))
                super()._check(epoch, logs)

        es = Spy(monitor="loss", patience=0, mode="min", verbose=0,
                 baseline=-1.0, save_best_model=False)
        model.fit(ds, eval_data=ds, batch_size=16, epochs=3, verbose=0,
                  callbacks=[es])
        assert model.stop_training
        # checks ran on eval logs (unprefixed keys straight from evaluate())
        assert seen and all("loss" in s for s in seen)
        assert len(seen) == 1  # one check per epoch — eval, not also train

    def test_adamw_rejects_l1decay(self):
        from paddle_tpu.regularizer import L1Decay
        with pytest.raises(TypeError, match="DECOUPLED"):
            paddle.optimizer.AdamW(learning_rate=0.1,
                                   weight_decay=L1Decay(0.1))

    def test_crash_still_closes_callbacks(self, tmp_path):
        model, ds = _toy_model_and_data()
        ended = []

        class Tracker(Callback):
            def on_train_end(self, logs=None):
                ended.append(True)

        class Bomb(Callback):
            def on_train_batch_end(self, step, logs=None):
                raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            model.fit(ds, batch_size=16, epochs=1, verbose=0,
                      callbacks=[Tracker(), Bomb()])
        assert ended == [True]

    def test_model_checkpoint_saves(self, tmp_path):
        model, ds = _toy_model_and_data()
        model.fit(ds, batch_size=16, epochs=2, verbose=0,
                  save_dir=str(tmp_path), save_freq=1)
        assert os.path.exists(tmp_path / "0.pdparams")
        assert os.path.exists(tmp_path / "1.pdparams")
        assert os.path.exists(tmp_path / "final.pdparams")

    def test_visualdl_writes_scalars(self, tmp_path):
        model, ds = _toy_model_and_data()
        model.fit(ds, batch_size=16, epochs=1, verbose=0,
                  callbacks=[VisualDL(str(tmp_path))])
        lines = open(tmp_path / "scalars.jsonl").read().splitlines()
        assert len(lines) == 4
        rec = json.loads(lines[0])
        assert "loss" in rec and "step" in rec

    def test_lr_scheduler_steps_per_batch(self):
        paddle.seed(0)
        net = paddle.nn.Linear(4, 2)
        sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1,
                                              step_size=4, gamma=0.5)
        model, ds = _toy_model_and_data()
        model.prepare(paddle.optimizer.SGD(learning_rate=sched,
                                           parameters=model.network.parameters()),
                      paddle.nn.CrossEntropyLoss())
        model.fit(ds, batch_size=16, epochs=1, verbose=0)
        # 4 batches → one decay step boundary crossed
        assert sched() == pytest.approx(0.05)


class TestDistributions:
    def test_normal(self):
        from paddle_tpu.distribution import Normal, kl_divergence
        n = Normal(0.0, 1.0)
        s = n.sample([2000])
        arr = np.asarray(s.numpy())
        assert abs(arr.mean()) < 0.1 and abs(arr.std() - 1) < 0.1
        lp = float(n.log_prob(paddle.to_tensor(0.0)).numpy())
        assert lp == pytest.approx(-0.5 * np.log(2 * np.pi), abs=1e-5)
        ent = float(n.entropy().numpy())
        assert ent == pytest.approx(0.5 + 0.5 * np.log(2 * np.pi), abs=1e-5)
        kl = float(kl_divergence(Normal(0.0, 1.0), Normal(1.0, 1.0)).numpy())
        assert kl == pytest.approx(0.5, abs=1e-5)
        assert float(kl_divergence(n, n).numpy()) == pytest.approx(0.0,
                                                                   abs=1e-6)

    def test_uniform(self):
        from paddle_tpu.distribution import Uniform
        u = Uniform(2.0, 4.0)
        arr = np.asarray(u.sample([1000]).numpy())
        assert arr.min() >= 2.0 and arr.max() < 4.0
        assert float(u.entropy().numpy()) == pytest.approx(np.log(2.0))
        assert float(u.log_prob(paddle.to_tensor(3.0)).numpy()) == \
            pytest.approx(-np.log(2.0))
        assert np.isneginf(float(u.log_prob(paddle.to_tensor(5.0)).numpy()))

    def test_categorical(self):
        from paddle_tpu.distribution import Categorical, kl_divergence
        logits = np.log(np.array([0.5, 0.25, 0.25], "f"))
        c = Categorical(logits)
        samp = np.asarray(c.sample([4000]).numpy())
        freq = np.bincount(samp, minlength=3) / 4000
        np.testing.assert_allclose(freq, [0.5, 0.25, 0.25], atol=0.05)
        ent = float(c.entropy().numpy())
        assert ent == pytest.approx(1.5 * np.log(2), rel=1e-4)
        assert float(kl_divergence(c, c).numpy()) == pytest.approx(0.0,
                                                                   abs=1e-6)
        lp = np.asarray(c.log_prob(paddle.to_tensor(np.array([0, 2]))).numpy())
        np.testing.assert_allclose(lp, np.log([0.5, 0.25]), rtol=1e-4)


class TestRegularizer:
    def test_l2_matches_manual(self):
        from paddle_tpu.regularizer import L2Decay
        paddle.seed(0)
        w0 = np.random.RandomState(0).randn(3, 3).astype("f")
        for wd in (L2Decay(0.1), 0.1):
            p = paddle.to_tensor(w0.copy())
            p.stop_gradient = False
            opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p],
                                       weight_decay=wd)
            loss = paddle.sum(p * 0.0)  # zero data grad → pure decay
            loss.backward()
            opt.step()
            np.testing.assert_allclose(np.asarray(p.numpy()),
                                       w0 - 0.1 * w0, rtol=1e-5)

    def test_l1_signs(self):
        from paddle_tpu.regularizer import L1Decay
        w0 = np.array([[1.0, -2.0], [0.5, -0.5]], "f")
        p = paddle.to_tensor(w0.copy())
        p.stop_gradient = False
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p],
                                   weight_decay=L1Decay(0.1))
        loss = paddle.sum(p * 0.0)
        loss.backward()
        opt.step()
        np.testing.assert_allclose(np.asarray(p.numpy()),
                                   w0 - 0.1 * np.sign(w0), rtol=1e-5)


class TestSummaryFlops:
    def test_flops_xla_cost_model(self):
        import paddle_tpu as paddle

        net = paddle.nn.Linear(8, 4)
        f = paddle.flops(net, [2, 8])
        assert 100 <= f <= 200, f  # 2*B*in*out + bias adds

    def test_model_summary_totals(self):
        import paddle_tpu as paddle

        m = paddle.Model(paddle.nn.Linear(4, 2))
        assert m.summary()["total_params"] == 10
