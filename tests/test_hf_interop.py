"""models.interop: load HF checkpoints into the model zoo (the public
inverse of the parity suites' copy helpers) — randomly initialized HF
models imported through load_hf_bert / load_hf_gpt2 must reproduce the
HF forward exactly."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.models import (BertConfig, BertModel, GPTConfig,
                               GPTForCausalLM)  # noqa: E402
from paddle_tpu.models.interop import load_hf_bert, load_hf_gpt2  # noqa: E402

rs = np.random.RandomState(43)


def test_load_hf_bert_reproduces_hf():
    hf = transformers.BertModel(transformers.BertConfig(
        vocab_size=90, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=20, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        hidden_act="gelu"))
    hf.eval()
    pm = BertModel(BertConfig(
        vocab_size=90, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, max_position_embeddings=20, dropout=0.0))
    pm.eval()
    load_hf_bert(pm, hf)  # live module form
    ids = rs.randint(0, 90, (2, 12)).astype(np.int64)
    seq, pooled = pm(paddle.to_tensor(ids))
    with torch.no_grad():
        out = hf(input_ids=torch.tensor(ids))
    np.testing.assert_allclose(np.asarray(seq.numpy()),
                               out.last_hidden_state.numpy(),
                               atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(pooled.numpy()),
                               out.pooler_output.numpy(),
                               atol=2e-5, rtol=1e-4)


def test_load_hf_gpt2_state_dict_and_generate():
    hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=80, n_embd=24, n_layer=2, n_head=4, n_positions=18,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        activation_function="gelu"))
    hf.eval()
    pm = GPTForCausalLM(GPTConfig(
        vocab_size=80, hidden_size=24, num_layers=2, num_heads=4,
        max_position_embeddings=18, dropout=0.0, attn_dropout=0.0,
        tie_word_embeddings=True))
    pm.eval()
    load_hf_gpt2(pm, hf.state_dict())  # state_dict form
    prompt = rs.randint(0, 80, (2, 5)).astype(np.int64)
    got = np.asarray(pm.generate(
        paddle.to_tensor(prompt.astype(np.int32)),
        max_new_tokens=6).numpy())
    with torch.no_grad():
        want = hf.generate(torch.tensor(prompt), max_new_tokens=6,
                           do_sample=False, pad_token_id=0).numpy()
    np.testing.assert_array_equal(got, want)


def test_untied_model_without_lm_head_raises():
    hf = transformers.GPT2Model(transformers.GPT2Config(
        vocab_size=80, n_embd=24, n_layer=1, n_head=4, n_positions=18))
    pm = GPTForCausalLM(GPTConfig(
        vocab_size=80, hidden_size=24, num_layers=1, num_heads=4,
        max_position_embeddings=18, tie_word_embeddings=False))
    with pytest.raises(KeyError, match="lm_head"):
        load_hf_gpt2(pm, hf)
    load_hf_gpt2(pm, hf, strict=False)  # explicit opt-in works


def test_export_roundtrip_to_hf():
    """Our trained weights exported with to_hf_bert_state load into a
    fresh HF model and reproduce OUR forward — the export direction of
    the interop contract."""
    from paddle_tpu.models.interop import to_hf_bert_state

    paddle.seed(51)
    pm = BertModel(BertConfig(
        vocab_size=70, hidden_size=16, num_layers=1, num_heads=2,
        intermediate_size=32, max_position_embeddings=12, dropout=0.0))
    pm.eval()
    hf = transformers.BertModel(transformers.BertConfig(
        vocab_size=70, hidden_size=16, num_hidden_layers=1,
        num_attention_heads=2, intermediate_size=32,
        max_position_embeddings=12, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        hidden_act="gelu"))
    hf.eval()
    sd = {k: torch.tensor(v) for k, v in to_hf_bert_state(pm).items()}
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    assert not unexpected, unexpected
    ids = rs.randint(0, 70, (2, 8)).astype(np.int64)
    seq, _ = pm(paddle.to_tensor(ids))
    with torch.no_grad():
        out = hf(input_ids=torch.tensor(ids))
    np.testing.assert_allclose(np.asarray(seq.numpy()),
                               out.last_hidden_state.numpy(),
                               atol=2e-5, rtol=1e-4)


def test_shape_mismatch_raises():
    hf = transformers.BertModel(transformers.BertConfig(
        vocab_size=90, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=20))
    pm = BertModel(BertConfig(vocab_size=91, hidden_size=32, num_layers=2,
                              num_heads=4, intermediate_size=64,
                              max_position_embeddings=20))
    with pytest.raises(ValueError, match="shape mismatch"):
        load_hf_bert(pm, hf)
