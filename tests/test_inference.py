"""Inference export/serve tests.

Mirrors the reference's inference/api tests (analysis_predictor_tester.cc):
export a trained model, reload in a fresh predictor, assert identical
outputs — including the AOT (StableHLO) path that needs no python model
code at serve time."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (
    Config,
    Predictor,
    create_predictor,
    load_inference_model,
    save_inference_model,
)


def _trained_mlp():
    paddle.seed(7)
    net = paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.ReLU(), paddle.nn.Linear(16, 3))
    return net


class TestSaveLoad:
    def test_aot_roundtrip_matches_eager(self, tmp_path):
        net = _trained_mlp()
        x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        net.eval()
        ref = np.asarray(net(paddle.to_tensor(x)).numpy())
        prefix = str(tmp_path / "model" / "mlp")
        save_inference_model(prefix, net, example_inputs=[x])
        # AOT artifacts exist
        assert os.path.exists(prefix + ".pdexport")
        assert os.path.exists(prefix + ".pdiparams")
        manifest = json.load(open(prefix + ".pdmodel.json"))
        assert manifest["format"] == "jax.export/stablehlo"
        assert manifest["input_specs"][0]["shape"] == [4, 8]

        pred = load_inference_model(prefix)
        assert pred._mode == "aot"
        out, = pred.run([x])
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_pickle_fallback_without_example_inputs(self, tmp_path):
        net = _trained_mlp()
        x = np.random.RandomState(1).randn(2, 8).astype(np.float32)
        net.eval()
        ref = np.asarray(net(paddle.to_tensor(x)).numpy())
        prefix = str(tmp_path / "m2")
        save_inference_model(prefix, net)  # no example → no AOT artifact
        assert not os.path.exists(prefix + ".pdexport")
        pred = create_predictor(Config(prefix))
        assert pred._mode == "jit"
        out, = pred.run([x])
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_save_restores_training_mode(self, tmp_path):
        net = _trained_mlp()
        net.train()
        save_inference_model(str(tmp_path / "m3"), net)
        assert net.training


class TestPredictorAPI:
    def test_zero_copy_handles(self, tmp_path):
        """The get_input_handle/copy_from_cpu/run/copy_to_cpu contract
        (api/analysis_predictor.cc ZeroCopyRun)."""
        net = _trained_mlp()
        net.eval()
        x = np.random.RandomState(2).randn(4, 8).astype(np.float32)
        ref = np.asarray(net(paddle.to_tensor(x)).numpy())
        prefix = str(tmp_path / "m4")
        save_inference_model(prefix, net, example_inputs=[x])
        pred = create_predictor(Config(prefix))
        names = pred.get_input_names()
        assert names == ["x0"]
        h = pred.get_input_handle("x0")
        h.copy_from_cpu(x)
        pred.run()
        out_h = pred.get_output_handle(pred.get_output_names()[0])
        pred.run()
        np.testing.assert_allclose(out_h.copy_to_cpu(), ref,
                                   rtol=1e-5, atol=1e-6)

    def test_config_knobs_inert(self):
        c = Config("/nonexistent/prefix")
        c.enable_use_gpu(100, 0)
        c.disable_gpu()
        c.enable_mkldnn()
        c.enable_tensorrt_engine()
        c.enable_memory_optim()
        c.switch_ir_optim(True)
        assert "switches" in c.summary()

    def test_missing_model_raises(self):
        with pytest.raises((FileNotFoundError, ValueError)):
            Predictor(Config("/nonexistent/prefix"))

    def test_input_spec_export(self, tmp_path):
        from paddle_tpu.jit import InputSpec
        net = _trained_mlp()
        prefix = str(tmp_path / "m5")
        save_inference_model(prefix, net,
                             input_spec=[InputSpec([2, 8], "float32")])
        assert os.path.exists(prefix + ".pdexport")
        pred = load_inference_model(prefix)
        out, = pred.run([np.zeros((2, 8), np.float32)])
        assert out.shape == (2, 3)
