"""Inference export/serve tests.

Mirrors the reference's inference/api tests (analysis_predictor_tester.cc):
export a trained model, reload in a fresh predictor, assert identical
outputs — including the AOT (StableHLO) path that needs no python model
code at serve time."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (
    Config,
    Predictor,
    create_predictor,
    load_inference_model,
    save_inference_model,
)


class _Sum12(paddle.nn.Layer):
    """12 inputs, each weighted differently so binding order matters."""

    def __init__(self):
        super().__init__()
        self.w = self.create_parameter([1], default_initializer=None)

    def forward(self, *xs):
        return sum((i + 1) * x for i, x in enumerate(xs)) + 0 * self.w


def _trained_mlp():
    paddle.seed(7)
    net = paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.ReLU(), paddle.nn.Linear(16, 3))
    return net


class TestSaveLoad:
    def test_aot_roundtrip_matches_eager(self, tmp_path):
        net = _trained_mlp()
        x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        net.eval()
        ref = np.asarray(net(paddle.to_tensor(x)).numpy())
        prefix = str(tmp_path / "model" / "mlp")
        save_inference_model(prefix, net, example_inputs=[x])
        # AOT artifacts exist
        assert os.path.exists(prefix + ".pdexport")
        assert os.path.exists(prefix + ".pdiparams")
        manifest = json.load(open(prefix + ".pdmodel.json"))
        assert manifest["format"] == "jax.export/stablehlo"
        assert manifest["input_specs"][0]["shape"] == [4, 8]

        pred = load_inference_model(prefix)
        assert pred._mode == "aot"
        out, = pred.run([x])
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_pickle_fallback_without_example_inputs(self, tmp_path):
        net = _trained_mlp()
        x = np.random.RandomState(1).randn(2, 8).astype(np.float32)
        net.eval()
        ref = np.asarray(net(paddle.to_tensor(x)).numpy())
        prefix = str(tmp_path / "m2")
        save_inference_model(prefix, net)  # no example → no AOT artifact
        assert not os.path.exists(prefix + ".pdexport")
        pred = create_predictor(Config(prefix))
        assert pred._mode == "jit"
        out, = pred.run([x])
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_save_restores_training_mode(self, tmp_path):
        net = _trained_mlp()
        net.train()
        save_inference_model(str(tmp_path / "m3"), net)
        assert net.training


class TestPredictorAPI:
    def test_zero_copy_handles(self, tmp_path):
        """The get_input_handle/copy_from_cpu/run/copy_to_cpu contract
        (api/analysis_predictor.cc ZeroCopyRun)."""
        net = _trained_mlp()
        net.eval()
        x = np.random.RandomState(2).randn(4, 8).astype(np.float32)
        ref = np.asarray(net(paddle.to_tensor(x)).numpy())
        prefix = str(tmp_path / "m4")
        save_inference_model(prefix, net, example_inputs=[x])
        pred = create_predictor(Config(prefix))
        names = pred.get_input_names()
        assert names == ["x0"]
        h = pred.get_input_handle("x0")
        h.copy_from_cpu(x)
        pred.run()
        out_h = pred.get_output_handle(pred.get_output_names()[0])
        pred.run()
        np.testing.assert_allclose(out_h.copy_to_cpu(), ref,
                                   rtol=1e-5, atol=1e-6)

    def test_config_knobs_inert(self):
        c = Config("/nonexistent/prefix")
        c.enable_use_gpu(100, 0)
        c.disable_gpu()
        c.enable_mkldnn()
        c.enable_tensorrt_engine()
        c.enable_memory_optim()
        c.switch_ir_optim(True)
        assert "switches" in c.summary()

    def test_inert_knobs_warn_once(self, caplog):
        """CUDA/MKLDNN/TensorRT knobs are silently inert no more: one
        warning per knob per process (not per call — serving loops build
        Configs in bulk)."""
        import logging

        from paddle_tpu import inference as _inf

        _inf._warned_inert.clear()
        with caplog.at_level(logging.WARNING, logger="paddle_tpu.inference"):
            c = Config("/nonexistent/prefix")
            c.enable_mkldnn()
            c.enable_mkldnn()          # repeated call: no second record
            Config("/other").enable_mkldnn()  # other instance: still once
            c.enable_tensorrt_engine()
            c.enable_use_gpu()
            c.enable_xpu()
        inert = [r.getMessage() for r in caplog.records
                 if "INERT" in r.getMessage()]
        assert len(inert) == 4
        assert sum("enable_mkldnn" in m for m in inert) == 1
        assert any("enable_tensorrt_engine" in m for m in inert)

    def test_enable_tpu(self, caplog):
        """enable_tpu is the real path — honored, recorded, no warning."""
        import logging

        from paddle_tpu import inference as _inf

        _inf._warned_inert.clear()
        c = Config("/nonexistent/prefix")
        with caplog.at_level(logging.WARNING, logger="paddle_tpu.inference"):
            c.enable_tpu()
        assert c.use_tpu() is True
        assert '"use_tpu": true' in c.summary()
        assert not [r for r in caplog.records if "INERT" in r.getMessage()]

    def test_bucket_cache_compiles_once_per_shape(self, tmp_path):
        """The serving-facing contract: warm() AOT-compiles a shape
        bucket once; repeated run() calls on it never compile again."""
        from paddle_tpu.jit import InputSpec
        net = _trained_mlp()
        net.eval()
        prefix = str(tmp_path / "mbkt")
        save_inference_model(prefix, net,
                             input_spec=[InputSpec([-1, 8], "float32")])
        pred = load_inference_model(prefix)
        assert pred.compile_count == 0
        assert pred.warm([(4, 8)]) is True
        assert pred.compile_count == 1
        x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        ref = pred.run([x])
        for _ in range(3):
            pred.run([x])
        assert pred.compile_count == 1          # cache hit, no recompile
        pred.run([x[:2]])
        assert pred.compile_count == 2          # new bucket: one compile
        np.testing.assert_array_equal(pred.run([x])[0], ref[0])

    def test_missing_model_raises(self):
        with pytest.raises((FileNotFoundError, ValueError)):
            Predictor(Config("/nonexistent/prefix"))

    def test_input_spec_export(self, tmp_path):
        from paddle_tpu.jit import InputSpec
        net = _trained_mlp()
        prefix = str(tmp_path / "m5")
        save_inference_model(prefix, net,
                             input_spec=[InputSpec([2, 8], "float32")])
        assert os.path.exists(prefix + ".pdexport")
        pred = load_inference_model(prefix)
        out, = pred.run([np.zeros((2, 8), np.float32)])
        assert out.shape == (2, 3)

    def test_dynamic_batch_export_serves_any_batch(self, tmp_path):
        """Regression (advisor r1/r2): InputSpec([-1, 8]) used to bake the
        dynamic dim to 1, silently serving batch-1 only. Now exports via
        jax.export symbolic shapes."""
        from paddle_tpu.jit import InputSpec
        net = _trained_mlp()
        prefix = str(tmp_path / "mdyn")
        save_inference_model(prefix, net,
                             input_spec=[InputSpec([-1, 8], "float32")])
        pred = load_inference_model(prefix)
        assert pred._mode == "aot"
        for b in (1, 3, 17):
            out, = pred.run([np.random.RandomState(b)
                             .randn(b, 8).astype(np.float32)])
            assert out.shape == (b, 3)
        manifest = json.load(open(prefix + ".pdmodel.json"))
        assert manifest["input_specs"][0]["shape"] == [-1, 8]

    def test_many_input_handle_ordering(self, tmp_path):
        """Regression (advisor r1/r2): lexicographic sorted() bound x10
        before x2 for models with 11+ inputs."""
        net = _Sum12()
        net.eval()
        prefix = str(tmp_path / "m12")
        examples = [np.full((1,), 1.0, np.float32) for _ in range(12)]
        save_inference_model(prefix, net, example_inputs=examples)
        pred = load_inference_model(prefix)
        names = pred.get_input_names()
        assert names == [f"x{i}" for i in range(12)]
        for i, n in enumerate(names):
            h = pred.get_input_handle(n)
            h.copy_from_cpu(np.full((1,), float(i), np.float32))
        out, = pred.run()
        expect = sum((i + 1) * float(i) for i in range(12))
        assert np.allclose(out, expect)

    def test_zero_copy_natural_order_fallback(self):
        """When input names must be inferred from handles alone, numeric
        suffixes bind in natural order (x2 before x10)."""
        from paddle_tpu.inference import _natural_key
        names = [f"x{i}" for i in range(12)]
        assert sorted(names, key=_natural_key) == names
