"""Launcher tests — real subprocesses on localhost, mirroring the
reference's TestDistBase style (SURVEY.md §4: multi-node is only ever
exercised as multi-process on 127.0.0.1)."""
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.distributed.launch import _parse_args, get_cluster_from_args
from paddle_tpu.distributed.launch_utils import (
    Cluster,
    Pod,
    Trainer,
    find_free_ports,
    get_cluster,
    start_local_trainers,
    terminate_local_procs,
    watch_local_trainers,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestClusterSpec:
    def test_get_cluster(self):
        ips = ["10.0.0.1", "10.0.0.2"]
        eps = [f"{ip}:{p}" for ip in ips for p in (6070, 6071)]
        cluster, pod = get_cluster(ips, "10.0.0.2", eps, 2)
        assert cluster.trainers_nranks() == 4
        assert cluster.trainers_endpoints() == eps
        assert pod.rank == 1
        assert [t.rank for t in pod.trainers] == [2, 3]

    def test_parse_args_and_cluster(self):
        args = _parse_args(["--ips=127.0.0.1", "--nproc_per_node=2",
                            "--started_port=6170", "train.py", "--lr=0.1"])
        assert args.training_script == "train.py"
        assert args.training_script_args == ["--lr=0.1"]
        cluster, pod = get_cluster_from_args(args)
        assert cluster.trainers_nranks() == 2
        assert cluster.trainers_endpoints() == ["127.0.0.1:6170",
                                                "127.0.0.1:6171"]

    def test_find_free_ports(self):
        ports = find_free_ports(3)
        assert len(set(ports)) == 3


def _write_script(tmp_path, body):
    script = tmp_path / "trainer.py"
    script.write_text(textwrap.dedent(body))
    return str(script)


class TestLocalTrainers:
    def test_env_contract_and_success(self, tmp_path):
        """Each spawned trainer sees the PADDLE_TRAINER_* env schema."""
        script = _write_script(tmp_path, """
            import json, os, sys
            rank = os.environ["PADDLE_TRAINER_ID"]
            out = {
                "rank": rank,
                "nranks": os.environ["PADDLE_TRAINERS_NUM"],
                "endpoint": os.environ["PADDLE_CURRENT_ENDPOINT"],
                "endpoints": os.environ["PADDLE_TRAINER_ENDPOINTS"],
                "master": os.environ["PADDLE_MASTER"],
            }
            open(os.path.join(os.path.dirname(__file__),
                              f"out.{rank}.json"), "w").write(json.dumps(out))
        """)
        eps = [f"127.0.0.1:{p}" for p in find_free_ports(2)]
        cluster, pod = get_cluster(["127.0.0.1"], "127.0.0.1", eps, 2)
        procs = start_local_trainers(cluster, pod, script, [],
                                     log_dir=str(tmp_path / "logs"))
        codes = watch_local_trainers(procs, 2, poll_interval=0.1)
        assert codes == [0, 0]
        import json
        for rank in (0, 1):
            d = json.loads((tmp_path / f"out.{rank}.json").read_text())
            assert d["rank"] == str(rank)
            assert d["nranks"] == "2"
            assert d["endpoint"] == eps[rank]
            assert d["endpoints"] == ",".join(eps)
            assert d["master"] == eps[0]
        # log files exist
        assert (tmp_path / "logs" / "workerlog.0").exists()

    def test_failure_tears_down_pod(self, tmp_path):
        """Reference policy: any trainer failure kills the pod
        (launch_utils.py:517) — no elastic restart."""
        script = _write_script(tmp_path, """
            import os, sys, time
            if os.environ["PADDLE_TRAINER_ID"] == "1":
                sys.exit(3)
            time.sleep(60)   # rank 0 would hang forever
        """)
        eps = [f"127.0.0.1:{p}" for p in find_free_ports(2)]
        cluster, pod = get_cluster(["127.0.0.1"], "127.0.0.1", eps, 2)
        procs = start_local_trainers(cluster, pod, script, [])
        with pytest.raises(RuntimeError, match="trainer 1 failed"):
            watch_local_trainers(procs, 2, poll_interval=0.1)
        # rank 0 must have been terminated too
        assert all(tp.proc.poll() is not None for tp in procs)


class TestLaunchCLI:
    def test_module_entrypoint(self, tmp_path):
        script = _write_script(tmp_path, """
            import os
            assert os.environ["PADDLE_TRAINERS_NUM"] == "2"
            print("trainer", os.environ["PADDLE_TRAINER_ID"], "ok")
        """)
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node=2", "--log_dir", str(tmp_path / "lg"),
             script],
            env=env, capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        logs = sorted((tmp_path / "lg").iterdir())
        assert len(logs) == 2
        assert "ok" in logs[0].read_text()


class TestSpawn:
    def test_spawn_env(self, tmp_path):
        """spawn() runs func in N processes with the trainer env set."""
        script = _write_script(tmp_path, """
            import os, sys
            sys.path.insert(0, %r)
            os.environ.setdefault("JAX_PLATFORMS", "cpu")

            def work(out_dir):
                import os
                rank = os.environ["PADDLE_TRAINER_ID"]
                open(os.path.join(out_dir, f"sp.{rank}"), "w").write(
                    os.environ["PADDLE_TRAINERS_NUM"])

            if __name__ == "__main__":
                from paddle_tpu.distributed.spawn import spawn
                spawn(work, args=(sys.argv[1],), nprocs=2)
        """ % REPO)
        r = subprocess.run([sys.executable, script, str(tmp_path)],
                           capture_output=True, text=True, timeout=120,
                           env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert r.returncode == 0, r.stderr
        assert (tmp_path / "sp.0").read_text() == "2"
        assert (tmp_path / "sp.1").read_text() == "2"

    def test_spawn_failure_propagates(self, tmp_path):
        script = _write_script(tmp_path, """
            import sys
            sys.path.insert(0, %r)

            def bad():
                raise ValueError("boom-42")

            if __name__ == "__main__":
                from paddle_tpu.distributed.spawn import spawn
                try:
                    spawn(bad, nprocs=2)
                except RuntimeError as e:
                    assert "boom-42" in str(e)
                    sys.exit(0)
                sys.exit(1)
        """ % REPO)
        r = subprocess.run([sys.executable, script], capture_output=True,
                           text=True, timeout=120,
                           env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert r.returncode == 0, r.stderr + r.stdout


class TestPackaging:
    """Packaging parity (reference setup.py.in:513-515 console scripts)."""

    def test_pyproject_declares_fleetrun(self):
        import os
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        text = open(os.path.join(root, "pyproject.toml")).read()
        assert 'fleetrun = "paddle_tpu.distributed.launch:launch"' in text
        assert 'libpaddle_tpu_core.so' in text

    def test_module_launch_help(self):
        import subprocess
        import sys
        p = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--help"], capture_output=True, text=True, timeout=120)
        assert p.returncode == 0
        assert "nproc_per_node" in p.stdout
