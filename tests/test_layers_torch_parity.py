"""Layer-level parity vs torch.nn modules with weights copied across:
norm layers (incl. BatchNorm running-stat updates — paddle momentum m
== torch momentum 1-m), embedding with padding_idx, and LSTM/GRU full
sequence outputs (same per-layer weight layout and gate order)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402

rs = np.random.RandomState(11)


def _cmp(pd_out, t_out, atol=1e-5):
    np.testing.assert_allclose(np.asarray(pd_out.numpy()),
                               t_out.detach().numpy(), atol=atol,
                               rtol=1e-4)


def test_batchnorm2d_train_eval_and_running_stats():
    paddle.seed(0)
    pb = nn.BatchNorm2D(5, momentum=0.9, epsilon=1e-5)
    tb = torch.nn.BatchNorm2d(5, momentum=0.1, eps=1e-5)
    w = rs.rand(5).astype(np.float32) + 0.5
    b = rs.randn(5).astype(np.float32)
    pb.weight.set_value(w)
    pb.bias.set_value(b)
    with torch.no_grad():
        tb.weight.copy_(torch.tensor(w))
        tb.bias.copy_(torch.tensor(b))

    for _ in range(3):  # train steps update running stats
        x = rs.randn(4, 5, 6, 6).astype(np.float32)
        pb.train()
        tb.train()
        _cmp(pb(paddle.to_tensor(x)), tb(torch.tensor(x)), atol=1e-4)
    np.testing.assert_allclose(np.asarray(pb._mean.numpy()),
                               tb.running_mean.numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(pb._variance.numpy()),
                               tb.running_var.numpy(), atol=1e-4)
    pb.eval()
    tb.eval()
    x = rs.randn(2, 5, 6, 6).astype(np.float32)
    _cmp(pb(paddle.to_tensor(x)), tb(torch.tensor(x)), atol=1e-4)


def test_groupnorm_instancenorm_parity():
    x = rs.randn(3, 8, 5, 5).astype(np.float32)
    pg = nn.GroupNorm(num_groups=4, num_channels=8, epsilon=1e-5)
    tg = torch.nn.GroupNorm(4, 8, eps=1e-5)
    w = rs.rand(8).astype(np.float32) + 0.5
    b = rs.randn(8).astype(np.float32)
    pg.weight.set_value(w)
    pg.bias.set_value(b)
    with torch.no_grad():
        tg.weight.copy_(torch.tensor(w))
        tg.bias.copy_(torch.tensor(b))
    _cmp(pg(paddle.to_tensor(x)), tg(torch.tensor(x)), atol=1e-5)

    pi = nn.InstanceNorm2D(8, epsilon=1e-5)
    ti = torch.nn.InstanceNorm2d(8, eps=1e-5)
    _cmp(pi(paddle.to_tensor(x)), ti(torch.tensor(x)), atol=1e-5)


def test_embedding_padding_idx_parity():
    table = rs.randn(20, 6).astype(np.float32)
    pe = nn.Embedding(20, 6, padding_idx=3)
    pe.weight.set_value(table)
    te = torch.nn.Embedding(20, 6, padding_idx=3)
    with torch.no_grad():
        te.weight.copy_(torch.tensor(table))
        te.weight[3] = 0  # torch zeroes the row at init; paddle masks
    ids = np.array([[1, 3, 5], [3, 0, 19]], np.int64)
    _cmp(pe(paddle.to_tensor(ids)), te(torch.tensor(ids)))


def _copy_rnn_weights(p_rnn, t_rnn, layers, bidirect=False):
    sd = {k: v for k, v in
          ((n, p) for n, p in t_rnn.named_parameters())}
    for L in range(layers):
        for suf in ([""] if not bidirect else ["", "_reverse"]):
            for kind in ["weight_ih", "weight_hh", "bias_ih", "bias_hh"]:
                tname = f"{kind}_l{L}{suf}"
                arr = np.asarray(getattr(
                    p_rnn, f"{tname}").numpy()) if hasattr(
                        p_rnn, tname) else None
                assert arr is not None, f"paddle rnn lacks {tname}"
                with torch.no_grad():
                    sd[tname].copy_(torch.tensor(arr))


@pytest.mark.parametrize("cls,tcls", [("LSTM", torch.nn.LSTM),
                                      ("GRU", torch.nn.GRU)])
def test_rnn_sequence_parity(cls, tcls):
    paddle.seed(2)
    p_rnn = getattr(nn, cls)(input_size=6, hidden_size=8, num_layers=2)
    t_rnn = tcls(input_size=6, hidden_size=8, num_layers=2,
                 batch_first=True)
    try:
        _copy_rnn_weights(p_rnn, t_rnn, layers=2)
    except AssertionError as e:
        pytest.skip(f"weight naming differs: {e}")
    x = rs.randn(3, 7, 6).astype(np.float32)
    p_out = p_rnn(paddle.to_tensor(x))
    p_y = p_out[0] if isinstance(p_out, (tuple, list)) else p_out
    t_y, _ = t_rnn(torch.tensor(x))
    _cmp(p_y, t_y, atol=1e-4)
