"""3D-parallel layout system (distributed/layout.py + engine wiring).

Pins the tentpole contracts on the 8 virtual CPU devices:

  * the SpecLayout table — every gpt/bert/ernie param matches a
    NON-replicated spec (silent full replication of a transformer weight
    is the failure mode the table exists to prevent); unmatched names
    warn and replicate; prune() fits table specs onto any mesh;
  * opt-state ZeRO semantics — slots inherit their param's spec, while
    scalar/0-d/1-element slots ALWAYS replicate (regression pin: the
    shapes-match heuristic must not pin a beta-power slot to a 1-elem
    param's spec);
  * parity — dp8, dp2×fsdp2×tp2 and dp2×fsdp4 agree at fixed global
    batch to f32 ULP-scale tolerances; accum_steps=4 ≡ accum_steps=1;
    recompute="dots" is numerically invisible;
  * donation — zero silent-fallback under 3D + remat + accumulation;
  * HLO — the 3D step carries all-gather (fsdp param gather) alongside
    the dp grad all-reduce;
  * elasticity — a dp8-saved checkpoint restores onto dp2×fsdp2×tp2,
    then back onto dp8, agreeing with dp8-throughout to f32 ULP;
  * deprecation routing — distributed.sharding / meta_parallel
    entrypoints warn once per process and forward onto the layout
    implementations; recompute/grad_merge re-export them.

Run standalone via tools/mesh3d_smoke.sh.
"""
import warnings

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import layout as layout_mod
from paddle_tpu.distributed.layout import SpecLayout
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.framework.transfer import shard_batch
from paddle_tpu.hapi import Model
from paddle_tpu.hapi.engine import TrainEngine

pytestmark = pytest.mark.mesh3d

needs8 = pytest.mark.skipif(jax.device_count() < 8,
                            reason="needs the 8-virtual-device conftest mesh")

MESH3D = {"dp": 2, "fsdp": 2, "tp": 2}
MESH_F4 = {"dp": 2, "fsdp": 4}


class _MLP(paddle.nn.Layer):
    """Layout-matchable names: fc1 (up), fc2 (down)."""

    def __init__(self):
        super().__init__()
        self.fc1 = paddle.nn.Linear(4, 8)
        self.act = paddle.nn.ReLU()
        self.fc2 = paddle.nn.Linear(8, 2)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


def _model(lr=0.01):
    paddle.seed(0)
    net = _MLP()
    model = Model(net)
    model.prepare(
        paddle.optimizer.Adam(learning_rate=lr,
                              parameters=net.parameters()),
        paddle.nn.CrossEntropyLoss())
    return model


def _dataset(n=24):
    from paddle_tpu.io import TensorDataset

    rs = np.random.RandomState(0)
    x = rs.randn(n, 4).astype("float32")
    y = (x.sum(1) > 0).astype("int64")
    return TensorDataset([x, y])


def _weights(model):
    return {k: np.asarray(p._value)
            for k, p in model.network.named_parameters()}


# -- the PartitionSpec table -------------------------------------------------
class TestLayoutTable:
    @staticmethod
    def _assert_all_matched(named_params):
        lay = SpecLayout()
        unmatched, replicated = [], []
        for name, p in named_params:
            shape = tuple(p.shape)
            spec = lay.spec_for(name, shape)
            if spec is None:
                unmatched.append(name)
            elif int(np.prod(shape)) > 1 and spec == P():
                replicated.append(name)
        assert not unmatched, f"no table match: {unmatched}"
        assert not replicated, f"silently replicated: {replicated}"

    def test_every_gpt_param_matches_non_replicated(self):
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                        num_heads=2, max_position_embeddings=16)
        self._assert_all_matched(GPTForCausalLM(cfg).named_parameters())

    def test_every_bert_param_matches_non_replicated(self):
        from paddle_tpu.models.bert import BertConfig, BertForPretraining

        cfg = BertConfig(vocab_size=64, hidden_size=16, num_layers=2,
                         num_heads=2, intermediate_size=32,
                         max_position_embeddings=16)
        self._assert_all_matched(BertForPretraining(cfg).named_parameters())

    def test_every_ernie_param_matches_non_replicated(self):
        from paddle_tpu.models import ErnieModel

        m = ErnieModel(vocab_size=64, hidden_size=16, num_layers=1,
                       num_heads=2, intermediate_size=32,
                       max_position_embeddings=16)
        self._assert_all_matched(m.named_parameters())

    def test_canonical_table_entries(self):
        lay = SpecLayout()
        assert lay.spec_for("gpt.wte.weight", (64, 16)) == \
            P(("fsdp", "tp"), None)
        assert lay.spec_for("gpt.h_0.attn.qkv.weight", (16, 48)) == \
            P("fsdp", "tp")
        assert lay.spec_for("gpt.h_0.attn.out.weight", (16, 16)) == \
            P("tp", "fsdp")
        assert lay.spec_for("gpt.h_0.mlp.fc1.weight", (16, 64)) == \
            P("fsdp", "tp")
        assert lay.spec_for("gpt.h_0.mlp.fc2.weight", (64, 16)) == \
            P("tp", "fsdp")
        assert lay.spec_for("gpt.h_0.ln_1.weight", (16,)) == P("fsdp")
        assert lay.spec_for("gpt.h_0.attn.qkv.bias", (48,)) == P("tp")
        assert lay.spec_for("scale", ()) == P()
        assert lay.spec_for("conv.kernel", (3, 3, 8, 8)) is None

    @needs8
    def test_prune_fits_spec_to_mesh(self):
        lay = SpecLayout()
        mesh3d = build_mesh(MESH3D)
        # [2, 16] token-type embedding: fsdp×tp=4 does not divide 2 →
        # trailing tuple axes drop until fsdp alone fits
        spec = lay.spec_for("embeddings.token_type.weight", (2, 16))
        assert spec == P(("fsdp", "tp"), None)
        assert lay.prune(spec, (2, 16), mesh3d) == P(("fsdp",), None)
        # axes the mesh lacks drop per-dim
        mesh_dp = build_mesh({"dp": 8})
        assert lay.prune(P("fsdp", "tp"), (16, 16), mesh_dp) == P()
        # non-dividing single axis drops to None
        assert lay.prune(P("fsdp"), (3,), mesh3d) == P()

    def test_resolve_warns_unmatched_and_replicates(self):
        lay = SpecLayout()
        with pytest.warns(UserWarning, match="REPLICATED"):
            out = lay.resolve({"conv.kernel": (3, 3, 8, 8),
                               "fc1.weight": (4, 8)})
        assert out["conv.kernel"] == P()
        assert out["fc1.weight"] == P("fsdp", "tp")

    @needs8
    def test_batch_axes(self):
        lay = SpecLayout()
        dp = lay.batch_axes(build_mesh({"dp": 8}))
        assert dp == "dp" and isinstance(dp, str)  # PR-4 call shape
        assert lay.batch_axes(build_mesh(MESH3D)) == ("dp", "fsdp")
        assert lay.batch_axes(build_mesh({"fsdp": 4, "tp": 2})) == ("fsdp",)


# -- engine resolution + opt slots -------------------------------------------
@needs8
class TestEngineLayoutResolution:
    def test_unmatched_param_warns_and_replicates(self):
        paddle.seed(0)
        net = paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                                   paddle.nn.ReLU(),
                                   paddle.nn.Linear(8, 2))
        model = Model(net)
        model.prepare(
            paddle.optimizer.Adam(learning_rate=0.01,
                                  parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        eng = TrainEngine(model)
        with pytest.warns(UserWarning, match="REPLICATED"):
            eng.begin(mesh=MESH3D, layout=SpecLayout())
        # "0.weight" matches no table pattern → replicated
        assert eng._state_sharding["trainable"]["0.weight"].spec == P()
        eng.finish()

    def test_matched_params_and_slots_shard(self):
        eng = TrainEngine(_model()).begin(mesh=MESH3D, layout=SpecLayout())
        sh = eng._state_sharding
        assert sh["trainable"]["fc1.weight"].spec == P("fsdp", "tp")
        assert sh["trainable"]["fc2.weight"].spec == P("tp", "fsdp")
        # ZeRO: Adam moments live on their param's shards
        for slot in ("moment1", "moment2"):
            assert sh["opt"]["fc1.weight"][slot].spec == P("fsdp", "tp")
        eng.finish()

    def test_scalar_and_one_elem_slots_replicate(self):
        """Regression pin (PR-4 satellite): the shapes-match slot
        heuristic must never pin a scalar/1-element slot — even when
        shapes coincide with a 1-element param's."""
        eng = TrainEngine(_model()).begin(mesh=MESH3D, layout=SpecLayout())
        raw = {
            "trainable": {"fc1.weight": np.zeros((4, 8), np.float32),
                          "gain": np.zeros((1,), np.float32)},
            "frozen": {}, "buffers": {},
            "opt": {"fc1.weight": {"moment1": np.zeros((4, 8), np.float32),
                                   "beta1_pow": np.zeros((), np.float32)},
                    "gain": {"moment1": np.zeros((1,), np.float32)}},
            "lr": np.float32(0.0), "step": np.int32(0),
        }
        eng._sharding_rule = \
            lambda name, p: P("fsdp") if name == "gain" else None
        sh = eng._build_state_sharding(raw)
        assert sh["trainable"]["fc1.weight"].spec != P()
        assert sh["opt"]["fc1.weight"]["moment1"].spec == \
            sh["trainable"]["fc1.weight"].spec
        assert sh["opt"]["fc1.weight"]["beta1_pow"].spec == P()
        # shapes match ((1,) == (1,)) but 1-element slots still replicate
        assert sh["trainable"]["gain"].spec == P("fsdp")
        assert sh["opt"]["gain"]["moment1"].spec == P()
        eng.finish()

    def test_dp_only_keeps_pr4_step_path(self, monkeypatch):
        """Bitwise-compat guard: without layout/remat/accum the engine
        must compile the UNCHANGED PR-4 step (same builder, bare-string
        'dp' batch axis → identical shard_batch spec and jit keys)."""
        def boom(self):
            raise AssertionError("featured step built on the default path")

        monkeypatch.setattr(TrainEngine, "_build_featured_step", boom)
        eng = TrainEngine(_model()).begin(mesh={"dp": 8})
        assert eng.batch_axes == "dp" and isinstance(eng.batch_axes, str)
        eng.finish()
        with pytest.raises(AssertionError, match="featured step"):
            TrainEngine(_model()).begin(mesh=MESH3D, layout=SpecLayout())


# -- parity ------------------------------------------------------------------
@needs8
class TestParity3D:
    @staticmethod
    def _per_step(mesh=None, steps=4, B=16, **begin_kw):
        paddle.seed(0)
        model = _model()
        rs = np.random.RandomState(7)
        x = rs.randn(steps * B, 4).astype("float32")
        y = (x.sum(1) > 0).astype("int64")
        eng = TrainEngine(model).begin(mesh=mesh, **begin_kw)
        model.network.train()
        for i in range(steps):
            lo, hi = i * B, (i + 1) * B
            eng.step([paddle.to_tensor(x[lo:hi])],
                     [paddle.to_tensor(y[lo:hi])])
        losses = eng.drain()
        eng.finish()
        return losses, _weights(model)

    def test_3d_meshes_match_dp8_to_ulp(self):
        """SAME global batch on dp8 (replicated params), dp2×fsdp2×tp2
        and dp2×fsdp4 (layout-sharded params + opt): per-step losses and
        final weights agree to f32 ULP-scale tolerances — sharding
        relocates the math, it must not change it."""
        l_dp, w_dp = self._per_step(mesh={"dp": 8})
        l_3d, w_3d = self._per_step(mesh=MESH3D, layout=SpecLayout())
        l_f4, w_f4 = self._per_step(mesh=MESH_F4, layout=SpecLayout())
        assert len(l_dp) == len(l_3d) == len(l_f4) == 4
        np.testing.assert_allclose(l_dp, l_3d, rtol=2e-5, atol=1e-6)
        np.testing.assert_allclose(l_dp, l_f4, rtol=2e-5, atol=1e-6)
        for k in w_dp:
            np.testing.assert_allclose(w_dp[k], w_3d[k], rtol=1e-4,
                                       atol=1e-6, err_msg=k)
            np.testing.assert_allclose(w_dp[k], w_f4[k], rtol=1e-4,
                                       atol=1e-6, err_msg=k)

    def test_accum4_matches_accum1(self):
        """fit(accum_steps=4): mean-of-means over 4 equal microbatches
        inside the scan ≡ the one full-batch step (up to float
        reassociation) — losses AND updated weights."""
        l1, w1 = self._per_step()                      # PR-4 path
        l4, w4 = self._per_step(accum_steps=4)         # featured path
        np.testing.assert_allclose(l1, l4, rtol=1e-5, atol=1e-6)
        for k in w1:
            np.testing.assert_allclose(w1[k], w4[k], rtol=1e-4,
                                       atol=1e-6, err_msg=k)

    def test_accum4_on_3d_mesh_matches_dp8(self):
        l_dp, w_dp = self._per_step(mesh={"dp": 8})
        l_a, w_a = self._per_step(mesh=MESH3D, layout=SpecLayout(),
                                  accum_steps=4, recompute="dots")
        np.testing.assert_allclose(l_dp, l_a, rtol=2e-5, atol=1e-6)
        for k in w_dp:
            np.testing.assert_allclose(w_dp[k], w_a[k], rtol=1e-4,
                                       atol=1e-6, err_msg=k)

    def test_recompute_is_numerically_invisible(self):
        """Remat re-runs the identical forward ops in backward — the
        losses must match the no-remat run exactly-ish (same reduction
        shapes, no reassociation introduced)."""
        l0, w0 = self._per_step()
        lr_, wr = self._per_step(recompute="dots")
        np.testing.assert_allclose(l0, lr_, rtol=2e-6, atol=1e-7)
        for k in w0:
            np.testing.assert_allclose(w0[k], wr[k], rtol=1e-5,
                                       atol=1e-7, err_msg=k)

    def test_fit_loop_3d(self):
        """The whole fit() wiring: layout/recompute/accum kwargs reach
        the engine, the loader placement splits over ('dp','fsdp'),
        history matches a dp8 fit."""
        ma = _model()
        ha = ma.fit(_dataset(), batch_size=8, epochs=2, shuffle=False,
                    verbose=0, mesh={"dp": 8})
        mb = _model()
        hb = mb.fit(_dataset(), batch_size=8, epochs=2, shuffle=False,
                    verbose=0, mesh=MESH3D, layout=True,
                    recompute="dots", accum_steps=2)
        np.testing.assert_allclose(ha["loss"], hb["loss"], rtol=2e-5,
                                   atol=1e-6)
        wa, wb = _weights(ma), _weights(mb)
        for k in wa:
            np.testing.assert_allclose(wa[k], wb[k], rtol=1e-4,
                                       atol=1e-6, err_msg=k)


# -- donation + HLO ----------------------------------------------------------
@needs8
class TestFeaturedStepMechanics:
    def test_no_silent_donation_fallback_3d_remat_accum(self):
        """The featured step (layout + remat + scan accumulation) must
        keep the donation contract: every pre-step state leaf consumed,
        zero fallback warnings."""
        eng = TrainEngine(_model()).begin(
            mesh=MESH3D, layout=SpecLayout(), recompute="dots",
            accum_steps=2)
        refs = [v for tree in (eng.state["trainable"], eng.state["opt"],
                               eng.state["buffers"])
                for v in jax.tree_util.tree_leaves(tree)]
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(16, 4).astype("float32"))
        y = paddle.to_tensor(rs.randint(0, 2, (16,)).astype("int64"))
        with warnings.catch_warnings():
            warnings.filterwarnings("error", message=".*donated buffers.*")
            eng.step([x], [y])
        undonated = [v for v in refs if not v.is_deleted()]
        assert not undonated, f"{len(undonated)} state buffers survived " \
                              "the donated dispatch (silent fallback)"
        assert all(np.isfinite(v) for v in eng.drain())
        eng.finish()

    def test_hlo_has_fsdp_gather_alongside_dp_all_reduce(self):
        """The acceptance HLO shape: param all-gather (fsdp resharding)
        AND the data-parallel grad all-reduce in ONE partitioned step."""
        eng = TrainEngine(_model()).begin(mesh=MESH3D, layout=SpecLayout())
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(16, 4).astype("float32"))
        y = paddle.to_tensor(rs.randint(0, 2, (16,)).astype("int64"))
        text = eng.lower_step([x], [y]).compile().as_text()
        eng.finish()
        assert "all-gather" in text or "reduce-scatter" in text, \
            "no fsdp collective in the 3D step HLO"
        assert "all-reduce" in text, "no grad all-reduce in the 3D step HLO"

    def test_microbatch_split(self):
        tree = {"x": np.arange(24).reshape(12, 2)}
        out = layout_mod.microbatch_split(tree, 4)
        assert out["x"].shape == (4, 3, 2)
        np.testing.assert_array_equal(np.asarray(out["x"]).reshape(12, 2),
                                      np.arange(24).reshape(12, 2))
        with pytest.raises(ValueError, match="not divisible"):
            layout_mod.microbatch_split({"x": np.zeros((10, 2))}, 4)

    def test_bad_recompute_policy_fails_eagerly(self):
        with pytest.raises(ValueError, match="unknown recompute policy"):
            TrainEngine(_model()).begin(recompute="dotz")

    def test_bad_accum_steps_rejected(self):
        with pytest.raises(ValueError, match="accum_steps"):
            TrainEngine(_model()).begin(accum_steps=0)


# -- the sharded data path ---------------------------------------------------
@needs8
class TestShardBatchTupleAxis:
    def test_tuple_axis_splits_over_product(self):
        mesh = build_mesh(MESH3D)
        x = np.arange(64, dtype=np.float32).reshape(16, 4)
        out = shard_batch([paddle.to_tensor(x)], mesh, axis=("dp", "fsdp"))
        arr = out[0]._value
        assert arr.sharding.spec == P(("dp", "fsdp"))
        assert {s.data.shape for s in arr.addressable_shards} == {(4, 4)}

    def test_indivisible_replicates(self):
        mesh = build_mesh(MESH3D)
        x = np.zeros((6, 4), np.float32)  # 6 % (dp2*fsdp2) != 0
        out = shard_batch([paddle.to_tensor(x)], mesh, axis=("dp", "fsdp"))
        assert out[0]._value.sharding.spec == P()

    def test_string_axis_unchanged(self):
        mesh = build_mesh({"dp": 8})
        x = np.zeros((16, 4), np.float32)
        out = shard_batch([paddle.to_tensor(x)], mesh)
        assert out[0]._value.sharding.spec == P("dp")


# -- elastic any-mesh reshard ------------------------------------------------
@needs8
class TestElasticAnyMesh:
    def test_dp8_to_3d_and_back_ulp(self, tmp_path, caplog):
        """The acceptance round trip: dp8-saved checkpoint restores onto
        dp2×fsdp2×tp2 (layout shardings), trains an epoch, restores back
        onto dp8, and the final weights agree with dp8-throughout to f32
        ULP tolerances."""
        ma = _model()
        ma.fit(_dataset(), batch_size=8, epochs=3, shuffle=False,
               verbose=0, mesh={"dp": 8})
        ref = _weights(ma)

        mb = _model()
        mb.fit(_dataset(), batch_size=8, epochs=1, shuffle=False,
               verbose=0, mesh={"dp": 8}, resume=str(tmp_path))
        mc = _model()
        with caplog.at_level("INFO", logger="paddle_tpu.hapi"):
            mc.fit(_dataset(), batch_size=8, epochs=2, shuffle=False,
                   verbose=0, mesh=MESH3D, layout=True,
                   resume=str(tmp_path))
        out = caplog.text
        assert "ELASTIC resume" in out and "dp=8" in out
        assert "dp2×fsdp2×tp2" in out
        md = _model()
        md.fit(_dataset(), batch_size=8, epochs=3, shuffle=False,
               verbose=0, mesh={"dp": 8}, resume=str(tmp_path))
        got = _weights(md)
        for k in ref:
            np.testing.assert_allclose(got[k], ref[k], rtol=1e-4,
                                       atol=1e-6, err_msg=k)

    def test_dp8_to_3d_restore_is_bitwise(self, tmp_path):
        """The restore itself (before any training) is lossless across
        the mesh change: weights right after the 3D elastic resume equal
        the dp8-saved weights bit for bit."""
        ma = _model()
        ma.fit(_dataset(), batch_size=8, epochs=1, shuffle=False,
               verbose=0, mesh={"dp": 8}, resume=str(tmp_path))
        w8 = _weights(ma)
        mb = _model()
        mb.fit(_dataset(), batch_size=8, epochs=1, shuffle=False,
               verbose=0, mesh=MESH3D, layout=True, resume=str(tmp_path))
        got = _weights(mb)
        for k in w8:
            np.testing.assert_array_equal(got[k], w8[k], err_msg=k)


# -- deprecation routing -----------------------------------------------------
class TestDeprecationRouting:
    def test_sharding_warns_once_and_forwards(self, monkeypatch):
        from paddle_tpu.distributed import sharding as sh

        monkeypatch.setattr(sh, "_deprecation_warned", False)
        with pytest.warns(DeprecationWarning, match="layout"):
            spec = sh.shard_spec((64, 16), "fsdp", 2)
        assert spec == layout_mod.zero_spec((64, 16), "fsdp", 2)
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # second call: silent
            sh.shard_spec((64, 16), "fsdp", 2)

    def test_meta_parallel_warns_once(self, monkeypatch):
        from paddle_tpu.distributed import meta_parallel as mp

        monkeypatch.setattr(mp, "_deprecation_warned", False)
        with pytest.warns(DeprecationWarning, match="layout"):
            mp.param_sharding({})
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            mp.param_sharding({})

    def test_recompute_reexports_layout_impl(self):
        from paddle_tpu.distributed import recompute as rc

        assert rc.POLICIES is layout_mod.POLICIES
        assert rc.remat is layout_mod.remat
        g = jax.grad(rc.checkpoint(lambda x: (x * x).sum(),
                                   policy="dots"))(np.float32(3.0))
        assert float(g) == pytest.approx(6.0)

    def test_grad_merge_reexports_layout_impl(self):
        from paddle_tpu.distributed import grad_merge as gm

        assert gm.split_microbatches is layout_mod.microbatch_split
        assert gm.microbatch_scan is layout_mod.microbatch_scan

    def test_spec_layout_public_export(self):
        import paddle_tpu.distributed as dist

        assert dist.SpecLayout is SpecLayout
