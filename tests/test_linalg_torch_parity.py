"""paddle.linalg parity vs torch.linalg on identical matrices: norms
(vector/fro/inf/axis forms), decompositions up to sign/phase
conventions, solves, and einsum over a matrix of equations."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as paddle  # noqa: E402

rs = np.random.RandomState(47)


def _cmp(pd_out, t_out, atol=1e-5):
    np.testing.assert_allclose(np.asarray(pd_out.numpy()),
                               t_out.numpy(), atol=atol, rtol=1e-4)


A = rs.randn(5, 5).astype(np.float32)
SPD = (A @ A.T + 5 * np.eye(5)).astype(np.float32)
B = rs.randn(5, 3).astype(np.float32)


@pytest.mark.parametrize("p,axis", [
    (2, None), ("fro", None), (1, 1), (np.inf, 1), (2, 0), (1, None),
])
def test_norm_forms(p, axis):
    got = paddle.linalg.norm(paddle.to_tensor(A), p=p, axis=axis)
    if axis is None and p in (1,):  # torch needs explicit dims for p=1
        want = torch.linalg.vector_norm(torch.tensor(A), ord=1)
    elif axis is None:
        want = torch.linalg.norm(torch.tensor(A),
                                 ord="fro" if p == "fro" else None)
    else:
        want = torch.linalg.vector_norm(torch.tensor(A), ord=p, dim=axis)
    _cmp(got, want)


def test_solve_inv_det_slogdet():
    _cmp(paddle.linalg.solve(paddle.to_tensor(SPD), paddle.to_tensor(B)),
         torch.linalg.solve(torch.tensor(SPD), torch.tensor(B)), atol=1e-4)
    _cmp(paddle.linalg.inv(paddle.to_tensor(SPD)),
         torch.linalg.inv(torch.tensor(SPD)), atol=1e-4)
    _cmp(paddle.linalg.det(paddle.to_tensor(SPD)),
         torch.linalg.det(torch.tensor(SPD)), atol=1e-2)
    sign, logdet = paddle.linalg.slogdet(paddle.to_tensor(SPD))
    tsign, tlog = torch.linalg.slogdet(torch.tensor(SPD))
    assert float(sign) == pytest.approx(float(tsign))
    assert float(logdet) == pytest.approx(float(tlog), abs=1e-4)


def test_cholesky_and_reconstruction():
    L = paddle.linalg.cholesky(paddle.to_tensor(SPD))
    Ln = np.asarray(L.numpy())
    np.testing.assert_allclose(Ln @ Ln.T, SPD, atol=1e-4)
    _cmp(L, torch.linalg.cholesky(torch.tensor(SPD)), atol=1e-4)


def test_qr_svd_up_to_convention():
    """Decompositions compare by reconstruction + singular values (sign
    conventions differ legitimately across backends)."""
    q, r = paddle.linalg.qr(paddle.to_tensor(B))
    qn, rn = np.asarray(q.numpy()), np.asarray(r.numpy())
    np.testing.assert_allclose(qn @ rn, B, atol=1e-5)
    np.testing.assert_allclose(qn.T @ qn, np.eye(3), atol=1e-5)

    u, s, vh = paddle.linalg.svd(paddle.to_tensor(B), full_matrices=False)
    np.testing.assert_allclose(
        np.asarray(u.numpy()) @ np.diag(np.asarray(s.numpy()))
        @ np.asarray(vh.numpy()), B, atol=1e-5)
    _cmp(s, torch.linalg.svdvals(torch.tensor(B)), atol=1e-5)


def test_eigh_matches():
    wv, _ = np.linalg.eigh(SPD)
    w, v = paddle.linalg.eigh(paddle.to_tensor(SPD))
    np.testing.assert_allclose(np.asarray(w.numpy()), wv, atol=1e-4)
    vn = np.asarray(v.numpy())
    np.testing.assert_allclose(SPD @ vn, vn * np.asarray(w.numpy()),
                               atol=1e-3)


@pytest.mark.parametrize("eq,shapes", [
    ("ij,jk->ik", [(3, 4), (4, 5)]),
    ("bij,bjk->bik", [(2, 3, 4), (2, 4, 5)]),
    ("ii->", [(5, 5)]),
    ("ii->i", [(5, 5)]),
    ("ij->ji", [(3, 4)]),
    ("ij,ij->", [(3, 4), (3, 4)]),
    ("bsh,hd->bsd", [(2, 3, 4), (4, 6)]),
    ("...ij,...jk->...ik", [(2, 2, 3), (2, 3, 2)]),
    ("ij,kj->ik", [(3, 4), (5, 4)]),
])
def test_einsum_matrix(eq, shapes):
    xs = [rs.randn(*s).astype(np.float32) for s in shapes]
    got = paddle.einsum(eq, *[paddle.to_tensor(x) for x in xs])
    want = np.einsum(eq, *xs)
    np.testing.assert_allclose(np.asarray(got.numpy()), want, atol=1e-5,
                               rtol=1e-4)
