"""Loss-family parity vs torch.nn.functional on identical inputs:
weighted/ignore_index NLL, BCE (probs and logits, weighted), margin
ranking, hinge embedding, cosine embedding, and weighted cross_entropy
— the reduction and masking conventions where implementations drift."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as tF  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402

rs = np.random.RandomState(41)


def _cmp(pd_out, t_out, atol=1e-5):
    np.testing.assert_allclose(np.asarray(pd_out.numpy()),
                               t_out.detach().numpy(), atol=atol,
                               rtol=1e-5)


@pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
def test_nll_weighted_ignore_index(reduction):
    logp = tF.log_softmax(torch.tensor(
        rs.randn(8, 5).astype(np.float32)), dim=-1)
    labels = rs.randint(0, 5, (8,)).astype(np.int64)
    labels[2] = labels[6] = -100  # ignored rows
    w = (rs.rand(5).astype(np.float32) + 0.5)
    got = F.nll_loss(paddle.to_tensor(logp.numpy()),
                     paddle.to_tensor(labels),
                     weight=paddle.to_tensor(w), ignore_index=-100,
                     reduction=reduction)
    want = tF.nll_loss(logp, torch.tensor(labels), torch.tensor(w),
                       ignore_index=-100, reduction=reduction)
    _cmp(got, want)


def test_nll_segmentation_shape_and_degenerates():
    """[N, C, H, W] class-axis-1 form, an ignored row with -inf log-prob
    (must not NaN), and the all-ignored batch (must NaN like torch)."""
    logp4 = tF.log_softmax(torch.tensor(
        rs.randn(2, 4, 3, 5).astype(np.float32)), dim=1)
    lab4 = rs.randint(0, 4, (2, 3, 5)).astype(np.int64)
    lab4[0, 0, 0] = -100
    got = F.nll_loss(paddle.to_tensor(logp4.numpy()),
                     paddle.to_tensor(lab4), ignore_index=-100)
    want = tF.nll_loss(logp4, torch.tensor(lab4), ignore_index=-100)
    _cmp(got, want)

    # -inf log-prob on an IGNORED row stays masked, not NaN
    logp = np.full((3, 2), -0.5, np.float32)
    logp[1, 0] = -np.inf
    lab = np.array([1, -100, 0], np.int64)
    got = F.nll_loss(paddle.to_tensor(logp), paddle.to_tensor(lab),
                     ignore_index=-100)
    assert np.isfinite(float(got))

    # all-ignored batch: 0/0 == NaN, matching torch
    lab_all = np.array([-100, -100, -100], np.int64)
    got = F.nll_loss(paddle.to_tensor(logp), paddle.to_tensor(lab_all),
                     ignore_index=-100)
    want = tF.nll_loss(torch.tensor(logp), torch.tensor(lab_all),
                       ignore_index=-100)
    assert np.isnan(float(got)) and np.isnan(float(want))


@pytest.mark.parametrize("reduction", ["mean", "sum"])
def test_bce_probs_and_logits_weighted(reduction):
    p = rs.rand(6, 4).astype(np.float32) * 0.96 + 0.02
    y = (rs.rand(6, 4) > 0.5).astype(np.float32)
    w = rs.rand(6, 4).astype(np.float32) + 0.5
    got = F.binary_cross_entropy(paddle.to_tensor(p), paddle.to_tensor(y),
                                 weight=paddle.to_tensor(w),
                                 reduction=reduction)
    want = tF.binary_cross_entropy(torch.tensor(p), torch.tensor(y),
                                   torch.tensor(w), reduction=reduction)
    _cmp(got, want)
    z = rs.randn(6, 4).astype(np.float32) * 3
    got = F.binary_cross_entropy_with_logits(
        paddle.to_tensor(z), paddle.to_tensor(y), reduction=reduction)
    want = tF.binary_cross_entropy_with_logits(
        torch.tensor(z), torch.tensor(y), reduction=reduction)
    _cmp(got, want)


def test_margin_and_embedding_losses():
    a = rs.randn(7).astype(np.float32)
    b = rs.randn(7).astype(np.float32)
    y = np.sign(rs.randn(7)).astype(np.float32)
    got = F.margin_ranking_loss(paddle.to_tensor(a), paddle.to_tensor(b),
                                paddle.to_tensor(y), margin=0.3)
    want = tF.margin_ranking_loss(torch.tensor(a), torch.tensor(b),
                                  torch.tensor(y), margin=0.3)
    _cmp(got, want)
    x = rs.randn(7).astype(np.float32)
    got = F.hinge_embedding_loss(paddle.to_tensor(x), paddle.to_tensor(y),
                                 margin=1.0)
    want = tF.hinge_embedding_loss(torch.tensor(x), torch.tensor(y),
                                   margin=1.0)
    _cmp(got, want)
    u = rs.randn(5, 8).astype(np.float32)
    v = rs.randn(5, 8).astype(np.float32)
    yy = np.sign(rs.randn(5)).astype(np.float32)
    got = F.cosine_embedding_loss(paddle.to_tensor(u), paddle.to_tensor(v),
                                  paddle.to_tensor(yy), margin=0.2)
    want = tF.cosine_embedding_loss(torch.tensor(u), torch.tensor(v),
                                    torch.tensor(yy), margin=0.2)
    _cmp(got, want)


def test_cross_entropy_weighted_ignore():
    logits = rs.randn(9, 6).astype(np.float32)
    labels = rs.randint(0, 6, (9,)).astype(np.int64)
    labels[4] = -100
    w = rs.rand(6).astype(np.float32) + 0.5
    got = F.cross_entropy(paddle.to_tensor(logits),
                          paddle.to_tensor(labels),
                          weight=paddle.to_tensor(w), ignore_index=-100)
    want = tF.cross_entropy(torch.tensor(logits), torch.tensor(labels),
                            torch.tensor(w), ignore_index=-100)
    _cmp(got, want)
