"""LR-schedule curve parity vs torch.optim.lr_scheduler: paddle's
scheduler contract evaluates the lr BEFORE the optimizer step of that
epoch (scheduler.step() advances the epoch), so paddle lr at epoch k ==
torch get_last_lr() after k scheduler steps."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as paddle  # noqa: E402

EPOCHS = 25


def _torch_curve(sched_factory):
    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.SGD([p], lr=0.5)
    sched = sched_factory(opt)
    lrs = []
    for _ in range(EPOCHS):
        lrs.append(sched.get_last_lr()[0])
        opt.step()
        sched.step()
    return np.asarray(lrs)


def _paddle_curve(sched):
    lrs = []
    for _ in range(EPOCHS):
        lrs.append(sched())
        sched.step()
    return np.asarray(lrs)


@pytest.mark.parametrize("pd,th", [
    (lambda: paddle.optimizer.lr.StepDecay(0.5, step_size=7, gamma=0.3),
     lambda o: torch.optim.lr_scheduler.StepLR(o, step_size=7, gamma=0.3)),
    (lambda: paddle.optimizer.lr.MultiStepDecay(0.5, [5, 11, 17],
                                                gamma=0.2),
     lambda o: torch.optim.lr_scheduler.MultiStepLR(o, [5, 11, 17],
                                                    gamma=0.2)),
    (lambda: paddle.optimizer.lr.ExponentialDecay(0.5, gamma=0.9),
     lambda o: torch.optim.lr_scheduler.ExponentialLR(o, gamma=0.9)),
    (lambda: paddle.optimizer.lr.CosineAnnealingDecay(0.5, T_max=20),
     lambda o: torch.optim.lr_scheduler.CosineAnnealingLR(o, T_max=20)),
    (lambda: paddle.optimizer.lr.LambdaDecay(
        0.5, lr_lambda=lambda e: 1.0 / (1 + e)),
     lambda o: torch.optim.lr_scheduler.LambdaLR(
        o, lr_lambda=lambda e: 1.0 / (1 + e))),
])
def test_schedule_curve_parity(pd, th):
    np.testing.assert_allclose(_paddle_curve(pd()), _torch_curve(th),
                               rtol=1e-6, atol=1e-9)


def test_reduce_on_plateau_parity():
    losses = [1.0, 0.9, 0.85, 0.85, 0.85, 0.85, 0.84, 0.84, 0.84, 0.84,
              0.84, 0.84, 0.5, 0.5, 0.5, 0.5, 0.5, 0.5, 0.5, 0.5]
    ps = paddle.optimizer.lr.ReduceOnPlateau(0.5, factor=0.1, patience=3)
    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.SGD([p], lr=0.5)
    ts = torch.optim.lr_scheduler.ReduceLROnPlateau(opt, factor=0.1,
                                                    patience=3)
    got, want = [], []
    for lv in losses:
        ps.step(metrics=lv)
        got.append(ps())
        ts.step(lv)
        want.append(opt.param_groups[0]["lr"])
    np.testing.assert_allclose(got, want, rtol=1e-6)
