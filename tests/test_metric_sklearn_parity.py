"""Metric parity vs sklearn (reference metric op analogs: accuracy_op,
auc_op, precision_recall): streamed updates across batches must agree
with sklearn computed on the concatenated stream."""
import numpy as np
import pytest

sklearn = pytest.importorskip("sklearn")
from sklearn import metrics as sk  # noqa: E402

import paddle_tpu as paddle  # noqa: E402

rs = np.random.RandomState(53)


def test_auc_streamed_matches_sklearn():
    m = paddle.metric.Auc(num_thresholds=4095)
    all_p, all_y = [], []
    for _ in range(5):  # stream batches like a fluid eval loop
        y = (rs.rand(200) > 0.6).astype(np.int64)
        logits = rs.randn(200) * 1.2 + y * 1.5
        p = 1 / (1 + np.exp(-logits))
        preds = np.stack([1 - p, p], axis=1).astype(np.float32)
        m.update(preds, y.reshape(-1, 1))
        all_p.append(p)
        all_y.append(y)
    got = m.accumulate()
    want = sk.roc_auc_score(np.concatenate(all_y), np.concatenate(all_p))
    assert got == pytest.approx(want, abs=2e-3)  # binned AUC tolerance


def test_accuracy_matches_sklearn():
    m = paddle.metric.Accuracy()
    all_pred, all_y = [], []
    for _ in range(3):
        y = rs.randint(0, 4, (64,)).astype(np.int64)
        logits = rs.randn(64, 4).astype(np.float32)
        logits[np.arange(64), y] += rs.rand(64) * 2  # some correct
        corr = m.compute(paddle.to_tensor(logits),
                         paddle.to_tensor(y.reshape(-1, 1)))
        m.update(corr)
        all_pred.append(logits.argmax(-1))
        all_y.append(y)
    got = float(np.asarray(m.accumulate()))
    want = sk.accuracy_score(np.concatenate(all_y),
                             np.concatenate(all_pred))
    assert got == pytest.approx(want, abs=1e-6)


def test_precision_recall_match_sklearn():
    p_m = paddle.metric.Precision()
    r_m = paddle.metric.Recall()
    all_s, all_y = [], []
    for _ in range(4):
        y = (rs.rand(100) > 0.5).astype(np.int64)
        s = np.clip(rs.rand(100) * 0.6 + y * 0.3, 0, 1).astype(np.float32)
        p_m.update(s, y)
        r_m.update(s, y)
        all_s.append(s)
        all_y.append(y)
    ys = np.concatenate(all_y)
    preds = (np.concatenate(all_s) > 0.5).astype(np.int64)
    assert float(p_m.accumulate()) == pytest.approx(
        sk.precision_score(ys, preds), abs=1e-6)
    assert float(r_m.accumulate()) == pytest.approx(
        sk.recall_score(ys, preds), abs=1e-6)
