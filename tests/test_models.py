"""Language-model family tests: GPT, BERT, MoE, 3D-hybrid-parallel GPT."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.mesh import build_mesh, mesh_guard
from paddle_tpu.models import GPTConfig, GPTForCausalLM, BertConfig, \
    BertForPretraining
from paddle_tpu.models import gpt_hybrid
from paddle_tpu.nn.layer_base import functional_call, state_pytrees
from paddle_tpu.nn.layer.moe import MoELayer


def _tiny_gpt(**kw):
    base = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                max_position_embeddings=32, dropout=0.0, attn_dropout=0.0)
    base.update(kw)
    return GPTConfig(**base)


class TestGPT:
    def test_recompute_loss_and_grad_parity(self):
        """GPTConfig.recompute wraps each block in jax.checkpoint; loss
        and EVERY per-parameter gradient must match the non-remat model —
        this is the path the full-1.3B single-chip measurement relies on
        (bench.py body_gpt13b)."""
        import jax

        ids_np = np.random.RandomState(1).randint(0, 64, (2, 16))
        results = {}
        for remat in (False, True):
            paddle.seed(3)
            model = GPTForCausalLM(_tiny_gpt(recompute=remat))
            model.train()
            params, buffers = state_pytrees(model)

            def loss_fn(p):
                out, _ = functional_call(
                    model, p, (paddle.to_tensor(ids_np, "int64"),),
                    buffers=buffers, method="loss")
                return out.value if hasattr(out, "value") else out

            loss, grads = jax.value_and_grad(loss_fn)(params)
            results[remat] = (float(loss), grads)
        np.testing.assert_allclose(results[False][0], results[True][0],
                                   rtol=1e-5)
        g0, g1 = results[False][1], results[True][1]
        assert set(g0) == set(g1)
        for name in g0:  # per-leaf: permuted/compensating errors fail
            np.testing.assert_allclose(
                np.asarray(g0[name]), np.asarray(g1[name]),
                rtol=1e-4, atol=1e-6, err_msg=name)

    def test_forward_and_loss(self):
        paddle.seed(0)
        model = GPTForCausalLM(_tiny_gpt())
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 64, (2, 16)), "int64")
        logits = model(ids)
        assert tuple(logits.shape) == (2, 16, 64)
        loss = model.loss(ids)
        assert np.isfinite(float(loss))

    def test_training_reduces_loss(self):
        paddle.seed(0)
        model = GPTForCausalLM(_tiny_gpt())
        model.train()
        params, buffers = state_pytrees(model)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3)
        state = opt.init_pytree(params)
        ids = jnp.asarray(
            np.random.RandomState(1).randint(0, 64, (4, 16)), jnp.int32)

        @jax.jit
        def step(params, state, ids):
            def loss_fn(p):
                out, _ = functional_call(
                    model, p, (paddle.Tensor(ids),),
                    kwargs={"labels": paddle.Tensor(ids)}, buffers=buffers,
                    rng=jax.random.PRNGKey(0))
                return out[1].value

            loss, g = jax.value_and_grad(loss_fn)(params)
            p2, s2 = opt.apply_pytree(params, g, state, lr=1e-3, step=1)
            return p2, s2, loss

        losses = []
        for _ in range(8):
            params, state, loss = step(params, state, ids)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_tensor_parallel_runs_on_mesh(self):
        mesh = build_mesh({"dp": 2, "mp": 4})
        with mesh_guard(mesh):
            paddle.seed(0)
            model = GPTForCausalLM(_tiny_gpt(tensor_parallel=True))
            model.eval()
            params, buffers = state_pytrees(model)
            ids = jnp.asarray(
                np.random.RandomState(0).randint(0, 64, (4, 16)), jnp.int32)

            def fwd(p, ids):
                out, _ = functional_call(model, p, (paddle.Tensor(ids),),
                                         buffers=buffers)
                return out.value

            lowered = jax.jit(fwd).lower(params, ids)
            hlo = lowered.compile().as_text()
            assert "all-reduce" in hlo or "all-gather" in hlo
            out = jax.jit(fwd)(params, ids)
            assert out.shape == (4, 16, 64)


class TestBert:
    def test_pretraining_loss(self):
        paddle.seed(0)
        cfg = BertConfig(vocab_size=100, hidden_size=32, num_layers=2,
                         num_heads=4, intermediate_size=64,
                         max_position_embeddings=32, dropout=0.0)
        model = BertForPretraining(cfg)
        model.eval()
        rs = np.random.RandomState(0)
        ids = paddle.to_tensor(rs.randint(0, 100, (2, 16)), "int64")
        mlm_labels = paddle.to_tensor(
            np.where(rs.rand(2, 16) < 0.15, rs.randint(0, 100, (2, 16)),
                     -100), "int64")
        nsp = paddle.to_tensor(rs.randint(0, 2, (2,)), "int64")
        loss = model.loss(ids, mlm_labels, nsp)
        assert np.isfinite(float(loss))

    def test_ernie_defaults(self):
        from paddle_tpu.models import ErnieModel

        m = ErnieModel(hidden_size=32, num_layers=1, num_heads=4,
                       intermediate_size=64, max_position_embeddings=16,
                       dropout=0.0)
        assert m.cfg.vocab_size == 18000 and m.cfg.type_vocab_size == 4


class TestMoE:
    def test_single_expert_equals_ffn(self):
        paddle.seed(0)
        moe = MoELayer(16, 32, num_experts=1, top_k=1, capacity_factor=8.0)
        moe.eval()
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 8, 16).astype("float32"))
        out = moe(x)
        # reference: the single expert's FFN applied to every token
        xv = x.numpy()
        w1 = np.asarray(moe.w1.value)[0]
        b1 = np.asarray(moe.b1.value)[0]
        w2 = np.asarray(moe.w2.value)[0]
        b2 = np.asarray(moe.b2.value)[0]
        h = xv @ w1 + b1
        h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
        ref = h @ w2 + b2  # gate prob == 1 for a single expert
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)
        assert np.isfinite(float(moe.l_aux))

    def test_top2_shapes_and_aux(self):
        paddle.seed(0)
        moe = MoELayer(16, 32, num_experts=4, top_k=2)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 8, 16).astype("float32"))
        out = moe(x)
        assert tuple(out.shape) == (2, 8, 16)
        assert float(moe.l_aux) >= 0.0

    def test_capacity_drops_tokens(self):
        paddle.seed(0)
        # capacity 1 token per expert: most tokens dropped -> output mostly 0
        moe = MoELayer(8, 16, num_experts=2, top_k=1, capacity_factor=0.01)
        x = paddle.to_tensor(
            np.random.RandomState(2).randn(1, 32, 8).astype("float32"))
        out = moe(x).numpy()
        zero_rows = np.sum(np.all(out == 0.0, axis=-1))
        assert zero_rows >= 28  # 32 tokens, 2 slots


class TestHybridGPT:
    def _dense_reference(self, cfg, params, ids):
        """Single-device forward with the SAME pytree (blocks unstacked)."""
        D = cfg.hidden_size
        eps = cfg.layer_norm_epsilon

        def ln(x, w, b):
            mu = x.mean(-1, keepdims=True)
            var = ((x - mu) ** 2).mean(-1, keepdims=True)
            return (x - mu) * jax.lax.rsqrt(var + eps) * w + b

        x = jnp.take(params["wte"], ids, axis=0) + params["wpe"][:ids.shape[1]]
        b = params["blocks"]
        pp, Lp = b["ln1_w"].shape[:2]
        for s in range(pp):
            for l in range(Lp):  # noqa: E741
                p = {k: v[s, l] for k, v in b.items()}
                h = ln(x, p["ln1_w"], p["ln1_b"])
                qkv = h @ p["wqkv"] + p["bqkv"]
                B, S = qkv.shape[0], qkv.shape[1]
                hd = D // cfg.num_heads
                # head-major qkv layout (see gpt_hybrid._make_block)
                qkv = qkv.reshape(B, S, cfg.num_heads, 3, hd)
                q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
                sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (hd ** 0.5)
                mask = jnp.tril(jnp.ones((S, S), bool))
                sc = jnp.where(mask, sc, -1e30)
                ctx = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(sc, -1), v)
                x = x + ctx.reshape(B, S, D) @ p["wo"] + p["bo"]
                h2 = ln(x, p["ln2_w"], p["ln2_b"])
                x = x + jax.nn.gelu(h2 @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]
        x = ln(x, params["ln_f_w"], params["ln_f_b"])
        logits = x @ params["wte"].T
        logp = jax.nn.log_softmax(logits[:, :-1], -1)
        picked = jnp.take_along_axis(logp, ids[:, 1:, None], -1)[..., 0]
        return -picked.mean()

    def test_loss_and_grads_match_dense(self):
        cfg = _tiny_gpt(hidden_size=16, num_layers=2, num_heads=2,
                        vocab_size=32, max_position_embeddings=16)
        mesh = build_mesh({"dp": 2, "pp": 2, "mp": 2})
        params = gpt_hybrid.init_params(cfg, pp=2, seed=0)
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, 32, (4, 8)), jnp.int32)

        loss_fn = gpt_hybrid.make_loss_fn(cfg, mesh, n_microbatches=2,
                                          remat=False)
        loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params, ids)
        ref_loss, ref_grads = jax.jit(jax.value_and_grad(
            lambda p, i: self._dense_reference(cfg, p, i)))(params, ids)

        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
        flat = gpt_hybrid._flatten(grads)
        flat_ref = gpt_hybrid._flatten(ref_grads)
        for k in flat_ref:
            np.testing.assert_allclose(
                np.asarray(flat[k]), np.asarray(flat_ref[k]),
                rtol=5e-3, atol=1e-4, err_msg=k)

    def test_train_step_runs_sharded(self):
        cfg = _tiny_gpt(hidden_size=16, num_layers=2, num_heads=2,
                        vocab_size=32, max_position_embeddings=16)
        mesh = build_mesh({"dp": 2, "pp": 2, "mp": 2})
        params = gpt_hybrid.init_params(cfg, pp=2, seed=0)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3)
        step, init_state, (p_sh, s_sh, d_sh) = gpt_hybrid.make_train_step(
            cfg, mesh, opt, n_microbatches=2, lr=1e-3)
        params = jax.device_put(params, p_sh)
        state = jax.device_put(init_state(params), s_sh)
        ids = jax.device_put(jnp.asarray(
            np.random.RandomState(0).randint(0, 32, (4, 8)), jnp.int32), d_sh)
        l0 = None
        for i in range(5):
            params, state, loss = step(params, state, ids)
            l0 = float(loss) if l0 is None else l0
        assert float(loss) < l0


class TestS2DStem:
    def test_s2d_stem_matches_standard_resnet(self):
        # exact rewrite (vision/models/resnet.py _s2d_stem_conv): same
        # checkpoint, same outputs
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.vision.models import resnet18

        paddle.seed(0)
        a = resnet18(num_classes=7)
        b = resnet18(num_classes=7, s2d_stem=True)
        b.set_state_dict(a.state_dict())
        a.eval()
        b.eval()
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 3, 64, 64).astype(np.float32))
        np.testing.assert_allclose(np.asarray(a(x).numpy()),
                                   np.asarray(b(x).numpy()),
                                   rtol=1e-4, atol=1e-5)

    def test_norm_buffers_are_f32_under_x64(self):
        # BN running stats created without an explicit dtype became f64
        # whenever x64 is enabled (CPU policy) and poisoned every
        # downstream conv to f64 — the round-3 f64-poisoning bug class
        import paddle_tpu as paddle

        bn = paddle.nn.BatchNorm2D(4)
        assert str(bn._mean.dtype).endswith("float32")
        assert str(bn._variance.dtype).endswith("float32")

    def test_s2d_resnet_exports_and_serves(self, tmp_path):
        # the weight-transform inside forward must trace into the AOT
        # export (StableHLO) and serve identically
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.inference import (load_inference_model,
                                          save_inference_model)
        from paddle_tpu.vision.models import resnet18

        paddle.seed(0)
        net = resnet18(num_classes=4, s2d_stem=True)
        net.eval()
        x = np.random.RandomState(0).randn(1, 3, 32, 32).astype(np.float32)
        prefix = str(tmp_path / "s2drn")
        save_inference_model(prefix, net, example_inputs=[x])
        pred = load_inference_model(prefix)
        out, = pred.run([x])
        expect = np.asarray(net(paddle.to_tensor(x)).numpy())
        np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)
