"""Runtime telemetry suite (`monitor` marker — tools/obs_smoke.sh):

  * utils/metrics.py registry: counter/gauge/histogram/reservoir +
    golden exposition text;
  * serving /metrics BYTE-IDENTICAL regression pin across the registry
    migration;
  * MFU math against a hand-computed flops case;
  * JSONL event-log schema + rotation;
  * MonitorServer /metrics, /healthz, federation;
  * /debug/trace?steps=N and SIGUSR1 arm → bounded jax.profiler capture
    on a RUNNING fit (non-empty trace dir, job keeps training);
  * checkpoint durability counters landing in the shared registry.
"""
import json
import os
import signal
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi.model import Model
from paddle_tpu.io import Dataset
from paddle_tpu.utils.metrics import (MetricsRegistry, Reservoir,
                                      default_registry)

pytestmark = pytest.mark.monitor


# -- helpers ----------------------------------------------------------------
class _DS(Dataset):
    def __init__(self, n=48, d=8):
        self.n, self.d = n, d

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rs = np.random.RandomState(i)
        return (rs.randn(self.d).astype("float32"),
                rs.randn(1).astype("float32"))


def _model():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    m = Model(net)
    m.prepare(paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters()),
              nn.MSELoss())
    return m


@pytest.fixture
def monitored(tmp_path):
    """A fresh monitor singleton bound to a tmp telemetry dir + an
    ephemeral port; restores the flags and tears the singleton down."""
    from paddle_tpu import monitor
    from paddle_tpu.framework import flags

    prev = flags.get_flags(["FLAGS_telemetry_dir", "FLAGS_monitor_port"])
    monitor.reset()
    flags.set_flags({"FLAGS_telemetry_dir": str(tmp_path / "telemetry"),
                     "FLAGS_monitor_port": 0})
    try:
        yield tmp_path / "telemetry"
    finally:
        monitor.reset()
        flags.set_flags(prev)


def _scrape(url):
    return urllib.request.urlopen(url, timeout=5).read().decode()


# -- registry ---------------------------------------------------------------
class TestRegistry:
    def test_counter_gauge_histogram_render_golden(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total", "outcomes", label="kind",
                        preset=("a", "b"))
        g = reg.gauge("t_gauge", "a gauge")
        h = reg.histogram("t_ms", "a histogram", [1, 10])
        c.inc("a", 2)
        g.set(2.5)
        for v in (0.5, 5.0, 50.0):
            h.observe(v)
        assert reg.prometheus_text() == (
            "# HELP t_total outcomes\n"
            "# TYPE t_total counter\n"
            't_total{kind="a"} 2\n'
            't_total{kind="b"} 0\n'
            "# HELP t_gauge a gauge\n"
            "# TYPE t_gauge gauge\n"
            "t_gauge 2.5\n"
            "# HELP t_ms a histogram\n"
            "# TYPE t_ms histogram\n"
            't_ms_bucket{le="1"} 1\n'
            't_ms_bucket{le="10"} 2\n'
            't_ms_bucket{le="+Inf"} 3\n'
            "t_ms_sum 55.5\n"
            "t_ms_count 3\n")

    def test_get_or_create_returns_same_metric(self):
        reg = MetricsRegistry()
        assert reg.counter("x") is reg.counter("x")
        assert reg.gauge("y") is reg.gauge("y")
        assert reg.histogram("z", buckets=[1]) is reg.histogram("z")

    def test_unlabeled_counter_and_computed_gauge(self):
        reg = MetricsRegistry()
        c = reg.counter("n_total", "n")
        c.inc()
        c.inc(4)
        assert c.get() == 5
        reg.gauge("computed", "fn-backed", fn=lambda: 7)
        assert "computed 7" in reg.prometheus_text()

    def test_fixed_counter_hides_extra_series_but_tracks_them(self):
        reg = MetricsRegistry()
        c = reg.counter("f_total", "f", label="r", preset=("a",),
                        fixed=True)
        c.inc("a")
        c.inc("surprise")
        text = reg.prometheus_text()
        assert 'f_total{r="a"} 1' in text
        assert "surprise" not in text
        assert c.get("surprise") == 1

    def test_reservoir_quantiles_are_exact_order_stats(self):
        r = Reservoir(size=100)
        for v in range(1, 101):
            r.observe(float(v))
        assert r.quantile(0.0) == 1.0
        assert r.quantile(0.50) == pytest.approx(50.0, abs=1.0)
        assert r.quantile(1.0) == 100.0
        # bounded window: old observations age out
        for v in range(1000, 1100):
            r.observe(float(v))
        assert r.quantile(0.0) >= 1000.0

    def test_snapshot(self):
        reg = MetricsRegistry()
        reg.counter("a_total", label="k", preset=("x",)).inc("x", 3)
        reg.gauge("b").set(1.5)
        reg.histogram("c", buckets=[1]).observe(2.0)
        snap = reg.snapshot()
        assert snap["a_total"] == {"x": 3}
        assert snap["b"] == 1.5
        assert snap["c"]["count"] == 1 and snap["c"]["mean"] == 2.0


# -- serving byte-identical regression pin ----------------------------------
SERVING_GOLDEN_HEAD = """\
# HELP paddle_serving_qps completed requests per second over the trailing window
# TYPE paddle_serving_qps gauge
paddle_serving_qps 0
# HELP paddle_serving_p50_ms request latency p50 in milliseconds
# TYPE paddle_serving_p50_ms gauge
paddle_serving_p50_ms 0
# HELP paddle_serving_p99_ms request latency p99 in milliseconds
# TYPE paddle_serving_p99_ms gauge
paddle_serving_p99_ms 0
# HELP paddle_serving_padding_waste_ratio padded input elements / dispatched input elements (batch-slot AND sequence padding)
# TYPE paddle_serving_padding_waste_ratio gauge
paddle_serving_padding_waste_ratio 0.25
# HELP paddle_serving_compile_count predictor shape-bucket compilations since start
# TYPE paddle_serving_compile_count gauge
paddle_serving_compile_count 5
# HELP paddle_serving_requests_total request outcomes by result
# TYPE paddle_serving_requests_total counter
paddle_serving_requests_total{result="accepted"} 3
paddle_serving_requests_total{result="responses"} 0
paddle_serving_requests_total{result="rejected_queue_full"} 1
paddle_serving_requests_total{result="rejected_draining"} 0
paddle_serving_requests_total{result="deadline_expired"} 0
paddle_serving_requests_total{result="cancelled"} 0
paddle_serving_requests_total{result="errors"} 0
# HELP paddle_serving_batch_size requests coalesced per dispatched batch
# TYPE paddle_serving_batch_size histogram
paddle_serving_batch_size_bucket{le="1"} 0
paddle_serving_batch_size_bucket{le="2"} 1
paddle_serving_batch_size_bucket{le="4"} 2
paddle_serving_batch_size_bucket{le="8"} 2
paddle_serving_batch_size_bucket{le="16"} 2
paddle_serving_batch_size_bucket{le="32"} 2
paddle_serving_batch_size_bucket{le="64"} 2
paddle_serving_batch_size_bucket{le="128"} 2
paddle_serving_batch_size_bucket{le="+Inf"} 2
paddle_serving_batch_size_sum 5
paddle_serving_batch_size_count 2
# HELP paddle_serving_queue_latency_ms milliseconds a request waited in the batch queue
# TYPE paddle_serving_queue_latency_ms histogram
paddle_serving_queue_latency_ms_bucket{le="0.5"} 0
paddle_serving_queue_latency_ms_bucket{le="1"} 0
paddle_serving_queue_latency_ms_bucket{le="2"} 1
paddle_serving_queue_latency_ms_bucket{le="5"} 1
paddle_serving_queue_latency_ms_bucket{le="10"} 1
paddle_serving_queue_latency_ms_bucket{le="20"} 1
paddle_serving_queue_latency_ms_bucket{le="50"} 1
paddle_serving_queue_latency_ms_bucket{le="100"} 1
paddle_serving_queue_latency_ms_bucket{le="250"} 1
paddle_serving_queue_latency_ms_bucket{le="500"} 1
paddle_serving_queue_latency_ms_bucket{le="1000"} 1
paddle_serving_queue_latency_ms_bucket{le="5000"} 1
paddle_serving_queue_latency_ms_bucket{le="+Inf"} 1
paddle_serving_queue_latency_ms_sum 1.2
paddle_serving_queue_latency_ms_count 1
# HELP paddle_serving_request_latency_ms end-to-end request latency in milliseconds
# TYPE paddle_serving_request_latency_ms histogram
paddle_serving_request_latency_ms_bucket{le="1"} 0
paddle_serving_request_latency_ms_bucket{le="2"} 0
paddle_serving_request_latency_ms_bucket{le="5"} 0
paddle_serving_request_latency_ms_bucket{le="10"} 0
paddle_serving_request_latency_ms_bucket{le="20"} 0
paddle_serving_request_latency_ms_bucket{le="50"} 0
paddle_serving_request_latency_ms_bucket{le="100"} 0
paddle_serving_request_latency_ms_bucket{le="250"} 0
paddle_serving_request_latency_ms_bucket{le="500"} 0
paddle_serving_request_latency_ms_bucket{le="1000"} 0
paddle_serving_request_latency_ms_bucket{le="5000"} 0
paddle_serving_request_latency_ms_bucket{le="+Inf"} 0
paddle_serving_request_latency_ms_sum 0
paddle_serving_request_latency_ms_count 0
"""


class TestServingExpositionPin:
    def test_byte_identical_after_registry_migration(self):
        """The golden text was captured from the PRE-migration
        serving/metrics.py on this deterministic scenario; the
        registry-backed implementation must reproduce it byte for
        byte."""
        from paddle_tpu.serving.metrics import ServingMetrics

        m = ServingMetrics()
        m.count("accepted", 3)
        m.count("rejected_queue_full")
        m.observe_batch(3, 4)
        m.observe_batch(2, 4, real_elems=6, total_elems=8)
        m.observe_queue_wait(0.0012)
        m.set_compile_count(5)
        assert m.prometheus_text() == SERVING_GOLDEN_HEAD

    def test_counters_attribute_still_dictlike(self):
        from paddle_tpu.serving.metrics import ServingMetrics

        m = ServingMetrics()
        m.count("errors")
        assert m.counters["errors"] == 1
        assert m.counters["accepted"] == 0
        assert m.snapshot()["errors"] == 1


# -- MFU + memory meters ----------------------------------------------------
class TestMfuAndMeters:
    def test_mfu_hand_computed(self, tmp_path):
        """4 steps of a 2 GFLOP step in 2.0 s on a 1 TFLOP/s device:
        MFU = 2e9 * 4 / 2.0 / 1e12 = 0.004 exactly."""
        from paddle_tpu.monitor import TrainTelemetry

        t = TrainTelemetry(telemetry_dir=str(tmp_path))
        t.set_flops_per_step(2e9, peak=1e12)
        rec = t.window(step=4, epoch=0, steps=4, wall_s=2.0, batch_size=8,
                       loss=1.0, lr=0.1)
        assert rec["mfu"] == pytest.approx(0.004)
        assert t.g_mfu.get() == pytest.approx(0.004)
        assert rec["samples_per_sec"] == pytest.approx(16.0)
        t.close()

    def test_mfu_zero_without_flops(self, tmp_path):
        from paddle_tpu.monitor import TrainTelemetry

        t = TrainTelemetry(telemetry_dir=str(tmp_path))
        rec = t.window(step=1, epoch=0, steps=1, wall_s=0.1, batch_size=8)
        assert rec["mfu"] == 0.0
        t.close()

    def test_first_step_interval_lands_in_gauge_not_histogram(self, tmp_path):
        """With mark_start() anchored before the first dispatch, the
        FIRST measured interval (the compile-bearing one) goes to
        paddle_train_first_step_ms and later steps to the histogram
        (review fix: the compile interval was discarded and step 2
        mislabeled as the first)."""
        import time as _time

        from paddle_tpu.monitor import TrainTelemetry

        t = TrainTelemetry(telemetry_dir=str(tmp_path))
        t.on_fit_begin()
        before = t.h_step.total
        t.mark_start()
        _time.sleep(0.05)  # the "compile"
        t.step_mark()
        for _ in range(3):
            t.step_mark()
        assert t.g_first_step_ms.get() >= 45.0, \
            "compile interval missing from first-step gauge"
        assert t.h_step.total - before == 3, \
            "steady-state steps miscounted in the histogram"
        t.close()

    def test_warning_hook_counts_every_repeat(self, tmp_path):
        """Python's default filter dedups same-location warnings before
        showwarning — the donation counter must still count every
        occurrence (review fix), while the console sees it once."""
        import warnings

        from paddle_tpu.monitor import TrainTelemetry

        t = TrainTelemetry(telemetry_dir=str(tmp_path))
        before = t.c_donation_fallback.get()
        restore = t.install_warning_hook()
        try:
            for _ in range(5):
                warnings.warn("Some donated buffers were not usable",
                              UserWarning)
        finally:
            restore()
        assert t.c_donation_fallback.get() - before == 5
        # restore() puts the filter stack back: the same warning no
        # longer reaches the (restored) hook chain for counting
        warnings.warn("Some donated buffers were not usable", UserWarning)
        assert t.c_donation_fallback.get() - before == 5
        t.close()

    def test_device_memory_stats_graceful_none(self):
        """CPU backend has no memory_stats — the meter must answer None,
        not crash or fake zeros."""
        from paddle_tpu.monitor import device_memory_stats

        stats = device_memory_stats()
        assert stats is None or "bytes_in_use" in stats

    def test_peak_flops_flag_override(self):
        from paddle_tpu.framework import flags
        from paddle_tpu.monitor import peak_flops_per_device

        prev = flags.get_flags(["FLAGS_device_peak_flops"])
        try:
            flags.set_flags({"FLAGS_device_peak_flops": 123.0})
            assert peak_flops_per_device() == 123.0
        finally:
            flags.set_flags(prev)

    def test_engine_cost_analysis_reports_flops(self):
        """The number the MFU gauge is built on: the compiled train
        step's XLA cost analysis carries a positive 'flops'."""
        m = _model()
        eng = m._engine or None
        from paddle_tpu.hapi.engine import TrainEngine

        eng = TrainEngine(m).begin()
        x = paddle.to_tensor(np.zeros((8, 8), "float32"))
        y = paddle.to_tensor(np.zeros((8, 1), "float32"))
        ca = eng.step_cost_analysis([x], [y])
        assert ca.get("flops", 0) > 0


# -- JSONL event log --------------------------------------------------------
class TestJsonl:
    def test_schema_and_rotation(self, tmp_path):
        from paddle_tpu.monitor import JsonlWriter

        w = JsonlWriter(str(tmp_path), rotate_mb=0.004, keep=3)
        for i in range(400):
            w.write({"event": "window", "step": i, "loss": 0.5})
        w.close()
        files = sorted(os.listdir(tmp_path))
        assert "events.jsonl" in files
        rotated = [f for f in files if f.startswith("events.jsonl.")]
        assert rotated, "rotation never happened"
        assert len(rotated) <= 3, f"rotation unbounded: {files}"
        # every line of every segment is valid JSON with the schema keys
        for f in files:
            for line in open(tmp_path / f):
                rec = json.loads(line)
                assert rec["event"] == "window" and "step" in rec

    def test_fit_event_stream_schema(self, monitored):
        m = _model()
        m.fit(_DS(), batch_size=8, epochs=1, log_freq=2, verbose=0)
        lines = [json.loads(x)
                 for x in open(monitored / "events.jsonl")]
        events = [x["event"] for x in lines]
        assert events[0] == "fit_begin" and events[-1] == "fit_end"
        windows = [x for x in lines if x["event"] == "window"]
        assert windows, "no step windows emitted"
        w = windows[-1]
        for key in ("ts", "step", "epoch", "steps", "samples_per_sec",
                    "step_ms_mean", "mfu", "loss", "lr", "phase_ms",
                    "mem"):
            assert key in w, f"window record missing {key}: {w}"
        assert {"data", "dispatch", "sync"} <= set(w["phase_ms"])
        assert w["samples_per_sec"] > 0
        # MFU is nonzero: XLA cost analysis + the nominal CPU peak
        assert w["mfu"] > 0
        # windows cover every dispatched step exactly once
        assert sum(x["steps"] for x in windows) == 6  # 48/8 per epoch


# -- HTTP surface -----------------------------------------------------------
class TestMonitorServer:
    def test_metrics_healthz_and_404(self, monitored):
        from paddle_tpu import monitor

        m = _model()
        m.fit(_DS(), batch_size=8, epochs=1, verbose=0)
        srv = monitor.get_monitor_server()
        assert srv is not None
        body = _scrape(srv.url + "/metrics")
        for want in ("paddle_train_mfu", "paddle_train_step_ms",
                     "paddle_train_samples_per_sec",
                     "paddle_train_step_time_p50_ms",
                     "paddle_train_step_time_p99_ms"):
            assert want in body, want
        h = json.loads(_scrape(srv.url + "/healthz"))
        assert h["status"] == "ok" and h["step"] == 6
        with pytest.raises(urllib.error.HTTPError) as e:
            _scrape(srv.url + "/nope")
        assert e.value.code == 404

    def test_debug_trace_requires_steps(self, monitored):
        from paddle_tpu import monitor

        monitor.fit_monitor()
        srv = monitor.get_monitor_server()
        with pytest.raises(urllib.error.HTTPError) as e:
            _scrape(srv.url + "/debug/trace")
        assert e.value.code == 400

    def test_federation_merges_rank_bodies(self):
        from paddle_tpu.monitor import MonitorServer

        rank_reg = MetricsRegistry()
        rank_reg.gauge("rank_only_gauge", "from the rank").set(42)
        with MonitorServer(registry=rank_reg, port=0) as rank_srv:
            rank_url = rank_srv.url
            own = MetricsRegistry()
            own.counter("launcher_counter").inc()
            with MonitorServer(registry=own, port=0,
                               federate=[rank_url]) as fed:
                body = _scrape(fed.url + "/metrics")
        assert "launcher_counter 1" in body
        assert f"# federated from {rank_url}/metrics" in body
        assert "rank_only_gauge 42" in body

    def test_federation_assigned_after_construction_still_counts(self):
        """The launcher assigns .federate AFTER construction (the rank
        ports derive from the bound port) — the error counter must
        still register and increment (review fix: it was created only
        when federate was non-empty at __init__)."""
        from paddle_tpu.monitor import MonitorServer

        own = MetricsRegistry()
        with MonitorServer(registry=own, port=0,
                           fetch_timeout_s=0.3) as fed:
            fed.federate = ["http://127.0.0.1:9"]
            body = _scrape(fed.url + "/metrics")
        assert "FETCH FAILED" in body
        assert own.counter(
            "paddle_monitor_federation_errors_total").get() == 1

    def test_federation_dead_ranks_cost_one_timeout_not_n(self):
        """N dead ranks fetch concurrently: the scrape must not take
        N x fetch_timeout_s (a pod scrape blowing the scraper deadline
        loses the healthy launcher counters too)."""
        import time as _time

        from paddle_tpu.monitor import MonitorServer

        dead = [f"http://127.0.0.1:{p}" for p in (9, 10, 11, 12, 13, 14)]
        own = MetricsRegistry()
        with MonitorServer(registry=own, port=0, federate=dead,
                           fetch_timeout_s=1.0) as fed:
            t0 = _time.monotonic()
            body = _scrape(fed.url + "/metrics")
            elapsed = _time.monotonic() - t0
        assert body.count("FETCH FAILED") == 6
        assert elapsed < 4.0, \
            f"6 dead ranks took {elapsed:.1f}s — fetches are sequential"

    def test_federation_survives_dead_rank(self):
        from paddle_tpu.monitor import MonitorServer

        own = MetricsRegistry()
        with MonitorServer(registry=own, port=0,
                           federate=["http://127.0.0.1:9"],
                           fetch_timeout_s=0.3) as fed:
            body = _scrape(fed.url + "/metrics")
        assert "FETCH FAILED" in body
        assert own.counter(
            "paddle_monitor_federation_errors_total").get() == 1

    def test_concurrent_scrapes_with_slow_rank_no_convoy(self):
        """One SLOW federated rank must not convoy the monitor: while
        N scrapes sit in its fetch, /healthz on the same server answers
        immediately (the rank fetch happens OUTSIDE the registry lock,
        and the HTTP server threads per request), and the N scrapes
        overlap on the slow rank instead of serializing behind it."""
        import threading
        import time as _time
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from paddle_tpu.monitor import MonitorServer

        class _SlowRank(BaseHTTPRequestHandler):
            def do_GET(self):
                _time.sleep(1.2)
                body = b"slow_rank_gauge 7\n"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        rank_httpd = ThreadingHTTPServer(("127.0.0.1", 0), _SlowRank)
        rank_httpd.daemon_threads = True
        threading.Thread(target=rank_httpd.serve_forever,
                         daemon=True).start()
        rank_url = "http://127.0.0.1:%d" % rank_httpd.server_address[1]
        own = MetricsRegistry()
        own.counter("launcher_counter").inc()
        try:
            with MonitorServer(registry=own, port=0, federate=[rank_url],
                               fetch_timeout_s=5.0) as fed:
                bodies = {}

                def scrape(i):
                    bodies[i] = _scrape(fed.url + "/metrics")

                t0 = _time.monotonic()
                threads = [threading.Thread(target=scrape, args=(i,))
                           for i in range(4)]
                for t in threads:
                    t.start()
                _time.sleep(0.2)   # scrapes are now parked in the fetch
                t1 = _time.monotonic()
                h = json.loads(_scrape(fed.url + "/healthz"))
                healthz_s = _time.monotonic() - t1
                for t in threads:
                    t.join()
                total = _time.monotonic() - t0
        finally:
            rank_httpd.shutdown()
            rank_httpd.server_close()
        assert h["status"] == "ok"
        assert healthz_s < 1.0, \
            f"/healthz took {healthz_s:.2f}s behind a slow rank scrape"
        assert len(bodies) == 4
        for b in bodies.values():
            assert "launcher_counter 1" in b and "slow_rank_gauge 7" in b
        assert total < 3.5, \
            f"4 scrapes of a 1.2s rank took {total:.1f}s — serialized"


# -- on-demand trace capture on a RUNNING fit -------------------------------
def _trace_files(root):
    out = []
    for base, _dirs, files in os.walk(root):
        out.extend(os.path.join(base, f) for f in files)
    return out


class TestTraceCapture:
    def test_debug_trace_captures_running_fit(self, monitored):
        """Arm /debug/trace?steps=2 from a callback DURING the fit (the
        HTTP hit happens while the job is running) and assert a
        non-empty jax.profiler trace directory exists afterwards —
        without the fit restarting or failing."""
        from paddle_tpu import monitor
        from paddle_tpu.hapi.callbacks import Callback

        armed = {}

        class ArmTrace(Callback):
            def on_train_batch_end(self, step, logs=None):
                if step == 1 and not armed:
                    srv = monitor.get_monitor_server()
                    armed.update(json.loads(_scrape(
                        srv.url + "/debug/trace?steps=2")))

        m = _model()
        m.fit(_DS(), batch_size=8, epochs=1, verbose=0,
              callbacks=[ArmTrace()])
        assert armed["armed_steps"] == 2
        files = _trace_files(armed["trace_dir"])
        assert files, f"trace dir {armed['trace_dir']} is empty"
        telem, _srv = monitor.fit_monitor()
        assert telem.c_traces.get() >= 1

    def test_sigusr1_arms_bounded_capture(self, monitored):
        """SIGUSR1 mid-fit (the headless /debug/trace) arms a bounded
        capture that completes on the training thread."""
        from paddle_tpu import monitor
        from paddle_tpu.hapi.callbacks import Callback

        fired = []

        class Kick(Callback):
            def on_train_batch_end(self, step, logs=None):
                if step == 1 and not fired:
                    fired.append(True)
                    os.kill(os.getpid(), signal.SIGUSR1)

        m = _model()
        m.fit(_DS(), batch_size=8, epochs=1, verbose=0,
              callbacks=[Kick()])
        telem, _srv = monitor.fit_monitor()
        assert telem.c_traces.get() >= 1
        assert telem.last_trace_dir and _trace_files(telem.last_trace_dir)

    def test_trace_armed_past_fit_end_still_closes(self, monitored):
        """A capture armed for more steps than remain must be finalized
        at fit exit (valid artifact, profiler not left running)."""
        from paddle_tpu import monitor

        telem, _srv = monitor.fit_monitor()
        m = _model()
        telem.arm_trace(10_000)
        m.fit(_DS(), batch_size=8, epochs=1, verbose=0)
        assert not telem.trace_pending
        assert _trace_files(telem.last_trace_dir)


# -- checkpoint durability counters -----------------------------------------
class TestCheckpointCounters:
    def test_save_restore_quarantine_counters(self, tmp_path):
        from paddle_tpu.distributed.checkpoint import CheckpointManager

        reg = default_registry()
        before = reg.snapshot()
        state = {"w": np.arange(8, dtype=np.float32)}
        with CheckpointManager(str(tmp_path / "ck"), max_to_keep=3) as mgr:
            mgr.save(1, state, force=True)
            mgr.save(2, state, force=True)
            # corrupt the newest committed generation: restore must
            # quarantine it and cascade
            gen2 = mgr._gen_dir(2)
            leaf = next(
                os.path.join(gen2, "leaves", f)
                for f in os.listdir(os.path.join(gen2, "leaves")))
            with open(leaf, "r+b") as f:
                f.write(b"\xff\xff\xff\xff")
            step, back = mgr.restore_latest(template={"w": None})
        assert step == 1
        after = reg.snapshot()
        assert after["paddle_ckpt_saves_total"]["ok"] - \
            before["paddle_ckpt_saves_total"]["ok"] == 2
        assert after["paddle_ckpt_quarantines_total"] - \
            before["paddle_ckpt_quarantines_total"] == 1
        assert after["paddle_ckpt_cascade_depth"] == 1
        assert after["paddle_ckpt_save_ms"]["count"] - \
            before["paddle_ckpt_save_ms"]["count"] == 2
        assert after["paddle_ckpt_restore_ms"]["count"] - \
            before["paddle_ckpt_restore_ms"]["count"] == 1

    def test_fit_ckpt_stall_histogram(self, monitored, tmp_path):
        from paddle_tpu import monitor

        m = _model()
        m.fit(_DS(), batch_size=8, epochs=1, verbose=0,
              resume=str(tmp_path / "ck"), save_dir=str(tmp_path / "ck"),
              checkpoint_interval=2)
        telem, _srv = monitor.fit_monitor()
        assert telem.h_ckpt_stall.total >= 1
        srv = monitor.get_monitor_server()
        assert "paddle_ckpt_step_stall_ms" in _scrape(srv.url + "/metrics")


# -- launcher restart accounting --------------------------------------------
class TestLaunchCounters:
    def test_failure_reasons_preset(self):
        """The restart-reason series exist (zero-valued) from import, so
        dashboards can alert on them before the first failure."""
        from paddle_tpu.distributed import launch  # noqa: F401

        text = default_registry().prometheus_text()
        for reason in ("preempted", "watchdog", "durability", "crash"):
            assert (f'paddle_launch_trainer_failures_total'
                    f'{{reason="{reason}"}}') in text
