"""Native runtime core tests (csrc/core.cc via ctypes) + the subsystems it
backs: flags mirror, monitor, profiler chrome-trace export, ring buffer,
multiprocess DataLoader."""
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.core as core


needs_native = pytest.mark.skipif(not core.available(),
                                  reason="native core unavailable (no g++)")


class TestFlagsMonitor:
    @needs_native
    def test_flag_roundtrip_and_mirror(self):
        try:
            paddle.set_flags({"FLAGS_check_nan_inf": True})
            assert paddle.get_flags(
                "FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] is True
            assert core.flag_get("FLAGS_check_nan_inf") == "True"
        finally:  # leaked True slows every op and once crashed traces
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_stats(self):
        core.stat_reset("t.x")
        core.stat_add("t.x", 3)
        core.stat_add("t.x", 4)
        assert core.stat_get("t.x") == 7
        assert core.stat_list().get("t.x") == 7
        core.stat_reset("t.x")
        assert core.stat_get("t.x") == 0


class TestProfilerTrace:
    @needs_native
    def test_record_event_to_chrome_trace(self, tmp_path):
        from paddle_tpu.utils.profiler import RecordEvent, export_chrome_trace
        core.trace_clear()
        core.profiler_enable(True)
        try:
            with RecordEvent("outer"):
                with RecordEvent("inner"):
                    time.sleep(0.002)
        finally:
            core.profiler_enable(False)
        path = str(tmp_path / "trace.json")
        n = export_chrome_trace(path)
        assert n == 2
        d = json.load(open(path))
        names = {e["name"] for e in d["traceEvents"]}
        assert names == {"outer", "inner"}
        for e in d["traceEvents"]:
            assert e["ph"] == "X" and e["dur"] >= 0
        core.trace_clear()

    @needs_native
    def test_disabled_records_nothing(self):
        core.trace_clear()
        core.profiler_enable(False)
        from paddle_tpu.utils.profiler import RecordEvent
        with RecordEvent("ghost"):
            pass
        assert core.event_count() == 0


class TestRingBuffer:
    def test_producer_consumer(self):
        rb = core.RingBuffer(4, 256)
        N = 50

        def producer():
            for i in range(N):
                assert rb.put(bytes([i % 256]) * (i + 1))
            rb.close()

        t = threading.Thread(target=producer)
        t.start()
        got = 0
        while True:
            try:
                r = rb.get()
            except EOFError:
                break
            payload, release = r
            assert len(payload) == got + 1
            assert payload[0] == got % 256
            release()
            got += 1
        t.join()
        assert got == N

    def test_put_timeout_when_full(self):
        rb = core.RingBuffer(1, 16)
        assert rb.put(b"a")
        assert rb.put(b"b", timeout_ms=50) is False
        rb.close()

    def test_get_timeout_when_empty(self):
        rb = core.RingBuffer(1, 16)
        assert rb.get(timeout_ms=50) is None
        rb.close()

    @needs_native
    def test_oversize_payload_rejected(self):
        rb = core.RingBuffer(1, 8)
        with pytest.raises(ValueError):
            rb.put(b"x" * 9)
        rb.close()

    @needs_native
    def test_destroy_while_reader_blocked(self):
        """Regression (advisor r1/r2): pt_ring_destroy used to delete the
        Ring right after notify_all while a blocked reader re-locks r->mu
        on wakeup — a use-after-free. destroy now drains in-flight callers
        (refcount) before freeing."""
        for _ in range(20):
            rb = core.RingBuffer(2, 16)
            results = []

            def reader(rb=rb, results=results):
                try:
                    results.append(rb.get(timeout_ms=2000))
                except EOFError:
                    results.append("eof")

            ts = [threading.Thread(target=reader) for _ in range(4)]
            for t in ts:
                t.start()
            time.sleep(0.005)  # let readers block inside acquire_read
            rb._lib.pt_ring_destroy(rb._h)  # close+drain+free
            rb._h = -1  # prevent double-destroy in __del__
            for t in ts:
                t.join(timeout=5)
                assert not t.is_alive()
            assert all(r == "eof" or r is None for r in results)


class TestBatchAssemble:
    def test_matches_np_stack(self):
        samples = [np.random.rand(7, 5).astype(np.float32) for _ in range(9)]
        out = core.assemble_batch(samples)
        np.testing.assert_array_equal(out, np.stack(samples))

    def test_mixed_shapes_falls_back(self):
        samples = [np.zeros((2, 2)), np.zeros((3, 2))]
        with pytest.raises(ValueError):
            core.assemble_batch(samples)


class _IotaDataset(paddle.io.Dataset):
    """Module-scope (picklable) so forkserver workers can load it."""

    def __init__(self, n=64):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return (np.full((4, 4), i, np.float32), np.int64(i))


class _PoisonDataset(paddle.io.Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("poison-idx-5")
        return np.zeros(2, np.float32)


class _DieDataset(paddle.io.Dataset):
    def __len__(self):
        return 16

    def __getitem__(self, i):
        import os
        import time as _t
        if i >= 4:
            _t.sleep(0.3)
            os._exit(9)  # simulate segfault/OOM-kill
        return np.zeros(2, np.float32)


def _check_wid_init(wid):
    assert wid in (0, 1)


class TestMultiprocessDataLoader:
    def _dataset(self, n=64):
        return _IotaDataset(n)

    def test_workers_match_single_process(self):
        ds = self._dataset()
        kwargs = dict(batch_size=8, shuffle=False, drop_last=False)
        single = [b for b in paddle.io.DataLoader(ds, num_workers=0,
                                                  **kwargs)]
        multi = [b for b in paddle.io.DataLoader(ds, num_workers=2,
                                                 **kwargs)]
        assert len(single) == len(multi) == 8
        for (x1, y1), (x2, y2) in zip(single, multi):
            np.testing.assert_array_equal(np.asarray(x1.numpy()),
                                          np.asarray(x2.numpy()))
            np.testing.assert_array_equal(np.asarray(y1.numpy()),
                                          np.asarray(y2.numpy()))

    def test_worker_exception_propagates(self):
        dl = paddle.io.DataLoader(_PoisonDataset(), batch_size=2,
                                  num_workers=2)
        with pytest.raises(RuntimeError, match="poison-idx-5"):
            list(dl)

    def test_unpicklable_dataset_falls_back_to_fork(self):
        """Local (unpicklable) datasets still work via fork, with a
        warning recommending module scope."""
        n = 8

        class Local(paddle.io.Dataset):
            def __len__(self):
                return n

            def __getitem__(self, i):
                return np.full((2,), i, np.float32)

        dl = paddle.io.DataLoader(Local(), batch_size=4, num_workers=1)
        with pytest.warns(RuntimeWarning, match="not\\s+picklable"):
            out = list(dl)
        assert len(out) == 2

    def test_forkserver_is_default_for_picklable(self):
        assert paddle.io.DataLoader(
            _IotaDataset(8), batch_size=4,
            num_workers=1)._pick_start_method() in ("forkserver", "spawn")

    def test_tensor_dataset_parity(self):
        """Tensor samples must stack identically with and without workers."""
        xs = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(16, 2))
        ys = paddle.to_tensor(np.arange(16, dtype=np.int64))
        ds = paddle.io.TensorDataset([xs, ys])
        single = list(paddle.io.DataLoader(ds, batch_size=4, num_workers=0))
        multi = list(paddle.io.DataLoader(ds, batch_size=4, num_workers=2))
        assert len(single) == len(multi) == 4
        for (x1, y1), (x2, y2) in zip(single, multi):
            assert tuple(x2.shape) == (4, 2)
            np.testing.assert_array_equal(np.asarray(x1.numpy()),
                                          np.asarray(x2.numpy()))
            np.testing.assert_array_equal(np.asarray(y1.numpy()),
                                          np.asarray(y2.numpy()))

    def test_early_break_shuts_down_workers(self):
        """Abandoning iteration must not leak worker processes."""
        import multiprocessing as mp
        import time as _time
        before = len(mp.active_children())
        dl = paddle.io.DataLoader(self._dataset(), batch_size=4,
                                  num_workers=2)
        for batch in dl:
            break
        deadline = _time.monotonic() + 15
        while _time.monotonic() < deadline:
            if len(mp.active_children()) <= before:
                break
            _time.sleep(0.2)
        assert len(mp.active_children()) <= before, \
            "worker processes leaked after early break"

    def test_dead_worker_raises(self):
        """A worker killed mid-flight must raise, not hang (reference:
        dataloader SIGCHLD watch, fluid/reader.py)."""
        dl = paddle.io.DataLoader(_DieDataset(), batch_size=4,
                                  num_workers=1)
        with pytest.raises(RuntimeError, match="died|failed"):
            list(dl)

    def test_worker_init_fn_called(self):
        # init fn runs in the child; observable effect must come through
        # data, so just assert it doesn't crash the pipeline
        dl = paddle.io.DataLoader(self._dataset(8), batch_size=4,
                                  num_workers=2,
                                  worker_init_fn=_check_wid_init)
        assert len(list(dl)) == 2
