"""nn.Layer system + layer zoo tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_shapes_and_params():
    l = nn.Linear(4, 3)
    assert l.weight.shape == [4, 3]
    assert l.bias.shape == [3]
    out = l(paddle.randn([2, 4]))
    assert out.shape == [2, 3]
    assert len(l.parameters()) == 2


def test_layer_registration_and_state_dict():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 2)

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    net = Net()
    sd = net.state_dict()
    assert set(sd) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}
    # round trip
    sd2 = {k: paddle.zeros(v.shape) for k, v in sd.items()}
    net.set_state_dict(sd2)
    assert float(net.fc1.weight.numpy().sum()) == 0.0


def test_sequential_and_layerlist():
    s = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
    assert s(paddle.randn([1, 4])).shape == [1, 2]
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll) == 3
    assert len(list(ll.parameters())) == 6


def test_conv2d_matches_reference():
    import jax.numpy as jnp

    conv = nn.Conv2D(2, 4, 3, padding=1)
    x = paddle.randn([1, 2, 8, 8])
    out = conv(x)
    assert out.shape == [1, 4, 8, 8]
    # stride + no padding
    conv2 = nn.Conv2D(2, 4, 3, stride=2, padding=0)
    assert conv2(x).shape == [1, 4, 3, 3]
    # groups
    conv3 = nn.Conv2D(4, 4, 3, padding=1, groups=2)
    assert conv3(paddle.randn([1, 4, 5, 5])).shape == [1, 4, 5, 5]


def test_conv2d_transpose():
    deconv = nn.Conv2DTranspose(3, 2, 4, stride=2, padding=1)
    out = deconv(paddle.randn([1, 3, 8, 8]))
    assert out.shape == [1, 2, 16, 16]


def test_pooling():
    x = paddle.randn([1, 2, 8, 8])
    assert nn.MaxPool2D(2, 2)(x).shape == [1, 2, 4, 4]
    assert nn.AvgPool2D(2, 2)(x).shape == [1, 2, 4, 4]
    assert nn.AdaptiveAvgPool2D(1)(x).shape == [1, 2, 1, 1]
    a = np.random.rand(1, 1, 4, 4).astype(np.float32)
    out = nn.AvgPool2D(2, 2)(paddle.to_tensor(a)).numpy()
    ref = a.reshape(1, 1, 2, 2, 2, 2).mean((3, 5))
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(3)
    x = paddle.randn([4, 3, 5, 5]) * 3 + 1
    bn.train()
    out = bn(x)
    # normalized output: near zero mean, unit var per channel
    o = out.numpy()
    assert abs(o.mean()) < 1e-2
    assert abs(o.std() - 1) < 5e-2
    # running stats moved off init
    assert not np.allclose(bn._mean.numpy(), 0.0)
    bn.eval()
    out_eval = bn(x)
    assert out_eval.shape == [4, 3, 5, 5]


def test_layernorm_matches_numpy():
    ln = nn.LayerNorm(8)
    x = np.random.rand(2, 4, 8).astype(np.float32)
    out = ln(paddle.to_tensor(x)).numpy()
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_embedding_and_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    ids = paddle.to_tensor(np.array([[0, 1, 2]]))
    out = emb(ids)
    assert out.shape == [1, 3, 4]
    np.testing.assert_allclose(out.numpy()[0, 0], 0.0)


def test_dropout_train_vs_eval():
    d = nn.Dropout(0.5)
    x = paddle.ones([1000])
    d.train()
    y = d(x)
    dropped = float((y.numpy() == 0).mean())
    assert 0.3 < dropped < 0.7
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), x.numpy())


def test_activations():
    x = paddle.to_tensor(np.linspace(-2, 2, 9, dtype=np.float32))
    np.testing.assert_allclose(nn.ReLU()(x).numpy(),
                               np.maximum(x.numpy(), 0))
    assert nn.GELU()(x).shape == [9]
    np.testing.assert_allclose(nn.Sigmoid()(x).numpy(),
                               1 / (1 + np.exp(-x.numpy())), rtol=1e-5)
    sm = nn.Softmax(-1)(paddle.randn([3, 5]))
    np.testing.assert_allclose(sm.numpy().sum(-1), 1.0, rtol=1e-5)


def test_losses():
    logits = paddle.randn([4, 5])
    label = paddle.to_tensor(np.array([0, 1, 2, 3]))
    ce = nn.CrossEntropyLoss()(logits, label)
    assert ce.shape == []
    mse = nn.MSELoss()(paddle.ones([3]), paddle.zeros([3]))
    np.testing.assert_allclose(mse.numpy(), 1.0)
    l1 = nn.L1Loss()(paddle.ones([3]) * 2, paddle.zeros([3]))
    np.testing.assert_allclose(l1.numpy(), 2.0)
    bce = nn.BCEWithLogitsLoss()(paddle.zeros([4]), paddle.ones([4]) * 0.5)
    np.testing.assert_allclose(bce.numpy(), np.log(2), rtol=1e-5)


def test_lstm_and_gru():
    lstm = nn.LSTM(8, 16, num_layers=2)
    x = paddle.randn([4, 10, 8])  # [B, S, I]
    out, (h, c) = lstm(x)
    assert out.shape == [4, 10, 16]
    assert h.shape == [2, 4, 16]
    assert c.shape == [2, 4, 16]

    gru = nn.GRU(8, 16, direction="bidirect")
    out, h = gru(x)
    assert out.shape == [4, 10, 32]
    assert h.shape == [2, 4, 16]


def test_rnn_grad_flows():
    lstm = nn.LSTM(4, 8)
    x = paddle.randn([2, 5, 4])
    out, _ = lstm(x)
    out.sum().backward()
    for p in lstm.parameters():
        assert p.grad is not None


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    q = paddle.randn([2, 6, 16])
    out = mha(q, q, q)
    assert out.shape == [2, 6, 16]


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=16, nhead=4, dim_feedforward=32)
    enc = nn.TransformerEncoder(layer, num_layers=2)
    src = paddle.randn([2, 8, 16])
    out = enc(src)
    assert out.shape == [2, 8, 16]
    # each stacked layer must have independent params
    p0 = enc.layers[0].linear1.weight.numpy()
    p1 = enc.layers[1].linear1.weight.numpy()
    assert not np.allclose(p0, p1)


def test_full_transformer():
    t = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=2,
                       num_decoder_layers=2, dim_feedforward=32)
    src = paddle.randn([2, 6, 16])
    tgt = paddle.randn([2, 4, 16])
    out = t(src, tgt)
    assert out.shape == [2, 4, 16]


def test_grad_clip_global_norm():
    clip = nn.ClipGradByGlobalNorm(1.0)
    p = paddle.to_tensor([3.0, 4.0], stop_gradient=False)
    pg = clip([(p, paddle.to_tensor([3.0, 4.0]))])
    np.testing.assert_allclose(np.linalg.norm(pg[0][1].numpy()), 1.0,
                               rtol=1e-4)


def test_hooks():
    l = nn.Linear(2, 2)
    calls = []
    h = l.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
    l(paddle.randn([1, 2]))
    assert calls == [1]
    h.remove()
    l(paddle.randn([1, 2]))
    assert calls == [1]


def test_spectral_norm_scales_to_unit_sigma():
    sn = nn.SpectralNorm([8, 6], dim=0, power_iters=25)
    w = paddle.randn([8, 6])
    wn = sn(w)
    top_sv = np.linalg.svd(np.asarray(wn.numpy()), compute_uv=False)[0]
    np.testing.assert_allclose(top_sv, 1.0, rtol=1e-4)
    # u/v buffers persist across calls (power iteration warm start)
    u0 = np.asarray(sn.weight_u.numpy()).copy()
    sn(w)
    assert not np.allclose(u0, 0)
    # conv-style 4D weight with dim=1
    sn4 = nn.SpectralNorm([3, 8, 2, 2], dim=1, power_iters=25)
    w4 = paddle.randn([3, 8, 2, 2])
    wn4 = sn4(w4)
    m = np.transpose(np.asarray(wn4.numpy()), (1, 0, 2, 3)).reshape(8, -1)
    np.testing.assert_allclose(
        np.linalg.svd(m, compute_uv=False)[0], 1.0, rtol=1e-4)


def test_viterbi_decoder_matches_brute_force():
    import itertools

    from paddle_tpu.text import ViterbiDecoder

    C, L = 4, 5
    rng = np.random.RandomState(3)
    trans = rng.randn(C, C).astype(np.float32)
    pot = rng.randn(2, L, C).astype(np.float32)
    lens = np.array([L, 3], np.int64)
    dec = ViterbiDecoder(paddle.to_tensor(trans), include_bos_eos_tag=True)
    scores, paths = dec(paddle.to_tensor(pot), paddle.to_tensor(lens))
    scores = np.asarray(scores.numpy())
    paths = np.asarray(paths.numpy())

    for b, n in enumerate(lens):
        best, bp = -1e9, None
        for seq in itertools.product(range(C), repeat=int(n)):
            s = trans[C - 2, seq[0]] + pot[b, 0, seq[0]]
            for t in range(1, int(n)):
                s += trans[seq[t - 1], seq[t]] + pot[b, t, seq[t]]
            s += trans[seq[-1], C - 1]
            if s > best:
                best, bp = s, seq
        np.testing.assert_allclose(scores[b], best, rtol=1e-5)
        assert tuple(paths[b, :int(n)]) == bp
        assert (paths[b, int(n):] == 0).all()


def test_viterbi_decoder_jits():
    import jax

    from paddle_tpu.text import ViterbiDecoder

    C = 4
    rng = np.random.RandomState(5)
    dec = ViterbiDecoder(paddle.to_tensor(rng.randn(C, C).astype(np.float32)),
                         include_bos_eos_tag=False)

    @jax.jit
    def f(pot, lens):
        s, p = dec(paddle.Tensor(pot), paddle.Tensor(lens))
        return s.value, p.value

    s, p = f(rng.randn(3, 6, C).astype(np.float32),
             np.array([6, 6, 2], np.int64))
    assert s.shape == (3,) and p.shape == (3, 6)
