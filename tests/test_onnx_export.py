"""paddle.onnx.export: jaxpr -> ONNX ModelProto, validated by round-trip
execution through the in-tree numpy runtime (this image has no
onnx/onnxruntime).  Reference analog: python/paddle/onnx/export.py
(paddle2onnx); parity bar = exported graph reproduces the Layer's
forward numerics."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import onnx as ponnx
from paddle_tpu.onnx import proto

rs = np.random.RandomState(0)


def _roundtrip(layer, inputs, atol=1e-5, rtol=1e-4, n_outs=1):
    layer.eval()
    f = ponnx.export(layer, "/tmp/onnx_test_artifact",
                     example_inputs=list(inputs))
    m = ponnx.ONNXModel(f)
    got = m.run(list(inputs))
    want = layer(*[paddle.to_tensor(x) for x in inputs])
    want = [np.asarray(w.numpy()) for w in
            (want if isinstance(want, (list, tuple)) else [want])]
    assert len(got) == len(want) >= n_outs
    for g, w in zip(got, want):
        assert g.shape == w.shape
        np.testing.assert_allclose(g, w, atol=atol, rtol=rtol)
    return m


def test_mlp_layernorm_roundtrip():
    paddle.seed(3)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.LayerNorm(16),
                        nn.Linear(16, 4), nn.Softmax(-1))
    m = _roundtrip(net, [rs.randn(5, 8).astype(np.float32)])
    assert m.opset >= 13 and m.input_names == ["x0"]


def test_cnn_conv_pool_roundtrip():
    paddle.seed(5)
    net = nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Conv2D(8, 16, 3, stride=2, padding=1), nn.BatchNorm2D(16),
        nn.ReLU(), nn.Flatten(), nn.Linear(16 * 4 * 4, 10))
    _roundtrip(net, [rs.randn(2, 3, 16, 16).astype(np.float32)], atol=1e-4)


def test_grouped_dilated_conv_roundtrip():
    paddle.seed(6)
    net = nn.Conv2D(8, 8, 3, padding=2, dilation=2, groups=4)
    _roundtrip(net, [rs.randn(2, 8, 12, 12).astype(np.float32)], atol=1e-4)


def test_embedding_gather_roundtrip():
    paddle.seed(7)
    net = nn.Sequential(nn.Embedding(100, 12), nn.Linear(12, 4))
    _roundtrip(net, [rs.randint(0, 100, (3, 7)).astype(np.int32)])


def test_transformer_encoder_layer_roundtrip():
    paddle.seed(9)
    net = nn.TransformerEncoderLayer(d_model=32, nhead=4,
                                     dim_feedforward=64, dropout=0.0)
    _roundtrip(net, [rs.randn(2, 9, 32).astype(np.float32)], atol=1e-4)


def test_bert_model_roundtrip():
    from paddle_tpu.models import BertConfig, BertModel

    paddle.seed(11)
    model = BertModel(BertConfig(
        vocab_size=500, hidden_size=64, num_layers=2, num_heads=4,
        intermediate_size=128, max_position_embeddings=64, dropout=0.0))
    ids = rs.randint(0, 500, (2, 16)).astype(np.int32)
    _roundtrip(model, [ids], atol=5e-4, n_outs=2)


def test_dynamic_batch_export(tmp_path):
    """-1 dims in InputSpec export as true dynamic dims: one artifact
    serves several batch sizes (runtime Shape/Gather/Concat shape
    computation instead of baked Reshape targets)."""
    from paddle_tpu.static import InputSpec

    paddle.seed(3)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.LayerNorm(16),
                        nn.Linear(16, 4))
    net.eval()
    f = ponnx.export(net, str(tmp_path / "dyn"),
                     input_spec=[InputSpec([-1, 8], "float32")])
    m = ponnx.ONNXModel(f)
    for B in (1, 3, 7):
        x = rs.randn(B, 8).astype(np.float32)
        got = m.run([x])[0]
        want = np.asarray(net(paddle.to_tensor(x)).numpy())
        assert got.shape == (B, 4)
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-4)


def test_dynamic_batch_bert(tmp_path):
    from paddle_tpu.models import BertConfig, BertModel
    from paddle_tpu.static import InputSpec

    paddle.seed(11)
    model = BertModel(BertConfig(
        vocab_size=500, hidden_size=64, num_layers=2, num_heads=4,
        intermediate_size=128, max_position_embeddings=64, dropout=0.0))
    model.eval()
    f = ponnx.export(model, str(tmp_path / "dynbert"),
                     input_spec=[InputSpec([-1, 16], "int32")])
    m = ponnx.ONNXModel(f)
    for B in (2, 5):
        ids = rs.randint(0, 500, (B, 16)).astype(np.int32)
        got = m.run([ids])
        want = model(paddle.to_tensor(ids))
        want = [np.asarray(w.numpy()) for w in
                (want if isinstance(want, (list, tuple)) else [want])]
        for gv, wv in zip(got, want):
            assert gv.shape == wv.shape
            np.testing.assert_allclose(gv, wv, atol=5e-4, rtol=1e-3)


def test_dynamic_dim_slice_raises_attributably(tmp_path):
    """Slicing along the dynamic axis must fail as UnsupportedOnnxOp
    naming the op, not a raw jax symbolic-shape error."""
    from paddle_tpu.static import InputSpec

    class SliceDyn(nn.Layer):
        def forward(self, x):
            return x[1:]  # limit depends on the dynamic batch dim

    with pytest.raises(ponnx.UnsupportedOnnxOp, match="slice"):
        ponnx.export(SliceDyn(), str(tmp_path / "s"),
                     input_spec=[InputSpec([-1, 4], "float32")])


def test_input_spec_path_and_return_name(tmp_path):
    from paddle_tpu.static import InputSpec

    paddle.seed(1)
    net = nn.Linear(4, 2)
    net.eval()
    f = ponnx.export(net, str(tmp_path / "m"),
                     input_spec=[InputSpec([3, 4], "float32")])
    assert f.endswith(".onnx")
    m = ponnx.ONNXModel(f)
    out = m.run([np.zeros((3, 4), np.float32)])[0]
    assert out.shape == (3, 2)


def test_unsupported_primitive_raises_loudly():
    class TopK(nn.Layer):
        def forward(self, x):
            v, _ = paddle.topk(x, k=2)
            return v

    with pytest.raises((ponnx.UnsupportedOnnxOp, NotImplementedError)):
        ponnx.export(TopK(), "/tmp/onnx_topk",
                     example_inputs=[rs.randn(3, 5).astype(np.float32)])


def test_bfloat16_widens_to_f32():
    paddle.seed(2)
    net = nn.Linear(4, 3)
    net.astype("bfloat16")
    net.eval()
    f = ponnx.export(net, "/tmp/onnx_bf16",
                     example_inputs=[rs.randn(2, 4).astype(np.float32)])
    m = ponnx.ONNXModel(f)
    for t in m.initializers.values():
        assert t.dtype != np.float16 and str(t.dtype) != "bfloat16"
    out = m.run([np.ones((2, 4), np.float32)])[0]
    assert out.dtype == np.float32 and np.isfinite(out).all()


def test_rem_cumsum_scalar_take_semantics():
    """Regression: lax.rem keeps the dividend's sign (Mod fmod=1),
    reverse cumsum must flip the cumsum axis, and scalar take exports
    through the Gather + Reshape path."""
    class Ops(nn.Layer):
        def forward(self, x):
            r = paddle.remainder(x, paddle.to_tensor(np.float32(3.0)))
            c = paddle.cumsum(x, axis=1)
            s = x[1]  # scalar take along axis 0
            return r, c, s

    x = np.array([[-5., 4., -1.], [2., -7., 6.]], np.float32)
    _roundtrip(Ops(), [x], n_outs=3)


def test_general_dot_general_high_rank_rhs():
    """Regression: einsum with rank-3 rhs must take the general
    transpose/reshape lowering, not the MatMul fast path."""
    class Heads(nn.Layer):
        def __init__(self):
            super().__init__()
            self.w = self.create_parameter([6, 2, 3])

        def forward(self, x):  # bsh,hnd->bsnd
            import paddle_tpu

            return paddle_tpu.einsum("bsh,hnd->bsnd", x, self.w)

    _roundtrip(Heads(), [rs.randn(2, 4, 6).astype(np.float32)])


def test_iota_exports_compact_and_int_div_truncates():
    class IotaDiv(nn.Layer):
        def forward(self, x):
            pos = paddle.arange(0, 8, dtype="int32")          # iota
            q = paddle.floor_divide(paddle.to_tensor(
                np.int32(-3)) * pos, paddle.to_tensor(np.int32(2)))
            return x + pos.astype("float32"), q

    m = _roundtrip(IotaDiv(), [rs.randn(2, 8).astype(np.float32)],
                   n_outs=2)
    # iota stored as 1-D arange, never a broadcast blob: no initializer
    # larger than the model weights should exist
    assert all(t.size <= 64 for t in m.initializers.values())


def test_wire_format_parses_as_protobuf():
    """The artifact must be real protobuf: re-decode the model with the
    generic parser and check the spec field numbers are where they
    should be (ModelProto.graph=7, opset_import=8; GraphProto.node=1)."""
    paddle.seed(4)
    net = nn.Linear(2, 2)
    net.eval()
    f = ponnx.export(net, "/tmp/onnx_wire",
                     example_inputs=[np.zeros((1, 2), np.float32)])
    with open(f, "rb") as fh:
        blob = fh.read()
    top = proto.parse(blob)
    assert 7 in top and 8 in top          # graph, opset_import
    assert proto.signed(top[1][0]) == 8   # ir_version
    graph = proto.parse(top[7][0])
    assert 1 in graph and 11 in graph and 12 in graph  # nodes, ins, outs
