"""Property test: randomly composed layer stacks must export to ONNX and
round-trip through the numpy runtime within fp32 tolerance of the
Layer's own forward.  Seeded and deterministic — 12 architectures drawn
from the supported op families (linear/conv/norm/activation/pool/
softmax), catching converter regressions the hand-written cases miss."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import onnx as ponnx


def _random_mlp(rng):
    dims = [int(rng.choice([4, 8, 16]))]
    layers = []
    for _ in range(rng.randint(1, 4)):
        d = int(rng.choice([4, 8, 16, 32]))
        layers.append(nn.Linear(dims[-1], d))
        dims.append(d)
        act = rng.choice(["relu", "gelu", "tanh", "sigmoid", "none"])
        if act == "relu":
            layers.append(nn.ReLU())
        elif act == "gelu":
            layers.append(nn.GELU())
        elif act == "tanh":
            layers.append(nn.Tanh())
        elif act == "sigmoid":
            layers.append(nn.Sigmoid())
        if rng.rand() < 0.4:
            layers.append(nn.LayerNorm(d))
    if rng.rand() < 0.5:
        layers.append(nn.Softmax(-1))
    shape = (int(rng.randint(1, 5)), dims[0])
    return nn.Sequential(*layers), shape


def _random_cnn(rng):
    c = int(rng.choice([2, 3]))
    layers = []
    ch = c
    for _ in range(rng.randint(1, 3)):
        out = int(rng.choice([4, 8]))
        k = int(rng.choice([1, 3]))
        layers.append(nn.Conv2D(ch, out, k, padding=k // 2,
                                stride=int(rng.choice([1, 2]))))
        ch = out
        layers.append(nn.ReLU())
        if rng.rand() < 0.4:
            layers.append(nn.MaxPool2D(2, 2, ceil_mode=False))
        if rng.rand() < 0.3:
            layers.append(nn.BatchNorm2D(ch))
    layers.append(nn.Flatten())
    shape = (2, c, 16, 16)
    return nn.Sequential(*layers), shape


@pytest.mark.parametrize("seed", range(12))
def test_random_architecture_roundtrip(seed, tmp_path):
    rng = np.random.RandomState(1000 + seed)
    paddle.seed(seed)
    net, shape = (_random_mlp(rng) if seed % 2 == 0 else _random_cnn(rng))
    net.eval()
    x = rng.randn(*shape).astype(np.float32)
    f = ponnx.export(net, str(tmp_path / f"fz{seed}"), example_inputs=[x])
    got = ponnx.ONNXModel(f).run([x])[0]
    want = np.asarray(net(paddle.to_tensor(x)).numpy())
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-3)
