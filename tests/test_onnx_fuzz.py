"""Property test: randomly composed layer stacks must export to ONNX and
round-trip through the numpy runtime within fp32 tolerance of the
Layer's own forward.  Seeded and deterministic — 12 architectures drawn
from the supported op families (linear/conv/norm/activation/pool/
softmax), catching converter regressions the hand-written cases miss."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import onnx as ponnx


def _random_mlp(rng):
    dims = [int(rng.choice([4, 8, 16]))]
    layers = []
    for _ in range(rng.randint(1, 4)):
        d = int(rng.choice([4, 8, 16, 32]))
        layers.append(nn.Linear(dims[-1], d))
        dims.append(d)
        act = rng.choice(["relu", "gelu", "tanh", "sigmoid", "none"])
        if act == "relu":
            layers.append(nn.ReLU())
        elif act == "gelu":
            layers.append(nn.GELU())
        elif act == "tanh":
            layers.append(nn.Tanh())
        elif act == "sigmoid":
            layers.append(nn.Sigmoid())
        if rng.rand() < 0.4:
            layers.append(nn.LayerNorm(d))
    if rng.rand() < 0.5:
        layers.append(nn.Softmax(-1))
    shape = (int(rng.randint(1, 5)), dims[0])
    return nn.Sequential(*layers), shape


def _random_cnn(rng):
    c = int(rng.choice([2, 3]))
    layers = []
    ch = c
    for _ in range(rng.randint(1, 3)):
        out = int(rng.choice([4, 8]))
        k = int(rng.choice([1, 3]))
        layers.append(nn.Conv2D(ch, out, k, padding=k // 2,
                                stride=int(rng.choice([1, 2]))))
        ch = out
        layers.append(nn.ReLU())
        if rng.rand() < 0.4:
            layers.append(nn.MaxPool2D(2, 2, ceil_mode=False))
        if rng.rand() < 0.3:
            layers.append(nn.BatchNorm2D(ch))
    layers.append(nn.Flatten())
    shape = (2, c, 16, 16)
    return nn.Sequential(*layers), shape


def _randomize_norm_state(net, rng):
    """Untrained norm layers are near-identity (weight=1, bias=0,
    mean=0, var=1), which would let buffer-wiring bugs in the converter
    slip under tolerance — draw real values for every affine/running
    stat so BatchNormalization/LayerNorm lowering is actually checked."""
    for sub in net.sublayers(include_self=True):
        if isinstance(sub, (nn.BatchNorm2D, nn.LayerNorm)):
            for pname in ("weight", "bias"):
                p = getattr(sub, pname, None)
                if p is not None:
                    p.set_value(rng.uniform(
                        0.5, 1.5, np.asarray(p.numpy()).shape)
                        .astype(np.float32))
        if isinstance(sub, nn.BatchNorm2D):
            n = np.asarray(sub._mean.numpy()).shape
            sub._mean.set_value(rng.randn(*n).astype(np.float32) * 0.3)
            sub._variance.set_value(
                rng.uniform(0.5, 2.0, n).astype(np.float32))


@pytest.mark.parametrize("seed", range(12))
def test_random_architecture_roundtrip(seed, tmp_path):
    from tests.test_onnx_export import _roundtrip

    rng = np.random.RandomState(1000 + seed)
    paddle.seed(seed)
    net, shape = (_random_mlp(rng) if seed % 2 == 0 else _random_cnn(rng))
    _randomize_norm_state(net, rng)
    x = rng.randn(*shape).astype(np.float32)
    _roundtrip(net, [x], atol=2e-4, rtol=1e-3)
