"""Op-lowering tests through the OpTest harness (numpy reference +
numeric-grad), covering the dense-op set the 5 baseline configs use
(SURVEY.md §7 step 4): elementwise/broadcast binary ops, activations,
matmul, reductions, shape ops, softmax/cross-entropy, norm layers, conv,
pooling, embedding, clip."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from op_test import OpTest


def rs(seed=0):
    return np.random.RandomState(seed)


class TestElementwiseOps(OpTest):
    def test_add_broadcast(self):
        a, b = rs().randn(3, 4).astype("f"), rs(1).randn(4).astype("f")
        self.check_output(paddle.add, np.add, [a, b])
        self.check_grad(paddle.add, [a, b])

    def test_subtract(self):
        a, b = rs().randn(2, 5).astype("f"), rs(1).randn(2, 5).astype("f")
        self.check_output(paddle.subtract, np.subtract, [a, b])
        self.check_grad(paddle.subtract, [a, b])

    def test_multiply(self):
        a, b = rs().randn(3, 4).astype("f"), rs(1).randn(3, 4).astype("f")
        self.check_output(paddle.multiply, np.multiply, [a, b])
        self.check_grad(paddle.multiply, [a, b])

    def test_divide(self):
        a = rs().randn(3, 4).astype("f")
        b = rs(1).rand(3, 4).astype("f") + 1.0
        self.check_output(paddle.divide, np.divide, [a, b])
        self.check_grad(paddle.divide, [a, b])

    def test_pow_maximum_minimum(self):
        a = rs().rand(3, 3).astype("f") + 0.5
        self.check_output(lambda x: paddle.pow(x, 2.5),
                          lambda x: np.power(x, 2.5), [a])
        self.check_grad(lambda x: paddle.pow(x, 2.5), [a])
        b = rs(1).randn(3, 3).astype("f")
        c = rs(2).randn(3, 3).astype("f")
        self.check_output(paddle.maximum, np.maximum, [b, c])
        self.check_output(paddle.minimum, np.minimum, [b, c])


class TestActivationOps(OpTest):
    cases = {
        "relu": (F.relu, lambda x: np.maximum(x, 0)),
        "sigmoid": (F.sigmoid, lambda x: 1 / (1 + np.exp(-x))),
        "tanh": (F.tanh, np.tanh),
        "exp": (paddle.exp, np.exp),
        "log": (paddle.log, np.log),
        "sqrt": (paddle.sqrt, np.sqrt),
        "silu": (F.silu, lambda x: x / (1 + np.exp(-x))),
        "softplus": (F.softplus, lambda x: np.log1p(np.exp(-np.abs(x)))
                     + np.maximum(x, 0)),
    }

    def test_forward_and_grad(self):
        for name, (op, ref) in self.cases.items():
            x = (rs().rand(4, 5).astype("f") + 0.5  # positive for log/sqrt
                 if name in ("log", "sqrt") else rs().randn(4, 5).astype("f"))
            self.check_output(op, ref, [x], atol=1e-5, rtol=1e-4)
            # relu grad is non-smooth at 0 — nudge away
            if name == "relu":
                x = x + np.sign(x) * 0.05
            self.check_grad(op, [x], max_relative_error=2e-2)

    def test_gelu_matches_reference_formula(self):
        x = rs().randn(3, 4).astype("f")
        # exact erf gelu vs the tanh approximation agree to ~2e-3
        out = F.gelu(paddle.to_tensor(x)).numpy()
        approx = 0.5 * x * (1 + np.tanh(np.sqrt(2 / np.pi)
                                        * (x + 0.044715 * x ** 3)))
        np.testing.assert_allclose(np.asarray(out), approx, atol=2e-3)
        self.check_grad(F.gelu, [x], max_relative_error=2e-2)


class TestMatmulOps(OpTest):
    def test_matmul(self):
        a, b = rs().randn(4, 6).astype("f"), rs(1).randn(6, 3).astype("f")
        self.check_output(paddle.matmul, np.matmul, [a, b], rtol=1e-4)
        self.check_grad(paddle.matmul, [a, b], max_relative_error=1e-2)

    def test_matmul_transpose_flags(self):
        a, b = rs().randn(6, 4).astype("f"), rs(1).randn(3, 6).astype("f")
        self.check_output(
            lambda x, y: paddle.matmul(x, y, transpose_x=True,
                                       transpose_y=True),
            lambda x, y: x.T @ y.T, [a, b], rtol=1e-4)

    def test_batched(self):
        a = rs().randn(2, 4, 5).astype("f")
        b = rs(1).randn(2, 5, 3).astype("f")
        self.check_output(paddle.bmm, np.matmul, [a, b], rtol=1e-4)


class TestReduceOps(OpTest):
    def test_sum_mean_max_min(self):
        x = rs().randn(3, 4, 5).astype("f")
        self.check_output(lambda t: paddle.sum(t, axis=1),
                          lambda a: a.sum(1), [x], rtol=1e-4)
        self.check_output(lambda t: paddle.mean(t, axis=(0, 2)),
                          lambda a: a.mean((0, 2)), [x], rtol=1e-4)
        self.check_output(lambda t: paddle.max(t, axis=-1),
                          lambda a: a.max(-1), [x])
        self.check_output(lambda t: paddle.min(t),
                          lambda a: a.min(), [x])
        self.check_grad(lambda t: paddle.sum(t, axis=1), [x])
        self.check_grad(lambda t: paddle.mean(t, axis=(0, 2)), [x])

    def test_prod_logsumexp(self):
        x = (rs().rand(3, 4).astype("f") + 0.5)
        self.check_output(lambda t: paddle.prod(t, axis=1),
                          lambda a: a.prod(1), [x], rtol=1e-4)
        self.check_output(
            lambda t: paddle.logsumexp(t, axis=1),
            lambda a: np.log(np.exp(a).sum(1)), [x], rtol=1e-4)


class TestShapeOps(OpTest):
    def test_reshape_transpose_concat_split_stack(self):
        x = rs().randn(2, 6).astype("f")
        y = rs(1).randn(2, 6).astype("f")
        self.check_output(lambda t: paddle.reshape(t, [3, 4]),
                          lambda a: a.reshape(3, 4), [x])
        self.check_output(lambda t: paddle.transpose(t, [1, 0]),
                          lambda a: a.T, [x])
        self.check_output(lambda a, b: paddle.concat([a, b], axis=0),
                          lambda a, b: np.concatenate([a, b], 0), [x, y])
        self.check_output(lambda a, b: paddle.stack([a, b], axis=1),
                          lambda a, b: np.stack([a, b], 1), [x, y])
        self.check_output(lambda t: paddle.split(t, 3, axis=1),
                          lambda a: np.split(a, 3, 1), [x])
        self.check_grad(lambda t: paddle.reshape(t, [3, 4]), [x])
        self.check_grad(lambda a, b: paddle.concat([a, b], axis=0), [x, y])

    def test_gather_slice_where(self):
        x = rs().randn(5, 3).astype("f")
        idx = np.array([0, 2, 4])
        self.check_output(lambda t: paddle.gather(t, paddle.to_tensor(idx)),
                          lambda a: a[idx], [x])
        self.check_output(lambda t: t[1:4, :2],
                          lambda a: a[1:4, :2], [x])
        cond = x > 0
        self.check_output(
            lambda t: paddle.where(paddle.to_tensor(cond), t, -t),
            lambda a: np.where(cond, a, -a), [x])
        self.check_grad(lambda t: paddle.gather(t, paddle.to_tensor(idx)),
                        [x])

    def test_squeeze_unsqueeze_tile_flip(self):
        x = rs().randn(2, 1, 3).astype("f")
        self.check_output(lambda t: paddle.squeeze(t, axis=1),
                          lambda a: a.squeeze(1), [x])
        self.check_output(lambda t: paddle.unsqueeze(t, axis=0),
                          lambda a: a[None], [x])
        self.check_output(lambda t: paddle.tile(t, [2, 1, 1]),
                          lambda a: np.tile(a, (2, 1, 1)), [x])
        self.check_output(lambda t: paddle.flip(t, axis=[0]),
                          lambda a: a[::-1].copy(), [x])


class TestSoftmaxXentOps(OpTest):
    def test_softmax(self):
        x = rs().randn(4, 7).astype("f")

        def ref(a):
            e = np.exp(a - a.max(-1, keepdims=True))
            return e / e.sum(-1, keepdims=True)

        self.check_output(F.softmax, ref, [x], rtol=1e-4)
        self.check_grad(F.softmax, [x], max_relative_error=2e-2)

    def test_log_softmax(self):
        x = rs().randn(4, 7).astype("f")

        def ref(a):
            m = a - a.max(-1, keepdims=True)
            return m - np.log(np.exp(m).sum(-1, keepdims=True))

        self.check_output(F.log_softmax, ref, [x], rtol=1e-4)

    def test_cross_entropy_fused(self):
        """softmax_with_cross_entropy_op.cc:301 semantics: fused, stable."""
        logits = rs().randn(6, 5).astype("f")
        labels = rs(1).randint(0, 5, (6,))

        def ref(a):
            m = a - a.max(-1, keepdims=True)
            lse = np.log(np.exp(m).sum(-1)) - m[np.arange(6), labels]
            return lse.mean()

        def op(t):
            return F.cross_entropy(t, paddle.to_tensor(labels))

        self.check_output(op, ref, [logits], rtol=1e-4)
        self.check_grad(op, [logits], max_relative_error=2e-2)


class TestNormOps(OpTest):
    def test_layer_norm(self):
        x = rs().randn(4, 8).astype("f")
        g = np.ones(8, "f") + rs(1).randn(8).astype("f") * 0.1
        b = rs(2).randn(8).astype("f") * 0.1

        def ref(a, gg, bb):
            mu = a.mean(-1, keepdims=True)
            var = a.var(-1, keepdims=True)
            return (a - mu) / np.sqrt(var + 1e-5) * gg + bb

        def op(t, gg, bb):
            return F.layer_norm(t, 8, weight=gg, bias=bb)

        self.check_output(op, ref, [x, g, b], rtol=1e-4, atol=1e-5)
        self.check_grad(op, [x, g, b], max_relative_error=2e-2)

    def test_batch_norm_eval(self):
        x = rs().randn(4, 3, 5).astype("f")
        mean = rs(1).randn(3).astype("f") * 0.1
        var = rs(2).rand(3).astype("f") + 0.5
        w = np.ones(3, "f")
        b = np.zeros(3, "f")

        def ref(a, *_):
            return (a - mean[None, :, None]) / \
                np.sqrt(var[None, :, None] + 1e-5)

        def op(t, *_):
            return F.batch_norm(t, paddle.to_tensor(mean),
                                paddle.to_tensor(var), paddle.to_tensor(w),
                                paddle.to_tensor(b), training=False)

        self.check_output(op, ref, [x], rtol=1e-4, atol=1e-5)


class TestConvPoolOps(OpTest):
    def test_conv2d(self):
        x = rs().randn(1, 2, 6, 6).astype("f")
        w = rs(1).randn(3, 2, 3, 3).astype("f") * 0.2

        def ref(a, ww):
            out = np.zeros((1, 3, 4, 4), np.float64)
            for oc in range(3):
                for i in range(4):
                    for j in range(4):
                        out[0, oc, i, j] = (a[0, :, i:i + 3, j:j + 3]
                                            * ww[oc]).sum()
            return out

        self.check_output(lambda a, ww: F.conv2d(a, ww), ref, [x, w],
                          rtol=1e-3, atol=1e-4)
        self.check_grad(lambda a, ww: F.conv2d(a, ww), [x, w],
                        max_relative_error=2e-2)

    def test_pooling(self):
        x = rs().randn(1, 1, 4, 4).astype("f")

        def ref_max(a):
            return a.reshape(1, 1, 2, 2, 2, 2).max((3, 5))

        def ref_avg(a):
            return a.reshape(1, 1, 2, 2, 2, 2).mean((3, 5))

        self.check_output(lambda t: F.max_pool2d(t, 2, 2), ref_max, [x])
        self.check_output(lambda t: F.avg_pool2d(t, 2, 2), ref_avg, [x],
                          rtol=1e-4)
        self.check_grad(lambda t: F.avg_pool2d(t, 2, 2), [x])


class TestEmbeddingClipOps(OpTest):
    def test_embedding(self):
        table = rs().randn(10, 4).astype("f")
        ids = np.array([[1, 3], [7, 0]])

        def op(w):
            return F.embedding(paddle.to_tensor(ids), w)

        self.check_output(op, lambda w: w[ids], [table])
        self.check_grad(op, [table])

    def test_clip(self):
        x = rs().randn(4, 4).astype("f") * 2
        self.check_output(lambda t: paddle.clip(t, -1.0, 1.0),
                          lambda a: np.clip(a, -1, 1), [x])
        # clip grad non-smooth at boundaries; keep interior
        xi = np.clip(x, -0.9, 0.9).astype("f")
        self.check_grad(lambda t: paddle.clip(t, -1.0, 1.0), [xi])


class TestCumulativeOps(OpTest):
    def test_cumsum_cumprod(self):
        x = rs().rand(3, 4).astype("f") + 0.5
        self.check_output(lambda t: paddle.cumsum(t, axis=1),
                          lambda a: a.cumsum(1), [x], rtol=1e-4)
        self.check_output(lambda t: paddle.cumprod(t, dim=1),
                          lambda a: a.cumprod(1), [x], rtol=1e-4)
        self.check_grad(lambda t: paddle.cumsum(t, axis=1), [x])

    def test_sort_topk_argmax_values(self):
        x = rs().randn(3, 6).astype("f")
        self.check_output(lambda t: paddle.sort(t, axis=1),
                          lambda a: np.sort(a, 1), [x])
        self.check_output(
            lambda t: paddle.topk(t, 2, axis=1)[0],
            lambda a: np.sort(a, 1)[:, ::-1][:, :2].copy(), [x])
        self.check_output(lambda t: paddle.argmax(t, axis=1),
                          lambda a: a.argmax(1), [x])


class TestImageOps(OpTest):
    def test_unfold_fold_roundtrip(self):
        """fold(unfold(x)) with stride=kernel (non-overlapping) == x."""
        x = rs().randn(2, 3, 8, 8).astype("f")
        cols = F.unfold(paddle.to_tensor(x), kernel_sizes=2, strides=2)
        back = F.fold(cols, output_sizes=8, kernel_sizes=2, strides=2)
        np.testing.assert_allclose(np.asarray(back.numpy()), x, rtol=1e-5)

    def test_fold_overlap_sums(self):
        """Overlapping patches scatter-ADD (col2im semantics): folding
        all-ones cols with k=2,s=1 counts patch coverage per pixel."""
        oh = ow = 3  # output 4x4, kernel 2, stride 1 → 3x3 patches
        cols = np.ones((1, 1 * 2 * 2, oh * ow), np.float32)
        out = np.asarray(F.fold(paddle.to_tensor(cols), output_sizes=4,
                                kernel_sizes=2, strides=1).numpy())
        expect = np.array([[1, 2, 2, 1],
                           [2, 4, 4, 2],
                           [2, 4, 4, 2],
                           [1, 2, 2, 1]], np.float32)
        np.testing.assert_allclose(out[0, 0], expect)

    def test_affine_grid_identity(self):
        theta = np.tile(np.array([[[1, 0, 0], [0, 1, 0]]], np.float32),
                        (2, 1, 1))
        grid = F.affine_grid(paddle.to_tensor(theta), [2, 3, 4, 5])
        g = np.asarray(grid.numpy())
        assert g.shape == (2, 4, 5, 2)
        np.testing.assert_allclose(g[0, 0, 0], [-1, -1], atol=1e-6)
        np.testing.assert_allclose(g[0, -1, -1], [1, 1], atol=1e-6)
        # identity grid + grid_sample == identity resize
        x = rs().randn(2, 3, 4, 5).astype("f")
        y = F.grid_sample(paddle.to_tensor(x), grid)
        np.testing.assert_allclose(np.asarray(y.numpy()), x, atol=1e-5)

    def test_temporal_shift(self):
        B, T, C, H, W = 2, 4, 8, 2, 2
        x = rs().randn(B * T, C, H, W).astype("f")
        out = np.asarray(F.temporal_shift(paddle.to_tensor(x), T,
                                          shift_ratio=0.25).numpy())
        v = x.reshape(B, T, C, H, W)
        o = out.reshape(B, T, C, H, W)
        np.testing.assert_allclose(o[:, :-1, :2], v[:, 1:, :2])   # back
        np.testing.assert_allclose(o[:, -1, :2], 0)
        np.testing.assert_allclose(o[:, 1:, 2:4], v[:, :-1, 2:4])  # fwd
        np.testing.assert_allclose(o[:, 0, 2:4], 0)
        np.testing.assert_allclose(o[:, :, 4:], v[:, :, 4:])       # rest

    def test_fold_asymmetric_4pad_roundtrip(self):
        """4-int [top, left, bottom, right] padding form (reference
        unfold_op) round-trips through unfold→fold on the interior."""
        x = rs().randn(1, 2, 6, 6).astype("f")
        pads = [1, 0, 2, 1]
        cols = F.unfold(paddle.to_tensor(x), kernel_sizes=3, strides=3,
                        paddings=pads)
        back = F.fold(cols, output_sizes=6, kernel_sizes=3, strides=3,
                      paddings=pads)
        np.testing.assert_allclose(np.asarray(back.numpy()), x, rtol=1e-5)

    def test_temporal_shift_nhwc(self):
        x = rs().randn(4, 2, 2, 8).astype("f")  # [N*T, H, W, C]
        out = np.asarray(F.temporal_shift(paddle.to_tensor(x), 2,
                                          data_format="NHWC").numpy())
        ref = np.asarray(F.temporal_shift(
            paddle.to_tensor(np.moveaxis(x, -1, 1).copy()), 2).numpy())
        np.testing.assert_allclose(out, np.moveaxis(ref, 1, -1), rtol=1e-6)


class TestRound4TailOpGrads(OpTest):
    """Analytic-vs-numeric gradient checks (the reference OpTest
    check_grad contract) for the round-4 registry-tail ops that
    differentiate."""

    def test_row_conv_grad(self):
        x = rs().randn(1, 4, 3).astype("f") * 0.5
        w = rs().randn(2, 3).astype("f") * 0.5
        self.check_grad(lambda a, b: F.row_conv(a, b), [x, w])

    def test_conv_shift_grad(self):
        a = rs().randn(2, 5).astype("f") * 0.5
        b = rs().randn(2, 3).astype("f") * 0.5
        self.check_grad(lambda x, y: F.conv_shift(x, y), [a, b])

    def test_bilinear_grad(self):
        a = rs().randn(2, 3).astype("f") * 0.5
        b = rs().randn(2, 4).astype("f") * 0.5
        w = rs().randn(2, 3, 4).astype("f") * 0.5
        self.check_grad(lambda x, y, w_: F.bilinear(x, y, w_), [a, b, w])

    def test_sequence_conv_grad(self):
        from paddle_tpu.text import sequence as sq

        x = rs().randn(1, 4, 2).astype("f") * 0.5
        ln = np.array([3])
        w = rs().randn(6, 3).astype("f") * 0.5
        self.check_grad(
            lambda a, b: sq.sequence_conv(a, paddle.to_tensor(ln), b, 3),
            [x, w])

    def test_sequence_pool_grads(self):
        from paddle_tpu.text import sequence as sq

        x = rs().randn(2, 4).astype("f")
        ln = np.array([3, 2])
        for pt in ("SUM", "AVERAGE", "SQRT", "MAX", "LAST"):
            self.check_grad(
                lambda a, pt=pt: sq.sequence_pool(
                    a, paddle.to_tensor(ln), pt), [x])

    def test_deform_conv2d_grads(self):
        from paddle_tpu.vision import ops as V

        x = rs().randn(1, 2, 4, 4).astype("f") * 0.5
        off = rs().randn(1, 18, 4, 4).astype("f") * 0.3
        w = rs().randn(2, 2, 3, 3).astype("f") * 0.5
        self.check_grad(
            lambda a, o, w_: V.deform_conv2d(a, o, w_, padding=1),
            [x, off, w], max_relative_error=2e-2)  # bilinear kinks

    def test_linear_chain_crf_grad(self):
        from paddle_tpu.text import linear_chain_crf

        em = rs().randn(2, 3, 3).astype("f") * 0.5
        tr = rs().randn(5, 3).astype("f") * 0.5
        lab = np.array([[0, 1, 2], [2, 0, 0]])
        ln = np.array([3, 2])
        self.check_grad(
            lambda e, t: linear_chain_crf(
                e, t, paddle.to_tensor(lab), paddle.to_tensor(ln)),
            [em, tr])

    def test_addmm_segment_grads(self):
        a = rs().randn(2, 2).astype("f")
        b = rs().randn(2, 2).astype("f")
        c = rs().randn(2, 2).astype("f")
        self.check_grad(lambda i, x, y: paddle.addmm(i, x, y, beta=2.0,
                                                     alpha=0.5), [a, b, c])
        d = rs().randn(3, 2).astype("f")
        ids = np.array([0, 0, 1])
        self.check_grad(
            lambda v: paddle.segment_sum(v, paddle.to_tensor(ids)), [d])
