"""Optimizer tests: update-rule math vs references + lr schedulers."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def quad_problem(opt_cls, steps=100, **kw):
    """Minimize ||x - c||^2; returns final distance."""
    paddle.seed(0)
    target = np.array([1.0, -2.0, 3.0], np.float32)
    x = nn.Parameter(np.zeros(3, np.float32))
    opt = opt_cls(parameters=[x], **kw)
    for _ in range(steps):
        loss = ((x - paddle.to_tensor(target)) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return float(np.abs(x.numpy() - target).max())


def test_sgd_converges():
    assert quad_problem(paddle.optimizer.SGD, learning_rate=0.1) < 1e-3


def test_momentum_converges():
    assert quad_problem(paddle.optimizer.Momentum, steps=200,
                        learning_rate=0.05, momentum=0.9) < 1e-3


def test_adam_converges():
    assert quad_problem(paddle.optimizer.Adam, steps=300,
                        learning_rate=0.1) < 1e-2


def test_adamw_decay():
    # with pure decay and zero grads, weights shrink
    x = nn.Parameter(np.ones(3, np.float32))
    opt = paddle.optimizer.AdamW(learning_rate=0.1, parameters=[x],
                                 weight_decay=0.5)
    loss = (x * 0.0).sum()
    loss.backward()
    opt.step()
    assert np.all(x.numpy() < 1.0)


def test_sgd_matches_manual():
    x = nn.Parameter(np.array([2.0], np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=[x])
    (x * 3.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(x.numpy(), [2.0 - 0.5 * 3.0], rtol=1e-6)


def test_adam_matches_manual_first_step():
    x = nn.Parameter(np.array([1.0], np.float32))
    opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[x])
    (x * 2.0).sum().backward()
    opt.step()
    # first adam step ~ -lr * g/|g| = -0.1
    np.testing.assert_allclose(x.numpy(), [0.9], atol=1e-5)


def test_all_optimizers_run():
    for cls, kw in [
        (paddle.optimizer.Adagrad, dict(learning_rate=0.1)),
        (paddle.optimizer.Adamax, dict(learning_rate=0.1)),
        (paddle.optimizer.Adadelta, dict(learning_rate=1.0)),
        (paddle.optimizer.RMSProp, dict(learning_rate=0.01)),
        (paddle.optimizer.Lamb, dict(learning_rate=0.01)),
        (paddle.optimizer.LarsMomentum, dict(learning_rate=0.1)),
        (paddle.optimizer.Ftrl, dict(learning_rate=0.1)),
    ]:
        d = quad_problem(cls, steps=50, **kw)
        assert np.isfinite(d), cls.__name__


def test_weight_decay_l2():
    x = nn.Parameter(np.array([1.0], np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[x],
                               weight_decay=0.1)
    (x * 0.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(x.numpy(), [1.0 - 0.1 * 0.1], rtol=1e-5)


def test_grad_clip_in_optimizer():
    x = nn.Parameter(np.array([0.0], np.float32))
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[x],
                               grad_clip=nn.ClipGradByGlobalNorm(0.5))
    (x * 100.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(x.numpy(), [-0.5], rtol=1e-4)


def test_lr_scheduler_step_decay():
    sched = paddle.optimizer.lr.StepDecay(learning_rate=1.0, step_size=2,
                                          gamma=0.1)
    x = nn.Parameter(np.zeros(1, np.float32))
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[x])
    lrs = []
    for _ in range(4):
        lrs.append(opt.get_lr())
        sched.step()
    np.testing.assert_allclose(lrs, [1.0, 1.0, 0.1, 0.1], rtol=1e-6)


def test_lr_warmup():
    sched = paddle.optimizer.lr.LinearWarmup(
        learning_rate=0.1, warmup_steps=5, start_lr=0.0, end_lr=0.1)
    vals = []
    for _ in range(7):
        vals.append(sched())
        sched.step()
    assert vals[0] == 0.0
    np.testing.assert_allclose(vals[5], 0.1, rtol=1e-6)


def test_cosine_annealing():
    sched = paddle.optimizer.lr.CosineAnnealingDecay(learning_rate=1.0,
                                                     T_max=10)
    v0 = sched()
    for _ in range(10):
        sched.step()
    np.testing.assert_allclose(v0, 1.0)
    np.testing.assert_allclose(sched(), 0.0, atol=1e-6)


def test_optimizer_state_dict_roundtrip():
    x = nn.Parameter(np.ones(3, np.float32), name="p0")
    opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[x])
    (x * 2).sum().backward()
    opt.step()
    sd = opt.state_dict()
    opt2 = paddle.optimizer.Adam(learning_rate=0.1, parameters=[x])
    opt2.set_state_dict(sd)
    assert opt2._step_count == 1
    np.testing.assert_allclose(
        np.asarray(opt2._slots[id(x)]["moment1"]),
        np.asarray(opt._slots[id(x)]["moment1"]))


def test_functional_apply_pytree_matches_eager():
    import jax.numpy as jnp

    paddle.seed(3)
    w = np.random.rand(4, 2).astype(np.float32)
    g = np.random.rand(4, 2).astype(np.float32)

    # eager
    p = nn.Parameter(w.copy())
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[p])
    p.grad = paddle.to_tensor(g.copy())
    opt.step()

    # functional
    opt2 = paddle.optimizer.Adam(learning_rate=0.01)
    params = {"w": jnp.asarray(w)}
    state = opt2.init_pytree(params)
    new_params, _ = opt2.apply_pytree(params, {"w": jnp.asarray(g)}, state,
                                      lr=0.01, step=1)
    np.testing.assert_allclose(p.numpy(), np.asarray(new_params["w"]),
                               rtol=1e-5)
