"""Optimizer trajectory parity vs torch.optim: identical initial params
and gradient sequences must yield matching parameter trajectories (the
update rules' exact math, incl. bias correction and decoupled decay —
reference analogs adam_op.cc / momentum_op.cc / sgd_op.cc / rmsprop)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as paddle  # noqa: E402

rs = np.random.RandomState(3)
STEPS = 10


def _run_paddle(opt_factory, w0, grads):
    p = paddle.to_tensor(w0.copy(), stop_gradient=False)
    opt = opt_factory([p])
    for g in grads:
        p.grad = paddle.to_tensor(g)
        opt.step()
        opt.clear_grad()
    return np.asarray(p.numpy())


def _run_torch(opt_factory, w0, grads):
    t = torch.tensor(w0.copy(), requires_grad=True)
    opt = opt_factory([t])
    for g in grads:
        t.grad = torch.tensor(g)
        opt.step()
        opt.zero_grad()
    return t.detach().numpy()


@pytest.fixture
def problem():
    w0 = rs.randn(5, 3).astype(np.float32)
    grads = [rs.randn(5, 3).astype(np.float32) for _ in range(STEPS)]
    return w0, grads


def test_sgd_parity(problem):
    w0, grads = problem
    got = _run_paddle(lambda ps: paddle.optimizer.SGD(
        learning_rate=0.1, parameters=ps), w0, grads)
    want = _run_torch(lambda ps: torch.optim.SGD(ps, lr=0.1), w0, grads)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_momentum_parity(problem):
    w0, grads = problem
    got = _run_paddle(lambda ps: paddle.optimizer.Momentum(
        learning_rate=0.05, momentum=0.9, parameters=ps), w0, grads)
    want = _run_torch(lambda ps: torch.optim.SGD(
        ps, lr=0.05, momentum=0.9), w0, grads)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_adam_parity(problem):
    w0, grads = problem
    got = _run_paddle(lambda ps: paddle.optimizer.Adam(
        learning_rate=1e-2, beta1=0.9, beta2=0.999, epsilon=1e-8,
        parameters=ps), w0, grads)
    want = _run_torch(lambda ps: torch.optim.Adam(
        ps, lr=1e-2, betas=(0.9, 0.999), eps=1e-8), w0, grads)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_adamw_parity(problem):
    w0, grads = problem
    got = _run_paddle(lambda ps: paddle.optimizer.AdamW(
        learning_rate=1e-2, weight_decay=0.05, parameters=ps), w0, grads)
    want = _run_torch(lambda ps: torch.optim.AdamW(
        ps, lr=1e-2, weight_decay=0.05), w0, grads)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_rmsprop_parity(problem):
    w0, grads = problem
    got = _run_paddle(lambda ps: paddle.optimizer.RMSProp(
        learning_rate=1e-2, rho=0.9, epsilon=1e-8, parameters=ps),
        w0, grads)
    want = _run_torch(lambda ps: torch.optim.RMSprop(
        ps, lr=1e-2, alpha=0.9, eps=1e-8), w0, grads)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_adagrad_parity(problem):
    w0, grads = problem
    got = _run_paddle(lambda ps: paddle.optimizer.Adagrad(
        learning_rate=0.05, epsilon=1e-10, parameters=ps), w0, grads)
    want = _run_torch(lambda ps: torch.optim.Adagrad(
        ps, lr=0.05, eps=1e-10), w0, grads)
    np.testing.assert_allclose(got, want, atol=1e-6)
