"""ModelAverage / EMA / Lookahead wrapper tests.

Reference contract: fluid/optimizer.py ModelAverage:3141 (windowed
average + apply/restore), ExponentialMovingAverage:3450 (shadow + decay
ramp), LookaheadOptimizer:5212 (slow/fast sync every k)."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.optimizer import (EMA, ExponentialMovingAverage,
                                  LookaheadOptimizer, ModelAverage)


class TestEMAFunctional:
    def test_shadow_math(self):
        ema = ExponentialMovingAverage(decay=0.9)
        params = {"w": jnp.ones((2,))}
        st = ema.init_pytree(params)
        st = ema.update_pytree({"w": jnp.full((2,), 2.0)}, st)
        # shadow = 0.9*1 + 0.1*2 = 1.1
        np.testing.assert_allclose(np.asarray(st["shadow"]["w"]),
                                   [1.1, 1.1], rtol=1e-6)
        assert int(st["step"]) == 1

    def test_thres_steps_ramp(self):
        ema = ExponentialMovingAverage(decay=0.999, thres_steps=True)
        params = {"w": jnp.zeros((1,))}
        st = ema.init_pytree({"w": jnp.ones((1,))})
        # step 0: decay = min(0.999, 1/10) = 0.1 -> shadow = 0.1*1+0.9*0
        st = ema.update_pytree(params, st)
        np.testing.assert_allclose(np.asarray(st["shadow"]["w"]), [0.1],
                                   rtol=1e-6)

    def test_jit_composes_with_train_step(self):
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        ema = ExponentialMovingAverage(decay=0.5)
        params = {"w": jnp.float32(1.0)}

        def step(p, s, e):
            g = {"w": jnp.float32(1.0)}
            p, s = opt.apply_pytree(p, g, s, step=1)
            e = ema.update_pytree(p, e)
            return p, s, e

        p, s, e = jax.jit(step)(params, opt.init_pytree(params),
                                ema.init_pytree(params))
        np.testing.assert_allclose(float(p["w"]), 0.9, rtol=1e-6)
        # shadow = 0.5*1 + 0.5*0.9
        np.testing.assert_allclose(float(e["shadow"]["w"]), 0.95, rtol=1e-6)


class TestEMAEager:
    def test_update_apply_restore(self):
        paddle.seed(0)
        lin = paddle.nn.Linear(4, 4)
        ema = EMA(decay=0.0, parameters=lin.parameters())  # shadow == param
        ema.update()
        orig = np.asarray(lin.weight.value).copy()
        lin.weight._value = lin.weight.value + 1.0
        with ema.apply():
            np.testing.assert_allclose(np.asarray(lin.weight.value), orig,
                                       rtol=1e-6)
        np.testing.assert_allclose(np.asarray(lin.weight.value), orig + 1.0,
                                   rtol=1e-6)


class TestModelAverage:
    def test_three_step_average(self):
        ma = ModelAverage(average_window_rate=1.0, min_average_window=1,
                          max_average_window=100)
        params = {"w": jnp.float32(0.0)}
        st = ma.init_pytree(params)
        for v in (1.0, 2.0, 3.0):
            st = ma.update_pytree({"w": jnp.float32(v)}, st)
        avg = ma.average_pytree(st)
        # window math: each step restarts when num_acc >= min(max, rate*n)
        # with rate=1 the window tracks all updates; average over the
        # retained buckets must lie within [1, 3]
        assert 1.0 <= float(avg["w"]) <= 3.0

    def test_wide_window_is_plain_mean(self):
        ma = ModelAverage(average_window_rate=0.0, min_average_window=100,
                          max_average_window=100)
        st = ma.init_pytree({"w": jnp.float32(0.0)})
        for v in (1.0, 2.0, 3.0, 4.0):
            st = ma.update_pytree({"w": jnp.float32(v)}, st)
        np.testing.assert_allclose(float(ma.average_pytree(st)["w"]), 2.5,
                                   rtol=1e-6)

    def test_eager_apply_restore(self):
        paddle.seed(0)
        lin = paddle.nn.Linear(3, 3)
        ma = ModelAverage(0.0, parameters=lin.parameters(),
                          min_average_window=100, max_average_window=100)
        w0 = np.asarray(lin.weight.value).copy()
        ma.update()
        lin.weight._value = lin.weight.value + 2.0
        ma.update()
        with ma.apply():
            np.testing.assert_allclose(np.asarray(lin.weight.value),
                                       w0 + 1.0, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(lin.weight.value), w0 + 2.0,
                                   rtol=1e-6)


class TestLookahead:
    def test_sync_every_k(self):
        inner = paddle.optimizer.SGD(learning_rate=1.0)
        la = LookaheadOptimizer(inner, alpha=0.5, k=2)
        params = {"w": jnp.float32(10.0)}
        st = la.init_pytree(params)
        g = {"w": jnp.float32(1.0)}
        # step1: fast 10->9, no sync.  step2: fast 9->8, sync:
        # slow = 10 + 0.5*(8-10) = 9, fast = 9
        p, st = la.apply_pytree(params, g, st, step=1)
        assert float(p["w"]) == 9.0
        p, st = la.apply_pytree(p, g, st, step=2)
        assert float(p["w"]) == 9.0
        assert float(st["slow"]["w"]) == 9.0

    def test_jitted(self):
        inner = paddle.optimizer.SGD(learning_rate=1.0)
        la = LookaheadOptimizer(inner, alpha=0.5, k=2)
        params = {"w": jnp.float32(10.0)}

        @jax.jit
        def two(p, st):
            g = {"w": jnp.float32(1.0)}
            p, st = la.apply_pytree(p, g, st, step=1)
            return la.apply_pytree(p, g, st, step=2)

        p, st = two(params, la.init_pytree(params))
        assert float(p["w"]) == 9.0

    def test_eager_step(self):
        paddle.seed(0)
        lin = paddle.nn.Linear(2, 2, bias_attr=False)
        w0 = np.asarray(lin.weight.value).copy()
        inner = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=lin.parameters())
        la = LookaheadOptimizer(inner, alpha=0.5, k=2)
        x = paddle.ones([4, 2])
        for _ in range(2):
            loss = (lin(x) ** 2).mean()
            loss.backward()
            la.step()
            la.clear_grad()
        # after k=2 steps the weights must equal slow-sync of the fast path
        assert not np.allclose(np.asarray(lin.weight.value), w0)

    def test_validation(self):
        import pytest
        with pytest.raises(ValueError):
            LookaheadOptimizer(None)
        with pytest.raises(ValueError):
            LookaheadOptimizer(paddle.optimizer.SGD(), alpha=2.0)
        with pytest.raises(ValueError):
            LookaheadOptimizer(paddle.optimizer.SGD(), k=0)
