"""Pallas fused kernels vs XLA reference (OpTest contract: numpy/XLA
reference + gradient comparison, SURVEY.md §4 op unit tests).

On CPU the kernels run in pallas interpret mode; the same code compiles via
Mosaic on TPU (validated by bench/driver runs)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.ops.pallas.layer_norm import layer_norm


def _attn_ref(q, k, v, causal):
    qh, kh, vh = [jnp.swapaxes(x, 1, 2) for x in (q, k, v)]
    s = jnp.einsum("bhsd,bhtd->bhst", qh, kh) / np.sqrt(q.shape[-1])
    if causal:
        m = jnp.tril(jnp.ones(s.shape[-2:], bool))
        s = jnp.where(m, s, -1e30)
    w = jax.nn.softmax(s, -1)
    return jnp.swapaxes(jnp.einsum("bhst,bhtd->bhsd", w, vh), 1, 2)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_fwd_bwd(causal):
    rs = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rs.randn(2, 128, 2, 64), jnp.float32)
               for _ in range(3)]
    out = flash_attention(q, k, v, causal=causal)
    ref = _attn_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)

    g1 = jax.grad(lambda *a: (flash_attention(*a, causal=causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: (_attn_ref(*a, causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_flash_attention_jit_and_bf16():
    rs = np.random.RandomState(1)
    q, k, v = [jnp.asarray(rs.randn(1, 128, 2, 64), jnp.bfloat16)
               for _ in range(3)]
    out = jax.jit(lambda *a: flash_attention(*a, causal=True))(q, k, v)
    ref = _attn_ref(q.astype(jnp.float32), k.astype(jnp.float32),
                    v.astype(jnp.float32), True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)


def test_flash_attention_fallback_shapes():
    q = jnp.zeros((1, 129, 2, 64))  # 129 % 128 != 0
    with pytest.raises(NotImplementedError):
        flash_attention(q, q, q)


def test_layer_norm_fwd_bwd():
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(8, 16, 256), jnp.float32)
    w = jnp.asarray(rs.randn(256), jnp.float32)
    b = jnp.asarray(rs.randn(256), jnp.float32)

    def ref(x, w, b, eps=1e-5):
        m = jnp.mean(x, -1, keepdims=True)
        v = jnp.mean((x - m) ** 2, -1, keepdims=True)
        return (x - m) * jax.lax.rsqrt(v + eps) * w + b

    np.testing.assert_allclose(np.asarray(layer_norm(x, w, b)),
                               np.asarray(ref(x, w, b)),
                               rtol=1e-5, atol=1e-5)
    g1 = jax.grad(lambda *a: (layer_norm(*a) ** 2).sum(),
                  argnums=(0, 1, 2))(x, w, b)
    g2 = jax.grad(lambda *a: (ref(*a) ** 2).sum(), argnums=(0, 1, 2))(x, w, b)
    for a, bb in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-4, atol=1e-3)


def test_fused_op_dispatch_falls_back_cleanly(monkeypatch):
    """ops.fused attempts pallas, hits NotImplementedError on an untileable
    shape, and falls back to the XLA path with a correct result."""
    import paddle_tpu as paddle
    from paddle_tpu.ops import fused

    monkeypatch.setattr(fused, "_use_pallas", lambda: True)
    x = paddle.randn([2, 129, 4, 16])  # 129 % 128 != 0 → pallas raises
    out = fused.scaled_dot_product_attention(x, x, x)
    assert out.shape == [2, 129, 4, 16]
    ref = _attn_ref(x.value, x.value, x.value, False)
    np.testing.assert_allclose(np.asarray(out.value), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
