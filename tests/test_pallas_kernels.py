"""Pallas fused kernels vs XLA reference (OpTest contract: numpy/XLA
reference + gradient comparison, SURVEY.md §4 op unit tests).

On CPU the kernels run in pallas interpret mode; the same code compiles via
Mosaic on TPU (validated by bench/driver runs)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle

from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.ops.pallas.layer_norm import layer_norm


def _attn_ref(q, k, v, causal):
    qh, kh, vh = [jnp.swapaxes(x, 1, 2) for x in (q, k, v)]
    s = jnp.einsum("bhsd,bhtd->bhst", qh, kh) / np.sqrt(q.shape[-1])
    if causal:
        m = jnp.tril(jnp.ones(s.shape[-2:], bool))
        s = jnp.where(m, s, -1e30)
    w = jax.nn.softmax(s, -1)
    return jnp.swapaxes(jnp.einsum("bhst,bhtd->bhsd", w, vh), 1, 2)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_fwd_bwd(causal):
    rs = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rs.randn(2, 128, 2, 64), jnp.float32)
               for _ in range(3)]
    out = flash_attention(q, k, v, causal=causal)
    ref = _attn_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)

    g1 = jax.grad(lambda *a: (flash_attention(*a, causal=causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: (_attn_ref(*a, causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_flash_attention_jit_and_bf16():
    rs = np.random.RandomState(1)
    q, k, v = [jnp.asarray(rs.randn(1, 128, 2, 64), jnp.bfloat16)
               for _ in range(3)]
    out = jax.jit(lambda *a: flash_attention(*a, causal=True))(q, k, v)
    ref = _attn_ref(q.astype(jnp.float32), k.astype(jnp.float32),
                    v.astype(jnp.float32), True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)


def test_flash_attention_fallback_shapes():
    q = jnp.zeros((1, 129, 2, 64))  # 129 % 128 != 0
    with pytest.raises(NotImplementedError):
        flash_attention(q, q, q)


def test_layer_norm_fwd_bwd():
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(8, 16, 256), jnp.float32)
    w = jnp.asarray(rs.randn(256), jnp.float32)
    b = jnp.asarray(rs.randn(256), jnp.float32)

    def ref(x, w, b, eps=1e-5):
        m = jnp.mean(x, -1, keepdims=True)
        v = jnp.mean((x - m) ** 2, -1, keepdims=True)
        return (x - m) * jax.lax.rsqrt(v + eps) * w + b

    np.testing.assert_allclose(np.asarray(layer_norm(x, w, b)),
                               np.asarray(ref(x, w, b)),
                               rtol=1e-5, atol=1e-5)
    g1 = jax.grad(lambda *a: (layer_norm(*a) ** 2).sum(),
                  argnums=(0, 1, 2))(x, w, b)
    g2 = jax.grad(lambda *a: (ref(*a) ** 2).sum(), argnums=(0, 1, 2))(x, w, b)
    for a, bb in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-4, atol=1e-3)


def test_fused_op_dispatch_falls_back_cleanly(monkeypatch):
    """ops.fused attempts pallas, hits NotImplementedError on an untileable
    shape, and falls back to the XLA path with a correct result."""
    import paddle_tpu as paddle
    from paddle_tpu.ops import fused

    monkeypatch.setattr(fused, "_use_pallas", lambda: True)
    x = paddle.randn([2, 129, 4, 16])  # 129 % 128 != 0 → pallas raises
    out = fused.scaled_dot_product_attention(x, x, x)
    assert out.shape == [2, 129, 4, 16]
    ref = _attn_ref(x.value, x.value, x.value, False)
    np.testing.assert_allclose(np.asarray(out.value), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


class TestFusedLinearCrossEntropy:
    """Chunked LM-head matmul + xent vs the direct computation."""

    def _direct(self, h, w, labels):
        z = (h.astype(np.float64) @ w.astype(np.float64))
        m = z.max(-1, keepdims=True)
        lse = np.log(np.exp(z - m).sum(-1)) + m[:, 0]
        picked = z[np.arange(len(labels)), labels]
        return lse - picked

    def test_forward_matches_direct(self):
        from paddle_tpu.ops import fused
        rs = np.random.RandomState(0)
        N, H, V = 12, 16, 1000
        h = rs.randn(N, H).astype("f")
        w = (rs.randn(H, V) * 0.1).astype("f")
        labels = rs.randint(0, V, N)
        out = fused.fused_linear_cross_entropy(
            paddle.to_tensor(h), paddle.to_tensor(w),
            paddle.to_tensor(labels), chunk_size=128)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   self._direct(h, w, labels), rtol=1e-4)

    def test_vocab_not_multiple_of_chunk(self):
        from paddle_tpu.ops import fused
        rs = np.random.RandomState(1)
        N, H, V = 6, 8, 37  # 37 not divisible by 16
        h = rs.randn(N, H).astype("f")
        w = (rs.randn(H, V) * 0.1).astype("f")
        labels = rs.randint(0, V, N)
        out = fused.fused_linear_cross_entropy(
            paddle.to_tensor(h), paddle.to_tensor(w),
            paddle.to_tensor(labels), chunk_size=16)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   self._direct(h, w, labels), rtol=1e-4)

    def test_gradients_match_direct(self):
        from paddle_tpu.ops import fused
        import jax
        import jax.numpy as jnp
        rs = np.random.RandomState(2)
        N, H, V = 8, 12, 300
        h = rs.randn(N, H).astype("f")
        w = (rs.randn(H, V) * 0.1).astype("f")
        labels = jnp.asarray(rs.randint(0, V, N))

        def fused_loss(hh, ww):
            return fused._flce(hh, ww, labels, 64).mean()

        def direct_loss(hh, ww):
            z = (hh @ ww).astype(jnp.float32)
            lp = jax.nn.log_softmax(z, -1)
            return -jnp.take_along_axis(lp, labels[:, None], 1).mean()

        gh1, gw1 = jax.grad(fused_loss, (0, 1))(jnp.asarray(h),
                                                jnp.asarray(w))
        gh2, gw2 = jax.grad(direct_loss, (0, 1))(jnp.asarray(h),
                                                 jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(gh1), np.asarray(gh2),
                                   rtol=1e-3, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2),
                                   rtol=1e-3, atol=1e-6)

    def test_batched_leading_shape(self):
        from paddle_tpu.ops import fused
        rs = np.random.RandomState(3)
        B, S, H, V = 2, 5, 8, 50
        h = rs.randn(B, S, H).astype("f")
        w = (rs.randn(H, V) * 0.1).astype("f")
        labels = rs.randint(0, V, (B, S))
        out = fused.fused_linear_cross_entropy(
            paddle.to_tensor(h), paddle.to_tensor(w),
            paddle.to_tensor(labels), chunk_size=16)
        assert tuple(out.shape) == (B, S)
        flat = self._direct(h.reshape(-1, H), w, labels.reshape(-1))
        np.testing.assert_allclose(np.asarray(out.numpy()).reshape(-1),
                                   flat, rtol=1e-4)
